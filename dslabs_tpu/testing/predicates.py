"""State predicates: named checks over a system state.

Re-design of framework/tst/.../StatePredicate.java:46-438.  A predicate maps a
state to (truth value, detail string); exceptions during evaluation are
captured in the PredicateResult (StatePredicate.java:257-340) and interpreted
by the search layer (invariant exception => violation; prune exception =>
pruned; goal exception => ignored — SearchSettings.java:77-135).

The standard library (RESULTS_OK, NONE_DECIDED, CLIENTS_DONE, ...) is ported
behaviourally from StatePredicate.java:52-156.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Tuple

__all__ = ["PredicateResult", "StatePredicate", "RESULTS_OK", "NONE_DECIDED",
           "CLIENTS_DONE", "ALL_RESULTS_SAME", "client_done",
           "client_has_results", "all_results_match", "any_results_match",
           "contains_message_matching", "results_have_type"]


class PredicateResult:
    """Outcome of evaluating a predicate on a state."""

    __slots__ = ("predicate", "value", "detail", "exception")

    def __init__(self, predicate: "StatePredicate", value: bool,
                 detail: Optional[str] = None,
                 exception: Optional[BaseException] = None):
        self.predicate = predicate
        self.value = value
        self.detail = detail
        self.exception = exception

    @property
    def exception_thrown(self) -> bool:
        return self.exception is not None

    def error_message(self) -> str:
        if self.exception is not None:
            return (f"Exception thrown while evaluating \"{self.predicate.name}\""
                    f": {self.exception!r}")
        verb = "holds" if self.value else "violated"
        msg = f"Predicate \"{self.predicate.name}\" {verb}"
        if self.detail:
            msg += f" ({self.detail})"
        return msg

    def __repr__(self) -> str:
        return f"PredicateResult({self.predicate.name!r}, {self.value}, {self.detail!r})"


class StatePredicate:
    """Named predicate over a state.

    ``fn(state)`` may return a bool or a (bool, detail) tuple.  Combinators
    negate/and/or/implies mirror StatePredicate.java:382-432.
    """

    def __init__(self, name: str, fn: Callable[[Any], Any],
                 tkey: Any = None):
        self.name = name
        self._fn = fn
        # Tensor-translation metadata (SURVEY §8.1 "the TPU backend is a
        # new Search strategy selectable by settings"): ``tkey`` names a
        # primitive predicate for the tensor backend's twin adapters
        # (e.g. ("PAXOS_HAS_STATUS", addr, slot, status)); ``structure``
        # records combinator shape so compound predicates translate
        # structurally.  Both are inert on the object path.
        self.tkey = tkey
        self.structure = None

    def check(self, state: Any) -> PredicateResult:
        """Full evaluation, capturing exceptions."""
        try:
            out = self._fn(state)
        except Exception as e:  # noqa: BLE001 — predicate sandbox
            return PredicateResult(self, False, None, e)
        if isinstance(out, tuple):
            value, detail = out
        else:
            value, detail = bool(out), None
        return PredicateResult(self, bool(value), detail)

    def test(self, state: Any, expected: bool = True) -> Optional[PredicateResult]:
        """Fast path: return None when the predicate evaluates to ``expected``
        with no exception; otherwise the full result
        (StatePredicate.java:368-380)."""
        r = self.check(state)
        if r.exception is None and r.value == expected:
            return None
        return r

    # ----------------------------------------------------------- combinators

    def negate(self) -> "StatePredicate":
        p = StatePredicate(f"not ({self.name})",
                           lambda s: not self.check_raises(s))
        p.structure = ("not", self)
        return p

    def check_raises(self, state: Any) -> bool:
        r = self.check(state)
        if r.exception is not None:
            raise r.exception
        return r.value

    def and_(self, other: "StatePredicate") -> "StatePredicate":
        p = StatePredicate(f"({self.name}) and ({other.name})",
                           lambda s: self.check_raises(s) and other.check_raises(s))
        p.structure = ("and", self, other)
        return p

    def or_(self, other: "StatePredicate") -> "StatePredicate":
        p = StatePredicate(f"({self.name}) or ({other.name})",
                           lambda s: self.check_raises(s) or other.check_raises(s))
        p.structure = ("or", self, other)
        return p

    def implies(self, other: "StatePredicate") -> "StatePredicate":
        p = StatePredicate(f"({self.name}) implies ({other.name})",
                           lambda s: (not self.check_raises(s)) or other.check_raises(s))
        p.structure = ("implies", self, other)
        return p

    def __repr__(self) -> str:
        return f"StatePredicate({self.name!r})"


# --------------------------------------------------------------- the library
# Behavioural ports of StatePredicate.java:52-156.  These operate on any state
# exposing .client_workers() -> dict addr->ClientWorker and .network() (for the
# message predicate).

def _results_ok(state) -> Tuple[bool, Optional[str]]:
    for addr, worker in state.client_workers().items():
        ok, detail = worker.results_ok()
        if not ok:
            return False, f"client {addr}: {detail}"
    return True, None


RESULTS_OK = StatePredicate("Clients got expected results", _results_ok,
                            tkey=("RESULTS_OK",))

NONE_DECIDED = StatePredicate(
    "No results returned",
    lambda state: all(len(w.results) == 0 for w in state.client_workers().values()),
    tkey=("NONE_DECIDED",))

CLIENTS_DONE = StatePredicate(
    "All clients done",
    lambda state: all(w.done() for w in state.client_workers().values()),
    tkey=("CLIENTS_DONE",))


def client_done(address) -> StatePredicate:
    return StatePredicate(
        f"Client {address} done",
        lambda state: state.client_workers()[address].done(),
        tkey=("CLIENT_DONE", address))


def client_has_results(address, num_results: int) -> StatePredicate:
    return StatePredicate(
        f"Client {address} has {num_results} result(s)",
        lambda state: len(state.client_workers()[address].results) >= num_results,
        tkey=("CLIENT_HAS_RESULTS", address, num_results))


def _all_results_same(state) -> Tuple[bool, Optional[str]]:
    seen = None
    for addr, w in state.client_workers().items():
        r = tuple(w.results)
        if seen is None:
            seen = (addr, r)
        elif seen[1] != r:
            return False, f"{seen[0]} saw {seen[1]}, {addr} saw {r}"
    return True, None


ALL_RESULTS_SAME = StatePredicate("All clients' results same",
                                  _all_results_same,
                                  tkey=("ALL_RESULTS_SAME",))


def all_results_match(predicate: Callable[[Any], bool],
                      name: str = "All results match") -> StatePredicate:
    return StatePredicate(name, lambda state: all(
        predicate(r) for w in state.client_workers().values() for r in w.results))


def any_results_match(predicate: Callable[[Any], bool],
                      name: str = "Some result matches") -> StatePredicate:
    return StatePredicate(name, lambda state: any(
        predicate(r) for w in state.client_workers().values() for r in w.results))


def contains_message_matching(name: str,
                              predicate: Callable[[Any], bool]) -> StatePredicate:
    return StatePredicate(
        f"Contains message matching: {name}",
        lambda state: any(predicate(me.message) for me in state.network()))


def results_have_type(result_type: type) -> StatePredicate:
    return all_results_match(
        lambda r: isinstance(r, result_type),
        name=f"All results are {result_type.__name__}")
