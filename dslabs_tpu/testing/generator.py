"""NodeGenerator: serializable factory for servers, clients, and workloads.

Re-design of framework/tst/.../NodeGenerator.java:40-178.  States use it to
construct nodes on ``add_server``/``add_client_worker``.
"""

from __future__ import annotations

from typing import Callable, Optional

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.node import Node
from dslabs_tpu.testing.workload import Workload

__all__ = ["NodeGenerator"]


class NodeGenerator:

    def __init__(self,
                 server_supplier: Optional[Callable[[Address], Node]] = None,
                 client_supplier: Optional[Callable[[Address], Node]] = None,
                 workload_supplier: Optional[Callable[[Address], Workload]] = None):
        self._server_supplier = server_supplier
        self._client_supplier = client_supplier
        self._workload_supplier = workload_supplier

    def server(self, address: Address) -> Node:
        if self._server_supplier is None:
            raise RuntimeError("NodeGenerator has no server supplier")
        return self._server_supplier(address)

    def client(self, address: Address) -> Node:
        if self._client_supplier is None:
            raise RuntimeError("NodeGenerator has no client supplier")
        return self._client_supplier(address)

    def workload(self, address: Address) -> Workload:
        if self._workload_supplier is None:
            raise RuntimeError("NodeGenerator has no workload supplier")
        return self._workload_supplier(address)

    def has_workload_supplier(self) -> bool:
        return self._workload_supplier is not None

    def with_workload(self, workload_or_supplier) -> "NodeGenerator":
        """Return a copy with a different workload supplier."""
        supplier = (workload_or_supplier if callable(workload_or_supplier)
                    else (lambda _addr: workload_or_supplier))
        return NodeGenerator(self._server_supplier, self._client_supplier,
                             supplier)
