"""AbstractState: common base of the run state and the search state.

Re-design of framework/tst/.../AbstractState.java:50-324.  Holds three node
maps (servers, client workers, bare clients) plus the NodeGenerator; the
copy constructor used for successor states clones **only one designated node**
(copy-on-write stepping, AbstractState.java:96-115).  Equality covers exactly
the node maps.
"""

from __future__ import annotations

import copy
from typing import Dict, Iterable, Optional

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.node import Node
from dslabs_tpu.testing.client_worker import ClientWorker
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.workload import Workload
from dslabs_tpu.utils.structural import StructEq, clone

__all__ = ["AbstractState"]


class AbstractState(StructEq):

    def __init__(self, generator: NodeGenerator):
        self.servers: Dict[Address, Node] = {}
        self.client_workers_map: Dict[Address, ClientWorker] = {}
        self.clients: Dict[Address, Node] = {}
        self._gen = generator

    @classmethod
    def _cow_copy(cls, src: "AbstractState", node_to_clone: Address) -> "AbstractState":
        """Copy-on-write successor: share every node except ``node_to_clone``,
        which is deep-cloned (AbstractState.java:96-115).  Subclasses must
        finish their own bookkeeping after calling this."""
        new = cls.__new__(cls)
        new.servers = dict(src.servers)
        new.client_workers_map = dict(src.client_workers_map)
        new.clients = dict(src.clients)
        new._gen = src._gen
        root = node_to_clone.root_address()
        for m in (new.servers, new.client_workers_map, new.clients):
            if root in m:
                m[root] = clone(m[root])
                break
        return new

    # -------------------------------------------------------------- equality

    def _eq_fields(self):
        return {"servers": self.servers,
                "client_workers": self.client_workers_map,
                "clients": self.clients}

    # ------------------------------------------------------------- accessors

    @property
    def generator(self) -> NodeGenerator:
        return self._gen

    def client_workers(self) -> Dict[Address, ClientWorker]:
        return self.client_workers_map

    def node(self, address: Address) -> Optional[Node]:
        root = address.root_address()
        return (self.servers.get(root) or self.client_workers_map.get(root)
                or self.clients.get(root))

    def has_node(self, address: Address) -> bool:
        return self.node(address) is not None

    def addresses(self) -> Iterable[Address]:
        yield from self.servers
        yield from self.client_workers_map
        yield from self.clients

    def nodes(self) -> Iterable[Node]:
        yield from self.servers.values()
        yield from self.client_workers_map.values()
        yield from self.clients.values()

    def num_nodes(self) -> int:
        return (len(self.servers) + len(self.client_workers_map)
                + len(self.clients))

    # ----------------------------------------------------------- add / remove

    def add_server(self, address: Address) -> Node:
        node = self._gen.server(address)
        self.servers[address] = node
        self._setup_node(address)
        return node

    def add_client_worker(self, address: Address,
                          workload: Optional[Workload] = None,
                          record_commands_and_results: bool = True) -> ClientWorker:
        client = self._gen.client(address)
        if workload is None:
            workload = self._gen.workload(address)
        worker = ClientWorker(client, workload, record_commands_and_results)
        self.client_workers_map[address] = worker
        self._setup_node(address)
        return worker

    def add_client(self, address: Address) -> Node:
        node = self._gen.client(address)
        self.clients[address] = node
        self._setup_node(address)
        return node

    def remove_node(self, address: Address) -> None:
        root = address.root_address()
        for m in (self.servers, self.client_workers_map, self.clients):
            if root in m:
                del m[root]
                self._cleanup_node(root)
                return
        raise KeyError(f"No node at {address}")

    def add_command(self, command, result=None) -> None:
        """Fan a command out to every client worker (AbstractState.java:265-323)."""
        for worker in self.client_workers_map.values():
            self._ensure_node_config(worker.address)
            worker.add_command(command, result)

    # ------------------------------------------------------- engine contract

    def network(self):
        raise NotImplementedError

    def timers(self, address: Address):
        raise NotImplementedError

    def _setup_node(self, address: Address) -> None:
        raise NotImplementedError

    def _ensure_node_config(self, address: Address) -> None:
        raise NotImplementedError

    def _cleanup_node(self, address: Address) -> None:
        raise NotImplementedError
