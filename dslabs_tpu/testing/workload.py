"""Workloads: per-client command streams with expected results.

Re-design of framework/tst/.../Workload.java:44-574.  A workload yields
(command, expected-result) pairs per client; the string-template form supports
the reference's ``%``-substitutions (Workload.java:96-226):

  %r    random alphanumeric string of 8 chars      %rN   ... of N chars
  %n    random int in [1, 100]                     %nN   ... in [1, N]
  %i    1-based command index;  %i-1 / %i+1        %a    client address string

The same random draws are shared between a command string and its result
string when the identical token appears in both (keyed by token text, consumed
in order) — exactly the reference's randomness-map protocol.
"""

from __future__ import annotations

import hashlib
import random
import re
import string
from typing import Any, Callable, Dict, List, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.types import Command, Result

__all__ = ["Workload", "InfiniteWorkload", "workload_builder",
           "stream_rng", "derandomized"]


def derandomized() -> bool:
    """Whether command streams draw from the COUNTER-MODE rng (a pure
    function of (client address, command index)) instead of the global
    rng.  On under the tensor search strategy: the twin adapters must be
    able to RE-DERIVE what command a client sends at index i to decode
    terminal states and replay staged phases (round-4 verdict item 8 —
    the global-rng stream made infinite workloads a loud decode
    refusal).  The object path's semantics are unchanged either way:
    draws are still uniform per draw site, just keyed."""
    from dslabs_tpu.utils.flags import GlobalSettings

    return GlobalSettings.search_backend == "tensor"


def stream_rng(a: Address, i: int) -> random.Random:
    """The counter-mode stream: rng for client ``a``'s i-th command
    (0-based), identical across every copy of the workload."""
    seed = int.from_bytes(
        hashlib.md5(f"{a}|{i}".encode()).digest()[:8], "big")
    return random.Random(seed)

_TOKEN = re.compile(r"%(?:r(\d*)|n(\d*)|i(?:-1|\+1)?|a)")


def _substitute(s: str, a: Address, i: int,
                randomness: Optional[Dict[str, List[str]]],
                rng=None):
    """One pass of %-token replacement.  When ``randomness`` is None, fresh
    draws are made and recorded; when given, recorded draws are consumed.
    ``rng`` overrides the global random module (the counter-mode
    deterministic stream, see :func:`stream_rng`)."""
    recording: Dict[str, List[str]] = {}
    use_recorded = randomness is not None
    r = rng if rng is not None else random

    def repl(m: re.Match) -> str:
        tok = m.group(0)
        kind = tok[1]
        if kind == "r" or kind == "n":
            val: Optional[str] = None
            if use_recorded and randomness.get(tok):
                val = randomness[tok].pop(0)
            if val is None:
                if kind == "r":
                    n = int(m.group(1)) if m.group(1) else 8
                    val = "".join(r.choices(
                        string.ascii_letters + string.digits, k=n))
                else:
                    ub = int(m.group(2)) if m.group(2) else 100
                    val = str(r.randint(1, ub))
            if not use_recorded:
                recording.setdefault(tok, []).append(val)
            return val
        if kind == "i":
            if tok == "%i-1":
                return str(i - 1)
            if tok == "%i+1":
                return str(i + 1)
            return str(i)
        if kind == "a":
            return str(a)
        return tok

    out = _TOKEN.sub(repl, s)
    return out, recording


def do_replacements(command: Optional[str], result: Optional[str],
                    a: Address, i: int,
                    rng=None) -> Tuple[Optional[str], Optional[str]]:
    if command is None:
        return None, None
    new_cmd, rec = _substitute(command, a, i, None, rng)
    if result is None:
        return new_cmd, None
    new_res, _ = _substitute(result, a, i, rec, rng)
    return new_cmd, new_res


class Workload:
    """A stream of commands (and optionally expected results) for one client.

    Construct via :func:`workload_builder` or the convenience classmethods.
    """

    def __init__(self, *,
                 commands: Optional[List[Command]] = None,
                 results: Optional[List[Result]] = None,
                 command_strings: Optional[List[str]] = None,
                 result_strings: Optional[List[str]] = None,
                 parser: Optional[Callable[[str, Optional[str]],
                                           Tuple[Command, Optional[Result]]]] = None,
                 num_times: int = 1,
                 finite: bool = True,
                 replacements: bool = True,
                 millis_between_requests: int = 0):
        if commands is not None:
            if command_strings is not None or result_strings is not None:
                raise ValueError("Cannot mix commands and command strings")
            if results is not None and len(commands) != len(results):
                raise ValueError("Commands/results size mismatch")
            self._commands: Optional[List[Command]] = list(commands)
            self._results: List[Result] = list(results) if results else []
            self._command_strings = None
            self._result_strings: List[str] = []
            self._parser = None
        elif command_strings is not None:
            if results is not None:
                raise ValueError("Cannot mix commands and command strings")
            if parser is None:
                raise ValueError("String workload requires a parser")
            if result_strings is not None and len(command_strings) != len(result_strings):
                raise ValueError("Commands/results size mismatch")
            self._commands = None
            self._results = []
            self._command_strings = list(command_strings)
            self._result_strings = list(result_strings) if result_strings else []
            self._parser = parser
        else:
            raise ValueError("Must have commands or command strings")
        if not finite and self._list_size() == 0:
            raise ValueError("Cannot create empty infinite workload")
        self._finite = finite
        self._replacements = replacements
        self._num_times = max(1, num_times) if finite else 1
        self.millis_between_requests = millis_between_requests
        self._i = 0

    # ------------------------------------------------------------------ core

    def _list_size(self) -> int:
        return (len(self._commands) if self._commands is not None
                else len(self._command_strings))

    def _next_pair(self, a: Address) -> Tuple[Command, Optional[Result]]:
        if not self.has_next():
            raise RuntimeError("Workload finished.")
        index = self._i % self._list_size()
        if self._commands is not None:
            command = self._commands[index]
            result = self._results[index] if self.has_results() else None
        else:
            cs = self._command_strings[index]
            rs = self._result_strings[index] if self.has_results() else None
            if self._replacements:
                rng = stream_rng(a, self._i) if derandomized() else None
                cs, rs = do_replacements(cs, rs, a, self._i + 1, rng)
            command, result = self._parser(cs, rs)
        self._i += 1
        return command, result

    def next_command_and_result(self, client_address: Address) -> Tuple[Command, Result]:
        if not self.has_results():
            raise RuntimeError("Workload doesn't contain results")
        return self._next_pair(client_address)

    def next_command(self, client_address: Address) -> Command:
        return self._next_pair(client_address)[0]

    def has_next(self) -> bool:
        return not self._finite or self._i < self._list_size() * self._num_times

    def has_results(self) -> bool:
        if self._commands is not None:
            return len(self._commands) == len(self._results) and self._list_size() > 0
        return (len(self._command_strings) == len(self._result_strings)
                and self._list_size() > 0)

    def add(self, command, result=None) -> "Workload":
        if not self._finite or self._num_times > 1:
            raise RuntimeError("Cannot add to an infinite or repeating workload")
        if isinstance(command, str):
            if self._command_strings is None:
                raise RuntimeError("Workload doesn't have command strings")
            if result is None and self._command_strings and self.has_results():
                raise RuntimeError("Workload has results")
            self._command_strings.append(command)
            if result is not None:
                self._result_strings.append(result)
        else:
            if self._commands is None:
                raise RuntimeError("Workload has command strings")
            if result is None and self._commands and self.has_results():
                raise RuntimeError("Workload has results")
            self._commands.append(command)
            if result is not None:
                self._results.append(result)
        return self

    def reset(self) -> None:
        self._i = 0

    def size(self) -> int:
        return self._list_size() * self._num_times if self._finite else -1

    def infinite(self) -> bool:
        return not self._finite

    # Equality: workloads are part of ClientWorker state, but progress is
    # captured by the worker's sentCommands/results; like the reference
    # (ClientWorker equality is (client, results) only) workloads never
    # participate in structural equality.

    def __repr__(self) -> str:
        return (f"Workload(size={self.size()}, i={self._i}, "
                f"results={self.has_results()})")


class InfiniteWorkload(Workload):
    """Convenience: endlessly repeating workload (InfiniteWorkload.java:28-58)."""

    def __init__(self, **kwargs):
        kwargs["finite"] = False
        super().__init__(**kwargs)


def workload_builder(**kwargs) -> Workload:
    """Keyword-style builder mirroring Workload.builder() (Workload.java:466-557)."""
    return Workload(**kwargs)
