"""KVStore workload helpers and predicates.

Behavioural port of labs/lab1-clientserver/tst/dslabs/kvstore/
KVStoreWorkload.java:37-341 — the string command format, builders, the
different-keys infinite workload, and the APPENDS_LINEARIZABLE predicate.

String command format (shared with the reference's viz configs):
  ``GET:key`` / ``PUT:key:value`` / ``APPEND:key:value``
Result strings: ``KeyNotFound`` / ``PutOk`` / anything else is the expected
value (GetResult for GET, AppendResult for APPEND).
"""

from __future__ import annotations

import random
import string as _string
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.types import Command, Result
from dslabs_tpu.labs.clientserver.kvstore import (Append, AppendResult, Get,
                                                  GetResult, KeyNotFound, Put,
                                                  PutOk)
from dslabs_tpu.testing.predicates import StatePredicate
from dslabs_tpu.testing.workload import Workload

__all__ = ["kv_parser", "kv_workload", "put", "get", "append", "put_ok",
           "get_result", "key_not_found", "append_result",
           "APPENDS_LINEARIZABLE", "appends_linearizable",
           "different_keys_infinite_workload", "put_get_workload",
           "append_different_key_workload", "append_same_key_workload",
           "simple_workload"]


# ------------------------------------------------------- command constructors

def put(key, value) -> Put:
    return Put(str(key), str(value))


def get(key) -> Get:
    return Get(str(key))


def append(key, value) -> Append:
    return Append(str(key), str(value))


def put_ok() -> PutOk:
    return PutOk()


def get_result(value) -> GetResult:
    return GetResult(str(value))


def key_not_found() -> KeyNotFound:
    return KeyNotFound()


def append_result(value) -> AppendResult:
    return AppendResult(str(value))


# ------------------------------------------------------------------- parsing

def parse_command(s: str) -> Command:
    parts = s.split(":", 2)
    op = parts[0].upper()
    if op == "GET":
        return Get(parts[1])
    if op == "PUT":
        return Put(parts[1], parts[2])
    if op == "APPEND":
        return Append(parts[1], parts[2])
    raise ValueError(f"Unknown KVStore command string: {s}")


def parse_result(command: Command, s: Optional[str]) -> Optional[Result]:
    if s is None:
        return None
    if s == "KeyNotFound":
        return KeyNotFound()
    if s == "PutOk" or isinstance(command, Put):
        return PutOk()
    if isinstance(command, Get):
        return GetResult(s)
    if isinstance(command, Append):
        return AppendResult(s)
    raise ValueError(f"Cannot parse result {s!r} for {command!r}")


def kv_parser(cmd: str, res: Optional[str]) -> Tuple[Command, Optional[Result]]:
    command = parse_command(cmd)
    return command, parse_result(command, res)


def kv_workload(commands: List[str], results: Optional[List[str]] = None,
                **kwargs) -> Workload:
    return Workload(command_strings=commands, result_strings=results,
                    parser=kv_parser, **kwargs)


# -------------------------------------------------------- standard workloads

def simple_workload() -> Workload:
    """The reference's simpleWorkload: a fixed hit-every-op sequence."""
    return kv_workload(
        ["PUT:key1:v1", "APPEND:key1:v2", "GET:key1", "GET:key2",
         "PUT:key2:v3", "APPEND:key2:v4", "GET:key2"],
        ["PutOk", "v1v2", "v1v2", "KeyNotFound", "PutOk", "v3v4", "v3v4"])


def put_get_workload() -> Workload:
    return kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"])


def append_different_key_workload(size: int) -> Workload:
    """Each client appends to its own key (%a): results grow per client."""
    return kv_workload(
        ["APPEND:key-%a:x" for _ in range(size)],
        ["x" * (i + 1) for i in range(size)])


def append_same_key_workload(size: int) -> Workload:
    """All clients append distinct markers to one shared key; checked with
    APPENDS_LINEARIZABLE rather than exact expected results."""
    return kv_workload([f"APPEND:the-key:%a." for _ in range(size)])


class DifferentKeysInfiniteWorkload(Workload):
    """Alternating put/get on per-client keys, endlessly
    (KVStoreWorkload.java:222-271)."""

    def __init__(self, millis_between_requests: int = 0):
        super().__init__(commands=[Put("init", "x")], results=[PutOk()],
                         finite=False,
                         millis_between_requests=millis_between_requests)
        self._data: Dict[str, str] = {}
        self._last_was_get = True
        self._last_put_key: Optional[str] = None

    def _next_pair(self, a: Address):
        from dslabs_tpu.testing.workload import derandomized, stream_rng

        if derandomized():
            # Counter-mode stream: the pair at index i is a pure
            # function of (a, i) — evens Put a fresh (key, value), odds
            # Get back the preceding Put's — so twin adapters can
            # re-derive any command for decode/staged replay
            # (testing/workload.py stream_rng).
            i = self._i
            self._i += 1
            rng = stream_rng(a, i - (i % 2))
            key = f"{a}-{rng.randint(1, 5)}"
            v = "".join(rng.choices(
                _string.ascii_letters + _string.digits, k=8))
            if i % 2 == 0:
                return Put(key, v), PutOk()
            return Get(key), GetResult(v)
        if self._last_was_get:
            self._last_put_key = f"{a}-{random.randint(1, 5)}"
            v = "".join(random.choices(_string.ascii_letters + _string.digits, k=8))
            self._data[self._last_put_key] = v
            self._last_was_get = False
            return Put(self._last_put_key, v), PutOk()
        self._last_was_get = True
        return (Get(self._last_put_key),
                GetResult(self._data[self._last_put_key]))

    def has_results(self) -> bool:
        return True

    def reset(self) -> None:
        super().reset()
        self._data.clear()
        self._last_was_get = True
        self._last_put_key = None


def different_keys_infinite_workload(millis_between_requests: int = 0) -> Workload:
    return DifferentKeysInfiniteWorkload(millis_between_requests)


# ------------------------------------------------------------------ predicate

def _appends_linearizable(addresses):
    def check(state):
        all_results: List[str] = []
        workers = state.client_workers()
        targets = addresses if addresses is not None else list(workers.keys())
        for a in targets:
            cw = workers[a]
            for c, r in zip(cw.sent_commands, cw.results):
                if not isinstance(c, Append):
                    raise RuntimeError("Client workers have non-Append commands")
                if not isinstance(r, AppendResult):
                    return False, f"{a} got {r!r} as result for {c!r}"
                if not r.value.endswith(c.value):
                    return False, f"{a} got {r!r} as result for {c!r}"
                all_results.append(r.value)
        all_results.sort(key=len)
        for x, y in zip(all_results, all_results[1:]):
            if not y.startswith(x) or x == y:
                return False, f"{x!r} is inconsistent with {y!r}"
        return True, None

    return StatePredicate(
        "Sequence of appends to the same key is linearizable", check,
        tkey=("RESULTS_LINEARIZABLE",))


APPENDS_LINEARIZABLE = _appends_linearizable(None)


def appends_linearizable(*addresses) -> StatePredicate:
    return _appends_linearizable(list(addresses))
