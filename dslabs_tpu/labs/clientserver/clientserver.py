"""Lab 1: exactly-once client/server on an unreliable network.

Reference semantics: labs/lab1-clientserver/src/dslabs/clientserver/
(SimpleClient.java:18, SimpleServer.java:16, Request/Reply messages,
ClientTimer 100ms — Timers.java).  The server wraps its application in
AMOApplication; the client stamps each command with a monotonically
increasing sequence number and retries on a 100ms timer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.client_utils import SyncClientMixin
from dslabs_tpu.core.node import Node
from dslabs_tpu.core.types import Application, Client, Command, Message, Result, Timer
from dslabs_tpu.labs.clientserver.amo import AMOApplication, AMOCommand, AMOResult

__all__ = ["Request", "Reply", "ClientTimer", "SimpleClient", "SimpleServer",
           "CLIENT_RETRY_MS"]

CLIENT_RETRY_MS = 100  # lab1 Timers.java


@dataclass(frozen=True)
class Request(Message):
    command: AMOCommand


@dataclass(frozen=True)
class Reply(Message):
    result: AMOResult


@dataclass(frozen=True)
class ClientTimer(Timer):
    command: AMOCommand


class SimpleServer(Node):

    def __init__(self, address: Address, app: Application):
        super().__init__(address)
        self.app = AMOApplication(app)

    def init(self) -> None:
        pass

    def handle_Request(self, m: Request, sender: Address) -> None:
        result = self.app.execute(m.command)
        if result is not None:
            self.send(Reply(result), sender)


class SimpleClient(SyncClientMixin, Node, Client):

    def __init__(self, address: Address, server_address: Address):
        super().__init__(address)
        self.server_address = server_address
        self.seq_num = 0
        self.pending: Optional[AMOCommand] = None
        self.result: Optional[Result] = None

    def init(self) -> None:
        pass

    # ------------------------------------------------------ client interface

    def send_command(self, command: Command) -> None:
        self.seq_num += 1
        amo = AMOCommand(command, self.address, self.seq_num)
        self.pending = amo
        self.result = None
        self.send(Request(amo), self.server_address)
        self.set_timer(ClientTimer(amo), CLIENT_RETRY_MS)

    def has_result(self) -> bool:
        return self.result is not None

    def _take_result(self) -> Result:
        return self.result

    # -------------------------------------------------------------- handlers

    def handle_Reply(self, m: Reply, sender: Address) -> None:
        if (self.pending is not None
                and m.result.sequence_num == self.pending.sequence_num):
            self.result = m.result.result
            self.pending = None
            self._notify_result()

    def on_ClientTimer(self, t: ClientTimer) -> None:
        if self.pending is not None and t.command == self.pending:
            self.send(Request(self.pending), self.server_address)
            self.set_timer(ClientTimer(self.pending), CLIENT_RETRY_MS)
