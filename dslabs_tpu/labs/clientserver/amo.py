"""At-most-once application wrapper.

Reference semantics: labs/lab1-clientserver/src/dslabs/atmostonce/
(AMOApplication.java:15-48, AMOCommand.java, AMOResult.java).  Wraps any
Application; deduplicates by (client address, sequence number), caching the
last result per client.  Reused by labs 2-4 (SURVEY §2.8).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.types import Application, Command, Result
from dslabs_tpu.utils.structural import StructEq

__all__ = ["AMOCommand", "AMOResult", "AMOApplication"]


@dataclass(frozen=True)
class AMOCommand(Command):
    command: Command
    client_address: Address
    sequence_num: int


@dataclass(frozen=True)
class AMOResult(Result):
    result: Result
    sequence_num: int


class AMOApplication(Application, StructEq):
    """Deterministic at-most-once wrapper around an inner application."""

    def __init__(self, application: Application):
        self.application = application
        # client address -> (last executed seq num, its AMOResult)
        self.last: Dict[Address, Tuple[int, AMOResult]] = {}

    def execute(self, command: Command) -> AMOResult:
        assert isinstance(command, AMOCommand)
        if self.already_executed(command):
            stored = self.last[command.client_address]
            if stored[0] == command.sequence_num:
                return stored[1]
            # An older command: its result is gone; the reference returns null.
            return None
        result = AMOResult(self.application.execute(command.command),
                           command.sequence_num)
        self.last[command.client_address] = (command.sequence_num, result)
        return result

    def already_executed(self, command: AMOCommand) -> bool:
        stored = self.last.get(command.client_address)
        return stored is not None and command.sequence_num <= stored[0]

    def execute_read_only(self, command: Command) -> Result:
        """Execute a read-only command without AMO bookkeeping (used by
        protocols that bypass replication for reads)."""
        assert command.read_only()
        return self.application.execute(command)
