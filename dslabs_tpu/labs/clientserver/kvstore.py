"""The key-value store application.

Reference semantics: labs/lab1-clientserver/src/dslabs/kvstore/KVStore.java:13-80.
Commands: Get / Put / Append; results: GetResult / KeyNotFound / PutOk /
AppendResult (Append returns the post-append value).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from dslabs_tpu.core.types import Application, Command, Result
from dslabs_tpu.utils.structural import StructEq

__all__ = ["Get", "Put", "Append", "GetResult", "KeyNotFound", "PutOk",
           "AppendResult", "KVStore", "KVStoreCommand"]


class KVStoreCommand(Command):
    """Marker base for KVStore commands."""
    __slots__ = ()


@dataclass(frozen=True)
class Get(KVStoreCommand):
    key: str

    def read_only(self) -> bool:
        return True


@dataclass(frozen=True)
class Put(KVStoreCommand):
    key: str
    value: str


@dataclass(frozen=True)
class Append(KVStoreCommand):
    key: str
    value: str


@dataclass(frozen=True)
class GetResult(Result):
    value: str


@dataclass(frozen=True)
class KeyNotFound(Result):
    pass


@dataclass(frozen=True)
class PutOk(Result):
    pass


@dataclass(frozen=True)
class AppendResult(Result):
    value: str


class KVStore(Application, StructEq):

    def __init__(self, initial: Dict[str, str] = None):
        self.store: Dict[str, str] = dict(initial) if initial else {}

    def execute(self, command: Command) -> Result:
        if isinstance(command, Get):
            if command.key in self.store:
                return GetResult(self.store[command.key])
            return KeyNotFound()
        if isinstance(command, Put):
            self.store[command.key] = command.value
            return PutOk()
        if isinstance(command, Append):
            new_value = self.store.get(command.key, "") + command.value
            self.store[command.key] = new_value
            return AppendResult(new_value)
        raise ValueError(f"Unknown KVStore command: {command!r}")
