"""Lab 0: ping-pong — the canonical minimal node pair.

Reference implementation mirroring labs/lab0-pingpong/src/dslabs/pingpong/
(PingApplication.java:13-34, PingServer.java:11-33, PingClient.java:18-88,
Messages.java:9-16, Timers.java:8).  The reference ships this lab complete;
it is the example every other lab builds on.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.client_utils import SyncClientMixin
from dslabs_tpu.core.node import Node
from dslabs_tpu.core.types import (Application, Client, Command, Message,
                                   Result, Timer)

__all__ = ["Ping", "Pong", "PingApplication", "PingRequest", "PongReply",
           "PingTimer", "PingServer", "PingClient", "PING_TIMER_MS"]

PING_TIMER_MS = 10  # Timers.java:8


@dataclass(frozen=True)
class Ping(Command):
    value: str


@dataclass(frozen=True)
class Pong(Result):
    value: str


class PingApplication(Application):
    """Ping -> Pong echo (PingApplication.java:13-34)."""

    def execute(self, command: Command) -> Result:
        assert isinstance(command, Ping)
        return Pong(command.value)

    def __eq__(self, other):
        return type(other) is PingApplication

    def __hash__(self):
        return hash("PingApplication")


@dataclass(frozen=True)
class PingRequest(Message):
    ping: Ping


@dataclass(frozen=True)
class PongReply(Message):
    pong: Pong


@dataclass(frozen=True)
class PingTimer(Timer):
    ping: Ping


class PingServer(Node):
    """Stateless executor of the PingApplication (PingServer.java:11-33)."""

    def __init__(self, address: Address):
        super().__init__(address)
        self.app = PingApplication()

    def init(self) -> None:
        pass

    def handle_PingRequest(self, m: PingRequest, sender: Address) -> None:
        pong = self.app.execute(m.ping)
        self.send(PongReply(pong), sender)


class PingClient(SyncClientMixin, Node, Client):
    """Sends pings, retries on a 10ms timer (PingClient.java:18-88)."""

    def __init__(self, address: Address, server_address: Address):
        super().__init__(address)
        self.server_address = server_address
        self.ping: Optional[Ping] = None
        self.pong: Optional[Pong] = None

    def init(self) -> None:
        pass

    # -------------------------------------------------------- client interface

    def send_command(self, command: Command) -> None:
        assert isinstance(command, Ping)
        self.ping = command
        self.pong = None
        self.send(PingRequest(command), self.server_address)
        self.set_timer(PingTimer(command), PING_TIMER_MS)

    def has_result(self) -> bool:
        return self.pong is not None

    def _take_result(self) -> Result:
        return self.pong

    # --------------------------------------------------------------- handlers

    def handle_PongReply(self, m: PongReply, sender: Address) -> None:
        if self.ping is not None and m.pong.value == self.ping.value:
            self.pong = m.pong
            self.ping = None
            self._notify_result()

    def on_PingTimer(self, t: PingTimer) -> None:
        if self.ping is not None and t.ping == self.ping:
            self.send(PingRequest(self.ping), self.server_address)
            self.set_timer(PingTimer(self.ping), PING_TIMER_MS)
