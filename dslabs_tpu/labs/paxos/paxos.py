"""Lab 3: multi-instance Paxos (the north-star workload).

The reference ships PaxosServer/PaxosClient as skeletons with a fixed probe
interface (labs/lab3-paxos/src/dslabs/paxos/PaxosServer.java:37-110:
``status``/``command``/``firstNonCleared``/``lastNonEmpty``;
PaxosLogSlotStatus.java:3-12) and fixed client message names
(PaxosRequest/PaxosReply).  The protocol below is a self-designed
multi-Paxos built to the acceptance spec in PaxosTest.java:67-1160:

  * **Stable leader.** Ballots are ``(round, server_index)``.  A server that
    misses leader heartbeats for one ElectionTimer period starts phase 1
    (P1a/P1b) with a higher round; followers suppress their own elections
    while a leader with ballot >= theirs is heartbeating.  In the steady
    state each agreement costs P2a(n) + P2b(n) + heartbeat-amortised commit
    distribution, within the <= 15 n messages/agreement budget
    (PaxosTest.java:571-593).
  * **Log replication.**  The leader assigns consecutive slots, replicates
    with P2a/P2b, marks slots CHOSEN on majority, executes chosen slots in
    order against an AMOApplication, and every server replies to the
    requesting client on execution (any replica can answer; the AMO layer
    dedups).  New leaders adopt the highest-ballot accepted value per slot
    from a P1b majority and fill holes with no-ops.
  * **Catch-up + garbage collection.**  Heartbeats carry the leader's
    contiguous-chosen watermark and the cluster-wide executed minimum;
    followers request missing chosen entries (CatchupRequest/Reply), and all
    servers clear log entries every server has executed
    (test11ClearsMemory, PaxosTest.java:599-644).  ``first_non_cleared``
    is the GC frontier + 1.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.client_utils import SyncClientMixin
from dslabs_tpu.core.node import Node
from dslabs_tpu.core.types import (Application, Client, Command, Message,
                                   Result, Timer)
from dslabs_tpu.labs.clientserver.amo import AMOApplication, AMOCommand, AMOResult

__all__ = ["PaxosServer", "PaxosClient", "PaxosRequest", "PaxosReply",
           "PaxosDecision",
           "PaxosLogSlotStatus", "Ballot",
           "HEARTBEAT_MILLIS", "CLIENT_RETRY_MILLIS"]

ELECTION_MILLIS_MIN = 150
ELECTION_MILLIS_MAX = 300
HEARTBEAT_MILLIS = 50
CLIENT_RETRY_MILLIS = 100


class PaxosLogSlotStatus:
    EMPTY = "EMPTY"
    ACCEPTED = "ACCEPTED"
    CHOSEN = "CHOSEN"
    CLEARED = "CLEARED"


# Ballot = (round, proposer_index); compares lexicographically.
Ballot = Tuple[int, int]


@dataclass(frozen=True)
class PaxosRequest(Message):
    command: Command  # AMOCommand from clients; raw commands in relay mode


@dataclass(frozen=True)
class PaxosDecision(Message):
    """Relay-mode output: delivered locally to the parent node for each
    chosen slot, in slot order (the sub-node replication pattern of lab 4,
    ShardStoreServer.java — Paxos as a group-replicated log)."""
    slot: int
    command: Optional[Command]


@dataclass(frozen=True)
class PaxosReply(Message):
    result: AMOResult


@dataclass(frozen=True)
class P1a(Message):
    ballot: Ballot


@dataclass(frozen=True)
class P1b(Message):
    ballot: Ballot
    # slot -> (accepted ballot, command-or-None, chosen flag)
    log: Tuple[Tuple[int, Tuple[Ballot, Optional[Command], bool]], ...]


@dataclass(frozen=True)
class P2a(Message):
    ballot: Ballot
    slot: int
    command: Optional[Command]  # None = no-op hole filler


@dataclass(frozen=True)
class P2b(Message):
    ballot: Ballot
    slot: int


@dataclass(frozen=True)
class Heartbeat(Message):
    ballot: Ballot
    commit: int       # leader's contiguous-chosen watermark
    gc_through: int   # every server has executed through this slot


@dataclass(frozen=True)
class HeartbeatReply(Message):
    ballot: Ballot
    executed_through: int


@dataclass(frozen=True)
class CatchupRequest(Message):
    from_slot: int


@dataclass(frozen=True)
class CatchupReply(Message):
    # slot -> command for chosen slots
    entries: Tuple[Tuple[int, Optional[Command]], ...]


@dataclass(frozen=True)
class ElectionTimer(Timer):
    pass


@dataclass(frozen=True)
class HeartbeatTimer(Timer):
    ballot: Ballot


@dataclass(frozen=True)
class ClientTimer(Timer):
    sequence_num: int


class _LogEntry:
    """Mutable per-slot record; equality/hash via fields (search state)."""

    __slots__ = ("ballot", "command", "chosen")

    def __init__(self, ballot: Ballot, command: Optional[Command],
                 chosen: bool = False):
        self.ballot = ballot
        self.command = command
        self.chosen = chosen

    def __eq__(self, other):
        return (type(other) is _LogEntry and self.ballot == other.ballot
                and self.command == other.command and self.chosen == other.chosen)

    def __hash__(self):
        return hash((self.ballot, self.command, self.chosen))

    def __repr__(self):
        return (f"LogEntry(ballot={self.ballot}, chosen={self.chosen}, "
                f"command={self.command})")


class PaxosServer(Node):

    def __init__(self, address: Address, servers: Tuple[Address, ...],
                 app: Optional[Application]):
        """With an application, executes chosen commands against it and
        replies to clients (lab 3).  With ``app=None`` (relay mode), instead
        delivers each chosen command to the parent node as a local
        ``PaxosDecision`` — Paxos as a replicated log for sub-node
        composition (lab 4)."""
        super().__init__(address)
        self.servers = tuple(servers)
        self.index = self.servers.index(address)
        self.majority = len(self.servers) // 2 + 1
        self.app = AMOApplication(app) if app is not None else None

        self.log: Dict[int, _LogEntry] = {}
        self.ballot: Ballot = (0, 0)          # highest ballot seen/promised
        self.leader = False                    # won phase 1 for self.ballot
        self.slot_in = 1                       # next slot the leader assigns
        self.executed_through = 0              # contiguous executed prefix
        self.cleared_through = 0               # GC frontier (slots <= cleared)
        self.heard_from_leader = False         # reset by ElectionTimer

        # Leader bookkeeping (meaningful only while leader).
        self.p1b_votes: Dict[Address, P1b] = {}
        self.p2b_votes: Dict[int, Tuple[Address, ...]] = {}
        self.proposed_seq: Dict[Address, int] = {}  # client -> highest seq proposed
        self.peer_executed: Dict[Address, int] = {}
        self.gc_through = 0

    def init(self) -> None:
        # A lone server must be able to elect itself immediately.
        self.set_timer(ElectionTimer(), ELECTION_MILLIS_MIN, ELECTION_MILLIS_MAX)
        if len(self.servers) == 1:
            self._start_election()

    # ------------------------------------------------------- probe interface
    # (PaxosServer.java:37-110 — the tests' log-inspection API)

    def status(self, slot: int) -> str:
        if slot <= self.cleared_through:
            return PaxosLogSlotStatus.CLEARED
        e = self.log.get(slot)
        if e is None:
            return PaxosLogSlotStatus.EMPTY
        return (PaxosLogSlotStatus.CHOSEN if e.chosen
                else PaxosLogSlotStatus.ACCEPTED)

    def command(self, slot: int) -> Optional[Command]:
        if slot <= self.cleared_through:
            return None
        e = self.log.get(slot)
        if e is None or e.command is None:
            return None
        if isinstance(e.command, AMOCommand):
            return e.command.command  # unwrap
        return e.command  # relay mode carries raw commands

    def first_non_cleared(self) -> int:
        return self.cleared_through + 1

    def last_non_empty(self) -> int:
        return max(self.log.keys(), default=self.cleared_through)

    # ------------------------------------------------------------- elections

    def _send_to_all(self, msg: Message) -> None:
        """Broadcast to peers and deliver to ourselves synchronously (our
        own vote/acceptance never rides the network)."""
        self.broadcast(msg, [s for s in self.servers if s != self.address])
        self.deliver_message(msg, self.address)

    def _reply(self, msg: Message, to: Address) -> None:
        if to == self.address:
            self.deliver_message(msg, self.address)
        else:
            self.send(msg, to)

    def _is_leader_ballot(self) -> bool:
        return self.leader and self.ballot[1] == self.index

    def is_leader(self) -> bool:
        return self._is_leader_ballot()

    def _start_election(self) -> None:
        self.ballot = (self.ballot[0] + 1, self.index)
        self.leader = False
        self.p1b_votes = {}
        self._send_to_all(P1a(self.ballot))

    def on_ElectionTimer(self, t: ElectionTimer) -> None:
        if not self._is_leader_ballot() and not self.heard_from_leader:
            self._start_election()
        self.heard_from_leader = False
        self.set_timer(ElectionTimer(), ELECTION_MILLIS_MIN, ELECTION_MILLIS_MAX)

    def handle_P1a(self, m: P1a, sender: Address) -> None:
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.leader = False
        if m.ballot == self.ballot:
            # Promise: report our accepted entries above the GC frontier.
            entries = tuple(sorted(
                (s, (e.ballot, e.command, e.chosen)) for s, e in self.log.items()))
            self._reply(P1b(self.ballot, entries), sender)

    def handle_P1b(self, m: P1b, sender: Address) -> None:
        if m.ballot != self.ballot or self.ballot[1] != self.index or self.leader:
            return
        self.p1b_votes[sender] = m
        if len(self.p1b_votes) < self.majority:
            return
        # Won phase 1: adopt the highest-ballot value per slot, fill holes.
        self.leader = True
        self.p2b_votes = {}
        self.proposed_seq = {}
        self.peer_executed = {self.address: self.executed_through}
        adopted: Dict[int, _LogEntry] = {}
        for vote in self.p1b_votes.values():
            for slot, (ballot, command, chosen) in vote.log:
                cur = adopted.get(slot)
                if chosen:
                    adopted[slot] = _LogEntry(ballot, command, True)
                elif cur is None or (not cur.chosen and ballot > cur.ballot):
                    adopted[slot] = _LogEntry(ballot, command, False)
        for slot, e in adopted.items():
            if slot <= self.cleared_through:
                continue
            mine = self.log.get(slot)
            if mine is None or not mine.chosen:
                self.log[slot] = _LogEntry(self.ballot, e.command, e.chosen)
        top = self.last_non_empty()
        # Repropose adopted non-chosen values and fill holes with no-ops.
        for slot in range(self.executed_through + 1, top + 1):
            e = self.log.get(slot)
            if e is None:
                self.log[slot] = _LogEntry(self.ballot, None, False)
            if e is None or not e.chosen:
                self._send_p2a(slot)
        self.slot_in = top + 1
        for slot, e in self.log.items():
            if isinstance(e.command, AMOCommand):
                c = e.command
                self.proposed_seq[c.client_address] = max(
                    self.proposed_seq.get(c.client_address, -1), c.sequence_num)
        self._execute_chosen()
        self.set_timer(HeartbeatTimer(self.ballot), HEARTBEAT_MILLIS)
        self._send_heartbeats()

    # ----------------------------------------------------------- replication

    def _send_p2a(self, slot: int) -> None:
        e = self.log[slot]
        self._send_to_all(P2a(self.ballot, slot, e.command))

    def handle_PaxosRequest(self, m: PaxosRequest, sender: Address) -> None:
        c = m.command
        if self.app is not None and self.app.already_executed(c):
            result = self.app.execute(c)
            if result is not None:
                # Reply to the originating client, not the sender: the
                # request may have been forwarded by a peer server.
                self.send(PaxosReply(result), c.client_address)
            return
        if not self._is_leader_ballot():
            # Forward externally-originated (client / parent-injected)
            # requests to the believed leader once; never re-forward a
            # peer's forward (a stale view could bounce a request around
            # forever in run mode).  A parent-injected request arrives with
            # sender == our own address.
            believed = self.servers[self.ballot[1]]
            if ((sender == self.address or sender not in self.servers)
                    and believed != self.address):
                self.send(m, believed)
            return
        if self.app is not None and isinstance(c, AMOCommand):
            if self.proposed_seq.get(c.client_address, -1) >= c.sequence_num:
                return  # already in flight; client retries are absorbed
            self.proposed_seq[c.client_address] = c.sequence_num
        elif any(e.command == c and not e.chosen for e in self.log.values()):
            # Relay mode: dedup only against in-flight (unchosen) entries.
            # A decided command the parent executor chose to skip (e.g. a
            # client op logged before the group adopted its first config)
            # must stay re-proposable; the parent's AMO layer absorbs
            # duplicate executions.
            return
        slot = self.slot_in
        self.slot_in += 1
        self.log[slot] = _LogEntry(self.ballot, c, False)
        self._send_p2a(slot)

    def handle_P2a(self, m: P2a, sender: Address) -> None:
        if m.ballot >= self.ballot:
            if m.ballot > self.ballot:
                self.leader = False
            self.ballot = m.ballot
            self.heard_from_leader = True
            e = self.log.get(m.slot)
            if m.slot > self.cleared_through and (e is None or not e.chosen):
                self.log[m.slot] = _LogEntry(m.ballot, m.command, False)
            self._reply(P2b(m.ballot, m.slot), sender)

    def handle_P2b(self, m: P2b, sender: Address) -> None:
        if m.ballot != self.ballot or not self._is_leader_ballot():
            return
        e = self.log.get(m.slot)
        if e is None or e.chosen or e.ballot != m.ballot:
            return
        votes = self.p2b_votes.get(m.slot, ())
        if sender in votes:
            return
        # Canonical order: vote arrival order must not distinguish states.
        votes = tuple(sorted(votes + (sender,), key=str))
        self.p2b_votes[m.slot] = votes
        if len(votes) >= self.majority:
            e.chosen = True
            self.p2b_votes.pop(m.slot, None)
            self._execute_chosen()

    # ------------------------------------------------------------- execution

    def _execute_chosen(self) -> None:
        while True:
            e = self.log.get(self.executed_through + 1)
            if e is None or not e.chosen:
                break
            self.executed_through += 1
            if self.app is None:
                if self._parent is not None:
                    self._parent.handle_message_local(
                        PaxosDecision(self.executed_through, e.command))
            elif e.command is not None:
                result = self.app.execute(e.command)
                if result is not None:
                    self.send(PaxosReply(result), e.command.client_address)
        if self._is_leader_ballot():
            self.peer_executed[self.address] = self.executed_through
            self._maybe_gc()

    # -------------------------------------------------- heartbeats / catchup

    def _send_heartbeats(self) -> None:
        hb = Heartbeat(self.ballot, self.executed_through, self.gc_through)
        self.broadcast(hb, [s for s in self.servers if s != self.address])

    def on_HeartbeatTimer(self, t: HeartbeatTimer) -> None:
        if t.ballot != self.ballot or not self._is_leader_ballot():
            return  # stale chain or deposed: stop heartbeating
        self._send_heartbeats()
        # Retransmit P2as for in-flight slots (a lost P2a/P2b would otherwise
        # stall the slot forever: client retries are absorbed by proposed_seq
        # and heartbeats suppress elections).
        for slot in range(self.executed_through + 1, self.slot_in):
            e = self.log.get(slot)
            if e is not None and not e.chosen:
                self._send_p2a(slot)
        self.set_timer(HeartbeatTimer(self.ballot), HEARTBEAT_MILLIS)

    def handle_Heartbeat(self, m: Heartbeat, sender: Address) -> None:
        if m.ballot < self.ballot:
            return
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.leader = False
        self.heard_from_leader = True
        self._gc_to(m.gc_through)
        if self.executed_through < m.commit:
            self.send(CatchupRequest(self.executed_through + 1), sender)
        self.send(HeartbeatReply(self.ballot, self.executed_through), sender)

    def handle_HeartbeatReply(self, m: HeartbeatReply, sender: Address) -> None:
        if m.ballot != self.ballot or not self._is_leader_ballot():
            return
        self.peer_executed[sender] = max(
            self.peer_executed.get(sender, 0), m.executed_through)
        self._maybe_gc()

    def _maybe_gc(self) -> None:
        # GC requires EVERY server to have executed the slot (a lagging
        # server still needs the entries to catch up).
        if len(self.peer_executed) < len(self.servers):
            return
        floor = min(self.peer_executed.values())
        if floor > self.gc_through:
            self.gc_through = floor
            self._gc_to(floor)

    def _gc_to(self, through: int) -> None:
        through = min(through, self.executed_through)
        if through <= self.cleared_through:
            return
        for slot in range(self.cleared_through + 1, through + 1):
            self.log.pop(slot, None)
        self.cleared_through = through

    def handle_CatchupRequest(self, m: CatchupRequest, sender: Address) -> None:
        entries = []
        slot = max(m.from_slot, self.cleared_through + 1)
        # Cap the reply so repeated requests from a lagging follower don't
        # flood the network with full-backlog copies.
        while slot <= self.executed_through and len(entries) < 100:
            e = self.log.get(slot)
            if e is None or not e.chosen:
                break
            entries.append((slot, e.command))
            slot += 1
        if entries:
            self.send(CatchupReply(tuple(entries)), sender)

    def handle_CatchupReply(self, m: CatchupReply, sender: Address) -> None:
        for slot, command in m.entries:
            if slot <= self.cleared_through:
                continue
            e = self.log.get(slot)
            if e is None or not e.chosen:
                self.log[slot] = _LogEntry(self.ballot, command, True)
        self._execute_chosen()


class PaxosClient(SyncClientMixin, Node, Client):
    """Any-server retry client (PaxosClient.java:13-64): broadcast the
    pending command to every server; whichever executes it replies; retry on
    a 100ms timer."""

    def __init__(self, address: Address, servers: Tuple[Address, ...]):
        super().__init__(address)
        self.servers = tuple(servers)
        self.seq_num = 0
        self.pending: Optional[AMOCommand] = None
        self.result: Optional[Result] = None

    def init(self) -> None:
        pass

    def send_command(self, command: Command) -> None:
        self.seq_num += 1
        amo = AMOCommand(command, self.address, self.seq_num)
        self.pending = amo
        self.result = None
        self.broadcast(PaxosRequest(amo), self.servers)
        self.set_timer(ClientTimer(self.seq_num), CLIENT_RETRY_MILLIS)

    def has_result(self) -> bool:
        return self.result is not None

    def _take_result(self) -> Result:
        return self.result

    def handle_PaxosReply(self, m: PaxosReply, sender: Address) -> None:
        if (self.pending is not None
                and m.result.sequence_num == self.pending.sequence_num):
            self.result = m.result.result
            self.pending = None
            self._notify_result()

    def on_ClientTimer(self, t: ClientTimer) -> None:
        if self.pending is not None and t.sequence_num == self.pending.sequence_num:
            self.broadcast(PaxosRequest(self.pending), self.servers)
            self.set_timer(ClientTimer(self.pending.sequence_num),
                           CLIENT_RETRY_MILLIS)
