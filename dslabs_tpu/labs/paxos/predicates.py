"""Log-consistency predicates for lab 3.

Behavioural port of the invariant machinery inside PaxosTest.java:113-346
(MARKERS_VALID, slotValid, LOGS_CONSISTENT, LOGS_CONSISTENT_ALL_SLOTS,
hasStatus/hasCommand helpers).  These drive both the object-graph checker and
(via host fallback) the TPU search backend.
"""

from __future__ import annotations

from typing import Optional, Tuple

from dslabs_tpu.labs.clientserver.amo import AMOCommand
from dslabs_tpu.labs.paxos.paxos import PaxosLogSlotStatus as S
from dslabs_tpu.testing.predicates import StatePredicate

__all__ = ["MARKERS_VALID", "LOGS_CONSISTENT", "LOGS_CONSISTENT_ALL_SLOTS",
           "slot_valid", "has_status", "has_command"]


def _check_markers(st) -> Tuple[bool, Optional[str]]:
    for a, p in st.servers.items():
        nc = p.first_non_cleared()
        ne = p.last_non_empty()
        if nc < 1:
            return False, f"{a} returned {nc} as first non-cleared slot"
        if ne < 0:
            return False, f"{a} returned {ne} as last non-empty slot"
        if p.status(nc) == S.CLEARED:
            return False, (f"{a} first non-cleared {nc} has status CLEARED")
        if ne > 0 and p.status(ne) == S.EMPTY:
            return False, f"{a} last non-empty {ne} has status EMPTY"
        if nc > 1 and p.status(nc - 1) != S.CLEARED:
            return False, f"{a} slot before first non-cleared {nc} isn't CLEARED"
        if p.status(ne + 1) != S.EMPTY:
            return False, f"{a} slot after last non-empty {ne} isn't EMPTY"
        if nc > ne + 1:
            return False, (f"{a} first non-cleared {nc} > last non-empty {ne} + 1")
    return True, None


MARKERS_VALID = StatePredicate(
    "First non-cleared and last non-empty valid", _check_markers,
    tkey=("PAXOS_MARKERS_VALID",))


def _slot_valid(st, i: int) -> Tuple[bool, Optional[str]]:
    chosen_cmd = None
    is_chosen = False
    is_cleared = False
    for a, p in st.servers.items():
        nc, ne = p.first_non_cleared(), p.last_non_empty()
        s, c = p.status(i), p.command(i)
        if i < nc and s != S.CLEARED:
            return False, f"{a} slot {i} status {s} but firstNonCleared {nc}"
        if i > ne and s != S.EMPTY:
            return False, f"{a} slot {i} status {s} but lastNonEmpty {ne}"
        if s in (S.EMPTY, S.CLEARED) and c is not None:
            return False, f"{a} slot {i} status {s} but returned command {c}"
        if isinstance(c, AMOCommand):
            return False, f"{a} returned an AMOCommand for slot {i}"
        if s == S.CLEARED:
            is_cleared = True
        if s == S.CHOSEN:
            if is_chosen and chosen_cmd != c:
                return False, (f"Two different commands ({chosen_cmd} and {c}) "
                               f"chosen for slot {i}")
            chosen_cmd = c
            is_chosen = True
    if not is_chosen and not is_cleared:
        return True, None
    count = 0
    for p in st.servers.values():
        s, c = p.status(i), p.command(i)
        if s != S.EMPTY and (s != S.ACCEPTED or not is_chosen or chosen_cmd == c):
            count += 1
    if 2 * count <= len(st.servers):
        if is_chosen:
            return False, (f"{chosen_cmd} chosen for slot {i} without a "
                           f"majority accepting")
        return False, f"Slot {i} cleared without a majority accepting"
    return True, None


def slot_valid(i: int) -> StatePredicate:
    return StatePredicate(f"Logs consistent for slot {i}",
                          lambda st: _slot_valid(st, i),
                          tkey=("PAXOS_SLOT_VALID", i))


def _logs_consistent(st, all_slots: bool) -> Tuple[bool, Optional[str]]:
    ok, msg = _check_markers(st)
    if not ok:
        return ok, msg
    min_nc = min((p.first_non_cleared() for p in st.servers.values()), default=1)
    max_ne = max((p.last_non_empty() for p in st.servers.values()), default=0)
    start = 1 if all_slots else min_nc
    for i in range(start, max_ne + 1):
        ok, msg = _slot_valid(st, i)
        if not ok:
            return ok, msg
    return True, None


LOGS_CONSISTENT = StatePredicate(
    "Active log slots consistent", lambda st: _logs_consistent(st, False),
    tkey=("PAXOS_LOGS_CONSISTENT", False))

LOGS_CONSISTENT_ALL_SLOTS = StatePredicate(
    "Non-empty log slots consistent", lambda st: _logs_consistent(st, True),
    tkey=("PAXOS_LOGS_CONSISTENT", True))


def has_status(a, i: int, status: str) -> StatePredicate:
    return StatePredicate(f"{a} has status {status} in slot {i}",
                          lambda st: st.servers[a].status(i) == status,
                          tkey=("PAXOS_HAS_STATUS", a, i, status))


def has_command(a, i: int, c) -> StatePredicate:
    return StatePredicate(f"{a} has command {c} in slot {i}",
                          lambda st: st.servers[a].command(i) == c,
                          tkey=("PAXOS_HAS_COMMAND", a, i, c))
