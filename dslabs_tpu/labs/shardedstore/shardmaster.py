"""Lab 4, part 1a: the ShardMaster application.

Behavioural port of labs/lab4-shardedstore/src/dslabs/shardmaster/
ShardMaster.java:1-100 with semantics fixed by ShardMasterTest.java:43-372:

  * Configs are numbered from INITIAL_CONFIG_NUM=0 (created by the first
    Join, which maps every shard to that group).
  * Join/Leave rebalance deterministically, moving as few shards as
    possible, to |max - min| <= 1 (test05/test08): joins drain one shard at
    a time from the largest group into the newcomer until it holds
    numShards // numGroups, then keep draining largest->smallest until
    balanced; leaves feed the departed group's shards to the smallest
    groups one at a time.  Ties break on the lowest group id.
  * Move relocates exactly one shard, no rebalance (test07).
  * Query(n): n < 0 means latest; n >= latest returns latest; historical
    configs are retained verbatim (test06).  Errors: re-Join, unknown
    Leave/group Move, out-of-range shard, no-op Move, Query before any
    config, Leave of the last group.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.types import Application, Command, Result
from dslabs_tpu.utils.structural import StructEq

__all__ = ["ShardMaster", "Join", "Leave", "Move", "Query", "Ok", "Error",
           "ShardConfig", "INITIAL_CONFIG_NUM"]

INITIAL_CONFIG_NUM = 0


class ShardMasterCommand(Command):
    pass


@dataclass(frozen=True)
class Join(ShardMasterCommand):
    group_id: int
    servers: FrozenSet[Address]

    def __init__(self, group_id: int, servers):
        object.__setattr__(self, "group_id", group_id)
        object.__setattr__(self, "servers", frozenset(servers))


@dataclass(frozen=True)
class Leave(ShardMasterCommand):
    group_id: int


@dataclass(frozen=True)
class Move(ShardMasterCommand):
    group_id: int
    shard_num: int


@dataclass(frozen=True)
class Query(ShardMasterCommand):
    config_num: int

    def read_only(self) -> bool:
        return True


class ShardMasterResult(Result):
    pass


@dataclass(frozen=True)
class Ok(ShardMasterResult):
    pass


@dataclass(frozen=True)
class Error(ShardMasterResult):
    pass


@dataclass(frozen=True)
class ShardConfig(ShardMasterResult):
    config_num: int
    # group id -> (members, shard numbers)
    group_info: Tuple[Tuple[int, Tuple[FrozenSet[Address], FrozenSet[int]]], ...]

    def __init__(self, config_num: int, group_info):
        object.__setattr__(self, "config_num", config_num)
        if isinstance(group_info, dict):
            group_info = tuple(sorted(
                (g, (frozenset(members), frozenset(shards)))
                for g, (members, shards) in group_info.items()))
        object.__setattr__(self, "group_info", group_info)

    def groups(self) -> Dict[int, Tuple[FrozenSet[Address], FrozenSet[int]]]:
        return dict(self.group_info)

    def shards_for(self, group_id: int) -> FrozenSet[int]:
        return self.groups()[group_id][1]

    def group_of(self, shard: int) -> int:
        for g, (_, shards) in self.group_info:
            if shard in shards:
                return g
        raise KeyError(shard)


class ShardMaster(Application, StructEq):

    def __init__(self, num_shards: int):
        self.num_shards = num_shards
        self.configs: List[ShardConfig] = []
        # group id -> members (live view used to build the next config)
        self.groups: Dict[int, FrozenSet[Address]] = {}
        self.shards: Dict[int, List[int]] = {}  # group id -> sorted shards

    # ----------------------------------------------------------- rebalancing

    def _largest(self) -> int:
        return max(self.shards, key=lambda g: (len(self.shards[g]), -g))

    def _smallest(self) -> int:
        return min(self.shards, key=lambda g: (len(self.shards[g]), g))

    def _snapshot(self) -> None:
        num = (self.configs[-1].config_num + 1 if self.configs
               else INITIAL_CONFIG_NUM)
        self.configs.append(ShardConfig(num, {
            g: (self.groups[g], frozenset(s)) for g, s in self.shards.items()}))

    def _balanced(self) -> bool:
        sizes = [len(s) for s in self.shards.values()]
        return max(sizes) - min(sizes) <= 1

    def _move_one(self, frm: int, to: int) -> None:
        shard = self.shards[frm].pop()  # highest-numbered shard: deterministic
        self.shards[to].append(shard)
        self.shards[to].sort()

    # -------------------------------------------------------------- commands

    def execute(self, command: Command) -> Result:
        if isinstance(command, Join):
            if command.group_id in self.groups:
                return Error()
            self.groups[command.group_id] = command.servers
            if not self.shards:
                self.shards[command.group_id] = list(
                    range(1, self.num_shards + 1))
            else:
                self.shards[command.group_id] = []
                target = self.num_shards // len(self.shards)
                while len(self.shards[command.group_id]) < target:
                    self._move_one(self._largest(), command.group_id)
                while not self._balanced():
                    self._move_one(self._largest(), self._smallest())
            self._snapshot()
            return Ok()

        if isinstance(command, Leave):
            if command.group_id not in self.groups or len(self.groups) == 1:
                return Error()
            del self.groups[command.group_id]
            orphaned = self.shards.pop(command.group_id)
            for shard in sorted(orphaned):
                g = self._smallest()
                self.shards[g].append(shard)
                self.shards[g].sort()
            self._snapshot()
            return Ok()

        if isinstance(command, Move):
            g, shard = command.group_id, command.shard_num
            if (g not in self.groups or shard < 1 or shard > self.num_shards
                    or shard in self.shards[g]):
                return Error()
            for other in self.shards.values():
                if shard in other:
                    other.remove(shard)
            self.shards[g].append(shard)
            self.shards[g].sort()
            self._snapshot()
            return Ok()

        if isinstance(command, Query):
            if not self.configs:
                return Error()
            n = command.config_num
            if n < 0 or n >= len(self.configs):
                return self.configs[-1]
            return self.configs[n]

        raise ValueError(f"Unknown ShardMaster command: {command!r}")
