"""Lab 4, parts 1b/2b: the sharded, reconfigurable KV store.

The reference ships these as skeletons (labs/lab4-shardedstore/src/dslabs/
shardkv/ShardStoreServer.java, ShardStoreClient.java, ShardStoreNode.java:40-66
fixes ``keyToShard``); the protocol below is designed to the acceptance spec
in ShardStoreBaseTest/ShardStorePart1Test/ShardStorePart2Test:

  * Each replica group runs a **Paxos sub-node** (the add_sub_node pattern,
    Node.java:149-171) in relay mode: every state change — client commands,
    config changes, shard installs, handoff completions, 2PC votes — is a
    command in the group's replicated log, and the executor that consumes
    ``PaxosDecision``s is a deterministic function of that log, so all
    replicas converge.
  * **Reconfiguration** is processed one config at a time: the group leader
    polls the shard masters (Query(next)); a NewConfig decision diffs shard
    ownership, snapshots outgoing shards (KV pairs + AMO dedup state, which
    must travel with the shard), and marks incoming shards unservable until
    a ShardMove arrives and its InstallShards decision executes.  Handoff
    completion (MoveDone) frees the snapshot; the next config is only
    adopted once the current handoff has fully drained.
  * **Routing**: clients learn the config from the shard masters, broadcast
    to the owning group, and re-query on WrongGroup.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Optional, Tuple

from dslabs_tpu.core.address import Address, SubAddress
from dslabs_tpu.core.client_utils import SyncClientMixin
from dslabs_tpu.core.node import Node
from dslabs_tpu.core.types import Client, Command, Message, Result, Timer
from dslabs_tpu.labs.clientserver.amo import AMOApplication, AMOCommand, AMOResult
from dslabs_tpu.labs.paxos.paxos import (PaxosDecision, PaxosRequest,
                                         PaxosReply, PaxosServer)
from dslabs_tpu.labs.shardedstore.shardmaster import Query, ShardConfig
from dslabs_tpu.labs.shardedstore.txkvstore import (Transaction,
                                                    TransactionalKVStore)

__all__ = ["ShardStoreNode", "ShardStoreServer", "ShardStoreClient",
           "ShardStoreRequest", "ShardStoreReply", "WrongGroup",
           "key_to_shard", "CLIENT_RETRY_MILLIS", "QUERY_MILLIS"]

CLIENT_RETRY_MILLIS = 100
QUERY_MILLIS = 50
PAXOS_ID = "paxos"


def _java_string_hash(s: str) -> int:
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    if h >= 2 ** 31:
        h -= 2 ** 32
    return h


def key_to_shard(key: str, num_shards: int) -> int:
    """Shard of ``key`` in 1..num_shards: trailing digits (mod num_shards)
    when present, else a deterministic string hash
    (ShardStoreNode.java:40-66; Python's salted hash() is unusable here).
    The digit accumulation wraps at 32 bits like Java int arithmetic, so
    keys with 10+ trailing digits map exactly as the reference does."""
    i = len(key)
    while i > 0 and key[i - 1].isdigit():
        i -= 1
    digits = key[i:]
    if digits:
        h = 0
        for d in digits:
            h = (h * 10 + int(d)) & 0xFFFFFFFF
        if h >= 2 ** 31:
            h -= 2 ** 32
    else:
        h = _java_string_hash(key)
    mod = h % num_shards
    if mod <= 0:
        mod += num_shards
    return mod


# ----------------------------------------------------------------- messages

@dataclass(frozen=True)
class ShardStoreRequest(Message):
    command: AMOCommand


@dataclass(frozen=True)
class ShardStoreReply(Message):
    result: AMOResult


@dataclass(frozen=True)
class WrongGroup(Message):
    sequence_num: int


@dataclass(frozen=True)
class ShardMove(Message):
    config_num: int
    from_group: int
    shards: FrozenSet[int]
    kv: Tuple[Tuple[str, str], ...]
    amo: Tuple[Tuple[Address, Tuple[int, AMOResult]], ...]


@dataclass(frozen=True)
class ShardMoveAck(Message):
    config_num: int
    shards: FrozenSet[int]


# ------------------------------------------------- replicated log commands

@dataclass(frozen=True)
class NewConfig(Command):
    config: ShardConfig


@dataclass(frozen=True)
class InstallShards(Command):
    config_num: int
    from_group: int
    shards: FrozenSet[int]
    kv: Tuple[Tuple[str, str], ...]
    amo: Tuple[Tuple[Address, Tuple[int, AMOResult]], ...]


@dataclass(frozen=True)
class MoveDone(Command):
    config_num: int
    to_group: int
    shards: FrozenSet[int]


# ------------------------------------------------------------- 2PC protocol
# Cross-group transactions run two-phase commit with shard-level locking:
# the coordinator (group owning the smallest shard of the key set) drives
# prepares/votes/decisions; conflicts vote abort (no waiting => no
# deadlock) and the client's retry restarts the transaction.  Each type is
# both a Message (between groups) and a Command (proposed verbatim into the
# receiving group's replicated log so all replicas process it).

TxId = Tuple[Address, int]  # (client address, sequence number)


@dataclass(frozen=True)
class TxPrepare(Message, Command):
    tx: AMOCommand
    round: int  # retry round; stale-round votes/decisions are ignored
    coordinator_group: int
    # The coordinator's config when it computed the participant set.  A
    # participant on a DIFFERENT config votes abort: a config-lagging
    # group can believe it owns none of the tx's shards, in which case
    # "my_shards <= owned" is vacuously true and it would vote yes with
    # no values and no locks — committing a transaction whose writes it
    # then silently drops (observed as a lost MultiPut write under
    # unreliable delivery in test06).
    config_num: int
    # The coordinator group's members, so the abort vote can be routed
    # even when the voter's config no longer lists the coordinator group
    # (e.g. it was removed by a Leave the voter already installed).
    coordinator_members: Tuple[Address, ...]


@dataclass(frozen=True)
class TxVote(Message, Command):
    tx_id: TxId
    round: int
    group_id: int
    ok: bool
    # current values of the tx's keys owned by the voter (missing = absent)
    values: Tuple[Tuple[str, str], ...]


@dataclass(frozen=True)
class TxDecision(Message, Command):
    tx_id: TxId
    round: int
    coordinator_group: int
    commit: bool
    # key -> new value (None = delete); each group applies its owned keys
    writes: Tuple[Tuple[str, Optional[str]], ...]


@dataclass(frozen=True)
class TxAck(Message, Command):
    tx_id: TxId
    round: int
    group_id: int


# -------------------------------------------------------------------- timers

@dataclass(frozen=True)
class ClientTimer(Timer):
    sequence_num: int


@dataclass(frozen=True)
class QueryTimer(Timer):
    pass


# --------------------------------------------------------------------- nodes

class ShardStoreNode(Node):

    def __init__(self, address: Address, shard_masters: Tuple[Address, ...],
                 num_shards: int):
        super().__init__(address)
        self.shard_masters = tuple(shard_masters)
        self.num_shards = num_shards

    def key_to_shard(self, key: str) -> int:
        return key_to_shard(key, self.num_shards)

    def command_shards(self, command: Command) -> FrozenSet[int]:
        if isinstance(command, Transaction):
            return frozenset(self.key_to_shard(k) for k in command.key_set())
        return frozenset((self.key_to_shard(command.key),))

    def broadcast_to_shard_masters(self, message: Message) -> None:
        self.broadcast(message, self.shard_masters)


class ShardStoreServer(ShardStoreNode):

    def __init__(self, address: Address, shard_masters: Tuple[Address, ...],
                 num_shards: int, group: Tuple[Address, ...], group_id: int):
        super().__init__(address, shard_masters, num_shards)
        self.group = tuple(group)
        self.group_id = group_id
        self.app = AMOApplication(TransactionalKVStore())
        self.current_config: Optional[ShardConfig] = None
        self.owned: FrozenSet[int] = frozenset()
        self.incoming: FrozenSet[int] = frozenset()
        # (config_num, dest group) -> (shards, kv snapshot, amo snapshot)
        self.outgoing: Dict[Tuple[int, int], Tuple[FrozenSet[int],
                                                   Tuple, Tuple]] = {}
        self.qseq = 0
        # --- 2PC state (deterministic function of the group log) ---
        self.locks: Dict[int, "TxId"] = {}  # shard -> holding tx
        # participant side: tx_id -> (tx, coordinator_group, ok, values)
        self.prepared: Dict["TxId", Tuple[AMOCommand, int, bool, Tuple]] = {}
        # coordinator side: tx_id -> [tx, votes{group: (ok, values)},
        #                             decision(None/bool), writes, acked set]
        self.coord: Dict["TxId", list] = {}
        self.tx_round: Dict["TxId", int] = {}  # latest 2PC round per tx
        self.tx_done: Dict["TxId", bool] = {}  # finished txs (True = committed)

    def init(self) -> None:
        paxos_addr = SubAddress(self.address, PAXOS_ID)
        group_paxos = tuple(SubAddress(a, PAXOS_ID) for a in self.group)
        paxos = PaxosServer(paxos_addr, group_paxos, None)  # relay mode
        self.add_sub_node(paxos)
        paxos.init()
        self.set_timer(QueryTimer(), QUERY_MILLIS)

    # ------------------------------------------------------------- utilities

    @property
    def paxos(self) -> PaxosServer:
        return self.sub_nodes[PAXOS_ID]

    def _propose(self, command: Command) -> None:
        """Feed a command into the group's replicated log via the local
        Paxos sub-node (it forwards to the group leader if necessary)."""
        self.paxos.handle_message_local(PaxosRequest(command))

    def _next_config_num(self) -> int:
        return self.current_config.config_num + 1 if self.current_config is not None else 0

    def _my_shards(self, config: ShardConfig) -> FrozenSet[int]:
        info = config.groups().get(self.group_id)
        return info[1] if info is not None else frozenset()

    def _reconfig_done(self) -> bool:
        # Handoff fully drained AND no 2PC state outstanding: moving a shard
        # mid-transaction would strand its prepared locks and lose the
        # transaction's committed writes on the departed shard.
        return (not self.incoming and not self.outgoing and not self.locks
                and not self.prepared and not self.coord)

    def _snapshot_for(self, shards: FrozenSet[int]):
        kv = tuple(sorted(
            (k, v) for k, v in self.app.application.store.items()
            if self.key_to_shard(k) in shards))
        amo = tuple(sorted(
            ((c, (seq, res)) for c, (seq, res) in self.app.last.items()),
            key=lambda e: str(e[0])))
        return kv, amo

    def _merge_amo(self, amo) -> None:
        for client, (seq, res) in amo:
            cur = self.app.last.get(client)
            if cur is None or seq > cur[0]:
                self.app.last[client] = (seq, res)

    # --------------------------------------------------- network handlers

    def handle_ShardStoreRequest(self, m: ShardStoreRequest, sender: Address) -> None:
        self._propose(m.command)

    def handle_PaxosReply(self, m: PaxosReply, sender: Address) -> None:
        """Reply from the shard-master Paxos group to our config query."""
        cfg = m.result.result
        if (isinstance(cfg, ShardConfig)
                and cfg.config_num == self._next_config_num()
                and self._reconfig_done()):
            self._propose(NewConfig(cfg))

    def handle_ShardMove(self, m: ShardMove, sender: Address) -> None:
        if self.current_config is None or m.config_num > self.current_config.config_num:
            return  # we haven't reached this config yet; sender retries
        if m.config_num < self.current_config.config_num or m.shards <= self.owned:
            # Already installed (possibly long ago): re-ack so the sender
            # can complete its handoff even if earlier acks were lost.
            self.send(ShardMoveAck(m.config_num, m.shards), sender)
            return
        self._propose(InstallShards(m.config_num, m.from_group, m.shards,
                                    m.kv, m.amo))

    def handle_TxPrepare(self, m: TxPrepare, sender: Address) -> None:
        self._propose(m)

    def handle_TxVote(self, m: TxVote, sender: Address) -> None:
        self._propose(m)

    def handle_TxDecision(self, m: TxDecision, sender: Address) -> None:
        self._propose(m)

    def handle_TxAck(self, m: TxAck, sender: Address) -> None:
        self._propose(m)

    def handle_ShardMoveAck(self, m: ShardMoveAck, sender: Address) -> None:
        for (config_num, to_group), (shards, _, _) in self.outgoing.items():
            if config_num == m.config_num and shards == m.shards:
                self._propose(MoveDone(config_num, to_group, shards))
                return

    # ------------------------------------------------------------- decisions

    def handle_PaxosDecision(self, m: PaxosDecision, sender: Address) -> None:
        c = m.command
        if isinstance(c, AMOCommand):
            self._execute_client_command(c)
        elif isinstance(c, NewConfig):
            self._apply_new_config(c.config)
        elif isinstance(c, InstallShards):
            self._apply_install(c)
        elif isinstance(c, MoveDone):
            self.outgoing.pop((c.config_num, c.to_group), None)
        elif isinstance(c, TxPrepare):
            self._apply_tx_prepare(c)
        elif isinstance(c, TxVote):
            self._apply_tx_vote(c)
        elif isinstance(c, TxDecision):
            self._apply_tx_decision(c)
        elif isinstance(c, TxAck):
            entry = self.coord.get(c.tx_id)
            if entry is not None and entry[5] == c.round:
                entry[4] = entry[4] | {c.group_id}
                if entry[4] >= self._participant_groups(entry[0].command):
                    del self.coord[c.tx_id]

    def _execute_client_command(self, c: AMOCommand) -> None:
        shards = self.command_shards(c.command)
        if self.current_config is None:
            return
        mine = self._my_shards(self.current_config)
        if not shards <= mine:
            if (isinstance(c.command, Transaction)
                    and min(shards) in mine):
                self._coordinate_tx(c)
                return
            self.send(WrongGroup(c.sequence_num), c.client_address)
            return
        if not shards <= self.owned:
            return  # shards still in flight; the client retries
        if any(s in self.locks for s in shards):
            return  # a cross-group tx holds these shards; client retries
        result = self.app.execute(c)
        if result is not None:
            self.send(ShardStoreReply(result), c.client_address)

    # ------------------------------------------------------------------ 2PC

    def _tx_id(self, c: AMOCommand):
        return (c.client_address, c.sequence_num)

    def _participant_groups(self, tx: Command) -> FrozenSet[int]:
        cfg = self.current_config
        shards = self.command_shards(tx)
        return frozenset(g for g, (_, g_shards) in cfg.group_info
                         if shards & g_shards)

    def _coordinate_tx(self, c: AMOCommand) -> None:
        """Coordinator executor path for a multi-group transaction."""
        tx_id = self._tx_id(c)
        if self.app.already_executed(c):
            result = self.app.execute(c)
            if result is not None:
                self.send(ShardStoreReply(result), c.client_address)
            return
        if tx_id in self.coord:
            return  # already in progress; retries are absorbed
        rnd = self.tx_round.get(tx_id, 0) + 1
        self.tx_round[tx_id] = rnd
        self.coord[tx_id] = [c, {}, None, (), frozenset(), rnd]
        if self.paxos.is_leader():
            self._send_prepares(tx_id)

    def _send_prepares(self, tx_id) -> None:
        entry = self.coord[tx_id]
        prepare = TxPrepare(entry[0], entry[5], self.group_id,
                            self.current_config.config_num, self.group)
        groups = self.current_config.groups()
        for g in self._participant_groups(entry[0].command):
            if g not in entry[1]:
                self.broadcast(prepare, groups[g][0])

    def _apply_tx_prepare(self, c: TxPrepare) -> None:
        tx_id = self._tx_id(c.tx)
        if self.current_config is None:
            return
        done = self.tx_done.get(tx_id)
        if done is not None:
            self._send_vote_to(c.coordinator_group,
                               TxVote(tx_id, c.round, self.group_id, True, ()))
            return
        if self.current_config.config_num != c.config_num:
            # Config mismatch: our shard view disagrees with the
            # coordinator's participant computation — vote abort so the
            # client retries after the configs converge (see TxPrepare).
            # Routed via the prepare's own member list: the coordinator
            # group may be absent from OUR config (a Leave we already
            # installed), and a dropped vote would wedge it forever.
            if self.paxos.is_leader():
                self.broadcast(TxVote(tx_id, c.round, self.group_id,
                                      False, ()), c.coordinator_members)
            return
        cur = self.prepared.get(tx_id)
        if cur is not None and cur[4] != c.round:
            if cur[4] < c.round:
                # A newer round supersedes our stale prepare: release it and
                # re-prepare below (its votes can no longer be accepted).
                for sh in [sh for sh, t in self.locks.items() if t == tx_id]:
                    del self.locks[sh]
                del self.prepared[tx_id]
            else:
                return  # stale prepare from an older round: ignore
        if tx_id not in self.prepared:
            my_shards = (self.command_shards(c.tx.command)
                         & self._my_shards(self.current_config))
            conflict = any(self.locks.get(s, tx_id) != tx_id
                           for s in my_shards)
            ok = not conflict and my_shards <= self.owned
            values = ()
            if ok:
                for s in my_shards:
                    self.locks[s] = tx_id
                store = self.app.application.store
                values = tuple(sorted(
                    (k, store[k]) for k in self._tx_keys(c.tx.command)
                    if self.key_to_shard(k) in my_shards and k in store))
            self.prepared[tx_id] = (c.tx, c.coordinator_group, ok, values,
                                    c.round)
        _, coord_group, ok, values, rnd = self.prepared[tx_id]
        self._send_vote_to(coord_group,
                           TxVote(tx_id, rnd, self.group_id, ok, values))

    @staticmethod
    def _tx_keys(tx: Command):
        return tx.key_set() if isinstance(tx, Transaction) else (tx.key,)

    def _send_vote_to(self, group_id: int, vote: TxVote) -> None:
        if not self.paxos.is_leader():
            return
        members = self.current_config.groups().get(group_id)
        if members is not None:
            self.broadcast(vote, members[0])

    def _apply_tx_vote(self, c: TxVote) -> None:
        entry = self.coord.get(c.tx_id)
        # The `entry[2] is not None` guard is load-bearing beyond plain
        # idempotence: a participant that voted YES for round r can later
        # emit ABORT for the SAME round (duplicate TxPrepare delivered
        # after it installed a newer config — the config-mismatch abort
        # path in _apply_tx_prepare).  Once the round's decision is
        # fixed, every late vote must be ignored or that interleaving
        # would flip a committed transaction to aborted after the
        # client already got its reply (pinned by
        # test_yes_then_abort_same_round_duplicate).
        if entry is None or entry[2] is not None or c.round != entry[5]:
            return
        entry[1][c.group_id] = (c.ok, c.values)
        participants = self._participant_groups(entry[0].command)
        votes = entry[1]
        if any(not ok for ok, _ in votes.values()):
            entry[2] = False
            entry[3] = ()
        elif set(votes) >= participants:
            # All yes: run the transaction over the gathered values.
            db = {}
            for ok, values in votes.values():
                db.update(dict(values))
            tx = entry[0].command
            result = tx.run(db)
            writes = tuple(sorted(
                (k, db.get(k)) for k in tx.write_set()))
            entry[2] = True
            entry[3] = writes
            # Record in the AMO cache so client retries get the result.
            amo_result = AMOResult(result, entry[0].sequence_num)
            cur = self.app.last.get(entry[0].client_address)
            if cur is None or entry[0].sequence_num > cur[0]:
                self.app.last[entry[0].client_address] = (
                    entry[0].sequence_num, amo_result)
            self.send(ShardStoreReply(amo_result), entry[0].client_address)
        else:
            return
        if self.paxos.is_leader():
            self._send_decision(c.tx_id)

    def _send_decision(self, tx_id) -> None:
        entry = self.coord[tx_id]
        decision = TxDecision(tx_id, entry[5], self.group_id, entry[2],
                              entry[3])
        groups = self.current_config.groups()
        for g in self._participant_groups(entry[0].command):
            if g not in entry[4]:
                self.broadcast(decision, groups[g][0])

    def _apply_tx_decision(self, c: TxDecision) -> None:
        p = self.prepared.get(c.tx_id)
        if p is not None and p[4] != c.round:
            p = None  # decision from another round: leave our prepare alone
        else:
            self.prepared.pop(c.tx_id, None)
        if p is not None:
            _, _, ok, _, _ = p
            if c.commit and ok:
                store = self.app.application.store
                my = self._my_shards(self.current_config)
                for k, v in c.writes:
                    if self.key_to_shard(k) in my:
                        if v is None:
                            store.pop(k, None)
                        else:
                            store[k] = v
                self.tx_done[c.tx_id] = True
            for s in [s for s, t in self.locks.items() if t == c.tx_id]:
                del self.locks[s]
        # Aborted coordinator entries are cleared so a client retry can
        # restart the transaction from scratch (stale-round decisions must
        # not clear a newer round's entry).
        entry = self.coord.get(c.tx_id)
        if entry is not None and entry[2] is False and entry[5] == c.round:
            del self.coord[c.tx_id]
        # Always ack (even duplicate decisions: an earlier ack may be lost).
        if self.paxos.is_leader() and self.current_config is not None:
            members = self.current_config.groups().get(c.coordinator_group)
            if members is not None:
                self.broadcast(TxAck(c.tx_id, c.round, self.group_id),
                               members[0])

    def _apply_new_config(self, cfg: ShardConfig) -> None:
        if cfg.config_num != self._next_config_num() or not self._reconfig_done():
            return
        mine_new = self._my_shards(cfg)
        if self.current_config is None:
            # The system's first config: shards start empty, no handoff.
            self.owned = mine_new
            self.current_config = cfg
            return
        lost = self.owned - mine_new
        gained = mine_new - self.owned
        for group_id, (_, g_shards) in cfg.group_info:
            to_g = lost & g_shards
            if to_g:
                kv, amo = self._snapshot_for(to_g)
                self.outgoing[(cfg.config_num, group_id)] = (to_g, kv, amo)
        for k in [k for k in self.app.application.store
                  if self.key_to_shard(k) in lost]:
            del self.app.application.store[k]
        self.owned = self.owned - lost
        self.incoming = gained
        self.current_config = cfg
        if self.paxos.is_leader():
            self._send_moves()

    def _apply_install(self, c: InstallShards) -> None:
        if (self.current_config is None or c.config_num != self.current_config.config_num
                or not c.shards <= self.incoming):
            return
        self.app.application.store.update(dict(c.kv))
        self._merge_amo(c.amo)
        self.owned = self.owned | c.shards
        self.incoming = self.incoming - c.shards
        if self.paxos.is_leader():
            self._send_ack(c)

    # -------------------------------------------------- leader side effects

    def _send_moves(self) -> None:
        if self.current_config is None:
            return
        groups = self.current_config.groups()
        for (config_num, to_group), (shards, kv, amo) in self.outgoing.items():
            if config_num != self.current_config.config_num:
                continue
            members = groups.get(to_group)
            if members is not None:
                self.broadcast(
                    ShardMove(config_num, self.group_id, shards, kv, amo),
                    members[0])

    def _send_ack(self, c: InstallShards) -> None:
        members = self.current_config.groups().get(c.from_group)
        if members is not None:
            self.broadcast(ShardMoveAck(c.config_num, c.shards), members[0])

    def on_QueryTimer(self, t: QueryTimer) -> None:
        if self.paxos.is_leader():
            if self._reconfig_done() or self.current_config is None:
                self.qseq += 1
                self.broadcast_to_shard_masters(PaxosRequest(AMOCommand(
                    Query(self._next_config_num()), self.address, self.qseq)))
            self._send_moves()
            for tx_id, entry in self.coord.items():
                if entry[2] is None:
                    self._send_prepares(tx_id)
                else:
                    self._send_decision(tx_id)
            for tx_id, (tx, coord_group, ok, values, rnd) in \
                    self.prepared.items():
                self._send_vote_to(coord_group,
                                   TxVote(tx_id, rnd, self.group_id, ok,
                                          values))
        self.set_timer(QueryTimer(), QUERY_MILLIS)


class ShardStoreClient(SyncClientMixin, ShardStoreNode, Client):

    def __init__(self, address: Address, shard_masters: Tuple[Address, ...],
                 num_shards: int):
        super().__init__(address, shard_masters, num_shards)
        self.current_config: Optional[ShardConfig] = None
        self.seq_num = 0
        self.qseq = 0
        self.pending: Optional[AMOCommand] = None
        self.result: Optional[Result] = None

    def init(self) -> None:
        self._query_config()

    def _query_config(self) -> None:
        self.qseq += 1
        self.broadcast_to_shard_masters(PaxosRequest(AMOCommand(
            Query(-1), self.address, self.qseq)))

    def _target_group(self) -> Optional[Tuple[Address, ...]]:
        if self.current_config is None or self.pending is None:
            return None
        shards = self.command_shards(self.pending.command)
        groups = self.current_config.groups()
        # Multi-group transactions go to the coordinator: the group owning
        # the smallest shard in the key set.
        for shard in sorted(shards):
            for _, (members, g_shards) in self.current_config.group_info:
                if shard in g_shards:
                    return tuple(members)
        return None

    def _send_pending(self) -> None:
        target = self._target_group()
        if target is not None:
            self.broadcast(ShardStoreRequest(self.pending), target)
        else:
            self._query_config()

    # ------------------------------------------------------ client interface

    def send_command(self, command: Command) -> None:
        self.seq_num += 1
        amo = AMOCommand(command, self.address, self.seq_num)
        self.pending = amo
        self.result = None
        self._send_pending()
        self.set_timer(ClientTimer(self.seq_num), CLIENT_RETRY_MILLIS)

    def has_result(self) -> bool:
        return self.result is not None

    def _take_result(self) -> Result:
        return self.result

    # -------------------------------------------------------------- handlers

    def handle_ShardStoreReply(self, m: ShardStoreReply, sender: Address) -> None:
        if (self.pending is not None
                and m.result.sequence_num == self.pending.sequence_num):
            self.result = m.result.result
            self.pending = None
            self._notify_result()

    def handle_WrongGroup(self, m: WrongGroup, sender: Address) -> None:
        if self.pending is not None and m.sequence_num == self.pending.sequence_num:
            self._query_config()

    def handle_PaxosReply(self, m: PaxosReply, sender: Address) -> None:
        cfg = m.result.result
        if isinstance(cfg, ShardConfig):
            if self.current_config is None or cfg.config_num > self.current_config.config_num:
                self.current_config = cfg
                if self.pending is not None:
                    self._send_pending()

    def on_ClientTimer(self, t: ClientTimer) -> None:
        if self.pending is not None and t.sequence_num == self.pending.sequence_num:
            self._query_config()
            self._send_pending()
            self.set_timer(ClientTimer(self.seq_num), CLIENT_RETRY_MILLIS)
