"""Lab 4, part 2a: the transactional key-value store application.

Behavioural port of labs/lab4-shardedstore/src/dslabs/kvstore/
TransactionalKVStore.java:16-152.  A Transaction is a single-round command
with a-priori read/write sets and a pure ``run(db)`` over the values of its
key set; MultiGet / MultiPut / Swap are the concrete transactions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Tuple

from dslabs_tpu.core.types import Command, Result
from dslabs_tpu.labs.clientserver.kvstore import KVStore, KVStoreCommand

__all__ = ["Transaction", "MultiGet", "MultiPut", "Swap", "MultiGetResult",
           "MultiPutOk", "SwapOk", "TransactionalKVStore", "KEY_NOT_FOUND"]

KEY_NOT_FOUND = "KeyNotFound"


class Transaction(KVStoreCommand):
    """Single-round transaction: read/write sets known a priori."""

    def read_set(self) -> FrozenSet[str]:
        raise NotImplementedError

    def write_set(self) -> FrozenSet[str]:
        raise NotImplementedError

    def key_set(self) -> FrozenSet[str]:
        return self.read_set() | self.write_set()

    def run(self, db: Dict[str, str]) -> Result:
        """Mutate ``db`` (the current values of key_set) in place; return
        the transaction's result."""
        raise NotImplementedError

    def read_only(self) -> bool:
        return not self.write_set()


@dataclass(frozen=True)
class MultiGet(Transaction):
    keys: FrozenSet[str]

    def __init__(self, keys):
        object.__setattr__(self, "keys", frozenset(keys))

    def read_set(self) -> FrozenSet[str]:
        return self.keys

    def write_set(self) -> FrozenSet[str]:
        return frozenset()

    def run(self, db: Dict[str, str]) -> Result:
        return MultiGetResult(
            {k: db.get(k, KEY_NOT_FOUND) for k in self.keys})


@dataclass(frozen=True)
class MultiPut(Transaction):
    values: Tuple[Tuple[str, str], ...]

    def __init__(self, values):
        if isinstance(values, dict):
            values = tuple(sorted(values.items()))
        object.__setattr__(self, "values", values)

    def read_set(self) -> FrozenSet[str]:
        return frozenset()

    def write_set(self) -> FrozenSet[str]:
        return frozenset(k for k, _ in self.values)

    def run(self, db: Dict[str, str]) -> Result:
        db.update(dict(self.values))
        return MultiPutOk()


@dataclass(frozen=True)
class Swap(Transaction):
    key1: str
    key2: str

    def read_set(self) -> FrozenSet[str]:
        return frozenset((self.key1, self.key2))

    def write_set(self) -> FrozenSet[str]:
        return self.read_set()

    def run(self, db: Dict[str, str]) -> Result:
        v1, v2 = db.get(self.key1), db.get(self.key2)
        if v2 is None:
            db.pop(self.key1, None)
        else:
            db[self.key1] = v2
        if v1 is None:
            db.pop(self.key2, None)
        else:
            db[self.key2] = v1
        return SwapOk()


@dataclass(frozen=True)
class MultiGetResult(Result):
    values: Tuple[Tuple[str, str], ...]

    def __init__(self, values):
        if isinstance(values, dict):
            values = tuple(sorted(values.items()))
        object.__setattr__(self, "values", values)

    def as_dict(self) -> Dict[str, str]:
        return dict(self.values)


@dataclass(frozen=True)
class MultiPutOk(Result):
    pass


@dataclass(frozen=True)
class SwapOk(Result):
    pass


class TransactionalKVStore(KVStore):

    def execute(self, command: Command) -> Result:
        if isinstance(command, Transaction):
            # Materialise the key-set view, run, and write back the writes.
            db = {k: self.store[k] for k in command.key_set()
                  if k in self.store}
            result = command.run(db)
            for k in command.write_set():
                if k in db:
                    self.store[k] = db[k]
                else:
                    self.store.pop(k, None)
            return result
        return super().execute(command)
