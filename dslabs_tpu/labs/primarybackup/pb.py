"""Lab 2, part 2: primary-backup replication on top of the ViewServer.

The reference ships this as a skeleton (labs/lab2-primarybackup/src/dslabs/
primarybackup/PBServer.java, PBClient.java — "Your code here"); the protocol
below is designed to the acceptance spec in PrimaryBackupTest.java:75-905:

  * Servers ping the ViewServer every PING_MILLIS with the number of the view
    they have adopted *and are ready to serve* — a primary with an unsynced
    backup keeps pinging the previous view number so the ViewServer cannot
    move past a view whose backup lacks the application state
    (test19MultipleFailuresSearch depends on this).
  * The primary wraps the application in AMOApplication (at-most-once,
    test08).  With a synced backup, each client request is forwarded and
    acked before the primary executes and replies, so an acknowledged write
    is always visible after failover (test06/test09/test18).  The primary
    admits one outstanding operation at a time, which fixes the order the
    backup applies operations without any sequencing protocol; concurrent
    requests are dropped and covered by client retries.
  * On adopting a view with a fresh backup the primary sends a full state
    transfer (the whole AMOApplication) and refuses client requests until it
    is acked.  Retries of forwards/transfers ride the ping timer.
  * The client polls the ViewServer for the current primary, retries its
    pending command on a 100ms timer, and re-polls the view on every retry so
    it finds the new primary after failover.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.client_utils import SyncClientMixin
from dslabs_tpu.core.node import Node
from dslabs_tpu.core.types import Application, Client, Command, Message, Result, Timer
from dslabs_tpu.labs.clientserver.amo import AMOApplication, AMOCommand, AMOResult
from dslabs_tpu.labs.primarybackup.viewserver import (GetView, Ping, View,
                                                      ViewReply)
from dslabs_tpu.utils.structural import clone

__all__ = ["Request", "Reply", "ForwardRequest", "ForwardAck", "StateTransfer",
           "StateTransferAck", "PingTimer", "ClientTimer", "PBServer",
           "PBClient", "PING_MILLIS", "CLIENT_RETRY_MILLIS"]

PING_MILLIS = 25  # Timers.java:13
CLIENT_RETRY_MILLIS = 100  # Timers.java:17


@dataclass(frozen=True)
class Request(Message):
    command: AMOCommand


@dataclass(frozen=True)
class Reply(Message):
    result: AMOResult


@dataclass(frozen=True)
class ForwardRequest(Message):
    view_num: int
    command: AMOCommand


@dataclass(frozen=True)
class ForwardAck(Message):
    view_num: int
    command: AMOCommand


@dataclass(frozen=True)
class StateTransfer(Message):
    view: View
    app: AMOApplication


@dataclass(frozen=True)
class StateTransferAck(Message):
    view_num: int


@dataclass(frozen=True)
class PingTimer(Timer):
    pass


@dataclass(frozen=True)
class ClientTimer(Timer):
    command: AMOCommand


class PBServer(Node):

    def __init__(self, address: Address, vsa: Address, app: Application):
        super().__init__(address)
        self.vsa = vsa
        self.app = AMOApplication(app)
        self.view: Optional[View] = None
        self.synced = True  # backup (if any) has our state / we have state
        self.pending: Optional[Tuple[Address, AMOCommand]] = None

    def init(self) -> None:
        self.send(Ping(0), self.vsa)
        self.set_timer(PingTimer(), PING_MILLIS)

    # ------------------------------------------------------------ view state

    def _is_primary(self) -> bool:
        return self.view is not None and self.view.primary == self.address

    def _is_backup(self) -> bool:
        return self.view is not None and self.view.backup == self.address

    def _acked_view_num(self) -> int:
        if self.view is None:
            return 0
        if self._is_primary() and self.view.backup is not None and not self.synced:
            # Not ready to serve this view: never acknowledge it (the
            # previous view of the same primary had number view_num - 1).
            return self.view.view_num - 1
        return self.view.view_num

    def _adopt(self, view: View) -> None:
        if self.view is not None and view.view_num <= self.view.view_num:
            return
        self.view = view
        self.pending = None
        if self._is_primary():
            if view.backup is not None:
                self.synced = False
                self.send(StateTransfer(view, clone(self.app)), view.backup)
            else:
                self.synced = True
        elif self._is_backup():
            self.synced = False  # wait for the state transfer
        else:
            self.synced = True

    # -------------------------------------------------------------- handlers

    def handle_ViewReply(self, m: ViewReply, sender: Address) -> None:
        self._adopt(m.view)

    def on_PingTimer(self, t: PingTimer) -> None:
        self.send(Ping(self._acked_view_num()), self.vsa)
        if self._is_primary() and self.view.backup is not None:
            if not self.synced:
                self.send(StateTransfer(self.view, clone(self.app)),
                          self.view.backup)
            elif self.pending is not None:
                self.send(ForwardRequest(self.view.view_num, self.pending[1]),
                          self.view.backup)
        self.set_timer(PingTimer(), PING_MILLIS)

    def handle_Request(self, m: Request, sender: Address) -> None:
        if not self._is_primary() or not self.synced:
            return  # not serving; the client retries
        if self.app.already_executed(m.command):
            result = self.app.execute(m.command)
            if result is not None:
                self.send(Reply(result), sender)
            return
        if self.view.backup is None:
            result = self.app.execute(m.command)
            if result is not None:
                self.send(Reply(result), sender)
            return
        if self.pending is not None:
            return  # one outstanding op at a time; client retries
        self.pending = (sender, m.command)
        self.send(ForwardRequest(self.view.view_num, m.command), self.view.backup)

    def handle_ForwardRequest(self, m: ForwardRequest, sender: Address) -> None:
        if (not self._is_backup() or m.view_num != self.view.view_num
                or not self.synced):
            return
        self.app.execute(m.command)  # AMO layer absorbs duplicates
        self.send(ForwardAck(m.view_num, m.command), sender)

    def handle_ForwardAck(self, m: ForwardAck, sender: Address) -> None:
        if (not self._is_primary() or self.view.view_num != m.view_num
                or self.pending is None or self.pending[1] != m.command):
            return
        client, command = self.pending
        self.pending = None
        result = self.app.execute(command)
        if result is not None:
            self.send(Reply(result), client)

    def handle_StateTransfer(self, m: StateTransfer, sender: Address) -> None:
        if m.view.backup != self.address:
            return
        self._adopt(m.view)  # the transfer may teach us the view itself
        if self.view.view_num != m.view.view_num:
            return  # we have adopted a newer view; stale transfer
        if not self.synced:
            self.app = clone(m.app)
            self.synced = True
        self.send(StateTransferAck(m.view.view_num), sender)

    def handle_StateTransferAck(self, m: StateTransferAck, sender: Address) -> None:
        if self._is_primary() and self.view.view_num == m.view_num:
            self.synced = True


class PBClient(SyncClientMixin, Node, Client):

    def __init__(self, address: Address, vsa: Address):
        super().__init__(address)
        self.vsa = vsa
        self.view: Optional[View] = None
        self.seq_num = 0
        self.pending: Optional[AMOCommand] = None
        self.result: Optional[Result] = None

    def init(self) -> None:
        self.send(GetView(), self.vsa)

    # ------------------------------------------------------ client interface

    def send_command(self, command: Command) -> None:
        self.seq_num += 1
        amo = AMOCommand(command, self.address, self.seq_num)
        self.pending = amo
        self.result = None
        self._send_pending()
        self.set_timer(ClientTimer(amo), CLIENT_RETRY_MILLIS)

    def has_result(self) -> bool:
        return self.result is not None

    def _take_result(self) -> Result:
        return self.result

    def _send_pending(self) -> None:
        if self.view is not None and self.view.primary is not None:
            self.send(Request(self.pending), self.view.primary)
        else:
            self.send(GetView(), self.vsa)

    # -------------------------------------------------------------- handlers

    def handle_ViewReply(self, m: ViewReply, sender: Address) -> None:
        if self.view is None or m.view.view_num > self.view.view_num:
            self.view = m.view
            if self.pending is not None:
                self._send_pending()

    def handle_Reply(self, m: Reply, sender: Address) -> None:
        if (self.pending is not None
                and m.result.sequence_num == self.pending.sequence_num):
            self.result = m.result.result
            self.pending = None
            self._notify_result()

    def on_ClientTimer(self, t: ClientTimer) -> None:
        if self.pending is not None and t.command == self.pending:
            self.send(GetView(), self.vsa)
            self._send_pending()
            self.set_timer(ClientTimer(self.pending), CLIENT_RETRY_MILLIS)
