"""Lab 2, part 1: the ViewServer.

Behavioural re-design of labs/lab2-primarybackup/src/dslabs/primarybackup/
(ViewServer.java:12-54, View.java:8, Messages.java:10-23, Timers.java:7-14),
with the view-change rules reverse-engineered from ViewServerTest.java:40-303:

  * A view is ``(view_num, primary, backup)``.  STARTUP_VIEWNUM=0 (no
    primary), INITIAL_VIEWNUM=1.
  * Servers ping every PING_MILLIS with the number of the latest view they
    have adopted; a ping from the current primary carrying the current view
    number *acks* the view.  A server missing DEAD_TICKS consecutive
    PingCheckTimer intervals is dead.
  * The view may only change once the current view has been acked
    (ViewServerTest test08/test10), and changes at most one step at a time
    (test12: consecutive views differ).  Change rules, evaluated after every
    ping and ping-check tick:
      - startup: the first alive server becomes primary of view 1 (test02);
      - primary dead and backup alive: backup promoted, first alive idle
        server (if any) becomes backup (test05/test07);
      - backup dead and primary alive: backup replaced by first alive idle
        server or dropped (test09);
      - no backup and an alive idle server exists: it becomes backup, even if
        the primary is currently dead (test12);
      - otherwise: no change — in particular a dead primary with no live
        backup freezes the view forever (crash-stop; test07 of
        PrimaryBackupTest).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.node import Node
from dslabs_tpu.core.types import Message, Timer

__all__ = ["View", "Ping", "GetView", "ViewReply", "PingCheckTimer",
           "ViewServer", "STARTUP_VIEWNUM", "INITIAL_VIEWNUM",
           "PING_CHECK_MILLIS", "DEAD_TICKS"]

STARTUP_VIEWNUM = 0
INITIAL_VIEWNUM = 1
PING_CHECK_MILLIS = 100  # Timers.java:8
DEAD_TICKS = 2


@dataclass(frozen=True)
class View:
    view_num: int
    primary: Optional[Address]
    backup: Optional[Address]


@dataclass(frozen=True)
class Ping(Message):
    view_num: int


@dataclass(frozen=True)
class GetView(Message):
    pass


@dataclass(frozen=True)
class ViewReply(Message):
    view: View


@dataclass(frozen=True)
class PingCheckTimer(Timer):
    pass


class ViewServer(Node):

    def __init__(self, address: Address):
        super().__init__(address)
        self.view = View(STARTUP_VIEWNUM, None, None)
        self.acked = False
        # Ticks since each known server's last ping, in first-ping order
        # (the order breaks ties when choosing an idle server — must be
        # deterministic for the model checker).
        self.ticks: Dict[Address, int] = {}

    def init(self) -> None:
        self.set_timer(PingCheckTimer(), PING_CHECK_MILLIS)

    # -------------------------------------------------------------- handlers

    def handle_Ping(self, m: Ping, sender: Address) -> None:
        self.ticks[sender] = 0
        if sender == self.view.primary and m.view_num == self.view.view_num:
            self.acked = True
        self._evaluate()
        self.send(ViewReply(self.view), sender)

    def handle_GetView(self, m: GetView, sender: Address) -> None:
        self.send(ViewReply(self.view), sender)

    def on_PingCheckTimer(self, t: PingCheckTimer) -> None:
        for a in self.ticks:
            self.ticks[a] += 1
        self._evaluate()
        self.set_timer(PingCheckTimer(), PING_CHECK_MILLIS)

    # ------------------------------------------------------------ view logic

    def _alive(self, a: Optional[Address]) -> bool:
        return a is not None and a in self.ticks and self.ticks[a] < DEAD_TICKS

    def _idle(self) -> Optional[Address]:
        for a, t in self.ticks.items():
            if t < DEAD_TICKS and a != self.view.primary and a != self.view.backup:
                return a
        return None

    def _evaluate(self) -> None:
        v = self.view
        if v.primary is None:
            first = self._idle()
            if first is not None:
                self._new_view(first, None)
            return
        if not self.acked:
            return
        if not self._alive(v.primary):
            if self._alive(v.backup):
                self._new_view(v.backup, self._idle())
            elif v.backup is None:
                idle = self._idle()
                if idle is not None:
                    self._new_view(v.primary, idle)
        elif v.backup is not None and not self._alive(v.backup):
            self._new_view(v.primary, self._idle())
        elif v.backup is None:
            idle = self._idle()
            if idle is not None:
                self._new_view(v.primary, idle)

    def _new_view(self, primary: Address, backup: Optional[Address]) -> None:
        self.view = View(self.view.view_num + 1, primary, backup)
        self.acked = False
