"""Host-RAM spill tier + capacity ladder (ISSUE 6, tpu/spill.py,
docs/capacity.md): strict searches survive HBM exhaustion with EXACT
counts, never a dropped state:

* strict DEPTH_EXHAUSTED with the device visited table capped at ~1/8
  of the reachable state count: exact unique/explored/verdict parity
  against the uncapped run and ``dropped_states == 0`` — single-device
  AND sharded engines (the acceptance criterion);
* a run SIGKILLed mid-spill resumes from the unified checkpoint to the
  identical verdict and counts (the dump's visited_keys is the exact
  device ∪ host-tier union, CRC-checked and .prev-rotated like every
  other dump);
* the supervisor's capacity ladder: ``CapacityOverflow`` becomes a
  classified, recoverable failure — the rung retries with spill
  enabled, resuming from checkpoint;
* the new spill dispatches (drain/evict/reinject) ride the standard
  ``_dispatch`` seam: FaultPlan site rules target them, transient
  faults retry in place, a hang is abandoned by the watchdog and the
  ladder fails over — verdict parity throughout;
* a spill checkpoint from a FOREIGN config is refused loudly
  (CheckpointMismatch), never resumed silently;
* the early-warning instrumentation (DSLABS_VISITED_WARN) and loud
  beam-drop accounting (DSLABS_DROPPED_WARN, dropped_states) fire
  before/at the degradations they describe.

Marked ``capacity`` (``make capacity-smoke``); paxos d5 additionally
``slow``.
"""

import dataclasses
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import checkpoint as ckpt_mod  # noqa: E402
from dslabs_tpu.tpu import spill as spill_mod  # noqa: E402
from dslabs_tpu.tpu.engine import (CapacityOverflow,  # noqa: E402
                                   TensorSearch)
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import (ShardedTensorSearch,  # noqa: E402
                                    make_mesh)
from dslabs_tpu.tpu.supervisor import (FaultPlan,  # noqa: E402
                                       RetryPolicy, SearchSupervisor,
                                       TransientDeviceError)

pytestmark = pytest.mark.capacity


def _pruned_pingpong():
    pp = make_pingpong_protocol(2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def _pruned_clientserver(nc=3, w=4):
    cs = make_clientserver_protocol(n_clients=nc, w=w)
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


# Shared uncapped lab1 reference (module-scoped: the baseline is used
# by several parity tests and costs a full strict BFS).
LAB1_DEPTH = 11


@pytest.fixture(scope="module")
def lab1_base():
    out = TensorSearch(_pruned_clientserver(), chunk=1024,
                       max_depth=LAB1_DEPTH).run()
    assert out.end_condition == "DEPTH_EXHAUSTED"
    return out


def _eighth_cap(unique: int) -> int:
    return 1 << max(3, int(np.floor(np.log2(max(unique // 8, 8)))))


def _assert_exact(a, b):
    assert a.end_condition == b.end_condition
    assert a.unique_states == b.unique_states
    assert a.states_explored == b.states_explored
    assert a.depth == b.depth


# ------------------------------------------------------------ unit layer

def test_host_tier_absorb_contains_dedup():
    """The tier is an EXACT set: absorb dedups within the batch and
    against the store, contains answers per row, host_cap is a loud
    wall (the ladder escalates it, never a silent drop)."""
    tier = spill_mod.HostVisitedTier(host_cap=8)
    keys = np.arange(24, dtype=np.uint32).reshape(6, 4)
    dup = np.concatenate([keys, keys[:3]])
    assert tier.absorb(dup) == 6
    assert len(tier) == 6
    assert tier.contains(keys).all()
    assert not tier.contains(keys + np.uint32(100)).any()
    assert tier.absorb(keys) == 0          # idempotent
    with pytest.raises(CapacityOverflow):
        tier.absorb(np.arange(100, 100 + 12 * 4,
                              dtype=np.uint32).reshape(12, 4))


def test_spill_manager_unique_formula():
    """unique = len(tier) + vis_n_epoch - dup_epoch, with refilter
    charging duplicates and evict starting a fresh epoch."""
    sp = spill_mod.SpillManager(spill_mod.SpillConfig(high_water=0.5))
    keys = np.arange(40, dtype=np.uint32).reshape(10, 4)
    sp.evict(keys)                         # epoch 1 -> tier
    assert sp.unique(0) == 10
    rows = np.arange(12, dtype=np.int32).reshape(3, 4)
    kept = sp.refilter(rows, keys[:3])     # all three are re-discoveries
    assert len(kept) == 0 and sp.dup_epoch == 3
    assert sp.unique(3) == 10              # 3 device inserts, all dups
    sp.evict(keys[:3])                     # dups absorb to nothing new
    assert len(sp.tier) == 10 and sp.dup_epoch == 0


# ------------------------------------------------- engine parity layer

def test_device_spill_parity_pingpong():
    """Tiny space, table capped to a single bucket: evictions and
    refilters happen, counts stay exact (single-device engine)."""
    pp = _pruned_pingpong()
    base = TensorSearch(pp, chunk=64, max_depth=12).run()
    sp = TensorSearch(pp, chunk=64, max_depth=12, visited_cap=8,
                      spill=True).run()
    _assert_exact(base, sp)
    assert sp.spilled_keys > 0
    assert sp.dropped_states == 0


def test_device_spill_parity_lab1_eighth_capacity(lab1_base):
    """ACCEPTANCE: strict lab1 with the device visited table capped at
    ~1/8 of the reachable count completes DEPTH_EXHAUSTED with exact
    unique/explored parity and zero dropped states — 'table full'
    degrades to 'slower, still exact'."""
    cap = _eighth_cap(lab1_base.unique_states)
    assert cap * 8 <= lab1_base.unique_states * 2
    out = TensorSearch(_pruned_clientserver(), chunk=16,
                       max_depth=LAB1_DEPTH, visited_cap=cap,
                       frontier_cap=1 << 11, spill=True).run()
    _assert_exact(lab1_base, out)
    assert out.dropped_states == 0
    assert out.spilled_keys > 0            # the tier really engaged
    assert out.host_tier_hits > 0          # refilter really corrected
    assert out.respilled_frontier > 0      # frontier really spooled


def test_sharded_spill_parity_lab1_eighth_capacity(lab1_base):
    """The same acceptance bar on the sharded engine (2-device mesh):
    global abort/revert, sharded drain/evict/reinject, exact counts."""
    cap_total = _eighth_cap(lab1_base.unique_states)
    mesh = make_mesh(2)
    out = ShardedTensorSearch(
        _pruned_clientserver(), mesh, chunk_per_device=16,
        frontier_cap=256, visited_cap=cap_total, max_depth=LAB1_DEPTH,
        strict=True, spill=True).run()
    _assert_exact(lab1_base, out)
    assert out.dropped_states == 0
    assert out.spilled_keys > 0
    # Per-level load factor rides SearchOutcome.levels (satellite).
    assert out.levels and all("load_factor" in r for r in out.levels)


def test_spill_checkpoint_resume_parity(lab1_base, tmp_path):
    """A spill run checkpointed per level resumes from its dump to the
    identical verdict and counts (in-process half of the kill-resume
    acceptance; the dump's visited_keys is the device ∪ tier union)."""
    cap = _eighth_cap(lab1_base.unique_states)
    pth = str(tmp_path / "spill.ckpt")
    kw = dict(chunk=16, visited_cap=cap, frontier_cap=1 << 11,
              spill=True, checkpoint_path=pth, checkpoint_every=1)
    partial = TensorSearch(_pruned_clientserver(), max_depth=6,
                           **kw).run()
    assert partial.depth == 6
    assert os.path.exists(pth)
    out = TensorSearch(_pruned_clientserver(), max_depth=LAB1_DEPTH,
                       **kw).run(resume=True)
    _assert_exact(lab1_base, out)
    # Cross-engine: a NON-spill engine with a big enough table resumes
    # the same spill dump (the format is tier-agnostic).
    out2 = TensorSearch(_pruned_clientserver(), chunk=1024,
                        max_depth=LAB1_DEPTH, visited_cap=1 << 20,
                        checkpoint_path=pth).run(resume=True)
    _assert_exact(lab1_base, out2)


@pytest.mark.fault
def test_sigkill_mid_spill_resume_parity(lab1_base, tmp_path):
    """ACCEPTANCE: the capped lab1 run SIGKILLed MID-SPILL (tier
    already populated, checkpoints on disk) resumes from the dump to
    the identical DEPTH_EXHAUSTED verdict and exact counts."""
    cap = _eighth_cap(lab1_base.unique_states)
    pth = str(tmp_path / "kill.ckpt")
    child_src = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_compilation_cache_dir',"
        " '/tmp/jaxcache-cpu')\n"
        "import dataclasses\n"
        "from dslabs_tpu.tpu.engine import TensorSearch\n"
        "from dslabs_tpu.tpu.protocols.clientserver import"
        " make_clientserver_protocol\n"
        "cs = make_clientserver_protocol(n_clients=3, w=4)\n"
        "cs = dataclasses.replace(cs, goals={},"
        " prunes={'CLIENTS_DONE': cs.goals['CLIENTS_DONE']})\n"
        f"TensorSearch(cs, chunk=16, max_depth={LAB1_DEPTH},"
        f" visited_cap={cap}, frontier_cap=2048, spill=True,"
        f" checkpoint_path={pth!r}, checkpoint_every=1).run()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DSLABS_COMPILE_CACHE="/tmp/jaxcache-cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        # Kill once the dump proves the spill tier is live (the run
        # evicts by ~depth 5-6 at 1/8 capacity).
        deadline = time.time() + 120
        while time.time() < deadline:
            d = ckpt_mod.peek_depth(pth)
            if d is not None and d >= 6:
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert ckpt_mod.peek_depth(pth) is not None
    out = TensorSearch(_pruned_clientserver(), chunk=16,
                       max_depth=LAB1_DEPTH, visited_cap=cap,
                       frontier_cap=2048, spill=True,
                       checkpoint_path=pth,
                       checkpoint_every=1).run(resume=True)
    _assert_exact(lab1_base, out)
    assert out.dropped_states == 0


def test_bfs_refuses_foreign_spill_checkpoint(tmp_path):
    """A spill dump written by a DIFFERENT protocol config is refused
    with a loud CheckpointMismatch naming both fingerprints — never
    resumed (or skipped) silently."""
    pth = str(tmp_path / "foreign.ckpt")
    pp_engine = TensorSearch(_pruned_pingpong(), chunk=64,
                             max_depth=12, visited_cap=8, spill=True,
                             checkpoint_path=pth, checkpoint_every=1)
    pp_engine.run()
    assert os.path.exists(pth)
    lab1 = TensorSearch(_pruned_clientserver(), chunk=64,
                        max_depth=4, visited_cap=1 << 12, spill=True,
                        checkpoint_path=pth)
    assert not lab1.has_resumable_checkpoint()
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        lab1.run(resume=True)


# ------------------------------------------------- supervisor ladder

def test_supervisor_capacity_ladder(lab1_base, tmp_path):
    """spill='ladder': CapacityOverflow is a CLASSIFIED failure (kind
    'capacity' on the chain) and the rung retries WITH the spill tier,
    resuming from its checkpoint — identical verdict and counts."""
    cap = _eighth_cap(lab1_base.unique_states)
    sup = SearchSupervisor(
        _pruned_clientserver(), ladder=("device",), mesh=make_mesh(1),
        chunk=32, visited_cap=max(cap * 2, 256),
        frontier_cap=1 << 11, max_depth=LAB1_DEPTH,
        checkpoint_path=str(tmp_path / "ladder.ckpt"),
        checkpoint_every=2, policy=RetryPolicy(max_retries=1),
        spill="ladder")
    out = sup.run()
    _assert_exact(lab1_base, out)
    assert any(f.kind == "capacity" for f in sup.failures)
    assert out.failovers >= 1
    assert out.spilled_keys > 0


def test_supervisor_default_still_passes_capacity_through():
    """The historical contract is untouched by default: without the
    opt-in, CapacityOverflow passes through unwrapped (also pinned by
    test_supervisor.py)."""
    from dslabs_tpu.tpu.visited import BKT

    with pytest.raises(CapacityOverflow):
        SearchSupervisor(
            _pruned_clientserver(nc=1, w=2), ladder=("device",),
            mesh=make_mesh(1), chunk=64, visited_cap=BKT,
            policy=RetryPolicy(max_retries=1)).run()


# ----------------------------------------- spill-dispatch fault matrix

@pytest.mark.fault
def test_faultplan_spill_dispatch_transient_retry(lab1_base):
    """Transient raise-variants targeted at EVERY new spill site
    (drain/refilter, evict, reinject) via FaultPlan site rules: each
    retries in place through the standard boundary, counts exact."""
    cap = _eighth_cap(lab1_base.unique_states)
    plan = FaultPlan()
    for site in ("spill_drain", "spill_evict", "spill_reinject"):
        plan.raise_at(1, engine="device", site=site,
                      error=TransientDeviceError)
    sup = SearchSupervisor(
        _pruned_clientserver(), ladder=("device",), mesh=make_mesh(1),
        chunk=16, visited_cap=cap, frontier_cap=1 << 11,
        max_depth=LAB1_DEPTH, policy=RetryPolicy(max_retries=3),
        spill=True, fault_plan=plan)
    out = sup.run()
    _assert_exact(lab1_base, out)
    assert plan.fired == 3
    assert out.retries == 3


@pytest.mark.fault
def test_faultplan_spill_dispatch_hang_fails_over(lab1_base):
    """A HANG on a spill dispatch is abandoned by the wall-clock
    watchdog (never retried in place) and the ladder fails over to the
    host rung — verdict parity, degradation visible."""
    cap = _eighth_cap(lab1_base.unique_states)
    plan = FaultPlan().hang_at(2, engine="device", site="spill_drain")
    sup = SearchSupervisor(
        _pruned_clientserver(), ladder=("device", "host"),
        mesh=make_mesh(1), chunk=16, visited_cap=cap,
        frontier_cap=1 << 11, max_depth=LAB1_DEPTH,
        policy=RetryPolicy(max_retries=1, deadline_secs=1.5,
                           deadline_first_secs=90.0),
        spill=True, fault_plan=plan)
    out = sup.run()
    assert out.engine == "host"
    assert out.failovers == 1
    assert sup.failures[0].kind == "wedged"
    _assert_exact(lab1_base, out)


# ------------------------------------------------ loud-accounting layer

def test_visited_warn_fires_before_overflow():
    """DSLABS_VISITED_WARN (default 0.85): operators see table
    pressure BEFORE the overflow contract degrades anything."""
    proto = _pruned_clientserver(nc=3, w=2)
    with pytest.warns(RuntimeWarning, match="capacity pressure"):
        out = ShardedTensorSearch(
            proto, make_mesh(1), chunk_per_device=64,
            frontier_cap=1 << 10, visited_cap=64, strict=False,
            max_depth=5).run()
    assert out.end_condition == "DEPTH_EXHAUSTED"


def test_dropped_states_surfaced_and_warned(monkeypatch):
    """Beam drops are a COUNT everywhere (SearchOutcome.dropped_states)
    and loud past DSLABS_DROPPED_WARN — the BENCH_r03 5.8M-drop shape
    can no longer hide behind a flag."""
    monkeypatch.setenv("DSLABS_DROPPED_WARN", "1")
    proto = _pruned_clientserver(nc=3, w=3)
    with pytest.warns(RuntimeWarning, match="dropped"):
        out = ShardedTensorSearch(
            proto, make_mesh(1), chunk_per_device=64,
            frontier_cap=64, visited_cap=1 << 12, strict=False,
            max_depth=8).run()
    assert out.dropped_states > 0
    assert out.dropped_states == out.dropped


def test_spill_record_trace_rejected():
    with pytest.raises(ValueError, match="record_trace"):
        TensorSearch(_pruned_pingpong(), spill=True, record_trace=True)


# ------------------------------------------------- async drain (ISSUE 15c)

@pytest.mark.capacity2
def test_async_drain_default_on_and_sync_parity(lab1_base):
    """The async gear is the default (DSLABS_SPILL_ASYNC), its counts
    are exact, and the legacy sync gear produces the identical
    verdict — async is a scheduling change, never a semantic one."""
    cap = _eighth_cap(lab1_base.unique_states)
    kw = dict(chunk=16, max_depth=LAB1_DEPTH, visited_cap=cap,
              frontier_cap=1 << 11)
    a = TensorSearch(_pruned_clientserver(),
                     spill=spill_mod.SpillConfig(async_drain=True),
                     **kw).run()
    s = TensorSearch(_pruned_clientserver(),
                     spill=spill_mod.SpillConfig(async_drain=False),
                     **kw).run()
    _assert_exact(lab1_base, a)
    _assert_exact(lab1_base, s)
    assert a.dropped_states == s.dropped_states == 0
    # The async run measured its wall split; overlap = drain work the
    # driver never blocked on (host drain no longer additive with the
    # device chunk wall).
    assert a.spill_drain_ms > 0
    assert a.spill_drain_ms >= a.spill_wait_ms
    assert s.spill_wait_ms == 0 and s.spill_drain_ms == 0


@pytest.mark.capacity2
def test_async_drain_level_records_carry_wall_split(lab1_base):
    """The per-level records carry the drain/wait/overlap split
    (telemetry satellite: the spill detour's cost is attributable per
    level, not just in aggregate)."""
    from dslabs_tpu.tpu import telemetry as tel_mod

    cap = _eighth_cap(lab1_base.unique_states)
    tel = tel_mod.Telemetry()
    out = TensorSearch(_pruned_clientserver(), chunk=16,
                       max_depth=LAB1_DEPTH, visited_cap=cap,
                       frontier_cap=1 << 11, spill=True,
                       telemetry=tel).run()
    _assert_exact(lab1_base, out)
    recs = [r for r in tel.levels if r.get("spill")]
    assert recs, "spill level records missing the wall split"
    for r in recs:
        for k in ("drain_wall", "drain_wait", "drain_overlap"):
            assert k in r["spill"]
    total_drain = sum(r["spill"]["drain_wall"] for r in recs)
    assert abs(total_drain - out.spill_drain_ms / 1000.0) < 0.25


@pytest.mark.capacity2
def test_async_drain_worker_error_surfaces_loudly():
    """A drain job that raises (host tier full) surfaces at the next
    barrier as the same loud CapacityOverflow the sync gear raises —
    never swallowed on the worker thread."""
    with pytest.raises(CapacityOverflow, match="host spill tier"):
        TensorSearch(_pruned_clientserver(), chunk=16,
                     max_depth=LAB1_DEPTH, visited_cap=64,
                     frontier_cap=1 << 11,
                     spill=spill_mod.SpillConfig(
                         async_drain=True, host_cap=32)).run()


@pytest.mark.capacity2
@pytest.mark.fault
def test_async_drain_abort_revert_chaos(lab1_base):
    """ACCEPTANCE (abort/revert chaos): transient faults injected at
    every spill dispatch site under the ASYNC gear retry through the
    standard boundary with exact counts — the abort-wholesale-revert
    contract holds while drains are in flight."""
    cap = _eighth_cap(lab1_base.unique_states)
    plan = FaultPlan()
    for site in ("spill_drain", "spill_evict", "spill_reinject"):
        plan.raise_at(1, engine="device", site=site,
                      error=TransientDeviceError)
    sup = SearchSupervisor(
        _pruned_clientserver(), ladder=("device",), mesh=make_mesh(1),
        chunk=16, visited_cap=cap, frontier_cap=1 << 11,
        max_depth=LAB1_DEPTH, policy=RetryPolicy(max_retries=3),
        spill=spill_mod.SpillConfig(async_drain=True),
        fault_plan=plan)
    out = sup.run()
    _assert_exact(lab1_base, out)
    assert plan.fired == 3
    assert out.dropped_states == 0


# ------------------------------------------------------------ slow tier

@pytest.mark.slow
def test_spill_parity_paxos_d5():
    """Third protocol family at depth 5 (the perf-smoke paxos rung)
    through the capacity ladder: exact parity at ~1/8 table capacity."""
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol

    proto = make_paxos_protocol(n=3, n_clients=1, w=1, max_slots=2,
                                net_cap=16, timer_cap=4)
    base = TensorSearch(proto, chunk=1024, max_depth=5,
                        visited_cap=1 << 15).run()
    assert base.end_condition == "DEPTH_EXHAUSTED"
    cap = _eighth_cap(base.unique_states)
    out = TensorSearch(proto, chunk=16, max_depth=5, visited_cap=cap,
                       frontier_cap=1 << 12, spill=True).run()
    _assert_exact(base, out)
    assert out.dropped_states == 0
    assert out.spilled_keys > 0
