"""Elastic mesh resilience (ISSUE 9): the degraded-mesh failover
ladder, the adaptive OOM knob-shrink, and the seeded chaos harness —
every path on the 8-virtual-device CPU dryrun mesh:

* ``expand_ladder`` turns ``"sharded"`` into
  ``sharded(D) -> sharded(D/2) -> ... -> sharded(2)`` and a fatal mesh
  rung degrades by HALVES (engine stays ``"sharded"``, ``mesh_width``
  and ``mesh_shrunk`` events say which half);
* cross-mesh-width resume parity matrix: one strict search
  checkpointed on an 8-wide mesh resumes on 4-, 2-, then 1-wide
  meshes to the IDENTICAL verdict/unique/explored with zero drops
  (pingpong + lab1), including the warden SIGKILL-mid-level variant
  (8-wide child killed, 4-wide child killed, 2-wide child finishes);
* an OOM-classified dispatch failure costs a knob-shrink RE-LEVEL
  (halved chunk + superstep budget, resume in place), not a rung —
  bounded by DSLABS_KNOB_SHRINKS, then the rung burns normally;
* the seeded chaos soak (tpu/chaos.py): >= 20 deterministic faults
  across >= 3 dispatch sites — transient storms, OOMs, a fatal, a
  hang — and the strict verdict still matches the fault-free run
  exactly.

Marked ``chaos`` (``make chaos-smoke``); the long soak variants are
additionally ``slow``.
"""

import dataclasses
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import chaos as chaos_mod  # noqa: E402
from dslabs_tpu.tpu.chaos import (ChaosOOM, ChaosSpec,  # noqa: E402
                                  build_plan, soak)
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh  # noqa: E402
from dslabs_tpu.tpu.supervisor import (FaultPlan,  # noqa: E402
                                       RetryPolicy, SearchSupervisor,
                                       classify_oom, expand_ladder)
from dslabs_tpu.tpu.telemetry import Telemetry  # noqa: E402
from dslabs_tpu.tpu.warden import Warden  # noqa: E402

pytestmark = pytest.mark.chaos

CHILD_ENV = {"DSLABS_COMPILE_CACHE": "/tmp/jaxcache-cpu"}


class FatalError(RuntimeError):
    """Injected non-transient, non-OOM failure."""


def _pruned_pingpong():
    pp = make_pingpong_protocol(2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def _pruned_clientserver():
    cs = make_clientserver_protocol(n_clients=1, w=2)
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


# Module-level for warden children ("tests.test_chaos:prune_*").
def prune_clientserver(cs):
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


LAB1_REFS = {
    "factory": "dslabs_tpu.tpu.protocols.clientserver:"
               "make_clientserver_protocol",
    "factory_kwargs": {"n_clients": 1, "w": 2},
    "transform": "tests.test_chaos:prune_clientserver",
}

# One shared config per protocol family so every test (and the warden
# children, via the persistent compile cache) reuses the same XLA
# programs per mesh width.
PP_KW = dict(chunk=16, frontier_cap=1 << 8, visited_cap=1 << 10)
LAB1_KW = dict(chunk=64, frontier_cap=1 << 9, visited_cap=1 << 12)


def _sup(proto, **kw):
    kw.setdefault("mesh", make_mesh(8))
    for k, v in PP_KW.items():
        kw.setdefault(k, v)
    return SearchSupervisor(proto, **kw)


def _same_verdict(a, b):
    assert a.end_condition == b.end_condition
    assert a.unique_states == b.unique_states
    assert a.states_explored == b.states_explored


# ------------------------------------------------------ ladder mechanics

def test_expand_ladder_widths():
    """The width ladder is pinned: sharded(D) -> halves down to 2,
    then the historical device/host tail; non-elastic and narrow
    meshes expand to the identity."""
    assert expand_ladder(("sharded", "device", "host"), 8, True) == [
        ("sharded", None), ("sharded", 4), ("sharded", 2),
        ("device", None), ("host", None)]
    assert expand_ladder(("sharded", "device", "host"), 6, True) == [
        ("sharded", None), ("sharded", 3), ("sharded", 2),
        ("device", None), ("host", None)]
    assert expand_ladder(("sharded", "device"), 2, True) == [
        ("sharded", None), ("device", None)]
    assert expand_ladder(("sharded", "device", "host"), 8, False) == [
        ("sharded", None), ("device", None), ("host", None)]
    assert expand_ladder(("device", "host"), 8, True) == [
        ("device", None), ("host", None)]


def test_classify_oom_markers():
    assert classify_oom(MemoryError("boom"))
    assert classify_oom(ChaosOOM("chaos injected allocation failure"))
    assert classify_oom(RuntimeError("RESOURCE_EXHAUSTED: hbm"))
    assert classify_oom(RuntimeError("ran out of memory on device"))
    assert not classify_oom(RuntimeError("INVALID_ARGUMENT"))
    assert not classify_oom(None)


def test_elastic_fatal_degrades_by_half_not_cliff(tmp_path):
    """TENTPOLE: a fatal error on the 8-wide rung costs HALF the mesh
    — the supervisor rebuilds a 4-wide mesh, resumes the unified
    checkpoint re-sharded to the new owner map, and lands the
    identical strict verdict with the shrink on the outcome and the
    flight log."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    assert base.end_condition == "SPACE_EXHAUSTED"
    tel = Telemetry()
    out = _sup(proto, elastic=True,
               fault_plan=FaultPlan().raise_at(8, error=FatalError,
                                               engine="sharded"),
               checkpoint_path=str(tmp_path / "el.npz"),
               checkpoint_every=1, telemetry=tel,
               policy=RetryPolicy(max_retries=0)).run()
    _same_verdict(out, base)
    assert out.engine == "sharded"          # still a MESH verdict
    assert out.mesh_width == 4              # ... on half the chips
    assert out.mesh_shrinks == 1
    assert out.failovers == 1
    assert out.resumed_from_depth > 0
    assert out.dropped_states == 0
    kinds = [e.get("kind") for e in tel.events]
    assert "mesh_shrunk" in kinds
    shrunk = next(e for e in tel.events
                  if e.get("kind") == "mesh_shrunk")
    assert (shrunk["from_width"], shrunk["to_width"]) == (8, 4)


def test_knob_shrink_absorbs_oom_in_place(tmp_path):
    """TENTPOLE: an OOM-classified dispatch failure retries IN PLACE
    with halved knobs — a re-level, not a failover; the outcome and
    the knobs_shrunk event carry the story."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    tel = Telemetry()
    sup = _sup(proto, elastic=True,
               fault_plan=FaultPlan().raise_at(6, error=MemoryError,
                                               engine="sharded"),
               checkpoint_path=str(tmp_path / "oom.npz"),
               checkpoint_every=1, telemetry=tel,
               policy=RetryPolicy(max_retries=0))
    out = sup.run()
    _same_verdict(out, base)
    assert out.engine == "sharded"
    assert out.mesh_width == 8              # the mesh never shrank
    assert out.mesh_shrinks == 0
    assert out.knob_retries == 1
    assert out.failovers == 0
    kinds = [e.get("kind") for e in tel.events]
    assert "knobs_shrunk" in kinds and "mesh_shrunk" not in kinds
    # The re-level rebuilt the rung with the chunk halved.
    shrunk = sup._engines[("sharded", None, None, 1)]
    assert shrunk.cpd == PP_KW["chunk"] // 2


def test_knob_shrink_ladder_is_bounded_then_rung_burns():
    """A persistent OOM exhausts the bounded shrink ladder (default 2
    re-levels) and the rung burns normally — the next rung still lands
    the exact verdict."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    out = _sup(proto, ladder=("sharded", "device"),
               fault_plan=FaultPlan().raise_always(
                   error=MemoryError, engine="sharded"),
               policy=RetryPolicy(max_retries=0)).run()
    _same_verdict(out, base)
    assert out.engine == "device"
    assert out.knob_retries == 2            # DSLABS_KNOB_SHRINKS
    assert out.failovers == 1


# ------------------------------------------- cross-width resume parity

def _resume_matrix(proto, tmp_path, base_kw, stage_depths):
    """Run the full-width baseline, then the SAME search staged across
    8 -> 4 -> 2 -> 1 wide meshes via checkpoint resume; the final
    verdict/counts must be exact."""
    kw = dict(chunk_per_device=base_kw["chunk"],
              frontier_cap=base_kw["frontier_cap"],
              visited_cap=base_kw["visited_cap"])
    base = ShardedTensorSearch(proto, make_mesh(8), **kw).run()
    assert base.end_condition == "SPACE_EXHAUSTED"
    ck = str(tmp_path / "matrix.npz")
    widths = (8, 4, 2, 1)
    out = None
    for w, d in zip(widths, stage_depths):
        search = ShardedTensorSearch(
            proto, make_mesh(w), max_depth=d, checkpoint_path=ck,
            checkpoint_every=1, **kw)
        out = search.run(resume=(w != widths[0]))
        if d is not None:
            assert out.end_condition in ("DEPTH_EXHAUSTED",
                                         "SPACE_EXHAUSTED")
    _same_verdict(out, base)
    assert out.dropped_states == 0
    return base, out


def test_cross_width_resume_matrix_pingpong(tmp_path):
    """SATELLITE: strict pingpong checkpointed at depth 2 on the
    8-wide mesh resumes on 4-, 2-, and 1-wide meshes (the unified
    dump re-shards frontier + visited keys per owner) with exact
    unique/explored/verdict parity and zero drops."""
    _resume_matrix(_pruned_pingpong(), tmp_path, PP_KW,
                   (2, 3, 4, None))


def test_cross_width_resume_matrix_lab1(tmp_path):
    """SATELLITE: the same 8 -> 4 -> 2 -> 1 parity matrix on the lab1
    strict clientserver BFS (deeper space, more checkpoints cross the
    width changes)."""
    _resume_matrix(_pruned_clientserver(), tmp_path, LAB1_KW,
                   (2, 4, 6, None))


def test_warden_sigkill_mid_level_resumes_on_narrower_meshes(tmp_path):
    """ACCEPTANCE: strict lab1 on the 8-device CPU dryrun mesh,
    SIGKILLed mid-level (after a durable checkpoint), resumes on a
    4-wide child; THAT child is SIGKILLed too and the 2-wide child
    finishes — exact verdict/unique/explored parity,
    ``dropped_states == 0``, both shrinks attributable."""
    proto = _pruned_clientserver()
    base = ShardedTensorSearch(
        proto, make_mesh(8), chunk_per_device=LAB1_KW["chunk"],
        frontier_cap=LAB1_KW["frontier_cap"],
        visited_cap=LAB1_KW["visited_cap"]).run()
    w = Warden(**LAB1_REFS, ladder=("sharded", "device", "host"),
               elastic=True, checkpoint_path=str(tmp_path / "wk.npz"),
               checkpoint_every=1, env=CHILD_ENV,
               chunk=LAB1_KW["chunk"],
               frontier_cap=LAB1_KW["frontier_cap"],
               visited_cap=LAB1_KW["visited_cap"],
               # at=2 + after_ckpt: each targeted child dies on its
               # first dispatch after a DURABLE checkpoint exists —
               # deterministic mid-level kills on both the 8-wide and
               # the (shorter-lived, resumed) 4-wide child.
               fault={"kind": "die", "at": 2, "spawns": [0, 1],
                      "after_ckpt": True})
    out = w.run()
    _same_verdict(out, base)
    assert out.engine == "sharded"
    assert out.mesh_width == 2
    assert out.mesh_shrinks == 2
    assert out.child_restarts == 2
    assert out.resumed_from_depth > 0
    assert out.dropped_states == 0
    assert [d.kind for d in w.deaths] == ["oom", "oom"]


def test_swarm_checkpoint_survives_mesh_width_change(tmp_path):
    """SATELLITE bugfix: swarm dumps no longer pin D/K in their
    fingerprint — a fleet checkpointed on 8 devices resumes on 4
    (walker rows / histories / PRNG keys / key groups redistributed),
    while a genuinely different config (another seed) still refuses
    loudly."""
    from dslabs_tpu.tpu import checkpoint as ckpt_mod
    from dslabs_tpu.tpu.swarm import SwarmSearch

    proto = _pruned_pingpong()
    ck = str(tmp_path / "swarm.npz")
    kw = dict(walkers_per_device=8, max_steps=12, steps_per_round=4,
              seed=7, visited_cap=1 << 10, checkpoint_path=ck,
              checkpoint_every=1)
    first = SwarmSearch(proto, mesh=make_mesh(8), max_rounds=2, **kw)
    out1 = first.run(check_initial=False)
    assert os.path.exists(ck)
    explored1 = out1.states_explored

    with pytest.warns(RuntimeWarning, match="redistributes"):
        resumed = SwarmSearch(proto, mesh=make_mesh(4), max_rounds=4,
                              **kw)
        out2 = resumed.run(check_initial=False, resume=True)
    assert out2.resumed_from_depth >= 1     # continued, not restarted
    assert out2.states_explored >= explored1

    other = SwarmSearch(proto, mesh=make_mesh(4), max_rounds=1,
                        **{**kw, "seed": 8})
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        other.run(check_initial=False, resume=True)


# --------------------------------------------------- the chaos harness

def test_chaos_plan_is_seed_deterministic():
    """Same seed -> bit-identical schedule; different seed -> a
    different one.  The kind budget is exact: every requested fault is
    scheduled."""
    counts = {("sharded", "init"): 1, ("sharded", "superstep"): 10,
              ("sharded", "promote"): 9}
    spec = ChaosSpec(seed=3, faults=24)
    p1, p2 = build_plan(spec, counts), build_plan(spec, counts)
    assert p1.schedule == p2.schedule
    assert len(p1.schedule) == 24
    kinds = [k for (_e, _s, _i, k) in p1.schedule]
    assert kinds.count("oom") == 2
    assert kinds.count("fatal") == 1
    assert kinds.count("hang") == 1
    assert kinds.count("transient") == 20
    sites = {(e, s) for (e, s, _i, _k) in p1.schedule}
    assert len(sites) == 3
    p3 = build_plan(ChaosSpec(seed=4, faults=24), counts)
    assert p3.schedule != p1.schedule
    # Hangs pin to the promote site (lowest watchdog deadline scale).
    assert all(s == "promote" for (_e, s, _i, k) in p1.schedule
               if k == "hang")


def test_chaos_soak_lab1_acceptance(tmp_path):
    """ACCEPTANCE: a seeded chaos soak on strict lab1 over the
    8-device dryrun mesh injects >= 20 faults across >= 3 dispatch
    sites — transient storms, OOM re-levels, a fatal rung burn, a
    hang — and returns the fault-free verdict with IDENTICAL
    unique/explored counts and zero dropped states."""
    report = soak(
        _pruned_clientserver(),
        spec=ChaosSpec(seed=1, faults=24),
        supervisor_kwargs=dict(mesh=make_mesh(8), **LAB1_KW),
        checkpoint_path=str(tmp_path / "soak.npz"),
        min_fired=20, min_sites=3)
    assert report["parity"] is True
    assert report["fired"] >= 20
    assert len(report["sites_fired"]) >= 3
    assert report["chaos"]["dropped_states"] == 0
    # The soak exercised BOTH degradation axes, attributably.
    assert report["chaos"]["mesh_shrinks"] >= 1
    assert report["chaos"]["knob_retries"] >= 1
    assert report["chaos"]["retries"] >= 10
    assert "hang" in report["kinds_fired"]


@pytest.mark.slow
def test_chaos_soak_long_multi_seed(tmp_path):
    """The long soak (``make chaos-smoke``): three seeds, more faults
    each — sustained injection across every site never breaks strict
    parity."""
    for seed in (11, 12, 13):
        report = soak(
            _pruned_clientserver(),
            spec=ChaosSpec(seed=seed, faults=32, oom_faults=3),
            supervisor_kwargs=dict(mesh=make_mesh(8), **LAB1_KW),
            checkpoint_path=str(tmp_path / f"soak{seed}.npz"),
            min_fired=24, min_sites=3)
        assert report["parity"] is True


@pytest.mark.slow
def test_chaos_cli_smoke(tmp_path, capsys):
    """The by-hand entry point: ``python -m dslabs_tpu.tpu.chaos``
    prints the soak report as one JSON line and exits 0 on parity."""
    import json

    # lab1 reuses the XLA programs the suite already compiled (the
    # CLI's kwargs match LAB1_KW by design).
    assert chaos_mod.main(["--protocol", "lab1", "--seed", "2",
                           "--faults", "20", "--mesh", "8"]) == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["parity"] is True
