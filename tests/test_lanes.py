"""Batched job lanes (ISSUE 14, tpu/lanes.py): N tenant searches as
ONE compiled program.

The load-bearing contract is EXACT PARITY: a job run in a lane batch
lands the bit-identical unique/explored/verdict its solo run lands, at
every batch width, with lane-mates at different depths, through
continuous-batching swap-ins, across a SIGKILL-mid-batch resume, and
with a poisoned neighbor evicted mid-flight.  On top of that the
amortisation pin (a 4-lane batch spends <= 0.5x the dispatches of four
solo runs — the economics the feature exists for), the solo-path
overhead guard (lanes off = solo engines untouched), the service
integration (lane packer quotas, COSTS sums, eviction-to-solo), and
the observability schema (STATUS lanes block, ledger compare guards).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from dslabs_tpu.tpu import visited as visited_mod
from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.lanes import (LaneBatchWarden, LaneJob, LaneSearch,
                                  job_signature)
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

pytestmark = pytest.mark.lanes

# Children share the suite's persistent compile cache
# (tests/conftest.py) or every spawn pays a cold XLA build.
CHILD_ENV = {"DSLABS_COMPILE_CACHE": "/tmp/jaxcache-cpu"}

KW = dict(frontier_cap=1 << 10, chunk=64, visited_cap=1 << 12)


# Module-level so lane-batch children can import them by reference —
# closures cannot cross the spawn boundary.

def prune_pingpong(pp):
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def prune_clientserver(cs):
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


def _pingpong():
    return prune_pingpong(make_pingpong_protocol(workload_size=2))


def _lab1_wide():
    # A bigger space (582 explored / 80 unique / depth 11) so
    # multi-chunk waves and mixed-depth lanes are genuinely exercised.
    return prune_clientserver(
        make_clientserver_protocol(n_clients=2, w=2))


def _same(a, b):
    assert a.end_condition == b.end_condition
    assert a.states_explored == b.states_explored
    assert a.unique_states == b.unique_states
    assert a.depth == b.depth


class _Spy:
    """Dispatch counter at the _dispatch seam (the overhead-guard
    idiom from tests/test_telemetry.py)."""

    def __init__(self):
        self.n = 0
        self.tags = []

    def __call__(self, tag, fn, *args):
        self.n += 1
        self.tags.append(tag)
        return fn(*args)


# ------------------------------------------------------ parity matrix

@pytest.mark.parametrize("L", [1, 2, 4])
def test_lane_parity_matrix_pingpong_strict(L):
    """ACCEPTANCE: every lane's unique/explored/verdict is
    bit-identical to its solo run at L in {1, 2, 4}."""
    proto = _pingpong()
    solo = TensorSearch(proto, strict=True, **KW).run()
    ls = LaneSearch(proto, n_lanes=L, strict=True, **KW)
    res = ls.run_lanes([LaneJob(f"j{i}") for i in range(L)])
    assert not res.errors
    assert len(res.outcomes) == L
    for out in res.outcomes.values():
        _same(out, solo)
        assert out.engine == "lanes"
        assert out.lane_width == L


@pytest.mark.parametrize("strict", [True, False])
def test_lane_parity_lab1(strict):
    proto = _lab1_wide()
    solo = TensorSearch(proto, strict=strict, **KW).run()
    ls = LaneSearch(proto, n_lanes=2, strict=strict, **KW)
    res = ls.run_lanes([LaneJob("a"), LaneJob("b")])
    assert not res.errors
    for out in res.outcomes.values():
        _same(out, solo)


def test_lane_parity_mixed_depth_limits():
    """Lane-mates at DIFFERENT per-lane depth limits finish at
    different levels; each still matches its own solo run exactly —
    a finished lane is a provable no-op for its neighbors."""
    proto = _lab1_wide()
    solo = {d: TensorSearch(proto, strict=True, max_depth=d,
                            **KW).run()
            for d in (None, 4, 7)}
    ls = LaneSearch(proto, n_lanes=4, strict=True, **KW)
    res = ls.run_lanes([LaneJob("full"), LaneJob("d4", max_depth=4),
                        LaneJob("d7", max_depth=7),
                        LaneJob("full2")])
    assert not res.errors
    _same(res.outcomes["full"], solo[None])
    _same(res.outcomes["full2"], solo[None])
    _same(res.outcomes["d4"], solo[4])
    _same(res.outcomes["d7"], solo[7])


def test_lane_goal_verdict_parity():
    """Terminal-flag verdicts (checkState order) survive the lane
    extraction: same predicate, same first-hit state, same counters."""
    proto = make_pingpong_protocol(workload_size=2)   # has a goal
    solo = TensorSearch(proto, strict=True, **KW).run()
    ls = LaneSearch(proto, n_lanes=2, strict=True, **KW)
    res = ls.run_lanes([LaneJob("g0"), LaneJob("g1")])
    assert not res.errors
    for out in res.outcomes.values():
        _same(out, solo)
        assert out.predicate_name == solo.predicate_name
        assert out.goal_state is not None
        for k in solo.goal_state:
            assert np.array_equal(np.asarray(out.goal_state[k]),
                                  np.asarray(solo.goal_state[k])), k


# --------------------------------------------- continuous batching

def test_continuous_batching_swap_in_parity():
    """More jobs than lanes: drained lanes refill at level boundaries
    (zero recompiles — same jitted programs) and every swapped-in
    job's verdict is bit-identical to solo."""
    proto = _lab1_wide()
    solo = TensorSearch(proto, strict=True, **KW).run()
    solo_d4 = TensorSearch(proto, strict=True, max_depth=4,
                           **KW).run()
    ls = LaneSearch(proto, n_lanes=2, strict=True, **KW)
    jobs = [LaneJob("a", max_depth=4), LaneJob("b"),
            LaneJob("c", max_depth=4), LaneJob("d"), LaneJob("e")]
    res = ls.run_lanes(jobs, swap=True)
    assert not res.errors
    assert res.swaps >= 2            # lanes were genuinely refilled
    for jid in ("b", "d", "e"):
        _same(res.outcomes[jid], solo)
    for jid in ("a", "c"):
        _same(res.outcomes[jid], solo_d4)


def test_dispatch_amortization_4_lanes():
    """ACCEPTANCE: a 4-lane batch's dispatches-per-job is <= 0.5x the
    4-solo baseline (measured at the _dispatch seam — the same seam
    telemetry spans and the COSTS ledger count)."""
    proto = _lab1_wide()
    spy = _Spy()
    solo = TensorSearch(proto, strict=True, **KW)
    solo._dispatch_hook = spy
    solo.run()
    solo_n = spy.n
    spy4 = _Spy()
    ls = LaneSearch(proto, n_lanes=4, strict=True, **KW)
    ls._dispatch_hook = spy4
    res = ls.run_lanes([LaneJob(f"x{i}") for i in range(4)])
    assert not res.errors
    assert spy4.n / 4 <= 0.5 * solo_n, (spy4.n, solo_n)
    # one superstep + one promote + one sync per LEVEL for the WHOLE
    # batch — the shape the amortisation comes from.
    assert spy4.tags.count("lanes.superstep") == res.levels


def test_solo_paths_untouched_when_lanes_off():
    """Overhead guard: building and running a LaneSearch in the same
    process leaves the solo engine's dispatch + device_get counts and
    the visited-insert lowering override untouched."""
    from dslabs_tpu.tpu import engine as engine_mod

    proto = _pingpong()

    def measure():
        spy = _Spy()
        gets = {"n": 0}
        orig = engine_mod.device_get
        s = TensorSearch(proto, strict=True, **KW)
        s._dispatch_hook = spy

        def counting_get(x):
            gets["n"] += 1
            return orig(x)

        engine_mod.device_get = counting_get
        try:
            out = s.run()
        finally:
            engine_mod.device_get = orig
        return out, spy.n, gets["n"]

    out_before, n_before, g_before = measure()
    ls = LaneSearch(proto, n_lanes=2, strict=True, **KW)
    ls.run_lanes([LaneJob("a"), LaneJob("b")])
    assert visited_mod._FORCE_JNP == 0    # override is trace-scoped
    out_after, n_after, g_after = measure()
    _same(out_before, out_after)
    assert n_before == n_after
    assert g_before == g_after


# --------------------------------------------- checkpoints + resume

def test_lane_checkpoint_is_solo_resumable(tmp_path):
    """A lane's per-lane dump is the ENGINE-AGNOSTIC unified format:
    a solo TensorSearch resumes it to the exact full-run verdict —
    the mechanism a poisoned lane's solo retry rides."""
    proto = _lab1_wide()
    solo = TensorSearch(proto, strict=True, **KW).run()
    ckpt = str(tmp_path / "lane0" / "ckpt.npz")
    os.makedirs(os.path.dirname(ckpt))
    ls = LaneSearch(proto, n_lanes=2, strict=True, **KW)
    res = ls.run_lanes([
        LaneJob("stub", max_depth=6, checkpoint_path=ckpt,
                checkpoint_every=1),
        LaneJob("mate", max_depth=3)])
    assert not res.errors
    resumed = TensorSearch(proto, strict=True,
                           checkpoint_path=ckpt, **KW)
    assert resumed.has_resumable_checkpoint()
    out = resumed.run(resume=True)
    _same(out, solo)


def test_sigkill_mid_batch_resumes_every_lane(tmp_path):
    """ACCEPTANCE: a SIGKILLed lane-batch child respawns and EVERY
    lane resumes from its own checkpoint to the bit-identical solo
    verdict (per-lane fault domains inside one process)."""
    proto = _lab1_wide()
    solo = TensorSearch(proto, strict=True, **KW).run()
    jobs = []
    for i in range(4):
        ck = str(tmp_path / f"j{i}" / "ckpt.npz")
        os.makedirs(os.path.dirname(ck))
        jobs.append({"job_id": f"j{i}", "checkpoint_path": ck,
                     "checkpoint_every": 1})
    w = LaneBatchWarden(
        factory="dslabs_tpu.tpu.protocols.clientserver:"
                "make_clientserver_protocol",
        factory_kwargs={"n_clients": 2, "w": 2},
        transform="tests.test_lanes:prune_clientserver",
        jobs=jobs, n_lanes=4, strict=True,
        run_dir=str(tmp_path / "batch"),
        env=CHILD_ENV, extra_sys_path=[os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))],
        fault={"kind": "die", "at": 12}, **KW)
    res = w.run()
    assert res.errors == {}, res.errors
    assert res.child_restarts >= 1
    assert w.deaths and w.deaths[0]["kind"] == "oom"
    for jid in ("j0", "j1", "j2", "j3"):
        _same(res.outcomes[jid], solo)
    # shares of the batch still sum to ~1.0 across the restart
    total = sum(o.lane_share for o in res.outcomes.values())
    assert 0.99 <= total <= 1.01, total
    # the batch run dir is watchable (flight + STATUS with the
    # schema-pinned per-lane block)
    st = json.load(open(tmp_path / "batch" / "STATUS.json"))
    assert st["lanes"], st
    for lrec in st["lanes"]:
        for key in ("lane", "job_id", "depth", "explored", "unique",
                    "frontier"):
            assert key in lrec, (key, lrec)


def test_poisoned_lane_evicts_neighbors_bit_exact():
    """ACCEPTANCE: a lane that hits the strict visited-pressure
    contract is POISONED (eviction error, solo-retry material) while
    its lane-mate's verdict stays bit-identical to solo."""
    proto = _lab1_wide()
    kw = dict(frontier_cap=1 << 10, chunk=64, visited_cap=64)
    # Solo contract at this cap: the full-space job raises.
    from dslabs_tpu.tpu.engine import CapacityOverflow

    with pytest.raises(CapacityOverflow):
        TensorSearch(proto, strict=True, **kw).run()
    solo_d3 = TensorSearch(proto, strict=True, max_depth=3,
                           **kw).run()
    ls = LaneSearch(proto, n_lanes=2, strict=True, **kw)
    res = ls.run_lanes([LaneJob("big"), LaneJob("small", max_depth=3)])
    assert "big" in res.errors
    assert "CapacityOverflow" in res.errors["big"]
    _same(res.outcomes["small"], solo_d3)


# ------------------------------------------------- scheduler packing

def _job(tenant, seq, **over):
    from dslabs_tpu.service.queue import Job

    kw = dict(job_id=f"{tenant}-{seq:03d}", tenant=tenant,
              factory="f:mk", factory_kwargs={"w": 2}, strict=True,
              chunk=64, frontier_cap=256, visited_cap=1024,
              ladder=("device", "host"))
    kw.update(over)
    return Job(**kw)


def test_job_signature_eligibility():
    base = _job("a", 1)
    assert job_signature(base) == job_signature(_job("b", 2))
    # different knobs / factory = different program shapes
    assert job_signature(base) != job_signature(_job("a", 3, chunk=32))
    assert job_signature(base) != job_signature(
        _job("a", 4, factory="g:mk"))
    # not lane-eligible: chaos faults, evicted-solo, non-device ladder
    assert job_signature(_job("a", 5, fault={"kind": "die"})) is None
    assert job_signature(_job("a", 6, solo=True)) is None
    assert job_signature(
        _job("a", 7, ladder=("sharded", "host"))) is None


def test_pick_batch_quota_and_signature():
    """The lane packer preserves DRR semantics: a tenant's lane count
    obeys its quota, non-matching heads are restored in order, and
    matching jobs across tenants fill the batch."""
    from dslabs_tpu.service.scheduler import DeficitRoundRobin

    drr = DeficitRoundRobin(quota=1)
    for t in ("a", "b", "c"):
        drr.push(_job(t, 1))
        drr.push(_job(t, 2))
    batch = drr.pick_batch({}, job_signature, max_jobs=4)
    # quota 1: ONE job per tenant despite 2 queued each
    assert len(batch) == 3
    assert sorted(j.tenant for j in batch) == ["a", "b", "c"]
    assert drr.pending() == 3          # the rest stayed queued
    # quota 2 lets both of a tenant's jobs share a batch
    drr2 = DeficitRoundRobin(quota=2)
    for t in ("a", "b"):
        drr2.push(_job(t, 1))
        drr2.push(_job(t, 2))
    batch2 = drr2.pick_batch({}, job_signature, max_jobs=4)
    assert len(batch2) == 4
    # an incompatible head never joins and is not lost
    drr3 = DeficitRoundRobin(quota=1)
    drr3.push(_job("a", 1))
    drr3.push(_job("b", 1, chunk=32))     # different signature
    batch3 = drr3.pick_batch({}, job_signature, max_jobs=4)
    assert [j.tenant for j in batch3] == ["a"]
    assert drr3.pending() == 1
    nxt = drr3.pick({})
    assert nxt is not None and nxt.tenant == "b"


# --------------------------------------------------- service stack

def _mk_server(root, lanes, **over):
    from dslabs_tpu.service.server import CheckServer

    kw = dict(workers=1, queue_cap=16, elastic=False, admission=False,
              env=CHILD_ENV, lanes=lanes)
    kw.update(over)
    return CheckServer(str(root), **kw)


def _submit_jobs(srv, tenants=("alice", "bob"), per=2):
    for t in tenants:
        for _ in range(per):
            r = srv.submit(
                factory="dslabs_tpu.tpu.protocols.pingpong:"
                        "make_exhaustive_pingpong",
                factory_kwargs={"workload_size": 2}, tenant=t,
                chunk=64, frontier_cap=1 << 8, visited_cap=1 << 12,
                max_secs=60.0)
            assert r.get("accepted"), r


def test_service_lane_drain_costs_match_solo(tmp_path):
    """ACCEPTANCE: per-tenant COSTS sums across a batched drain equal
    the solo drain's exactly (explored/unique are copied from
    bit-identical verdicts), dispatches-per-job drops to <= 0.5x, the
    cost shares of each batch sum to its device seconds (no double
    billing), and the lanes observability block lands in
    SERVER_STATUS + the drain summary + the journal."""
    from dslabs_tpu.tpu import tracing

    def drain(lanes, root):
        srv = _mk_server(root, lanes, quota=2)
        _submit_jobs(srv)
        summary = srv.drain(max_secs=300)
        srv.close()
        return summary

    solo = drain(0, tmp_path / "solo")
    lane = drain(4, tmp_path / "lane")
    assert solo["failed"] == 0 and lane["failed"] == 0
    key = ("tenant", "end", "unique", "explored", "depth")
    sv = sorted(tuple(r.get(k) for k in key) for r in solo["results"])
    lv = sorted(tuple(r.get(k) for k in key) for r in lane["results"])
    assert sv == lv
    agg = {}
    for mode, root in (("solo", tmp_path / "solo"),
                       ("lane", tmp_path / "lane")):
        recs, torn = tracing.read_flight_lax(
            str(root / tracing.COSTS_NAME))
        assert torn == 0
        agg[mode] = tracing.aggregate_costs(recs)
    for t in ("alice", "bob"):
        assert agg["solo"][t]["explored"] == agg["lane"][t]["explored"]
        assert agg["solo"][t]["unique"] == agg["lane"][t]["unique"]
    assert (lane["dispatches_per_job"]
            <= 0.5 * solo["dispatches_per_job"])
    lb = lane["lanes"]
    assert lb["batches"] >= 1 and lb["jobs_in_lanes"] == 4
    assert lb["evicted"] == 0
    assert lb["by_signature"]
    st = json.load(open(tmp_path / "lane" / "SERVER_STATUS.json"))
    assert st["lanes"]["batches"] == lb["batches"]
    journal, _ = tracing.read_flight_lax(
        str(tmp_path / "lane" / "journal.jsonl"))
    evs = [r for r in journal if r.get("t") == "lane_batch"]
    assert evs and all(r.get("run_dir") for r in evs)
    # trace attribution: a lane job's causal tree carries the SHARED
    # batch spans, marked shared
    tr = tracing.assemble(str(tmp_path / "lane"),
                          job=evs[0]["jobs"][0])
    (j,) = tr["jobs"]
    kinds = {n["kind"] for n in j["nodes"]}
    assert "lane_batch" in kinds, kinds
    shared = [n for n in j["nodes"] if n.get("shared")]
    assert shared


@pytest.mark.slow
def test_service_evicted_lane_retries_solo(tmp_path):
    """ACCEPTANCE (eviction end to end): a job whose lane poisons
    (strict table pressure) is re-queued ``solo=True`` and still
    lands a verdict through the solo warden ladder (host rung's
    unbounded visited set), while its lane-mates' batched verdicts
    stand."""
    srv = _mk_server(tmp_path, 2, quota=2)
    # One tenant, two jobs: same signature, so they batch; the tiny
    # visited cap poisons BOTH strict lanes -> both evict -> both
    # retry solo -> host-rung verdicts.
    for _ in range(2):
        r = srv.submit(
            factory="dslabs_tpu.tpu.protocols.pingpong:"
                    "make_exhaustive_pingpong",
            factory_kwargs={"workload_size": 2}, tenant="carol",
            chunk=64, frontier_cap=1 << 8, visited_cap=8,
            max_secs=120.0)
        assert r.get("accepted"), r
    summary = srv.drain(max_secs=300)
    srv.close()
    assert summary["completed"] == 2, summary
    assert summary["lanes"]["evicted"] == 2
    ends = {r["end"] for r in summary["results"]}
    assert ends == {"SPACE_EXHAUSTED"}, ends
    engines = {r["engine"] for r in summary["results"]}
    assert "lanes" not in engines       # the verdicts came from solo


# ------------------------------------------------- observability

def test_lane_dispatch_sites_registered_and_clean():
    """The lane programs are canonical dispatch sites: every tag in
    LaneSearch.dispatch_site_programs() is registered in
    telemetry.DISPATCH_SITES (no J0), and the jaxpr audit of the lane
    engine reports ZERO findings — `analysis all` covers the new hot
    path."""
    from dslabs_tpu.analysis.jaxpr_audit import audit_search
    from dslabs_tpu.tpu.telemetry import DISPATCH_SITES

    for tag in ("lanes.init", "lanes.superstep", "lanes.promote",
                "lanes.inject", "lanes.restore", "lanes.sync",
                "lanes.flags"):
        assert tag in DISPATCH_SITES, tag
    assert DISPATCH_SITES["lanes.superstep"]["hot"]
    assert DISPATCH_SITES["lanes.superstep"]["donated"]
    ls = LaneSearch(_pingpong(), n_lanes=2, frontier_cap=1 << 8,
                    visited_cap=1 << 10)
    assert set(ls.dispatch_site_programs()) <= set(DISPATCH_SITES)
    findings = audit_search(ls)
    assert findings == [], [f.as_dict() for f in findings]


def test_status_lanes_schema_and_watch(tmp_path):
    """STATUS.json from a lane batch is schema-pinned with the
    per-lane block and `telemetry watch` renders a batched child."""
    from dslabs_tpu.tpu import telemetry as tel_mod

    tel = tel_mod.Telemetry.for_checkpoint(
        str(tmp_path / "ckpt.npz"), engine_hint="lane-batch")
    ls = LaneSearch(_pingpong(), n_lanes=2, telemetry=tel, **KW)
    res = ls.run_lanes([LaneJob("a"), LaneJob("b", max_depth=3)])
    tel.close()
    assert not res.errors
    st = json.load(open(tmp_path / "STATUS.json"))
    assert isinstance(st["lanes"], list) and st["lanes"]
    for lrec in st["lanes"]:
        assert set(lrec) >= {"lane", "job_id", "depth", "explored",
                             "unique", "frontier"}
    frame = tel_mod.render_watch(str(tmp_path))
    assert "job lane" in frame
    # level records carry one per-device lane per RESIDENT job lane
    lane_levels = [r for r in tel.levels if r.get("lanes")]
    assert lane_levels
    first = lane_levels[0]
    assert len(first["per_device"]["explored"]) == len(first["lanes"])


def test_compare_guards_dispatches_per_job_and_occupancy(tmp_path):
    """`telemetry compare`: a dispatches-per-job RISE or an occupancy
    DROP past the threshold is a regression (rc 1); parity is quiet."""
    from dslabs_tpu.tpu import telemetry as tel_mod

    ok = str(tmp_path / "ok.jsonl")
    rec = {"t": "bench", "value": 100.0,
           "lanes": {"value": 400.0, "dispatches_per_job": 8.0,
                     "occupancy": 4.0},
           "service": {"dispatches_per_job": 8.0}}
    for _ in range(2):
        tel_mod.append_ledger(ok, rec)
    cmp = tel_mod.compare_ledger(tel_mod.read_ledger(ok))
    assert cmp["regressions"] == []
    bad = str(tmp_path / "bad.jsonl")
    tel_mod.append_ledger(bad, rec)
    tel_mod.append_ledger(bad, {
        "t": "bench", "value": 100.0,
        "lanes": {"value": 400.0, "dispatches_per_job": 20.0,
                  "occupancy": 1.5}})
    cmp = tel_mod.compare_ledger(tel_mod.read_ledger(bad))
    flagged = {e["phase"] for e in cmp["regressions"]}
    assert "service:dispatches_per_job" in flagged
    assert "lanes:occupancy" in flagged
    rendered = tel_mod.render_compare(cmp)
    assert "dispatches_per_job" in rendered
