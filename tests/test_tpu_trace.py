"""TPU trace reconstruction tests: a tensor-search terminal outcome must
replay onto the object twin as a minimizable, printable causal trace
(SURVEY §8.1; SearchState.java:361-474, TraceMinimizer.java:33-61)."""

import dataclasses
import io

import pytest

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import CLIENTS_DONE

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.trace import (decode_trace,  # noqa: E402
                                  reconstruct_object_trace)


def _object_initial(nc=1, w=2):
    from dslabs_tpu.labs.clientserver.clientserver import (SimpleClient,
                                                           SimpleServer)
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.labs.clientserver.kvstore import KVStore

    server = LocalAddress("server")
    gen = NodeGenerator(
        server_supplier=lambda a: SimpleServer(a, KVStore()),
        client_supplier=lambda a: SimpleClient(a, server),
        workload_supplier=lambda a: None)
    state = SearchState(gen)
    state.add_server(server)
    for c in range(nc):
        state.add_client_worker(
            LocalAddress(f"client{c}"),
            kv_workload([f"PUT:key{c}:v{i}" for i in range(1, w + 1)],
                        ["PutOk"] * w))
    return state


def test_goal_trace_replays_on_object_twin():
    search = TensorSearch(make_clientserver_protocol(n_clients=1, w=2),
                          chunk=128, record_trace=True)
    outcome = search.run()
    assert outcome.end_condition == "GOAL_FOUND"
    assert outcome.trace, "record_trace must produce an event list"

    # tensor-space decode: records must be concrete message/timer lanes
    records = decode_trace(search, outcome)
    assert len(records) == len(outcome.trace)

    end = reconstruct_object_trace(search, outcome, _object_initial(),
                                   predicate=CLIENTS_DONE)
    r = CLIENTS_DONE.check(end)
    assert r.value, "replayed object state must satisfy the matched goal"
    # BFS traces are shortest by construction; the minimizer must not
    # lengthen them, and the printer must produce a causal trace.
    assert end.depth <= len(outcome.trace)
    buf = io.StringIO()
    end.print_trace(out=buf)
    # The causal trace must list the delivered events (envelope reprs).
    assert "Message(" in buf.getvalue() or "Timer(" in buf.getvalue()


def test_violation_trace_minimizes_on_object_twin():
    p = make_clientserver_protocol(n_clients=1, w=1)
    done = p.goals["CLIENTS_DONE"]
    p = dataclasses.replace(
        p, goals={},
        invariants={"NEVER_DONE": lambda s, f=done: ~f(s)})
    search = TensorSearch(p, chunk=128, record_trace=True)
    outcome = search.run()
    assert outcome.end_condition == "INVARIANT_VIOLATED"

    never_done = CLIENTS_DONE.negate()
    end = reconstruct_object_trace(search, outcome, _object_initial(1, 1),
                                   predicate=never_done)
    assert not never_done.check(end).value  # still violating after minimize
    buf = io.StringIO()
    end.print_trace(out=buf)
    assert buf.getvalue().strip()
