"""Unified telemetry layer (ISSUE 7, tpu/telemetry.py).

The contract under test, in the paper's discipline that every signal
must come from scalar readbacks already paid for:

* **span count == dispatch count** on pingpong, BOTH engines — the
  recorder rides the existing ``_dispatch`` seam, one span per
  dispatch, never more, never fewer;
* **zero added overhead** — attaching telemetry changes neither the
  dispatch counts nor the number of device->host readbacks (the
  ``engine.device_get`` spy), the hard acceptance constraint;
* **crash-safe flight recorder** — a SIGKILL'd run leaves a parseable
  JSONL tail whose last record names the IN-FLIGHT dispatch (the
  BENCH_r05 diagnosability fix);
* **report CLI** — renders per-level throughput and per-site latency
  percentiles from the flight log alone (golden sections pinned);
* **supervisor/bench integration** — retries/failovers become events,
  and the bench JSON's ``telemetry`` block + error-with-spans shape
  are schema-pinned so future phases can't silently drop fields.

``make obs-smoke`` runs this file including the slow bench shape.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import engine  # noqa: E402
from dslabs_tpu.tpu import telemetry as tel_mod  # noqa: E402
from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh  # noqa: E402
from dslabs_tpu.tpu.telemetry import (Telemetry, build_report,  # noqa: E402
                                      read_flight, render_report,
                                      tail_records)

pytestmark = pytest.mark.obs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pruned_pingpong():
    pp = make_pingpong_protocol(workload_size=2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def _counting_hook(counts):
    def hook(tag, fn, *args):
        counts[tag] = counts.get(tag, 0) + 1
        return fn(*args)
    return hook


def _spans(tel):
    return [r for r in tel.ring if r["t"] == "span"]


# ------------------------------------------------- span/dispatch parity

def test_span_count_equals_dispatch_count_device_engine():
    counts = {}
    tel = Telemetry()
    search = TensorSearch(_pruned_pingpong(), max_depth=8,
                          frontier_cap=1 << 10, visited_cap=1 << 12)
    search._dispatch_hook = _counting_hook(counts)
    tel.attach(search)
    out = search.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert sum(counts.values()) == len(_spans(tel))
    # Span tags are the dispatch tags, verbatim.
    by_tag = {}
    for s in _spans(tel):
        by_tag[s["tag"]] = by_tag.get(s["tag"], 0) + 1
    assert by_tag == counts


def test_span_count_equals_dispatch_count_sharded_engine():
    counts = {}
    tel = Telemetry()
    search = ShardedTensorSearch(
        _pruned_pingpong(), make_mesh(8), chunk_per_device=16,
        frontier_cap=1 << 8, visited_cap=1 << 10, max_depth=8,
        telemetry=tel)
    search._dispatch_hook = _counting_hook(counts)
    out = search.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert sum(counts.values()) == len(_spans(tel))
    assert any(s["tag"] == "sharded.superstep" for s in _spans(tel))


# ----------------------------------------------------- overhead guard

def test_overhead_guard_no_added_dispatches_or_transfers(
        monkeypatch, tmp_path):
    """ACCEPTANCE (extended by ISSUE 8, then ISSUE 10): telemetry adds
    ZERO device dispatches and ZERO device->host readbacks — dispatch
    counts and device_get call counts are bit-identical with and
    without the recorder, both engines, WITH the per-device stats
    lanes and the STATUS.json live-monitor writer enabled (full
    flight-recorder config, not a RAM-only stub).  ISSUE 10 extension:
    the soundness sanitizer OFF (DSLABS_SANITIZE unset or =0) adds
    zero dispatches, zero transfers, and zero telemetry events too."""
    monkeypatch.delenv("DSLABS_SANITIZE", raising=False)
    proto = _pruned_pingpong()
    gets = []
    real = engine.device_get

    def spy(x):
        gets.append(1)
        return real(x)

    monkeypatch.setattr(engine, "device_get", spy)

    def full_tel(name):
        # Flight log + derived STATUS.json: the whole mesh-scope
        # recorder, every writer engaged.
        tel = Telemetry(flight_log=str(tmp_path / name / "flight.jsonl"))
        assert tel.status_path is not None
        return tel

    def run_device(telemetry):
        counts = {}
        s = TensorSearch(proto, max_depth=8, frontier_cap=1 << 10,
                         visited_cap=1 << 12, telemetry=telemetry)
        s._dispatch_hook = _counting_hook(counts)
        del gets[:]
        out = s.run()
        return counts, len(gets), out

    c0, g0, o0 = run_device(None)
    c1, g1, o1 = run_device(full_tel("dev"))
    assert c0 == c1, "telemetry changed the dispatch schedule"
    assert g0 == g1, "telemetry added device->host transfers"
    assert (o0.unique_states, o0.end_condition) == \
        (o1.unique_states, o1.end_condition)
    assert (tmp_path / "dev" / "STATUS.json").exists()

    # ISSUE 13 extension: causal tracing ENABLED (trace context in the
    # env, trace fields on every span) is bit-identical too — the
    # trace discipline is record fields only, never device work.
    monkeypatch.setenv("DSLABS_TRACE_ID", "cafe0123cafe0123")
    monkeypatch.setenv("DSLABS_PARENT_SPAN", "job-x:a1")
    tel_tr = full_tel("dev-traced")
    assert tel_tr.trace_id == "cafe0123cafe0123"
    ct, gt, ot = run_device(tel_tr)
    assert ct == c0, "tracing changed the dispatch schedule"
    assert gt == g0, "tracing added device->host transfers"
    assert (ot.unique_states, ot.end_condition) == \
        (o0.unique_states, o0.end_condition)
    assert ot.trace_id == "cafe0123cafe0123"
    spans_tr = [r for r in tel_tr.ring if r["t"] == "span"]
    assert spans_tr and all(s.get("trace") == "cafe0123cafe0123"
                            for s in spans_tr)
    monkeypatch.delenv("DSLABS_TRACE_ID")
    monkeypatch.delenv("DSLABS_PARENT_SPAN")

    # ISSUE 10: DSLABS_SANITIZE=0 is bit-identical to unset — same
    # dispatch schedule, same transfer count, and no sanitizer events
    # in the recorder.
    monkeypatch.setenv("DSLABS_SANITIZE", "0")
    tel_off = full_tel("dev-sanitize-off")
    c2, g2, _o2 = run_device(tel_off)
    assert c2 == c0, "DSLABS_SANITIZE=0 changed the dispatch schedule"
    assert g2 == g0, "DSLABS_SANITIZE=0 added device->host transfers"
    assert not [e for e in tel_off.events
                if e.get("kind") == "sanitizer_finding"]
    monkeypatch.delenv("DSLABS_SANITIZE", raising=False)

    def run_sharded(telemetry):
        counts = {}
        s = ShardedTensorSearch(
            proto, make_mesh(8), chunk_per_device=16,
            frontier_cap=1 << 8, visited_cap=1 << 10, max_depth=8,
            telemetry=telemetry)
        s._dispatch_hook = _counting_hook(counts)
        del gets[:]
        s.run()
        return counts, len(gets)

    cs0, gs0 = run_sharded(None)
    cs1, gs1 = run_sharded(full_tel("sharded"))
    assert cs0 == cs1, "telemetry changed the sharded dispatch schedule"
    assert gs0 == gs1, "telemetry added sharded device->host transfers"
    assert (tmp_path / "sharded" / "STATUS.json").exists()

    # ISSUE 13: tracing enabled, sharded engine — still bit-identical.
    monkeypatch.setenv("DSLABS_TRACE_ID", "cafe0123cafe0123")
    cst, gst = run_sharded(full_tel("sharded-traced"))
    assert cst == cs0, "tracing changed the sharded dispatch schedule"
    assert gst == gs0, "tracing added sharded device->host transfers"
    monkeypatch.delenv("DSLABS_TRACE_ID")


# ------------------------------------------------------- flight log IO

def test_flight_log_records_and_levels(tmp_path):
    flight = str(tmp_path / "flight.jsonl")
    tel = Telemetry(flight_log=flight)
    search = TensorSearch(_pruned_pingpong(), max_depth=8,
                          frontier_cap=1 << 10, visited_cap=1 << 12)
    tel.attach(search)
    out = search.run()
    tel.close()
    recs = read_flight(flight)
    kinds = {r["t"] for r in recs}
    assert {"meta", "dispatch", "span", "level", "outcome"} <= kinds
    spans = [r for r in recs if r["t"] == "span"]
    starts = [r for r in recs if r["t"] == "dispatch"]
    assert len(spans) == len(starts)        # every start closed
    levels = [r for r in recs if r["t"] == "level"]
    assert len(levels) == out.depth
    oc = [r for r in recs if r["t"] == "outcome"][-1]
    assert oc["end_condition"] == out.end_condition
    assert oc["unique_states"] == out.unique_states


def test_read_flight_tolerates_torn_tail_only(tmp_path):
    p = tmp_path / "t.jsonl"
    good = json.dumps({"t": "span", "tag": "device.step", "i": 0})
    p.write_text(good + "\n" + good + "\n" + '{"t": "disp')  # torn tail
    assert len(read_flight(str(p))) == 2
    # A torn line mid-file is corruption, not truncation.
    p.write_text('{"t": "sp\n' + good + "\n")
    with pytest.raises(ValueError):
        read_flight(str(p))
    # tail_records never raises — diagnostics must not mask the error.
    assert tail_records(str(p)) == []
    assert tail_records(None) == []


def test_run_dir_layout_names_flight_log(tmp_path):
    from dslabs_tpu.tpu import checkpoint as ckpt_mod

    ck = str(tmp_path / "search.ckpt")
    lay = ckpt_mod.run_dir_layout(ck)
    assert lay["flight_log"] == str(tmp_path / "flight.jsonl")
    assert lay["compile_cache"] == str(tmp_path / "compile_cache")
    tel = Telemetry.for_checkpoint(ck)
    assert tel.flight_log == lay["flight_log"]
    tel.close()


# ------------------------------------------------------ SIGKILL survival

_KILL_CHILD = r"""
import dataclasses, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache-cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol
from dslabs_tpu.tpu.telemetry import Telemetry

pp = make_pingpong_protocol(workload_size=2)
pp = dataclasses.replace(pp, goals={},
                         prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
search = TensorSearch(pp, max_depth=10, frontier_cap=1 << 10,
                      visited_cap=1 << 12)
n = [0]
def hook(tag, fn, *args):
    n[0] += 1
    if n[0] == 6:
        print("WEDGED", flush=True)
        time.sleep(600.0)           # the wedge: parent SIGKILLs us here
    return fn(*args)
search._dispatch_hook = hook
Telemetry(flight_log=sys.argv[1]).attach(search)
search.run()
"""


def test_flight_log_survives_sigkill_names_inflight_dispatch(tmp_path):
    """ACCEPTANCE: a SIGKILL'd run leaves a parseable JSONL tail whose
    last record is the begin marker of the dispatch that was in
    flight — the wedge is attributable from the file alone."""
    flight = str(tmp_path / "flight.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILL_CHILD, flight],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=ROOT)
    try:
        line = proc.stdout.readline()       # blocks until mid-dispatch
        assert "WEDGED" in line
        time.sleep(0.3)                     # let the marker line flush
        proc.send_signal(signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    recs = read_flight(flight)              # parses despite the kill
    assert recs, "flight log must survive SIGKILL"
    spans = {(r["tag"], r["i"]) for r in recs if r["t"] == "span"}
    starts = [r for r in recs if r["t"] == "dispatch"]
    open_starts = [r for r in starts if (r["tag"], r["i"]) not in spans]
    assert len(open_starts) == 1, recs[-3:]
    # The report names the same in-flight dispatch.
    rep = build_report(recs)
    assert rep["in_flight"] is not None
    assert rep["in_flight"]["tag"] == open_starts[0]["tag"]
    assert "in-flight at EOF" in render_report(rep)


# --------------------------------------------- per-device lanes / skew

def test_per_device_lanes_and_skew_on_8_device_mesh():
    """ACCEPTANCE (ISSUE 8): on the n_devices=8 CPU dryrun mesh (the
    MULTICHIP_r05 configuration) every level record carries per-device
    lanes with 8 entries and finite skew metrics — read off the SAME
    fused stats vector the level sync already pays for."""
    import math

    tel = Telemetry()
    search = ShardedTensorSearch(
        _pruned_pingpong(), make_mesh(8), chunk_per_device=16,
        frontier_cap=1 << 8, visited_cap=1 << 10, max_depth=8,
        telemetry=tel)
    out = search.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert out.levels, "sharded outcome must carry level records"
    for rec in out.levels:
        pd = rec["per_device"]
        for lane in ("explored", "frontier", "load_factor", "drops"):
            assert len(pd[lane]) == 8, (lane, pd)
        sk = rec["skew"]
        for lane in ("explored", "frontier"):
            assert math.isfinite(sk[lane]["imbalance"])
            assert math.isfinite(sk[lane]["cv"])
            assert sk[lane]["imbalance"] >= 1.0 or \
                sk[lane]["mean"] == 0.0
        # The level's per-device explored deltas sum to the level's
        # global explored delta (the lanes ARE the pre-psum values).
    total = sum(sum(r["per_device"]["explored"]) for r in out.levels)
    assert total == out.states_explored
    # on_level fed the registry gauges.
    assert "skew.sharded" in tel.registry.gauges
    assert tel.registry.gauges["skew.sharded"].value >= 1.0


def test_per_device_lanes_swarm_rounds():
    """Swarm rounds keep their pre-psum per-device walker stats in the
    same round readback: 8 lanes per round record on the 8-device
    mesh."""
    from dslabs_tpu.tpu.swarm import SwarmSearch

    tel = Telemetry()
    sw = SwarmSearch(_pruned_pingpong(), mesh=make_mesh(8),
                     walkers_per_device=4, max_steps=8,
                     steps_per_round=4, seed=0, visited_cap=1 << 10,
                     max_rounds=2)
    tel.attach(sw)
    sw.run()
    rounds = [r for r in tel.levels if r.get("engine") == "swarm"]
    assert rounds, "swarm rounds must land level records"
    for rec in rounds:
        assert len(rec["per_device"]["explored"]) == 8
        assert len(rec["per_device"]["unique"]) == 8
        assert rec["skew"]["explored"]["imbalance"] >= 1.0


# ------------------------------------------------- STATUS.json / watch

def test_status_json_schema_and_watch_finished_run(tmp_path, capsys):
    """Tentpole leg 2: the engines' feeds atomically rewrite
    STATUS.json in the run dir (schema pinned here), and
    ``telemetry watch`` renders depth/rate/skew from the run dir
    ALONE."""
    from dslabs_tpu.tpu import checkpoint as ckpt_mod

    ck = str(tmp_path / "search.ckpt")
    assert ckpt_mod.run_dir_layout(ck)["status"] == \
        str(tmp_path / "STATUS.json")
    tel = Telemetry.for_checkpoint(ck)
    assert tel.status_path == str(tmp_path / "STATUS.json")
    search = ShardedTensorSearch(
        _pruned_pingpong(), make_mesh(8), chunk_per_device=16,
        frontier_cap=1 << 8, visited_cap=1 << 10, max_depth=8,
        telemetry=tel)
    out = search.run()
    tel.close()

    st = json.loads((tmp_path / "STATUS.json").read_text())
    for key in ("t", "pid", "updated", "uptime", "spans", "levels",
                "last_span", "in_flight", "flight_log", "engine",
                "depth", "explored", "unique", "rate_per_min",
                "rate_per_min_window", "skew", "per_device",
                "end_condition", "mesh_width", "trace_id",
                "parent_span", "span_id"):
        assert key in st, f"STATUS.json missing {key!r}"
    # ISSUE 13 satellite: BOTH rates are real numbers — cumulative
    # over the whole run, sliding-window over the last N levels.
    assert st["rate_per_min"] is not None
    assert st["rate_per_min_window"] is not None
    assert st["t"] == "status"
    assert st["pid"] == os.getpid()
    assert st["engine"] == "sharded"
    assert st["depth"] == out.depth
    assert st["end_condition"] == out.end_condition
    assert st["in_flight"] is None          # run finished cleanly
    assert len(st["per_device"]["explored"]) == 8
    # Live mesh width (ISSUE 9): derived from the per-device lanes so
    # `telemetry watch` shows a degraded mesh the moment it shrinks.
    assert st["mesh_width"] == 8

    assert tel_mod.main(["watch", str(tmp_path), "--once"]) == 0
    text = capsys.readouterr().out
    assert f"depth {out.depth}" in text
    assert "rate" in text
    assert "skew:" in text
    assert f"end: {out.end_condition}" in text


_WATCH_KILL_CHILD = r"""
import dataclasses, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache-cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol
from dslabs_tpu.tpu.telemetry import Telemetry

pp = make_pingpong_protocol(workload_size=2)
pp = dataclasses.replace(pp, goals={},
                         prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
search = TensorSearch(pp, max_depth=10, frontier_cap=1 << 10,
                      visited_cap=1 << 12)
n = [0]
def hook(tag, fn, *args):
    n[0] += 1
    if n[0] == 6:
        print("WEDGED", flush=True)
        time.sleep(600.0)           # the wedge: parent SIGKILLs us here
    return fn(*args)
search._dispatch_hook = hook
Telemetry.for_checkpoint(sys.argv[1] + "/search.ckpt").attach(search)
search.run()
"""


def test_watch_survives_sigkill_mid_level(tmp_path):
    """ACCEPTANCE: ``telemetry watch`` renders a run in ANOTHER
    process from the run dir alone and survives that run being
    SIGKILLed mid-level — the atomic STATUS.json is never torn, the
    flight log's torn tail is tolerated, and the last in-flight
    dispatch is named."""
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _WATCH_KILL_CHILD, run_dir],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=ROOT)
    try:
        line = proc.stdout.readline()       # blocks until mid-dispatch
        assert "WEDGED" in line
        time.sleep(0.3)                     # let the marker line flush
        # The run is alive but wedged: the watcher (another process's
        # view, same code path) already renders from the dir alone.
        live = tel_mod.render_watch(run_dir)
        assert "in-flight" in live
        proc.send_signal(signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    frame = tel_mod.render_watch(run_dir)
    assert "engine device" in frame         # depth/rate line rendered
    assert "depth" in frame and "rate" in frame
    assert "in-flight" in frame, frame      # the dispatch it died in
    assert tel_mod.main(["watch", run_dir, "--once"]) == 0


# ------------------------------------------------- report --json schema

def test_report_json_schema_pin(tmp_path, capsys):
    """ISSUE 8 satellite: ``report --json`` emits the same sections as
    the rendered report, machine-readable — ONE schema for grading
    scripts and the ledger compare path (top-level keys pinned)."""
    flight = str(tmp_path / "flight.jsonl")
    tel = Telemetry(flight_log=flight)
    search = TensorSearch(_pruned_pingpong(), max_depth=8,
                          frontier_cap=1 << 10, visited_cap=1 << 12)
    tel.attach(search)
    out = search.run()
    tel.close()
    assert tel_mod.main(["report", flight, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    for key in ("meta", "n_spans", "sites", "series", "timeline",
                "outcomes", "counts", "total_wall", "compile_wall",
                "in_flight", "source"):
        assert key in rep, f"report --json missing {key!r}"
    assert rep["source"] == flight
    assert rep["in_flight"] is None
    assert len(rep["series"]["device"]) == out.depth
    # The per-device lanes ride the series records (the heatmap's and
    # the graders' one source).
    assert rep["series"]["device"][0]["per_device"]["explored"]
    assert rep["outcomes"][-1]["end_condition"] == out.end_condition


# --------------------------------------------------- bench ledger diff

def test_ledger_compare_flags_injected_regression_and_parity(
        tmp_path, capsys):
    """ACCEPTANCE: ``telemetry compare`` on a ledger with an injected
    slow run flags the regression with the offending phase and delta;
    a parity run flags nothing."""
    from dslabs_tpu.tpu.telemetry import (append_ledger, compare_ledger,
                                          read_ledger)

    ledger = str(tmp_path / "BENCH_HISTORY.jsonl")
    base = {"t": "bench", "value": 4.0e6,
            "strict": {"value": 4.0e6, "unique": 1000},
            "swarm": {"value": 2.0e6}}
    append_ledger(ledger, base)
    append_ledger(ledger, {**base, "value": 3.9e6,
                           "strict": {"value": 3.8e6}})  # parity noise
    assert tel_mod.main(["compare", ledger]) == 0
    text = capsys.readouterr().out
    assert "parity: no phase regressed" in text
    assert "REGRESSION" not in text

    append_ledger(ledger, {**base, "value": 1.0e6,
                           "strict": {"value": 0.9e6}})  # injected slow
    assert tel_mod.main(["compare", ledger]) == 1
    text = capsys.readouterr().out
    assert "REGRESSION: phase=strict" in text
    cmp = compare_ledger(read_ledger(ledger))
    reg = {e["phase"]: e for e in cmp["regressions"]}
    assert "strict" in reg and "headline" in reg
    assert reg["strict"]["delta_pct"] < -25.0
    # A torn tail (a run killed mid-append) must not kill the reader.
    with open(ledger, "a") as f:
        f.write('{"t": "ben')
    assert compare_ledger(read_ledger(ledger))["regressions"]


def test_ledger_compare_flags_headline_mesh_width_fallback(tmp_path):
    """ISSUE 12: a run whose headline silently fell back to a narrower
    mesh (mesh_width 8 -> 1) is a REGRESSION even when its states/min
    compares as a win — and an equal-width faster run stays a clean
    improvement."""
    from dslabs_tpu.tpu.telemetry import (append_ledger, compare_ledger,
                                          read_ledger)

    ledger = str(tmp_path / "BENCH_HISTORY.jsonl")
    append_ledger(ledger, {"t": "bench", "value": 4.0e6,
                           "mesh_width": 8,
                           "mesh": {"value": 4.0e6}})
    append_ledger(ledger, {"t": "bench", "value": 6.0e6,
                           "mesh_width": 1,
                           "mesh": {"value": 6.0e6}})
    cmp = compare_ledger(read_ledger(ledger))
    reg = {e["phase"]: e for e in cmp["regressions"]}
    assert "headline:mesh_width" in reg
    assert reg["headline:mesh_width"]["latest"] == 1
    assert reg["headline:mesh_width"]["best_prior"] == 8

    append_ledger(ledger, {"t": "bench", "value": 7.0e6,
                           "mesh_width": 8,
                           "mesh": {"value": 7.0e6}})
    cmp = compare_ledger(read_ledger(ledger))
    assert not any(e["phase"] == "headline:mesh_width"
                   for e in cmp["regressions"])
    assert cmp["mesh_width"]["mesh_width"]["latest"] == 8
    # The mesh phase itself is tracked like any rate phase.
    assert cmp["phases"]["mesh"]["latest"] == 7000000.0


# ------------------------------------------------------------ report CLI

def test_report_cli_golden_sections(tmp_path, capsys):
    """The report CLI renders per-level throughput and per-site latency
    percentiles FROM THE LOG ALONE (acceptance) — section headers and
    key fields pinned."""
    flight = str(tmp_path / "flight.jsonl")
    tel = Telemetry(flight_log=flight)
    search = ShardedTensorSearch(
        _pruned_pingpong(), make_mesh(8), chunk_per_device=16,
        frontier_cap=1 << 8, visited_cap=1 << 10, max_depth=8,
        telemetry=tel)
    out = search.run()
    tel.close()
    # A run dir (the checkpoint's directory) resolves to flight.jsonl.
    assert tel_mod.main(["report", str(tmp_path)]) == 0
    text = capsys.readouterr().out
    for header in ("== dslabs run report", "-- dispatch latency by site --",
                   "-- per-level throughput --",
                   "-- per-device skew (explored share per level) --",
                   "-- recovery timeline --",
                   "-- spill / overflow / recovery counts --"):
        assert header in text, f"missing section {header!r}"
    # Heatmap rows: one per level, 8 cells wide, with skew columns.
    heat = [ln for ln in text.splitlines() if ln.startswith("d ")
            or (ln.startswith("d") and "|" in ln and "imb=" in ln)]
    assert len(heat) == out.depth
    assert all(ln.count("|") == 2 and "cv=" in ln for ln in heat)
    assert "sharded.superstep" in text
    assert "[engine sharded]" in text
    assert f"outcome: {out.end_condition}" in text
    assert "p50ms" in text and "p99ms" in text and "states/s" in text
    # One throughput row per completed level.
    lines = text.splitlines()
    i = lines.index("[engine sharded]")
    rows = [ln for ln in lines[i + 2:] if ln and ln[0] != "["
            and not ln.startswith("--")]
    assert len([r for r in rows if r.strip()
                and r.strip()[0].isdigit()]) == out.depth


# ------------------------------------------- supervisor / event plumbing

def test_supervisor_retries_become_events_and_span_retries():
    from dslabs_tpu.tpu.supervisor import (FaultPlan, RetryPolicy,
                                           SearchSupervisor)

    tel = Telemetry()
    plan = FaultPlan().raise_at(2, engine="host")
    sup = SearchSupervisor(
        _pruned_pingpong(), ladder=("host",),
        policy=RetryPolicy(max_retries=2, backoff_base=0.001),
        fault_plan=plan, max_depth=8, chunk=1 << 8,
        frontier_cap=1 << 10, visited_cap=1 << 12, telemetry=tel)
    out = sup.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert out.retries >= 1
    ev = {r["kind"] for r in tel.events if r.get("t") == "event"}
    assert "rung" in ev and "retry" in ev
    assert tel.registry.counters["events.retry"].value >= 1
    # The retry is charged to the span of the dispatch that absorbed it.
    assert sum(s["retries"] for s in _spans(tel)) == out.retries


def test_profiler_window_knob_is_safe(tmp_path, monkeypatch):
    """DSLABS_PROFILE wraps post-warmup dispatches in jax.profiler
    windows; whatever the platform does with that, the search itself
    must be unaffected (the knob can never take a run down)."""
    monkeypatch.setenv("DSLABS_PROFILE", str(tmp_path / "prof"))
    monkeypatch.setenv("DSLABS_PROFILE_STEPS", "2")
    tel = Telemetry()
    search = TensorSearch(_pruned_pingpong(), max_depth=8,
                          frontier_cap=1 << 10, visited_cap=1 << 12)
    tel.attach(search)
    out = search.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert not tel._profile.active          # window closed behind itself


# ------------------------------------------------- bench JSON schema pin

@pytest.mark.slow
def test_bench_json_schema_pins_telemetry_and_wedge_shapes():
    """SCHEMA PIN (ISSUE-7 satellite): the bench's last-line JSON must
    carry (a) the ``telemetry`` block with per-phase span summaries and
    flight-log paths, and (b) on a wedged phase, ``wedge_diagnostics``
    whose entries name the phase, the child's last heartbeat, AND its
    last flight-recorder spans — including the in-flight dispatch of
    the hang (the BENCH_r05 fix).  Future phases cannot silently drop
    these fields."""
    env = dict(os.environ, DSLABS_FORCE_CPU="1",
               DSLABS_BENCH_FAKE_WEDGE="hang",
               DSLABS_BENCH_PREFLIGHT_SILENCE_SECS="8",
               DSLABS_FALLBACK_DEPTH="5",
               DSLABS_BENCH_DEADLINE_SECS="400")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        capture_output=True, text=True, timeout=380, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])

    # (b) the error-with-spans shape
    assert "wedge_diagnostics" in out, out.keys()
    diag = out["wedge_diagnostics"][0]
    for key in ("phase", "message", "last_heartbeat", "last_spans"):
        assert key in diag, diag.keys()
    assert diag["phase"] == "preflight"
    assert diag["last_heartbeat"] is not None
    # The hang ran inside a telemetry span: its begin marker is in the
    # flight tail, naming the in-flight dispatch.
    assert any(r.get("tag") == "preflight.hang"
               for r in diag["last_spans"]), diag["last_spans"]

    # (a) the telemetry block (cpu-fallback phase ran for real)
    tl = out["telemetry"]
    assert "run_dir" in tl and "phases" in tl
    ph = tl["phases"]["cpu-fallback"]
    for key in ("spans", "dispatches", "sites", "events", "levels",
                "flight_log"):
        assert key in ph, ph.keys()
    assert ph["spans"] > 0
    assert ph["levels"] > 0
    assert any(site.startswith("device.") for site in ph["sites"])
