"""Trace-viewer tests (visualization subsystem: DebuggerWindow/JTrees/
VizConfig analogs — SURVEY §2.6)."""

import json
import re

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.search.trace import SerializableTrace, save_trace
from dslabs_tpu.testing.predicates import NONE_DECIDED
from dslabs_tpu.viz import render_trace_html, serve_trace, viz_configs
from dslabs_tpu.viz.server import state_dump, viz_ignore

from tests.test_traces import violating_state


def test_render_trace_html(tmp_path):
    end = violating_state()
    path = save_trace(end, [NONE_DECIDED], "0", None, "PingTest", "viz",
                      directory=str(tmp_path))
    trace = SerializableTrace.load(path)
    page = render_trace_html(trace)
    # The embedded step data covers every event plus the initial state.
    m = re.search(r"const STEPS = (\[.*?\]);\n", page, re.S)
    assert m, "steps JSON missing from the page"
    steps = json.loads(m.group(1))
    assert len(steps) == len(trace.history) + 1
    assert steps[0]["event"] == "(initial state)"
    assert "pingserver" in steps[0]["state"]["nodes"]
    assert "client1" in steps[0]["state"]["nodes"]
    # Delivered events and diffs are renderable.
    assert any("Message(" in s["event"] or "Timer(" in s["event"]
               for s in steps[1:])


def test_serve_trace_writes_html(tmp_path):
    end = violating_state()
    path = save_trace(end, [NONE_DECIDED], "0", None, "PingTest", "viz2",
                      directory=str(tmp_path))
    out = str(tmp_path / "trace.html")
    assert serve_trace(path, out_path=out) == 0
    content = open(out).read()
    assert "dslabs trace viewer" in content
    assert serve_trace(str(tmp_path / "missing.trace")) == 1


def test_viz_ignore_hides_fields():
    @viz_ignore("secret")
    class FakeNode:
        def __init__(self):
            self.visible = 1
            self.secret = 2
            self._internal = 3

    class FakeState:
        def addresses(self):
            return [LocalAddress("n1")]

        def node(self, a):
            return FakeNode()

        def network(self):
            return []

        def timers(self, a):
            return None

    d = state_dump(FakeState())
    assert d["nodes"]["n1"] == {"visible": "1"}


def test_viz_configs_build_initial_states():
    configs = viz_configs()
    assert {"0", "1", "3"} <= set(configs)
    s0 = configs["0"](["1", "2", "a,b"])
    assert len(list(s0.addresses())) == 3   # server + 2 clients
    s3 = configs["3"](["3", "1"])
    assert len(list(s3.addresses())) == 4   # 3 paxos servers + client
    # The built states are searchable (events enumerable).
    assert s3.events(None)
