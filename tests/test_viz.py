"""Trace-viewer tests (visualization subsystem: DebuggerWindow/JTrees/
VizConfig analogs — SURVEY §2.6)."""

import json
import re

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.search.trace import SerializableTrace, save_trace
from dslabs_tpu.testing.predicates import NONE_DECIDED
from dslabs_tpu.viz import render_trace_html, serve_trace, viz_configs
from dslabs_tpu.viz.server import state_dump, viz_ignore

from tests.test_traces import violating_state


def test_render_trace_html(tmp_path):
    end = violating_state()
    path = save_trace(end, [NONE_DECIDED], "0", None, "PingTest", "viz",
                      directory=str(tmp_path))
    trace = SerializableTrace.load(path)
    page = render_trace_html(trace)
    # The embedded step data covers every event plus the initial state.
    m = re.search(r"const STEPS = (\[.*?\]);\n", page, re.S)
    assert m, "steps JSON missing from the page"
    steps = json.loads(m.group(1))
    assert len(steps) == len(trace.history) + 1
    assert steps[0]["event"] == "(initial state)"
    assert "pingserver" in steps[0]["state"]["nodes"]
    assert "client1" in steps[0]["state"]["nodes"]
    # Delivered events and diffs are renderable.
    assert any("Message(" in s["event"] or "Timer(" in s["event"]
               for s in steps[1:])


def test_serve_trace_writes_html(tmp_path):
    end = violating_state()
    path = save_trace(end, [NONE_DECIDED], "0", None, "PingTest", "viz2",
                      directory=str(tmp_path))
    out = str(tmp_path / "trace.html")
    assert serve_trace(path, out_path=out) == 0
    content = open(out).read()
    assert "dslabs trace viewer" in content
    assert serve_trace(str(tmp_path / "missing.trace")) == 1


def test_viz_ignore_hides_fields():
    @viz_ignore("secret")
    class FakeNode:
        def __init__(self):
            self.visible = 1
            self.secret = 2
            self._internal = 3

    class FakeState:
        def addresses(self):
            return [LocalAddress("n1")]

        def node(self, a):
            return FakeNode()

        def network(self):
            return []

        def timers(self, a):
            return None

    d = state_dump(FakeState())
    assert d["nodes"]["n1"] == {"visible": "1"}


def test_viz_configs_build_initial_states():
    configs = viz_configs()
    assert {"0", "1", "3"} <= set(configs)
    s0 = configs["0"](["1", "2", "a,b"])
    assert len(list(s0.addresses())) == 3   # server + 2 clients
    s3 = configs["3"](["3", "1"])
    assert len(list(s3.addresses())) == 4   # 3 paxos servers + client
    # The built states are searchable (events enumerable).
    assert s3.events(None)


def test_event_tree_branch_exploration():
    """EventTreeState.java:47-209 capability: pending events of any tree
    node are deliverable, steps are cached, branches diverge, and the
    path-from-initial reflects the chosen branch."""
    from dslabs_tpu.viz.debugger import EventTree

    state = viz_configs()["0"](["1", "1", "ping1,ping2"])
    tree = EventTree(state)
    pend = tree.pending(0)
    assert pend, "initial state must have deliverable events"
    a = tree.step(0, 0)
    assert a == 1
    assert tree.step(0, 0) == a, "step caching: same (node, event) -> same child"
    # A second event (if any) forms a DIFFERENT branch from the root.
    if len(pend) > 1:
        b = tree.step(0, 1)
        assert b not in (None, a)
    # Walk one branch deeper; the breadcrumb path follows it.
    deeper = tree.step(a, 0)
    if deeper is not None:
        j = tree.node_json(deeper)
        assert [p["id"] for p in j["path"]][:2] == [0, a]
        assert j["depth"] == 2
        assert j["parent_state"] is not None


def test_debugger_http_roundtrip():
    """The served debugger: GET /node/0 lists pending events; POST /step
    delivers one and the child is retrievable."""
    import json
    import urllib.request

    from dslabs_tpu.viz.debugger import serve_debugger

    state = viz_configs()["0"](["1", "1", "ping1"])
    server, tree = serve_debugger(state, open_browser=False, block=False)
    try:
        port = server.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return json.loads(r.read())

        root = get("/node/0")
        assert root["pending"], "root must list pending events"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/step",
            data=json.dumps({"id": 0, "event": 0}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as r:
            child = json.loads(r.read())["child"]
        assert child == 1
        node = get(f"/node/{child}")
        assert node["parent"] == 0 and node["depth"] == 1
        # The HTML app itself is served.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=5) as r:
            assert b"dslabs debugger" in r.read()
    finally:
        server.shutdown()
        server.server_close()


def test_debugger_tree_canvas_endpoint():
    """The /tree endpoint (StateTreeCanvas capability): the whole
    explored tree is served DFS-ordered with parent links, and the HTML
    app embeds the canvas renderer."""
    import json
    import urllib.request

    from dslabs_tpu.viz.debugger import serve_debugger

    state = viz_configs()["0"](["1", "1", "ping1"])
    server, tree = serve_debugger(state, open_browser=False, block=False)
    try:
        port = server.server_address[1]
        # Explore two branches from the root.
        tree.step(0, 0)
        child2 = tree.step(0, 1) if len(tree.pending(0)) > 1 else None
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/tree", timeout=5) as r:
            t = json.loads(r.read())
        ids = [n["id"] for n in t["nodes"]]
        assert ids[0] == 0 and 1 in ids
        by_id = {n["id"]: n for n in t["nodes"]}
        assert by_id[1]["parent"] == 0 and by_id[1]["depth"] == 1
        if child2 is not None:
            assert by_id[child2]["parent"] == 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=5) as r:
            html = r.read().decode()
        assert "drawTree" in html and 'id="tree"' in html
    finally:
        server.shutdown()
        server.server_close()
