"""Process-isolated dispatch warden (ISSUE 4): the deterministic
kill/hang/crash matrix, on CPU, no broken hardware required:

* a child SIGKILLed mid-search (injected ``die`` fault) is reaped,
  classified, and the next rung's child RESUMES from the durable
  checkpoint to the identical verdict/unique/explored counts as an
  unfaulted run — strict pingpong AND lab1, the tier-1 acceptance;
* a hung child (injected uninterruptible ``hang``) is SIGKILLed within
  its announced heartbeat grace — seconds, not a leaked thread;
* exit-code classification is pinned (wedge / oom / crash / failed);
* the checkpoint ``.prev`` rotation + content checksum make a SIGKILL
  landing mid-checkpoint-write recoverable: a truncated main dump
  falls back to the rotated previous dump with a loud warning and
  resumes to verdict parity.

Marked ``fault`` (``make fault-smoke`` runs the whole matrix); the
slowest spawn-heavy variants are additionally ``slow`` so the tier-1
gate keeps only the fast CPU warden tests.
"""

import dataclasses
import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import checkpoint as ckpt_mod  # noqa: E402
from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.supervisor import (EngineFailure,  # noqa: E402
                                       SearchSupervisor,
                                       SupervisorExhausted)
from dslabs_tpu.tpu.warden import (CHILD_RC_FAILED, Warden,  # noqa: E402
                                   classify_death)

pytestmark = pytest.mark.fault

# Children are fresh processes: share the suite's persistent compile
# cache (tests/conftest.py) or every spawn pays a cold XLA build.
CHILD_ENV = {"DSLABS_COMPILE_CACHE": "/tmp/jaxcache-cpu"}


# Module-level so warden children can import them by reference
# ("tests.test_warden:prune_pingpong") — closures cannot cross the
# spawn boundary.

def prune_pingpong(pp):
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def prune_clientserver(cs):
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


PINGPONG = {
    "factory":
        "dslabs_tpu.tpu.protocols.pingpong:make_pingpong_protocol",
    "factory_kwargs": {"workload_size": 2},
    "transform": "tests.test_warden:prune_pingpong",
}
LAB1 = {
    "factory":
        "dslabs_tpu.tpu.protocols.clientserver:"
        "make_clientserver_protocol",
    "factory_kwargs": {"n_clients": 1, "w": 2},
    "transform": "tests.test_warden:prune_clientserver",
}


def _warden(refs, **kw):
    kw.setdefault("chunk", 64)
    kw.setdefault("frontier_cap", 1 << 8)
    kw.setdefault("visited_cap", 1 << 12)
    kw.setdefault("env", CHILD_ENV)
    return Warden(**refs, **kw)


def _base_pingpong():
    return TensorSearch(prune_pingpong(make_pingpong_protocol(2)),
                        chunk=64).run()


def _base_lab1():
    return TensorSearch(
        prune_clientserver(make_clientserver_protocol(n_clients=1, w=2)),
        chunk=64).run()


def _same_verdict(a, b):
    assert a.end_condition == b.end_condition
    assert a.unique_states == b.unique_states
    assert a.states_explored == b.states_explored


# ------------------------------------------------- exit-code taxonomy

def test_exit_code_classification_pinned():
    """The death taxonomy is part of the warden's contract: a warden
    SIGKILL is a wedge, an unprompted SIGKILL is the OOM killer or an
    external kill, CHILD_RC_FAILED is a reported in-child failure,
    everything else is a crash."""
    import signal

    assert classify_death(-signal.SIGKILL, True) == "wedge"
    assert classify_death(-signal.SIGKILL, False) == "oom"
    assert classify_death(-signal.SIGSEGV, False) == "crash"
    assert classify_death(-signal.SIGTERM, False) == "crash"
    assert classify_death(CHILD_RC_FAILED, False) == "failed"
    assert classify_death(1, False) == "crash"
    assert classify_death(86, False) == "crash"


# --------------------------------------- SIGKILL mid-search -> resume

def test_child_sigkill_mid_search_resumes_strict_pingpong(tmp_path):
    """ACCEPTANCE: a child SIGKILLed mid-search (dispatch 8 of the
    device rung — wave 3, after checkpoints have landed) produces the
    IDENTICAL strict pingpong verdict as an unfaulted run, resumed
    from the durable checkpoint by the next rung's child."""
    base = _base_pingpong()
    assert base.end_condition == "SPACE_EXHAUSTED"
    w = _warden(PINGPONG, ladder=("device", "host"),
                checkpoint_path=str(tmp_path / "pp.npz"),
                checkpoint_every=1,
                fault={"kind": "die", "at": 8, "engine": "device",
                       "after_ckpt": True})
    out = w.run()
    _same_verdict(out, base)
    assert out.engine == "host"
    assert out.failovers == 1
    assert out.child_restarts == 1
    assert out.resumed_from_depth > 0
    assert [d.kind for d in w.deaths] == ["oom"]
    # The heartbeat protocol carried the dispatch seam's state out of
    # the dead child: tag, index, live depth, durable-resume depth.
    hb = w.deaths[0].last_hb
    assert hb is not None and hb["tag"].startswith("device.")
    for key in ("n", "depth", "ckpt_depth"):
        assert key in hb


def test_child_sigkill_mid_search_resumes_strict_lab1(tmp_path):
    """ACCEPTANCE: same SIGKILL-resume parity on the lab1 strict
    clientserver BFS (a deeper space; more checkpoints survive)."""
    base = _base_lab1()
    assert base.end_condition == "SPACE_EXHAUSTED"
    w = _warden(LAB1, ladder=("device", "host"),
                checkpoint_path=str(tmp_path / "cs.npz"),
                checkpoint_every=1,
                fault={"kind": "die", "at": 11, "engine": "device",
                       "after_ckpt": True})
    out = w.run()
    _same_verdict(out, base)
    assert out.engine == "host"
    assert out.child_restarts == 1
    assert out.resumed_from_depth > 0


# --------------------------------------------------- hang -> SIGKILL

def test_hung_child_is_reaped_within_deadline(tmp_path):
    """A child that wedges mid-dispatch (uninterruptible hang — the
    shape the in-process watchdog can only abandon) is SIGKILLed
    within its announced heartbeat grace and the search completes on
    the next rung.  The whole recovery must take seconds, not the
    3600 s the hang would run."""
    base = _base_pingpong()
    t0 = time.time()
    w = _warden(PINGPONG, ladder=("device", "host"),
                checkpoint_path=str(tmp_path / "hang.npz"),
                checkpoint_every=1,
                boot_grace=120.0, first_grace=120.0, steady_grace=3.0,
                idle_grace=60.0, grace_slack=1.0,
                fault={"kind": "hang", "at": 8, "engine": "device"})
    out = w.run()
    elapsed = time.time() - t0
    _same_verdict(out, base)
    assert [d.kind for d in w.deaths] == ["wedge"]
    assert out.killed_dispatches == 1
    assert out.child_restarts == 1
    # Generous bound for a loaded 1-core CI box; the hang itself was
    # cut at steady_grace + slack = 4 s.
    assert elapsed < 90.0, f"hung child reaped too slowly ({elapsed:.0f}s)"


# ------------------------------------------------ crash / failed rungs

@pytest.mark.slow
def test_abrupt_child_exit_classified_crash_and_failed_over(tmp_path):
    """An abrupt os._exit mid-search is a ``crash``; the ladder
    recovers on the next rung with verdict parity."""
    base = _base_pingpong()
    w = _warden(PINGPONG, ladder=("device", "host"),
                checkpoint_path=str(tmp_path / "crash.npz"),
                checkpoint_every=1,
                fault={"kind": "exit", "at": 8, "engine": "device"})
    out = w.run()
    _same_verdict(out, base)
    assert [d.kind for d in w.deaths] == ["crash"]
    assert w.deaths[0].exitcode == 86


@pytest.mark.slow
def test_in_child_fatal_error_reported_and_exhausts_ladder():
    """A classified in-child failure (injected fatal raise) is reported
    over the pipe (``failed``, CHILD_RC_FAILED) and a single-rung
    ladder surfaces it as a loud SupervisorExhausted with the per-rung
    chain — never a silent empty exit."""
    w = _warden(PINGPONG, ladder=("device",),
                fault={"kind": "raise", "at": 3, "engine": "device"})
    with pytest.raises(SupervisorExhausted) as ei:
        w.run()
    assert len(ei.value.failures) == 1
    f = ei.value.failures[0]
    assert isinstance(f, EngineFailure)
    assert f.engine == "device" and f.kind == "failed"
    assert w.deaths[0].exitcode == CHILD_RC_FAILED


@pytest.mark.slow
def test_last_rung_forces_cpu_runtime():
    """The last rung's child env pins JAX_PLATFORMS=cpu (plus the
    config re-pin): when the accelerator runtime itself is broken, the
    final rung must not touch it."""
    w = _warden(PINGPONG, ladder=("host",))
    out = w.run()
    assert out.engine == "host"
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert w.last_platform == "cpu"


# -------------------------------------- supervisor process-isolation

def test_supervisor_process_isolation_mode_verdict_parity():
    """SearchSupervisor(process_isolation=True) rides the warden with
    identical verdict semantics and the extended recovery accounting
    fields present on the outcome."""
    base = _base_pingpong()
    sup = SearchSupervisor(
        None, ladder=("device",), chunk=64, frontier_cap=1 << 8,
        visited_cap=1 << 12, process_isolation=True,
        protocol_factory=PINGPONG["factory"],
        factory_kwargs=PINGPONG["factory_kwargs"],
        protocol_transform=PINGPONG["transform"],
        warden_kwargs={"env": CHILD_ENV})
    out = sup.run()
    _same_verdict(out, base)
    assert out.engine == "device"
    assert (out.failovers, out.child_restarts,
            out.killed_dispatches) == (0, 0, 0)


def test_process_isolation_requires_factory():
    sup = SearchSupervisor(None, ladder=("device",),
                           process_isolation=True)
    with pytest.raises(ValueError, match="protocol_factory"):
        sup.run()


# ------------------------------- checkpoint torn-write robustness

def _mini_ckpt(fingerprint, depth):
    return ckpt_mod.SearchCheckpoint(
        fingerprint=fingerprint, depth=depth, explored=10 * depth,
        elapsed=1.0 * depth,
        frontier=np.full((2, 3), depth, np.int32),
        visited_keys=np.full((4, 4), depth, np.uint32))


def test_checkpoint_save_rotates_prev(tmp_path):
    """Every save rotates the previous dump to ``.prev``: after two
    saves both generations are on disk and checksum-verified."""
    path = str(tmp_path / "rot.npz")
    ckpt_mod.save(path, _mini_ckpt("fp", 1))
    assert not os.path.exists(path + ".prev")
    ckpt_mod.save(path, _mini_ckpt("fp", 2))
    assert os.path.exists(path + ".prev")
    assert ckpt_mod.load(path, "fp").depth == 2
    assert ckpt_mod.load(path + ".prev", "fp").depth == 1
    assert ckpt_mod.peek_depth(path) == 2


def test_truncated_main_falls_back_to_prev_with_loud_warning(tmp_path):
    """A torn main dump (truncation — the SIGKILL-mid-write shape)
    fails its read/checksum and the loader falls back to the rotated
    previous dump WITH a RuntimeWarning, never a crash or a silent
    root restart."""
    path = str(tmp_path / "torn.npz")
    ckpt_mod.save(path, _mini_ckpt("fp", 1))
    ckpt_mod.save(path, _mini_ckpt("fp", 2))
    with open(path, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 3])         # torn mid-write
    with pytest.warns(RuntimeWarning, match="falling back"):
        ck = ckpt_mod.load(path, "fp")
    assert ck.depth == 1                        # the rotated dump
    # peek_* must track what the loader would resume.
    assert ckpt_mod.peek_fingerprint(path) == "fp"
    assert ckpt_mod.peek_depth(path) == 1


def test_corrupt_payload_detected_by_checksum(tmp_path):
    """A bit-flip that keeps the zip READABLE is caught by the content
    checksum; with no ``.prev`` to fall back to the loader raises a
    loud CheckpointCorrupt instead of resuming garbage."""
    path = str(tmp_path / "flip.npz")
    ckpt_mod.save(path, _mini_ckpt("fp", 3))
    with open(path, "r+b") as f:
        blob = bytearray(f.read())
        # Flip a byte inside the frontier ARRAY PAYLOAD (npz members
        # are stored uncompressed, so the fill pattern is findable);
        # either the zip member CRC or the content checksum must
        # refuse the dump — never a silent resume of garbage.
        payload = np.full((2, 3), 3, np.int32).tobytes()
        off = blob.find(payload)
        assert off > 0, "frontier payload not found in npz"
        blob[off] ^= 0xFF
        f.seek(0)
        f.write(blob)
    with pytest.warns(RuntimeWarning):
        with pytest.raises(ckpt_mod.CheckpointCorrupt):
            ckpt_mod.load(path, "fp")


def test_sigkill_mid_checkpoint_write_resume_parity(tmp_path):
    """End-to-end resume parity across the rotation: a checkpointed
    run is cut at depth 2, the NEXT dump is 'killed mid-write'
    (rotation done, main torn), and a fresh engine resumes from the
    rotated dump to the identical verdict as an uninterrupted run."""
    proto = prune_pingpong(make_pingpong_protocol(2))
    full = TensorSearch(proto, chunk=64).run()
    path = str(tmp_path / "kill.npz")
    cut = TensorSearch(proto, chunk=64, max_depth=2,
                       checkpoint_path=path, checkpoint_every=1)
    assert cut.run().end_condition == "DEPTH_EXHAUSTED"
    # Simulate the torn write: the good depth-2 dump was rotated to
    # .prev and the in-flight replacement died mid-write.
    with open(path, "rb") as f:
        blob = f.read()
    os.replace(path, path + ".prev")
    with open(path, "wb") as f:
        f.write(blob[:200])
    resumed = TensorSearch(proto, chunk=64, checkpoint_path=path)
    with pytest.warns(RuntimeWarning, match="falling back"):
        r = resumed.run(resume=True)
    _same_verdict(r, full)
    assert resumed._resumed_from_depth == 2
