"""Fault-tolerant search supervisor (ISSUE 2): every recovery path
proven end-to-end on CPU via the deterministic fault-injection harness
(tpu/supervisor.py FaultPlan) installed at the dispatch boundary:

* transient-error retry succeeds within budget (identical outcome);
* exhausted retries / fatal errors fail over sharded -> single-device
  -> host on a lab1 strict BFS with a verdict identical to the
  unfaulted run;
* a run killed mid-search resumes from its checkpoint in both engines
  (and across engines — the dump format is engine-agnostic);
* a hung dispatch is detected by the wall-clock watchdog, abandoned,
  and recovered on the next rung;
* no recovery path ever returns a silent partial verdict — total
  failure is a loud SupervisorExhausted, semantic errors
  (CapacityOverflow, CheckpointMismatch) pass straight through.

Marked ``fault`` (``make fault-smoke`` runs exactly this suite under
JAX_PLATFORMS=cpu).
"""

import dataclasses
import os

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import checkpoint as ckpt_mod  # noqa: E402
from dslabs_tpu.tpu.engine import CapacityOverflow, TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import make_mesh  # noqa: E402
from dslabs_tpu.tpu.supervisor import (DispatchTimeout, EngineFailure,  # noqa: E402
                                       FaultPlan, RetryPolicy,
                                       SearchSupervisor,
                                       SupervisorExhausted,
                                       TransientDeviceError,
                                       classify_failure, install_retry)

pytestmark = pytest.mark.fault


class FatalError(RuntimeError):
    """An injected NON-transient failure (classified fatal)."""


def _pruned_pingpong():
    pp = make_pingpong_protocol(2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def _pruned_clientserver():
    cs = make_clientserver_protocol(n_clients=1, w=2)
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


def _sup(proto, **kw):
    kw.setdefault("mesh", make_mesh(8))
    kw.setdefault("chunk", 16)
    kw.setdefault("frontier_cap", 1 << 8)
    kw.setdefault("visited_cap", 1 << 10)
    return SearchSupervisor(proto, **kw)


def _same_verdict(a, b):
    assert a.end_condition == b.end_condition
    assert a.unique_states == b.unique_states
    assert a.states_explored == b.states_explored


# ------------------------------------------------------- classification

def test_failure_classification():
    assert classify_failure(TransientDeviceError("x")) == "transient"
    assert classify_failure(DispatchTimeout("x")) == "wedged"
    assert classify_failure(FatalError("x")) == "fatal"

    class XlaRuntimeError(RuntimeError):
        pass

    assert classify_failure(
        XlaRuntimeError("RESOURCE_EXHAUSTED: hbm oom")) == "transient"
    assert classify_failure(XlaRuntimeError("INVALID_ARGUMENT")) == "fatal"


# ------------------------------------------------------ retry-in-place

def test_transient_retry_within_budget_identical_outcome():
    """Two injected transient failures, budget three: the run recovers
    IN PLACE on the sharded rung; verdict and counts match the
    unfaulted run and the retries are visible on the outcome."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    assert base.end_condition == "SPACE_EXHAUSTED"
    out = _sup(proto,
               fault_plan=FaultPlan().raise_at(3, count=2),
               policy=RetryPolicy(max_retries=3,
                                  backoff_base=0.001)).run()
    _same_verdict(out, base)
    assert out.engine == "sharded"
    assert out.retries == 2
    assert out.failovers == 0


def test_retry_budget_is_per_rung():
    """Retries spent on a failed rung do not starve the next rung: each
    engine gets the full budget (the counters are per-engine)."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    plan = (FaultPlan()
            .raise_always(engine="sharded")            # exhausts rung 1
            .raise_at(2, count=1, engine="device"))    # one transient
    out = _sup(proto, fault_plan=plan,
               policy=RetryPolicy(max_retries=2,
                                  backoff_base=0.001)).run()
    _same_verdict(out, base)
    assert out.engine == "device"
    assert out.failovers == 1


# ---------------------------------------------------------- failover

def test_failover_ladder_lab1_strict_verdict_parity():
    """The acceptance path: exhausted retries on the sharded rung, a
    fatal error on the single-device rung — the host loop (the parity
    oracle) lands the IDENTICAL verdict on a lab1 strict BFS."""
    proto = _pruned_clientserver()
    base = _sup(proto, chunk=64, frontier_cap=1 << 9,
                visited_cap=1 << 12).run()
    assert base.end_condition == "SPACE_EXHAUSTED"
    plan = (FaultPlan()
            .raise_always(engine="sharded")
            .raise_always(error=FatalError, engine="device"))
    out = _sup(proto, chunk=64, frontier_cap=1 << 9,
               visited_cap=1 << 12, fault_plan=plan,
               policy=RetryPolicy(max_retries=1,
                                  backoff_base=0.001)).run()
    _same_verdict(out, base)
    assert out.engine == "host"
    assert out.failovers == 2
    assert out.retries >= 1          # the sharded rung did retry first


def test_goal_verdict_survives_failover():
    """Failover preserves TERMINAL verdicts too, not just exhaustion:
    the pingpong goal is found at the same BFS depth on the next rung."""
    proto = make_pingpong_protocol(2)
    base = _sup(proto).run()
    assert base.end_condition == "GOAL_FOUND"
    out = _sup(proto,
               fault_plan=FaultPlan().raise_always(error=FatalError,
                                                   engine="sharded"),
               policy=RetryPolicy(max_retries=0)).run()
    assert out.end_condition == "GOAL_FOUND"
    assert out.predicate_name == base.predicate_name
    assert out.depth == base.depth
    assert out.engine == "device" and out.failovers == 1


def test_all_rungs_fail_is_loud_and_attributable():
    """No silent partial verdict: when every rung fails, the supervisor
    raises SupervisorExhausted carrying the per-rung failure chain."""
    proto = _pruned_pingpong()
    with pytest.raises(SupervisorExhausted) as ei:
        _sup(proto,
             fault_plan=FaultPlan().raise_always(error=FatalError),
             policy=RetryPolicy(max_retries=0)).run()
    assert len(ei.value.failures) == 3
    assert all(isinstance(f, EngineFailure) for f in ei.value.failures)
    assert [f.engine for f in ei.value.failures] == [
        "sharded", "device", "host"]


def test_capacity_overflow_passes_through_unwrapped():
    """Semantic errors must NEVER be absorbed by retry or failover —
    a too-small strict visited table raises CapacityOverflow through
    the boundary unchanged (the capacity ladder owns that failure)."""
    from dslabs_tpu.tpu.visited import BKT

    proto = _pruned_clientserver()
    with pytest.raises(CapacityOverflow):
        _sup(proto, ladder=("device", "host"), chunk=64,
             visited_cap=BKT,
             policy=RetryPolicy(max_retries=3)).run()


# ---------------------------------------------------------- watchdog

def test_hung_dispatch_detected_and_recovered():
    """A dispatch that hangs (injected wedge) is abandoned by the
    wall-clock watchdog at its deadline and the search restarts on the
    next rung — same verdict, failover visible."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    # Hang dispatch 4 of the sharded rung (a warm site — the first
    # dispatch per site gets the compile-inclusive grace deadline).
    out = _sup(proto,
               fault_plan=FaultPlan().hang_at(4, engine="sharded",
                                              secs=60.0),
               policy=RetryPolicy(max_retries=1, backoff_base=0.001,
                                  deadline_secs=1.0,
                                  deadline_first_secs=300.0)).run()
    _same_verdict(out, base)
    assert out.engine == "device"
    assert out.failovers == 1


# ------------------------------------------------- checkpoint + resume

def test_kill_resume_single_device_engine(tmp_path):
    """Kill-and-resume on the single-device device-resident loop: a
    checkpointed run cut at depth 2 resumes to the identical verdict,
    unique count, and explored count as an uninterrupted run."""
    proto = _pruned_pingpong()
    full = TensorSearch(proto, chunk=64).run()
    ckpt = str(tmp_path / "dev.npz")
    cut = TensorSearch(proto, chunk=64, max_depth=2,
                       checkpoint_path=ckpt, checkpoint_every=1)
    assert cut.run().end_condition == "DEPTH_EXHAUSTED"
    assert os.path.exists(ckpt)
    resumed = TensorSearch(proto, chunk=64, checkpoint_path=ckpt)
    r = resumed.run(resume=True)
    _same_verdict(r, full)
    assert resumed._resumed_from_depth == 2


def test_kill_resume_crosses_engines(tmp_path):
    """The unified dump is ENGINE-AGNOSTIC: a checkpoint written by the
    single-device loop resumes on the host loop and vice versa — the
    property supervisor failover depends on."""
    proto = _pruned_pingpong()
    full = TensorSearch(proto, chunk=64).run()
    ckpt = str(tmp_path / "cross.npz")
    TensorSearch(proto, chunk=64, max_depth=2, checkpoint_path=ckpt,
                 checkpoint_every=1).run()
    host = TensorSearch(proto, chunk=64, checkpoint_path=ckpt,
                        use_host_visited=True).run(resume=True)
    _same_verdict(host, full)

    ckpt2 = str(tmp_path / "cross2.npz")
    TensorSearch(proto, chunk=64, max_depth=2, use_host_visited=True,
                 checkpoint_path=ckpt2, checkpoint_every=1).run()
    dev = TensorSearch(proto, chunk=64,
                       checkpoint_path=ckpt2).run(resume=True)
    _same_verdict(dev, full)


def test_failover_resumes_from_checkpoint(tmp_path):
    """A rung killed mid-search (fatal fault after the depth-2 dump):
    the next rung RESUMES from the checkpoint instead of the root and
    reports the resumed depth on the outcome."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    ckpt = str(tmp_path / "fo.npz")
    plan = FaultPlan().raise_at(8, error=FatalError, engine="sharded")
    out = _sup(proto, fault_plan=plan, checkpoint_path=ckpt,
               checkpoint_every=1,
               policy=RetryPolicy(max_retries=0)).run()
    _same_verdict(out, base)
    assert out.engine == "device"
    assert out.failovers == 1
    assert out.resumed_from_depth > 0


def test_checkpoint_mismatch_rejected_loudly(tmp_path):
    """Satellite: a dump from a different protocol/capacity config is
    refused with BOTH fingerprints in the error — never silently
    resumed, never silently ignored."""
    proto = _pruned_pingpong()
    ckpt = str(tmp_path / "mm.npz")
    TensorSearch(proto, chunk=64, max_depth=2, checkpoint_path=ckpt,
                 checkpoint_every=1).run()
    bigger = dataclasses.replace(proto, net_cap=proto.net_cap * 2)
    other = TensorSearch(bigger, chunk=64, checkpoint_path=ckpt)
    assert not other.has_resumable_checkpoint()
    with pytest.raises(ckpt_mod.CheckpointMismatch) as ei:
        other.run(resume=True)
    msg = str(ei.value)
    assert other._ckpt_fingerprint() in msg            # live config
    assert TensorSearch(proto, chunk=64)._ckpt_fingerprint() in msg
    # Differing STRICTNESS is a semantic mismatch too (beam counts may
    # over-report) — also refused.
    beam = TensorSearch(proto, chunk=64, strict=False,
                        checkpoint_path=ckpt)
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        beam.run(resume=True)


def test_supervisor_zero_fault_plan_is_transparent():
    """A supervisor with the default policy and no faults changes
    nothing: same verdict/counts as the bare engine, zero counters
    (the perf-smoke gate rides this same path)."""
    proto = _pruned_pingpong()
    bare = TensorSearch(proto, chunk=64).run()
    out = _sup(proto, ladder=("device",), chunk=64).run()
    _same_verdict(out, bare)
    assert (out.retries, out.failovers, out.resumed_from_depth) == (0, 0, 0)
    assert (out.abandoned_threads, out.child_restarts,
            out.killed_dispatches) == (0, 0, 0)
    assert out.engine == "device"


def test_superstep_transient_fault_retries_in_place():
    """ISSUE 3 satellite: a FaultPlan fault injected INSIDE a superstep
    dispatch retries exactly as the per-chunk dispatches did.  In
    superstep mode the sharded rung's dispatch sequence is
    init, (superstep, promote)*: index 3 IS a superstep dispatch."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    sup = _sup(proto, fault_plan=FaultPlan().raise_at(3, count=2),
               policy=RetryPolicy(max_retries=3, backoff_base=0.001))
    out = sup.run()
    assert sup._engines["sharded"].use_superstep, (
        "test must exercise the fused superstep driver")
    _same_verdict(out, base)
    assert out.engine == "sharded"
    assert out.retries == 2
    assert out.failovers == 0


def test_superstep_fatal_fails_over_and_resumes_checkpoint(tmp_path):
    """ISSUE 3 satellite: a fatal fault inside a superstep dispatch
    fails over down the ladder and the next rung resumes from the
    unified checkpoint at the correct depth — the dispatch-boundary /
    checkpoint contracts survive the superstep refactor unchanged."""
    proto = _pruned_pingpong()
    base = _sup(proto).run()
    ckpt = str(tmp_path / "ss.npz")
    # Dispatch 7 = the level-4 superstep (init + 2/level); checkpoints
    # land after levels 1..3 (async skip-if-busy may skip some, never
    # all — level gaps outlast the tiny dump).
    plan = FaultPlan().raise_at(7, error=FatalError, engine="sharded")
    sup = _sup(proto, fault_plan=plan, checkpoint_path=ckpt,
               checkpoint_every=1, policy=RetryPolicy(max_retries=0))
    out = sup.run()
    assert sup._engines["sharded"].use_superstep
    _same_verdict(out, base)
    assert out.engine == "device"
    assert out.failovers == 1
    assert 0 < out.resumed_from_depth <= 3


def test_superstep_watchdog_deadline_scales_with_trip_count():
    """The watchdog's steady-state deadline stretches by the published
    superstep trip-count scale (a fused level step legitimately runs a
    whole level's chunk work), while other sites keep the single-
    dispatch deadline."""
    from dslabs_tpu.tpu.supervisor import DispatchBoundary

    class Search:
        _dispatch_deadline_scales = {"superstep": 8.0}

    b = DispatchBoundary(RetryPolicy(deadline_secs=2.0))
    b.install(Search())
    assert b._deadline_scale("sharded.superstep") == 8.0
    assert b._deadline_scale("sharded.promote") == 1.0
    bare = DispatchBoundary(RetryPolicy(deadline_secs=2.0))
    assert bare._deadline_scale("sharded.superstep") == 1.0


def test_abandoned_thread_accounting_and_warning():
    """ISSUE 4 satellite: the in-process watchdog can only ABANDON a
    wedged dispatch, leaking a blocked daemon thread.  The boundary
    counts the still-blocked threads (surfaced as
    SearchOutcome.abandoned_threads / bench JSON) and warns past the
    threshold so in-process-mode degradation is visible."""
    import time as _time

    from dslabs_tpu.tpu.supervisor import DispatchBoundary

    b = DispatchBoundary(RetryPolicy(max_retries=0, deadline_secs=0.2,
                                     deadline_first_secs=0.2))

    def _block():
        # A genuinely blocked call (ignores the fault plan's release
        # event) — the wedged-XLA shape the watchdog cannot interrupt.
        _time.sleep(6.0)

    with pytest.raises(EngineFailure):
        b.dispatch("device.step", _block)
    assert b.abandoned_alive() == 1
    assert b.timeouts == 1
    with pytest.warns(RuntimeWarning, match="abandoned"):
        with pytest.raises(EngineFailure):
            b.dispatch("device.step", _block)
    assert b.abandoned_alive() == 2


def test_install_retry_single_engine():
    """install_retry (the backend's light-touch wrapper): transient
    faults retry in place on a bare engine; exhaustion is a loud
    EngineFailure, not a silent fallback."""
    proto = _pruned_pingpong()
    base = TensorSearch(proto, chunk=64).run()
    faulted = TensorSearch(proto, chunk=64)
    boundary = install_retry(
        faulted, RetryPolicy(max_retries=2, backoff_base=0.001),
        FaultPlan().raise_at(2, count=1))
    out = faulted.run()
    _same_verdict(out, base)
    assert boundary.retries == 1

    dead = TensorSearch(proto, chunk=64)
    install_retry(dead, RetryPolicy(max_retries=1, backoff_base=0.001),
                  FaultPlan().raise_always())
    with pytest.raises(EngineFailure):
        dead.run()
