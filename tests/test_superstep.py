"""On-device level supersteps + persistent compile cache (ISSUE 3).

The fused superstep (sharded.py ``_level_superstep``: one shard_map
program whose ``lax.while_loop`` drains every device's own frontier
shard) must match the legacy host-driven per-chunk driver
(``DSLABS_SHARDED_SUPERSTEP=0``, the parity oracle) EXACTLY — end
verdict, unique, explored, depth — while cutting host dispatches per
level from ``n_chunks + 1`` to at most 2 (superstep + promote; the
dispatch-counter tests assert it).  Mid-level time budgets keep their
contract under both drivers: TIME_EXHAUSTED never masks a violation
found in chunks already completed.  The persistent compile cache
(DSLABS_COMPILE_CACHE, tpu/compile_cache.py) plus AOT warm-up makes a
second identical construction's compile near-zero.

The heavier paxos/shardstore parity cases are marked ``perf`` AND
``slow``: ``make perf-smoke`` (-m perf) runs them as the dry-run
8-virtual-device parity gate, while the tier-1 suite (-m 'not slow')
keeps only the cheap pingpong cases.
"""

import dataclasses
import os

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import sharded as sharded_mod  # noqa: E402
from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh  # noqa: E402


def _pruned_pingpong():
    pp = make_pingpong_protocol(workload_size=2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def _run_pair(proto, max_depth=None, **kw):
    """The same config under the fused superstep and the legacy
    per-chunk driver; returns (superstep_outcome, legacy_outcome)."""
    mesh = make_mesh(8)
    kw.setdefault("chunk_per_device", 16)
    kw.setdefault("frontier_cap", 1 << 8)
    kw.setdefault("visited_cap", 1 << 10)
    fused = ShardedTensorSearch(proto, mesh, max_depth=max_depth,
                                superstep=True, **kw).run()
    legacy = ShardedTensorSearch(proto, mesh, max_depth=max_depth,
                                 superstep=False, **kw).run()
    return fused, legacy


def _assert_exact(fused, legacy):
    assert fused.end_condition == legacy.end_condition
    assert fused.unique_states == legacy.unique_states
    assert fused.states_explored == legacy.states_explored
    assert fused.depth == legacy.depth
    assert fused.dropped == legacy.dropped


# ------------------------------------------------------------- parity

@pytest.mark.perf
@pytest.mark.parametrize("strict", [True, False])
def test_superstep_vs_legacy_parity_pingpong(strict):
    fused, legacy = _run_pair(_pruned_pingpong(), strict=strict)
    assert fused.end_condition == "SPACE_EXHAUSTED"
    _assert_exact(fused, legacy)


@pytest.mark.perf
@pytest.mark.slow
def test_superstep_vs_legacy_parity_paxos_d5():
    """The dry-run 8-device paxos rung of the perf-smoke parity gate
    (acceptance: exact verdict/unique/explored match at depth 5)."""
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol

    proto = make_paxos_protocol(n=3, n_clients=1, w=1, max_slots=2,
                                net_cap=16, timer_cap=4)
    fused, legacy = _run_pair(proto, max_depth=5, chunk_per_device=64,
                              frontier_cap=1 << 12,
                              visited_cap=1 << 15)
    assert fused.end_condition == "DEPTH_EXHAUSTED"
    _assert_exact(fused, legacy)


@pytest.mark.perf
@pytest.mark.slow
def test_superstep_vs_legacy_parity_shardstore_d4():
    """Second protocol family (lab 4 shardstore lane layout) through
    the same superstep machinery."""
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_protocol

    proto = make_shardstore_protocol([[1], [2]])
    fused, legacy = _run_pair(proto, max_depth=4, chunk_per_device=64,
                              frontier_cap=1 << 12,
                              visited_cap=1 << 15)
    assert fused.end_condition == "DEPTH_EXHAUSTED"
    _assert_exact(fused, legacy)


def test_superstep_ev_spill_parity():
    """Event-window spill inside the while_loop: a tiny budget re-steps
    spilled chunks (j held back keeps the drain condition true) with
    exact counts."""
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    full = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, superstep=True).run()
    tiny = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, superstep=True, ev_budget=(2, 1),
        ev_spill=True).run()
    _assert_exact(tiny, full)


# ---------------------------------------------------- dispatch counting

def _counted_run(proto, superstep, **kw):
    mesh = make_mesh(8)
    kw.setdefault("chunk_per_device", 16)
    kw.setdefault("frontier_cap", 1 << 8)
    kw.setdefault("visited_cap", 1 << 10)
    search = ShardedTensorSearch(proto, mesh, superstep=superstep, **kw)
    counts = {}

    def hook(tag, fn, *args):
        counts[tag] = counts.get(tag, 0) + 1
        return fn(*args)

    search._dispatch_hook = hook
    return search.run(), counts


def test_superstep_host_dispatches_per_level_at_most_two():
    """The acceptance bound: the superstep driver spends <= 2 host
    dispatches per level (superstep + promote; the stats vector rides
    inside the superstep program) vs the legacy driver's
    n_chunks + sync (+ promote)."""
    proto = _pruned_pingpong()
    out, counts = _counted_run(proto, superstep=True)
    levels = out.depth
    assert levels >= 3
    assert counts.get("sharded.step", 0) == 0
    assert counts.get("sharded.sync", 0) == 0
    assert counts["sharded.superstep"] + counts["sharded.promote"] <= (
        2 * levels)

    legacy_out, legacy_counts = _counted_run(proto, superstep=False)
    _assert_exact(out, legacy_out)
    # The legacy driver pays at least one chunk step AND one sync per
    # level on top of the promote — strictly more host dispatches.
    assert legacy_counts["sharded.step"] >= levels
    assert legacy_counts["sharded.sync"] >= levels
    legacy_total = sum(v for k, v in legacy_counts.items())
    fused_total = sum(v for k, v in counts.items())
    assert fused_total < legacy_total


def test_single_device_mesh_skips_chunk_grid_widening():
    """Satellite: on a 1-device mesh the level rebalance is an identity,
    so the legacy chunk grid must NOT be widened by the
    ``max_n + D - 1`` slack (no extra mostly-invalid chunk)."""
    proto = _pruned_pingpong()
    mesh = make_mesh(1)
    search = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, superstep=False)
    assert search._rebalance_slack() == 0
    counts = {}

    def hook(tag, fn, *args):
        counts[tag] = counts.get(tag, 0) + 1
        return fn(*args)

    search._dispatch_hook = hook
    out = search.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    # Frontiers here never exceed one chunk: exactly one chunk step per
    # level — the pre-fix driver dispatched two whenever
    # max_n % chunk == 0 (the widening added a full invalid chunk).
    assert counts["sharded.step"] == out.depth
    mesh8 = make_mesh(8)
    # The legacy promote-boundary exchange needs the ceil-split slack
    # on a wide mesh; the fused row exchange (ISSUE 12 default) has no
    # rebalance at all, so no slack either.
    assert ShardedTensorSearch(
        proto, mesh8, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, superstep=False)._rebalance_slack() == 7
    assert ShardedTensorSearch(
        proto, mesh8, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, row_exchange=True)._rebalance_slack() == 0


# ------------------------------------------------------- level records

def test_level_records_on_outcome():
    """Satellite: structured per-level throughput records ride the
    outcome (depth/chunks/wall/explored/unique/next_frontier) — the
    bench emits them as its throughput series."""
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    out = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10).run()
    assert out.levels, "SearchOutcome.levels must carry per-level records"
    for i, rec in enumerate(out.levels):
        assert rec["depth"] == i + 1
        for key in ("chunks", "wall", "explored", "unique",
                    "next_frontier"):
            assert key in rec, rec
        assert rec["chunks"] >= 1
    # Cumulative counters are monotone; the final record's totals match
    # the outcome's.
    uniq = [r["unique"] for r in out.levels]
    assert uniq == sorted(uniq)
    assert out.levels[-1]["explored"] == out.states_explored
    assert out.levels[-1]["unique"] == out.unique_states


# ------------------------------------------------- mid-level time budget

class _DispatchClock:
    """Deterministic wall clock for time-budget tests: time() returns
    ``base + n_dispatches * step`` where the dispatch hook advances the
    counter — the budget then expires at an exact, chosen dispatch
    instead of a wall-clock race."""

    def __init__(self, step: float):
        self.base = 1_000_000.0
        self.step = step
        self.dispatches = 0

    def time(self) -> float:
        return self.base + self.dispatches * self.step

    def sleep(self, secs: float) -> None:  # pragma: no cover
        pass


def _violating_clientserver():
    p = make_clientserver_protocol(n_clients=1, w=1)
    done = p.goals["CLIENTS_DONE"]
    return dataclasses.replace(
        p, goals={}, invariants={"NEVER_DONE": lambda s, f=done: ~f(s)})


def _clocked_run(proto, superstep, max_secs, clock, **kw):
    mesh = make_mesh(8)
    kw.setdefault("chunk_per_device", 32)
    kw.setdefault("frontier_cap", 1 << 9)
    kw.setdefault("visited_cap", 1 << 12)
    search = ShardedTensorSearch(proto, mesh, max_secs=max_secs,
                                 superstep=superstep, **kw)

    def hook(tag, fn, *args):
        clock.dispatches += 1
        return fn(*args)

    search._dispatch_hook = hook
    return search.run()


@pytest.mark.parametrize("superstep", [True, False],
                         ids=["superstep", "legacy"])
def test_time_budget_returns_time_exhausted_mid_run(superstep,
                                                    monkeypatch):
    """Satellite: a tiny max_secs returns TIME_EXHAUSTED (with the
    partial counts, never a crash) under BOTH drivers.  The fake clock
    charges one 'second' per dispatch, so the budget expires after the
    first level's work — deterministically."""
    proto = _pruned_pingpong()
    full = _clocked_run(proto, superstep, None, _DispatchClock(0.0))
    assert full.end_condition == "SPACE_EXHAUSTED"

    clock = _DispatchClock(1.0)
    monkeypatch.setattr(sharded_mod, "time", clock)
    out = _clocked_run(proto, superstep, 3.5, clock)
    assert out.end_condition == "TIME_EXHAUSTED"
    assert 0 < out.states_explored < full.states_explored
    assert out.unique_states >= 1


@pytest.mark.parametrize("superstep", [True, False],
                         ids=["superstep", "legacy"])
def test_time_budget_never_masks_violation_in_completed_chunks(
        superstep, monkeypatch):
    """Satellite: a violation found in chunks already completed must be
    reported even when the wall budget is ALREADY exhausted at the
    sync — the checks run before any TIME_EXHAUSTED return.  The fake
    clock makes the budget expire during the violation's own level."""
    proto = _violating_clientserver()
    base = _clocked_run(proto, superstep, None, _DispatchClock(0.0))
    assert base.end_condition == "INVARIANT_VIOLATED"

    # The run takes `total` dispatches, the last being the one whose
    # sync finds the violation.  A budget of total - 0.5 dispatch-
    # "seconds" passes every check BEFORE that dispatch (elapsed <=
    # total - 1) but is exhausted at its sync (elapsed == total) — the
    # violation must still win.
    counting = _DispatchClock(0.0)
    total = _count_dispatches(proto, superstep, counting)
    clock = _DispatchClock(1.0)
    monkeypatch.setattr(sharded_mod, "time", clock)
    out = _clocked_run(proto, superstep, total - 0.5, clock)
    assert out.end_condition == "INVARIANT_VIOLATED", (
        "TIME_EXHAUSTED masked a violation found in completed chunks")
    assert out.predicate_name == base.predicate_name
    assert out.depth == base.depth


def _count_dispatches(proto, superstep, clock):
    mesh = make_mesh(8)
    search = ShardedTensorSearch(proto, mesh, chunk_per_device=32,
                                 frontier_cap=1 << 9,
                                 visited_cap=1 << 12,
                                 superstep=superstep)

    def hook(tag, fn, *args):
        clock.dispatches += 1
        return fn(*args)

    search._dispatch_hook = hook
    search.run()
    return clock.dispatches


# ------------------------------------------- compile cache + AOT warm-up

def test_compile_cache_populates_and_second_aot_is_fast(tmp_path,
                                                        monkeypatch):
    """Acceptance: with DSLABS_COMPILE_CACHE set, the cache dir is
    populated and a second identical construction's recorded compile
    time drops (the AOT .lower().compile() hits the on-disk cache
    instead of XLA)."""
    from dslabs_tpu.tpu import compile_cache

    cache = str(tmp_path / "xla-cache")
    prev = compile_cache.cache_dir()
    monkeypatch.setenv("DSLABS_COMPILE_CACHE", cache)
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    try:
        assert compile_cache.setup() == cache
        cold = ShardedTensorSearch(
            proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
            visited_cap=1 << 10, aot_warmup=True)
        assert cold.compile_secs > 0
        assert os.listdir(cache), "persistent cache dir not populated"
        out = cold.run()
        assert out.end_condition == "SPACE_EXHAUSTED"
        assert out.compile_secs == round(cold.compile_secs, 3)

        warm = ShardedTensorSearch(
            proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
            visited_cap=1 << 10, aot_warmup=True)
        # The XLA-compile half is served from disk; what remains is
        # tracing.  "Near-zero" on the tunnelled TPU runtime; on CPU
        # the margin is smaller, so assert a robust drop.
        assert warm.compile_secs < cold.compile_secs
        out2 = warm.run()
        assert out2.unique_states == out.unique_states
    finally:
        # Restore the session's cache dir — later tests (and their
        # compiles) must not write into this test's tmp dir.
        monkeypatch.delenv("DSLABS_COMPILE_CACHE")
        if prev:
            jax.config.update("jax_compilation_cache_dir", prev)


def test_compile_cache_env_knob_disables(monkeypatch):
    from dslabs_tpu.tpu import compile_cache

    monkeypatch.setenv("DSLABS_COMPILE_CACHE", "0")
    assert compile_cache.setup(default_dir="/tmp/should-not-be-used") is None


def test_checkpoint_default_cache_dir():
    from dslabs_tpu.tpu.checkpoint import default_compile_cache_dir

    assert default_compile_cache_dir(None) is None
    d = default_compile_cache_dir("/tmp/ckpts/search.npz")
    assert d == "/tmp/ckpts/compile_cache"
