"""Lab 1 tests — behavioural port of KVStoreTest, ClientServerPart1Test
(at-most-once server with reliable network) and ClientServerPart2Test
(exactly-once under unreliable delivery + search tests over duplication).
"""

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS, UNRELIABLE_TESTS,
                                lab_test)
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.amo import AMOApplication, AMOCommand
from dslabs_tpu.labs.clientserver.clientserver import SimpleClient, SimpleServer
from dslabs_tpu.labs.clientserver.kv_workload import (
    APPENDS_LINEARIZABLE, append, append_different_key_workload,
    append_result, append_same_key_workload,
    different_keys_infinite_workload, get, kv_workload, put, put_get_workload,
    put_ok, simple_workload)
from dslabs_tpu.labs.clientserver.kvstore import (Append, AppendResult, Get,
                                                  GetResult, KVStore,
                                                  KeyNotFound, Put, PutOk)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs, dfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import (CLIENTS_DONE, NONE_DECIDED,
                                           RESULTS_OK)

SERVER = LocalAddress("server")


# ------------------------------------------------------------- KVStore unit

@lab_test("1", 1, "Basic key-value operations", points=5, part=1, categories=(RUN_TESTS,))
def test_kvstore_semantics():
    kv = KVStore()
    assert kv.execute(Get("k")) == KeyNotFound()
    assert kv.execute(Put("k", "v")) == PutOk()
    assert kv.execute(Get("k")) == GetResult("v")
    assert kv.execute(Append("k", "w")) == AppendResult("vw")
    assert kv.execute(Append("k2", "x")) == AppendResult("x")
    assert kv.execute(Get("k2")) == GetResult("x")


@lab_test("1", 2, "KVStore state equality", part=1, categories=(RUN_TESTS,))
def test_kvstore_equality():
    a, b = KVStore(), KVStore()
    a.execute(Put("k", "v"))
    assert a != b
    b.execute(Put("k", "v"))
    assert a == b and hash(a) == hash(b)


# ----------------------------------------------------------------- AMO unit

@lab_test("1", 6, "AMO application deduplicates", part=2, categories=(RUN_TESTS,))
def test_amo_deduplicates():
    c1 = LocalAddress("c1")
    app = AMOApplication(KVStore())
    r1 = app.execute(AMOCommand(Append("k", "a"), c1, 1))
    assert r1.result == AppendResult("a")
    # Duplicate: same result, NOT re-executed.
    r2 = app.execute(AMOCommand(Append("k", "a"), c1, 1))
    assert r2 == r1
    assert app.application.execute(Get("k")) == GetResult("a")


@lab_test("1", 7, "AMO per-client sequencing", part=2, categories=(RUN_TESTS,))
def test_amo_per_client_sequencing():
    c1, c2 = LocalAddress("c1"), LocalAddress("c2")
    app = AMOApplication(KVStore())
    app.execute(AMOCommand(Append("k", "a"), c1, 1))
    app.execute(AMOCommand(Append("k", "b"), c2, 1))  # distinct client, runs
    assert app.application.execute(Get("k")) == GetResult("ab")
    # Old sequence number from c1 is dropped (returns None).
    assert app.execute(AMOCommand(Append("k", "zzz"), c1, 0)) is None
    assert app.already_executed(AMOCommand(Append("k", "a"), c1, 1))


# ------------------------------------------------------------- run fixtures

def make_run_state(num_clients=1, workload_factory=put_get_workload):
    gen = NodeGenerator(
        server_supplier=lambda a: SimpleServer(a, KVStore()),
        client_supplier=lambda a: SimpleClient(a, SERVER),
        workload_supplier=lambda a: workload_factory())
    state = RunState(gen)
    state.add_server(SERVER)
    for i in range(1, num_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"))
    return state


def assert_ok(state):
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


@lab_test("1", 2, "Single client basic operations", points=20, part=2, categories=(RUN_TESTS,))
def test_single_client_simple_workload():
    state = make_run_state(workload_factory=simple_workload)
    state.run(RunSettings().max_time(10))
    assert_ok(state)


@lab_test("1", 3, "Multi-client different key appends", points=20, part=2, categories=(RUN_TESTS,))
def test_multi_client_different_keys():
    state = make_run_state(
        num_clients=3,
        workload_factory=lambda: append_different_key_workload(4))
    state.run(RunSettings().max_time(10))
    assert_ok(state)


@lab_test("1", 1, "Single client basic operations", points=20, part=3, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test_unreliable_network_exactly_once():
    state = make_run_state(
        num_clients=2,
        workload_factory=lambda: append_different_key_workload(3))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.5)
    state.run(settings)
    assert_ok(state)


@lab_test("1", 4, "Multi-client same key appends", points=30, part=2, categories=(RUN_TESTS,))
def test_same_key_appends_linearizable():
    state = make_run_state(
        num_clients=3,
        workload_factory=lambda: append_same_key_workload(3))
    state.run(RunSettings().max_time(20))
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()


# ---------------------------------------------------------------- search

def make_search_state(num_clients=1, workload=None):
    gen = NodeGenerator(
        server_supplier=lambda a: SimpleServer(a, KVStore()),
        client_supplier=lambda a: SimpleClient(a, SERVER),
        workload_supplier=lambda a: workload or put_get_workload())
    state = SearchState(gen)
    state.add_server(SERVER)
    for i in range(1, num_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"))
    return state


@lab_test("1", 7, "Single client; Put, Append, Get", points=20, part=3, categories=(SEARCH_TESTS,))
def test_search_exactly_once_under_duplication():
    """BFS over the full duplication/reordering space: results always match
    (the AMO layer absorbs duplicate deliveries).  Port of
    ClientServerPart2Test search tests (:175-281)."""
    workload = kv_workload(["APPEND:k:a", "APPEND:k:b"], ["a", "ab"])
    state = make_search_state(workload=workload)
    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_goal(CLIENTS_DONE))
    settings.max_time(30)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND

    # Exhaust the done-pruned subspace: no interleaving violates RESULTS_OK.
    settings2 = (SearchSettings().add_invariant(RESULTS_OK)
                 .add_prune(CLIENTS_DONE))
    settings2.max_time(60)
    results2 = bfs(make_search_state(workload=workload), settings2)
    assert results2.end_condition == EndCondition.SPACE_EXHAUSTED


@lab_test("1", 10, "Multi-client same key", points=20, part=3, categories=(SEARCH_TESTS,))
def test_search_two_clients_linearizable_appends():
    workload = append_same_key_workload(1)
    state = make_search_state(num_clients=2, workload=workload)
    settings = (SearchSettings().add_invariant(APPENDS_LINEARIZABLE)
                .add_goal(CLIENTS_DONE))
    settings.max_time(60)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND


@lab_test("1", 1, "Client throws InterruptedException", points=5, part=2, categories=(RUN_TESTS,))
def test01_throws_exception():
    """ClientServerPart1Test.test01ThrowsException: with the run state
    never started, get_result must block (and time out) rather than
    return."""
    import pytest as _pytest

    state = make_run_state(num_clients=0)
    c = state.add_client(LocalAddress("client1"))
    c.send_command(get("FOO"))
    with _pytest.raises(TimeoutError):
        c.get_result(timeout=0.5)


@lab_test("1", 5, "Single client can finish operations", points=20, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test05_single_client_finishes_unreliable():
    """ClientServerPart1Test.test05: 25 appends complete despite 50% loss."""
    state = make_run_state(
        num_clients=1,
        workload_factory=lambda: append_different_key_workload(25))
    settings = RunSettings().max_time(30)
    settings.network_unreliable(True)
    state.run(settings)
    assert_ok(state)


@lab_test("1", 2, "Single client sequential appends", points=20, part=3, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test02_single_client_appends_unreliable():
    """ClientServerPart2Test.test02: 50 appends at deliver rate 0.8."""
    state = make_run_state(
        num_clients=1,
        workload_factory=lambda: append_different_key_workload(50))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.8)
    state.run(settings)
    assert_ok(state)


@lab_test("1", 3, "Multi-client different key appends", points=20, part=3, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test03_multi_client_different_key_unreliable():
    """ClientServerPart2Test.test03 (scaled 10x100 -> 5x20 for the Python
    runner's wall clock; same shape: many clients, own keys, 0.8)."""
    state = make_run_state(
        num_clients=5,
        workload_factory=lambda: append_different_key_workload(20))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.8)
    state.run(settings)
    assert_ok(state)


@lab_test("1", 4, "Multi-client same key appends", points=20, part=3, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test04_multi_client_same_key_unreliable():
    """ClientServerPart2Test.test04: 10 clients x 5 same-key appends at
    0.8, checked with APPENDS_LINEARIZABLE."""
    state = make_run_state(
        num_clients=10,
        workload_factory=lambda: append_same_key_workload(5))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.8)
    state.run(settings)
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()


@lab_test("1", 5, "Old commands garbage collected", points=20, part=3, categories=(RUN_TESTS,))
def test05_garbage_collection():
    """ClientServerPart2Test.test05GarbageCollection (scaled 1MBx5x3x5 ->
    100KBx3x2x2): server memory returns under the small bound once values
    are overwritten — the AMO result cache must not retain old results."""
    import cloudpickle

    from dslabs_tpu.utils.structural import sfreeze

    value_size, items, iters, num_clients = 100_000, 3, 2, 2
    small_bound = 500_000

    state = make_run_state(num_clients=0)
    clients = [state.add_client(LocalAddress(f"client{c}"))
               for c in range(1, num_clients + 1)]

    def nodes_size():
        # serialized size of the nodes' PUBLIC state (the reference's
        # nodesSize nulls transient fields before serializing,
        # BaseJUnitTest.java:453-467)
        return len(cloudpickle.dumps(sfreeze(
            {**dict(state.servers), **dict(state.clients)})))

    assert nodes_size() < small_bound
    state.start(RunSettings().max_time(120))
    kv = {}
    for _ in range(iters):
        for key in range(items):
            for ci, c in enumerate(clients, start=1):
                k = f"client{ci}-key{key}"
                v = "x" * value_size
                kv[k] = kv.get(k, "") + v
                c.send_command(append(k, v))
                assert c.get_result(timeout=5) == append_result(kv[k])
    assert nodes_size() > value_size * items * num_clients

    for key in range(items):
        for ci, c in enumerate(clients, start=1):
            c.send_command(put(f"client{ci}-key{key}", ""))
            assert c.get_result(timeout=5) == put_ok()
    state.stop()
    final = nodes_size()
    assert final < small_bound, f"{final} bytes retained after overwrite"


@lab_test("1", 6, "Long-running workload", points=20, part=3, categories=(RUN_TESTS,))
def test06_long_running_workload():
    """ClientServerPart2Test.test06 (30s -> 8s): infinite workloads keep
    making progress and no client waits >1s."""
    state = make_run_state(
        num_clients=2,
        workload_factory=lambda: different_keys_infinite_workload())
    state.run(RunSettings().max_time(8))
    assert_ok(state)
    for w in state.client_workers().values():
        mw = w.max_wait(state.stop_time)
        assert mw is not None and mw[0] < 1.0


def _search_state(num_clients=1, workload_factory=None):
    gen = NodeGenerator(
        server_supplier=lambda a: SimpleServer(a, KVStore()),
        client_supplier=lambda a: SimpleClient(a, SERVER),
        workload_supplier=lambda a: (workload_factory()
                                     if workload_factory else None))
    state = SearchState(gen)
    state.add_server(SERVER)
    for i in range(1, num_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"))
    return state


@lab_test("1", 8, "Single client; Append, Append, Get", points=20, part=3, categories=(SEARCH_TESTS,))
def test08_single_client_append_search():
    """ClientServerPart2Test.test08: goal reachable, then pruned space
    exhausts safely."""
    state = _search_state(workload_factory=lambda: kv_workload(
        ["APPEND:foo:x", "APPEND:foo:y", "GET:foo"],
        ["x", "xy", "xy"]))
    settings = SearchSettings().add_invariant(RESULTS_OK)
    settings.add_goal(CLIENTS_DONE).max_time(30)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND

    settings.clear_goals().add_prune(CLIENTS_DONE)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.SPACE_EXHAUSTED


@lab_test("1", 12, "No progress without communication", points=0, part=3, categories=(SEARCH_TESTS,))
def test07b_no_progress_without_network():
    """ClientServerPart2Test.test07 phase 3: with the network off, the
    NONE_DECIDED invariant holds across the whole (exhausted) space."""
    state = _search_state(workload_factory=lambda: kv_workload(
        ["PUT:foo:bar", "APPEND:foo:baz", "GET:foo"],
        ["PutOk", "barbaz", "barbaz"]))
    settings = SearchSettings().add_invariant(NONE_DECIDED)
    settings.network_active(False).max_time(15)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.SPACE_EXHAUSTED


@lab_test("1", 9, "Multi-client different keys", points=20, part=3, categories=(SEARCH_TESTS,))
def test09_multi_client_different_key_search():
    """ClientServerPart2Test.test09 (scaled 3 -> 2 rounds for the Python
    checker): goal + pruned exhaustion with two clients on own keys."""
    state = _search_state(
        num_clients=2,
        workload_factory=lambda: append_different_key_workload(2))
    settings = SearchSettings().add_invariant(RESULTS_OK)
    settings.add_goal(CLIENTS_DONE).max_time(60)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND

    settings.clear_goals().add_prune(CLIENTS_DONE)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.SPACE_EXHAUSTED


@lab_test("1", 11, "Infinite workload searches", points=20, part=3, categories=(SEARCH_TESTS,))
def test11_random_search_infinite_workloads():
    """ClientServerPart2Test.test11: invariant-only BFS under a time
    budget, then randomized DFS probes, then again with a second client."""
    state = _search_state(
        workload_factory=lambda: different_keys_infinite_workload())
    settings = SearchSettings().add_invariant(RESULTS_OK)
    settings.max_time(5)
    results = bfs(state, settings)
    assert results.end_condition in (EndCondition.TIME_EXHAUSTED,
                                     EndCondition.SPACE_EXHAUSTED)

    settings.set_max_depth(1000).max_time(5)
    results = dfs(state, settings)
    assert not results.terminal_found()

    state.add_client_worker(LocalAddress("client2"),
                            different_keys_infinite_workload())
    results = dfs(state, settings)
    assert not results.terminal_found()
