"""Lab 1 tests — behavioural port of KVStoreTest, ClientServerPart1Test
(at-most-once server with reliable network) and ClientServerPart2Test
(exactly-once under unreliable delivery + search tests over duplication).
"""

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS, UNRELIABLE_TESTS,
                                lab_test)
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.amo import AMOApplication, AMOCommand
from dslabs_tpu.labs.clientserver.clientserver import SimpleClient, SimpleServer
from dslabs_tpu.labs.clientserver.kv_workload import (
    APPENDS_LINEARIZABLE, append_different_key_workload,
    append_same_key_workload, kv_workload, put_get_workload, simple_workload)
from dslabs_tpu.labs.clientserver.kvstore import (Append, AppendResult, Get,
                                                  GetResult, KVStore,
                                                  KeyNotFound, Put, PutOk)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import CLIENTS_DONE, RESULTS_OK

SERVER = LocalAddress("server")


# ------------------------------------------------------------- KVStore unit

@lab_test("1", 1, "Basic key-value operations", points=5, part=1, categories=(RUN_TESTS,))
def test_kvstore_semantics():
    kv = KVStore()
    assert kv.execute(Get("k")) == KeyNotFound()
    assert kv.execute(Put("k", "v")) == PutOk()
    assert kv.execute(Get("k")) == GetResult("v")
    assert kv.execute(Append("k", "w")) == AppendResult("vw")
    assert kv.execute(Append("k2", "x")) == AppendResult("x")
    assert kv.execute(Get("k2")) == GetResult("x")


@lab_test("1", 2, "KVStore state equality", part=1, categories=(RUN_TESTS,))
def test_kvstore_equality():
    a, b = KVStore(), KVStore()
    a.execute(Put("k", "v"))
    assert a != b
    b.execute(Put("k", "v"))
    assert a == b and hash(a) == hash(b)


# ----------------------------------------------------------------- AMO unit

@lab_test("1", 6, "AMO application deduplicates", part=2, categories=(RUN_TESTS,))
def test_amo_deduplicates():
    c1 = LocalAddress("c1")
    app = AMOApplication(KVStore())
    r1 = app.execute(AMOCommand(Append("k", "a"), c1, 1))
    assert r1.result == AppendResult("a")
    # Duplicate: same result, NOT re-executed.
    r2 = app.execute(AMOCommand(Append("k", "a"), c1, 1))
    assert r2 == r1
    assert app.application.execute(Get("k")) == GetResult("a")


@lab_test("1", 7, "AMO per-client sequencing", part=2, categories=(RUN_TESTS,))
def test_amo_per_client_sequencing():
    c1, c2 = LocalAddress("c1"), LocalAddress("c2")
    app = AMOApplication(KVStore())
    app.execute(AMOCommand(Append("k", "a"), c1, 1))
    app.execute(AMOCommand(Append("k", "b"), c2, 1))  # distinct client, runs
    assert app.application.execute(Get("k")) == GetResult("ab")
    # Old sequence number from c1 is dropped (returns None).
    assert app.execute(AMOCommand(Append("k", "zzz"), c1, 0)) is None
    assert app.already_executed(AMOCommand(Append("k", "a"), c1, 1))


# ------------------------------------------------------------- run fixtures

def make_run_state(num_clients=1, workload_factory=put_get_workload):
    gen = NodeGenerator(
        server_supplier=lambda a: SimpleServer(a, KVStore()),
        client_supplier=lambda a: SimpleClient(a, SERVER),
        workload_supplier=lambda a: workload_factory())
    state = RunState(gen)
    state.add_server(SERVER)
    for i in range(1, num_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"))
    return state


def assert_ok(state):
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


@lab_test("1", 2, "Single client basic operations", points=20, part=2, categories=(RUN_TESTS,))
def test_single_client_simple_workload():
    state = make_run_state(workload_factory=simple_workload)
    state.run(RunSettings().max_time(10))
    assert_ok(state)


@lab_test("1", 3, "Multi-client different key appends", points=20, part=2, categories=(RUN_TESTS,))
def test_multi_client_different_keys():
    state = make_run_state(
        num_clients=3,
        workload_factory=lambda: append_different_key_workload(4))
    state.run(RunSettings().max_time(10))
    assert_ok(state)


@lab_test("1", 1, "Single client basic operations", points=20, part=3, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test_unreliable_network_exactly_once():
    state = make_run_state(
        num_clients=2,
        workload_factory=lambda: append_different_key_workload(3))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.5)
    state.run(settings)
    assert_ok(state)


@lab_test("1", 4, "Multi-client same key appends", points=30, part=2, categories=(RUN_TESTS,))
def test_same_key_appends_linearizable():
    state = make_run_state(
        num_clients=3,
        workload_factory=lambda: append_same_key_workload(3))
    state.run(RunSettings().max_time(20))
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()


# ---------------------------------------------------------------- search

def make_search_state(num_clients=1, workload=None):
    gen = NodeGenerator(
        server_supplier=lambda a: SimpleServer(a, KVStore()),
        client_supplier=lambda a: SimpleClient(a, SERVER),
        workload_supplier=lambda a: workload or put_get_workload())
    state = SearchState(gen)
    state.add_server(SERVER)
    for i in range(1, num_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"))
    return state


@lab_test("1", 7, "Single client; Put, Append, Get", points=20, part=3, categories=(SEARCH_TESTS,))
def test_search_exactly_once_under_duplication():
    """BFS over the full duplication/reordering space: results always match
    (the AMO layer absorbs duplicate deliveries).  Port of
    ClientServerPart2Test search tests (:175-281)."""
    workload = kv_workload(["APPEND:k:a", "APPEND:k:b"], ["a", "ab"])
    state = make_search_state(workload=workload)
    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_goal(CLIENTS_DONE))
    settings.max_time(30)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND

    # Exhaust the done-pruned subspace: no interleaving violates RESULTS_OK.
    settings2 = (SearchSettings().add_invariant(RESULTS_OK)
                 .add_prune(CLIENTS_DONE))
    settings2.max_time(60)
    results2 = bfs(make_search_state(workload=workload), settings2)
    assert results2.end_condition == EndCondition.SPACE_EXHAUSTED


@lab_test("1", 10, "Multi-client same key", points=20, part=3, categories=(SEARCH_TESTS,))
def test_search_two_clients_linearizable_appends():
    workload = append_same_key_workload(1)
    state = make_search_state(num_clients=2, workload=workload)
    settings = (SearchSettings().add_invariant(APPENDS_LINEARIZABLE)
                .add_goal(CLIENTS_DONE))
    settings.max_time(60)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND
