"""Pallas fingerprint kernel: the interpreter-mode kernel must be
bit-identical to the jnp reference path (they share the engine's mixing
math; this pins the BlockSpec/tiling plumbing)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dslabs_tpu.tpu.engine import row_fingerprints  # noqa: E402
from dslabs_tpu.tpu.kernels import TILE, fingerprint_rows  # noqa: E402


@pytest.mark.parametrize("b,l", [
    (TILE, 64),          # exactly one tile
    (3 * TILE, 257),     # multiple tiles, odd lane count
    (TILE + 7, 33),      # row padding path
    (5, 4),              # tiny batch, pure padding
])
def test_interpret_matches_jnp(b, l):
    rng = np.random.default_rng(b * 1000 + l)
    flat = jnp.asarray(
        rng.integers(-2**31, 2**31, size=(b, l), dtype=np.int64)
        .astype(np.int32))
    ref = np.asarray(row_fingerprints(flat))
    ker = np.asarray(fingerprint_rows(flat, mode="interpret"))
    np.testing.assert_array_equal(ref, ker)
