"""RunSettings probabilistic delivery (runner/run_settings.py): the
rate-resolution priority chain — link > sender > receiver > global —
plus the two unconditional cases (self-sends always deliver; a rate
above 1.0 is the reference's "explicitly reliable" placeholder,
RunSettings.java:126).  Previously untested (ISSUE 2 satellite).

Priority is pinned with degenerate rates (0.0 = never, 1.0/2.0 =
always, no RNG involved); the Bernoulli draw itself is pinned with a
seeded ``random`` so the delivered count for a fixed rate is exact and
reproducible.
"""

import random

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.testing.events import MessageEnvelope

A = LocalAddress("a")
B = LocalAddress("b")
C = LocalAddress("c")


def _env(frm=A, to=B):
    return MessageEnvelope(frm, to, {"m": 1})


def _rate(settings, frm=A, to=B, n=400, seed=12345):
    """Deterministic delivered fraction over n draws (seeded RNG)."""
    random.seed(seed)
    return sum(settings.should_deliver(_env(frm, to))
               for _ in range(n)) / n


def test_self_send_always_delivers():
    """frm == to short-circuits EVERYTHING — even a zero rate at every
    level and a deactivated network (RunSettings.java:41-60)."""
    s = (RunSettings().network_deliver_rate(0.0)
         .link_deliver_rate(A, A, 0.0)
         .sender_deliver_rate(A, 0.0)
         .receiver_deliver_rate(A, 0.0))
    s.partition([])          # connectivity off for everyone
    assert all(s.should_deliver(_env(A, A)) for _ in range(50))


def test_link_rate_beats_sender_receiver_and_global():
    s = (RunSettings().network_deliver_rate(0.0)
         .sender_deliver_rate(A, 0.0)
         .receiver_deliver_rate(B, 0.0)
         .link_deliver_rate(A, B, 1.0))
    assert _rate(s) == 1.0               # link=1 wins over three zeros
    s2 = (RunSettings().network_deliver_rate(1.0)
          .sender_deliver_rate(A, 1.0)
          .receiver_deliver_rate(B, 1.0)
          .link_deliver_rate(A, B, 0.0))
    assert _rate(s2) == 0.0              # link=0 wins over three ones
    # The link override is DIRECTIONAL: b->a is untouched by (a, b).
    assert _rate(s2, frm=B, to=A) == 1.0


def test_sender_rate_beats_receiver_and_global():
    s = (RunSettings().network_deliver_rate(0.0)
         .receiver_deliver_rate(B, 0.0)
         .sender_deliver_rate(A, 1.0))
    assert _rate(s) == 1.0
    s2 = (RunSettings().network_deliver_rate(1.0)
          .receiver_deliver_rate(B, 1.0)
          .sender_deliver_rate(A, 0.0))
    assert _rate(s2) == 0.0
    # A different sender is untouched by a's rate.
    assert _rate(s2, frm=C, to=B) == 1.0


def test_receiver_rate_beats_global():
    s = (RunSettings().network_deliver_rate(0.0)
         .receiver_deliver_rate(B, 1.0))
    assert _rate(s) == 1.0
    s2 = (RunSettings().network_deliver_rate(1.0)
          .receiver_deliver_rate(B, 0.0))
    assert _rate(s2) == 0.0
    assert _rate(s2, frm=A, to=C) == 1.0


def test_explicitly_reliable_placeholder_above_one():
    """link_unreliable(..., False) stores the 2.0 placeholder: it must
    short-circuit the Bernoulli draw entirely (always deliver), while
    still being OVERRIDDEN back to 0.5 by a later unreliable toggle."""
    s = RunSettings().network_deliver_rate(0.0)
    s.link_unreliable(A, B, False)       # stores rate 2.0 on the link
    assert s._link_rate[(A, B)] == 2.0
    assert _rate(s) == 1.0               # >1.0 = reliable, no draw
    s.link_unreliable(A, B, True)        # reliable placeholder -> 0.5
    assert s._link_rate[(A, B)] == 0.5
    # An explicit sub-1.0 rate is NOT clobbered by unreliable(True).
    s2 = RunSettings().link_deliver_rate(A, B, 0.25)
    s2.link_unreliable(A, B, True)
    assert s2._link_rate[(A, B)] == 0.25


def test_seeded_bernoulli_rate_is_deterministic_and_plausible():
    """The global 0.5 rate with a fixed seed: exact reproducibility
    across runs, and the delivered fraction sits near the rate (the
    draw really is rate-driven, not constant)."""
    s = RunSettings().network_unreliable(True)   # global rate 0.5
    assert s._network_rate == 0.5
    r1 = _rate(s, n=1000, seed=7)
    r2 = _rate(s, n=1000, seed=7)
    assert r1 == r2                      # seeded == reproducible
    assert 0.4 < r1 < 0.6
    # Different seed, different sequence (sanity that the seed matters).
    assert _rate(s, n=1000, seed=8) != r1


def test_connectivity_still_gates_before_rates():
    """TestSettings connectivity runs FIRST: a severed link never
    delivers regardless of a 1.0/2.0 rate on the same link."""
    s = RunSettings().link_deliver_rate(A, B, 1.0)
    s.partition([A])                     # only intra-{a} links stay up
    assert not s.should_deliver(_env(A, B))


def test_reset_network_clears_all_rates():
    s = (RunSettings().network_deliver_rate(0.0)
         .link_deliver_rate(A, B, 0.0)
         .sender_deliver_rate(A, 0.0)
         .receiver_deliver_rate(B, 0.0))
    s.reset_network()
    assert _rate(s) == 1.0               # no rates left: always deliver
