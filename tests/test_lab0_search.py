"""Lab 0 search tests — behavioural port of the reference's PingTest search
half (labs/lab0-pingpong/tst/dslabs/pingpong/PingTest.java:125-140): BFS finds
the all-clients-done goal, and the CLIENTS_DONE-pruned subspace is finite and
safe (RESULTS_OK holds everywhere).
"""

from dslabs_tpu.harness import SEARCH_TESTS, lab_test
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingClient, PingServer,
                                               Pong)
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs, dfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import CLIENTS_DONE, RESULTS_OK
from dslabs_tpu.testing.workload import Workload

SERVER = LocalAddress("pingserver")


def ping_parser(cmd, res):
    return Ping(cmd), (Pong(res) if res is not None else None)


def make_state(num_clients=1, num_pings=2):
    gen = NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=[f"ping-%i" for _ in range(num_pings)],
            result_strings=[f"ping-%i" for _ in range(num_pings)],
            parser=ping_parser),
    )
    state = SearchState(gen)
    state.add_server(SERVER)
    for i in range(1, num_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"))
    return state


@lab_test("0", 4, "Single client repeatedly pings", categories=(SEARCH_TESTS,))
def test_bfs_finds_clients_done_goal():
    state = make_state()
    settings = SearchSettings().add_invariant(RESULTS_OK).add_goal(CLIENTS_DONE)
    settings.max_time(30)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND
    goal = results.goal_matching_state
    assert goal is not None
    for w in goal.client_workers().values():
        assert w.done()
        assert w.results == [Pong("ping-1"), Pong("ping-2")]


@lab_test("0", 8, "Pruned ping space exhausts safely", categories=(SEARCH_TESTS,))
def test_bfs_exhausts_pruned_space_safely():
    state = make_state()
    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_prune(CLIENTS_DONE))
    settings.max_time(30)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.SPACE_EXHAUSTED


@lab_test("0", 9, "Random DFS respects depth limit", categories=(SEARCH_TESTS,))
def test_random_dfs_depth_limited():
    state = make_state()
    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .set_max_depth(100))
    settings.max_time(5)
    results = dfs(state, settings)
    # The object RandomDFS restarts probes until the clock runs out; the
    # tensor strategy (dfs -> strict BFS) may instead PROVE the bounded
    # space clean first — a strictly stronger pass.
    assert results.end_condition in (EndCondition.TIME_EXHAUSTED,
                                     EndCondition.SPACE_EXHAUSTED)
    assert results.invariant_violating_state is None


@lab_test("0", 10, "Search-state dedup on generation", categories=(SEARCH_TESTS,))
def test_search_state_dedup():
    """Stepping the same message twice from one state yields equivalent
    states (network-as-set, delivery does not consume)."""
    state = make_state()
    events = state.events()
    assert events, "initial state should have deliverable events"
    e = events[0]
    s1 = state.step_event(e, None, skip_checks=True)
    s2 = state.step_event(e, None, skip_checks=True)
    assert s1.search_equivalence_key() == s2.search_equivalence_key()
    assert s1 == s2
