"""Perf smoke gate (`make perf-smoke`, marker `perf`): a small strict
BFS on the CPU backend must not regress unique-states/min by more than
30% against the committed floor in BASELINE.json.

The floor is deliberately conservative (~half the rate measured on the
1-core reference box at commit time) so OS noise cannot flake the gate,
while a real hot-path regression (the measured round-3 pathologies were
all >2x) still trips it.  Update the floor when a PR lands a real
speedup: `python -m pytest tests/test_perf_smoke.py -s` prints the
measured rate.
"""

import dataclasses
import json
import os
import time

import pytest

pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

with open(os.path.join(ROOT, "BASELINE.json")) as f:
    _PERF = json.load(f)["perf_smoke"]


@pytest.mark.perf
def test_lab1_strict_bfs_states_per_min_floor():
    proto = dataclasses.replace(
        make_clientserver_protocol(**_PERF["protocol_kwargs"]), goals={})
    search = TensorSearch(proto, chunk=_PERF["chunk"],
                          frontier_cap=1 << 17, max_depth=2)
    search.run()                        # warm-up: compile outside the clock
    search.max_depth = _PERF["depth"]
    best = 0.0
    for _ in range(2):                  # best-of-2 absorbs scheduler noise
        t0 = time.time()
        out = search.run()
        best = max(best, out.unique_states / (time.time() - t0) * 60.0)
    assert out.end_condition == "DEPTH_EXHAUSTED"
    assert out.unique_states == _PERF["unique_states"], (
        "state-space drift: the floor was committed for "
        f"{_PERF['unique_states']} unique states, got {out.unique_states}")
    floor = _PERF["floor_states_per_min"]
    print(f"\nperf-smoke: {best:,.0f} unique states/min "
          f"(floor {floor:,.0f}, fail below {0.7 * floor:,.0f})")
    assert best >= 0.7 * floor, (
        f"perf regression: {best:,.0f} states/min is >30% below the "
        f"committed floor {floor:,.0f} (BASELINE.json perf_smoke)")


@pytest.mark.perf
def test_supervised_run_holds_the_same_floor():
    """The SAME gate through the search supervisor with a zero-fault
    plan (ISSUE 2): the dispatch-boundary wrapper must be overhead-free
    enough that the supervised run still clears the committed floor's
    30% margin — robustness is not allowed to tax the hot loop."""
    from dslabs_tpu.tpu.supervisor import SearchSupervisor

    proto = dataclasses.replace(
        make_clientserver_protocol(**_PERF["protocol_kwargs"]), goals={})
    sup = SearchSupervisor(proto, ladder=("device",),
                           chunk=_PERF["chunk"], frontier_cap=1 << 17,
                           max_depth=2)
    sup.run()                           # warm-up: compile off the clock
    sup.max_depth = _PERF["depth"]
    best = 0.0
    for _ in range(2):
        t0 = time.time()
        out = sup.run()
        best = max(best, out.unique_states / (time.time() - t0) * 60.0)
    assert out.end_condition == "DEPTH_EXHAUSTED"
    assert out.unique_states == _PERF["unique_states"]
    assert (out.retries, out.failovers) == (0, 0)
    floor = _PERF["floor_states_per_min"]
    print(f"\nperf-smoke (supervised): {best:,.0f} unique states/min "
          f"(floor {floor:,.0f}, fail below {0.7 * floor:,.0f})")
    assert best >= 0.7 * floor, (
        f"supervisor overhead regression: {best:,.0f} states/min is "
        f">30% below the committed floor {floor:,.0f}")
