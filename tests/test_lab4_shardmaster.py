"""Lab 4 part 1 tests — behavioural port of ShardMasterTest.java:43-372
(pure-Application unit tests, including the determinism check test08)."""

from dslabs_tpu.harness import RUN_TESTS, lab_test
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.shardedstore.shardmaster import (Error, Join, Leave,
                                                      Move, Ok, Query,
                                                      ShardConfig, ShardMaster,
                                                      INITIAL_CONFIG_NUM)
from dslabs_tpu.utils.structural import clone

NUM_SHARDS = 10


def group(i):
    return frozenset(LocalAddress(f"server{j}") for j in range(3 * i - 2, 3 * i + 1))


def full_range(n=NUM_SHARDS):
    return set(range(1, n + 1))


class Harness:

    def __init__(self, num_shards=NUM_SHARDS):
        self.sm = ShardMaster(num_shards)
        self.max_seen = -1
        self.seen = {}

    def execute(self, command):
        return clone(self.sm.execute(command))

    def get_config(self, config_num=-1, check_is_next=False):
        result = self.execute(Query(config_num))
        assert result == self.execute(Query(config_num))
        assert isinstance(result, ShardConfig)
        if config_num >= INITIAL_CONFIG_NUM:
            assert config_num >= result.config_num
        if result.config_num in self.seen:
            assert not check_is_next, "Got an old configuration"
            assert self.seen[result.config_num] == result
        else:
            if check_is_next:
                assert result.config_num == self.max_seen + 1
            self.seen[result.config_num] = result
        self.max_seen = max(self.max_seen, result.config_num)
        return result

    def check_config(self, config, group_ids, num_moved=0, num_shards=NUM_SHARDS):
        sizes = [len(shards) for _, (_, shards) in config.group_info]
        assert max(sizes) - min(sizes) <= 1 + 2 * num_moved
        assert set(config.groups().keys()) == set(group_ids)
        for gid in group_ids:
            assert config.groups()[gid][0] == group(gid)
        seen = set()
        for gid, (_, shards) in config.group_info:
            assert not (seen & shards)
            seen |= shards
        assert seen == full_range(num_shards)

    def check_movement(self, previous, current, num_shards=NUM_SHARDS):
        assert current.config_num == previous.config_num + 1
        p_groups, c_groups = previous.groups(), current.groups()
        num_moved = sum(
            len(p_groups[g][1] - (c_groups[g][1] if g in c_groups else frozenset()))
            for g in p_groups)
        assert abs(len(p_groups) - len(c_groups)) <= 1
        if len(p_groups) < len(c_groups):
            new_g = next(g for g in c_groups if g not in p_groups)
            assert len(c_groups[new_g][1]) == num_moved
            assert num_moved == num_shards // len(c_groups)
        elif len(c_groups) < len(p_groups):
            removed = next(g for g in p_groups if g not in c_groups)
            assert len(p_groups[removed][1]) == num_moved
        else:
            assert num_moved == 1


@lab_test("4", 1, "Commands return OK", points=5, part=1, categories=(RUN_TESTS,))
def test01_commands_return_ok():
    h = Harness()
    assert h.execute(Join(1, group(1))) == Ok()
    assert h.execute(Join(2, group(2))) == Ok()
    config = h.get_config()
    shard = next(iter(config.groups()[1][1]))
    assert h.execute(Move(2, shard)) == Ok()
    assert h.execute(Leave(2)) == Ok()


@lab_test("4", 2, "Initial query returns NO_CONFIG", points=5, part=1, categories=(RUN_TESTS,))
def test02_initial_query_returns_no_config():
    h = Harness()
    assert h.execute(Query(-1)) == Error()


@lab_test("4", 3, "Bad commands return ERROR", points=5, part=1, categories=(RUN_TESTS,))
def test03_commands_return_error():
    h = Harness()
    h.execute(Join(1, group(1)))
    assert h.execute(Join(1, group(1))) == Error()
    assert h.execute(Leave(2)) == Error()
    h.execute(Join(2, group(2)))
    config = h.get_config()
    shard = next(iter(config.groups()[1][1]))
    assert h.execute(Move(1, shard)) == Error()
    assert h.execute(Move(3, shard)) == Error()
    assert h.execute(Move(2, 0)) == Error()
    assert h.execute(Move(2, NUM_SHARDS + 1)) == Error()


@lab_test("4", 4, "Initial config correct", points=5, part=1, categories=(RUN_TESTS,))
def test04_initial_config_correct():
    h = Harness()
    h.execute(Join(1, group(1)))
    received = h.get_config(check_is_next=True)
    assert received == ShardConfig(
        INITIAL_CONFIG_NUM, {1: (group(1), frozenset(full_range()))})


def _basic_join_leave(h):
    h.execute(Join(1, group(1)))
    previous = h.get_config(check_is_next=True)
    h.check_config(previous, [1])

    for gid in (2, 3):
        h.execute(Join(gid, group(gid)))
        nxt = h.get_config(check_is_next=True)
        h.check_config(nxt, list(range(1, gid + 1)))
        h.check_movement(previous, nxt)
        previous = nxt

    for gid in (3, 2):
        h.execute(Leave(gid))
        nxt = h.get_config(check_is_next=True)
        h.check_config(nxt, list(range(1, gid)))
        h.check_movement(previous, nxt)
        previous = nxt


@lab_test("4", 5, "Basic join/leave", points=5, part=1, categories=(RUN_TESTS,))
def test05_basic_join_leave():
    _basic_join_leave(Harness())


@lab_test("4", 6, "Historical queries", points=5, part=1, categories=(RUN_TESTS,))
def test06_historical_queries():
    h = Harness()
    _basic_join_leave(h)
    for i in range(5):
        h.get_config(INITIAL_CONFIG_NUM + i)


@lab_test("4", 7, "Move command", points=5, part=1, categories=(RUN_TESTS,))
def test07_move_shards():
    h = Harness()
    h.execute(Join(1, group(1)))
    h.execute(Join(2, group(2)))
    config = h.get_config()
    group_one = set(config.groups()[1][1])
    assert len(group_one) == 5

    remaining = set(group_one)
    for shard in group_one:
        h.execute(Move(2, shard))
        remaining.discard(shard)
        config = h.get_config(check_is_next=True)
        h.check_config(config, [1, 2],
                       num_moved=len(group_one) - len(remaining))
        assert set(config.groups()[1][1]) == remaining

    h.execute(Join(3, group(3)))
    config = h.get_config(check_is_next=True)
    h.check_config(config, [1, 2, 3])


@lab_test("4", 8, "Application deterministic", points=10, part=1, categories=(RUN_TESTS,))
def test08_determinism():
    reference = None
    for _ in range(10):
        h = Harness(num_shards=100)
        h.execute(Join(1, group(1)))
        h.check_config(h.get_config(), [1], num_shards=100)
        h.execute(Join(2, group(2)))
        h.check_config(h.get_config(), [1, 2], num_shards=100)
        h.execute(Join(3, group(3)))
        h.check_config(h.get_config(), [1, 2, 3], num_shards=100)
        h.execute(Leave(3))
        config = h.get_config()
        h.check_config(config, [1, 2], num_shards=100)
        group_one = sorted(config.groups()[1][1])
        assert len(group_one) == 50
        for j in range(10):
            h.execute(Move(2, group_one[j]))
            config = h.get_config()
            h.check_config(config, [1, 2], num_moved=j + 1, num_shards=100)
        h.execute(Join(3, group(3)))
        final = h.get_config()
        if reference is None:
            reference = final
        else:
            assert final == reference  # the application is deterministic
