"""Lab 2 part 2 tests — behavioural port of PrimaryBackupTest.java:75-905
(run tests: basic ops, backup takeover, failover reads, at-most-once under
loss, all-servers-dead liveness; search tests: single-client BFS with
RESULTS_OK, linearizable appends)."""

import time

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS, UNRELIABLE_TESTS,
                                lab_test)
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import (
    APPENDS_LINEARIZABLE, append_different_key_workload,
    append_same_key_workload, kv_workload, put_get_workload, simple_workload)
from dslabs_tpu.labs.clientserver.kvstore import KVStore
from dslabs_tpu.labs.primarybackup.pb import PBClient, PBServer
from dslabs_tpu.labs.primarybackup.viewserver import (PING_CHECK_MILLIS,
                                                      ViewServer)
from dslabs_tpu.labs.clientserver.kv_workload import get, put, get_result, put_ok
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import ALL_RESULTS_SAME, CLIENTS_DONE, RESULTS_OK

VSA = LocalAddress("viewserver")


def server(i):
    return LocalAddress(f"server{i}")


def client(i):
    return LocalAddress(f"client{i}")


def generator(workload_factory=put_get_workload):
    def server_supplier(a):
        if a == VSA:
            return ViewServer(a)
        return PBServer(a, VSA, KVStore())

    return NodeGenerator(
        server_supplier=server_supplier,
        client_supplier=lambda a: PBClient(a, VSA),
        workload_supplier=lambda a: workload_factory())


def make_run_state(workload_factory=put_get_workload):
    state = RunState(generator(workload_factory))
    state.add_server(VSA)
    return state


def assert_ok(state):
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


def settle(state, settings, secs):
    """Run the live system for a bit so views form / heal."""
    state.start(settings)
    time.sleep(secs)
    state.stop()


# ------------------------------------------------------------------ run tests

@lab_test("2", 2, "Single client, single server, simple operations", points=5, part=2, categories=(RUN_TESTS,))
def test02_basic():
    state = make_run_state(simple_workload)
    state.add_server(server(1))
    state.add_client_worker(client(1))
    state.run(RunSettings().max_time(10))
    assert_ok(state)


@lab_test("2", 4, "Backup is chosen", points=5, part=2, categories=(RUN_TESTS,))
def test04_backup_chosen_and_replicates():
    state = make_run_state(simple_workload)
    settings = RunSettings().max_time(15)
    state.add_server(server(1))
    state.add_server(server(2))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    state.add_client_worker(client(1))
    state.run(settings)
    assert_ok(state)


@lab_test("2", 6, "Backup takes over", points=10, part=2, categories=(RUN_TESTS,))
def test06_backup_takes_over():
    state = make_run_state()
    settings = RunSettings().max_time(15)
    state.add_server(server(1))
    c = state.add_client(client(1))
    state.start(settings)

    c.send_command(put("foo1", "bar1"))
    assert c.get_result(timeout=5) == put_ok()

    state.add_server(server(2))
    # Wait for the backup view to form and sync.
    time.sleep(PING_CHECK_MILLIS * 8 / 1000)

    c.send_command(put("foo2", "bar2"))
    assert c.get_result(timeout=5) == put_ok()

    state.remove_node(server(1))
    c.send_command(get("foo1"))
    assert c.get_result(timeout=5) == get_result("bar1")
    c.send_command(get("foo2"))
    assert c.get_result(timeout=5) == get_result("bar2")
    state.stop()


@lab_test("2", 7, "Kill all servers", points=10, part=2, categories=(RUN_TESTS,))
def test07_kill_all_servers():
    state = make_run_state()
    settings = RunSettings().max_time(15)
    state.add_server(server(1))
    state.add_server(server(2))
    c = state.add_client(client(1))
    state.start(settings)

    c.send_command(put("foo", "bar"))
    assert c.get_result(timeout=5) == put_ok()

    # Kill every server holding state; a fresh server must NOT serve.
    state.stop()
    state.remove_node(server(1))
    state.remove_node(server(2))
    state.add_server(server(3))
    state.start(settings)

    c.send_command(get("foo"))
    time.sleep(PING_CHECK_MILLIS * 4 / 1000)
    assert not c.has_result()
    state.stop()


@lab_test("2", 8, "At-most-once append", points=15, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test08_at_most_once_unreliable():
    state = make_run_state(lambda: append_different_key_workload(10))
    settings = RunSettings().max_time(30)
    state.add_server(server(1))
    state.add_server(server(2))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    state.add_client_worker(client(1))
    settings.network_deliver_rate(0.8).node_unreliable(VSA, False)
    state.run(settings)
    assert_ok(state)


@lab_test("2", 11, "Concurrent appends, same key, fail to backup", points=15, part=2, categories=(RUN_TESTS,))
def test11_concurrent_appends_linearizable_failover():
    state = make_run_state(lambda: append_same_key_workload(5))
    settings = RunSettings().max_time(30)
    state.add_server(server(1))
    state.add_server(server(2))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    for i in range(1, 4):
        state.add_client_worker(client(i))
    state.run(settings)
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()

    for a in list(state.client_workers()):
        state.remove_node(a)
    # Heal, then read from the primary and (after failover) the old backup.
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)

    read = kv_workload(["GET:the-key"])
    state.add_client_worker(LocalAddress("client-readprimary"), read)
    state.run(settings)

    state.remove_node(server(1))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    state.add_client_worker(LocalAddress("client-readbackup"), read)
    settings.add_invariant(ALL_RESULTS_SAME)
    state.run(settings)
    r = ALL_RESULTS_SAME.check(state)
    assert r.value, r.error_message()


# --------------------------------------------------------------- search tests

def make_search_state(workload):
    state = SearchState(generator(lambda: workload))
    state.add_server(VSA)
    return state


@lab_test("2", 16, "Single client, single server", points=15, part=2, categories=(SEARCH_TESTS,))
def test16_single_client_search():
    workload = kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"])
    state = make_search_state(workload)
    state.add_server(server(1))
    state.add_client_worker(client(1))

    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_goal(CLIENTS_DONE))
    settings.max_time(60)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results

    # The done-pruned subspace never violates RESULTS_OK.
    settings2 = (SearchSettings().add_invariant(RESULTS_OK)
                 .add_prune(CLIENTS_DONE))
    settings2.max_time(60).set_max_depth(22)
    results2 = bfs(make_search_state(workload), settings2)
    assert results2.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                      EndCondition.TIME_EXHAUSTED), results2


@lab_test("2", 18, "Multi-client, multi-server; writes visible", points=20, part=2, categories=(SEARCH_TESTS,))
def test18_two_client_appends_linearizable_search():
    """Staged search in the reference's initView style
    (PrimaryBackupTest.java:124-187): first reach the synced two-server view
    with the clients gated off, then search client completion with the ping
    machinery frozen (settings gate events, never mutate states — SURVEY
    §7.7)."""
    from dslabs_tpu.testing.predicates import StatePredicate

    workload = append_same_key_workload(1)
    state = make_search_state(workload)
    state.add_server(server(1))
    state.add_server(server(2))
    state.add_client_worker(client(1))
    state.add_client_worker(client(2))

    def view2_synced(s):
        s1, s2 = s.node(server(1)), s.node(server(2))
        return (s1.view is not None and s1.view.view_num == 2
                and s1.view.primary == server(1) and s1.view.backup == server(2)
                and s1.synced and s2.view is not None
                and s2.view.view_num == 2 and s2.synced)

    stage1 = (SearchSettings()
              .add_goal(StatePredicate("view 2 formed and synced", view2_synced)))
    stage1.max_time(60)
    stage1.sender_active(client(1), False).sender_active(client(2), False)
    stage1.deliver_timers(client(1), False).deliver_timers(client(2), False)
    results = bfs(state, stage1)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    synced_state = results.goal_matching_state

    stage2 = (SearchSettings().add_invariant(APPENDS_LINEARIZABLE)
              .add_goal(CLIENTS_DONE))
    stage2.max_time(120)
    stage2.deliver_timers(VSA, False)
    stage2.deliver_timers(server(1), False).deliver_timers(server(2), False)
    results = bfs(synced_state, stage2)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
