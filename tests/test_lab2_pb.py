"""Lab 2 part 2 tests — behavioural port of PrimaryBackupTest.java:75-905
(run tests: basic ops, backup takeover, failover reads, at-most-once under
loss, all-servers-dead liveness; search tests: single-client BFS with
RESULTS_OK, linearizable appends)."""

import time

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS, UNRELIABLE_TESTS,
                                lab_test)
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import (
    APPENDS_LINEARIZABLE, append_different_key_workload,
    append_same_key_workload, kv_workload, put_get_workload, simple_workload)
from dslabs_tpu.labs.clientserver.kvstore import KVStore
from dslabs_tpu.labs.primarybackup.pb import PBClient, PBServer
from dslabs_tpu.labs.primarybackup.pb import PING_MILLIS
from dslabs_tpu.labs.primarybackup.viewserver import (PING_CHECK_MILLIS,
                                                      ViewServer)
from dslabs_tpu.labs.clientserver.kv_workload import (
    different_keys_infinite_workload, get, put, get_result, put_ok)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs, dfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import ALL_RESULTS_SAME, CLIENTS_DONE, RESULTS_OK

VSA = LocalAddress("viewserver")


def server(i):
    return LocalAddress(f"server{i}")


def client(i):
    return LocalAddress(f"client{i}")


def generator(workload_factory=put_get_workload):
    def server_supplier(a):
        if a == VSA:
            return ViewServer(a)
        return PBServer(a, VSA, KVStore())

    return NodeGenerator(
        server_supplier=server_supplier,
        client_supplier=lambda a: PBClient(a, VSA),
        workload_supplier=lambda a: workload_factory())


def make_run_state(workload_factory=put_get_workload):
    state = RunState(generator(workload_factory))
    state.add_server(VSA)
    return state


def assert_ok(state):
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


def settle(state, settings, secs):
    """Run the live system for a bit so views form / heal."""
    state.start(settings)
    time.sleep(secs)
    state.stop()


# ------------------------------------------------------------------ run tests

@lab_test("2", 2, "Single client, single server, simple operations", points=5, part=2, categories=(RUN_TESTS,))
def test02_basic():
    state = make_run_state(simple_workload)
    state.add_server(server(1))
    state.add_client_worker(client(1))
    state.run(RunSettings().max_time(10))
    assert_ok(state)


@lab_test("2", 4, "Backup is chosen", points=5, part=2, categories=(RUN_TESTS,))
def test04_backup_chosen_and_replicates():
    state = make_run_state(simple_workload)
    settings = RunSettings().max_time(15)
    state.add_server(server(1))
    state.add_server(server(2))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    state.add_client_worker(client(1))
    state.run(settings)
    assert_ok(state)


@lab_test("2", 6, "Backup takes over", points=10, part=2, categories=(RUN_TESTS,))
def test06_backup_takes_over():
    state = make_run_state()
    settings = RunSettings().max_time(15)
    state.add_server(server(1))
    c = state.add_client(client(1))
    state.start(settings)

    c.send_command(put("foo1", "bar1"))
    assert c.get_result(timeout=5) == put_ok()

    state.add_server(server(2))
    # Wait for the backup view to form and sync.
    time.sleep(PING_CHECK_MILLIS * 8 / 1000)

    c.send_command(put("foo2", "bar2"))
    assert c.get_result(timeout=5) == put_ok()

    state.remove_node(server(1))
    c.send_command(get("foo1"))
    assert c.get_result(timeout=5) == get_result("bar1")
    c.send_command(get("foo2"))
    assert c.get_result(timeout=5) == get_result("bar2")
    state.stop()


@lab_test("2", 7, "Kill all servers", points=10, part=2, categories=(RUN_TESTS,))
def test07_kill_all_servers():
    state = make_run_state()
    settings = RunSettings().max_time(15)
    state.add_server(server(1))
    state.add_server(server(2))
    c = state.add_client(client(1))
    state.start(settings)

    c.send_command(put("foo", "bar"))
    assert c.get_result(timeout=5) == put_ok()

    # Kill every server holding state; a fresh server must NOT serve.
    state.stop()
    state.remove_node(server(1))
    state.remove_node(server(2))
    state.add_server(server(3))
    state.start(settings)

    c.send_command(get("foo"))
    time.sleep(PING_CHECK_MILLIS * 4 / 1000)
    assert not c.has_result()
    state.stop()


@lab_test("2", 8, "At-most-once append", points=15, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test08_at_most_once_unreliable():
    state = make_run_state(lambda: append_different_key_workload(10))
    settings = RunSettings().max_time(30)
    state.add_server(server(1))
    state.add_server(server(2))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    state.add_client_worker(client(1))
    settings.network_deliver_rate(0.8).node_unreliable(VSA, False)
    state.run(settings)
    assert_ok(state)


@lab_test("2", 11, "Concurrent appends, same key, fail to backup", points=15, part=2, categories=(RUN_TESTS,))
def test11_concurrent_appends_linearizable_failover():
    state = make_run_state(lambda: append_same_key_workload(5))
    settings = RunSettings().max_time(30)
    state.add_server(server(1))
    state.add_server(server(2))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    for i in range(1, 4):
        state.add_client_worker(client(i))
    state.run(settings)
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()

    for a in list(state.client_workers()):
        state.remove_node(a)
    # Heal, then read from the primary and (after failover) the old backup.
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)

    read = kv_workload(["GET:the-key"])
    state.add_client_worker(LocalAddress("client-readprimary"), read)
    state.run(settings)

    state.remove_node(server(1))
    settle(state, settings, PING_CHECK_MILLIS * 6 / 1000)
    state.add_client_worker(LocalAddress("client-readbackup"), read)
    settings.add_invariant(ALL_RESULTS_SAME)
    state.run(settings)
    r = ALL_RESULTS_SAME.check(state)
    assert r.value, r.error_message()


# --------------------------------------------------------------- search tests

def make_search_state(workload):
    state = SearchState(generator(lambda: workload))
    state.add_server(VSA)
    return state


@lab_test("2", 16, "Single client, single server", points=15, part=2, categories=(SEARCH_TESTS,))
def test16_single_client_search():
    workload = kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"])
    state = make_search_state(workload)
    state.add_server(server(1))
    state.add_client_worker(client(1))

    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_goal(CLIENTS_DONE))
    settings.max_time(60)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results

    # The done-pruned subspace never violates RESULTS_OK.  (The state
    # is rebuilt with the SAME topology — an earlier port slip searched
    # a ViewServer-only state here, which exhausts vacuously.)
    settings2 = (SearchSettings().add_invariant(RESULTS_OK)
                 .add_prune(CLIENTS_DONE))
    settings2.max_time(60).set_max_depth(22)
    state2 = make_search_state(workload)
    state2.add_server(server(1))
    state2.add_client_worker(client(1))
    results2 = bfs(state2, settings2)
    assert results2.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                      EndCondition.TIME_EXHAUSTED), results2


@lab_test("2", 18, "Multi-client, multi-server; writes visible", points=20, part=2, categories=(SEARCH_TESTS,))
def test18_two_client_appends_linearizable_search():
    """Staged search in the reference's initView style
    (PrimaryBackupTest.java:124-187): first reach the synced two-server view
    with the clients gated off, then search client completion with the ping
    machinery frozen (settings gate events, never mutate states — SURVEY
    §7.7)."""
    from dslabs_tpu.testing.predicates import StatePredicate

    workload = append_same_key_workload(1)
    state = make_search_state(workload)
    state.add_server(server(1))
    state.add_server(server(2))
    state.add_client_worker(client(1))
    state.add_client_worker(client(2))

    def view2_synced(s):
        s1, s2 = s.node(server(1)), s.node(server(2))
        return (s1.view is not None and s1.view.view_num == 2
                and s1.view.primary == server(1) and s1.view.backup == server(2)
                and s1.synced and s2.view is not None
                and s2.view.view_num == 2 and s2.synced)

    stage1 = (SearchSettings()
              .add_goal(StatePredicate("view 2 formed and synced", view2_synced,
                                       tkey=("PB_VIEW_SYNCED", 2,
                                             "server1", "server2"))))
    stage1.max_time(60)
    stage1.sender_active(client(1), False).sender_active(client(2), False)
    stage1.deliver_timers(client(1), False).deliver_timers(client(2), False)
    results = bfs(state, stage1)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    synced_state = results.goal_matching_state

    stage2 = (SearchSettings().add_invariant(APPENDS_LINEARIZABLE)
              .add_goal(CLIENTS_DONE))
    stage2.max_time(120)
    stage2.deliver_timers(VSA, False)
    stage2.deliver_timers(server(1), False).deliver_timers(server(2), False)
    results = bfs(synced_state, stage2)
    assert results.end_condition == EndCondition.GOAL_FOUND, results


# ------------------------------------------------ additional reference ports

def current_view(state):
    return state.servers[VSA].view


def wait_for_view(state, primary, backup, ticks=8):
    """waitForView (PrimaryBackupTest.java:233-247): poll until the
    expected (primary, backup) view is active."""
    for _ in range(ticks):
        v = current_view(state)
        if v.primary == primary and v.backup == backup:
            return v
        time.sleep(PING_CHECK_MILLIS / 1000)
    v = current_view(state)
    assert v.primary == primary and v.backup == backup, \
        f"expected ({primary},{backup}), got {v}"
    return v


def setup_run_view(state, settings, primary, backup):
    """setupRunView (PrimaryBackupTest.java:249-264)."""
    state.start(settings)
    state.add_server(primary)
    wait_for_view(state, primary, None)
    if backup is not None:
        state.add_server(backup)
        wait_for_view(state, primary, backup)
        time.sleep(PING_CHECK_MILLIS * 4 / 1000)  # let the backup sync
    state.stop()


@lab_test("2", 1, "Client throws InterruptedException", points=5, part=2, categories=(RUN_TESTS,))
def test01_throws_exception():
    state = make_run_state()
    c = state.add_client(client(1))
    c.send_command(get("foo"))
    with pytest.raises(TimeoutError):
        c.get_result(timeout=0.5)


@lab_test("2", 3, "Primary chosen", points=5, part=2, categories=(RUN_TESTS,))
def test03_primary_chosen():
    state = make_run_state()
    settings = RunSettings().max_time(10)
    setup_run_view(state, settings, server(1), None)


@lab_test("2", 5, "Count number of ViewServer requests", points=10, part=2, categories=(RUN_TESTS,))
def test05_max_viewserver_pings_count():
    """test05MaxViewServerPingsCount (scaled 500 -> 60 rounds): servers may
    not spam the ViewServer beyond the ping-interval budget."""
    state = make_run_state()
    settings = RunSettings().max_time(60)
    state.add_server(server(1))
    state.add_server(server(2))
    c = state.add_client(client(1))
    state.start(settings)

    t1 = time.time()
    for i in range(60):
        c.send_command(put(f"xk{i}", str(i)))
        assert c.get_result(timeout=5) == put_ok()
        c.send_command(get(f"xk{i}"))
        assert c.get_result(timeout=5) == get_result(str(i))
        time.sleep(PING_MILLIS / 10 / 1000)
    elapsed_ms = (time.time() - t1) * 1000
    state.stop()

    received = state.network.num_messages_received(VSA)
    # numNodes x 2 pings per PING_MILLIS (PrimaryBackupTest.java:341)
    allowed = elapsed_ms / PING_MILLIS * (len(state.servers)
                                          + len(state.clients)) * 2
    assert received <= allowed, \
        f"Too many ViewServer messages: {received} (allowed {allowed:.0f})"


@lab_test("2", 9, "Fail to new backup", points=10, part=2, categories=(RUN_TESTS,))
def test09_fail_put():
    """test09FailPut: acknowledged writes survive a backup death, a
    promotion to a fresh backup, and then a primary death."""
    state = make_run_state()
    settings = RunSettings().max_time(30)
    setup_run_view(state, settings, server(1), server(2))
    state.add_server(server(3))
    c = state.add_client(client(1))
    state.start(settings)

    for k, v in (("a", "aa"), ("b", "bb"), ("c", "cc")):
        c.send_command(put(k, v))
        assert c.get_result(timeout=5) == put_ok()
        c.send_command(get(k))
        assert c.get_result(timeout=5) == get_result(v)

    state.remove_node(server(2))
    c.send_command(put("a", "aaa"))
    assert c.get_result(timeout=5) == put_ok()
    c.send_command(get("a"))
    assert c.get_result(timeout=5) == get_result("aaa")
    wait_for_view(state, server(1), server(3))
    time.sleep(PING_CHECK_MILLIS * 4 / 1000)
    c.send_command(get("a"))
    assert c.get_result(timeout=5) == get_result("aaa")

    state.remove_node(server(1))
    c.send_command(put("b", "bbb"))
    assert c.get_result(timeout=10) == put_ok()
    wait_for_view(state, server(3), None)
    for k, v in (("a", "aaa"), ("b", "bbb"), ("c", "cc")):
        c.send_command(get(k))
        assert c.get_result(timeout=5) == get_result(v)
    state.stop()


def _concurrent_fail_to_backup(workload_factory, read_cmds, deliver_rate=None):
    """Shared body of test10/test11 (PrimaryBackupTest.java:455-563): run
    concurrent writers, heal, read from the primary, kill it, read from the
    promoted backup — both reads must agree (ALL_RESULTS_SAME)."""
    state = make_run_state(workload_factory)
    settings = RunSettings().max_time(60)
    if deliver_rate is not None:
        settings.network_deliver_rate(deliver_rate)
    setup_run_view(state, settings, server(1), server(2))
    for i in range(1, 4):
        state.add_client_worker(client(i))
    state.run(settings)

    for a in list(state.client_workers()):
        state.remove_node(a)

    # Heal fully, then read the keys from the primary.
    settings.reset_network()
    state.start(settings)
    time.sleep(PING_CHECK_MILLIS * 4 / 1000)
    state.stop()

    state.add_client_worker(LocalAddress("client-readprimary"),
                            kv_workload(read_cmds))
    state.run(settings)

    state.remove_node(server(1))
    state.start(settings)
    wait_for_view(state, server(2), None)
    state.stop()

    state.add_client_worker(LocalAddress("client-readbackup"),
                            kv_workload(read_cmds))
    state.run(settings)
    r = ALL_RESULTS_SAME.check(state)
    assert r.value, r.error_message()


@lab_test("2", 10, "Concurrent puts, same keys, fail to backup", points=15, part=2, categories=(RUN_TESTS,))
def test10_concurrent_put():
    import random as _random

    rng = _random.Random(7)

    def puts():
        return kv_workload([f"PUT:k{rng.randrange(2)}:{rng.randrange(1000)}"
                            for _ in range(30)])

    _concurrent_fail_to_backup(puts, ["GET:k0", "GET:k1"])


@lab_test("2", 21, "Concurrent appends failover read-back (extended)", points=0, part=2, categories=(RUN_TESTS,))
def test11b_concurrent_append_fail_to_backup():
    _concurrent_fail_to_backup(lambda: append_same_key_workload(20),
                               ["GET:the-key"])


@lab_test("2", 12, "Concurrent puts, same keys, fail to backup", points=20, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test12_concurrent_put_unreliable():
    import random as _random

    rng = _random.Random(11)

    def puts():
        return kv_workload([f"PUT:k{rng.randrange(2)}:{rng.randrange(1000)}"
                            for _ in range(15)])

    _concurrent_fail_to_backup(puts, ["GET:k0", "GET:k1"], deliver_rate=0.8)


@lab_test("2", 13, "Concurrent appends, same key, fail to backup", points=20, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test13_concurrent_append_unreliable():
    _concurrent_fail_to_backup(lambda: append_same_key_workload(10),
                               ["GET:the-key"], deliver_rate=0.8)


def _repeated_crashes(deliver_rate=None, length_secs=10):
    """test14/test15 (PrimaryBackupTest.java:565-635, scaled 30s -> 10s):
    randomly crash a server and add a fresh one while infinite-workload
    clients keep running."""
    import random as _random
    import threading

    state = make_run_state(lambda: different_keys_infinite_workload(10))
    settings = RunSettings().max_time(length_secs + 30)
    if deliver_rate is not None:
        settings.network_deliver_rate(deliver_rate)
        settings.node_unreliable(VSA, False)
    servers = [server(i) for i in range(1, 4)]
    for a in servers:
        state.add_server(a)
    state.start(settings)
    stop = threading.Event()
    total = [3]

    def crasher():
        rng = _random.Random(5)
        stop.wait(PING_CHECK_MILLIS * 10 / 1000)
        while not stop.is_set():
            to_kill = servers[rng.randrange(len(servers))]
            total[0] += 1
            to_add = server(total[0])
            servers.append(to_add)
            state.add_server(to_add)
            servers.remove(to_kill)
            state.remove_node(to_kill)
            if stop.wait(PING_CHECK_MILLIS * 10 / 1000):
                return

    th = threading.Thread(target=crasher, daemon=True)
    th.start()
    for i in range(1, 4):
        state.add_client_worker(client(i))
    time.sleep(length_secs)
    stop.set()
    th.join(5)
    state.stop()
    assert_ok(state)
    for w in state.client_workers().values():
        mw = w.max_wait(state.stop_time)
        assert mw is not None and mw[0] < 5.0, f"max wait {mw}"


@lab_test("2", 14, "Repeated crashes", points=15, part=2, categories=(RUN_TESTS,))
def test14_repeated_crashes():
    _repeated_crashes()


@lab_test("2", 15, "Repeated crashes", points=20, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test15_repeated_crashes_unreliable():
    _repeated_crashes(deliver_rate=0.8)


@lab_test("2", 17, "Single client, multi-server", points=15, part=2, categories=(SEARCH_TESTS,))
def test17_single_client_multi_server_search():
    """test17SingleClientMultiServerSearch: from the synced two-server
    view, the client can finish, and the done-pruned subspace stays clean
    (third server gated off, as the reference does)."""
    workload = kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"])
    state = make_search_state(workload)
    for i in (1, 2, 3):
        state.add_server(server(i))
    state.add_client_worker(client(1))

    def view2_synced(s):
        s1, s2 = s.node(server(1)), s.node(server(2))
        return (s1.view is not None and s1.view.view_num == 2
                and s1.view.primary == server(1)
                and s1.view.backup == server(2)
                and s1.synced and s2.view is not None
                and s2.view.view_num == 2 and s2.synced)

    from dslabs_tpu.testing.predicates import StatePredicate

    init_settings = SearchSettings().max_time(60)
    init_settings.node_active(client(1), False)
    init_settings.node_active(server(3), False)
    init_settings.deliver_timers(client(1), False)
    init_settings.deliver_timers(server(3), False)
    init_settings.add_goal(StatePredicate(
        "view 2 synced", view2_synced,
        tkey=("PB_VIEW_SYNCED", 2, "server1", "server2")))
    results = bfs(state, init_settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    view_ready = results.goal_matching_state

    settings = SearchSettings().max_time(120)
    settings.add_invariant(RESULTS_OK).add_goal(CLIENTS_DONE)
    settings.node_active(server(3), False)
    settings.deliver_timers(server(3), False)
    # Freeze the ping machinery so the search explores the replication
    # protocol, not the view-change interleavings (the reference prunes
    # later views the same way, PrimaryBackupTest.java:688-696).
    settings.deliver_timers(VSA, False)
    settings.deliver_timers(server(1), False)
    settings.deliver_timers(server(2), False)
    results = bfs(view_ready, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results

    settings.clear_goals().add_prune(CLIENTS_DONE)
    settings.set_max_depth(view_ready.depth + 6)
    results = bfs(view_ready, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results


@lab_test("2", 19, "Multi-client, multi-server; multiple failures to backup", points=20, part=2, categories=(SEARCH_TESTS,))
def test19_multiple_failures_search():
    """test19MultipleFailuresSearch (simplified): from the synced view, an
    acknowledged write must remain visible after the primary fails and the
    backup serves alone — searched over the narrowed failover space."""
    from dslabs_tpu.testing.predicates import StatePredicate

    workload = kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"])
    state = make_search_state(workload)
    state.add_server(server(1))
    state.add_server(server(2))
    state.add_client_worker(client(1))

    def view2_synced(s):
        s1, s2 = s.node(server(1)), s.node(server(2))
        return (s1.view is not None and s1.view.view_num == 2
                and s1.view.primary == server(1)
                and s1.view.backup == server(2)
                and s1.synced and s2.view is not None
                and s2.view.view_num == 2 and s2.synced
                # the ViewServer must have the view ACKED, or it can
                # never change views again (viewserver.py:125-126)
                and s.node(VSA).acked)

    init_settings = SearchSettings().max_time(60)
    init_settings.node_active(client(1), False)
    init_settings.deliver_timers(client(1), False)
    init_settings.add_goal(StatePredicate(
        "view 2 synced", view2_synced,
        tkey=("PB_VIEW_SYNCED", 2, "server1", "server2", "acked")))
    results = bfs(state, init_settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    view_ready = results.goal_matching_state

    # Find a state where the first write is acknowledged.
    from dslabs_tpu.testing.predicates import client_has_results

    s2 = SearchSettings().max_time(120)
    s2.add_invariant(RESULTS_OK)
    s2.deliver_timers(VSA, False)
    s2.deliver_timers(server(1), False).deliver_timers(server(2), False)
    s2.add_goal(client_has_results(client(1), 1))
    results = bfs(view_ready, s2)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    acked = results.goal_matching_state

    # Primary partitioned away.  Stage the failover the way the
    # reference's initView does (PrimaryBackupTest.java:124-187): first
    # reach the promoted view with the client gated off, then let the
    # client finish with the ping machinery frozen.
    acked.drop_pending_messages()

    def promoted(s):
        s2n = s.node(server(2))
        return (s2n.view is not None and s2n.view.primary == server(2)
                and s2n.view.backup is None and s2n.synced)

    s3 = SearchSettings().max_time(180)
    s3.add_invariant(RESULTS_OK)
    s3.partition(VSA, server(2), client(1))
    s3.node_active(client(1), False).deliver_timers(client(1), False)
    s3.deliver_timers(server(1), False)   # dead primary's timers are noise
    s3.set_max_depth(acked.depth + 10)    # promotion takes ~8 events
    s3.add_goal(StatePredicate("backup promoted", promoted,
                               tkey=("PB_PROMOTED", "server2")))
    results = bfs(acked, s3)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    failed_over = results.goal_matching_state

    s4 = SearchSettings().max_time(120)
    s4.add_invariant(RESULTS_OK).add_goal(CLIENTS_DONE)
    s4.partition(VSA, server(2), client(1))
    s4.deliver_timers(VSA, False).deliver_timers(server(2), False)
    results = bfs(failed_over, s4)
    assert results.end_condition == EndCondition.GOAL_FOUND, results


@lab_test("2", 20, "Multi-client, multi-server random depth-first search", points=20, part=2, categories=(SEARCH_TESTS,))
def test20_random_search():
    state = make_search_state(append_same_key_workload(1))
    state.add_server(server(1))
    state.add_server(server(2))
    state.add_client_worker(client(1))
    state.add_client_worker(client(2))

    settings = SearchSettings()
    settings.set_max_depth(1000).max_time(8)
    settings.add_invariant(APPENDS_LINEARIZABLE)
    settings.add_prune(CLIENTS_DONE)
    results = dfs(state, settings)
    assert not results.terminal_found()
