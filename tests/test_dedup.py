"""The shared device-resident visited table (dslabs_tpu/tpu/visited.py)
and the single-device device-resident wave loop built on it (ISSUE 1):

* collision/eviction unit tests with crafted keys sharing one bucket;
* the overflow contract — a full table treats unresolved keys as FRESH
  (sound, may re-explore; never a silent drop) behind a visible flag,
  in the module, the single-device engine, and the sharded engine;
* dedup parity — the device-table loop must produce the IDENTICAL
  unique-state set and final verdict as the legacy host ``sorted_member``
  loop (``run_host``, the parity oracle) on lab0 pingpong and lab1
  clientserver;
* the transfer contract — per-wave device->host transfers in the device
  loop are scalars only (no [N, 4] fingerprint pulls), counted through
  the ``engine.device_get`` instrumented wrapper.
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import dslabs_tpu.tpu.engine as engine  # noqa: E402
from dslabs_tpu.tpu import visited as visited_mod  # noqa: E402
from dslabs_tpu.tpu.engine import CapacityOverflow, TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402

BKT = visited_mod.BKT


def _keys_in_bucket(n, cap, bucket=3, seed=0):
    """Craft n distinct keys whose home bucket (lane 2 & (cap/BKT - 1))
    is ``bucket`` — bucket-collision fodder for the probe loop."""
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 2 ** 32, size=(n, 4), dtype=np.uint64).astype(
        np.uint32)
    vb = cap // BKT
    keys[:, 2] = (keys[:, 2] & ~np.uint32(vb - 1)) | np.uint32(bucket)
    # Distinctness: lane 3 is a counter, so no two crafted keys collide.
    keys[:, 3] = np.arange(n, dtype=np.uint32)
    return jnp.asarray(keys)


def test_bucket_collision_spills_to_probe_chain():
    """More same-bucket keys than one bucket holds: the overflow walks
    the double-hash chain, every key inserts exactly once, and a second
    insert of the same batch resolves all of them as known."""
    cap = 1 << 10
    keys = _keys_in_bucket(BKT + 5, cap)
    valid = jnp.ones((keys.shape[0],), bool)
    table, ins, unres = visited_mod.insert(
        visited_mod.empty_table(cap), keys, valid)
    assert int(ins.sum()) == keys.shape[0]
    assert int(unres.sum()) == 0
    # Home bucket completely full, spill landed elsewhere.
    home = np.asarray(table)[3 * BKT:(3 + 1) * BKT]
    assert (home != np.uint32(0xFFFFFFFF)).any(axis=1).all()
    _, ins2, unres2 = visited_mod.insert(table, keys, valid)
    assert int(ins2.sum()) == 0 and int(unres2.sum()) == 0


def test_in_batch_duplicates_insert_once():
    cap = 1 << 10
    base = _keys_in_bucket(4, cap, seed=1)
    dup = jnp.concatenate([base, base, base])
    valid = jnp.ones((dup.shape[0],), bool)
    table, ins, unres = visited_mod.insert(
        visited_mod.empty_table(cap), dup, valid)
    assert int(ins.sum()) == 4          # one copy of each distinct key
    assert int(unres.sum()) == 0
    occupied = (np.asarray(table)[:cap] != np.uint32(0xFFFFFFFF)).any(axis=1)
    assert int(occupied.sum()) == 4


def test_full_table_overflow_is_visible_and_fresh():
    """The overflow contract at module level: with every slot taken, new
    keys come back UNRESOLVED (visible flag) — candidates for sound
    re-exploration, never silently swallowed as 'seen'."""
    cap = BKT                           # one bucket = the whole table
    fill = _keys_in_bucket(BKT, cap, bucket=0, seed=2)
    table, ins, unres = visited_mod.insert(
        visited_mod.empty_table(cap), fill, jnp.ones((BKT,), bool))
    assert int(ins.sum()) == BKT and int(unres.sum()) == 0
    more = _keys_in_bucket(3, cap, bucket=0, seed=3)
    more = more.at[:, 3].add(1000)      # distinct from the fill batch
    table, ins2, unres2 = visited_mod.insert(
        table, more, jnp.ones((3,), bool))
    assert int(ins2.sum()) == 0
    assert int(unres2.sum()) == 3       # all flagged, none dropped
    # Known keys still resolve as known even when the table is full.
    _, ins3, unres3 = visited_mod.insert(
        table, fill, jnp.ones((BKT,), bool))
    assert int(ins3.sum()) == 0 and int(unres3.sum()) == 0


def _pruned_pingpong(w=2):
    pp = make_pingpong_protocol(w)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def _pruned_clientserver(nc=2, w=1):
    cs = make_clientserver_protocol(n_clients=nc, w=w)
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


def _table_key_set(search):
    """Extract the device table's occupied keys as a set of
    (h1, h2) uint64 pairs (the host oracle's key format)."""
    table = np.asarray(search._last_dev_carry["visited"],
                       dtype=np.uint64)[:-1]
    occ = (table != np.uint64(0xFFFFFFFF)).any(axis=1)
    rows = table[occ]
    h1 = (rows[:, 0] << np.uint64(32)) | rows[:, 1]
    h2 = (rows[:, 2] << np.uint64(32)) | rows[:, 3]
    return set(zip(h1.tolist(), h2.tolist()))


@pytest.mark.parametrize("proto,chunk", [
    (_pruned_pingpong(), 64),
    (_pruned_clientserver(), 128),
], ids=["lab0-pingpong", "lab1-clientserver"])
def test_device_table_matches_host_oracle(proto, chunk):
    """Verdict + unique COUNT + unique SET parity: the device-table loop
    against the legacy host sorted_member loop on the same protocol."""
    dev = TensorSearch(proto, chunk=chunk)
    d = dev.run()
    host = TensorSearch(proto, chunk=chunk)
    h = host.run_host()
    assert d.end_condition == h.end_condition == "SPACE_EXHAUSTED"
    assert d.unique_states == h.unique_states
    assert d.states_explored == h.states_explored
    assert d.visited_overflow == 0
    host_set = set(zip(host._host_visited[0].tolist(),
                       host._host_visited[1].tolist()))
    assert _table_key_set(dev) == host_set


@pytest.mark.parametrize("depth", [2, 4])
def test_device_table_depth_limited_parity(depth):
    proto = _pruned_clientserver()
    d = TensorSearch(proto, chunk=128, max_depth=depth).run()
    h = TensorSearch(proto, chunk=128, max_depth=depth).run_host()
    assert d.end_condition == h.end_condition == "DEPTH_EXHAUSTED"
    assert d.unique_states == h.unique_states
    assert d.states_explored == h.states_explored


def test_goal_verdict_parity_device_vs_host():
    pp = make_pingpong_protocol(2)
    d = TensorSearch(pp, chunk=64).run()
    h = TensorSearch(pp, chunk=64).run_host()
    assert d.end_condition == h.end_condition == "GOAL_FOUND"
    assert d.predicate_name == h.predicate_name
    assert d.depth == h.depth           # BFS shortest goal depth


def test_engine_strict_raises_on_table_full():
    """Single-device strict engine: a too-small table is a LOUD
    CapacityOverflow (exact unique counts cannot survive
    treat-as-fresh), never a silent drop or hang."""
    proto = _pruned_clientserver()
    with pytest.raises(CapacityOverflow):
        TensorSearch(proto, chunk=64, visited_cap=BKT).run()


def test_engine_beam_degrades_treat_as_fresh():
    """strict=False + a full table: the search still terminates (depth
    bound), reports a nonzero visited_overflow, and explores at LEAST
    the true space (re-exploration is sound; dropping would undercount)."""
    proto = _pruned_clientserver()
    exact = TensorSearch(proto, chunk=64, max_depth=4).run()
    tiny = TensorSearch(proto, chunk=64, max_depth=4, visited_cap=BKT,
                        strict=False).run()
    assert tiny.end_condition == "DEPTH_EXHAUSTED"
    assert tiny.visited_overflow > 0
    assert tiny.states_explored >= exact.states_explored


def test_sharded_beam_degrades_treat_as_fresh():
    """The same contract on the sharded engine (strict=False): overflow
    visible via SearchOutcome.visited_overflow, search sound.  The
    visited_cap is PER DEVICE (8 owner shards), so the space must be
    deep/wide enough that some owner's BKT-slot table fills AND then
    receives a further key — lab1 c3-w2 at depth 5 (83 unique states,
    ~10 per owner) is the smallest config that reliably does."""
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    proto = _pruned_clientserver(nc=3, w=2)
    mesh = make_mesh(8)
    exact = ShardedTensorSearch(
        proto, mesh, chunk_per_device=64, frontier_cap=1 << 10,
        visited_cap=1 << 12, strict=False, max_depth=5).run()
    assert exact.visited_overflow == 0
    tiny = ShardedTensorSearch(
        proto, mesh, chunk_per_device=64, frontier_cap=1 << 10,
        visited_cap=BKT, strict=False, max_depth=5).run()
    assert tiny.end_condition == "DEPTH_EXHAUSTED"
    assert tiny.visited_overflow > 0
    assert tiny.states_explored >= exact.states_explored


def test_sharded_strict_raises_on_table_full():
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    proto = _pruned_clientserver(nc=3, w=2)
    mesh = make_mesh(8)
    with pytest.raises(CapacityOverflow):
        ShardedTensorSearch(
            proto, mesh, chunk_per_device=64, frontier_cap=1 << 10,
            visited_cap=BKT, strict=True).run()


def test_device_loop_transfers_scalars_only(monkeypatch):
    """The acceptance contract: per-wave device->host transfers in the
    device-resident run() are scalars/short stat vectors — no [N, 4]
    fingerprint pulls, no state-row pulls.  Counted via the
    engine.device_get instrumented wrapper."""
    sizes = []
    real = engine.device_get

    def spy(x):
        arr = real(x)
        sizes.append(arr.size)
        return arr

    monkeypatch.setattr(engine, "device_get", spy)
    proto = _pruned_clientserver()
    search = TensorSearch(proto, chunk=128)
    out = search.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert sizes, "device loop must route readbacks through device_get"
    stats_len = 7 + len(search._flag_names)
    assert max(sizes) <= stats_len, (
        f"a non-scalar readback leaked into the wave loop: {sizes}")
    # One stats vector per wave (+ spill re-syncs, none here): bounded by
    # the level count, nothing per-chunk or per-state.
    assert len(sizes) <= out.depth + 2
