"""Checkable fault scenarios (ISSUE 19, ``make scenario-smoke``).

The fault plane's contract, tested bottom-up:

* **fault-free parity / overhead guard**: a spec with ``fault=None``
  AND a spec with a zero-budget :class:`FaultModel` both produce the
  verdict/explored/unique of the plain spec on BOTH engines — the
  fault lanes are pure declaration until an era/crash/drop budget is
  actually spent;
* **acceptance workloads**: paxos partition-then-heal explores every
  interleaving of CUT/HEAL with protocol events and proves the quorum
  invariant (exact pinned counts); the broken-quorum variant yields an
  INVARIANT_VIOLATED witness whose decoded trace NAMES the heal event;
  the crash/restart primary-backup spec wipes volatile fields to their
  inits and keeps durable ones;
* **carrier parity**: because fault state is ordinary bounded node
  lanes, bit-packing, symmetry canonicalization, the spill tier, and
  checkpoint/resume (including SIGKILL-mid-scenario) carry it with
  exact verdict parity, and a fault-model mismatch between dump and
  resume is refused loudly (the fault signature is part of the
  checkpoint fingerprint);
* **hygiene**: structural misdeclarations (split symmetry groups,
  unknown kinds/fields, negative budgets) raise SpecError at the
  compile gate, and conformance rule C6 flags handlers that read or
  branch on the ``$fault`` controller's internals;
* **chaos bridge**: the seeded engine-chaos soak runs a partitioned
  scenario job with exact verdict parity (model faults and engine
  faults compose).

docs/scenarios.md is the field guide.
"""

import dataclasses
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from dslabs_tpu.analysis.conformance import lint_source
from dslabs_tpu.tpu import checkpoint as ckpt_mod
from dslabs_tpu.tpu.compiler import SpecError
from dslabs_tpu.tpu.engine import TensorSearch, flatten_state
from dslabs_tpu.tpu.faults import Crash, FaultModel, Partition
from dslabs_tpu.tpu.specs import (paxos_partition_spec, paxos_spec,
                                  pb_crash_spec)
from dslabs_tpu.tpu.trace import decode_trace, replay_on_object

pytestmark = pytest.mark.scenario

# Small-knob config shared by every search here so the suite reuses a
# handful of XLA programs (same discipline as LAB1_KW in test_chaos).
KW = dict(chunk=64, frontier_cap=1 << 13, visited_cap=1 << 16)

# Pinned ground truth, established by exhaustive runs on both engines:
# plain 3-acceptor paxos (goal moved to prune) and its one-era
# proposer/acceptor partition variant.
PLAIN = dict(end="SPACE_EXHAUSTED", explored=1548, unique=202, depth=11)
PART = dict(end="SPACE_EXHAUSTED", explored=3416, unique=564, depth=13,
            partition_events=320)


def _pruned(p):
    """Move goals to prunes: run the full space, keep invariants."""
    return dataclasses.replace(p, goals={}, prunes=dict(p.goals),
                               invariants=dict(p.invariants))


def _plain_paxos():
    return _pruned(paxos_spec(3).compile())


def _part_paxos():
    return _pruned(paxos_partition_spec(3).compile())


def _assert_exact(a, b):
    assert a.end_condition == b.end_condition, (a, b)
    assert a.unique_states == b.unique_states, (a, b)
    assert a.states_explored == b.states_explored, (a, b)
    assert a.depth == b.depth, (a, b)


@pytest.fixture(scope="module")
def plain_base():
    out = TensorSearch(_plain_paxos(), **KW).run()
    assert out.end_condition == PLAIN["end"]
    return out


@pytest.fixture(scope="module")
def part_base():
    out = TensorSearch(_part_paxos(), **KW).run()
    assert out.end_condition == PART["end"]
    return out


# ------------------------------------------- fault-free parity guard

def test_zero_budget_fault_model_is_parity_oracle(plain_base):
    """OVERHEAD GUARD: a declared-but-zero-budget fault model adds
    lanes and zero valid fault events — verdict, explored, and unique
    are EQUAL to the plain spec on both engines, and every fault
    counter stays zero."""
    fm0 = FaultModel(partition=Partition(
        blocks=(("proposer",), ("acceptor",)), max_eras=0))
    proto = _pruned(paxos_spec(3, fault=fm0).compile())
    for host in (False, True):
        out = TensorSearch(proto, use_host_visited=host, **KW).run()
        _assert_exact(plain_base, out)
        assert out.fault_events == 0
        assert out.partition_events == 0
        assert out.crash_events == 0
        assert out.drop_events == 0
        assert out.dup_events == 0


def test_plain_paxos_pins(plain_base):
    """The oracle itself is pinned — if the base model drifts, every
    parity assertion in this file is re-baselined consciously."""
    assert plain_base.end_condition == PLAIN["end"]
    assert plain_base.states_explored == PLAIN["explored"]
    assert plain_base.unique_states == PLAIN["unique"]
    assert plain_base.depth == PLAIN["depth"]
    # fault=None lowers with no fault plumbing at all.
    assert plain_base.fault_events == 0


def test_fault_controller_is_hidden_last_node():
    """The ``$fault`` controller is appended LAST (user node indices
    are stable) and the partition-only event segment is CUT+HEAL."""
    spec = paxos_spec(3, fault=FaultModel(partition=Partition(
        blocks=(("proposer",), ("acceptor",)))))
    proto = spec.compile()
    assert spec.nodes[-1].name == "$fault"
    assert proto.fault is not None
    assert proto.fault.n_events == 2
    assert proto.fault.event_label(0) == "CUT"
    assert proto.fault.event_label(1) == "HEAL"
    # Plain spec carries no descriptor at all (byte-identity gate).
    assert _plain_paxos().fault is None


# ------------------------------------------------ acceptance: paxos

def test_paxos_partition_safety_exact(part_base):
    """ACCEPTANCE: one proposer/acceptor partition era over 3-acceptor
    paxos — the full interleaving space of CUT/HEAL with protocol
    events is explored (pinned counts), the quorum invariant HOLDS,
    and the device and host engines agree exactly, fault counters
    included."""
    assert part_base.end_condition == PART["end"]
    assert part_base.states_explored == PART["explored"]
    assert part_base.unique_states == PART["unique"]
    assert part_base.depth == PART["depth"]
    assert part_base.partition_events == PART["partition_events"]
    assert part_base.fault_events == PART["partition_events"]
    host = TensorSearch(_part_paxos(), use_host_visited=True,
                        **KW).run()
    _assert_exact(part_base, host)
    assert host.partition_events == PART["partition_events"]


def test_broken_quorum_witness_names_the_partition_event():
    """ACCEPTANCE: quorum=1 + initial_cut makes deciding without a
    majority reachable only after the heal — the search returns an
    INVARIANT_VIOLATED witness whose decoded trace contains the HEAL
    fault record, replay-verified step by step in tensor space."""
    proto = paxos_partition_spec(3, broken=True).compile()
    search = TensorSearch(proto, record_trace=True, **KW)
    out = search.run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.predicate_name == "DECIDE_HAS_QUORUM"
    assert out.depth == 5
    # decode_trace replays every event through _step_one and asserts
    # per-step deliverability — reaching the end IS the verification.
    records = decode_trace(search, out)
    assert len(records) == out.depth
    labels = [a[0] for k, a in records if k == "fault"]
    assert labels == ["HEAL"]
    assert records[0][0] == "fault"
    assert all(k == "message" for k, _ in records[1:])
    # The object twin has no fault controller: scenario witnesses are
    # tensor-replay only, refused loudly (not silently skipped).
    search.p = dataclasses.replace(
        search.p, decode_message=lambda rec: None,
        decode_timer=lambda node, rec: None)
    with pytest.raises(NotImplementedError, match="fault event"):
        replay_on_object(search, out, None)


# --------------------------------------- acceptance: crash / restart

def test_pb_crash_volatile_wiped_durable_kept():
    """ACCEPTANCE: a CRASH event resets every volatile lane of the
    crashed node to its declared init and leaves the durable (``amo``)
    lanes untouched — checked directly on ``_step_one`` against a
    deliberately dirtied row."""
    import jax
    import jax.numpy as jnp

    proto = pb_crash_spec().compile()
    search = TensorSearch(proto, chunk=256, frontier_cap=1 << 15,
                          visited_cap=1 << 18, max_depth=6)
    fl = proto.fault
    assert fl.n_crashable > 0
    row = np.asarray(flatten_state(
        jax.tree.map(jnp.asarray, search.initial_state())))[0]
    nodes0 = np.asarray(search._slice_state(row)["nodes"]).copy()
    k = 0
    wipe = np.asarray(fl.wipe[k])
    keep = ~wipe
    assert wipe.any() and keep.any()
    dirty = nodes0.copy()
    dirty[wipe] = 7
    row2 = row.copy()
    row2[:dirty.shape[0]] = dirty
    tgrid = proto.n_nodes * proto.timer_cap
    ev = proto.net_cap + tgrid + fl.seg_crash + k
    succ, ok, _ = jax.jit(search._step_one)(
        jnp.asarray(row2), jnp.asarray(ev))
    assert bool(ok), "CRASH event not deliverable from the dirty state"
    succ = np.asarray(succ)[:dirty.shape[0]]
    init = np.asarray(fl.init_vec)
    # Exact successor: volatile lanes back to init, the controller's
    # down flag raised and crash counter bumped, EVERYTHING else —
    # durable lanes included — untouched.
    n = int(fl.crash_nodes[k])
    expected = dirty.copy()
    expected[wipe] = init[wipe]
    expected[int(fl.down_off[n])] = 1
    expected[fl.crashes_off] = dirty[fl.crashes_off] + 1
    assert (succ[wipe] == init[wipe]).all(), "volatile lanes not wiped"
    assert (succ == expected).all(), "durable lanes touched"
    # And the whole crash/restart interleaving space runs: counters
    # move, verdict reached.
    out = search.run()
    assert out.crash_events > 0
    assert out.fault_events >= out.crash_events


# ------------------------------------- carriers: pack/symmetry/spill

@pytest.mark.slow
def test_fault_lanes_survive_packing_symmetry_and_spill(part_base):
    """Fault lanes are ordinary bounded node lanes: the bit-packed
    frontier encoding and the host-RAM spill tier reproduce the
    partition scenario EXACTLY (verdict, counts, fault counters), and
    symmetry canonicalization keeps the verdict while never splitting
    the partition blocks (host/device agree on the reduced space)."""
    packed = TensorSearch(_part_paxos(), packed=True, **KW).run()
    _assert_exact(part_base, packed)
    assert packed.partition_events == PART["partition_events"]

    # visited_cap 256 << 564 unique forces tier eviction, while one
    # 32-row chunk's unique successors still fit an empty table.
    spilled = TensorSearch(_part_paxos(), spill=True,
                           chunk=32, frontier_cap=1 << 13,
                           visited_cap=1 << 8).run()
    _assert_exact(part_base, spilled)
    assert spilled.dropped_states == 0

    sym_dev = TensorSearch(_part_paxos(), symmetry=True, **KW).run()
    sym_host = TensorSearch(_part_paxos(), symmetry=True,
                            use_host_visited=True, **KW).run()
    _assert_exact(sym_dev, sym_host)
    assert sym_dev.end_condition == PART["end"]
    assert 0 < sym_dev.unique_states <= part_base.unique_states


# -------------------------------------------- carriers: checkpoints

def test_checkpoint_resume_mid_scenario_parity(part_base, tmp_path):
    """A partition-scenario run checkpointed per level resumes from a
    depth-6 partial dump to the identical verdict and exact counts
    (in-process half of the kill/resume contract)."""
    pth = str(tmp_path / "part.ckpt")
    partial = TensorSearch(_part_paxos(), max_depth=6,
                           checkpoint_path=pth, checkpoint_every=1,
                           **KW).run()
    assert partial.end_condition == "DEPTH_EXHAUSTED"
    out = TensorSearch(_part_paxos(), checkpoint_path=pth,
                       checkpoint_every=1, **KW).run(resume=True)
    _assert_exact(part_base, out)


def test_checkpoint_refuses_fault_model_mismatch(tmp_path):
    """The fault signature is part of the checkpoint fingerprint: a
    dump written WITHOUT a fault model is refused by the partition
    scenario (and vice versa) with a loud CheckpointMismatch — never
    resumed silently."""
    pth = str(tmp_path / "plain.ckpt")
    TensorSearch(_plain_paxos(), max_depth=4, checkpoint_path=pth,
                 checkpoint_every=1, **KW).run()
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        TensorSearch(_part_paxos(), checkpoint_path=pth,
                     checkpoint_every=1, **KW).run(resume=True)
    pth2 = str(tmp_path / "part.ckpt")
    TensorSearch(_part_paxos(), max_depth=4, checkpoint_path=pth2,
                 checkpoint_every=1, **KW).run()
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        TensorSearch(_plain_paxos(), checkpoint_path=pth2,
                     checkpoint_every=1, **KW).run(resume=True)


@pytest.mark.slow
def test_sigkill_mid_scenario_resume_parity(part_base, tmp_path):
    """ACCEPTANCE: the partition scenario SIGKILLed mid-search (dumps
    on disk) resumes from the checkpoint to the identical verdict and
    exact counts."""
    pth = str(tmp_path / "kill.ckpt")
    child_src = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_compilation_cache_dir',"
        " '/tmp/jaxcache-cpu')\n"
        "import dataclasses\n"
        "from dslabs_tpu.tpu.engine import TensorSearch\n"
        "from dslabs_tpu.tpu.specs import paxos_partition_spec\n"
        "p = paxos_partition_spec(3).compile()\n"
        "p = dataclasses.replace(p, goals={},"
        " prunes=dict(p.goals), invariants=dict(p.invariants))\n"
        f"TensorSearch(p, chunk=64, frontier_cap={1 << 13},"
        f" visited_cap={1 << 16}, checkpoint_path={pth!r},"
        " checkpoint_every=1).run()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DSLABS_COMPILE_CACHE="/tmp/jaxcache-cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            d = ckpt_mod.peek_depth(pth)
            if d is not None and d >= 6:
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert ckpt_mod.peek_depth(pth) is not None
    out = TensorSearch(_part_paxos(), checkpoint_path=pth,
                       checkpoint_every=1, **KW).run(resume=True)
    _assert_exact(part_base, out)


# --------------------------------------------- compile-gate hygiene

def test_fault_model_structural_red_fixtures():
    """Misdeclared fault models die at the compile gate with
    structured SpecErrors — unknown kinds/fields, split symmetry
    groups, and nonsense budgets never reach the engine."""
    with pytest.raises(SpecError, match="unknown node kind"):
        paxos_spec(3, fault=FaultModel(partition=Partition(
            blocks=(("proposer",), ("nonesuch",))))).compile()
    with pytest.raises(SpecError, match="symmetry group"):
        paxos_spec(3, fault=FaultModel(partition=Partition(
            blocks=((("acceptor", 0),), (("acceptor", 1),
                                         ("acceptor", 2)))))).compile()
    with pytest.raises(SpecError, match="initial_cut"):
        paxos_spec(3, fault=FaultModel(partition=Partition(
            blocks=(("proposer",), ("acceptor",)),
            max_eras=0, initial_cut=True))).compile()
    with pytest.raises(SpecError, match="not declared"):
        paxos_spec(3, fault=FaultModel(crash=Crash(
            durable={"acceptor": ("nonesuch",)}))).compile()
    with pytest.raises(SpecError, match=">= 2 blocks"):
        paxos_spec(3, fault=FaultModel(partition=Partition(
            blocks=(("acceptor",),)))).compile()


# ------------------------------------------- conformance: C6 fixtures

def test_c6_handler_reading_fault_internals_flagged():
    src = textwrap.dedent("""
        class FooNode(Node):
            def handle_Req(self, message, sender):
                if self.view.get("pcut", 0):          # finding
                    return
                down = self.view.get_at("down_server", 0)  # finding
                kind = "$fault"                        # finding
                self.state.put("drops", 1)             # finding
    """)
    c6 = [f for f in lint_source(src, "fixture.py") if f.code == "C6"]
    assert len(c6) == 4
    msgs = " ".join(f.message for f in c6)
    assert "pcut" in msgs and "down_server" in msgs
    assert "$fault" in msgs and "drops" in msgs
    assert all(f.leg == "conformance" for f in c6)


def test_c6_clean_handler_no_findings():
    """Protocol-owned fields that merely resemble nothing of the
    controller's stay clean — C6 keys on the reserved names only."""
    src = textwrap.dedent("""
        class FooNode(Node):
            def handle_Req(self, message, sender):
                amo = self.state.get("amo", 0)
                seq = self.state.get_at("seq", 1)
                self.state.put("dec", 1)
    """)
    assert [f for f in lint_source(src, "fixture.py")
            if f.code == "C6"] == []


# -------------------------------------------------- telemetry wiring

def test_fault_counters_reach_telemetry_and_status(tmp_path):
    """The schema-pinned ``faults`` block flows end to end: outcome
    counters -> telemetry record -> STATUS.json -> report renderer."""
    from dslabs_tpu.tpu.telemetry import (Telemetry, build_report,
                                          render_report)

    flight = str(tmp_path / "flight.jsonl")
    tel = Telemetry(flight_log=flight)
    out = TensorSearch(_part_paxos(), telemetry=tel, **KW).run()
    assert out.partition_events == PART["partition_events"]
    st = tel._status
    assert st.get("faults") is not None
    assert st["faults"]["partition_events"] == PART["partition_events"]
    assert st["faults"]["fault_events"] == PART["partition_events"]
    for key in ("partition_events", "crash_events", "drop_events",
                "dup_events", "fault_events"):
        assert key in st["faults"]
    import json
    with open(flight) as f:
        records = [json.loads(line) for line in f if line.strip()]
    report = build_report(records)
    assert report["faults"]["partition_events"] == \
        PART["partition_events"]
    assert "faults:" in render_report(report)


def test_scenarios_verdict_parity_ledger_guard():
    """``telemetry compare`` treats ``scenarios.verdict_parity`` as a
    BINARY guard: a latest run with parity 0 is a regression
    regardless of the rate threshold; parity 1 never flags."""
    from dslabs_tpu.tpu.telemetry import compare_ledger

    def run(parity):
        return {"t": "bench", "value": 1.0,
                "scenarios": {"value": 100.0,
                              "verdict_parity": parity}}

    ok = compare_ledger([run(1), run(1)])
    assert ok["scenarios"]["verdict_parity"]["latest"] == 1
    assert not any(e["phase"] == "scenarios:verdict_parity"
                   for e in ok["regressions"])
    bad = compare_ledger([run(1), run(0)])
    assert any(e["phase"] == "scenarios:verdict_parity"
               for e in bad["regressions"])


def test_fault_counters_in_warden_scalar_fields():
    """The supervisor's merged-outcome accounting carries the fault
    counters (a failover mustn't silently zero them)."""
    from dslabs_tpu.tpu.warden import _SCALAR_FIELDS

    for key in ("fault_events", "partition_events", "crash_events",
                "drop_events", "dup_events"):
        assert key in _SCALAR_FIELDS


# ------------------------------------------------------ chaos bridge

@pytest.mark.slow
def test_chaos_soak_partitioned_scenario_job(tmp_path):
    """Engine chaos x model faults: the seeded injection soak runs the
    partitioned-scenario job on the virtual mesh with EXACT verdict
    parity against its own fault-free baseline."""
    from dslabs_tpu.tpu import chaos as chaos_mod
    from dslabs_tpu.tpu.sharded import make_mesh

    report = chaos_mod.soak(
        chaos_mod._protocol("paxos-partition"),
        spec=chaos_mod.ChaosSpec(seed=7, faults=12),
        supervisor_kwargs=dict(mesh=make_mesh(8), chunk=64,
                               frontier_cap=1 << 9,
                               visited_cap=1 << 12),
        checkpoint_path=str(tmp_path / "soak.npz"),
        min_fired=8, min_sites=2)
    assert report["parity"] is True
    assert report["chaos"]["dropped_states"] == 0
