"""Schema compiler (tpu/compiler.py): the generated lab 0 / lab 1 twins
explore state spaces ISOMORPHIC to the hand-written twins — identical
unique-state counts and verdicts at exhaustion (order-independent), with
the object checker as the outer oracle via the parity sweep's generated
entries (tests/test_verdict_parity_sweep.py)."""

import dataclasses

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.specs import clientserver_spec, pingpong_spec


def _pruned(p):
    return dataclasses.replace(
        p, goals={}, prunes={"DONE": p.goals["CLIENTS_DONE"]})


def test_generated_pingpong_matches_hand_twin():
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    gen = TensorSearch(_pruned(pingpong_spec(2).compile()),
                       chunk=128).run()
    hand = TensorSearch(_pruned(make_pingpong_protocol(2)),
                        chunk=128).run()
    assert gen.end_condition == hand.end_condition == "SPACE_EXHAUSTED"
    assert gen.unique_states == hand.unique_states
    assert gen.states_explored == hand.states_explored


def test_generated_pingpong_goal_and_violation():
    p = pingpong_spec(2).compile()
    out = TensorSearch(p, chunk=128).run()
    assert out.end_condition == "GOAL_FOUND"
    pv = pingpong_spec(2, never_done=True).compile()
    out = TensorSearch(dataclasses.replace(pv, goals={}),
                       chunk=128).run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.predicate_name == "NONE_DECIDED"


@pytest.mark.parametrize("nc,w", [(1, 2), (2, 1)])
def test_generated_clientserver_matches_hand_twin(nc, w):
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    gen = TensorSearch(_pruned(clientserver_spec(nc, w).compile()),
                       chunk=256).run()
    hand = TensorSearch(_pruned(make_clientserver_protocol(nc, w)),
                        chunk=256).run()
    assert gen.end_condition == hand.end_condition == "SPACE_EXHAUSTED"
    assert gen.unique_states == hand.unique_states
    assert gen.states_explored == hand.states_explored


def test_generated_pb_matches_hand_twin():
    """Lab 2 through the compiler (round-4 verdict item 7): the
    generated ViewServer+PBServer twin must walk the hand twin's state
    graph exactly — depth-limited unique/explored parity (the full
    pruned space is large; depth parity at increasing depths pins the
    transition function the same way the lab4 oracle tests do)."""
    from dslabs_tpu.tpu.protocols.primarybackup import make_pb_protocol
    from dslabs_tpu.tpu.specs import pb_spec

    gen_p = pb_spec(2, 1, 1).compile()
    hand_p = make_pb_protocol(2, 1, 1)
    for depth in (1, 2, 3, 4):
        gen = TensorSearch(gen_p, chunk=256, max_depth=depth).run()
        hand = TensorSearch(hand_p, chunk=256, max_depth=depth).run()
        assert gen.unique_states == hand.unique_states, (
            f"depth {depth}: gen {gen.unique_states} != "
            f"hand {hand.unique_states}")
        assert gen.states_explored == hand.states_explored, (
            f"depth {depth}: gen explored {gen.states_explored} != "
            f"hand {hand.states_explored}")


def test_generated_pb_two_client_parity():
    """Two clients through the generated lab2 twin: the forwarding and
    AMO lanes are per-client vectors, so this pins the array-field
    (get_at/put_at) compilation path on a stateful protocol."""
    from dslabs_tpu.tpu.protocols.primarybackup import make_pb_protocol
    from dslabs_tpu.tpu.specs import pb_spec

    gen_p = pb_spec(2, 2, 1).compile()
    hand_p = make_pb_protocol(2, 2, 1)
    for depth in (2, 3):
        gen = TensorSearch(gen_p, chunk=256, max_depth=depth).run()
        hand = TensorSearch(hand_p, chunk=256, max_depth=depth).run()
        assert gen.unique_states == hand.unique_states, (
            f"depth {depth}: gen {gen.unique_states} != "
            f"hand {hand.unique_states}")
        assert gen.states_explored == hand.states_explored


def test_generated_pb_goal():
    """The generated lab2 twin completes the workload (view startup ->
    state transfer -> forwarded op -> reply) exactly like the hand
    twin."""
    from dslabs_tpu.tpu.protocols.primarybackup import make_pb_protocol
    from dslabs_tpu.tpu.specs import pb_spec

    gen = TensorSearch(pb_spec(2, 1, 1).compile(), chunk=512,
                       max_depth=12).run()
    hand = TensorSearch(make_pb_protocol(2, 1, 1), chunk=512,
                        max_depth=12).run()
    assert gen.end_condition == hand.end_condition == "GOAL_FOUND"
    assert gen.depth == hand.depth
