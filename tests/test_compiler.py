"""Schema compiler (tpu/compiler.py): the generated lab 0 / lab 1 twins
explore state spaces ISOMORPHIC to the hand-written twins — identical
unique-state counts and verdicts at exhaustion (order-independent), with
the object checker as the outer oracle via the parity sweep's generated
entries (tests/test_verdict_parity_sweep.py)."""

import dataclasses

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.specs import clientserver_spec, pingpong_spec


def _pruned(p):
    return dataclasses.replace(
        p, goals={}, prunes={"DONE": p.goals["CLIENTS_DONE"]})


def test_generated_pingpong_matches_hand_twin():
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    gen = TensorSearch(_pruned(pingpong_spec(2).compile()),
                       chunk=128).run()
    hand = TensorSearch(_pruned(make_pingpong_protocol(2)),
                        chunk=128).run()
    assert gen.end_condition == hand.end_condition == "SPACE_EXHAUSTED"
    assert gen.unique_states == hand.unique_states
    assert gen.states_explored == hand.states_explored


def test_generated_pingpong_goal_and_violation():
    p = pingpong_spec(2).compile()
    out = TensorSearch(p, chunk=128).run()
    assert out.end_condition == "GOAL_FOUND"
    pv = pingpong_spec(2, never_done=True).compile()
    out = TensorSearch(dataclasses.replace(pv, goals={}),
                       chunk=128).run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.predicate_name == "NONE_DECIDED"


@pytest.mark.parametrize("nc,w", [(1, 2), (2, 1)])
def test_generated_clientserver_matches_hand_twin(nc, w):
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    gen = TensorSearch(_pruned(clientserver_spec(nc, w).compile()),
                       chunk=256).run()
    hand = TensorSearch(_pruned(make_clientserver_protocol(nc, w)),
                        chunk=256).run()
    assert gen.end_condition == hand.end_condition == "SPACE_EXHAUSTED"
    assert gen.unique_states == hand.unique_states
    assert gen.states_explored == hand.states_explored
