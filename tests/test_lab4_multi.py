"""Multi-server-group lab 4 twin (tpu/protocols/shardstore_multi.py):
depth-by-depth unique-count parity for the ``setupStates(2, 3, 1, 10)``
shape — 2 groups x 3 Paxos-replicated ShardStoreServers with REAL
in-group log lanes (the round-3 verdict's missing capability).

Oracle counts come from the object checker on the SAME staged state
(joined via the config controller, then one PUT client added; masters
and the controller gated exactly like ShardStoreBaseTest.java:209-220):

    state = make_search(2, 3, 1, 10); joined = _joined_state(state, 2, 3)
    joined.add_client_worker(client1, kv_workload(["PUT:key-1:v1"]))
    settings: RESULTS_OK invariant, CCA node+timers off,
              shardmaster timers off, max_depth = joined.depth + d

measured 2026-07-31 (tools-free repro: /tmp-style drivers in this file's
git history; the deeper runs are round-5 additions):
    (2, 3, 1, 10): depth 1 -> 10   2 -> 69    3 -> 392
                   depth 4 -> 1985 5 -> 9304  6 -> 41189
    (2, 2, 1, 10): depth 1 -> 8    2 -> 42    3 -> 180
                   depth 4 -> 681  5 -> 2365      (second staged start:
                   2-server groups — different majority, different
                   election interleavings from depth 1 on)

The twin starts from the equivalent staged state by construction
(init_* in the twin factory mirror the object staging: two pending
client config queries, per-server election + query timers, client retry
timer)."""

import os

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.specs_lab4 import \
    make_shardstore_multi_protocol

SLOW = not os.environ.get("DSLABS_SLOW_TESTS")

# Depth 6's oracle count (41189, measured 2026-07-31) stays OUT of the
# automated sweep: the twin side alone needs ~an hour of CPU at that
# depth, past the slow job's budget.  Depth 5 pins the same transition
# surface (every handler class fires by depth 4).
ORACLE = {1: 10, 2: 69, 3: 392, 4: 1985, 5: 9304}
ORACLE_N2 = {1: 8, 2: 42, 3: 180, 4: 681, 5: 2365}


@pytest.mark.skipif(SLOW, reason="multi-group twin compile is minutes on "
                    "CPU (DSLABS_SLOW_TESTS=1 enables)")
def test_lab4_multi_group_depth_parity():
    p = make_shardstore_multi_protocol(n_groups=2, n=3, num_shards=10)
    for depth, want in ORACLE.items():
        out = TensorSearch(p, chunk=128, max_depth=depth).run()
        assert out.unique_states == want, (
            f"depth {depth}: tensor {out.unique_states} != object {want}")


@pytest.mark.skipif(SLOW, reason="multi-group twin compile is minutes on "
                    "CPU (DSLABS_SLOW_TESTS=1 enables)")
def test_lab4_multi_group_n2_depth_parity():
    """The SECOND staged start (round-4 verdict item 6): 2-server
    groups — majority 2 of 2, so the in-group Paxos walks different
    quorum/election interleavings than the 3-server shape from the very
    first level."""
    p = make_shardstore_multi_protocol(n_groups=2, n=2, num_shards=10)
    for depth, want in ORACLE_N2.items():
        out = TensorSearch(p, chunk=128, max_depth=depth).run()
        assert out.unique_states == want, (
            f"depth {depth}: tensor {out.unique_states} != object {want}")
