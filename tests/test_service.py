"""Checking-as-a-service suite (ISSUE 11, ``make service-smoke``).

Covers the service layer bottom-up:

* the UNIFIED child-death taxonomy (supervisor.classify_child_death —
  one vocabulary for the warden's failover, the elastic ladder's
  classify_oom, and the scheduler's retry policy), table-driven;
* the bounded persistent queue: structured queue-full retry-after
  rejection (never raises, never blocks), torn-tail journal replay,
  tmp+replace compaction;
* DRR fairness + per-tenant concurrency quotas + the degrade policy
  (oom -> knob-shrink, wedge -> rung-step, failed -> no retry);
* the CPU-pinned conformance admission gate: an unsound spec is
  rejected with structured SpecError-derived findings BEFORE any twin
  compiles;
* ACCEPTANCE — the tenant-isolation chaos soak: three tenants, a
  seeded oom/hang/crash fault schedule killing one tenant's jobs;
  every unaffected tenant's verdict is bit-exact vs its solo baseline,
  the affected tenant lands degraded-but-sound verdicts or a
  structured failure (never a silent partial one), a full-queue
  submission gets the structured retry-after rejection, and no
  cross-tenant telemetry bleed (each job's run dir is self-contained).
"""

import json
import os
import signal
import textwrap
import time

import pytest

from dslabs_tpu.service import (AttemptPlan, CheckServer,
                                DeficitRoundRobin, Job, RetrySpec,
                                ServiceQueue, degrade, fairness_index,
                                replay_journal)
from dslabs_tpu.tpu.supervisor import (CHILD_RC_FAILED, _OOM_MARKERS,
                                       classify_child_death,
                                       classify_oom)
from dslabs_tpu.tpu.warden import classify_death

pytestmark = pytest.mark.service

# Children are fresh processes: share the suite's persistent compile
# cache (tests/conftest.py) or every spawn pays a cold XLA build.
CHILD_ENV = {"DSLABS_COMPILE_CACHE": "/tmp/jaxcache-cpu"}
FACTORY = ("dslabs_tpu.tpu.protocols.pingpong:"
           "make_exhaustive_pingpong")
SMALL = dict(factory_kwargs={"workload_size": 2}, chunk=64,
             frontier_cap=1 << 8, visited_cap=1 << 12)
# The grace ladder the warden hang test uses: an injected hang is cut
# at steady_grace + slack ~ 4 s instead of the compile-sized default.
GRACES = {"boot_grace": 120.0, "first_grace": 120.0,
          "steady_grace": 3.0, "idle_grace": 60.0, "grace_slack": 1.0}


def _server(root, **kw):
    kw.setdefault("admission", False)
    kw.setdefault("elastic", False)
    kw.setdefault("env", CHILD_ENV)
    kw.setdefault("warden_kwargs", dict(GRACES))
    return CheckServer(str(root), **kw)


def _same_verdict(a: dict, b: dict):
    for key in ("end", "unique", "explored", "depth"):
        assert a[key] == b[key], (key, a, b)


# ------------------------------------------- unified death taxonomy

# (exitcode, killed_by_warden, stderr tail, expected kind) — the one
# table the warden's failover, the service scheduler's retry policy,
# and the elastic ladder's OOM re-level all agree on.
TAXONOMY = [
    (-signal.SIGKILL, True, (), "wedge"),               # warden kill
    (-signal.SIGKILL, False, (), "oom"),                # kernel OOM
    (-signal.SIGSEGV, False, (), "crash"),
    (-signal.SIGTERM, False, (), "crash"),
    (CHILD_RC_FAILED, False, (), "failed"),             # clean report
    (1, False, (), "crash"),
    (86, False, (), "crash"),
    (None, False, (), "crash"),
    # stderr markers refine ONLY the abrupt kinds: a MemoryError
    # traceback / RESOURCE_EXHAUSTED tail turns a crash into an oom …
    (1, False, ("Traceback …", "MemoryError",), "oom"),
    (-signal.SIGSEGV, False, ("RESOURCE_EXHAUSTED: out of memory",),
     "oom"),
    (86, False, ("XlaRuntimeError: Allocation failure on device",),
     "oom"),
    # … but a warden kill stays a wedge and a clean report stays
    # failed even when stderr chattered about memory earlier.
    (-signal.SIGKILL, True, ("MemoryError",), "wedge"),
    (CHILD_RC_FAILED, False, ("MemoryError",), "failed"),
]


def test_unified_death_taxonomy_table():
    for exitcode, killed, stderr, want in TAXONOMY:
        got = classify_child_death(exitcode, killed, stderr)
        assert got == want, (exitcode, killed, stderr, got, want)
        # warden.classify_death IS the same function (one vocabulary).
        assert classify_death(exitcode, killed, stderr) == want


def test_taxonomy_agrees_with_classify_oom():
    """Every marker the elastic ladder's knob-shrink trigger
    (classify_oom) recognises also flips an abrupt child death to
    ``oom`` — the scheduler's retry policy and the in-process re-level
    can never disagree about what an OOM is."""
    for marker in _OOM_MARKERS:
        assert classify_oom(RuntimeError(f"XlaRuntimeError: {marker}"))
        assert classify_child_death(1, False, (marker,)) == "oom"
        assert classify_child_death(-signal.SIGSEGV, False,
                                    (marker,)) == "oom"


# ------------------------------------------------ queue + journal

def test_queue_full_returns_structured_rejection(tmp_path):
    q = ServiceQueue(str(tmp_path), cap=2)
    a = q.submit(Job(job_id=q.next_id("a"), tenant="a", factory="m:f"))
    b = q.submit(Job(job_id=q.next_id("a"), tenant="a", factory="m:f"))
    assert a["accepted"] and b["accepted"]
    t0 = time.time()
    r = q.submit(Job(job_id=q.next_id("b"), tenant="b", factory="m:f"))
    # Never blocks (sub-second), never raises, fully structured.
    assert time.time() - t0 < 1.0
    assert r == {"accepted": False, "rejected": True,
                 "reason": "queue_full",
                 "retry_after_secs": r["retry_after_secs"],
                 "queue_depth": 2, "queue_cap": 2}
    assert r["retry_after_secs"] > 0
    assert q.summary()["backpressure"] is True
    q.close()


def test_journal_replay_tolerates_torn_tail(tmp_path):
    q = ServiceQueue(str(tmp_path), cap=8)
    for i in range(3):
        q.submit(Job(job_id=q.next_id("t"), tenant="t", factory="m:f"))
    q.mark_started("t-000001", attempt=1)
    q.mark_done("t-000001", {"end": "SPACE_EXHAUSTED", "unique": 8})
    q.mark_started("t-000002", attempt=1)   # crash-interrupted
    q.close()
    # A SIGKILL mid-append leaves one torn tail line — the replayer
    # must shrug it off exactly like the flight-recorder reader.
    with open(q.journal_path, "a") as f:
        f.write('{"t": "done", "job_id": "t-0000')
    pending, records, seq = replay_journal(q.journal_path)
    assert seq == 3
    assert records["t-000001"]["status"] == "done"
    # started-but-unfinished jobs re-queue (the crash-recovery path).
    assert sorted(j.job_id for j in pending) == ["t-000002", "t-000003"]
    # A fresh queue over the same journal resumes that state.
    q2 = ServiceQueue(str(tmp_path), cap=8)
    assert q2.depth() == 2
    q2.close()


def test_journal_compaction_is_atomic(tmp_path):
    q = ServiceQueue(str(tmp_path), cap=8)
    for i in range(2):
        q.submit(Job(job_id=q.next_id("t"), tenant="t", factory="m:f"))
    q.mark_done("t-000001", {"end": "SPACE_EXHAUSTED"})
    q.compact()
    # tmp+replace: no stray .tmp, and the compacted journal replays to
    # the identical state.
    assert not os.path.exists(q.journal_path + ".tmp")
    pending, records, seq = replay_journal(q.journal_path)
    assert records["t-000001"]["status"] == "done"
    assert [j.job_id for j in pending] == ["t-000002"]
    # The queue keeps appending durably after compaction.
    q.submit(Job(job_id=q.next_id("t"), tenant="t", factory="m:f"))
    assert seq == 2 and q.depth() == 2
    q.close()


# --------------------------------------------- scheduler + fairness

def test_drr_interleaves_tenants_and_honors_quota():
    s = DeficitRoundRobin(quota=1)
    for i in range(4):
        s.push(Job(job_id=f"a-{i}", tenant="a", factory="m:f"))
    for i in range(2):
        s.push(Job(job_id=f"b-{i}", tenant="b", factory="m:f"))
    order, running = [], {}
    while True:
        j = s.pick(running)
        if j is None:
            break
        order.append(j.job_id)
    # A 4-deep backlog cannot starve the 2-job tenant: strict
    # alternation while both are backlogged.
    assert order == ["a-0", "b-0", "a-1", "b-1", "a-2", "a-3"]
    # Quota: a tenant at its concurrency limit is ineligible …
    s2 = DeficitRoundRobin(quota=1)
    s2.push(Job(job_id="a-0", tenant="a", factory="m:f"))
    assert s2.pick({"a": 1}) is None
    # … and a freed slot makes it runnable again.
    assert s2.pick({"a": 0}).job_id == "a-0"


def test_drr_budget_weighting():
    """A tenant submitting one 4-unit job and a tenant submitting four
    1-unit jobs get the same budget share: the big job must wait for
    its deficit, letting the small jobs through first."""
    s = DeficitRoundRobin(quota=4)
    s.push(Job(job_id="big-0", tenant="big", factory="m:f",
               budget_units=4.0))
    for i in range(4):
        s.push(Job(job_id=f"small-{i}", tenant="small", factory="m:f"))
    order = []
    while True:
        j = s.pick({})
        if j is None:
            break
        order.append(j.job_id)
    assert order.index("big-0") >= 2
    assert sorted(order) == ["big-0", "small-0", "small-1", "small-2",
                             "small-3"]


def test_degrade_policy_table():
    retry = RetrySpec(max_attempts=3)
    p = AttemptPlan(attempt=1, chunk=64, ladder=("device", "host"))
    # oom -> knob-shrink re-level: the next attempt is strictly lighter.
    nxt = degrade(p, "oom", retry)
    assert (nxt.chunk, nxt.knob_shrinks, nxt.ladder) == (32, 1,
                                                         ("device",
                                                          "host"))
    # wedge -> kill + rung-step.
    nxt = degrade(p, "wedge", retry)
    assert (nxt.ladder, nxt.rung_steps) == (("host",), 1)
    assert degrade(AttemptPlan(1, 64, ("host",)), "wedge",
                   retry).ladder == ("host",)
    # crash -> plain bounded retry.
    assert degrade(p, "crash", retry).chunk == 64
    # failed -> structured failure, never a retry.
    assert degrade(p, "failed", retry) is None
    # the retry budget is a hard bound for every kind.
    assert degrade(AttemptPlan(3, 64, ("device",)), "oom", retry) is None


def test_fairness_index_pinned():
    assert fairness_index({}) == 1.0
    assert fairness_index({"a": {"verdicts": 4, "budget_spent": 4.0},
                           "b": {"verdicts": 2,
                                 "budget_spent": 2.0}}) == 1.0
    # a converts budget 4x better than b: max/mean = 2 / 1.25 = 1.6
    assert fairness_index({"a": {"verdicts": 4, "budget_spent": 2.0},
                           "b": {"verdicts": 1,
                                 "budget_spent": 2.0}}) == 1.6


# ------------------------------------------------- admission gate

UNSOUND_MODULE = textwrap.dedent("""
    import random


    class EvilNode:
        def __init__(self, address):
            self.peers = []

        def handle_Req(self, message, sender):
            message["seq"] = random.randint(0, 3)   # C1 + C2
            self.send(message, sender)


    def make_evil_protocol():
        return EvilNode("n1")
""")


def test_admission_rejects_unsound_spec_before_any_twin(tmp_path):
    (tmp_path / "evil_user_proto.py").write_text(UNSOUND_MODULE)
    srv = _server(tmp_path / "svc", admission=True,
                  extra_sys_path=[str(tmp_path)])
    res = srv.submit("evil_user_proto:make_evil_protocol",
                     tenant="mallory")
    assert res["accepted"] is False and res["reason"] == "unsound_spec"
    codes = {f["code"] for f in res["findings"]}
    assert codes & {"C1", "C2"}, res["findings"]
    for f in res["findings"]:        # SpecError-derived finding shape
        assert {"code", "path", "obj", "line", "message"} <= set(f)
    # Rejected BEFORE any twin compiled: no job dir, nothing queued,
    # and the rejection is on the tenant's ledger.
    assert not os.path.exists(os.path.join(str(tmp_path / "svc"),
                                           "jobs"))
    assert srv.queue.depth() == 0
    assert srv.server_status()["tenants"]["mallory"]["rejected"] == 1
    # A sound shipped factory passes the same gate (cached per spec).
    ok = srv.submit(FACTORY, tenant="alice", **SMALL)
    assert ok["accepted"], ok
    srv.close()


# ------------------------------------- scheduler-level degradation

def test_oom_death_costs_a_knob_shrink_relevel(tmp_path):
    """A job whose ONLY rung dies OOM-shaped is retried by the
    scheduler with halved chunk knobs, resumed from its own durable
    checkpoint — the PR 9 knob-shrink answer applied at job
    granularity — and still lands the exact verdict."""
    solo = _server(tmp_path / "solo", workers=1)
    solo.submit(FACTORY, tenant="base", **SMALL)
    base = solo.drain()["results"][0]
    solo.close()
    assert base["status"] == "done"

    srv = _server(tmp_path / "svc", workers=1)
    srv.submit(FACTORY, tenant="alice", ladder=("device",),
               fault={"kind": "die", "at": 8, "after_ckpt": True},
               **SMALL)
    out = srv.drain()["results"][0]
    srv.close()
    assert out["status"] == "done"
    _same_verdict(out, base)
    assert out["attempts"] == 2
    assert out["knob_shrinks"] == 1
    assert [d["kind"] for d in out["deaths"]] == ["oom"]
    assert out["degraded"] is True
    assert out["resumed_from_depth"] > 0


# --------------------------------- ACCEPTANCE: tenant isolation soak

def test_tenant_isolation_chaos_soak(tmp_path):
    """ISSUE 11 acceptance: >= 3 tenants, a seeded fault schedule
    killing one tenant's jobs (oom, hang, crash variants) plus a
    deterministic in-child failure; neighbors' verdicts bit-exact vs
    their solo baselines, the victim degraded-but-sound or
    structured-failed, a full-queue submission rejected with the
    structured retry-after shape, zero cross-tenant telemetry bleed."""

    def run_solo(tenant):
        srv = _server(tmp_path / f"solo-{tenant}", workers=1)
        assert srv.submit(FACTORY, tenant=tenant, **SMALL)["accepted"]
        summary = srv.drain()
        srv.close()
        assert summary["completed"] == 1
        return summary["results"][0]

    base_b = run_solo("bob")
    base_c = run_solo("carol")
    _same_verdict(base_b, base_c)            # same protocol, same space

    srv = _server(tmp_path / "svc", workers=2, queue_cap=6)
    # The seeded schedule on tenant alice: one job per fault variant.
    faults = {
        "oom": {"kind": "die", "at": 8, "after_ckpt": True},
        "hang": {"kind": "hang", "at": 8},
        "crash": {"kind": "exit", "at": 5},
    }
    alice_jobs = {}
    for kind, fault in faults.items():
        res = srv.submit(FACTORY, tenant="alice", fault=fault, **SMALL)
        assert res["accepted"], res
        alice_jobs[res["job_id"]] = kind
    # A deterministic in-child failure on a single-rung ladder: must
    # land a STRUCTURED failure (never a silent partial verdict).
    res = srv.submit(FACTORY, tenant="alice", ladder=("device",),
                     fault={"kind": "raise", "at": 3}, **SMALL)
    assert res["accepted"]
    raise_job = res["job_id"]
    assert srv.submit(FACTORY, tenant="bob", **SMALL)["accepted"]
    assert srv.submit(FACTORY, tenant="carol", **SMALL)["accepted"]
    # Queue is now at cap: the next submission gets the structured
    # retry-after rejection, not an exception and not a stall.
    over = srv.submit(FACTORY, tenant="dave", **SMALL)
    assert over["accepted"] is False
    assert over["reason"] == "queue_full"
    assert over["retry_after_secs"] > 0
    assert over["queue_depth"] == 6 and over["queue_cap"] == 6

    summary = srv.drain()
    srv.close()
    results = {r["job_id"]: r for r in summary["results"]}
    assert len(results) == 6

    # Unaffected tenants: bit-exact vs their SOLO baselines, zero
    # degradation absorbed.
    for tenant, base in (("bob", base_b), ("carol", base_c)):
        (job,) = [r for r in results.values() if r["tenant"] == tenant]
        assert job["status"] == "done"
        _same_verdict(job, base)
        assert job["degraded"] is False and not job["deaths"]

    # The victim: every fault variant lands a degraded-but-SOUND
    # verdict (exact counts, recovered via failover-from-checkpoint),
    # with the death classified under the unified taxonomy …
    want_kind = {"oom": "oom", "hang": "wedge", "crash": "crash"}
    for job_id, kind in alice_jobs.items():
        r = results[job_id]
        assert r["status"] == "done", r
        _same_verdict(r, base_b)
        assert r["degraded"] is True
        assert [d["kind"] for d in r["deaths"]] == [want_kind[kind]], r
    # … and the deterministic failure is a structured verdict, not a
    # silent partial one and not an endless retry.
    r = results[raise_job]
    assert r["status"] == "failed" and r["kind"] == "failed"
    assert r["attempts"] == 1 and r["deaths"]

    # Zero cross-tenant telemetry bleed: every job's run dir is
    # self-contained (own STATUS.json + flight log + checkpoint), and
    # no other tenant's job id appears in it.
    run_dirs = {r["run_dir"] for r in results.values()}
    assert len(run_dirs) == 6
    for r in results.values():
        listing = os.listdir(r["run_dir"])
        assert "flight.jsonl" in listing and "STATUS.json" in listing
        blob = ""
        for name in ("flight.jsonl", "STATUS.json"):
            with open(os.path.join(r["run_dir"], name)) as f:
                blob += f.read()
        for other in results.values():
            if other["job_id"] != r["job_id"]:
                assert other["job_id"] not in blob

    # The aggregate monitor: SERVER_STATUS.json carries the per-tenant
    # ledger and the fairness index.
    with open(os.path.join(str(tmp_path / "svc"),
                           "SERVER_STATUS.json")) as f:
        status = json.load(f)
    assert status["queue_depth"] == 0 and status["backpressure"] is False
    t = status["tenants"]
    assert t["alice"]["completed"] == 3 and t["alice"]["failed"] == 1
    assert t["bob"]["completed"] == 1 and t["carol"]["completed"] == 1
    assert t["dave"]["rejected"] == 1
    assert summary["fairness_index"] >= 1.0


# ----------------------------------------------------------- CLI

@pytest.mark.slow
def test_service_cli_submit_status_drain(tmp_path, capsys):
    from dslabs_tpu.service.__main__ import main

    root = str(tmp_path / "svc")
    rc = main(["submit", "--root", root, "--tenant", "alice",
               "--factory", FACTORY,
               "--kwargs", json.dumps({"workload_size": 2}),
               "--chunk", "64", "--no-admission"])
    sub = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and sub["accepted"]

    rc = main(["status", "--root", root])
    st = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and st["queue"]["queue_depth"] == 1

    os.environ.setdefault("DSLABS_COMPILE_CACHE", "/tmp/jaxcache-cpu")
    rc = main(["drain", "--root", root, "--no-admission",
               "--workers", "1"])
    dr = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and dr["completed"] == 1 and dr["failed"] == 0
    assert dr["results"][0]["tenant"] == "alice"

    rc = main(["status", "--root", root])
    st = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert st["server"]["tenants"]["alice"]["completed"] == 1
