"""Verdict-parity tests: the TPU tensor-search backend must reproduce the
object-graph model checker's verdicts AND unique-state counts on identical
configurations (SURVEY §8.4 hard part #1 — equivalence-relation parity).

Runs on the 8-device virtual CPU mesh configured in conftest.py.
"""

import dataclasses

import pytest

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingClient, PingServer,
                                               Pong)
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import CLIENTS_DONE, RESULTS_OK
from dslabs_tpu.testing.workload import Workload
from dslabs_tpu.search.results import EndCondition

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol  # noqa: E402

SERVER = LocalAddress("pingserver")


def object_search(w, prune_done=False):
    def parser(c, r):
        return Ping(c), (Pong(r) if r is not None else None)

    gen = NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=[f"hi-{i}" for i in range(1, w + 1)],
            result_strings=[f"hi-{i}" for i in range(1, w + 1)],
            parser=parser))
    state = SearchState(gen)
    state.add_server(SERVER)
    state.add_client_worker(LocalAddress("client1"))
    settings = SearchSettings().add_invariant(RESULTS_OK)
    if prune_done:
        settings.add_prune(CLIENTS_DONE)
    else:
        settings.add_goal(CLIENTS_DONE)
    settings.max_time(60)
    return bfs(state, settings)


def tensor_search(w, prune_done=False):
    p = make_pingpong_protocol(w)
    if prune_done:
        p = dataclasses.replace(p, goals={},
                                prunes={"CLIENTS_DONE": p.goals["CLIENTS_DONE"]})
    return TensorSearch(p, chunk=512).run()


@pytest.mark.parametrize("w", [1, 2])
def test_goal_verdict_parity(w):
    obj = object_search(w)
    ten = tensor_search(w)
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert ten.end_condition == "GOAL_FOUND"


@pytest.mark.parametrize("w", [1, 2])
def test_exhaustive_unique_state_parity(w):
    """With CLIENTS_DONE pruned, both backends exhaust the same space and
    must discover exactly the same number of unique states."""
    obj = object_search(w, prune_done=True)
    ten = tensor_search(w, prune_done=True)
    assert obj.end_condition == EndCondition.SPACE_EXHAUSTED
    assert ten.end_condition == "SPACE_EXHAUSTED"
    assert ten.unique_states == obj.discovered_count, (
        f"object discovered {obj.discovered_count}, "
        f"tensor discovered {ten.unique_states}")


@pytest.mark.skipif(not __import__("os").environ.get("DSLABS_SLOW_TESTS"),
                    reason="multi-minute XLA compile; set DSLABS_SLOW_TESTS=1")
def test_paxos_depth_parity():
    """Depth-limited unique-state parity on lab 3 multi-Paxos (3 servers,
    1 client, 1 command): verified by hand for depths 1-6
    (6/25/102/427/1803/7540); CI checks depth 3."""
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.labs.clientserver.kvstore import KVStore
    from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer
    from dslabs_tpu.search.search import BFS
    from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol

    servers = tuple(LocalAddress(f"server{i}") for i in range(1, 4))
    gen = NodeGenerator(
        server_supplier=lambda a: PaxosServer(a, servers, KVStore()),
        client_supplier=lambda a: PaxosClient(a, servers),
        workload_supplier=lambda a: None)
    st = SearchState(gen)
    for a in servers:
        st.add_server(a)
    st.add_client_worker(LocalAddress("client0"),
                         kv_workload(["PUT:key-0:v1"], ["PutOk"]))
    settings = SearchSettings()
    settings.set_max_depth(3).max_time(300)
    obj = BFS(settings).run(st)

    p = make_paxos_protocol(n=3, n_clients=1, w=1, max_slots=2,
                            net_cap=48, timer_cap=6)
    ten = TensorSearch(p, chunk=256, max_depth=3).run()
    assert ten.unique_states == obj.discovered_count == 102
