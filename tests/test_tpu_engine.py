"""Verdict-parity tests: the TPU tensor-search backend must reproduce the
object-graph model checker's verdicts AND unique-state counts on identical
configurations (SURVEY §8.4 hard part #1 — equivalence-relation parity).

Runs on the 8-device virtual CPU mesh configured in conftest.py.
"""

import dataclasses

import pytest

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingClient, PingServer,
                                               Pong)
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import CLIENTS_DONE, RESULTS_OK
from dslabs_tpu.testing.workload import Workload
from dslabs_tpu.search.results import EndCondition

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol  # noqa: E402

SERVER = LocalAddress("pingserver")

SLOW = pytest.mark.skipif(
    not __import__("os").environ.get("DSLABS_SLOW_TESTS"),
    reason="extra parity point; covered by an ungated sibling config "
           "(set DSLABS_SLOW_TESTS=1 for the full matrix)")


def object_search(w, prune_done=False):
    def parser(c, r):
        return Ping(c), (Pong(r) if r is not None else None)

    gen = NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=[f"hi-{i}" for i in range(1, w + 1)],
            result_strings=[f"hi-{i}" for i in range(1, w + 1)],
            parser=parser))
    state = SearchState(gen)
    state.add_server(SERVER)
    state.add_client_worker(LocalAddress("client1"))
    settings = SearchSettings().add_invariant(RESULTS_OK)
    if prune_done:
        settings.add_prune(CLIENTS_DONE)
    else:
        settings.add_goal(CLIENTS_DONE)
    settings.max_time(60)
    return bfs(state, settings)


def tensor_search(w, prune_done=False):
    p = make_pingpong_protocol(w)
    if prune_done:
        p = dataclasses.replace(p, goals={},
                                prunes={"CLIENTS_DONE": p.goals["CLIENTS_DONE"]})
    return TensorSearch(p, chunk=512).run()


@pytest.mark.parametrize("w", [pytest.param(1, marks=SLOW), 2])
def test_goal_verdict_parity(w):
    obj = object_search(w)
    ten = tensor_search(w)
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert ten.end_condition == "GOAL_FOUND"


@pytest.mark.parametrize("w", [pytest.param(1, marks=SLOW), 2])
def test_exhaustive_unique_state_parity(w):
    """With CLIENTS_DONE pruned, both backends exhaust the same space and
    must discover exactly the same number of unique states."""
    obj = object_search(w, prune_done=True)
    ten = tensor_search(w, prune_done=True)
    assert obj.end_condition == EndCondition.SPACE_EXHAUSTED
    assert ten.end_condition == "SPACE_EXHAUSTED"
    assert ten.unique_states == obj.discovered_count, (
        f"object discovered {obj.discovered_count}, "
        f"tensor discovered {ten.unique_states}")


def _clientserver_object_search(nc, w, prune_done=False):
    from dslabs_tpu.labs.clientserver.clientserver import (SimpleClient,
                                                           SimpleServer)
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.labs.clientserver.kvstore import KVStore

    server = LocalAddress("server")
    gen = NodeGenerator(
        server_supplier=lambda a: SimpleServer(a, KVStore()),
        client_supplier=lambda a: SimpleClient(a, server),
        workload_supplier=lambda a: None)
    state = SearchState(gen)
    state.add_server(server)
    for c in range(nc):
        state.add_client_worker(
            LocalAddress(f"client{c}"),
            kv_workload([f"PUT:key{c}:v{i}" for i in range(1, w + 1)],
                        ["PutOk"] * w))
    settings = SearchSettings().add_invariant(RESULTS_OK)
    if prune_done:
        settings.add_prune(CLIENTS_DONE)
    else:
        settings.add_goal(CLIENTS_DONE)
    settings.max_time(120)
    return bfs(state, settings)


@pytest.mark.parametrize("nc,w", [
    pytest.param(1, 1, marks=SLOW),
    pytest.param(1, 2, marks=SLOW),
    (2, 1),
])
def test_clientserver_exhaustive_unique_state_parity(nc, w):
    """Lab 1 twin: same pruned-space unique-state count as the object
    checker (ClientServerPart2Test.java:175-281 semantics)."""
    import dataclasses as dc

    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    obj = _clientserver_object_search(nc, w, prune_done=True)
    p = make_clientserver_protocol(n_clients=nc, w=w)
    p = dc.replace(p, goals={},
                   prunes={"CLIENTS_DONE": p.goals["CLIENTS_DONE"]})
    ten = TensorSearch(p, chunk=256).run()
    assert obj.end_condition == EndCondition.SPACE_EXHAUSTED
    assert ten.end_condition == "SPACE_EXHAUSTED"
    assert ten.unique_states == obj.discovered_count, (
        f"object {obj.discovered_count} != tensor {ten.unique_states}")


@SLOW
def test_clientserver_goal_parity():
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    obj = _clientserver_object_search(1, 2)
    ten = TensorSearch(make_clientserver_protocol(n_clients=1, w=2),
                       chunk=256).run()
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert ten.end_condition == "GOAL_FOUND"


def _pb_object_search(ns, nc, w, max_depth):
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.labs.clientserver.kvstore import KVStore
    from dslabs_tpu.labs.primarybackup.pb import PBClient, PBServer
    from dslabs_tpu.labs.primarybackup.viewserver import ViewServer
    from dslabs_tpu.search.search import BFS

    vsa = LocalAddress("viewserver")

    def server_supplier(a):
        if a == vsa:
            return ViewServer(a)
        return PBServer(a, vsa, KVStore())

    gen = NodeGenerator(
        server_supplier=server_supplier,
        client_supplier=lambda a: PBClient(a, vsa),
        workload_supplier=lambda a: None)
    state = SearchState(gen)
    state.add_server(vsa)
    for s in range(1, ns + 1):
        state.add_server(LocalAddress(f"server{s}"))
    for c in range(nc):
        state.add_client_worker(
            LocalAddress(f"client{c}"),
            kv_workload([f"PUT:key{c}:v{i}" for i in range(1, w + 1)],
                        ["PutOk"] * w))
    settings = SearchSettings().add_invariant(RESULTS_OK)
    settings.set_max_depth(max_depth).max_time(300)
    return BFS(settings).run(state)


@pytest.mark.parametrize("ns,depth", [
    pytest.param(1, 3, marks=SLOW),
    (2, 3),
    pytest.param(2, 4, marks=SLOW),
])
def test_pb_depth_parity(ns, depth):
    """Lab 2 twin: depth-limited unique-state parity against the object
    checker (PrimaryBackupTest.java:660-905 search semantics), covering
    view formation, pings/ticks, and the state-transfer machinery."""
    from dslabs_tpu.tpu.protocols.primarybackup import make_pb_protocol

    obj = _pb_object_search(ns, 1, 1, depth)
    p = make_pb_protocol(ns=ns, n_clients=1, w=1)
    ten = TensorSearch(p, chunk=256, max_depth=depth).run()
    assert ten.unique_states == obj.discovered_count, (
        f"object {obj.discovered_count} != tensor {ten.unique_states}")


def test_paxos_depth_parity():
    """Depth-limited unique-state parity on lab 3 multi-Paxos (3 servers,
    1 client, 1 command): verified by hand for depths 1-6
    (6/25/102/427/1803/7540); CI checks depth 3 unconditionally
    (round-1 verdict: the flagship parity claim must not be gated)."""
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.labs.clientserver.kvstore import KVStore
    from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer
    from dslabs_tpu.search.search import BFS
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol

    servers = tuple(LocalAddress(f"server{i}") for i in range(1, 4))
    gen = NodeGenerator(
        server_supplier=lambda a: PaxosServer(a, servers, KVStore()),
        client_supplier=lambda a: PaxosClient(a, servers),
        workload_supplier=lambda a: None)
    st = SearchState(gen)
    for a in servers:
        st.add_server(a)
    st.add_client_worker(LocalAddress("client0"),
                         kv_workload(["PUT:key-0:v1"], ["PutOk"]))
    settings = SearchSettings()
    settings.set_max_depth(3).max_time(300)
    obj = BFS(settings).run(st)

    p = make_paxos_protocol(n=3, n_clients=1, w=1, max_slots=2,
                            net_cap=48, timer_cap=6)
    ten = TensorSearch(p, chunk=256, max_depth=3).run()
    assert ten.unique_states == obj.discovered_count == 102


def test_staged_search_with_dropped_messages():
    """Staged tensor search (PaxosTest.java:886-1096 pattern): reach an
    intermediate goal, drop all pending messages, and search onward from
    the extracted state — retry timers must re-drive to completion."""
    import dataclasses as dc

    import jax.numpy as jnp

    from dslabs_tpu.tpu.engine import drop_pending_messages
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    p = make_pingpong_protocol(workload_size=2)
    halfway = dc.replace(
        p, goals={"HALFWAY": lambda s: s["nodes"][0] == 2})
    phase1 = TensorSearch(halfway, chunk=128).run()
    assert phase1.end_condition == "GOAL_FOUND"
    mid = jax.tree.map(jnp.asarray, phase1.goal_state)
    assert int(mid["nodes"][0, 0]) == 2

    # Phase 2a: continue unmodified from the extracted state (one search
    # object for both phase-2 runs — same compiled program).
    cont = TensorSearch(p, chunk=128)
    phase2 = cont.run(initial=mid)
    assert phase2.end_condition == "GOAL_FOUND"

    # Phase 2b: drop every pending message first; only timers remain, so
    # the client retry timer must re-send and still reach CLIENTS_DONE.
    dropped = drop_pending_messages(mid)
    assert int((dropped["net"][0, :, 0] != 2 ** 31 - 1).sum()) == 0
    phase3 = cont.run(initial=dropped)
    assert phase3.end_condition == "GOAL_FOUND"
