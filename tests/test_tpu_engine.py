"""Verdict-parity tests: the TPU tensor-search backend must reproduce the
object-graph model checker's verdicts AND unique-state counts on identical
configurations (SURVEY §8.4 hard part #1 — equivalence-relation parity).

Runs on the 8-device virtual CPU mesh configured in conftest.py.
"""

import dataclasses

import pytest

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingClient, PingServer,
                                               Pong)
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import CLIENTS_DONE, RESULTS_OK
from dslabs_tpu.testing.workload import Workload
from dslabs_tpu.search.results import EndCondition

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol  # noqa: E402

SERVER = LocalAddress("pingserver")


def object_search(w, prune_done=False):
    def parser(c, r):
        return Ping(c), (Pong(r) if r is not None else None)

    gen = NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=[f"hi-{i}" for i in range(1, w + 1)],
            result_strings=[f"hi-{i}" for i in range(1, w + 1)],
            parser=parser))
    state = SearchState(gen)
    state.add_server(SERVER)
    state.add_client_worker(LocalAddress("client1"))
    settings = SearchSettings().add_invariant(RESULTS_OK)
    if prune_done:
        settings.add_prune(CLIENTS_DONE)
    else:
        settings.add_goal(CLIENTS_DONE)
    settings.max_time(60)
    return bfs(state, settings)


def tensor_search(w, prune_done=False):
    p = make_pingpong_protocol(w)
    if prune_done:
        p = dataclasses.replace(p, goals={},
                                prunes={"CLIENTS_DONE": p.goals["CLIENTS_DONE"]})
    return TensorSearch(p, chunk=512).run()


@pytest.mark.parametrize("w", [1, 2])
def test_goal_verdict_parity(w):
    obj = object_search(w)
    ten = tensor_search(w)
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert ten.end_condition == "GOAL_FOUND"


@pytest.mark.parametrize("w", [1, 2])
def test_exhaustive_unique_state_parity(w):
    """With CLIENTS_DONE pruned, both backends exhaust the same space and
    must discover exactly the same number of unique states."""
    obj = object_search(w, prune_done=True)
    ten = tensor_search(w, prune_done=True)
    assert obj.end_condition == EndCondition.SPACE_EXHAUSTED
    assert ten.end_condition == "SPACE_EXHAUSTED"
    assert ten.unique_states == obj.discovered_count, (
        f"object discovered {obj.discovered_count}, "
        f"tensor discovered {ten.unique_states}")
