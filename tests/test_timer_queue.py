"""TimerQueue partial-order semantics (reference: TimerQueueTest.java:86-176).

The model's single ordering rule: if t1 was set before t2 and
t2.min >= t1.max, t1 must fire first.
"""

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.core.types import Timer
from dslabs_tpu.search.timer_queue import TimerQueue
from dslabs_tpu.testing.events import TimerEnvelope

from dataclasses import dataclass

A = LocalAddress("a")


@dataclass(frozen=True)
class T(Timer):
    n: int


def te(n, lo, hi):
    return TimerEnvelope(A, T(n), lo, hi)


def deliverable_ids(q):
    return [x.timer.n for x in q.deliverable()]


def test_empty():
    q = TimerQueue()
    assert deliverable_ids(q) == []
    assert not q.is_deliverable(te(1, 5, 5))


def test_single_timer_deliverable():
    q = TimerQueue()
    q.add(te(1, 10, 10))
    assert deliverable_ids(q) == [1]
    assert q.is_deliverable(te(1, 10, 10))


def test_equal_bounds_fifo():
    # Same (min, max): strictly ordered — t2.min >= t1.max.
    q = TimerQueue()
    q.add(te(1, 10, 10))
    q.add(te(2, 10, 10))
    assert deliverable_ids(q) == [1]
    assert not q.is_deliverable(te(2, 10, 10))


def test_overlapping_bounds_interleave():
    # t2.min < t1.max: either may fire first.
    q = TimerQueue()
    q.add(te(1, 5, 15))
    q.add(te(2, 10, 20))
    assert deliverable_ids(q) == [1, 2]
    assert q.is_deliverable(te(2, 10, 20))


def test_retry_timer_cannot_overtake_itself():
    # Classic retry pattern: a re-set retry timer (same bounds) can't
    # overtake its earlier instance... but identical envelopes collapse in
    # equality terms; distinct-value retry timers cannot reorder.
    q = TimerQueue()
    q.add(te(1, 10, 10))
    q.add(te(2, 10, 10))
    q.add(te(3, 10, 10))
    assert deliverable_ids(q) == [1]


def test_unrelated_short_timer_interleaves():
    q = TimerQueue()
    q.add(te(1, 100, 100))
    q.add(te(2, 10, 20))  # 10 < 100: may fire before t1
    assert deliverable_ids(q) == [1, 2]


def test_skipped_timer_bound_propagates():
    # t1(min=5,max=10); t2(min=10,max=30) skipped (10>=10); t3(min=8,max=9)
    # deliverable (8 < 10).
    q = TimerQueue()
    q.add(te(1, 5, 10))
    q.add(te(2, 10, 30))
    q.add(te(3, 8, 9))
    assert deliverable_ids(q) == [1, 3]
    assert not q.is_deliverable(te(2, 10, 30))
    assert q.is_deliverable(te(3, 8, 9))


def test_bound_uses_min_of_yielded_maxes():
    # After yielding t1(max=20) and t2(max=8), the bound is 8: t3(min=9) is
    # not deliverable even though 9 < 20.
    q = TimerQueue()
    q.add(te(1, 1, 20))
    q.add(te(2, 2, 8))
    q.add(te(3, 9, 50))
    assert deliverable_ids(q) == [1, 2]


def test_remove_fires_and_unblocks():
    q = TimerQueue()
    q.add(te(1, 10, 10))
    q.add(te(2, 10, 10))
    q.remove(te(1, 10, 10))
    assert deliverable_ids(q) == [2]


def test_equality_ignores_sampled_length():
    a = te(1, 5, 15)
    b = te(1, 5, 15)
    _ = a.length_ms  # sample one
    assert a == b
    assert hash(a) == hash(b)


def test_queue_equality():
    q1, q2 = TimerQueue(), TimerQueue()
    q1.add(te(1, 10, 10))
    q2.add(te(1, 10, 10))
    assert q1 == q2 and hash(q1) == hash(q2)
    q2.add(te(2, 10, 10))
    assert q1 != q2
