"""Lab 4 tests — behavioural port of the TransactionalKVStore unit semantics
and ShardStorePart1Test run tests (basic ops, join/leave handoff, shard
movement, wrong-group routing)."""

import time

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS,
                                UNRELIABLE_TESTS, lab_test)
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import get, get_result, put, put_ok
from dslabs_tpu.labs.clientserver.kvstore import KeyNotFound
from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer
from dslabs_tpu.labs.shardedstore.shardmaster import (Join, Leave, Move, Ok,
                                                      Query, ShardConfig,
                                                      ShardMaster)
from dslabs_tpu.labs.shardedstore.shardstore import (ShardStoreClient,
                                                     ShardStoreServer,
                                                     key_to_shard)
from dslabs_tpu.labs.shardedstore.txkvstore import (MultiGet, MultiGetResult,
                                                    MultiPut, MultiPutOk,
                                                    Swap, SwapOk,
                                                    TransactionalKVStore,
                                                    KEY_NOT_FOUND)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import CLIENTS_DONE, RESULTS_OK

CCA = LocalAddress("configController")
MOVER = LocalAddress("mover")
NUM_SHARDS = 10


def shard_master(i):
    return LocalAddress(f"shardmaster{i}")


def server(g, i):
    return LocalAddress(f"server{g}-{i}")


def group(g, n=3):
    return frozenset(server(g, i) for i in range(1, n + 1))


# --------------------------------------------------------- unit: txkvstore

@lab_test("4", 40, "TransactionalKVStore semantics", part=3, categories=(RUN_TESTS,))
def test_txkvstore_semantics():
    kv = TransactionalKVStore()
    assert kv.execute(MultiPut({"a": "1", "b": "2"})) == MultiPutOk()
    r = kv.execute(MultiGet({"a", "b", "c"}))
    assert r == MultiGetResult({"a": "1", "b": "2", "c": KEY_NOT_FOUND})
    assert kv.execute(Swap("a", "b")) == SwapOk()
    assert kv.execute(MultiGet({"a", "b"})) == MultiGetResult(
        {"a": "2", "b": "1"})
    # Swap with a missing key moves the value and deletes the other side.
    assert kv.execute(Swap("a", "missing")) == SwapOk()
    assert kv.execute(MultiGet({"a", "missing"})) == MultiGetResult(
        {"a": KEY_NOT_FOUND, "missing": "2"})
    # Plain KVStore ops still work.
    assert kv.execute(put("x", "y")) == put_ok()
    assert kv.execute(get("x")) == get_result("y")


@lab_test("4", 15, "keyToShard matches reference hashing", part=2, categories=(RUN_TESTS,))
def test_key_to_shard():
    assert key_to_shard("key-3", 10) == 3
    assert key_to_shard("key-10", 10) == 10  # 10 mod 10 = 0 -> +10
    assert key_to_shard("key-13", 10) == 3
    s = key_to_shard("foo", 10)
    assert 1 <= s <= 10
    assert key_to_shard("foo", 10) == s  # deterministic
    # 10+ trailing digits overflow Java's 32-bit int accumulation
    # (ShardStoreNode.java keyToShard: hash = hash*10 + digit in int
    # arithmetic); 12345678901 wraps to -539222987, mod 10 -> 3.
    assert key_to_shard("x12345678901", 10) == 3
    # 4294967296 == 2^32 wraps to exactly 0 -> mod adjusts to numShards.
    assert key_to_shard("k4294967296", 10) == 10


# ------------------------------------------------------------- run fixtures

def _make_generator(servers_per_group, num_shard_masters, num_shards):
    masters = tuple(shard_master(i) for i in range(1, num_shard_masters + 1))

    def server_supplier(a):
        if a in masters:
            return PaxosServer(a, masters, ShardMaster(num_shards))
        name = str(a)
        g = int(name.split("server")[1].split("-")[0])
        grp = tuple(server(g, i) for i in range(1, servers_per_group + 1))
        return ShardStoreServer(a, masters, num_shards, grp, g)

    def client_supplier(a):
        # Config-controller-style clients (CCA, the movement driver) talk
        # to the shard-master group directly; everything else is a store
        # client routing by shard.
        if a == CCA or a == MOVER:
            return PaxosClient(a, masters)
        return ShardStoreClient(a, masters, num_shards)

    return masters, NodeGenerator(server_supplier=server_supplier,
                                  client_supplier=client_supplier,
                                  workload_supplier=lambda a: None)


def make_state(num_groups, servers_per_group=3, num_shard_masters=3,
               num_shards=NUM_SHARDS):
    masters, gen = _make_generator(servers_per_group, num_shard_masters,
                                   num_shards)
    state = RunState(gen)
    for m in masters:
        state.add_server(m)
    for g in range(1, num_groups + 1):
        for i in range(1, servers_per_group + 1):
            state.add_server(server(g, i))
    return state


def send_check(client, command, expected, timeout=8):
    client.send_command(command)
    result = client.get_result(timeout=timeout)
    assert result == expected, f"{command} -> {result} (expected {expected})"


@lab_test("4", 1, "Single group, basic workload", points=10, part=2, categories=(RUN_TESTS,))
def test_basic_single_group():
    state = make_state(1)
    settings = RunSettings().max_time(30)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    c = state.add_client(LocalAddress("client1"))
    send_check(c, put("key-1", "v1"), put_ok())
    send_check(c, get("key-1"), get_result("v1"))
    send_check(c, get("key-7"), KeyNotFound())
    send_check(c, put("key-7", "v7"), put_ok())
    send_check(c, get("key-7"), get_result("v7"))
    state.stop()


@lab_test("4", 3, "Shards move when group joins", points=15, part=2, categories=(RUN_TESTS,))
def test_join_moves_shards():
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())

    c = state.add_client(LocalAddress("client1"))
    for i in range(1, NUM_SHARDS + 1):
        send_check(c, put(f"key-{i}", f"v{i}"), put_ok())

    # Join the second group: half the shards (with data) must move.
    send_check(cc, Join(2, group(2)), Ok())
    for i in range(1, NUM_SHARDS + 1):
        send_check(c, get(f"key-{i}"), get_result(f"v{i}"))

    # Data written after the reconfiguration lands in the right group too.
    send_check(c, put("key-1", "v1b"), put_ok())
    send_check(c, get("key-1"), get_result("v1b"))

    # Leave group 1: all shards drain to group 2, nothing is lost.
    send_check(cc, Leave(1), Ok())
    for i in range(2, NUM_SHARDS + 1):
        send_check(c, get(f"key-{i}"), get_result(f"v{i}"))
    send_check(c, get("key-1"), get_result("v1b"))
    state.stop()


@lab_test("4", 4, "Shards move when moved by ShardMaster", points=15, part=2, categories=(RUN_TESTS,))
def test_move_command_relocates_data():
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())

    c = state.add_client(LocalAddress("client1"))
    send_check(c, put("key-3", "v3"), put_ok())

    cc.send_command(Query(-1))
    config = cc.get_result(timeout=5)
    assert isinstance(config, ShardConfig)
    dest = 2 if 3 in config.groups()[1][1] else 1
    send_check(cc, Move(dest, 3), Ok())

    send_check(c, get("key-3"), get_result("v3"))
    send_check(c, put("key-3", "v3b"), put_ok())
    send_check(c, get("key-3"), get_result("v3b"))
    state.stop()


@lab_test("4", 1, "Single group, simple transactional workload", points=5, part=3, categories=(RUN_TESTS,))
def test_single_group_transactions():
    """Transactions whose key set lives in one group run without 2PC."""
    state = make_state(1)
    settings = RunSettings().max_time(30)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    c = state.add_client(LocalAddress("client1"))
    send_check(c, MultiPut({"a1": "x", "b1": "y"}), MultiPutOk())
    send_check(c, MultiGet({"a1", "b1"}),
               MultiGetResult({"a1": "x", "b1": "y"}))
    send_check(c, Swap("a1", "b1"), SwapOk())
    send_check(c, MultiGet({"a1", "b1"}),
               MultiGetResult({"a1": "y", "b1": "x"}))
    state.stop()


@lab_test("4", 2, "Multi-group, simple transactional workload", points=5, part=3, categories=(RUN_TESTS,))
def test_cross_group_transactions():
    """2PC: transactions spanning groups commit atomically."""
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())
    c = state.add_client(LocalAddress("client1"))
    # key-1..key-10 span both groups (shards 1..10 split 5/5).
    send_check(c, MultiPut({f"key-{i}": f"v{i}" for i in range(1, 6)}),
               MultiPutOk())
    send_check(c, MultiGet({f"key-{i}" for i in range(1, 6)}),
               MultiGetResult({f"key-{i}": f"v{i}" for i in range(1, 6)}))
    send_check(c, Swap("key-1", "key-2"), SwapOk())
    send_check(c, MultiGet({"key-1", "key-2"}),
               MultiGetResult({"key-1": "v2", "key-2": "v1"}))
    # Swap against a missing key across groups.
    send_check(c, Swap("key-3", "key-9"), SwapOk())
    send_check(c, MultiGet({"key-3", "key-9"}),
               MultiGetResult({"key-3": KEY_NOT_FOUND, "key-9": "v3"}))
    state.stop()


@lab_test("4", 13, "Concurrent cross-group swaps (extended)", points=0, part=3, categories=(RUN_TESTS,))
def test_concurrent_cross_group_swaps():
    """Concurrent conflicting 2PC transactions stay atomic: swaps permute
    values, so the value multiset is preserved (TransactionalKVStoreWorkload
    MULTI_GETS_MATCH spirit)."""
    import threading
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())
    setup = state.add_client(LocalAddress("setup-client"))
    keys = ["key-1", "key-5", "key-6", "key-10"]
    send_check(setup, MultiPut({k: k for k in keys}), MultiPutOk())

    errors = []

    def swapper(name, k1, k2, n):
        c = state.add_client(LocalAddress(name))
        try:
            for _ in range(n):
                c.send_command(Swap(k1, k2))
                assert c.get_result(timeout=20) == SwapOk()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=swapper, args=("swap-a", "key-1", "key-6", 4)),
        threading.Thread(target=swapper, args=("swap-b", "key-5", "key-10", 4)),
        threading.Thread(target=swapper, args=("swap-c", "key-1", "key-10", 3)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors

    reader = state.add_client(LocalAddress("reader-client"))
    reader.send_command(MultiGet(set(keys)))
    result = reader.get_result(timeout=20)
    assert isinstance(result, MultiGetResult)
    # Swaps only permute: the multiset of values is invariant.
    assert sorted(result.as_dict().values()) == sorted(keys)
    state.stop()


# ------------------------------------------- additional reference ports (p2)

def _join_leave_body(state, n_keys=30):
    """test02JoinLeave body (ShardStorePart1Test.java:75-121, scaled
    100 -> 30 keys): keys survive joins, rewrites, and leaves."""
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    c = state.add_client(LocalAddress("client1"))
    kv = {}
    for i in range(1, n_keys + 1):
        kv[f"key-{i}"] = f"v{i}"
        send_check(c, put(f"key-{i}", f"v{i}"), put_ok())

    send_check(cc, Join(2, group(2)), Ok())
    send_check(cc, Join(3, group(3)), Ok())
    time.sleep(2)
    for k, v in kv.items():
        send_check(c, get(k), get_result(v))

    for i in range(1, n_keys + 1):
        kv[f"key-{i}"] = f"w{i}"
        send_check(c, put(f"key-{i}", f"w{i}"), put_ok())

    send_check(cc, Leave(1), Ok())
    send_check(cc, Leave(2), Ok())
    time.sleep(2)
    for k, v in kv.items():
        send_check(c, get(k), get_result(v))
    state.stop()


@lab_test("4", 2, "Multi-group join/leave", points=15, part=2, categories=(RUN_TESTS,))
def test02_join_leave():
    state = make_state(3)
    state.start(RunSettings().max_time(120))
    _join_leave_body(state)


@lab_test("4", 5, "Progress with majorities in each group", points=15, part=2, categories=(RUN_TESTS,))
def test05_progress_with_majorities():
    """test05ProgressWithMajorities: one server per group (and one shard
    master) cut off; join/leave still completes."""
    state = make_state(3)
    settings = RunSettings().max_time(120)
    for g in range(1, 4):
        settings.receiver_active(server(g, 3), False)
        settings.sender_active(server(g, 3), False)
    settings.receiver_active(shard_master(3), False)
    settings.sender_active(shard_master(3), False)
    state.start(settings)
    _join_leave_body(state, n_keys=15)


@lab_test("4", 8, "Multi-group join/leave", points=20, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test08_join_leave_unreliable():
    state = make_state(3)
    settings = RunSettings().max_time(180)
    settings.network_deliver_rate(0.8)
    state.start(settings)
    _join_leave_body(state, n_keys=10)


def _run_with_background(state, settings, background, length_secs,
                         n_clients=3, max_wait=4.0):
    """Shared body of test06/test07/test09: infinite-workload clients run
    while a background thread perturbs the system."""
    import threading

    from dslabs_tpu.labs.clientserver.kv_workload import \
        different_keys_infinite_workload

    cc = state.add_client(CCA)
    for g in range(1, 4):
        send_check(cc, Join(g, group(g)), Ok(), timeout=20)
    for i in range(1, n_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"),
                                different_keys_infinite_workload(10))
    stop = threading.Event()
    th = threading.Thread(target=background, args=(stop,), daemon=True)
    th.start()
    time.sleep(length_secs)
    stop.set()
    th.join(10)
    state.stop()
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()
    for w in state.client_workers().values():
        mw = w.max_wait(state.stop_time)
        assert mw is not None and mw[0] < max_wait, f"max wait {mw}"


@lab_test("4", 6, "Repeated partitioning of each group", points=20, part=2, categories=(RUN_TESTS,))
def test06_repeated_partitioning():
    """test06RepeatedPartitioning (scaled 50s -> 8s): a minority of each
    group keeps dropping out."""
    import random as _random

    state = make_state(3)
    settings = RunSettings().max_time(60)
    state.start(settings)

    def partitioner(stop):
        rng = _random.Random(3)
        while not stop.is_set():
            settings.reconnect()
            for g in range(1, 4):
                srvs = [server(g, i) for i in range(1, 4)]
                rng.shuffle(srvs)
                settings.node_active(srvs[0], False)
            if stop.wait(1.5):
                break
            settings.reconnect()
            if stop.wait(1.5):
                break
        settings.reconnect()

    _run_with_background(state, settings, partitioner, length_secs=8,
                         max_wait=2.5)


def _constant_movement(deliver_rate=None, length_secs=8):
    """test07ConstantMovement: shards keep moving between groups while
    clients run."""
    import random as _random

    state = make_state(3)
    settings = RunSettings().max_time(90)
    if deliver_rate is not None:
        settings.network_deliver_rate(deliver_rate)
    state.start(settings)
    mover_client = [None]

    def mover(stop):
        rng = _random.Random(9)
        mc = state.add_client(MOVER)
        mover_client[0] = mc
        while not stop.is_set():
            g = rng.randrange(1, 4)
            s = rng.randrange(1, NUM_SHARDS + 1)
            try:
                mc.send_command(Move(g, s))
                mc.get_result(timeout=5)
            except TimeoutError:
                pass
            if stop.wait(0.3):
                break

    _run_with_background(state, settings, mover, length_secs=length_secs)


@lab_test("4", 7, "Repeated shard movement", points=20, part=2, categories=(RUN_TESTS,))
def test07_constant_movement():
    _constant_movement()


@lab_test("4", 9, "Repeated shard movement", points=30, part=2, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test09_constant_movement_unreliable():
    _constant_movement(deliver_rate=0.8)


# ----------------------------------------------------------- search fixtures

def make_search(num_groups, servers_per_group=1, num_shard_masters=1,
                num_shards=NUM_SHARDS):
    from dslabs_tpu.search.search_state import SearchState

    masters, gen = _make_generator(servers_per_group, num_shard_masters,
                                   num_shards)
    state = SearchState(gen)
    for m in masters:
        state.add_server(m)
    for g in range(1, num_groups + 1):
        for i in range(1, servers_per_group + 1):
            state.add_server(server(g, i))
    return state


def _joined_state(state, n_groups, servers_per_group=1,
                  num_shard_masters=1):
    """Drive the Join commands to completion through the config
    controller, narrowed to the {CCA, shard masters} partition exactly as
    the reference does (ShardStoreBaseTest.java:209-220) — the groups
    learn the config during the NEXT search phase, not here."""
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.predicates import client_done
    from dslabs_tpu.testing.workload import Workload

    cmds = [Join(g, group(g, servers_per_group))
            for g in range(1, n_groups + 1)]
    state.add_client_worker(CCA, Workload(commands=cmds,
                                          results=[Ok()] * len(cmds)))

    masters = [shard_master(i) for i in range(1, num_shard_masters + 1)]
    settings = SearchSettings().max_time(420)
    settings.add_invariant(RESULTS_OK)
    settings.partition(CCA, *masters)
    # Store servers are cut off anyway; their timers only add noise.
    for a in list(state.servers):
        if "server" in str(a):
            settings.deliver_timers(a, False)
    settings.add_goal(client_done(CCA))
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    return results.goal_matching_state


@lab_test("4", 10, "Single client, single group", points=20, part=2, categories=(SEARCH_TESTS,))
def test10_single_client_single_group_search():
    """ShardStorePart1Test.test10: put/get completes and the done-pruned
    space stays clean with one single-server group."""
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload

    state = make_search(1, 1, 1, 10)
    joined = _joined_state(state, 1)
    joined.add_client_worker(
        LocalAddress("client1"),
        kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"]))

    settings = SearchSettings().max_time(240)
    settings.add_invariant(RESULTS_OK).add_goal(CLIENTS_DONE)
    settings.node_active(CCA, False)
    settings.deliver_timers(CCA, False)
    # The singleton shard master is already the decided leader; its
    # election/heartbeat timers only multiply interleavings.
    settings.deliver_timers(shard_master(1), False)
    results = bfs(joined, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results

    settings.clear_goals().add_prune(CLIENTS_DONE)
    settings.set_max_depth(joined.depth + 6)
    results = bfs(joined, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results


@lab_test("4", 11, "Single client, multi-group", points=20, part=2, categories=(SEARCH_TESTS,))
def test11_single_client_multi_group_search():
    """ShardStorePart1Test.test11: the workload spans both groups' shards."""
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload

    state = make_search(2, 1, 1, 10)
    joined = _joined_state(state, 2)
    joined.add_client_worker(
        LocalAddress("client1"),
        kv_workload(["PUT:key-1:v1", "PUT:key-6:v6", "GET:key-1"],
                    ["PutOk", "PutOk", "v1"]))

    # Full goal-finding over two groups is beyond the Python oracle's
    # budget (the tensor backend is the scaling path); ungated CI checks
    # bounded-depth safety of the same space, goal-finding runs under
    # DSLABS_SLOW_TESTS with a long budget.
    import os as _os

    settings = SearchSettings()
    settings.add_invariant(RESULTS_OK)
    settings.node_active(CCA, False)
    settings.deliver_timers(CCA, False)
    settings.deliver_timers(shard_master(1), False)
    if _os.environ.get("DSLABS_SLOW_TESTS"):
        settings.max_time(900).add_goal(CLIENTS_DONE)
        results = bfs(joined, settings)
        assert results.end_condition == EndCondition.GOAL_FOUND, results
    else:
        settings.max_time(120).set_max_depth(joined.depth + 6)
        results = bfs(joined, settings)
        assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                         EndCondition.TIME_EXHAUSTED), results


@lab_test("4", 12, "Multi-client, multi-group", points=20, part=2, categories=(SEARCH_TESTS,))
def test12_multi_client_multi_group_search():
    """ShardStorePart1Test.test12: two clients appending to keys in
    different groups; both orders linearize."""
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload

    state = make_search(2, 1, 1, 2)
    joined = _joined_state(state, 2)
    joined.add_client_worker(LocalAddress("client1"),
                             kv_workload(["APPEND:foo-1:X1"], ["X1"]))
    joined.add_client_worker(
        LocalAddress("client2"),
        kv_workload(["APPEND:foo-2:Y2"], ["Y2"]))

    import os as _os

    settings = SearchSettings()
    settings.add_invariant(RESULTS_OK)
    settings.node_active(CCA, False)
    settings.deliver_timers(CCA, False)
    settings.deliver_timers(shard_master(1), False)
    if _os.environ.get("DSLABS_SLOW_TESTS"):
        settings.max_time(900).add_goal(CLIENTS_DONE)
        results = bfs(joined, settings)
        assert results.end_condition == EndCondition.GOAL_FOUND, results
    else:
        settings.max_time(120).set_max_depth(joined.depth + 6)
        results = bfs(joined, settings)
        assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                         EndCondition.TIME_EXHAUSTED), results


def _random_search(servers_per_group):
    from dslabs_tpu.search.search import dfs
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload

    state = make_search(2, servers_per_group, 1, 2)
    joined = _joined_state(state, 2, servers_per_group)
    joined.add_client_worker(LocalAddress("client1"),
                             kv_workload(["APPEND:foo-1:x"]))
    joined.add_client_worker(LocalAddress("client2"),
                             kv_workload(["APPEND:foo-2:y"]))

    settings = SearchSettings()
    settings.set_max_depth(1000).max_time(8)
    settings.add_invariant(RESULTS_OK)
    settings.add_prune(CLIENTS_DONE)
    results = dfs(joined, settings)
    assert not results.terminal_found()


@lab_test("4", 13, "One server per group random search", points=20, part=2, categories=(SEARCH_TESTS,))
def test13_single_server_random_search():
    _random_search(1)


@lab_test("4", 14, "Multiple servers per group random search", points=20, part=2, categories=(SEARCH_TESTS,))
def test14_multi_server_random_search():
    _random_search(2)


# ------------------------------------------- additional reference ports (p3)

@lab_test("4", 3, "No progress when groups can't communicate", points=10, part=3, categories=(RUN_TESTS,))
def test03_no_progress():
    """ShardStorePart2Test.test03NoProgress: with the groups partitioned
    from each other (client still sees both), single-group transactions
    commit but a cross-group 2PC transaction must block."""
    state = make_state(2, num_shards=2)
    settings = RunSettings().max_time(30)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())
    c = state.add_client(LocalAddress("client1"))
    send_check(c, MultiPut({"key1-1": "foo1", "key1-2": "foo2"}),
               MultiPutOk(), timeout=15)
    time.sleep(1)

    g1 = [server(1, i) for i in range(1, 4)]
    g2 = [server(2, i) for i in range(1, 4)]
    # Groups in separate partitions; the client keeps links to every server.
    settings.partition(*g1)
    for s in g2:
        for s2 in g2:
            settings.link_active(s, s2, True)
    for s in g1 + g2:
        settings.link_active(LocalAddress("client1"), s, True)
        settings.link_active(s, LocalAddress("client1"), True)

    send_check(c, MultiPut({"key2-1": "foo1", "key3-1": "foo2"}),
               MultiPutOk(), timeout=15)
    send_check(c, MultiPut({"key2-2": "foo1", "key3-2": "foo2"}),
               MultiPutOk(), timeout=15)

    c.send_command(MultiPut({"key4-1": "foo1", "key4-2": "foo2"}))
    time.sleep(4)
    assert not c.has_result(), "cross-group 2PC committed without comms"
    state.stop()


def _multi_gets_match(state):
    for w in state.client_workers().values():
        for r in w.results:
            if isinstance(r, MultiGetResult):
                vals = set(r.as_dict().values())
                if len(vals) > 1:
                    return False
    return True


@lab_test("4", 4, "Isolation between MultiPuts and MultiGets", points=10, part=3, categories=(RUN_TESTS,))
def test04_put_get_isolation():
    """ShardStorePart2Test.test04 (scaled 100 -> 25 rounds): a MultiGet
    concurrent with atomic MultiPuts over the same two cross-group keys
    must never observe a torn write."""
    from dslabs_tpu.testing.predicates import StatePredicate
    from dslabs_tpu.testing.workload import Workload

    n_rounds = 25
    state = make_state(2, num_shards=2)
    settings = RunSettings().max_time(90)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())

    put_cmds = [MultiPut({f"key{i}-1": f"foo{i}", f"key{i}-2": f"foo{i}"})
                for i in range(n_rounds)]
    get_cmds = [MultiGet({f"key{i}-1", f"key{i}-2"}) for i in range(n_rounds)]
    state.add_client_worker(LocalAddress("client1"),
                            Workload(commands=put_cmds,
                                     results=[MultiPutOk()] * n_rounds))
    state.add_client_worker(LocalAddress("client2"),
                            Workload(commands=get_cmds))
    state.wait_for()
    state.stop()
    assert _multi_gets_match(state), "torn MultiGet observed"
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


def _repeated_puts_gets(deliver_rate=None, with_movement=False,
                        n_rounds=12):
    """test05/06/07 (scaled): repeated cross-group MultiPut/MultiGet with
    matching expectations; optionally unreliable and/or under movement."""
    import random as _random
    import threading

    from dslabs_tpu.testing.workload import Workload

    state = make_state(2, num_shards=2)
    # Generous budget: wait_for returns as soon as the workers finish
    # (seconds when healthy); the margin only matters when the host is
    # heavily loaded and the real-time emulation is starved for cycles.
    settings = RunSettings().max_time(300)
    if deliver_rate is not None:
        settings.network_deliver_rate(deliver_rate)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok(), timeout=20)
    send_check(cc, Join(2, group(2)), Ok(), timeout=20)

    put_cmds, put_res, get_cmds, get_res = [], [], [], []
    for i in range(n_rounds):
        put_cmds.append(MultiPut({f"key{i}-1": f"v{i}", f"key{i}-2": f"v{i}"}))
        put_res.append(MultiPutOk())
    state.add_client_worker(LocalAddress("client1"),
                            Workload(commands=put_cmds, results=put_res))

    stop = threading.Event()
    th = None
    if with_movement:
        def mover():
            rng = _random.Random(13)
            mc = state.add_client(MOVER)
            while not stop.is_set():
                try:
                    mc.send_command(Move(rng.randrange(1, 3),
                                         rng.randrange(1, 3)))
                    mc.get_result(timeout=5)
                except TimeoutError:
                    pass
                if stop.wait(0.4):
                    break

        th = threading.Thread(target=mover, daemon=True)
        th.start()

    state.wait_for()
    # Now read everything back atomically.
    for i in range(n_rounds):
        get_cmds.append(MultiGet({f"key{i}-1", f"key{i}-2"}))
        get_res.append(MultiGetResult({f"key{i}-1": f"v{i}",
                                       f"key{i}-2": f"v{i}"}))
    state.add_client_worker(LocalAddress("client2"),
                            Workload(commands=get_cmds, results=get_res))
    state.wait_for()
    stop.set()
    if th is not None:
        th.join(8)
    state.stop()
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()
    assert _multi_gets_match(state)


@lab_test("4", 5, "Repeated MultiPuts and MultiGets, different keys", points=20, part=3, categories=(RUN_TESTS,))
def test05_repeated_puts_gets():
    _repeated_puts_gets()


@lab_test("4", 6, "Repeated MultiPuts and MultiGets, different keys", points=20, part=3, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test06_repeated_puts_gets_unreliable():
    _repeated_puts_gets(deliver_rate=0.8, n_rounds=8)


@lab_test("4", 7, "Repeated MultiPuts and MultiGets; constant movement", points=20, part=3, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test07_constant_movement_tx():
    _repeated_puts_gets(deliver_rate=0.8, with_movement=True, n_rounds=8)


@lab_test("4", 8, "Single client, single group; MultiPut, MultiGet", points=20, part=3, categories=(SEARCH_TESTS,))
def test08_single_client_single_group_tx_search():
    """ShardStorePart2Test.test08: transactional workload search in one
    single-server group."""
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.workload import Workload

    state = make_search(1, 1, 1, 2)
    joined = _joined_state(state, 1)
    joined.add_client_worker(
        LocalAddress("client1"),
        Workload(commands=[MultiPut({"key-1": "x", "key-2": "y"}),
                           MultiGet({"key-1", "key-2"})],
                 results=[MultiPutOk(),
                          MultiGetResult({"key-1": "x", "key-2": "y"})]))

    settings = SearchSettings().max_time(240)
    settings.add_invariant(RESULTS_OK).add_goal(CLIENTS_DONE)
    settings.node_active(CCA, False)
    settings.deliver_timers(CCA, False)
    settings.deliver_timers(shard_master(1), False)
    results = bfs(joined, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results

    settings.clear_goals().add_prune(CLIENTS_DONE)
    settings.set_max_depth(joined.depth + 6)
    results = bfs(joined, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results


@lab_test("4", 9, "Single client, multi-group; MultiPut, MultiGet", points=20, part=3, categories=(SEARCH_TESTS,))
def test09_single_client_multi_group_tx_search():
    """ShardStorePart2Test.test09: the transaction spans both groups
    (cross-group 2PC searched to completion)."""
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.workload import Workload

    state = make_search(2, 1, 1, 2)
    joined = _joined_state(state, 2)
    joined.add_client_worker(
        LocalAddress("client1"),
        Workload(commands=[MultiPut({"key-1": "x", "key-2": "y"})],
                 results=[MultiPutOk()]))

    settings = SearchSettings().max_time(300)
    settings.add_invariant(RESULTS_OK).add_goal(CLIENTS_DONE)
    settings.node_active(CCA, False)
    settings.deliver_timers(CCA, False)
    settings.deliver_timers(shard_master(1), False)
    results = bfs(joined, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results


@lab_test("4", 10, "Multi-client, multi-group; MultiPut, Swap, MultiGet", points=20, part=3, categories=(SEARCH_TESTS,))
def test10_multi_client_multi_group_tx_search():
    """ShardStorePart2Test.java:255 test10MultiClientMultiGroupSearch:
    client1 runs MultiPut{foo-1: X, foo-2: Y} then Swap(foo-1, foo-2)
    across both groups while client2's MultiGet must observe the swapped
    pair atomically ({foo-1: Y, foo-2: X} under the expected-results
    serialization)."""
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.workload import Workload

    import os as _os

    state = make_search(2, 1, 1, 2)
    joined = _joined_state(state, 2)
    joined.add_client_worker(
        LocalAddress("client1"),
        Workload(commands=[MultiPut({"foo-1": "X", "foo-2": "Y"}),
                           Swap("foo-1", "foo-2")],
                 results=[MultiPutOk(), SwapOk()]))
    joined.add_client_worker(
        LocalAddress("client2"),
        Workload(commands=[MultiGet({"foo-1", "foo-2"})],
                 results=[MultiGetResult({"foo-1": "Y", "foo-2": "X"})]))

    settings = SearchSettings()
    settings.add_invariant(RESULTS_OK)
    settings.node_active(CCA, False)
    settings.deliver_timers(CCA, False)
    settings.deliver_timers(shard_master(1), False)
    if _os.environ.get("DSLABS_SLOW_TESTS"):
        settings.max_time(900).add_goal(CLIENTS_DONE)
        results = bfs(joined, settings)
        assert results.end_condition == EndCondition.GOAL_FOUND, results
    else:
        # Bounded-depth safety of the same space on the fast path (the
        # goal lies beyond the Python oracle's ungated budget, exactly
        # like test11/test12 of Part 1).
        settings.max_time(120).set_max_depth(joined.depth + 5)
        results = bfs(joined, settings)
        assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                         EndCondition.TIME_EXHAUSTED), results


def _tx_random_search(servers_per_group, max_secs=20):
    """ShardStorePart2Test.java:275-334 randomSearch: the Join, Join,
    Leave(1) reconfiguration happens DURING the search (no staged join),
    transactional clients race it, and the MultiGet-atomicity invariant
    pins that client2 sees either both puts or neither — a torn
    {X, KEY_NOT_FOUND} read is the classic non-atomic-commit bug."""
    from dslabs_tpu.search.search import dfs
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.predicates import StatePredicate
    from dslabs_tpu.testing.workload import Workload

    state = make_search(2, servers_per_group, 1, 2)
    cmds = [Join(1, group(1, servers_per_group)),
            Join(2, group(2, servers_per_group)),
            Leave(1)]
    state.add_client_worker(CCA, Workload(commands=cmds,
                                          results=[Ok()] * len(cmds)))
    state.add_client_worker(
        LocalAddress("client1"),
        Workload(commands=[MultiPut({"foo-1": "X", "foo-2": "Y"})],
                 results=[MultiPutOk()]))
    state.add_client_worker(
        LocalAddress("client2"),
        Workload(commands=[MultiGet({"foo-1", "foo-2"})]))

    ok_full = MultiGetResult({"foo-1": "X", "foo-2": "Y"})
    ok_none = MultiGetResult({"foo-1": KEY_NOT_FOUND,
                              "foo-2": KEY_NOT_FOUND})

    def multi_get_atomic(s):
        results = s.client_workers()[LocalAddress("client2")].results
        if not results:
            return True
        if len(results) > 1:
            return False, "client2 received multiple MultiGetResults"
        r = results[0]
        if r != ok_full and r != ok_none:
            return False, (f"{r} matches neither {ok_none} nor "
                           f"{ok_full}")
        return True

    settings = SearchSettings()
    settings.set_max_depth(1000).max_time(max_secs)
    settings.add_invariant(StatePredicate(
        "MultiGet returns correct results", multi_get_atomic))
    settings.add_invariant(RESULTS_OK)
    settings.add_prune(CLIENTS_DONE)
    results = dfs(state, settings)
    assert not results.terminal_found(), results


@lab_test("4", 12, "Multiple servers per group random search", points=20, part=3, categories=(SEARCH_TESTS,))
def test12_multi_server_tx_random_search():
    """ShardStorePart2Test.java:346 test12MultiServerRandomSearch: the
    randomSearch shape with REAL 3-server Paxos groups."""
    _tx_random_search(3)


@lab_test("4", 11, "One server per group random search", points=20, part=3, categories=(SEARCH_TESTS,))
def test11_tx_random_search():
    """ShardStorePart2Test.test11: random probes over transactional
    workloads (MultiPut, Swap, MultiGet)."""
    from dslabs_tpu.search.search import dfs
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.workload import Workload

    state = make_search(2, 1, 1, 2)
    joined = _joined_state(state, 2)
    joined.add_client_worker(
        LocalAddress("client1"),
        Workload(commands=[MultiPut({"key-1": "x", "key-2": "y"}),
                           Swap("key-1", "key-2")]))
    joined.add_client_worker(
        LocalAddress("client2"),
        Workload(commands=[MultiGet({"key-1", "key-2"})]))

    settings = SearchSettings()
    settings.set_max_depth(1000).max_time(8)
    settings.add_invariant(RESULTS_OK)
    settings.add_prune(CLIENTS_DONE)
    results = dfs(joined, settings)
    assert not results.terminal_found()


# ------------------------------------------------- unit: 2PC vote pinning

@lab_test("4", 38, "coordinator ignores same-round votes after decision",
          part=3, categories=(RUN_TESTS,))
def test_yes_then_abort_same_round_duplicate():
    """Pins the `entry[2] is not None` guard in _apply_tx_vote: a
    participant that voted YES for round r can later vote ABORT for the
    SAME round (duplicate TxPrepare delivered after it installed a newer
    config — the config-mismatch abort in _apply_tx_prepare).  Once the
    coordinator fixed the round's decision, the late vote must be
    ignored, or a committed transaction would flip to aborted after the
    client already got its reply (round-2 advisor finding)."""
    from dslabs_tpu.core.node import NodeConfig
    from dslabs_tpu.labs.clientserver.amo import AMOCommand
    from dslabs_tpu.labs.shardedstore.shardstore import TxVote

    node = ShardStoreServer(server(1, 1), (shard_master(1),), NUM_SHARDS,
                            tuple(group(1)), 1)
    sent = []
    node.config(NodeConfig(
        message_adder=lambda frm, to, m: sent.append((to, m)),
        timer_adder=lambda frm, t, mn, mx: None,
    ))
    node.init()
    # Two groups, each owning one of the tx's shards.
    node.current_config = ShardConfig(1, {
        1: (group(1), frozenset({key_to_shard("key-1", NUM_SHARDS)})),
        2: (group(2), frozenset({key_to_shard("key-2", NUM_SHARDS)})),
    })
    client = LocalAddress("client1")
    tx = AMOCommand(MultiPut({"key-1": "x", "key-2": "y"}), client, 1)
    tx_id = (client, 1)
    node.tx_round[tx_id] = 1
    node.coord[tx_id] = [tx, {}, None, (), frozenset(), 1]

    node._apply_tx_vote(TxVote(tx_id, 1, 1, True, (("key-1", "a"),)))
    assert node.coord[tx_id][2] is None  # one vote: undecided
    node._apply_tx_vote(TxVote(tx_id, 1, 2, True, (("key-2", "b"),)))
    entry = node.coord[tx_id]
    assert entry[2] is True              # all yes: committed
    writes = entry[3]
    assert dict(writes) == {"key-1": "x", "key-2": "y"}

    # The duplicate-delivery interleaving: group 2 re-votes ABORT for the
    # SAME round.  Must be a no-op.
    node._apply_tx_vote(TxVote(tx_id, 1, 2, False, ()))
    assert node.coord[tx_id][2] is True
    assert node.coord[tx_id][3] == writes

    # Contrast (documents current semantics): BEFORE the decision, a
    # same-round re-vote does overwrite — an abort then wins.
    tx2 = AMOCommand(MultiPut({"key-1": "x2", "key-2": "y2"}), client, 2)
    tx2_id = (client, 2)
    node.tx_round[tx2_id] = 1
    node.coord[tx2_id] = [tx2, {}, None, (), frozenset(), 1]
    node._apply_tx_vote(TxVote(tx2_id, 1, 2, True, (("key-2", "b"),)))
    node._apply_tx_vote(TxVote(tx2_id, 1, 2, False, ()))
    node._apply_tx_vote(TxVote(tx2_id, 1, 1, True, (("key-1", "a"),)))
    assert node.coord[tx2_id][2] is False
