"""Lab 4 tests — behavioural port of the TransactionalKVStore unit semantics
and ShardStorePart1Test run tests (basic ops, join/leave handoff, shard
movement, wrong-group routing)."""

import time

import pytest

from dslabs_tpu.harness import RUN_TESTS, lab_test
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import get, get_result, put, put_ok
from dslabs_tpu.labs.clientserver.kvstore import KeyNotFound
from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer
from dslabs_tpu.labs.shardedstore.shardmaster import (Join, Leave, Move, Ok,
                                                      Query, ShardConfig,
                                                      ShardMaster)
from dslabs_tpu.labs.shardedstore.shardstore import (ShardStoreClient,
                                                     ShardStoreServer,
                                                     key_to_shard)
from dslabs_tpu.labs.shardedstore.txkvstore import (MultiGet, MultiGetResult,
                                                    MultiPut, MultiPutOk,
                                                    Swap, SwapOk,
                                                    TransactionalKVStore,
                                                    KEY_NOT_FOUND)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.testing.generator import NodeGenerator

CCA = LocalAddress("configController")
NUM_SHARDS = 10


def shard_master(i):
    return LocalAddress(f"shardmaster{i}")


def server(g, i):
    return LocalAddress(f"server{g}-{i}")


def group(g, n=3):
    return frozenset(server(g, i) for i in range(1, n + 1))


# --------------------------------------------------------- unit: txkvstore

@lab_test("4", 12, "TransactionalKVStore semantics", part=3, categories=(RUN_TESTS,))
def test_txkvstore_semantics():
    kv = TransactionalKVStore()
    assert kv.execute(MultiPut({"a": "1", "b": "2"})) == MultiPutOk()
    r = kv.execute(MultiGet({"a", "b", "c"}))
    assert r == MultiGetResult({"a": "1", "b": "2", "c": KEY_NOT_FOUND})
    assert kv.execute(Swap("a", "b")) == SwapOk()
    assert kv.execute(MultiGet({"a", "b"})) == MultiGetResult(
        {"a": "2", "b": "1"})
    # Swap with a missing key moves the value and deletes the other side.
    assert kv.execute(Swap("a", "missing")) == SwapOk()
    assert kv.execute(MultiGet({"a", "missing"})) == MultiGetResult(
        {"a": KEY_NOT_FOUND, "missing": "2"})
    # Plain KVStore ops still work.
    assert kv.execute(put("x", "y")) == put_ok()
    assert kv.execute(get("x")) == get_result("y")


@lab_test("4", 15, "keyToShard matches reference hashing", part=2, categories=(RUN_TESTS,))
def test_key_to_shard():
    assert key_to_shard("key-3", 10) == 3
    assert key_to_shard("key-10", 10) == 10  # 10 mod 10 = 0 -> +10
    assert key_to_shard("key-13", 10) == 3
    s = key_to_shard("foo", 10)
    assert 1 <= s <= 10
    assert key_to_shard("foo", 10) == s  # deterministic


# ------------------------------------------------------------- run fixtures

def make_state(num_groups, servers_per_group=3, num_shard_masters=3,
               num_shards=NUM_SHARDS):
    masters = tuple(shard_master(i) for i in range(1, num_shard_masters + 1))

    def server_supplier(a):
        if a in masters:
            return PaxosServer(a, masters, ShardMaster(num_shards))
        name = str(a)
        g = int(name.split("server")[1].split("-")[0])
        grp = tuple(server(g, i) for i in range(1, servers_per_group + 1))
        return ShardStoreServer(a, masters, num_shards, grp, g)

    def client_supplier(a):
        if a == CCA:
            return PaxosClient(a, masters)
        return ShardStoreClient(a, masters, num_shards)

    gen = NodeGenerator(server_supplier=server_supplier,
                        client_supplier=client_supplier,
                        workload_supplier=lambda a: None)
    state = RunState(gen)
    for m in masters:
        state.add_server(m)
    for g in range(1, num_groups + 1):
        for i in range(1, servers_per_group + 1):
            state.add_server(server(g, i))
    return state


def send_check(client, command, expected, timeout=8):
    client.send_command(command)
    result = client.get_result(timeout=timeout)
    assert result == expected, f"{command} -> {result} (expected {expected})"


@lab_test("4", 1, "Single group, basic workload", points=10, part=2, categories=(RUN_TESTS,))
def test_basic_single_group():
    state = make_state(1)
    settings = RunSettings().max_time(30)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    c = state.add_client(LocalAddress("client1"))
    send_check(c, put("key-1", "v1"), put_ok())
    send_check(c, get("key-1"), get_result("v1"))
    send_check(c, get("key-7"), KeyNotFound())
    send_check(c, put("key-7", "v7"), put_ok())
    send_check(c, get("key-7"), get_result("v7"))
    state.stop()


@lab_test("4", 3, "Shards move when group joins", points=15, part=2, categories=(RUN_TESTS,))
def test_join_moves_shards():
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())

    c = state.add_client(LocalAddress("client1"))
    for i in range(1, NUM_SHARDS + 1):
        send_check(c, put(f"key-{i}", f"v{i}"), put_ok())

    # Join the second group: half the shards (with data) must move.
    send_check(cc, Join(2, group(2)), Ok())
    for i in range(1, NUM_SHARDS + 1):
        send_check(c, get(f"key-{i}"), get_result(f"v{i}"))

    # Data written after the reconfiguration lands in the right group too.
    send_check(c, put("key-1", "v1b"), put_ok())
    send_check(c, get("key-1"), get_result("v1b"))

    # Leave group 1: all shards drain to group 2, nothing is lost.
    send_check(cc, Leave(1), Ok())
    for i in range(2, NUM_SHARDS + 1):
        send_check(c, get(f"key-{i}"), get_result(f"v{i}"))
    send_check(c, get("key-1"), get_result("v1b"))
    state.stop()


@lab_test("4", 4, "Shards move when moved by ShardMaster", points=15, part=2, categories=(RUN_TESTS,))
def test_move_command_relocates_data():
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())

    c = state.add_client(LocalAddress("client1"))
    send_check(c, put("key-3", "v3"), put_ok())

    cc.send_command(Query(-1))
    config = cc.get_result(timeout=5)
    assert isinstance(config, ShardConfig)
    dest = 2 if 3 in config.groups()[1][1] else 1
    send_check(cc, Move(dest, 3), Ok())

    send_check(c, get("key-3"), get_result("v3"))
    send_check(c, put("key-3", "v3b"), put_ok())
    send_check(c, get("key-3"), get_result("v3b"))
    state.stop()


@lab_test("4", 1, "Single group, simple transactional workload", points=5, part=3, categories=(RUN_TESTS,))
def test_single_group_transactions():
    """Transactions whose key set lives in one group run without 2PC."""
    state = make_state(1)
    settings = RunSettings().max_time(30)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    c = state.add_client(LocalAddress("client1"))
    send_check(c, MultiPut({"a1": "x", "b1": "y"}), MultiPutOk())
    send_check(c, MultiGet({"a1", "b1"}),
               MultiGetResult({"a1": "x", "b1": "y"}))
    send_check(c, Swap("a1", "b1"), SwapOk())
    send_check(c, MultiGet({"a1", "b1"}),
               MultiGetResult({"a1": "y", "b1": "x"}))
    state.stop()


@lab_test("4", 2, "Multi-group, simple transactional workload", points=5, part=3, categories=(RUN_TESTS,))
def test_cross_group_transactions():
    """2PC: transactions spanning groups commit atomically."""
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())
    c = state.add_client(LocalAddress("client1"))
    # key-1..key-10 span both groups (shards 1..10 split 5/5).
    send_check(c, MultiPut({f"key-{i}": f"v{i}" for i in range(1, 6)}),
               MultiPutOk())
    send_check(c, MultiGet({f"key-{i}" for i in range(1, 6)}),
               MultiGetResult({f"key-{i}": f"v{i}" for i in range(1, 6)}))
    send_check(c, Swap("key-1", "key-2"), SwapOk())
    send_check(c, MultiGet({"key-1", "key-2"}),
               MultiGetResult({"key-1": "v2", "key-2": "v1"}))
    # Swap against a missing key across groups.
    send_check(c, Swap("key-3", "key-9"), SwapOk())
    send_check(c, MultiGet({"key-3", "key-9"}),
               MultiGetResult({"key-3": KEY_NOT_FOUND, "key-9": "v3"}))
    state.stop()


@lab_test("4", 5, "Repeated MultiPuts and MultiGets, concurrent swaps", points=20, part=3, categories=(RUN_TESTS,))
def test_concurrent_cross_group_swaps():
    """Concurrent conflicting 2PC transactions stay atomic: swaps permute
    values, so the value multiset is preserved (TransactionalKVStoreWorkload
    MULTI_GETS_MATCH spirit)."""
    import threading
    state = make_state(2)
    settings = RunSettings().max_time(60)
    state.start(settings)
    cc = state.add_client(CCA)
    send_check(cc, Join(1, group(1)), Ok())
    send_check(cc, Join(2, group(2)), Ok())
    setup = state.add_client(LocalAddress("setup-client"))
    keys = ["key-1", "key-5", "key-6", "key-10"]
    send_check(setup, MultiPut({k: k for k in keys}), MultiPutOk())

    errors = []

    def swapper(name, k1, k2, n):
        c = state.add_client(LocalAddress(name))
        try:
            for _ in range(n):
                c.send_command(Swap(k1, k2))
                assert c.get_result(timeout=20) == SwapOk()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=swapper, args=("swap-a", "key-1", "key-6", 4)),
        threading.Thread(target=swapper, args=("swap-b", "key-5", "key-10", 4)),
        threading.Thread(target=swapper, args=("swap-c", "key-1", "key-10", 3)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors

    reader = state.add_client(LocalAddress("reader-client"))
    reader.send_command(MultiGet(set(keys)))
    result = reader.get_result(timeout=20)
    assert isinstance(result, MultiGetResult)
    # Swaps only permute: the multiset of values is invariant.
    assert sorted(result.as_dict().values()) == sorted(keys)
    state.stop()
