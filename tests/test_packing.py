"""Bit-packed frontier encoding (ISSUE 15 leg (a), tpu/packing.py):
the packed path is ON by default and BIT-EXACT —

* descriptor round-trip: pack(unpack) is the identity over in-domain
  rows incl. SENTINEL lanes, jnp and numpy codecs agree bit-for-bit;
* hand twins (no declared domains) derive the IDENTITY descriptor, so
  the default-on path cannot perturb the pinned lab counts;
* bytes_per_state >= 2x reduction pinned on the generated lab1 and
  paxos specs (13.7x / 13.5x measured — asserted from the descriptor);
* packed-vs-unpacked EXACT parity (unique/explored/verdict/depth) on
  pingpong + lab1, strict and beam(strict=False), device loop vs the
  host-dedup oracle;
* a strict run at a frontier_cap sized in PACKED bytes completes a
  depth the unpacked layout provably cannot fit in the same HBM;
* out-of-domain live values are a loud CapacityOverflow (a wrong spec
  bound must never silently corrupt stored states);
* checkpoints store packed rows + the encoding marker: SIGKILL-mid-run
  resume parity on a packed dump, loud packed->raw cross-resume
  CONVERSION, and loud refusal of a foreign-descriptor dump.

Marked ``capacity2`` (``make capacity2-smoke``)."""

import dataclasses
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import checkpoint as ckpt_mod  # noqa: E402
from dslabs_tpu.tpu import packing as packing_mod  # noqa: E402
from dslabs_tpu.tpu.engine import (SENTINEL, CapacityOverflow,  # noqa: E402
                                   TensorSearch, flatten_state)
from dslabs_tpu.tpu.specs import (clientserver_spec,  # noqa: E402
                                  paxos_spec, pingpong_spec)

pytestmark = pytest.mark.capacity2


def _pruned(p):
    name = next(iter(p.goals))
    return dataclasses.replace(p, goals={},
                               prunes={name: p.goals[name]})


def _lab1():
    return _pruned(clientserver_spec(3, 4).compile())


def _assert_exact(a, b):
    assert a.end_condition == b.end_condition
    assert a.unique_states == b.unique_states
    assert a.states_explored == b.states_explored
    assert a.depth == b.depth


# --------------------------------------------------------- descriptor

def test_roundtrip_with_sentinels_and_negatives():
    """pack/unpack are exact inverses over in-domain values, SENTINEL
    lanes, and negative domains; jnp and numpy codecs agree."""
    proto = _lab1()
    eng = TensorSearch(proto, chunk=64)
    pk = eng._pk
    assert pk is not None and not pk.identity
    rng = np.random.default_rng(0)
    rows = np.zeros((64, pk.lanes), np.int32)
    doms, sents = packing_mod._flat_domains(proto)
    for i, (dom, s_cap) in enumerate(zip(doms, sents)):
        if dom is None:
            rows[:, i] = rng.integers(-2**31, 2**31 - 1, 64)
        else:
            rows[:, i] = rng.integers(dom[0], dom[1] + 1, 64)
        if s_cap:
            mask = rng.random(64) < 0.3
            rows[mask, i] = SENTINEL
    rt_np = pk.unpack_np(pk.pack_np(rows))
    assert (rt_np == rows).all()
    rt_jnp = np.asarray(pk.unpack_jnp(pk.pack_jnp(
        jax.numpy.asarray(rows))))
    assert (rt_jnp == rows).all()
    assert (np.asarray(pk.pack_jnp(jax.numpy.asarray(rows)))
            == pk.pack_np(rows)).all()


def test_hand_twin_derives_identity():
    """No declared domains -> identity descriptor -> the default-on
    packed path cannot touch the hand twins' traced programs."""
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    eng = TensorSearch(make_pingpong_protocol(2), chunk=64)
    assert eng._pk is None
    assert eng.plane == eng.lanes
    pk = packing_mod.derive_packing(eng.p, eng.lanes)
    assert pk.identity and pk.signature() == "raw"
    rows = np.arange(2 * eng.lanes, dtype=np.int32).reshape(2, -1)
    assert (pk.pack_np(rows) == rows).all()
    assert (pk.unpack_np(rows) == rows).all()


@pytest.mark.parametrize("proto,floor", [
    (clientserver_spec(3, 4).compile(), 2.0),
    (paxos_spec(3).compile(), 2.0),
])
def test_bytes_per_state_reduction_floor(proto, floor):
    """ACCEPTANCE: >= 2x bytes/state reduction on the lab1 and paxos
    specs, asserted from the packing descriptor itself."""
    eng = TensorSearch(dataclasses.replace(proto, goals={}), chunk=64)
    pk = eng._pk
    assert pk is not None
    assert pk.pack_ratio >= floor, pk.descriptor()
    assert pk.bytes_per_state * floor <= pk.bytes_per_state_unpacked


# ------------------------------------------------------------- parity

@pytest.mark.parametrize("spec_fn", [
    lambda: _pruned(pingpong_spec(2).compile()),
    _lab1,
])
@pytest.mark.parametrize("strict", [True, False])
def test_packed_vs_unpacked_exact_parity(spec_fn, strict):
    """ACCEPTANCE: bit-identical unique/explored/verdict between the
    packed (default) and unpacked device loops, strict AND beam."""
    kw = dict(chunk=128, frontier_cap=1 << 12, visited_cap=1 << 14,
              strict=strict, max_depth=11)
    packed = TensorSearch(spec_fn(), **kw).run()
    raw = TensorSearch(spec_fn(), packed=False, **kw).run()
    _assert_exact(packed, raw)
    assert packed.visited_overflow == raw.visited_overflow
    assert packed.dropped == raw.dropped
    # The accounting tells the truth about the encoding in force.
    assert packed.bytes_per_state < packed.bytes_per_state_unpacked
    assert raw.bytes_per_state == raw.bytes_per_state_unpacked


def test_packed_device_matches_host_oracle():
    """The packed device loop against the legacy host-dedup parity
    oracle (which keeps raw in-memory rows by design)."""
    kw = dict(chunk=128, frontier_cap=1 << 12, visited_cap=1 << 14,
              max_depth=8)
    dev = TensorSearch(_lab1(), **kw).run()
    host = TensorSearch(_lab1(), use_host_visited=True, **kw).run()
    _assert_exact(dev, host)


def test_packed_capacity_fits_deeper():
    """ACCEPTANCE: at a FIXED HBM byte budget, the packed layout
    completes a depth the unpacked layout provably cannot fit.  lab1's
    depth-9 frontier peaks at 206 rows; the budget holds 256 packed
    rows but only ~18 unpacked ones."""
    eng = TensorSearch(_lab1(), chunk=64)
    pk = eng._pk
    budget_bytes = 256 * pk.bytes_per_state
    raw_rows = budget_bytes // pk.bytes_per_state_unpacked
    assert raw_rows < 206 < 256
    packed = TensorSearch(_lab1(), chunk=64, frontier_cap=256,
                          visited_cap=1 << 14, max_depth=9).run()
    assert packed.end_condition == "DEPTH_EXHAUSTED"
    assert packed.depth == 9
    raw = TensorSearch(_lab1(), chunk=64, packed=False,
                       frontier_cap=max(raw_rows, 1),
                       visited_cap=1 << 14, max_depth=9).run()
    assert raw.end_condition == "CAPACITY_EXHAUSTED"


def test_out_of_domain_is_loud():
    """A live value outside its declared domain is a CapacityOverflow,
    never silent corruption: shrink the client counter's declared
    domain below its real range and run."""
    proto = _pruned(pingpong_spec(2).compile())
    ld = dict(proto.lane_domains)
    nodes = list(ld["nodes"])
    assert nodes[0] == (0, 3)      # client k walks 1..3
    nodes[0] = (0, 1)
    proto = dataclasses.replace(proto,
                                lane_domains=dict(ld, nodes=nodes))
    with pytest.raises(CapacityOverflow):
        TensorSearch(proto, chunk=64, max_depth=8).run()


# -------------------------------------------------------- checkpoints

def test_packed_checkpoint_rows_and_resume(tmp_path):
    """Checkpoint rows are stored PACKED (plane-wide + encoding
    marker) and resume to the identical verdict and counts."""
    pth = str(tmp_path / "packed.ckpt")
    kw = dict(chunk=64, frontier_cap=1 << 11, visited_cap=1 << 14,
              checkpoint_path=pth, checkpoint_every=1)
    full = TensorSearch(_lab1(), chunk=64, frontier_cap=1 << 11,
                        visited_cap=1 << 14, max_depth=9).run()
    partial = TensorSearch(_lab1(), max_depth=5, **kw).run()
    assert partial.depth == 5
    with np.load(pth) as z:
        eng = TensorSearch(_lab1(), chunk=64)
        assert z["frontier"].shape[1] == eng.plane
        assert eng.plane < eng.lanes
        assert "extra__frontier_encoding" in z.files
    eng2 = TensorSearch(_lab1(), max_depth=9, **kw)
    out = eng2.run(resume=True)
    _assert_exact(full, out)
    assert eng2._resumed_from_depth == 5


def test_cross_encoding_resume_loud_conversion(tmp_path):
    """packed dump -> unpacked engine converts with a LOUD warning;
    unpacked dump -> packed engine resumes cleanly; a dump whose
    descriptor this protocol cannot derive is REFUSED."""
    pth = str(tmp_path / "cross.ckpt")
    kw = dict(chunk=64, frontier_cap=1 << 11, visited_cap=1 << 14,
              checkpoint_path=pth, checkpoint_every=1)
    full = TensorSearch(_lab1(), chunk=64, frontier_cap=1 << 11,
                        visited_cap=1 << 14, max_depth=9).run()
    TensorSearch(_lab1(), max_depth=5, **kw).run()
    with pytest.warns(RuntimeWarning, match="PACKED checkpoint"):
        out = TensorSearch(_lab1(), packed=False, max_depth=9,
                           **kw).run(resume=True)
    _assert_exact(full, out)
    # raw dump -> packed engine (re-packs on load, no warning needed).
    pth2 = str(tmp_path / "raw.ckpt")
    kw2 = dict(kw, checkpoint_path=pth2)
    TensorSearch(_lab1(), packed=False, max_depth=5, **kw2).run()
    out2 = TensorSearch(_lab1(), max_depth=9, **kw2).run(resume=True)
    _assert_exact(full, out2)
    # Foreign descriptor: same protocol SHAPE, different declared
    # domains -> different packing signature -> loud refusal.
    TensorSearch(_lab1(), max_depth=5, **kw).run()
    alt = _pruned(clientserver_spec(3, 4).compile())
    ld = dict(alt.lane_domains)
    ld["nodes"] = [None] * len(ld["nodes"])
    alt = dataclasses.replace(alt, lane_domains=ld)
    eng = TensorSearch(alt, max_depth=9, **kw)
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        eng.run(resume=True)


@pytest.mark.fault
def test_sigkill_mid_packed_run_resume_parity(tmp_path):
    """ACCEPTANCE: a packed run SIGKILLed mid-search resumes from its
    packed dump to the identical verdict and exact counts."""
    pth = str(tmp_path / "kill.ckpt")
    full = TensorSearch(_lab1(), chunk=16, frontier_cap=1 << 11,
                        visited_cap=1 << 14, max_depth=9).run()
    child_src = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_compilation_cache_dir',"
        " '/tmp/jaxcache-cpu')\n"
        "import dataclasses\n"
        "from dslabs_tpu.tpu.engine import TensorSearch\n"
        "from dslabs_tpu.tpu.specs import clientserver_spec\n"
        "cs = clientserver_spec(3, 4).compile()\n"
        "cs = dataclasses.replace(cs, goals={},"
        " prunes={'CLIENTS_DONE': cs.goals['CLIENTS_DONE']})\n"
        f"TensorSearch(cs, chunk=16, max_depth=9,"
        f" visited_cap=1 << 14, frontier_cap=2048,"
        f" checkpoint_path={pth!r}, checkpoint_every=1).run()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DSLABS_COMPILE_CACHE="/tmp/jaxcache-cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            d = ckpt_mod.peek_depth(pth)
            if d is not None and d >= 4:
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert ckpt_mod.peek_depth(pth) is not None
    out = TensorSearch(_lab1(), chunk=16, max_depth=9,
                       visited_cap=1 << 14, frontier_cap=2048,
                       checkpoint_path=pth,
                       checkpoint_every=1).run(resume=True)
    _assert_exact(full, out)


# ------------------------------------------------- spill interaction

def test_packed_spill_exact_parity():
    """Packed + host-RAM spill tier + async drain together: exact
    counts at a capped table, rows spooled in the packed encoding."""
    base = TensorSearch(_lab1(), chunk=128, frontier_cap=1 << 12,
                        visited_cap=1 << 14, max_depth=8).run()
    sp = TensorSearch(_lab1(), chunk=16, frontier_cap=1 << 12,
                      visited_cap=256, spill=True, max_depth=8).run()
    _assert_exact(base, sp)
    assert sp.dropped_states == 0
    assert sp.spilled_keys > 0


def test_engine_reuse_across_runs_resets_spill_tier():
    """The warm-up-then-measure reuse pattern: run 2 on the same
    engine must not refilter against run 1's tier (the latent reuse
    bug the capacity2 bench phase exposed)."""
    eng = TensorSearch(_lab1(), chunk=16, frontier_cap=1 << 12,
                       visited_cap=256, spill=True, max_depth=4)
    w = eng.run()
    assert w.spilled_keys >= 0
    eng.max_depth = 8
    out = eng.run()
    base = TensorSearch(_lab1(), chunk=128, frontier_cap=1 << 12,
                        visited_cap=1 << 14, max_depth=8).run()
    _assert_exact(base, out)
