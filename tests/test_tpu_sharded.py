"""Sharded multi-chip BFS: verdict + unique-state parity vs the
single-device engine on the 8-device virtual CPU mesh (conftest.py).

Both configurations run to exhaustion (pruned space / depth limit), so
unique-state counts are exploration-order independent and must match the
single-device engine exactly — any routing/dedup-return regression in the
fingerprint-exchange path (sharded.py) shows up as a count mismatch.
"""

import dataclasses

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh


def _pruned_pingpong():
    pp = make_pingpong_protocol(workload_size=2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


@pytest.mark.parametrize("strict", [True, False])
def test_sharded_exhaustive_parity(strict):
    """SPACE_EXHAUSTED verdict and exact unique counts, both with the
    in-chunk dedup prefilter (strict) and with owner-side-only dedup
    (bench mode, strict=False)."""
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    single = TensorSearch(proto, chunk=64).run()
    sharded = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, strict=strict).run()
    assert sharded.end_condition == single.end_condition == "SPACE_EXHAUSTED"
    assert sharded.unique_states == single.unique_states
    assert sharded.states_explored == single.states_explored
    assert sharded.dropped == 0


def test_sharded_staged_search_from_goal_state():
    """run(initial=...) — the staged-search pattern on the sharded
    engine (PaxosTest.java:886-1096): extract a goal state from phase 1,
    search onward from it, and match the single-device engine's staged
    verdict and counts."""
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    proto = make_clientserver_protocol(n_clients=1, w=2)
    mesh = make_mesh(8)
    phase1 = ShardedTensorSearch(
        proto, mesh, chunk_per_device=32, frontier_cap=1 << 9,
        visited_cap=1 << 12, strict=True).run()
    assert phase1.end_condition == "GOAL_FOUND"

    # Phase 2: from the goal state, the whole pruned space is exhausted.
    proto2 = dataclasses.replace(
        proto, goals={}, prunes={"DONE": proto.goals["CLIENTS_DONE"]})
    single2 = TensorSearch(proto2, chunk=64).run(
        initial=phase1.goal_state)
    sharded2 = ShardedTensorSearch(
        proto2, mesh, chunk_per_device=32, frontier_cap=1 << 9,
        visited_cap=1 << 12, strict=True).run(initial=phase1.goal_state)
    assert (sharded2.end_condition == single2.end_condition
            == "SPACE_EXHAUSTED")
    assert sharded2.unique_states == single2.unique_states
    assert sharded2.states_explored == single2.states_explored


def test_sharded_violation_trace_replays_on_object_twin():
    """A sharded INVARIANT_VIOLATED yields a trace that replays on the
    object twin to a state violating the same predicate — the capability
    the round-2 verdict flagged as missing (production engine explaining
    its own counterexamples)."""
    from dslabs_tpu.testing.predicates import CLIENTS_DONE
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol
    from dslabs_tpu.tpu.trace import reconstruct_object_trace
    from tests.test_tpu_trace import _object_initial

    p = make_clientserver_protocol(n_clients=1, w=1)
    done = p.goals["CLIENTS_DONE"]
    p = dataclasses.replace(
        p, goals={}, invariants={"NEVER_DONE": lambda s, f=done: ~f(s)})
    mesh = make_mesh(8)
    sharded = ShardedTensorSearch(
        p, mesh, chunk_per_device=32, frontier_cap=1 << 9,
        visited_cap=1 << 12, strict=True, record_trace=True)
    outcome = sharded.run()
    assert outcome.end_condition == "INVARIANT_VIOLATED"
    assert outcome.trace, "sharded record_trace must produce an event list"

    single = TensorSearch(p, chunk=64, record_trace=True)
    s_out = single.run()
    assert s_out.end_condition == "INVARIANT_VIOLATED"
    # Same violation DEPTH as the single-device engine (BFS shortest).
    assert len(outcome.trace) == len(s_out.trace)

    never_done = CLIENTS_DONE.negate()
    end = reconstruct_object_trace(sharded, outcome, _object_initial(1, 1),
                                   predicate=never_done)
    r = never_done.check(end)
    assert not r.value, "replayed end state must violate NEVER_DONE"
    assert end.depth <= len(outcome.trace)


def test_checkpoint_resume_identical_outcome(tmp_path):
    """Kill-and-resume semantics (SURVEY §5 frontier checkpointing): a
    search checkpointed every level, interrupted, then resumed from the
    dump must reach the identical verdict, unique count, and explored
    count as an uninterrupted run."""
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    full = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10).run()
    assert full.end_condition == "SPACE_EXHAUSTED"

    ckpt = str(tmp_path / "search.npz")
    # "Crash" after 2 levels: only the checkpoint file survives.
    interrupted = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, max_depth=2,
        checkpoint_path=ckpt, checkpoint_every=1)
    out = interrupted.run()
    assert out.end_condition == "DEPTH_EXHAUSTED"
    import os
    assert os.path.exists(ckpt)

    resumed = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, checkpoint_path=ckpt)
    r = resumed.run(resume=True)
    assert r.end_condition == full.end_condition
    assert r.unique_states == full.unique_states
    assert r.states_explored == full.states_explored

    # The unified dump format (tpu/checkpoint.py) is engine-knob
    # agnostic: a DIFFERENT chunk size resumes the same file to the
    # same verdict and counts (the dump stores semantic search state,
    # not a carry layout).
    other = ShardedTensorSearch(
        proto, mesh, chunk_per_device=32, frontier_cap=1 << 8,
        visited_cap=1 << 10, checkpoint_path=ckpt)
    assert other.has_resumable_checkpoint()
    o = other.run(resume=True)
    assert o.end_condition == full.end_condition
    assert o.unique_states == full.unique_states
    assert o.states_explored == full.states_explored

    # A dump from a different PROTOCOL/CAPACITY config is rejected
    # loudly — CheckpointMismatch naming both fingerprints, never a
    # silent skip (see also tests/test_supervisor.py).
    import dataclasses as _dc

    import pytest as _pytest

    from dslabs_tpu.tpu.checkpoint import CheckpointMismatch

    bigger = _dc.replace(proto, net_cap=proto.net_cap * 2)
    mismatched = ShardedTensorSearch(
        bigger, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, checkpoint_path=ckpt)
    assert not mismatched.has_resumable_checkpoint()
    with _pytest.raises(CheckpointMismatch) as ei:
        mismatched.run(resume=True)
    assert proto.name in str(ei.value)
    assert mismatched._ckpt_fingerprint() in str(ei.value)

    # Resuming a checkpoint saved AFTER the final level (empty frontier)
    # returns the finished verdict instead of crashing.
    done_ckpt = str(tmp_path / "done.npz")
    finished = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, checkpoint_path=done_ckpt,
        checkpoint_every=1)
    f1 = finished.run()
    assert f1.end_condition == "SPACE_EXHAUSTED"
    f2 = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, checkpoint_path=done_ckpt).run(resume=True)
    assert f2.end_condition == "SPACE_EXHAUSTED"
    assert f2.unique_states == f1.unique_states


def test_event_window_spill_exact_counts():
    """A tiny ev_budget with window spill must reproduce the full-grid
    unique/explored counts exactly: events past a window re-step the
    chunk at the next window (sharded.py round-4 spill), so the budget
    is a throughput knob, never a coverage cut."""
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    full = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, strict=True).run()
    # Budget far below the protocol's event grid: forces multi-pass
    # spills on nearly every loaded chunk.
    tiny = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, strict=True, ev_budget=(2, 1),
        ev_spill=True).run()
    assert tiny.end_condition == full.end_condition == "SPACE_EXHAUSTED"
    assert tiny.unique_states == full.unique_states
    assert tiny.states_explored == full.states_explored
    assert tiny.dropped == 0


def test_count_only_final_level_matches_depth_limit():
    """max_depth runs count/check the final level's fresh states without
    building its frontier (noapp); unique/explored totals must equal a
    run whose frontier cap could hold that level."""
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    wide = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, strict=True, max_depth=4).run()
    assert wide.end_condition == "DEPTH_EXHAUSTED"
    single = TensorSearch(proto, chunk=64, max_depth=4).run()
    assert single.end_condition == "DEPTH_EXHAUSTED"
    assert wide.unique_states == single.unique_states
    assert wide.states_explored == single.states_explored
