"""Sharded multi-chip BFS: verdict + unique-state parity vs the
single-device engine on the 8-device virtual CPU mesh (conftest.py).

Both configurations run to exhaustion (pruned space / depth limit), so
unique-state counts are exploration-order independent and must match the
single-device engine exactly — any routing/dedup-return regression in the
fingerprint-exchange path (sharded.py) shows up as a count mismatch.
"""

import dataclasses

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh


def _pruned_pingpong():
    pp = make_pingpong_protocol(workload_size=2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


@pytest.mark.parametrize("strict", [True, False])
def test_sharded_exhaustive_parity(strict):
    """SPACE_EXHAUSTED verdict and exact unique counts, both with the
    in-chunk dedup prefilter (strict) and with owner-side-only dedup
    (bench mode, strict=False)."""
    proto = _pruned_pingpong()
    mesh = make_mesh(8)
    single = TensorSearch(proto, chunk=64).run()
    sharded = ShardedTensorSearch(
        proto, mesh, chunk_per_device=16, frontier_cap=1 << 8,
        visited_cap=1 << 10, strict=strict).run()
    assert sharded.end_condition == single.end_condition == "SPACE_EXHAUSTED"
    assert sharded.unique_states == single.unique_states
    assert sharded.states_explored == single.states_explored
    assert sharded.dropped == 0
