"""The tensor engine as a harness search strategy (tpu/backend.py):
verdict parity against the object checker on the ACTUAL lab search-test
configurations — partitions, timer gating, staged phases, provenance
replay — not twin-shaped parity fixtures.

These are the CI guards for the adapter layer's collapse arguments
(tpu/adapters/paxos.py docstring): every entry runs the same
SearchState + SearchSettings through both strategies and diffs the
verdicts (and, for depth-limited exhaustive entries, the exact
discovered counts)."""

import os

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.utils.flags import GlobalSettings

SLOW = not os.environ.get("DSLABS_SLOW_TESTS")


@pytest.fixture
def tensor_backend():
    GlobalSettings.search_backend = "tensor"
    yield
    GlobalSettings.search_backend = "object"


def _lab0_state():
    import tests.test_lab0_search as L0

    return L0.make_state()


def test_lab0_goal_and_exhaust_verdicts(tensor_backend):
    from dslabs_tpu.testing.predicates import (CLIENTS_DONE, RESULTS_OK)

    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_goal(CLIENTS_DONE))
    res = bfs(_lab0_state(), settings)
    assert res.end_condition == EndCondition.GOAL_FOUND
    goal = res.goal_matching_state
    assert goal.depth > 0
    # The replayed goal state is a REAL object state: the original
    # object predicate holds on it (checked again here, not only
    # inside the backend).
    assert CLIENTS_DONE.check(goal).value

    s2 = (SearchSettings().add_invariant(RESULTS_OK)
          .add_prune(CLIENTS_DONE))
    res2 = bfs(_lab0_state(), s2)
    assert res2.end_condition == EndCondition.SPACE_EXHAUSTED

    GlobalSettings.search_backend = "object"
    obj = bfs(_lab0_state(), s2)
    assert obj.end_condition == EndCondition.SPACE_EXHAUSTED
    assert obj.discovered_count == res2.discovered_count


def test_lab0_violation_verdict(tensor_backend):
    from dslabs_tpu.testing.predicates import NONE_DECIDED

    settings = SearchSettings().add_invariant(NONE_DECIDED)
    res = bfs(_lab0_state(), settings)
    assert res.end_condition == EndCondition.INVARIANT_VIOLATED
    bad = res.invariant_violating_state
    assert bad is not None
    assert not NONE_DECIDED.check(bad).value


def test_no_twin_fails_loudly(tensor_backend):
    from dslabs_tpu.labs.primarybackup.viewserver import ViewServer
    from dslabs_tpu.search.search_state import SearchState
    from dslabs_tpu.testing.generator import NodeGenerator
    from dslabs_tpu.tpu.backend import NoTensorTwin

    gen = NodeGenerator(server_supplier=lambda a: ViewServer(a),
                        client_supplier=lambda a: None,
                        workload_supplier=lambda a: None)
    state = SearchState(gen)
    state.add_server(LocalAddress("viewserver"))
    with pytest.raises(NoTensorTwin):
        bfs(state, SearchSettings())


def test_lab1_multiclient_verdicts(tensor_backend):
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    import tests.test_lab1 as L1
    from dslabs_tpu.search.search_state import SearchState
    from dslabs_tpu.testing.generator import NodeGenerator
    from dslabs_tpu.labs.clientserver.clientserver import (SimpleClient,
                                                           SimpleServer)
    from dslabs_tpu.labs.clientserver.kvstore import KVStore
    from dslabs_tpu.testing.predicates import (CLIENTS_DONE, RESULTS_OK)

    def mk():
        gen = NodeGenerator(
            server_supplier=lambda a: SimpleServer(a, KVStore()),
            client_supplier=lambda a: SimpleClient(a, L1.SERVER),
            workload_supplier=lambda a: None)
        state = SearchState(gen)
        state.add_server(L1.SERVER)
        for i in (1, 2):
            state.add_client_worker(
                LocalAddress(f"client{i}"),
                kv_workload([f"APPEND:foo:{i}"]))
        return state

    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_goal(CLIENTS_DONE).max_time(60))
    res = bfs(mk(), settings)
    assert res.end_condition == EndCondition.GOAL_FOUND

    GlobalSettings.search_backend = "object"
    obj = bfs(mk(), settings)
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert obj.goal_matching_state.depth == res.goal_matching_state.depth


@pytest.mark.skipif(SLOW, reason="lab3 twin compile is slow on CPU "
                    "(DSLABS_SLOW_TESTS=1 enables)")
def test_lab3_partitioned_staged_phases(tensor_backend):
    """The test20-shaped staged search: partitioned goal phase, then
    CLIENTS_DONE from the provenance-replayed goal state, with
    goal-depth parity against the object checker."""
    import tests.test_lab3_paxos as T

    def mk():
        state = T.make_search_state(3)
        state.add_client_worker(
            T.client(1), T.kv_workload(["PUT:foo:bar", "GET:foo"],
                                       ["PutOk", "bar"]))
        return state

    settings = SearchSettings().max_time(120)
    settings.partition(T.server(1), T.server(2), T.client(1))
    settings.add_invariant(T.RESULTS_OK)
    settings.add_invariant(T.LOGS_CONSISTENT_ALL_SLOTS)
    settings.add_goal(T.NONE_DECIDED.negate())
    res = bfs(mk(), settings)
    assert res.end_condition == EndCondition.GOAL_FOUND
    goal = res.goal_matching_state

    s2 = SearchSettings().max_time(120)
    s2.add_invariant(T.RESULTS_OK)
    s2.add_invariant(T.LOGS_CONSISTENT_ALL_SLOTS)
    s2.add_goal(T.CLIENTS_DONE)
    res2 = bfs(goal, s2)
    assert res2.end_condition == EndCondition.GOAL_FOUND

    GlobalSettings.search_backend = "object"
    obj = bfs(mk(), settings)
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert obj.goal_matching_state.depth == goal.depth


@pytest.mark.skipif(SLOW, reason="lab3 twin compile is slow on CPU "
                    "(DSLABS_SLOW_TESTS=1 enables)")
def test_lab3_depth_limited_count_parity(tensor_backend):
    """Depth-limited exhaustive runs are order-independent: the tensor
    backend's discovered count must equal the object checker's exactly
    under the SAME settings (partition + timer gating) — the live guard
    for the adapter's state-collapse argument."""
    import tests.test_lab3_paxos as T

    def mk():
        state = T.make_search_state(3)
        state.add_client_worker(T.client(1),
                                T.kv_workload(["PUT:foo:bar"]))
        return state

    settings = SearchSettings().max_time(120).set_max_depth(4)
    settings.partition(T.server(1), T.server(2), T.client(1))
    settings.deliver_timers(T.server(3), False)
    settings.add_invariant(T.LOGS_CONSISTENT_ALL_SLOTS)
    res = bfs(mk(), settings)
    assert res.end_condition == EndCondition.SPACE_EXHAUSTED

    GlobalSettings.search_backend = "object"
    obj = bfs(mk(), settings)
    assert obj.end_condition == EndCondition.SPACE_EXHAUSTED
    assert obj.discovered_count == res.discovered_count


@pytest.mark.skipif(SLOW, reason="lab3 twin compile is slow on CPU "
                    "(DSLABS_SLOW_TESTS=1 enables)")
def test_lab3_singleton_goal_parity(tensor_backend):
    """test27's singleton-group search: the twin's n == 1 win-on-own-vote
    cascade (election and agreement complete inside one transition, like
    the object's synchronous self-deliveries) reaches CLIENTS_DONE."""
    import tests.test_lab3_paxos as T

    def mk():
        state = T.make_search_state(1)
        state.add_client_worker(
            T.client(1), T.kv_workload(["PUT:foo:bar", "GET:foo"],
                                       ["PutOk", "bar"]))
        return state

    settings = SearchSettings().max_time(60)
    settings.add_invariant(T.RESULTS_OK)
    settings.add_invariant(T.LOGS_CONSISTENT_ALL_SLOTS)
    settings.add_goal(T.CLIENTS_DONE)
    res = bfs(mk(), settings)
    assert res.end_condition == EndCondition.GOAL_FOUND

    GlobalSettings.search_backend = "object"
    obj = bfs(mk(), settings)
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert obj.goal_matching_state.depth == res.goal_matching_state.depth


def test_lab2_single_server_verdicts(tensor_backend):
    """test16-shaped lab2 search through the tensor backend: the
    ViewServer + PBServer + client stack reaches CLIENTS_DONE with the
    object checker's goal depth."""
    import tests.test_lab2_pb as L2
    from dslabs_tpu.testing.predicates import (CLIENTS_DONE, RESULTS_OK)

    def mk():
        workload = L2.kv_workload(["PUT:foo:bar", "GET:foo"],
                                  ["PutOk", "bar"])
        state = L2.make_search_state(workload)
        state.add_server(L2.server(1))
        state.add_client_worker(L2.client(1))
        return state

    settings = (SearchSettings().add_invariant(RESULTS_OK)
                .add_goal(CLIENTS_DONE).max_time(90))
    res = bfs(mk(), settings)
    assert res.end_condition == EndCondition.GOAL_FOUND

    GlobalSettings.search_backend = "object"
    obj = bfs(mk(), settings)
    assert obj.end_condition == EndCondition.GOAL_FOUND
    assert obj.goal_matching_state.depth == res.goal_matching_state.depth


def test_lab4_two_phase_tensor(tensor_backend):
    """The ShardStorePart1Test.test10 flow end-to-end on the tensor
    strategy: the JOIN phase runs on the join twin, its goal state
    materialises as a real object state, and the MAIN phase validates
    that state as the canonical joined root of the shardstore twin
    (ShardStoreBinding.derive_root) — goal found, then the done-pruned
    depth-limited space matches the object checker's count exactly."""
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.testing.predicates import (CLIENTS_DONE, RESULTS_OK,
                                               client_done)
    import tests.test_lab4_shardstore as lab4

    def staged():
        state = lab4.make_search(1, 1, 1, 10)
        joined = lab4._joined_state(state, 1)
        joined.add_client_worker(
            LocalAddress("client1"),
            kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"]))
        return joined

    # Phase 1 (inside _joined_state) already ran on the tensor backend;
    # the staged state must carry join-twin provenance.
    joined = staged()
    assert getattr(joined, "_tensor_provenance", None) is not None
    assert joined._tensor_provenance.key[0] == "ss-join"
    assert client_done(lab4.CCA).check(joined).value

    settings = SearchSettings().max_time(240)
    settings.add_invariant(RESULTS_OK).add_goal(CLIENTS_DONE)
    settings.node_active(lab4.CCA, False)
    settings.deliver_timers(lab4.CCA, False)
    settings.deliver_timers(lab4.shard_master(1), False)
    res = bfs(joined, settings)
    assert res.end_condition == EndCondition.GOAL_FOUND
    goal = res.goal_matching_state
    assert CLIENTS_DONE.check(goal).value

    # Done-pruned depth-limited exhaust: exact count parity vs object.
    settings.clear_goals().add_prune(CLIENTS_DONE)
    settings.set_max_depth(joined.depth + 4)
    res2 = bfs(joined, settings)
    assert res2.end_condition == EndCondition.SPACE_EXHAUSTED

    GlobalSettings.search_backend = "object"
    joined_obj = staged()
    obj = bfs(joined_obj, settings)
    assert obj.end_condition == EndCondition.SPACE_EXHAUSTED
    assert obj.discovered_count == res2.discovered_count


def test_lab1_infinite_workload_tensor(tensor_backend):
    """ClientServerPart2Test.test11's shape on the tensor strategy with
    DERANDOMIZED streams (round-4 verdict item 8): exhaust verdicts,
    the add-a-client staged reuse, and — the part the old global-rng
    streams refused — terminal-state decode through the counter-mode
    command reconstruction (_StreamPairs)."""
    from dslabs_tpu.labs.clientserver.kv_workload import (
        different_keys_infinite_workload)
    from dslabs_tpu.labs.clientserver.kvstore import Put
    from dslabs_tpu.search.search import dfs
    from dslabs_tpu.testing.predicates import (RESULTS_OK,
                                               client_has_results)
    import tests.test_lab1 as L1

    state = L1._search_state(
        workload_factory=lambda: different_keys_infinite_workload())
    settings = SearchSettings().add_invariant(RESULTS_OK)
    settings.max_time(5)
    res = bfs(state, settings)
    assert res.end_condition in (EndCondition.TIME_EXHAUSTED,
                                 EndCondition.SPACE_EXHAUSTED)

    settings.set_max_depth(1000).max_time(5)
    res = dfs(state, settings)
    assert not res.terminal_found()

    state.add_client_worker(LocalAddress("client2"),
                            different_keys_infinite_workload())
    res = dfs(state, settings)
    assert not res.terminal_found()

    # Terminal-state materialisation through the stream reconstruction:
    # the goal state's results must be the ACTUAL commands the object
    # client drew — the counter-mode stream's first Put.
    state2 = L1._search_state(
        workload_factory=lambda: different_keys_infinite_workload())
    s2 = (SearchSettings().add_invariant(RESULTS_OK)
          .add_goal(client_has_results(LocalAddress("client1"), 1))
          .max_time(60))
    res2 = bfs(state2, s2)
    assert res2.end_condition == EndCondition.GOAL_FOUND
    goal = res2.goal_matching_state
    worker = goal.client_workers()[LocalAddress("client1")]
    assert len(worker.results) >= 1
    sent = worker.sent_commands[0]
    assert isinstance(sent, Put) and sent.key.startswith("client1-")


def test_lab1_deep_probe_dfs(tensor_backend):
    """The dfs-routed rollout probe (engine.random_rollouts via
    backend._rollout_probe): a violation that only exists ~24 levels
    deep — far past what a level-by-level search clears in this time
    budget — must still be found, with a real replayed object state
    (the round-4 advisor's RandomDFS depth-reach gap, closed)."""
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.search.search import dfs
    from dslabs_tpu.testing.predicates import client_has_results
    import tests.test_lab1 as L1

    w = 10
    state = L1._search_state(workload_factory=lambda: kv_workload(
        [f"PUT:key{i}:v{i}" for i in range(1, w + 1)]))
    settings = SearchSettings().max_time(45).set_max_depth(1000)
    settings.add_invariant(
        client_has_results(LocalAddress("client1"), w - 1).negate())
    res = dfs(state, settings)
    assert res.end_condition == EndCondition.INVARIANT_VIOLATED
    bad = res.invariant_violating_state
    assert bad is not None
    assert len(bad.client_workers()[LocalAddress("client1")].results) \
        >= w - 1
    assert bad.depth >= 2 * (w - 1)       # deep, as constructed
