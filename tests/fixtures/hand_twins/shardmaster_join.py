"""Tensorised twin of lab 4's JOIN phase: one shard master (a lone
PaxosServer running the ShardMaster application) + the config controller
(a PaxosClient ClientWorker) driving G sequential Join commands, with
every store server cut off (ShardStoreBaseTest.java:209-220 narrows the
partition to {CCA, shard masters} and suppresses store-server timers —
tests/test_lab4_shardstore.py _joined_state mirrors it).

Why the state collapses (labs/paxos/paxos.py):

* A ONE-server Paxos group decides synchronously: ``init`` self-elects
  immediately (paxos.py:201-205), ``_send_to_all`` delivers the leader's
  own P1a/P2a/P2b locally, majority = 1 — so a fresh PaxosRequest is
  proposed, chosen, executed, and GC'd inside the handler call.  The
  replicated log is empty in every reachable state; what remains is the
  decided-slot COUNT, the per-client AMO high-water mark, and the
  ``heard_from_leader`` flag (set by the self-delivered P2a on every
  fresh proposal, cleared by ElectionTimer; paxos.py:261-265 never
  re-elects a leader whose ballot is its own, so the ballot from the
  init self-election is CONSTANT).

* ``on_HeartbeatTimer`` for a lone server is a pure re-arm:
  ``_send_heartbeats`` broadcasts to peers only (paxos.py:412-414) and
  every slot is already chosen, so the P2a retransmit loop is empty.

* The client (PaxosClient, paxos.py:490-520) broadcasts the pending
  command to its single master and retries on ClientTimer; Join results
  are Ok() for distinct groups — value-collapsed like every app result
  (the adapter re-checks RESULTS_OK object-side via the backend's
  sampled exhaust re-check).

Node lanes (flat): [mc, amo, heard, k]
  mc     master decided-slot count
  amo    master's AMO high-water mark for the controller
  heard  master heard_from_leader
  k      controller workload index (W+1 = done)
Message lanes [tag, seq]: REQ = PaxosRequest(AMOCommand(Join_seq, cca,
seq)), REP = PaxosReply(AMOResult(Ok, seq)).
Timer lanes [tag, mn, mx, p0]: ELECTION / HEARTBEAT (master),
CLIENT(seq) (controller).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_join_protocol", "REQ", "REP", "T_ELECTION",
           "T_HEARTBEAT", "T_CLIENT", "CLIENT_MS", "ELECTION_MIN",
           "ELECTION_MAX", "HEARTBEAT_MS"]

REQ, REP = 0, 1
T_CLIENT, T_ELECTION, T_HEARTBEAT = 1, 2, 3

CLIENT_MS = 100                         # paxos.py CLIENT_RETRY_MILLIS
ELECTION_MIN, ELECTION_MAX = 150, 300   # paxos.py ELECTION_MILLIS_*
HEARTBEAT_MS = 50


def make_join_protocol(n_joins: int, net_cap: int = 12,
                       timer_cap: int = 4) -> TensorProtocol:
    W = n_joins
    MC, AMO, HEARD, K = range(4)
    MASTER, CLIENT = 0, 1
    MW, TW = 2, 4

    def msg_row(cond, tag, seq):
        rec = jnp.stack([jnp.asarray(x, jnp.int32) for x in (tag, seq)])
        return jnp.where(cond, rec,
                         jnp.full((MW,), SENTINEL, jnp.int32))[None]

    def timer_row(cond, node, tag, mn, mx, p0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32)
                         for x in (node, tag, mn, mx, p0)])
        return jnp.where(cond, rec,
                         jnp.full((1 + TW,), SENTINEL, jnp.int32))[None]

    blank_msg = jnp.full((1, MW), SENTINEL, jnp.int32)
    blank_set = jnp.full((1, 1 + TW), SENTINEL, jnp.int32)

    def step_message(nodes, msg):
        tag, seq = msg[0], msg[1]
        sends = []
        tsets = []

        # ---- REQ -> master (paxos.py handle_PaxosRequest; n=1: a fresh
        # command is chosen+executed+GC'd inline, and the self-delivered
        # P2a sets heard_from_leader)
        is_req = tag == REQ
        last = nodes[AMO]
        fresh = is_req & (seq > last)
        nodes = nodes.at[AMO].set(
            jnp.where(fresh, seq, last).astype(jnp.int32))
        nodes = nodes.at[MC].set(
            jnp.where(fresh, nodes[MC] + 1, nodes[MC]).astype(jnp.int32))
        nodes = nodes.at[HEARD].set(
            jnp.where(fresh, 1, nodes[HEARD]).astype(jnp.int32))
        # reply for fresh or exactly-cached seq (AMO re-reply)
        sends.append(msg_row(is_req & (seq >= last), REP, seq))

        # ---- REP -> controller (ClientWorker pumps the next Join)
        k = nodes[K]
        match = (tag == REP) & (seq == k) & (k <= W)
        k2 = jnp.where(match, k + 1, k)
        nodes = nodes.at[K].set(k2.astype(jnp.int32))
        has_next = match & (k2 <= W)
        sends.append(msg_row(has_next, REQ, k2))
        tsets.append(timer_row(has_next, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k2))

        sends = jnp.concatenate(
            sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(
            tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        sends = []
        tsets = []

        # ---- ElectionTimer (paxos.py:261-265): the lone master is its
        # own decided leader, so only heard resets; always re-arms.
        is_el = (node_idx == MASTER) & (tag == T_ELECTION)
        nodes = nodes.at[HEARD].set(
            jnp.where(is_el, 0, nodes[HEARD]).astype(jnp.int32))
        tsets.append(timer_row(is_el, MASTER, T_ELECTION,
                               ELECTION_MIN, ELECTION_MAX, 0))

        # ---- HeartbeatTimer: no peers, nothing in flight — pure re-arm.
        is_hb = (node_idx == MASTER) & (tag == T_HEARTBEAT)
        tsets.append(timer_row(is_hb, MASTER, T_HEARTBEAT,
                               HEARTBEAT_MS, HEARTBEAT_MS, 0))

        # ---- ClientTimer (paxos.py:505-520): re-broadcast the pending
        # request and re-arm while it is still outstanding.
        k = nodes[K]
        live = ((node_idx == CLIENT) & (tag == T_CLIENT) & (p0 == k)
                & (k <= W))
        sends.append(msg_row(live, REQ, k))
        tsets.append(timer_row(live, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k))

        sends = jnp.concatenate(
            sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(
            tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    MAX_SENDS = 2
    MAX_SETS = 3

    def init_nodes():
        # Master self-elected at init (heard still False — handle_P1a/P1b
        # do not touch heard_from_leader); the controller's first Join is
        # in flight.
        nodes = np.zeros((4,), np.int32)
        nodes[K] = 1
        return nodes

    def init_messages():
        return np.array([[REQ, 1]], np.int32)

    def init_timers():
        return np.array([
            [MASTER, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0],
            [MASTER, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, 0],
            [CLIENT, T_CLIENT, CLIENT_MS, CLIENT_MS, 1],
        ], np.int32)

    def msg_dest(msg):
        return jnp.where(msg[0] == REQ, MASTER, CLIENT).astype(jnp.int32)

    def clients_done(state):
        return state["nodes"][K] == W + 1

    return TensorProtocol(
        name=f"shardmaster-join-w{W}",
        n_nodes=2,
        node_width=4,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        goals={"CLIENTS_DONE": clients_done},
    )
