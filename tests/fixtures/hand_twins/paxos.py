"""Tensorised twin of lab 3 multi-Paxos — the north-star bench workload
(BASELINE.json: lab3-paxos BFS states/min).

Mirrors the object implementation in dslabs_tpu/labs/paxos/paxos.py
handler-for-handler, including everything that participates in object state
equality: the log, ballot/leader/heard flags, raw P1b vote contents,
P2b vote bitmasks, proposed_seq, peer_executed + GC frontiers, and the AMO
application state.  Handler cascades (leader self-accept/self-vote on
P2a/P2b, execution chains with client replies) are inlined exactly as the
object's local ``deliver_message`` calls are.

Performance-critical layout decision: the handlers do NOT thread the flat
``[NW]`` lane vector through hundreds of functional updates — that made
each vmapped update re-read/re-write the whole [batch, NW] array and one
chunk step moved ~40 GB of HBM traffic (measured round 2, 5.5 s for a
24k-successor chunk on a v5e).  Instead ``_unpack`` slices the vector once
into a dict of small per-field arrays (ballot [n], log [n, S, 4], votes
[n, n, 1+4S], ...), every update touches only its [batch, <=1+4S] column,
and ``_repack`` concatenates the lanes back in the exact original order —
so fingerprints, equality, and the engine contract are unchanged.

Workload model: ``n_clients`` clients each Put their own key W times
(value = f(seq)), so the KVStore + AMO state collapses to one
last-executed-seq lane per client.  Command ids: ``c * W + s`` (1-based);
0 = no-op.

Packed lanes per server (offsets from the server's base):
  0 ballot (round * n + leader_idx)   4 executed_through
  1 leader flag                       5 cleared_through
  2 heard_from_leader                 6 gc_through
  3 slot_in                           7 peer_executed bitmask
  8..8+n-1      peer_executed values
  AMO           n_clients lanes: last executed seq per client
  PROP          n_clients lanes: proposed_seq (0 = none)
  P2B           S lanes: vote bitmask per slot
  LOG           S x [exists, ballot, cmd, chosen]
  VOTES         n x [have, S x [exists, ballot, cmd, chosen]]  raw P1b votes

Clients contribute one lane each: k = seq in flight (W+1 = done).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_paxos_protocol"]

# Message tags
REQ, P1A, P1B, P2A, P2B, HB, HBR, CREQ, CREP, REPLY = range(10)
# Timer tags
T_ELECTION, T_HEARTBEAT, T_CLIENT = 1, 2, 3
# Exception code: a ballot/cmd reached a _pack_entry field width — the
# search ends EXCEPTION_THROWN instead of silently aliasing states.
EXC_PACK_WIDTH = 101

ELECTION_MIN, ELECTION_MAX = 150, 300
HEARTBEAT_MS = 50
CLIENT_MS = 100


def paxos_layout(n: int, n_clients: int, max_slots: int) -> dict:
    """Server lane offsets of the packed node vector (see the module
    docstring's lane table).  Shared by the twin factory and the harness
    backend's lane predicates (tpu/adapters/paxos.py) so the two can
    never drift."""
    S, NC = max_slots, n_clients
    PEER = 8
    AMO = PEER + n
    PROP = AMO + NC
    P2BV = PROP + NC
    LOG = P2BV + S
    VOTES = LOG + 4 * S
    SW = VOTES + n * (1 + 4 * S)
    return {"PEER": PEER, "AMO": AMO, "PROP": PROP, "P2BV": P2BV,
            "LOG": LOG, "VOTES": VOTES, "SW": SW,
            "NW": n * SW + NC, "N_NODES": n + NC}


def make_paxos_protocol(n: int = 3, n_clients: int = 1, w: int = 1,
                        max_slots: int = 2, net_cap: int = 64,
                        timer_cap: int = 8) -> TensorProtocol:
    S = max_slots
    NC = n_clients
    maj = n // 2 + 1

    # ---- server lane offsets (paxos_layout is the single source)
    _L = paxos_layout(n, NC, S)
    PEER, AMO, PROP = _L["PEER"], _L["AMO"], _L["PROP"]
    P2BV, LOG, VOTES = _L["P2BV"], _L["LOG"], _L["VOTES"]
    SW, NW, N_NODES = _L["SW"], _L["NW"], _L["N_NODES"]

    # ---- message layout: [tag, frm, to, p0..]  payload:
    #   REQ:   [client, seq]
    #   P1A:   [ballot]
    #   P1B:   [ballot, S x packed log entry]  (see _pack_entry: one
    #          int32 per slot — message width drives the engine's
    #          set-insert merge cost AND the HBM row width, the round-3
    #          measured bottleneck; the unpacked 4-lane form made MW 16
    #          and the network block 76% of every state row)
    #   P2A:   [ballot, slot, cmd]
    #   P2B:   [ballot, slot]
    #   HB:    [ballot, commit, gc]     HBR: [ballot, executed]
    #   CREQ:  [from_slot]              CREP: [base, count, S x cmd]
    #   REPLY: [client, seq]
    PAYLOAD = max(1 + S, 3, 2 + S)
    MW = 3 + PAYLOAD

    def _pack_entry(ex, lb, cmd, ch):
        """(exists, ballot, cmd, chosen) -> one int32: bijective within
        ballot < 2^12 (300+ elections — unreachable at search depths) and
        cmd < 2^17 (cmd ids are <= n_clients * w).  Bijectivity keeps
        state equality exact; all fields nonneg so the packed lane stays
        nonneg and lexicographic network order well-defined.  Width
        violations are guarded loudly: every step sets the exception lane
        to EXC_PACK_WIDTH if any ballot/cmd in the state reaches the pack
        limits (see _pack_guard) — distinct states can never silently
        alias."""
        return (ex | (ch << 1) | (lb << 2) | (cmd << 14)).astype(jnp.int32)

    def _pack_guard(st):
        """int32 exception code: EXC_PACK_WIDTH when any ballot or cmd id
        anywhere in the state has reached a _pack_entry field width (the
        NEXT pack would alias distinct states).  Checked on every step so
        the search ends loudly (EXCEPTION_THROWN) instead of undercounting
        states — the tensor analog of CapacityOverflow for a packed
        lane."""
        over = (jnp.any(st["b"] >= (1 << 12))
                | jnp.any(st["log"][:, :, 1] >= (1 << 12))
                | jnp.any(st["log"][:, :, 2] >= (1 << 17)))
        return jnp.where(over, EXC_PACK_WIDTH, 0).astype(jnp.int32)

    def _unpack_entry(v):
        return v & 1, (v >> 2) & 0xFFF, v >> 14, (v >> 1) & 1
    TW = 4  # [tag, min, max, p0]
    # Exact static send/set row budgets (finalize() asserts the count at
    # trace time; a miscount fails loudly, never truncates).  Server rows:
    # req 2 + req-p2a (n-1) + p1a 1 + p1b [S(n-1) + S + (n-1)] + p2a 1 +
    # p2b S + hb 2 + creq 1 + crep S.  Keeping this tight matters: the
    # engine's set-insert merge is O(MAX_SENDS x NET_CAP) compares per
    # (state, event) pair, so every blank pad row widens the hot loop.
    SRV_SENDS = 7 + 2 * (n - 1) + S * (n - 1) + 3 * S
    if n == 1:
        # Singleton: every send_p2a call site completes the agreement
        # inline (choose + exec_chain), so each of the up-to-(2S + 2)
        # call sites can add S reply rows on top of the base budget.
        SRV_SENDS += (2 * S + 2) * S
    # n == 1: the ElectionTimer handler runs the full win cascade (self
    # vote = majority), adding the leader's heartbeat re-arm as a third
    # set row on the timer path.
    SRV_SETS = 3 if n == 1 else 2
    CLI_SENDS, CLI_SETS = n, 1
    MAX_SENDS = SRV_SENDS + CLI_SENDS
    MAX_SETS = SRV_SETS + CLI_SETS

    def cmd_id(client, seq):
        return client * w + seq  # 1-based; 0 = none/noop

    def cmd_client(cmd):
        return (cmd - 1) // w

    def cmd_seq(cmd):
        return (cmd - 1) % w + 1

    # ------------------------------------------------------------- builders

    def mk_msg(tag, frm, to, payload):
        lanes = [jnp.asarray(tag, jnp.int32), jnp.asarray(frm, jnp.int32),
                 jnp.asarray(to, jnp.int32)]
        for v in payload:
            lanes.append(jnp.asarray(v, jnp.int32))
        while len(lanes) < MW:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    class Sends:
        """Collects conditional sends; blank rows are all-SENTINEL so blocks
        from mutually exclusive branches merge by elementwise minimum."""

        def __init__(self):
            self.rows = []

        def add(self, cond, tag, frm, to, payload):
            rec = mk_msg(tag, frm, to, payload)
            blank = jnp.full((MW,), SENTINEL, jnp.int32)
            self.rows.append(jnp.where(cond, rec, blank))

        def finalize(self, count):
            rows = list(self.rows)
            assert len(rows) <= count, (len(rows), count)
            blank = jnp.full((MW,), SENTINEL, jnp.int32)
            while len(rows) < count:
                rows.append(blank)
            return jnp.stack(rows)

    class Sets:
        def __init__(self):
            self.rows = []

        def add(self, cond, node, tag, mn, mx, p0):
            rec = jnp.stack([
                jnp.asarray(node, jnp.int32), jnp.asarray(tag, jnp.int32),
                jnp.asarray(mn, jnp.int32), jnp.asarray(mx, jnp.int32),
                jnp.asarray(p0, jnp.int32)])
            blank = jnp.full((1 + TW,), SENTINEL, jnp.int32)
            self.rows.append(jnp.where(cond, rec, blank))

        def finalize(self, count):
            rows = list(self.rows)
            assert len(rows) <= count, (len(rows), count)
            blank = jnp.full((1 + TW,), SENTINEL, jnp.int32)
            while len(rows) < count:
                rows.append(blank)
            return jnp.stack(rows)

    # -------------------------------------------------- unpack/repack state
    # st is a plain dict of small arrays; helpers mutate it in place (the
    # values themselves stay immutable jnp arrays — data flow is functional).
    #   b, ld, hd, si, ex, cl, gc, pm : [n]      scalars per server
    #   peer [n, n]  amo [n, NC]  prop [n, NC]  p2bv [n, S]
    #   log [n, S, 4]  votes [n, n, 1+4S]  k [NC]

    def _unpack(nodes):
        def per(off, width):
            return jnp.stack([nodes[i * SW + off:i * SW + off + width]
                              for i in range(n)])

        def sc(off):
            return jnp.stack([nodes[i * SW + off] for i in range(n)])

        return {
            "b": sc(0), "ld": sc(1), "hd": sc(2), "si": sc(3),
            "ex": sc(4), "cl": sc(5), "gc": sc(6), "pm": sc(7),
            "peer": per(PEER, n), "amo": per(AMO, NC),
            "prop": per(PROP, NC), "p2bv": per(P2BV, S),
            "log": per(LOG, 4 * S).reshape(n, S, 4),
            "votes": per(VOTES, n * (1 + 4 * S)).reshape(n, n, 1 + 4 * S),
            "k": nodes[n * SW:],
        }

    def _repack(st):
        parts = []
        for i in range(n):
            parts.extend([
                st["b"][i][None], st["ld"][i][None], st["hd"][i][None],
                st["si"][i][None], st["ex"][i][None], st["cl"][i][None],
                st["gc"][i][None], st["pm"][i][None],
                st["peer"][i], st["amo"][i], st["prop"][i], st["p2bv"][i],
                st["log"][i].reshape(4 * S),
                st["votes"][i].reshape(n * (1 + 4 * S)),
            ])
        parts.append(st["k"])
        return jnp.concatenate(parts).astype(jnp.int32)

    def _set(st, key, i, val):
        st[key] = st[key].at[i].set(jnp.asarray(val, jnp.int32))

    # One-hot row access: every traced-index read/write below goes through
    # these (select/sum over a static axis).  `.at[i, traced].set` /
    # `row[traced]` lowered to per-pair dynamic gathers/scatters, which
    # materialise at ~1 GB/s under the engine's flat vmap on TPU — the
    # round-2 chunk-step bottleneck.  The leading index `i` is always a
    # Python int (the per-node unroll), so `.at[i].set(row)` remains a
    # static update.

    def oh_get(row, idx, size):
        """row [size, ...] or [size]; traced idx -> row[idx], 0 if out of
        range."""
        m = (jnp.arange(size) == idx)
        return jnp.sum(m.reshape((size,) + (1,) * (row.ndim - 1)) * row,
                       axis=0)

    def oh_put(row, idx, size, val, cond):
        """row with row[idx] = val where cond (no-op when idx out of
        range)."""
        m = (jnp.arange(size) == idx) & cond
        mb = m.reshape((size,) + (1,) * (row.ndim - 1))
        return jnp.where(mb, jnp.asarray(val, row.dtype), row)

    def log_get(st, i, slot):
        """slot is 1-based traced int; returns [4] = (exists, ballot, cmd,
        chosen); all-zeros when out of range (callers mask)."""
        return oh_get(st["log"][i], slot - 1, S)

    def log_set(st, i, slot, entry, cond):
        row = oh_put(st["log"][i], slot - 1, S,
                     jnp.asarray(entry, jnp.int32), cond)
        st["log"] = st["log"].at[i].set(row)

    def exec_chain(st, i, sends: Sends, cond):
        """Execute contiguous chosen slots (paxos.py _execute_chosen),
        sending client replies; leader updates its own peer_executed."""
        for _ in range(S):
            ex = st["ex"][i]
            e = log_get(st, i, ex + 1)
            can = cond & (ex + 1 <= S) & (e[0] == 1) & (e[3] == 1)
            _set(st, "ex", i, jnp.where(can, ex + 1, ex))
            cmd = e[2]
            has_cmd = can & (cmd != 0)
            cl = cmd_client(cmd).clip(0, NC - 1)
            sq = cmd_seq(cmd)
            last = oh_get(st["amo"][i], cl, NC)
            reply = has_cmd & (sq >= last)
            newlast = jnp.where(has_cmd & (sq > last), sq, last)
            st["amo"] = st["amo"].at[i].set(
                oh_put(st["amo"][i], cl, NC, newlast, has_cmd))
            sends.add(reply, REPLY, i, n + cl, [cl, sq])
        # Leader bookkeeping + GC (object: peer_executed[self]=exec; gc)
        is_leader = cond & (st["ld"][i] == 1) & (st["b"][i] % n == i)
        _leader_exec_update(st, i, is_leader)

    def _leader_exec_update(st, i, is_leader):
        ex = st["ex"][i]
        mask = st["pm"][i]
        _set(st, "pm", i, jnp.where(is_leader, mask | (1 << i), mask))
        cur = st["peer"][i][i]
        st["peer"] = st["peer"].at[i, i].set(
            jnp.where(is_leader, ex, cur).astype(jnp.int32))
        maybe_gc(st, i, is_leader)

    def maybe_gc(st, i, cond):
        mask = st["pm"][i]
        have_all = mask == (1 << n) - 1
        floor = st["peer"][i][0]
        for j in range(1, n):
            floor = jnp.minimum(floor, st["peer"][i][j])
        do = cond & have_all & (floor > st["gc"][i])
        _set(st, "gc", i, jnp.where(do, floor, st["gc"][i]))
        gc_to(st, i, floor, do)

    def gc_to(st, i, through, cond):
        through = jnp.minimum(through, st["ex"][i])
        cleared = st["cl"][i]
        do = cond & (through > cleared)
        for slot in range(1, S + 1):
            clear = do & (jnp.asarray(slot) > cleared) & \
                (jnp.asarray(slot) <= through)
            log_set(st, i, jnp.asarray(slot), [0, 0, 0, 0], clear)
        _set(st, "cl", i, jnp.where(do, through, cleared))

    def accept_p2a(st, i, ballot, slot, cmd, cond):
        """The acceptor body of handle_P2a (ballot already >= checked)."""
        e = log_get(st, i, slot)
        write = cond & (slot > st["cl"][i]) & ~((e[0] == 1) & (e[3] == 1))
        log_set(st, i, slot, [1, ballot, cmd, 0], write)

    def record_own_p2b(st, i, ballot, slot, cond):
        """Leader self-vote (send_p2a -> self P2a -> self P2b), which can
        never reach majority alone for n >= 2 (no cascade)."""
        e = log_get(st, i, slot)
        ok = (cond & (st["b"][i] == ballot)
              & (e[0] == 1) & (e[3] == 0) & (e[1] == ballot))
        row = st["p2bv"][i]
        st["p2bv"] = st["p2bv"].at[i].set(jnp.where(
            (jnp.arange(S) == slot - 1) & ok, row | (1 << i), row))

    def send_p2a(st, i, slot, sends: Sends, cond):
        """Broadcast P2a for log[slot] + inline self-accept/self-vote."""
        e = log_get(st, i, slot)
        ballot = st["b"][i]
        for j in range(n):
            if j == i:
                continue
            sends.add(cond, P2A, i, j, [ballot, slot, e[2]])
        accept_p2a(st, i, ballot, slot, e[2], cond)
        _set(st, "hd", i, jnp.where(cond, 1, st["hd"][i]))
        record_own_p2b(st, i, ballot, slot, cond)
        if n == 1:
            # Singleton: the self-vote IS the majority — choose and
            # execute inside the proposing transition, exactly the
            # object's synchronous P2a -> P2b self-delivery cascade
            # (_send_to_all, paxos.py:238-241).
            e1 = log_get(st, i, slot)
            ch = cond & (e1[0] == 1) & (e1[3] == 0) & (e1[1] == ballot)
            row = st["p2bv"][i]
            st["p2bv"] = st["p2bv"].at[i].set(jnp.where(
                (jnp.arange(S) == slot - 1) & ch, 0, row))
            log_set(st, i, slot, [1, e1[1], e1[2], 1], ch)
            exec_chain(st, i, sends, ch)

    def heartbeat_sends(st, i, sends: Sends, cond):
        ballot = st["b"][i]
        commit = st["ex"][i]
        gc = st["gc"][i]
        for j in range(n):
            if j == i:
                continue
            sends.add(cond, HB, i, j, [ballot, commit, gc])

    # ----------------------------------------------------- message handlers

    def step_message(nodes, msg):
        tag, frm, to = msg[0], msg[1], msg[2]
        p = msg[3:]
        st = _unpack(nodes)
        srv_rows, srv_sets = None, None
        for i in range(n):
            here = to == i
            sends, sets = Sends(), Sets()
            _server_handle(st, i, here, tag, frm, p, sends, sets)
            r, t = sends.finalize(SRV_SENDS), sets.finalize(SRV_SETS)
            srv_rows = r if srv_rows is None else jnp.minimum(srv_rows, r)
            srv_sets = t if srv_sets is None else jnp.minimum(srv_sets, t)
        cli_rows, cli_sets = None, None
        for c in range(NC):
            here = to == n + c
            sends, sets = Sends(), Sets()
            _client_handle(st, c, here, tag, p, sends, sets)
            r, t = sends.finalize(CLI_SENDS), sets.finalize(CLI_SETS)
            cli_rows = r if cli_rows is None else jnp.minimum(cli_rows, r)
            cli_sets = t if cli_sets is None else jnp.minimum(cli_sets, t)
        rows = jnp.concatenate([srv_rows, cli_rows])
        tsets = jnp.concatenate([srv_sets, cli_sets])
        return _repack(st), rows, tsets, _pack_guard(st)

    def _server_handle(st, i, here, tag, frm, p, sends, sets):
        ballot = st["b"][i]

        # ---- PaxosRequest (handle_PaxosRequest, paxos.py)
        is_req = here & (tag == REQ)
        client, seq = p[0], p[1]
        ci = client.clip(0, NC - 1)
        amo_last = oh_get(st["amo"][i], ci, NC)
        already = seq <= amo_last
        sends.add(is_req & already & (seq == amo_last), REPLY, i,
                  n + client, [client, seq])
        is_leader = (st["ld"][i] == 1) & (ballot % n == i)
        believed = ballot % n
        fwd = (is_req & ~already & ~is_leader
               & ((frm == i) | (frm >= n)) & (believed != i))
        sends.add(fwd, REQ, i, believed, [client, seq])
        prop = oh_get(st["prop"][i], ci, NC)
        do_prop = is_req & ~already & is_leader & (seq > prop)
        slot = st["si"][i]
        in_range = slot <= S
        do_prop = do_prop & in_range
        st["prop"] = st["prop"].at[i].set(
            oh_put(st["prop"][i], ci, NC, seq, do_prop))
        _set(st, "si", i, jnp.where(do_prop, slot + 1, slot))
        log_set(st, i, slot, [1, ballot, cmd_id(client, seq), 0], do_prop)
        send_p2a(st, i, slot, sends, do_prop)

        # ---- P1a (handle_P1a)
        is_p1a = here & (tag == P1A)
        mb = p[0]
        adopt = is_p1a & (mb > ballot)
        _set(st, "b", i, jnp.where(adopt, mb, st["b"][i]))
        _set(st, "ld", i, jnp.where(adopt, 0, st["ld"][i]))
        promise = is_p1a & (mb == st["b"][i])
        sends.add(promise, P1B, i, frm,
                  [st["b"][i]] + [
                      _pack_entry(st["log"][i][s][0], st["log"][i][s][1],
                                  st["log"][i][s][2], st["log"][i][s][3])
                      for s in range(S)])

        # ---- P1b (handle_P1b)
        is_p1b = here & (tag == P1B)
        vb = p[0]
        accept_vote = (is_p1b & (vb == st["b"][i])
                       & (st["b"][i] % n == i)
                       & (st["ld"][i] == 0))
        # Unpack the S packed log entries back into the raw vote-row
        # layout [have, S x (exists, ballot, cmd, chosen)].
        vlanes = [jnp.ones((), jnp.int32)]
        for s in range(S):
            ex, lb, cmd, ch = _unpack_entry(p[1 + s].astype(jnp.int32))
            vlanes += [ex, lb, cmd, ch]
        vrec = jnp.stack(vlanes).astype(jnp.int32)
        st["votes"] = st["votes"].at[i].set(
            oh_put(st["votes"][i], frm, n, vrec, accept_vote))
        nvotes = jnp.sum(st["votes"][i][:, 0])
        win = accept_vote & (nvotes >= maj)
        _p1b_win(st, i, win, sends, sets)

        # ---- P2a (handle_P2a)
        is_p2a = here & (tag == P2A)
        ab, aslot, acmd = p[0], p[1], p[2]
        ok2a = is_p2a & (ab >= st["b"][i])
        _set(st, "ld", i, jnp.where(ok2a & (ab > st["b"][i]), 0,
                                    st["ld"][i]))
        _set(st, "b", i, jnp.where(ok2a, ab, st["b"][i]))
        _set(st, "hd", i, jnp.where(ok2a, 1, st["hd"][i]))
        accept_p2a(st, i, ab, aslot, acmd, ok2a)
        sends.add(ok2a, P2B, i, frm, [ab, aslot])

        # ---- P2b (handle_P2b)
        is_p2b = here & (tag == P2B)
        bb, bslot = p[0], p[1]
        lead_ok = (is_p2b & (bb == st["b"][i])
                   & (st["ld"][i] == 1) & (st["b"][i] % n == i))
        e = log_get(st, i, bslot)
        count_ok = lead_ok & (e[0] == 1) & (e[3] == 0) & (e[1] == bb)
        vmask = oh_get(st["p2bv"][i], bslot - 1, S)
        vmask2 = jnp.where(count_ok, vmask | (1 << frm.clip(0, n - 1)),
                           vmask)
        chosen_now = count_ok & (_popcount(vmask2) >= maj)
        st["p2bv"] = st["p2bv"].at[i].set(oh_put(
            st["p2bv"][i], bslot - 1, S,
            jnp.where(chosen_now, 0, vmask2), count_ok))
        log_set(st, i, bslot, [1, e[1], e[2], 1], chosen_now)
        exec_chain(st, i, sends, chosen_now)

        # ---- Heartbeat (handle_Heartbeat)
        is_hb = here & (tag == HB)
        hb_b, hb_commit, hb_gc = p[0], p[1], p[2]
        hb_ok = is_hb & (hb_b >= st["b"][i])
        _set(st, "ld", i, jnp.where(hb_ok & (hb_b > st["b"][i]), 0,
                                    st["ld"][i]))
        _set(st, "b", i, jnp.where(hb_ok, hb_b, st["b"][i]))
        _set(st, "hd", i, jnp.where(hb_ok, 1, st["hd"][i]))
        gc_to(st, i, hb_gc, hb_ok)
        lagging = hb_ok & (st["ex"][i] < hb_commit)
        sends.add(lagging, CREQ, i, frm, [st["ex"][i] + 1])
        sends.add(hb_ok, HBR, i, frm, [st["b"][i], st["ex"][i]])

        # ---- HeartbeatReply (handle_HeartbeatReply)
        is_hbr = here & (tag == HBR)
        rb, rexec = p[0], p[1]
        hbr_ok = (is_hbr & (rb == st["b"][i])
                  & (st["ld"][i] == 1) & (st["b"][i] % n == i))
        pcur = oh_get(st["peer"][i], frm, n)
        st["peer"] = st["peer"].at[i].set(oh_put(
            st["peer"][i], frm, n, jnp.maximum(pcur, rexec), hbr_ok))
        mask = st["pm"][i]
        _set(st, "pm", i,
             jnp.where(hbr_ok, mask | (1 << frm.clip(0, n - 1)), mask))
        maybe_gc(st, i, hbr_ok)

        # ---- CatchupRequest (handle_CatchupRequest)
        is_cq = here & (tag == CREQ)
        from_slot = jnp.maximum(p[0], st["cl"][i] + 1)
        cmds = []
        count = jnp.zeros((), jnp.int32)
        contiguous = jnp.asarray(True)
        for k in range(S):
            slot = from_slot + k
            e = log_get(st, i, slot)
            ok = (contiguous & (slot <= st["ex"][i])
                  & (e[0] == 1) & (e[3] == 1))
            contiguous = ok
            cmds.append(jnp.where(ok, e[2], 0))
            count = count + ok.astype(jnp.int32)
        sends.add(is_cq & (count > 0), CREP, i, frm,
                  [from_slot, count] + cmds)

        # ---- CatchupReply (handle_CatchupReply)
        is_cp = here & (tag == CREP)
        base, ccount = p[0], p[1]
        for k in range(S):
            slot = base + k
            cmd = p[2 + k]
            e = log_get(st, i, slot)
            install = (is_cp & (jnp.asarray(k) < ccount)
                       & (slot > st["cl"][i])
                       & ~((e[0] == 1) & (e[3] == 1)))
            log_set(st, i, slot, [1, st["b"][i], cmd, 1], install)
        exec_chain(st, i, sends, is_cp)

    def _p1b_win(st, i, win, sends: Sends, sets: Sets):
        """Phase-1 victory (handle_P1b body after majority)."""
        ballot = st["b"][i]
        _set(st, "ld", i, jnp.where(win, 1, st["ld"][i]))
        # p2b_votes = {}; peer_executed = {self: exec}
        st["p2bv"] = st["p2bv"].at[i].set(
            jnp.where(win, jnp.zeros((S,), jnp.int32), st["p2bv"][i]))
        _set(st, "pm", i, jnp.where(win, 1 << i, st["pm"][i]))
        me = jnp.arange(n) == i
        st["peer"] = st["peer"].at[i].set(
            jnp.where(win, jnp.where(me, st["ex"][i], 0),
                      st["peer"][i]).astype(jnp.int32))
        # Adoption: per slot, chosen wins; else max-ballot accepted.
        for s in range(1, S + 1):
            a_ex = jnp.zeros((), jnp.int32)
            a_b = jnp.full((), -1, jnp.int32)
            a_c = jnp.zeros((), jnp.int32)
            a_ch = jnp.zeros((), jnp.int32)
            for j in range(n):
                have = st["votes"][i][j, 0]
                ex = st["votes"][i][j, 1 + 4 * (s - 1) + 0]
                vb = st["votes"][i][j, 1 + 4 * (s - 1) + 1]
                vc = st["votes"][i][j, 1 + 4 * (s - 1) + 2]
                vch = st["votes"][i][j, 1 + 4 * (s - 1) + 3]
                valid = (have == 1) & (ex == 1)
                take = valid & ((vch == 1) & (a_ch == 0)
                                | (a_ch == 0) & ((a_ex == 0) | (vb > a_b)))
                a_b = jnp.where(take, vb, a_b)
                a_c = jnp.where(take, vc, a_c)
                a_ch = jnp.where(take, jnp.maximum(a_ch, vch), a_ch)
                a_ex = jnp.where(take, 1, a_ex)
            mine = st["log"][i, s - 1]
            adopt = win & (a_ex == 1) & (jnp.asarray(s) > st["cl"][i]) \
                & ~((mine[0] == 1) & (mine[3] == 1))
            log_set(st, i, jnp.asarray(s), [1, ballot, a_c, a_ch], adopt)
        # top = last non-empty; fill holes with no-ops; repropose unchosen.
        top = st["cl"][i]
        for s in range(1, S + 1):
            e = st["log"][i, s - 1]
            top = jnp.where(e[0] == 1, jnp.asarray(s, jnp.int32), top)
        for s in range(1, S + 1):
            e = st["log"][i, s - 1]
            in_span = win & (jnp.asarray(s) > st["ex"][i]) & \
                (jnp.asarray(s) <= top)
            fill = in_span & (e[0] == 0)
            log_set(st, i, jnp.asarray(s), [1, ballot, 0, 0], fill)
            e2 = st["log"][i, s - 1]
            reprop = in_span & (e2[3] == 0)
            send_p2a(st, i, jnp.asarray(s, jnp.int32), sends, reprop)
        _set(st, "si", i, jnp.where(win, top + 1, st["si"][i]))
        # proposed_seq from logged commands (max seq per client).
        for c in range(NC):
            best = jnp.zeros((), jnp.int32)
            for s in range(1, S + 1):
                e = st["log"][i, s - 1]
                mine_c = (e[0] == 1) & (e[2] != 0) & (cmd_client(e[2]) == c)
                best = jnp.where(mine_c,
                                 jnp.maximum(best, cmd_seq(e[2])), best)
            st["prop"] = st["prop"].at[i, c].set(
                jnp.where(win, best, st["prop"][i][c]).astype(jnp.int32))
        exec_chain(st, i, sends, win)
        sets.add(win, i, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, ballot)
        heartbeat_sends(st, i, sends, win)

    def _client_handle(st, c, here, tag, p, sends: Sends, sets: Sets):
        k = st["k"][c]
        is_reply = here & (tag == REPLY) & (p[0] == c)
        match = is_reply & (p[1] == k) & (k <= w)
        k2 = jnp.where(match, k + 1, k)
        st["k"] = st["k"].at[c].set(k2.astype(jnp.int32))
        has_next = match & (k2 <= w)
        for j in range(n):
            sends.add(has_next, REQ, n + c, j, [jnp.asarray(c), k2])
        sets.add(has_next, n + c, T_CLIENT, CLIENT_MS, CLIENT_MS, k2)

    # ------------------------------------------------------- timer handlers

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        st = _unpack(nodes)
        srv_rows, srv_sets = None, None
        for i in range(n):
            here = node_idx == i
            sends, sets = Sends(), Sets()
            _server_timer(st, i, here, tag, p0, sends, sets)
            r, t = sends.finalize(SRV_SENDS), sets.finalize(SRV_SETS)
            srv_rows = r if srv_rows is None else jnp.minimum(srv_rows, r)
            srv_sets = t if srv_sets is None else jnp.minimum(srv_sets, t)
        cli_rows, cli_sets = None, None
        for c in range(NC):
            here = node_idx == n + c
            sends, sets = Sends(), Sets()
            k = st["k"][c]
            live = here & (tag == T_CLIENT) & (p0 == k) & (k <= w)
            for j in range(n):
                sends.add(live, REQ, n + c, j, [jnp.asarray(c), k])
            sets.add(live, n + c, T_CLIENT, CLIENT_MS, CLIENT_MS, k)
            r, t = sends.finalize(CLI_SENDS), sets.finalize(CLI_SETS)
            cli_rows = r if cli_rows is None else jnp.minimum(cli_rows, r)
            cli_sets = t if cli_sets is None else jnp.minimum(cli_sets, t)
        rows = jnp.concatenate([srv_rows, cli_rows])
        tsets = jnp.concatenate([srv_sets, cli_sets])
        return _repack(st), rows, tsets, _pack_guard(st)

    def _server_timer(st, i, here, tag, p0, sends: Sends, sets: Sets):
        ballot = st["b"][i]
        is_leader = (st["ld"][i] == 1) & (ballot % n == i)

        # ---- ElectionTimer (on_ElectionTimer + _start_election inline)
        is_el = here & (tag == T_ELECTION)
        elect = is_el & ~is_leader & (st["hd"][i] == 0)
        new_ballot = (ballot // n + 1) * n + i
        _set(st, "b", i, jnp.where(elect, new_ballot, st["b"][i]))
        _set(st, "ld", i, jnp.where(elect, 0, st["ld"][i]))
        st["votes"] = st["votes"].at[i].set(
            jnp.where(elect, jnp.zeros((n, 1 + 4 * S), jnp.int32),
                      st["votes"][i]))
        for j in range(n):
            if j == i:
                continue
            sends.add(elect, P1A, i, j, [new_ballot])
        # Self-promise: own vote with own log (P1a -> P1b self-delivery).
        own = jnp.concatenate([jnp.ones((1,), jnp.int32),
                               st["log"][i].reshape(4 * S)])
        st["votes"] = st["votes"].at[i, i].set(
            jnp.where(elect, own, st["votes"][i][i]))
        if n == 1:
            # Singleton group: our own vote IS the majority — the object
            # server wins phase 1 inside the same ElectionTimer handler
            # (_send_to_all self-delivers P1a -> P1b -> handle_P1b,
            # paxos.py:238-241), so the twin fires the win cascade here
            # (it arms the leader heartbeat itself).
            _p1b_win(st, i, elect, sends, sets)
        _set(st, "hd", i, jnp.where(is_el, 0, st["hd"][i]))
        sets.add(is_el, i, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0)

        # ---- HeartbeatTimer (on_HeartbeatTimer)
        is_hbt = here & (tag == T_HEARTBEAT)
        live = is_hbt & (p0 == st["b"][i]) & is_leader
        heartbeat_sends(st, i, sends, live)
        for s in range(1, S + 1):
            e = st["log"][i, s - 1]
            inflight = (live & (jnp.asarray(s) > st["ex"][i])
                        & (jnp.asarray(s) < st["si"][i])
                        & (e[0] == 1) & (e[3] == 0))
            send_p2a(st, i, jnp.asarray(s, jnp.int32), sends, inflight)
        sets.add(live, i, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, p0)

    # ------------------------------------------------------------ initials

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        for i in range(n):
            nodes[i * SW + 3] = 1  # slot_in = 1
        if n == 1:
            # A lone server self-elects SYNCHRONOUSLY at init
            # (paxos.py:201-205: len(servers) == 1 -> _start_election,
            # P1a/P1b self-delivered inline) — the object never spends
            # an ElectionTimer event becoming leader, so neither may
            # the twin (pre-fix, every singleton path was one event
            # deeper than the object's, test_lab3_singleton_goal_parity).
            nodes[0] = 1               # ballot (1, 0) encoded round*n+i
            nodes[1] = 1               # leader
            nodes[VOTES] = 1           # own permanent P1b (empty log)
        for c in range(NC):
            nodes[n * SW + c] = 1    # first command in flight
        return nodes

    def init_messages():
        msgs = []
        for c in range(NC):
            for j in range(n):
                rec = np.zeros((MW,), np.int32)
                rec[0:3] = [REQ, n + c, j]
                rec[3:5] = [c, 1]
                msgs.append(rec)
        return np.stack(msgs)

    def init_timers():
        recs = []
        for i in range(n):
            recs.append([i, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0])
            if n == 1:
                # The init self-election's leader setup arms the
                # heartbeat (handle_P1b, paxos.py:317) — queue order
                # [Election, Heartbeat], exactly the object root state.
                recs.append([i, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS,
                             1])
        for c in range(NC):
            recs.append([n + c, T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(recs, np.int32)

    def msg_dest(msg):
        return msg[2]

    # ----------------------------------------------------------- predicates

    def clients_done(state):
        done = jnp.asarray(True)
        for c in range(NC):
            done = done & (state["nodes"][n * SW + c] == w + 1)
        return done

    def none_decided(state):
        nd = jnp.asarray(True)
        for c in range(NC):
            nd = nd & (state["nodes"][n * SW + c] == 1)
        return nd

    def logs_consistent(state):
        """slotValid core: no two different commands chosen in a slot."""
        ok = jnp.asarray(True)
        nodes = state["nodes"]
        for s in range(1, S + 1):
            chosen_cmd = jnp.full((), -1, jnp.int32)
            seen = jnp.zeros((), jnp.int32)
            bad = jnp.asarray(False)
            for i in range(n):
                e0 = nodes[i * SW + LOG + 4 * (s - 1)]
                ech = nodes[i * SW + LOG + 4 * (s - 1) + 3]
                ec = nodes[i * SW + LOG + 4 * (s - 1) + 2]
                is_ch = (e0 == 1) & (ech == 1)
                bad = bad | (is_ch & (seen == 1) & (ec != chosen_cmd))
                chosen_cmd = jnp.where(is_ch, ec, chosen_cmd)
                seen = jnp.where(is_ch, 1, seen)
            ok = ok & ~bad
        return ok

    return TensorProtocol(
        name=f"paxos-n{n}-c{NC}-w{w}-s{S}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        # Worst SIMULTANEOUS sends: the P1b-win cascade — S*(n-1) P2As
        # (reproposals) + S exec replies + (n-1) heartbeats; every other
        # branch is smaller.  Too small is a loud CapacityOverflow.
        max_live_sends=min(S * (n - 1) + S + (n - 1) + 1, MAX_SENDS),
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        invariants={"LOGS_CONSISTENT": logs_consistent},
        goals={"CLIENTS_DONE": clients_done},
    )


def _popcount(x):
    x = x.astype(jnp.uint32)
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    return ((x * 0x01010101) >> 24).astype(jnp.int32)
