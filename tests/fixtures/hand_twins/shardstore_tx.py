"""Tensorised twin of lab 4 Part 2: cross-group TRANSACTIONS (2PC) in
the search-test shape (ShardStorePart2Test.test09 / our object
test09_single_client_multi_group_tx_search): two one-server groups, one
shard master (timers frozen), config controller done, and a client whose
workload is W transactions each spanning BOTH groups (e.g.
MultiPut({key-1: v, key-6: v}) then MultiGet({key-1, key-6}) under a
10-shard Join(1)/Join(2) rebalance).

Everything the Part-1 twin models (config walk None -> cfg0 -> cfg1,
query gating, the g1 -> g2 handoff — see shardstore.py's docstring) is
reproduced here, plus the 2PC state machine of the object implementation
(dslabs_tpu/labs/shardedstore/shardstore.py):

* client routes a multi-group tx to the COORDINATOR group — the owner of
  the tx's smallest shard, statically group 1 here (_target_group).
* ``_coordinate_tx``: AMO-cached reply / absorb-while-in-progress / new
  round -> TxPrepare to every participant (including g1 itself — all 2PC
  traffic rides the network, so the checker explores its interleavings).
* ``_apply_tx_prepare``: tx_done -> yes-vote; config-NUM mismatch ->
  abort vote (the round-2 lost-write fix); stale round ignored, newer
  round supersedes (locks released, re-prepare); fresh prepare computes
  ok = no-conflict AND my shards owned (g2 voting while its handoff is
  in flight votes no), locks on ok.
* ``_apply_tx_vote``: first-writer votes, any-no -> abort decision,
  all-yes -> commit (coordinator records the AMO result and replies to
  the client), decision broadcast to un-acked participants.
* ``_apply_tx_decision``: round-matched prepare popped; commit & ok
  applies the tx's writes to owned shards and sets tx_done; own locks
  released; aborted coordinator entries cleared; ALWAYS ack.
* ``TxAck``: round-matched acks accumulate; all-acked deletes the entry.
* every 2PC message delivery is a relay-mode Paxos proposal at the
  receiving group -> decided-count + heard lanes bump on EVERY delivery,
  duplicates included (paxos.py:349-355), exactly as in the Part-1 twin.
* ``_reconfig_done`` (query gating) includes empty locks/prepared/coord.

Why the remaining object state collapses (the Part-1 collapse arguments
plus): vote VALUES are () in every reachable voting state (a
transaction's keys are written only by its own commit, and re-votes
after tx_done carry ()); commit WRITES are the workload constants; the
recorded MultiGet result is the committed constants (a participant can
only vote yes after the previous tx's decision released its locks, which
also applied its writes) — so store content, vote payloads, and AMO
result payloads are all derivable from the lanes below, and the lane
vector is bijective with the reachable object states.  MULTI_GETS_MATCH
therefore holds by construction in the twin (its object-side check runs
in tests/test_lab4_shardstore.py); the tensor predicate provided here
checks the reply-implies-commit invariant the collapse rests on.

Node lanes (0 = master, 1..2 = group servers, 3 = client):
  master  [mc, mamo_c, mamo_s1, mamo_s2]
  server g [scfg, samo, scount, sh, sq, out_flag, out_samo, in_flag,
            lock, (sp_rnd, sp_ok, sdone) x W]
    + coordinator block on g1 only: (ct_lrnd, ct_rnd, ct_v1, ct_v2,
      ct_dec, ct_a1, ct_a2) x W
  client  [k, cfg, cq]

Message lanes [tag, a, b, c]:
  QRY/QREP/SSREQ/SSREP/WG/SM/SMACK as in the Part-1 twin, plus
  TXP [t, rnd, dst_g]      TxPrepare (config_num constantly cfg1's)
  TXV [t, rnd, 2*from_g + ok]   TxVote -> coordinator
  TXD [t, rnd, 2*dst_g + commit]  TxDecision
  TXA [t, rnd, from_g]     TxAck -> coordinator
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_shardstore_tx_protocol"]

(QRY, QREP, SSREQ, SSREP, WG, SM, SMACK,
 TXP, TXV, TXD, TXA) = range(11)
T_CLIENT, T_QUERY, T_ELECTION, T_HEARTBEAT = 1, 2, 3, 4

CLIENT_MS = 100
QUERY_MS = 50
ELECTION_MIN, ELECTION_MAX = 150, 300
HEARTBEAT_MS = 50

G = 2          # two one-server groups; coordinator = group 1
N_CFG = 2      # cfg0 (everything at g1), cfg1 (the final rebalance)


def make_shardstore_tx_protocol(n_tx: int = 1, net_cap: int = 48,
                                timer_cap: int = 6) -> TensorProtocol:
    """``n_tx`` sequential client transactions, each spanning both
    groups (tx t = client seq t)."""
    W = n_tx
    MW, TW = 4, 4
    N_NODES = 1 + G + 1
    CLIENT = G + 1

    # ---- lane offsets
    M_MC, M_AMOC, M_AMOS = 0, 1, 2
    SRV = 2 + G
    S_CFG, S_AMO, S_CNT, S_H, S_Q, S_OUT, S_OSAMO, S_IN, S_LOCK = range(9)
    SPW = 3                                  # (sp_rnd, sp_ok, sdone) per tx
    S_BLK = 9 + SPW * W
    CT = SRV + S_BLK * G                     # coordinator block (g1)
    CTW = 7                                  # per-tx coordinator lanes
    (CT_LRND, CT_RND, CT_V1, CT_V2, CT_DEC, CT_A1, CT_A2) = range(CTW)
    C_K = CT + CTW * W
    C_CFG, C_CQ = C_K + 1, C_K + 2
    NW = C_K + 3

    def srv(g, off):
        return SRV + S_BLK * (g - 1) + off

    def sp(g, t, off):
        return SRV + S_BLK * (g - 1) + 9 + SPW * (t - 1) + off

    def ct(t, off):
        return CT + CTW * (t - 1) + off

    def msg_row(cond, tag, a, b=0, c=0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32) for x in (tag, a, b, c)])
        return jnp.where(cond, rec,
                         jnp.full((MW,), SENTINEL, jnp.int32))[None]

    def timer_row(cond, node, tag, mn, mx, p0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32)
                         for x in (node, tag, mn, mx, p0)])
        return jnp.where(cond, rec,
                         jnp.full((1 + TW,), SENTINEL, jnp.int32))[None]

    blank_msg = jnp.full((1, MW), SENTINEL, jnp.int32)
    blank_set = jnp.full((1, 1 + TW), SENTINEL, jnp.int32)

    def served_kind(arg):
        return jnp.where((arg < 0) | (arg >= N_CFG),
                         N_CFG - 1, arg).astype(jnp.int32)

    def set_lane(nodes, lane, cond, val):
        return nodes.at[lane].set(
            jnp.where(cond, val, nodes[lane]).astype(jnp.int32))

    def bump(nodes, g, cond):
        """Relay-mode proposal at group g: decided count + heard."""
        nodes = set_lane(nodes, srv(g, S_CNT), cond,
                         nodes[srv(g, S_CNT)] + 1)
        return set_lane(nodes, srv(g, S_H), cond, 1)

    def reconfig_done(nodes, g):
        """_reconfig_done: handoff drained AND no 2PC state outstanding
        (shardstore.py:283-287)."""
        done = ((nodes[srv(g, S_OUT)] == 0) & (nodes[srv(g, S_IN)] == 0)
                & (nodes[srv(g, S_LOCK)] == 0))
        for t in range(1, W + 1):
            done = done & (nodes[sp(g, t, 0)] == 0)
            if g == 1:
                done = done & (nodes[ct(t, CT_RND)] == 0)
        return done

    def one_tx(t, x):
        """where-chain select of per-tx lane values for traced tx id."""
        out = jnp.asarray(x(1), jnp.int32)
        for tt in range(2, W + 1):
            out = jnp.where(t == tt, x(tt), out)
        return out

    # ------------------------------------------------------------ handlers

    def step_message(nodes, msg):
        tag, a, b, c = msg[0], msg[1], msg[2], msg[3]
        sends = []
        tsets = []

        # ---- QRY -> master (identical to the Part-1 twin)
        is_qry = tag == QRY
        src, seq, arg = a, b, c
        for sidx in range(0, G + 1):
            lane = M_AMOC if sidx == 0 else M_AMOS + sidx - 1
            here = is_qry & (src == sidx)
            last = nodes[lane]
            fresh = here & (seq > last)
            nodes = set_lane(nodes, lane, fresh, seq)
            nodes = set_lane(nodes, M_MC, fresh, nodes[M_MC] + 1)
            sends.append(msg_row(here & (seq >= last), QREP, src, seq,
                                 served_kind(arg)))

        # ---- QREP -> client: adopt the latest config, send pending tx
        is_qrep_c = (tag == QREP) & (a == 0)
        k = nodes[C_K]
        adopt = is_qrep_c & (nodes[C_CFG] == 0)
        nodes = set_lane(nodes, C_CFG, adopt, 1)
        sends.append(msg_row(adopt & (k <= W), SSREQ, k))

        # ---- QREP -> server g: install next config when reconfig done
        for g in range(1, G + 1):
            here = (tag == QREP) & (a == g)
            kind = c
            scfg = nodes[srv(g, S_CFG)]
            install = (here & (kind == scfg) & (scfg < N_CFG)
                       & reconfig_done(nodes, g))
            is_final = install & (scfg == N_CFG - 1)
            if g == 1:
                nodes = set_lane(nodes, srv(g, S_OUT), is_final, 1)
                nodes = set_lane(nodes, srv(g, S_OSAMO), is_final,
                                 nodes[srv(g, S_AMO)])
                sends.append(msg_row(is_final, SM, 2,
                                     nodes[srv(g, S_AMO)]))
            else:
                nodes = set_lane(nodes, srv(g, S_IN), is_final, 1)
            nodes = set_lane(nodes, srv(g, S_CFG), install, scfg + 1)
            nodes = bump(nodes, g, install)

        # ---- SSREQ -> coordinator g1 (all txs span both groups; the
        # client routes to the min-shard owner = g1 under every config)
        is_ss = tag == SSREQ
        kk = a
        nodes = bump(nodes, 1, is_ss)
        scfg1 = nodes[srv(1, S_CFG)]
        samo1 = nodes[srv(1, S_AMO)]
        # cfg0: the tx is SINGLE-group (g1 owns everything) -> direct
        # execution exactly like a Part-1 command (no locks can exist
        # at cfg0: prepares carry cfg1's number and mismatch).
        direct = is_ss & (scfg1 == 1)
        execd = direct & (kk > samo1)
        nodes = set_lane(nodes, srv(1, S_AMO), execd, kk)
        sends.append(msg_row(direct & (kk >= samo1), SSREP, kk))
        # cfg1: _coordinate_tx — cached reply / absorb / new round
        co = is_ss & (scfg1 == 2)
        cached = co & (samo1 >= kk)
        sends.append(msg_row(cached & (kk == samo1), SSREP, kk))
        in_prog = one_tx(kk, lambda t: nodes[ct(t, CT_RND)]) > 0
        start = co & ~cached & ~in_prog
        for t in range(1, W + 1):
            here_t = start & (kk == t)
            rnd = nodes[ct(t, CT_LRND)] + 1
            nodes = set_lane(nodes, ct(t, CT_LRND), here_t, rnd)
            nodes = set_lane(nodes, ct(t, CT_RND), here_t, rnd)
            for off in (CT_V1, CT_V2, CT_DEC, CT_A1, CT_A2):
                nodes = set_lane(nodes, ct(t, off), here_t, 0)
            sends.append(msg_row(here_t, TXP, t, rnd, 1))
            sends.append(msg_row(here_t, TXP, t, rnd, 2))

        # ---- SSREP -> client (ClientWorker pumps the next command)
        is_rep = tag == SSREP
        k = nodes[C_K]
        match = is_rep & (a == k) & (k <= W)
        k2 = jnp.where(match, k + 1, k)
        nodes = nodes.at[C_K].set(k2.astype(jnp.int32))
        has_next = match & (k2 <= W)
        sends.append(msg_row(has_next, SSREQ, k2))
        tsets.append(timer_row(has_next, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k2))

        # ---- WG -> client: re-query (unreachable for tx workloads —
        # the coordinator always owns the min shard — kept for parity
        # with the object handler)
        is_wg = (tag == WG) & (a == nodes[C_K]) & (nodes[C_K] <= W)
        cq = nodes[C_CQ]
        nodes = set_lane(nodes, C_CQ, is_wg, cq + 1)
        sends.append(msg_row(is_wg, QRY, 0, cq + 1, -1))

        # ---- SM / SMACK: the g1 -> g2 handoff (as in the Part-1 twin)
        is_sm = (tag == SM) & (a == 2)
        scfg2 = nodes[srv(2, S_CFG)]
        at_final = scfg2 == N_CFG
        inst = is_sm & at_final & (nodes[srv(2, S_IN)] == 1)
        reack = is_sm & at_final & (nodes[srv(2, S_IN)] == 0)
        nodes = bump(nodes, 2, inst)
        samo2 = nodes[srv(2, S_AMO)]
        nodes = set_lane(nodes, srv(2, S_AMO), inst,
                         jnp.maximum(samo2, b))
        nodes = set_lane(nodes, srv(2, S_IN), inst, 0)
        sends.append(msg_row(inst | reack, SMACK, 1))
        is_ack = (tag == SMACK) & (a == 1)
        fin = is_ack & (nodes[srv(1, S_OUT)] == 1)
        nodes = bump(nodes, 1, fin)
        nodes = set_lane(nodes, srv(1, S_OUT), fin, 0)

        # ---- TXP -> participant dst (shardstore.py _apply_tx_prepare)
        is_txp = tag == TXP
        for g in (1, 2):
            here = is_txp & (c == g)
            nodes = bump(nodes, g, here)
            scfg = nodes[srv(g, S_CFG)]
            for t in range(1, W + 1):
                h = here & (a == t)
                rnd = b
                dn = nodes[sp(g, t, 2)] == 1
                # tx already done -> yes vote (any config)
                sends.append(msg_row(h & (scfg >= 1) & dn, TXV, t, rnd,
                                     2 * g + 1))
                # config mismatch (participant still at cfg0) -> abort
                sends.append(msg_row(h & (scfg == 1) & ~dn, TXV, t, rnd,
                                     2 * g + 0))
                # config match: prepare/resend/supersede
                m = h & (scfg == 2) & ~dn
                prnd = nodes[sp(g, t, 0)]
                stale = m & (prnd > rnd)
                supersede = m & (prnd > 0) & (prnd < rnd)
                # release own locks on supersede
                lock = nodes[srv(g, S_LOCK)]
                nodes = set_lane(nodes, srv(g, S_LOCK),
                                 supersede & (lock == t), 0)
                fresh = m & ((prnd == 0) | supersede)
                lock2 = nodes[srv(g, S_LOCK)]
                conflict = (lock2 != 0) & (lock2 != t)
                owned = (jnp.asarray(True) if g == 1
                         else nodes[srv(g, S_IN)] == 0)
                ok = fresh & ~conflict & owned
                nodes = set_lane(nodes, srv(g, S_LOCK), ok, t)
                nodes = set_lane(nodes, sp(g, t, 0), fresh, rnd)
                nodes = set_lane(nodes, sp(g, t, 1), fresh,
                                 ok.astype(jnp.int32))
                # vote with the STORED (round, ok) — fresh or resend
                vote = m & ~stale
                sends.append(msg_row(vote, TXV, t, nodes[sp(g, t, 0)],
                                     2 * g + nodes[sp(g, t, 1)]))

        # ---- TXV -> coordinator g1 (_apply_tx_vote)
        is_txv = tag == TXV
        nodes = bump(nodes, 1, is_txv)
        for t in range(1, W + 1):
            h = is_txv & (a == t)
            rnd, fg, okv = b, c // 2, c % 2
            live = (h & (nodes[ct(t, CT_RND)] == rnd) & (rnd > 0)
                    & (nodes[ct(t, CT_DEC)] == 0))
            vval = jnp.where(okv == 1, 1, 2)
            nodes = set_lane(nodes, ct(t, CT_V1), live & (fg == 1), vval)
            nodes = set_lane(nodes, ct(t, CT_V2), live & (fg == 2), vval)
            v1, v2 = nodes[ct(t, CT_V1)], nodes[ct(t, CT_V2)]
            dec_abort = live & ((v1 == 2) | (v2 == 2))
            dec_commit = live & (v1 == 1) & (v2 == 1)
            nodes = set_lane(nodes, ct(t, CT_DEC), dec_abort, 2)
            nodes = set_lane(nodes, ct(t, CT_DEC), dec_commit, 1)
            # commit: AMO record + client reply (coordinator side)
            nodes = set_lane(nodes, srv(1, S_AMO),
                             dec_commit & (nodes[srv(1, S_AMO)] < t), t)
            sends.append(msg_row(dec_commit, SSREP, t))
            decided = dec_abort | dec_commit
            cbit = dec_commit.astype(jnp.int32)
            sends.append(msg_row(decided, TXD, t, rnd, 2 * 1 + cbit))
            sends.append(msg_row(decided, TXD, t, rnd, 2 * 2 + cbit))

        # ---- TXD -> participant dst (_apply_tx_decision)
        is_txd = tag == TXD
        for g in (1, 2):
            here = is_txd & (c // 2 == g)
            nodes = bump(nodes, g, here)
            commit = c % 2 == 1
            for t in range(1, W + 1):
                h = here & (a == t)
                rnd = b
                pmatch = h & (nodes[sp(g, t, 0)] == rnd) & (rnd > 0)
                apply_w = pmatch & commit & (nodes[sp(g, t, 1)] == 1)
                nodes = set_lane(nodes, sp(g, t, 2), apply_w, 1)
                # pop prepared + release own locks (round-matched only)
                lock = nodes[srv(g, S_LOCK)]
                nodes = set_lane(nodes, srv(g, S_LOCK),
                                 pmatch & (lock == t), 0)
                nodes = set_lane(nodes, sp(g, t, 0), pmatch, 0)
                nodes = set_lane(nodes, sp(g, t, 1), pmatch, 0)
                if g == 1:
                    # aborted coordinator entry cleared (round-matched)
                    clear = (h & ~commit & (nodes[ct(t, CT_DEC)] == 2)
                             & (nodes[ct(t, CT_RND)] == rnd))
                    for off in (CT_RND, CT_V1, CT_V2, CT_DEC, CT_A1,
                                CT_A2):
                        nodes = set_lane(nodes, ct(t, off), clear, 0)
                # always ack when a config exists
                sends.append(msg_row(h & (nodes[srv(g, S_CFG)] >= 1),
                                     TXA, t, rnd, g))

        # ---- TXA -> coordinator g1
        is_txa = tag == TXA
        nodes = bump(nodes, 1, is_txa)
        for t in range(1, W + 1):
            h = is_txa & (a == t)
            rnd, fg = b, c
            live = h & (nodes[ct(t, CT_RND)] == rnd) & (rnd > 0)
            nodes = set_lane(nodes, ct(t, CT_A1), live & (fg == 1), 1)
            nodes = set_lane(nodes, ct(t, CT_A2), live & (fg == 2), 1)
            full = (live & (nodes[ct(t, CT_A1)] == 1)
                    & (nodes[ct(t, CT_A2)] == 1))
            for off in (CT_RND, CT_V1, CT_V2, CT_DEC, CT_A1, CT_A2):
                nodes = set_lane(nodes, ct(t, off), full, 0)

        sends = jnp.concatenate(sends + [blank_msg]
                                * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(tsets + [blank_set]
                                * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        sends = []
        tsets = []

        # ---- ClientTimer: re-query (+1 when no config yet) + resend
        k = nodes[C_K]
        live = ((node_idx == CLIENT) & (tag == T_CLIENT) & (p0 == k)
                & (k <= W))
        cq = nodes[C_CQ]
        has_cfg = nodes[C_CFG] == 1
        cq2 = jnp.where(live, jnp.where(has_cfg, cq + 1, cq + 2), cq)
        nodes = nodes.at[C_CQ].set(cq2.astype(jnp.int32))
        sends.append(msg_row(live, QRY, 0, cq + 1, -1))
        sends.append(jnp.where(has_cfg,
                               msg_row(live, SSREQ, k)[0],
                               msg_row(live, QRY, 0, cq + 2, -1)[0])[None])
        tsets.append(timer_row(live, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k))

        for g in range(1, G + 1):
            here = node_idx == g
            # ---- QueryTimer: gated on _reconfig_done (which now
            # includes empty 2PC state); _send_moves always runs
            is_q = here & (tag == T_QUERY)
            ask = is_q & reconfig_done(nodes, g)
            sq = nodes[srv(g, S_Q)]
            nodes = set_lane(nodes, srv(g, S_Q), ask, sq + 1)
            sends.append(msg_row(ask, QRY, g, sq + 1,
                                 nodes[srv(g, S_CFG)]))
            if g == 1:
                sends.append(msg_row(is_q & (nodes[srv(1, S_OUT)] == 1),
                                     SM, 2, nodes[srv(1, S_OSAMO)]))
            tsets.append(timer_row(is_q, g, T_QUERY, QUERY_MS, QUERY_MS,
                                   0))
            # ---- ElectionTimer / HeartbeatTimer (as in Part 1)
            is_el = here & (tag == T_ELECTION)
            nodes = set_lane(nodes, srv(g, S_H), is_el, 0)
            tsets.append(timer_row(is_el, g, T_ELECTION,
                                   ELECTION_MIN, ELECTION_MAX, 0))
            is_hb = here & (tag == T_HEARTBEAT)
            tsets.append(timer_row(is_hb, g, T_HEARTBEAT,
                                   HEARTBEAT_MS, HEARTBEAT_MS, 0))

        sends = jnp.concatenate(sends + [blank_msg]
                                * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(tsets + [blank_set]
                                * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    # Row budgets: total appended rows per step function (each row is
    # condition-masked; the pad below must never truncate a real one).
    # step_message: (G+1) QREP + client SSREQ + G install rows (1 SM) +
    # 2 direct/cached SSREP + 2W TXP + pumped SSREQ + WG-requery +
    # SM/SMACK rows (2) + TXP votes (2 per (g,t) x ... ) etc.
    MAX_SENDS = ((G + 1) + 1 + 1 + 2 + 2 * W + 1 + 1 + 2
                 + 2 * (3 * W)          # TXP: 3 vote rows per (g, t)
                 + W * 3                # TXV: reply + 2 decisions
                 + 2 * W                # TXD: ack per (g, t)
                 )
    MAX_SETS = 1 + 3 * G
    MAX_LIVE_SENDS = 6   # worst: a TXV commit (reply + 2 TXDs) + slack

    # ------------------------------------------------------------ initials

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        nodes[M_MC] = G
        nodes[C_K] = 1
        nodes[C_CQ] = 2
        return nodes

    def init_messages():
        return np.array([[QRY, 0, 1, -1], [QRY, 0, 2, -1]], np.int32)

    def init_timers():
        rows = []
        for g in range(1, G + 1):
            rows.append([g, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0])
            rows.append([g, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, 0])
            rows.append([g, T_QUERY, QUERY_MS, QUERY_MS, 0])
        rows.append([CLIENT, T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(rows, np.int32)

    def msg_dest(msg):
        tag, a, c = msg[0], msg[1], msg[3]
        dest = jnp.asarray(0, jnp.int32)                 # QRY -> master
        dest = jnp.where(tag == QREP,
                         jnp.where(a == 0, CLIENT, a), dest)
        dest = jnp.where(tag == SSREQ, 1, dest)          # coordinator
        dest = jnp.where((tag == SSREP) | (tag == WG), CLIENT, dest)
        dest = jnp.where((tag == SM) | (tag == SMACK), a, dest)
        dest = jnp.where(tag == TXP, c, dest)
        dest = jnp.where((tag == TXV) | (tag == TXA), 1, dest)
        dest = jnp.where(tag == TXD, c // 2, dest)
        return dest

    def clients_done(state):
        return state["nodes"][C_K] == W + 1

    def multi_gets_match(state):
        """The collapse invariant MULTI_GETS_MATCH rests on: a client
        that received tx t's reply implies the coordinator recorded its
        commit (so the reply content was the committed constants)."""
        ok = jnp.asarray(True)
        for t in range(1, W + 1):
            replied = state["nodes"][C_K] > t
            committed = state["nodes"][srv(1, S_AMO)] >= t
            ok = ok & (~replied | committed)
        return ok

    return TensorProtocol(
        name=f"shardstore-tx-g{G}-w{W}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        max_live_sends=MAX_LIVE_SENDS,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        invariants={"MULTI_GETS_MATCH": multi_gets_match},
        goals={"CLIENTS_DONE": clients_done},
    )
