"""Tensorised twin of lab 4 with MULTI-SERVER replica groups: G groups of
n Paxos-replicated ShardStoreServers (the ShardStoreBaseTest.java:47-122
``setupStates(G, n, 1, shards)`` shape), one frozen shard master, one
client — REAL in-group replicated-log lanes, the round-3 verdict's
missing capability (the 1-server twins in shardstore.py collapse the
group log away entirely).

Mirrored object semantics (all against dslabs_tpu/labs/shardedstore/
shardstore.py + labs/paxos/paxos.py in RELAY mode):

* **Group Paxos** (paxos.py, app=None): each server carries the full
  sub-node state — ballot (round*n + idx), leader/heard flags, log
  [S x (exists, ballot, cmd, chosen)], raw P1b vote rows, P2b vote
  bitmasks, executed/cleared/gc frontiers, peer_executed — the same
  lane discipline as the lab 3 twin (tpu/protocols/paxos.py), minus
  the AMO layer: decisions execute by driving the SHARDSTORE effect
  below (handle_PaxosDecision, shardstore.py:346-392), and request
  dedup is the relay rule (equal in-flight unchosen command,
  paxos.py:350-356).  ``_propose`` forwards a parent-injected request
  to the believed leader once (paxos.py:335-344) as a PREQ record.
* **Shardstore layer per server** — a deterministic function of the
  executed log prefix, materialised as lanes exactly like the object
  fields: scfg (config list position; 0 = none), owned/incoming shard
  bitmasks, outgoing flag + snapshot seq, per-client executed seq
  (samo — the KV and AMO maps collapse to it for the own-key workload,
  the same proof as tpu/protocols/shardstore.py), qseq.
  Exec effects: NewConfig gating incl. _reconfig_done, first-config
  adoption, lost->outgoing snapshot / gained->incoming
  (shardstore.py:_apply_new_config); client ops route WrongGroup /
  silent-in-flight / execute+reply (_execute_client_command);
  InstallShards merge + leader ack; MoveDone clears outgoing.  Leader
  side effects (_send_moves / _send_ack) fire on the executing leader.
* **Query machinery**: on_QueryTimer queries the master only when
  leader and reconfig-done (qseq++), re-sends pending moves, ALWAYS
  re-arms (shardstore.py:626-643); PaxosReply(cfg) proposes
  NewConfig when it is the next config and reconfig is done.
* **Master** (1-server Paxos + ShardMaster, timers frozen): the
  1-group twin's collapse — decided count + per-source AMO seq; the
  config list is STATIC after the staged Joins, extracted at build
  time by running the OBJECT ShardMaster on the same Join sequence.
* **Client** (ShardStoreClient): k (seq in flight; W+1 done), known
  config, qseq; init = query(-1) twice (init + send_command finding no
  config, shardstore.py:656-688) — matching the staged object state's
  two pending queries — WrongGroup/ClientTimer re-query, config
  adoption re-sends the pending command to the owning group.

Command ids in group logs: 0 = no-op hole filler; 1..NC*W client
commands (client c's seq k -> c*W + k); NC*W + 1 + j = NewConfig(j);
then InstallShards variants (one per snapshot seq 0..NC*W) and
MoveDone per (from group) — G = 2 keeps the move alphabet to the
single g1->g2 handoff the config walk can produce.

Scope bound (documented, loud): G == 2 (one possible handoff edge);
cross-group transactions are out of alphabet (no Transaction commands
in the workload => unreachable).  Verified by depth-by-depth
unique-count parity vs the object checker from the SAME staged joined
state (tests/test_lab4_multi.py: 10/69/392 at depths 1-3 for the
(2, 3, 1, 10) shape).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_shardstore_multi_protocol"]

# Message tags
(QRY, QREP, SSREQ, SSREP, WG, PREQ, P1A, P1B, P2A, P2B, HB, HBR,
 SM, SMACK) = range(14)
# Timer tags
T_ELECTION, T_HEARTBEAT, T_QUERY, T_CLIENT = 1, 2, 3, 4

ELECTION_MIN, ELECTION_MAX = 150, 300
HEARTBEAT_MS = 50
QUERY_MS = 50
CLIENT_MS = 100


def _configs(G: int, n: int, num_shards: int):
    """Run the OBJECT ShardMaster on the staged Join sequence; return
    per-config per-group shard bitmasks (bit s-1 = shard s)."""
    from dslabs_tpu.core.address import LocalAddress
    from dslabs_tpu.labs.shardedstore.shardmaster import Join, Query, \
        ShardMaster

    sm = ShardMaster(num_shards)
    for g in range(1, G + 1):
        sm.execute(Join(g, tuple(
            LocalAddress(f"server{g}-{i}") for i in range(1, n + 1))))
    out = []
    for j in range(G):
        cfg = sm.execute(Query(j))
        masks = {}
        for gid, (_, shards) in cfg.group_info:
            m = 0
            for s in shards:
                m |= 1 << (s - 1)
            masks[gid] = m
        out.append(masks)
    return out


def make_shardstore_multi_protocol(n_groups: int = 2, n: int = 3,
                                   num_shards: int = 10,
                                   w: int = 1,
                                   net_cap: int = 48,
                                   timer_cap: int = 6) -> TensorProtocol:
    from dslabs_tpu.labs.shardedstore.shardstore import key_to_shard

    G, NC, W = n_groups, 1, w
    assert G == 2, "scope bound: one handoff edge (module docstring)"
    maj = n // 2 + 1
    S = 2 + W + 2          # log slots: NewConfig x2 + client ops + IS/MD
    CFG = _configs(G, n, num_shards)

    # Command ids (per group log / P2A payloads)
    NCMD = NC * W                       # client commands 1..NCMD
    CMD_NC0 = NCMD + 1                  # NewConfig(configs[j]) = NC0 + j
    CMD_IS0 = CMD_NC0 + G               # InstallShards, snapshot seq v
    CMD_MD = CMD_IS0 + NC * W + 1       # MoveDone (g1 -> g2)
    N_CMDS = CMD_MD + 1

    # Client command shards (client 0, seq k -> key "key-k")
    put_shard = [key_to_shard(f"key-{k}", num_shards)
                 for k in range(1, W + 1)]
    put_mask = [1 << (s - 1) for s in put_shard]
    # The one handoff edge: shards g1 loses at cfg1.
    MOVE_MASK = CFG[0][1] & ~CFG[1][1]

    # ---- node indexing: 0 = master, 1..G*n = servers (g-major), then
    # the client.
    def srv(g, i):
        return 1 + g * n + i            # g, i 0-based

    N_NODES = 1 + G * n + NC
    CLIENT = 1 + G * n

    # ---- per-server lanes
    # paxos: b ld hd si ex cl gc pm peer[n] p2bv[S] log[S*4]
    #        votes[n*(1+4S)]
    # store: scfg owned inc outf osamo samo qseq
    PAX = 8
    PEER = PAX
    P2BV = PEER + n
    LOG = P2BV + S
    VOTES = LOG + 4 * S
    STORE = VOTES + n * (1 + 4 * S)
    SW = STORE + 7
    # Master block first: decided count + per-source AMO seq (client,
    # then each server) — the 1-group twin's collapse of the 1-server
    # ShardMaster paxos (configs static, log GC'd synchronously).
    MASTER_W = 2 + G * n
    SRV_OFF = MASTER_W
    NW = MASTER_W + G * n * SW + 3      # client: k, cfg, qseq
    K_OFF = MASTER_W + G * n * SW

    PAYLOAD = max(1 + S, 3, 2 + S)
    MW = 3 + PAYLOAD
    TW = 4

    def _pack_entry(ex, lb, cmd, ch):
        """Same bijective packing as the lab 3 twin (ballot < 2^12,
        cmd < 2^17 — N_CMDS is tiny)."""
        return (ex | (ch << 1) | (lb << 2) | (cmd << 14)).astype(jnp.int32)

    def _unpack_entry(v):
        return v & 1, (v >> 2) & 0xFFF, v >> 14, (v >> 1) & 1


    # ------------------------------------------------------------- builders

    def mk_msg(tag, frm, to, payload):
        lanes = [jnp.asarray(tag, jnp.int32), jnp.asarray(frm, jnp.int32),
                 jnp.asarray(to, jnp.int32)]
        for v in payload:
            lanes.append(jnp.asarray(v, jnp.int32))
        while len(lanes) < MW:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    class Sends:
        def __init__(self):
            self.rows = []

        def add(self, cond, tag, frm, to, payload):
            rec = mk_msg(tag, frm, to, payload)
            blank = jnp.full((MW,), SENTINEL, jnp.int32)
            self.rows.append(jnp.where(cond, rec, blank))

        def finalize(self, count=None):
            """Stack the guarded rows; the per-step totals are discovered
            with eval_shape at build time (no hand-counted budgets —
            the engine pads the smaller step kind to the larger)."""
            if not self.rows:
                return jnp.zeros((0, MW), jnp.int32)
            return jnp.stack(self.rows)

    class Sets(Sends):
        def add(self, cond, node, tag, mn, mx, p0):
            rec = jnp.stack([jnp.asarray(node, jnp.int32),
                             jnp.asarray(tag, jnp.int32),
                             jnp.asarray(mn, jnp.int32),
                             jnp.asarray(mx, jnp.int32),
                             jnp.asarray(p0, jnp.int32)])
            blank = jnp.full((1 + TW,), SENTINEL, jnp.int32)
            self.rows.append(jnp.where(cond, rec, blank))

        def finalize(self, count=None):
            if not self.rows:
                return jnp.zeros((0, 1 + TW), jnp.int32)
            return jnp.stack(self.rows)

    # ------------------------------------------------------- un/pack state

    def _unpack(nodes):
        st = {}
        st["mc"] = nodes[0]
        st["mamo"] = nodes[1:MASTER_W]
        for key, off in (
                ("b", 0), ("ld", 1), ("hd", 2), ("si", 3),
                ("ex", 4), ("cl", 5), ("gc", 6), ("pm", 7),
                ("scfg", STORE), ("own", STORE + 1),
                ("inc", STORE + 2), ("outf", STORE + 3),
                ("osamo", STORE + 4), ("samo", STORE + 5),
                ("qseq", STORE + 6)):
            st[key] = jnp.stack(
                [jnp.stack([nodes[SRV_OFF + (g * n + i) * SW + off]
                            for i in range(n)]) for g in range(G)])
        for key, off, width in (("peer", PEER, n), ("p2bv", P2BV, S),
                                ("log", LOG, 4 * S),
                                ("votes", VOTES, n * (1 + 4 * S))):
            st[key] = jnp.stack(
                [jnp.stack([nodes[SRV_OFF + (g * n + i) * SW + off:
                                  SRV_OFF + (g * n + i) * SW + off
                                  + width]
                            for i in range(n)]) for g in range(G)])
        st["log"] = st["log"].reshape(G, n, S, 4)
        st["votes"] = st["votes"].reshape(G, n, n, 1 + 4 * S)
        st["ck"] = nodes[K_OFF]
        st["ccfg"] = nodes[K_OFF + 1]
        st["cq"] = nodes[K_OFF + 2]
        return st

    def _repack(st):
        parts = [st["mc"][None], st["mamo"]]
        for g in range(G):
            for i in range(n):
                parts.extend([
                    st["b"][g, i][None], st["ld"][g, i][None],
                    st["hd"][g, i][None], st["si"][g, i][None],
                    st["ex"][g, i][None], st["cl"][g, i][None],
                    st["gc"][g, i][None], st["pm"][g, i][None],
                    st["peer"][g, i], st["p2bv"][g, i],
                    st["log"][g, i].reshape(4 * S),
                    st["votes"][g, i].reshape(n * (1 + 4 * S)),
                    st["scfg"][g, i][None], st["own"][g, i][None],
                    st["inc"][g, i][None], st["outf"][g, i][None],
                    st["osamo"][g, i][None], st["samo"][g, i][None],
                    st["qseq"][g, i][None],
                ])
        parts.append(st["ck"][None])
        parts.append(st["ccfg"][None])
        parts.append(st["cq"][None])
        return jnp.concatenate(parts).astype(jnp.int32)

    def _set(st, key, g, i, val):
        st[key] = st[key].at[g, i].set(jnp.asarray(val, jnp.int32))

    def log_get(st, g, i, slot):
        """One-hot log read at a traced 1-based slot."""
        oh = (jnp.arange(S) == slot - 1)
        return jnp.sum(oh[:, None] * st["log"][g, i], axis=0)

    def log_set(st, g, i, slot, entry, cond):
        oh = (jnp.arange(S) == slot - 1) & cond
        rec = jnp.stack([jnp.asarray(v, jnp.int32) for v in entry])
        st["log"] = st["log"].at[g, i].set(
            jnp.where(oh[:, None], rec[None, :], st["log"][g, i]))

    # ------------------------------------------------- shard-store helpers

    def group_mask(g, cfg_idx):
        """Static table lookup: configs[cfg_idx] shards of group g+1 as a
        bitmask (0 when the group is absent); cfg_idx is TRACED — one-hot
        over the G configs."""
        vals = jnp.asarray([CFG[j].get(g + 1, 0) for j in range(G)],
                           jnp.int32)
        oh = jnp.arange(G) == cfg_idx
        return jnp.sum(jnp.where(oh, vals, 0))

    def reconfig_done(st, g, i):
        return ((st["inc"][g, i] == 0) & (st["outf"][g, i] == 0))

    def cmd_is_client(cmd):
        return (cmd >= 1) & (cmd <= NCMD)

    def cmd_is_nc(cmd):
        return (cmd >= CMD_NC0) & (cmd < CMD_NC0 + G)

    def cmd_is_is(cmd):
        return (cmd >= CMD_IS0) & (cmd < CMD_IS0 + NC * W + 1)

    # --------------------------------------------------------- exec effect

    def exec_effect(st, g, i, cmd, sends: Sends, cond):
        """handle_PaxosDecision's switch (shardstore.py:346-392) for one
        executed command at server (g, i)."""
        sid = srv(g, i)
        is_leader = (st["ld"][g, i] == 1) & (st["b"][g, i] % n == i)

        # ---- NewConfig(j) (_apply_new_config)
        j = cmd - CMD_NC0
        nc_ok = (cond & cmd_is_nc(cmd)
                 & (j == st["scfg"][g, i])        # next config only
                 & reconfig_done(st, g, i))
        mine_new = group_mask(g, j)
        first = st["scfg"][g, i] == 0
        own = st["own"][g, i]
        lost = own & ~mine_new
        gained = mine_new & ~own
        _set(st, "own", g, i, jnp.where(
            nc_ok, jnp.where(first, mine_new, own & ~lost), own))
        _set(st, "inc", g, i, jnp.where(
            nc_ok & ~first, gained, st["inc"][g, i]))
        has_out = nc_ok & ~first & (lost != 0)
        _set(st, "outf", g, i, jnp.where(has_out, 1, st["outf"][g, i]))
        _set(st, "osamo", g, i, jnp.where(has_out, st["samo"][g, i],
                                          st["osamo"][g, i]))
        _set(st, "scfg", g, i, jnp.where(nc_ok, j + 1, st["scfg"][g, i]))
        # leader: _send_moves (the only edge is g1 -> g2)
        if g == 0:
            move = has_out & is_leader
            for t in range(n):
                sends.add(move, SM, sid, srv(1, t),
                          [jnp.asarray(1), st["samo"][g, i], 0])

        # ---- client command (_execute_client_command)
        cl_ok = cond & cmd_is_client(cmd)
        have_cfg = st["scfg"][g, i] > 0
        cmask = jnp.sum(jnp.where(
            jnp.arange(W) == (cmd - 1) % W,
            jnp.asarray(put_mask, jnp.int32), 0))
        mine = group_mask(g, st["scfg"][g, i] - 1)
        in_mine = (cmask & mine) == cmask
        wrong = cl_ok & have_cfg & ~in_mine
        sends.add(wrong, WG, sid, CLIENT, [(cmd - 1) % W + 1, 0, 0])
        owned_now = (cmask & st["own"][g, i]) == cmask
        do = cl_ok & have_cfg & in_mine & owned_now
        seq = (cmd - 1) % W + 1
        _set(st, "samo", g, i, jnp.where(
            do, jnp.maximum(st["samo"][g, i], seq), st["samo"][g, i]))
        sends.add(do, SSREP, sid, CLIENT, [seq, 0, 0])

        # ---- InstallShards (_apply_install); only g2 receives it
        if g == 1:
            v = cmd - CMD_IS0
            is_ok = (cond & cmd_is_is(cmd)
                     & (st["scfg"][g, i] == 2)    # cfg1 current
                     & ((MOVE_MASK & st["inc"][g, i]) == MOVE_MASK))
            _set(st, "own", g, i, jnp.where(
                is_ok, st["own"][g, i] | MOVE_MASK, st["own"][g, i]))
            _set(st, "inc", g, i, jnp.where(
                is_ok, st["inc"][g, i] & ~MOVE_MASK, st["inc"][g, i]))
            _set(st, "samo", g, i, jnp.where(
                is_ok, jnp.maximum(st["samo"][g, i], v),
                st["samo"][g, i]))
            ack = is_ok & is_leader
            for t in range(n):
                sends.add(ack, SMACK, sid, srv(0, t), [jnp.asarray(1), 0,
                                                       0])

        # ---- MoveDone
        md = cond & (cmd == CMD_MD)
        _set(st, "outf", g, i, jnp.where(md, 0, st["outf"][g, i]))

    def exec_chain(st, g, i, sends: Sends, cond):
        """_execute_chosen: advance ex through contiguous chosen slots,
        running the shardstore effect per slot; leader updates
        peer_executed + GC."""
        for _ in range(S):
            nxt = st["ex"][g, i] + 1
            e = log_get(st, g, i, nxt)
            run = cond & (nxt <= S) & (e[0] == 1) & (e[3] == 1)
            exec_effect(st, g, i, e[2], sends, run)
            _set(st, "ex", g, i, jnp.where(run, nxt, st["ex"][g, i]))
        is_leader = (st["ld"][g, i] == 1) & (st["b"][g, i] % n == i)
        lead = cond & is_leader
        me = jnp.arange(n) == i
        st["peer"] = st["peer"].at[g, i].set(jnp.where(
            lead & me, st["ex"][g, i], st["peer"][g, i]).astype(jnp.int32))
        maybe_gc(st, g, i, lead)

    def maybe_gc(st, g, i, cond):
        """_maybe_gc: all peers heard from and executed through s ->
        everyone may clear through s (leader propagates via HB)."""
        have_all = st["pm"][g, i] == (1 << n) - 1
        floor = st["peer"][g, i][0]
        for t in range(1, n):
            floor = jnp.minimum(floor, st["peer"][g, i][t])
        do = cond & have_all & (floor > st["gc"][g, i])
        _set(st, "gc", g, i, jnp.where(do, floor, st["gc"][g, i]))
        gc_to(st, g, i, st["gc"][g, i], do)

    def gc_to(st, g, i, through, cond):
        cleared = st["cl"][g, i]
        do = cond & (through > cleared)
        for s in range(1, S + 1):
            clear = do & (jnp.asarray(s) > cleared) & \
                (jnp.asarray(s) <= through)
            log_set(st, g, i, jnp.asarray(s), [0, 0, 0, 0], clear)
        _set(st, "cl", g, i, jnp.where(do, through, cleared))

    # --------------------------------------------------------- group paxos

    def send_p2a(st, g, i, slot, sends: Sends, cond):
        e = log_get(st, g, i, slot)
        ballot = st["b"][g, i]
        sid = srv(g, i)
        for t in range(n):
            if t == i:
                continue
            sends.add(cond, P2A, sid, srv(g, t), [ballot, slot, e[2]])
        # self-accept + own P2b vote (synchronous self-delivery)
        e0 = log_get(st, g, i, slot)
        write = cond & (slot > st["cl"][g, i]) & ~((e0[0] == 1)
                                                   & (e0[3] == 1))
        log_set(st, g, i, slot, [1, ballot, e0[2], 0], write)
        _set(st, "hd", g, i, jnp.where(cond, 1, st["hd"][g, i]))
        e1 = log_get(st, g, i, slot)
        ok = (cond & (e1[0] == 1) & (e1[3] == 0) & (e1[1] == ballot))
        row = st["p2bv"][g, i]
        st["p2bv"] = st["p2bv"].at[g, i].set(jnp.where(
            (jnp.arange(S) == slot - 1) & ok, row | (1 << i),
            row).astype(jnp.int32))

    def propose(st, g, i, cmd, sends: Sends, cond):
        """Leader-side proposal of a raw command (relay dedup:
        paxos.py:350-356 — equal in-flight unchosen entry absorbs)."""
        dup = jnp.asarray(False)
        for s in range(1, S + 1):
            e = log_get(st, g, i, jnp.asarray(s))
            dup = dup | ((e[0] == 1) & (e[3] == 0) & (e[2] == cmd))
        slot = st["si"][g, i]
        do = cond & ~dup & (slot <= S)
        log_set(st, g, i, slot, [1, st["b"][g, i], cmd, 0], do)
        _set(st, "si", g, i, jnp.where(do, slot + 1, slot))
        send_p2a(st, g, i, slot, sends, do)

    def handle_request(st, g, i, cmd, sends: Sends, cond, injected):
        """_propose / handle_PaxosRequest: leader proposes; a
        parent-injected request forwards once to the believed leader;
        a peer's forward is never re-forwarded (paxos.py:335-344)."""
        is_leader = (st["ld"][g, i] == 1) & (st["b"][g, i] % n == i)
        propose(st, g, i, cmd, sends, cond & is_leader)
        believed = st["b"][g, i] % n
        fwd = cond & ~is_leader & injected & (believed != i)
        sid = srv(g, i)
        for t in range(n):
            if t == i:
                continue
            sends.add(fwd & (believed == t), PREQ, sid, srv(g, t),
                      [cmd, 0, 0])

    # ----------------------------------------------------- message handler

    def step_message_raw(nodes, msg):
        tag, frm, to = msg[0], msg[1], msg[2]
        p = msg[3:]
        st = _unpack(nodes)
        all_sends = []
        all_sets = []

        # ---------------- master (node 0): collapsed ShardMaster paxos
        # (1-server group, timers frozen, static config list): decided
        # count + per-source AMO seq; a fresh query decides (mc + 1) and
        # replies, an exactly-cached one replies identically, an older
        # one is silent (AMO returns None, paxos.py:328-334).
        sends = Sends()
        m_here = to == 0
        is_q = m_here & (tag == QRY)
        qseq, arg = p[0], p[1]
        soh = jnp.arange(1 + G * n) == jnp.where(frm == CLIENT, 0, frm)
        cur = jnp.sum(soh * st["mamo"])
        fresh = is_q & (qseq > cur)
        st["mc"] = jnp.where(fresh, st["mc"] + 1, st["mc"]).astype(
            jnp.int32)
        st["mamo"] = jnp.where(soh & fresh, qseq,
                               st["mamo"]).astype(jnp.int32)
        reply = is_q & (qseq >= cur)
        kind = jnp.where((arg < 0) | (arg >= G), G - 1, arg)
        sends.add(reply, QREP, 0, frm, [qseq, kind, 0])
        all_sends.append(sends.finalize())
        all_sets.append(Sets().finalize())

        # ---------------- group servers
        for g in range(G):
            for i in range(n):
                sends, sets = Sends(), Sets()
                sid = srv(g, i)
                here = to == sid
                ballot = st["b"][g, i]

                # ---- QREP from master (handle_PaxosReply)
                is_qr = here & (tag == QREP)
                cfg_j = p[1]
                want = (is_qr & (cfg_j == st["scfg"][g, i])
                        & reconfig_done(st, g, i))
                handle_request(st, g, i, CMD_NC0 + cfg_j, sends, want,
                               jnp.asarray(True))

                # ---- SSREQ from client
                is_ss = here & (tag == SSREQ)
                handle_request(st, g, i, p[0], sends, is_ss,
                               jnp.asarray(True))

                # ---- PREQ (peer forward; never re-forwarded)
                is_pr = here & (tag == PREQ)
                handle_request(st, g, i, p[0], sends, is_pr,
                               jnp.asarray(False))

                # ---- ShardMove (only g2 receives; propose InstallShards)
                if g == 1:
                    is_sm = here & (tag == SM)
                    sm_ok = is_sm & (st["scfg"][g, i] == 2)
                    handle_request(st, g, i, CMD_IS0 + p[1], sends,
                                   sm_ok, jnp.asarray(True))
                # ---- ShardMoveAck (only g1; propose MoveDone)
                if g == 0:
                    is_sa = here & (tag == SMACK)
                    sa_ok = is_sa & (st["outf"][g, i] == 1)
                    handle_request(st, g, i, CMD_MD, sends, sa_ok,
                                   jnp.asarray(True))

                # ---- P1a (handle_P1a)
                is_p1a = here & (tag == P1A)
                mb = p[0]
                adopt = is_p1a & (mb > ballot)
                _set(st, "b", g, i, jnp.where(adopt, mb, st["b"][g, i]))
                _set(st, "ld", g, i, jnp.where(adopt, 0, st["ld"][g, i]))
                promise = is_p1a & (mb == st["b"][g, i])
                frm_i = (frm - 1 - g * n).clip(0, n - 1)
                sends.add(promise, P1B, sid, frm,
                          [st["b"][g, i]] + [
                              _pack_entry(st["log"][g, i, s, 0],
                                          st["log"][g, i, s, 1],
                                          st["log"][g, i, s, 2],
                                          st["log"][g, i, s, 3])
                              for s in range(S)])

                # ---- P1b (handle_P1b + win)
                is_p1b = here & (tag == P1B)
                vb = p[0]
                accept_vote = (is_p1b & (vb == st["b"][g, i])
                               & (st["b"][g, i] % n == i)
                               & (st["ld"][g, i] == 0))
                vlanes = [jnp.ones((), jnp.int32)]
                for s in range(S):
                    ex_, lb_, cm_, ch_ = _unpack_entry(
                        p[1 + s].astype(jnp.int32))
                    vlanes += [ex_, lb_, cm_, ch_]
                vrec = jnp.stack(vlanes).astype(jnp.int32)
                oh = jnp.arange(n) == frm_i
                st["votes"] = st["votes"].at[g, i].set(jnp.where(
                    (accept_vote & oh)[:, None], vrec[None, :],
                    st["votes"][g, i]).astype(jnp.int32))
                nvotes = jnp.sum(st["votes"][g, i][:, 0])
                win = accept_vote & (nvotes >= maj)
                _p1b_win(st, g, i, win, sends, sets)

                # ---- P2a
                is_p2a = here & (tag == P2A)
                ab, aslot, acmd = p[0], p[1], p[2]
                ok2a = is_p2a & (ab >= st["b"][g, i])
                _set(st, "ld", g, i, jnp.where(
                    ok2a & (ab > st["b"][g, i]), 0, st["ld"][g, i]))
                _set(st, "b", g, i, jnp.where(ok2a, ab, st["b"][g, i]))
                _set(st, "hd", g, i, jnp.where(ok2a, 1, st["hd"][g, i]))
                e = log_get(st, g, i, aslot)
                wr = ok2a & (aslot > st["cl"][g, i]) & ~((e[0] == 1)
                                                         & (e[3] == 1))
                log_set(st, g, i, aslot, [1, ab, acmd, 0], wr)
                sends.add(ok2a, P2B, sid, frm, [ab, aslot, 0])

                # ---- P2b
                is_p2b = here & (tag == P2B)
                bb, bslot = p[0], p[1]
                lead_ok = (is_p2b & (bb == st["b"][g, i])
                           & (st["ld"][g, i] == 1)
                           & (st["b"][g, i] % n == i))
                e = log_get(st, g, i, bslot)
                count_ok = lead_ok & (e[0] == 1) & (e[3] == 0) \
                    & (e[1] == bb)
                voh = jnp.arange(S) == bslot - 1
                vmask = jnp.sum(voh * st["p2bv"][g, i])
                vmask2 = jnp.where(count_ok,
                                   vmask | (1 << frm_i), vmask)
                chosen_now = count_ok & (_popcount(vmask2) >= maj)
                st["p2bv"] = st["p2bv"].at[g, i].set(jnp.where(
                    voh & count_ok, jnp.where(chosen_now, 0, vmask2),
                    st["p2bv"][g, i]).astype(jnp.int32))
                log_set(st, g, i, bslot, [1, e[1], e[2], 1], chosen_now)
                exec_chain(st, g, i, sends, chosen_now)

                # ---- Heartbeat
                is_hb = here & (tag == HB)
                hb_b, hb_commit, hb_gc = p[0], p[1], p[2]
                hb_ok = is_hb & (hb_b >= st["b"][g, i])
                _set(st, "ld", g, i, jnp.where(
                    hb_ok & (hb_b > st["b"][g, i]), 0, st["ld"][g, i]))
                _set(st, "b", g, i, jnp.where(hb_ok, hb_b,
                                              st["b"][g, i]))
                _set(st, "hd", g, i, jnp.where(hb_ok, 1, st["hd"][g, i]))
                gc_to(st, g, i, hb_gc, hb_ok)
                sends.add(hb_ok, HBR, sid, frm,
                          [st["b"][g, i], st["ex"][g, i], 0])

                # ---- HeartbeatReply
                is_hbr = here & (tag == HBR)
                hbr_ok = (is_hbr & (p[0] == st["b"][g, i])
                          & (st["ld"][g, i] == 1)
                          & (st["b"][g, i] % n == i))
                poh = jnp.arange(n) == frm_i
                pcur = jnp.sum(poh * st["peer"][g, i])
                st["peer"] = st["peer"].at[g, i].set(jnp.where(
                    poh & hbr_ok, jnp.maximum(pcur, p[1]),
                    st["peer"][g, i]).astype(jnp.int32))
                _set(st, "pm", g, i, jnp.where(
                    hbr_ok, st["pm"][g, i] | (1 << frm_i),
                    st["pm"][g, i]))
                maybe_gc(st, g, i, hbr_ok)

                all_sends.append(sends.finalize())
                all_sets.append(sets.finalize())

        # ---------------- client
        sends, sets = Sends(), Sets()
        c_here = to == CLIENT
        k = st["ck"]
        # QREP: adopt newer config; re-send pending
        is_qr = c_here & (tag == QREP)
        newer = is_qr & (p[1] + 1 > st["ccfg"])
        st["ccfg"] = jnp.where(newer, p[1] + 1,
                               st["ccfg"]).astype(jnp.int32)
        pend = k <= W
        send_now = newer & pend
        _client_send_pending(st, sends, send_now)
        # SSREP
        is_rep = c_here & (tag == SSREP) & (p[0] == k) & pend
        st["ck"] = jnp.where(is_rep, k + 1, st["ck"]).astype(jnp.int32)
        # WrongGroup -> re-query
        is_wg = c_here & (tag == WG) & (p[0] == k) & pend
        st["cq"] = jnp.where(is_wg, st["cq"] + 1,
                             st["cq"]).astype(jnp.int32)
        sends.add(is_wg, QRY, CLIENT, 0, [st["cq"], -1, 0])
        all_sends.append(sends.finalize())
        all_sets.append(sets.finalize())
        return (_repack(st), jnp.concatenate(all_sends),
                jnp.concatenate(all_sets))

    def _pad(rows, budget, width):
        if rows.shape[0] < budget:
            rows = jnp.concatenate([
                rows, jnp.full((budget - rows.shape[0], width), SENTINEL,
                               jnp.int32)])
        return rows

    def _client_send_pending(st, sends: Sends, cond):
        """_send_pending: broadcast SSREQ(k) to the owning group of the
        pending command's shard under the client's known config (the
        client only ever re-queries when it has NO config, which cannot
        hold here: cond requires a config)."""
        k = st["ck"]
        kmask = jnp.sum(jnp.where(jnp.arange(W) == (k - 1) % W,
                                  jnp.asarray(put_mask, jnp.int32), 0))
        for g in range(G):
            gm = group_mask(g, st["ccfg"] - 1)
            owns = (kmask & gm) == kmask
            for i in range(n):
                sends.add(cond & owns & (st["ccfg"] > 0), SSREQ, CLIENT,
                          srv(g, i), [k, 0, 0])

    def _p1b_win(st, g, i, win, sends: Sends, sets: Sets):
        ballot = st["b"][g, i]
        _set(st, "ld", g, i, jnp.where(win, 1, st["ld"][g, i]))
        st["p2bv"] = st["p2bv"].at[g, i].set(jnp.where(
            win, jnp.zeros((S,), jnp.int32), st["p2bv"][g, i]))
        _set(st, "pm", g, i, jnp.where(win, 1 << i, st["pm"][g, i]))
        me = jnp.arange(n) == i
        st["peer"] = st["peer"].at[g, i].set(jnp.where(
            win, jnp.where(me, st["ex"][g, i], 0),
            st["peer"][g, i]).astype(jnp.int32))
        # adoption: chosen wins; else max-ballot accepted
        for s in range(1, S + 1):
            a_ex = jnp.zeros((), jnp.int32)
            a_b = jnp.full((), -1, jnp.int32)
            a_c = jnp.zeros((), jnp.int32)
            a_ch = jnp.zeros((), jnp.int32)
            for t in range(n):
                have = st["votes"][g, i][t, 0]
                ex_ = st["votes"][g, i][t, 1 + 4 * (s - 1) + 0]
                vb_ = st["votes"][g, i][t, 1 + 4 * (s - 1) + 1]
                vc_ = st["votes"][g, i][t, 1 + 4 * (s - 1) + 2]
                vch = st["votes"][g, i][t, 1 + 4 * (s - 1) + 3]
                valid = (have == 1) & (ex_ == 1)
                take = valid & ((vch == 1) & (a_ch == 0)
                                | (a_ch == 0) & ((a_ex == 0)
                                                 | (vb_ > a_b)))
                a_b = jnp.where(take, vb_, a_b)
                a_c = jnp.where(take, vc_, a_c)
                a_ch = jnp.where(take, jnp.maximum(a_ch, vch), a_ch)
                a_ex = jnp.where(take, 1, a_ex)
            mine = st["log"][g, i, s - 1]
            adopt = win & (a_ex == 1) & (jnp.asarray(s) > st["cl"][g, i]) \
                & ~((mine[0] == 1) & (mine[3] == 1))
            log_set(st, g, i, jnp.asarray(s), [1, ballot, a_c, a_ch],
                    adopt)
        top = st["cl"][g, i]
        for s in range(1, S + 1):
            e = st["log"][g, i, s - 1]
            top = jnp.where(e[0] == 1, jnp.asarray(s, jnp.int32), top)
        for s in range(1, S + 1):
            e = st["log"][g, i, s - 1]
            in_span = win & (jnp.asarray(s) > st["ex"][g, i]) & \
                (jnp.asarray(s) <= top)
            fill = in_span & (e[0] == 0)
            log_set(st, g, i, jnp.asarray(s), [1, ballot, 0, 0], fill)
            e2 = st["log"][g, i, s - 1]
            reprop = in_span & (e2[3] == 0)
            send_p2a(st, g, i, jnp.asarray(s, jnp.int32), sends, reprop)
        _set(st, "si", g, i, jnp.where(win, top + 1, st["si"][g, i]))
        exec_chain(st, g, i, sends, win)
        sets.add(win, srv(g, i), T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS,
                 ballot)
        heartbeat_sends(st, g, i, sends, win)

    def heartbeat_sends(st, g, i, sends: Sends, cond):
        sid = srv(g, i)
        for t in range(n):
            if t == i:
                continue
            sends.add(cond, HB, sid, srv(g, t),
                      [st["b"][g, i], st["ex"][g, i], st["gc"][g, i]])

    # ------------------------------------------------------- timer handler

    def step_timer_raw(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        st = _unpack(nodes)
        all_sends, all_sets = [], []

        for g in range(G):
            for i in range(n):
                sends, sets = Sends(), Sets()
                sid = srv(g, i)
                here = node_idx == sid
                ballot = st["b"][g, i]
                is_leader = (st["ld"][g, i] == 1) & (ballot % n == i)

                # ---- ElectionTimer
                is_el = here & (tag == T_ELECTION)
                elect = is_el & ~is_leader & (st["hd"][g, i] == 0)
                new_ballot = (ballot // n + 1) * n + i
                _set(st, "b", g, i, jnp.where(elect, new_ballot,
                                              st["b"][g, i]))
                _set(st, "ld", g, i, jnp.where(elect, 0,
                                               st["ld"][g, i]))
                st["votes"] = st["votes"].at[g, i].set(jnp.where(
                    elect, jnp.zeros((n, 1 + 4 * S), jnp.int32),
                    st["votes"][g, i]).astype(jnp.int32))
                for t in range(n):
                    if t == i:
                        continue
                    sends.add(elect, P1A, sid, srv(g, t),
                              [new_ballot, 0, 0])
                own = jnp.concatenate([
                    jnp.ones((1,), jnp.int32),
                    st["log"][g, i].reshape(4 * S)])
                oh = jnp.arange(n) == i
                st["votes"] = st["votes"].at[g, i].set(jnp.where(
                    (elect & oh)[:, None], own[None, :],
                    st["votes"][g, i]).astype(jnp.int32))
                _set(st, "hd", g, i, jnp.where(is_el, 0,
                                               st["hd"][g, i]))
                sets.add(is_el, sid, T_ELECTION, ELECTION_MIN,
                         ELECTION_MAX, 0)

                # ---- HeartbeatTimer
                is_hbt = here & (tag == T_HEARTBEAT)
                live = is_hbt & (p0 == st["b"][g, i]) & is_leader
                heartbeat_sends(st, g, i, sends, live)
                for s in range(1, S + 1):
                    e = st["log"][g, i, s - 1]
                    inflight = (live & (jnp.asarray(s) > st["ex"][g, i])
                                & (jnp.asarray(s) < st["si"][g, i])
                                & (e[0] == 1) & (e[3] == 0))
                    send_p2a(st, g, i, jnp.asarray(s, jnp.int32), sends,
                             inflight)
                sets.add(live, sid, T_HEARTBEAT, HEARTBEAT_MS,
                         HEARTBEAT_MS, p0)

                # ---- QueryTimer (on_QueryTimer: leader-gated query +
                # move re-send; ALWAYS re-arms)
                is_qt = here & (tag == T_QUERY)
                q_ok = is_qt & is_leader & (
                    reconfig_done(st, g, i) | (st["scfg"][g, i] == 0))
                _set(st, "qseq", g, i, jnp.where(
                    q_ok, st["qseq"][g, i] + 1, st["qseq"][g, i]))
                sends.add(q_ok, QRY, sid, 0,
                          [st["qseq"][g, i], st["scfg"][g, i], 0])
                if g == 0:
                    resend = is_qt & is_leader & (st["outf"][g, i] == 1) \
                        & (st["scfg"][g, i] == 2)
                    for t in range(n):
                        sends.add(resend, SM, sid, srv(1, t),
                                  [jnp.asarray(1), st["osamo"][g, i], 0])
                sets.add(is_qt, sid, T_QUERY, QUERY_MS, QUERY_MS, 0)

                all_sends.append(sends.finalize())
                all_sets.append(sets.finalize())

        # ---- client retry timer
        sends, sets = Sends(), Sets()
        c_here = node_idx == CLIENT
        k = st["ck"]
        live = c_here & (tag == T_CLIENT) & (p0 == k) & (k <= W)
        # on_ClientTimer: _query_config; _send_pending (re-queries AGAIN
        # with no config, else broadcasts); re-arm.
        st["cq"] = jnp.where(live, st["cq"] + 1, st["cq"]).astype(
            jnp.int32)
        sends.add(live, QRY, CLIENT, 0, [st["cq"], -1, 0])
        no_cfg = st["ccfg"] == 0
        st["cq"] = jnp.where(live & no_cfg, st["cq"] + 1,
                             st["cq"]).astype(jnp.int32)
        sends.add(live & no_cfg, QRY, CLIENT, 0, [st["cq"], -1, 0])
        _client_send_pending(st, sends, live & ~no_cfg)
        sets.add(live, CLIENT, T_CLIENT, CLIENT_MS, CLIENT_MS, k)
        all_sends.append(sends.finalize())
        all_sets.append(sets.finalize())
        return (_repack(st), jnp.concatenate(all_sends),
                jnp.concatenate(all_sets))

    # ------------------------------------------------------------ initials

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        for g in range(G):
            for i in range(n):
                nodes[SRV_OFF + (g * n + i) * SW + 3] = 1   # slot_in = 1
        nodes[K_OFF] = 1                             # client waiting on 1
        nodes[K_OFF + 2] = 2                         # qseq after init
        return nodes

    def init_messages():
        # The staged joined-then-client-added state: the client's two
        # config queries (init + send_command finding no config).
        return np.array([
            [QRY, CLIENT, 0, 1, -1, 0][:MW] + [0] * (MW - 6),
            [QRY, CLIENT, 0, 2, -1, 0][:MW] + [0] * (MW - 6),
        ], np.int32)

    def init_timers():
        recs = []
        for g in range(G):
            for i in range(n):
                recs.append([srv(g, i), T_ELECTION, ELECTION_MIN,
                             ELECTION_MAX, 0])
                recs.append([srv(g, i), T_QUERY, QUERY_MS, QUERY_MS, 0])
        recs.append([CLIENT, T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(recs, np.int32)

    def msg_dest(msg):
        return msg[2]

    def clients_done(state):
        return state["nodes"][K_OFF] == W + 1

    # ---- send/set budgets DISCOVERED from the handler traces (no hand
    # counting: eval_shape runs the tracing without any compute)
    i32 = jnp.int32
    m_sh = jax.eval_shape(step_message_raw,
                          jax.ShapeDtypeStruct((NW,), i32),
                          jax.ShapeDtypeStruct((MW,), i32))
    t_sh = jax.eval_shape(step_timer_raw,
                          jax.ShapeDtypeStruct((NW,), i32),
                          jax.ShapeDtypeStruct((), i32),
                          jax.ShapeDtypeStruct((TW,), i32))
    MAX_SENDS = max(m_sh[1].shape[0], t_sh[1].shape[0])
    MAX_SETS = max(m_sh[2].shape[0], t_sh[2].shape[0])

    def step_message(nodes, msg):
        st, rows, tsets = step_message_raw(nodes, msg)
        return (st, _pad(rows, MAX_SENDS, MW),
                _pad(tsets, MAX_SETS, 1 + TW))

    def step_timer(nodes, node_idx, timer):
        st, rows, tsets = step_timer_raw(nodes, node_idx, timer)
        return (st, _pad(rows, MAX_SENDS, MW),
                _pad(tsets, MAX_SETS, 1 + TW))

    return TensorProtocol(
        name=f"shardstore-multi-g{G}x{n}-w{W}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        max_live_sends=min(32, MAX_SENDS),
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        goals={"CLIENTS_DONE": clients_done},
    )


def _popcount(x):
    x = x.astype(jnp.uint32)
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    return ((x * 0x01010101) >> 24).astype(jnp.int32)
