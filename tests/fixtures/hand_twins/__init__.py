"""Hand-written tensor twins, retired from the shipped tree in favor
of the compiled specs (tpu/specs_lab3.py, tpu/specs_lab4.py).  They
stay here as parity ORACLES: tests/test_spec_parity.py checks the
generated protocols reproduce their state counts exactly."""
