"""Tensorised twin of lab 4's sharded KV store for the search-test
configurations (ShardStorePart1Test.test10-12 shapes): G groups of ONE
server each, one shard master, NC clients, the config controller and
master timers frozen (tests/test_lab4_shardstore.py test10-13 mirror
these settings from ShardStoreBaseTest.java:209-220).

Why the state collapses (all against the object implementations in
dslabs_tpu/labs/shardedstore/shardstore.py and labs/paxos/paxos.py):

* A one-server Paxos group decides synchronously: ``_send_to_all``
  delivers the leader's own P1a/P2a/P2b locally (paxos.py:238-247),
  majority = 1, so a proposal is chosen, executed, AND garbage-collected
  inside the original handler call (exec -> _leader_exec_update ->
  maybe_gc clears through the executed prefix when n == 1).  The
  replicated log is always empty in every reachable state — no log
  lanes; what remains is the decided-slot COUNT, the heard_from_leader
  flag (set by the self-delivered P2a, cleared by ElectionTimer), and
  the constant ballot from the immediate self-election at init.

* The shard master (PaxosServer + ShardMaster app, timers frozen) logs
  every FRESH Query — handle_PaxosRequest AMO-wraps read-only commands
  like any other (paxos.py:326-360).  After the staged Joins its config
  list is STATIC ([cfg0] for G=1; [cfg0, cfg1] for G=2 — one config per
  Join), so a reply's payload is f(query arg): arg < 0 or beyond the
  list -> the latest config, else configs[arg] (shardmaster.py Query).

* The config walk (G=2): each group server queries for config
  _next_config_num() and installs replies in order None -> cfg0 -> cfg1
  (shardstore.py _apply_new_config).  Installing cfg1 at group 1 stores
  a SNAPSHOT of the lost shards' kv + the full AMO map in ``outgoing``;
  every later QueryTimer re-sends the SAME stored ShardMove, so the
  move's content is the per-client executed-seq vector at install time.
  Group 2 proposes InstallShards on a matching move (owned |= shards,
  AMO merged as a per-client max), acks, and group 1's MoveDone clears
  outgoing.  While a handoff is pending, ``_reconfig_done`` gates
  further queries (on_QueryTimer) and config installs.

* Every client queries with arg -1, so it only ever learns the LATEST
  config — one has-config bit per client — and routes commands by that
  final mapping; a group that does not yet cover a command's shard
  answers WrongGroup (config current, shard not mine) or stays silent
  (shard mine but still in flight), both mirrored per scfg/in_flag.

Node lanes (node order: 0 = master, 1..G = group servers,
G+1..G+NC = clients); NC = number of clients:
  master  [mc, mamo_c1..cNC, mamo_s1..sG]  decided count + AMO per source
  server g [scfg, scnt, sh, sq, out_flag, in_flag,
            samo_c1..cNC, osamo_c1..cNC]
    scfg: 0 = no config, i+1 = configs[i] installed
  client c [k, cfg, cq]                    workload index (W_c+1 = done),
                                           latest config known, query seq

Message lanes [tag, a, b, c, ...] (MW = max(4, 2 + NC)):
  QRY   [src, seq, arg]    PaxosRequest(AMOCommand(Query(arg), src, seq))
                           src: c in [0, NC) = client c, NC+g-1 = server g
  QREP  [dst, seq, kind]   PaxosReply(AMOResult(configs[kind], seq))
  SSREQ [c, k]             ShardStoreRequest(AMOCommand(cmd, client_c, k))
  SSREP [c, k]             ShardStoreReply(AMOResult(result, k))
  WG    [c, k]             WrongGroup(k)
  SM    [to_g, samo_1..NC] ShardMove(cfg1, from g1, shards, snapshot)
  SMACK [to_g]             ShardMoveAck(cfg1, shards)
Timer lanes [tag, min, max, p0]: CLIENT(seq) / QUERY / ELECTION / HEARTBEAT.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_shardstore_protocol"]

QRY, QREP, SSREQ, SSREP, WG, SM, SMACK, JREQ, JREP = range(9)
T_CLIENT, T_QUERY, T_ELECTION, T_HEARTBEAT = 1, 2, 3, 4

CLIENT_MS = 100     # shardstore.py CLIENT_RETRY_MILLIS
QUERY_MS = 50       # shardstore.py QUERY_MILLIS
ELECTION_MIN, ELECTION_MAX = 150, 300   # paxos.py
HEARTBEAT_MS = 50


def make_shardstore_protocol(groups_of,
                             net_cap: int = 48,
                             timer_cap: int = 6,
                             model_master_timers: bool = False,
                             model_ctl: bool = False) -> TensorProtocol:
    """``groups_of``: per-client, per-command owning group (1-based)
    under the FINAL config — ``groups_of[c][k-1]`` for client c's k-th
    command; a flat int list means one client (the original test10/11
    shape).  Precomputed on the host with the same ShardMaster rebalance
    the object system runs (see tests/test_tpu_lab4.py).
    G = max over all; with G = 2 the config walk and the g1 -> g2
    handoff are modelled (groups are built by successive Joins, so every
    shard a 2-group config assigns to g2 was g1's under cfg0)."""
    # ``model_master_timers``: the master's election/heartbeat timers are
    # live (test13's random search narrows nothing) — one extra heard
    # lane toggled exactly like the group servers'.  ``model_ctl``: the
    # config controller node and its join-phase debris are deliverable —
    # G pending ClientTimers (stale: delivery consumes, no re-arm,
    # paxos.py:505-520 with pending=None) and the 2G join REQ/REP
    # messages (REQ(G) re-replies the CACHED identical REP, every other
    # delivery is a no-op self-loop).  Both default off: the test10-12
    # settings suppress these events, and the runtime masks make them
    # invalid anyway — modelling them would only widen the grids.
    if groups_of and isinstance(groups_of[0], int):
        groups_of = [list(groups_of)]
    per_client: List[List[int]] = [list(g) for g in groups_of]
    NC = len(per_client)
    Ws = [len(g) for g in per_client]
    G = max(max(g) for g in per_client)
    assert all(min(g) >= 1 for g in per_client)
    assert G <= 2, "3+-group configs need multi-hop handoff modelling"
    N_CFG = G                       # one config per staged Join
    MW = max(4, 2 + NC)
    TW = 4
    SB = 6 + 2 * NC                 # server block width
    NW = (2 + NC + G) + SB * G + 3 * NC
    # CCA rides as a last node only when its debris is deliverable (its
    # only mutable state is the timer queue the engine already models).
    N_NODES = 1 + G + NC + (1 if model_ctl else 0)
    CCA = 1 + G + NC

    # lane offsets
    M_MC = 0
    M_H = 1                                   # heard_from_leader
    M_AMOC = 2                                # + c
    M_AMOS = 2 + NC                           # + g-1
    SRV = 2 + NC + G                          # server g: SRV + SB*(g-1)
    CLI = SRV + SB * G                        # client c: CLI + 3*c
    # server lane offsets within a block
    S_CFG, S_CNT, S_H, S_Q, S_OUT, S_IN = range(6)
    S_AMO = 6                                 # + c
    S_OSAMO = 6 + NC                          # + c

    def srv(g, off):
        return SRV + SB * (g - 1) + off

    def cli(c, off):
        return CLI + 3 * c + off

    def node_of(c):
        return G + 1 + c

    def grp_of(c, k):
        """Traced (client, workload index) -> owning group under the
        final config (static where-chain)."""
        out = jnp.asarray(per_client[0][0], jnp.int32)
        for cs in range(NC):
            for kk in range(1, Ws[cs] + 1):
                if (cs, kk) == (0, 1):
                    continue
                out = jnp.where((c == cs) & (k == kk),
                                per_client[cs][kk - 1], out)
        return out

    def msg_row(cond, tag, *payload):
        vals = [tag, *payload] + [0] * (MW - 1 - len(payload))
        rec = jnp.stack([jnp.asarray(x, jnp.int32) for x in vals])
        return jnp.where(cond, rec,
                         jnp.full((MW,), SENTINEL, jnp.int32))[None]

    def timer_row(cond, node, tag, mn, mx, p0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32)
                         for x in (node, tag, mn, mx, p0)])
        return jnp.where(cond, rec,
                         jnp.full((1 + TW,), SENTINEL, jnp.int32))[None]

    blank_msg = jnp.full((1, MW), SENTINEL, jnp.int32)
    blank_set = jnp.full((1, 1 + TW), SENTINEL, jnp.int32)

    # Which config index the master serves for a query arg
    # (shardmaster.py Query: arg < 0 or >= len -> latest).
    def served_kind(arg):
        latest = N_CFG - 1
        kind = jnp.where((arg < 0) | (arg >= N_CFG), latest, arg)
        return kind.astype(jnp.int32)

    # Does group g own command (c, k)'s shard under configs[idx]
    # (0-based)?  cfg0 assigns everything to group 1; the final config
    # follows groups_of.
    def cfg_mine(g, cfg_idx, c, k):
        under_final = grp_of(c, k) == g
        if g == 1:
            return jnp.where(cfg_idx == 0, True, under_final)
        return jnp.where(cfg_idx == 0, False, under_final)

    # ------------------------------------------------------------- handlers

    def step_message(nodes, msg):
        tag, a, b = msg[0], msg[1], msg[2]
        sends = []
        tsets = []

        # ---- QRY -> master (paxos.py handle_PaxosRequest; n=1: fresh
        # commands decide+execute+GC inline).  Sources: clients 0..NC-1,
        # servers NC..NC+G-1.
        is_qry = tag == QRY
        src, seq, arg = a, b, msg[3]
        for sidx in range(0, NC + G):
            lane = (M_AMOC + sidx if sidx < NC
                    else M_AMOS + sidx - NC)
            here = is_qry & (src == sidx)
            last = nodes[lane]
            fresh = here & (seq > last)
            nodes = nodes.at[lane].set(
                jnp.where(fresh, seq, last).astype(jnp.int32))
            nodes = nodes.at[M_MC].set(
                jnp.where(fresh, nodes[M_MC] + 1,
                          nodes[M_MC]).astype(jnp.int32))
            # A fresh proposal's self-delivered P2a sets the master's
            # heard_from_leader (paxos.py:367) — observable only when
            # its ElectionTimer is live (M_H is frozen at 1 otherwise).
            nodes = nodes.at[M_H].set(
                jnp.where(fresh, 1, nodes[M_H]).astype(jnp.int32))
            # reply for fresh or exactly-cached seq; payload = the served
            # config (dup deliveries carry the same arg, so recomputing
            # the kind from the message matches the cached result)
            sends.append(msg_row(here & (seq >= last), QREP, src, seq,
                                 served_kind(arg)))

        # ---- QREP -> client c: adopt the (always latest) config if
        # newer, then send the pending command (shardstore.py client
        # handle_PaxosReply + _send_pending)
        for c in range(NC):
            here = (tag == QREP) & (a == c)
            k = nodes[cli(c, 0)]
            adopt = here & (nodes[cli(c, 1)] == 0)
            nodes = nodes.at[cli(c, 1)].set(
                jnp.where(adopt, 1, nodes[cli(c, 1)]).astype(jnp.int32))
            sends.append(msg_row(adopt & (k <= Ws[c]), SSREQ, c, k))

        # ---- QREP -> server g: propose NewConfig iff the carried config
        # is exactly _next_config_num() and reconfig is done
        # (shardstore.py handle_PaxosReply + _apply_new_config)
        for g in range(1, G + 1):
            here = (tag == QREP) & (a == NC + g - 1)
            kind = msg[3]                             # configs[kind]
            scfg = nodes[srv(g, S_CFG)]
            done = ((nodes[srv(g, S_OUT)] == 0)
                    & (nodes[srv(g, S_IN)] == 0))
            install = here & (kind == scfg) & (scfg < N_CFG) & done
            # installing the FINAL config starts the handoff (only group
            # transitions that move shards: g1 loses, g2 gains; the first
            # config never moves anything)
            is_final = install & (scfg == N_CFG - 1) & (N_CFG > 1)
            if g == 1 and G > 1:
                nodes = nodes.at[srv(g, S_OUT)].set(
                    jnp.where(is_final, 1,
                              nodes[srv(g, S_OUT)]).astype(jnp.int32))
                for c in range(NC):
                    nodes = nodes.at[srv(g, S_OSAMO + c)].set(
                        jnp.where(is_final, nodes[srv(g, S_AMO + c)],
                                  nodes[srv(g, S_OSAMO + c)]
                                  ).astype(jnp.int32))
                # leader installs -> _send_moves inline
                sends.append(msg_row(
                    is_final, SM, 2,
                    *[nodes[srv(g, S_AMO + c)] for c in range(NC)]))
            elif g == 2:
                nodes = nodes.at[srv(g, S_IN)].set(
                    jnp.where(is_final, 1,
                              nodes[srv(g, S_IN)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_CFG)].set(
                jnp.where(install, scfg + 1,
                          nodes[srv(g, S_CFG)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_CNT)].set(
                jnp.where(install, nodes[srv(g, S_CNT)] + 1,
                          nodes[srv(g, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_H)].set(
                jnp.where(install, 1,
                          nodes[srv(g, S_H)]).astype(jnp.int32))

        # ---- SSREQ -> server grp_of(c, k): ALWAYS proposes (relay-mode
        # chosen entries are not deduped, paxos.py:349-355) -> count+1,
        # heard; execution is gated by config coverage and ownership
        # (shardstore.py _execute_client_command)
        is_ss = tag == SSREQ
        cc, kk = a, b
        kg = grp_of(cc, kk)
        for g in range(1, G + 1):
            here = is_ss & (kg == g)
            nodes = nodes.at[srv(g, S_CNT)].set(
                jnp.where(here, nodes[srv(g, S_CNT)] + 1,
                          nodes[srv(g, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_H)].set(
                jnp.where(here, 1, nodes[srv(g, S_H)]).astype(jnp.int32))
            scfg = nodes[srv(g, S_CFG)]
            has_cfg = scfg >= 1
            mine = (cfg_mine(g, (scfg - 1).clip(0, N_CFG - 1), cc, kk)
                    & has_cfg)
            # wrong group: current config exists but shard is not mine
            sends.append(msg_row(here & has_cfg & ~mine, WG, cc, kk))
            # mine but still incoming -> silent (client retries); only
            # group 2 ever gains shards, in one block per handoff
            if g == 2 and G > 1:
                owned = mine & (nodes[srv(g, S_IN)] == 0)
            else:
                owned = mine
            # per-client AMO high-water (static select over c)
            samo = nodes[srv(g, S_AMO)]
            for c in range(1, NC):
                samo = jnp.where(cc == c, nodes[srv(g, S_AMO + c)], samo)
            execd = here & owned & (kk > samo)        # owned ⊆ mine
            for c in range(NC):
                nodes = nodes.at[srv(g, S_AMO + c)].set(
                    jnp.where(execd & (cc == c), kk,
                              nodes[srv(g, S_AMO + c)]).astype(jnp.int32))
            sends.append(msg_row(here & owned & (kk >= samo),
                                 SSREP, cc, kk))

        # ---- SSREP -> client (ClientWorker pumps the next command)
        is_rep = tag == SSREP
        for c in range(NC):
            k = nodes[cli(c, 0)]
            match = is_rep & (a == c) & (b == k) & (k <= Ws[c])
            k2 = jnp.where(match, k + 1, k)
            nodes = nodes.at[cli(c, 0)].set(k2.astype(jnp.int32))
            has_next = match & (k2 <= Ws[c])
            sends.append(msg_row(has_next, SSREQ, c, k2))
            tsets.append(timer_row(has_next, node_of(c), T_CLIENT,
                                   CLIENT_MS, CLIENT_MS, k2))

        # ---- WG -> client: re-query (shardstore.py handle_WrongGroup)
        for c in range(NC):
            k = nodes[cli(c, 0)]
            is_wg = ((tag == WG) & (a == c) & (b == k) & (k <= Ws[c]))
            cq = nodes[cli(c, 2)]
            nodes = nodes.at[cli(c, 2)].set(
                jnp.where(is_wg, cq + 1, cq).astype(jnp.int32))
            sends.append(msg_row(is_wg, QRY, c, cq + 1, -1))

        # ---- join-phase debris (model_ctl): REQ(G) re-replies the
        # cached result — an IDENTICAL row the network set dedupes, so
        # every debris delivery is a self-loop (paxos.py:326-344 with
        # seq <= amo; PaxosClient.handle_PaxosReply with pending=None).
        if model_ctl:
            sends.append(msg_row((tag == JREQ) & (a == G), JREP, G))

        # ---- SM -> group 2: propose InstallShards when at the final
        # config with the shards still incoming; re-ack when already
        # installed; ignore when behind (shardstore.py handle_ShardMove)
        if G > 1:
            is_sm = (tag == SM) & (a == 2)
            scfg2 = nodes[srv(2, S_CFG)]
            at_final = scfg2 == N_CFG
            inst = is_sm & at_final & (nodes[srv(2, S_IN)] == 1)
            reack = is_sm & at_final & (nodes[srv(2, S_IN)] == 0)
            nodes = nodes.at[srv(2, S_CNT)].set(
                jnp.where(inst, nodes[srv(2, S_CNT)] + 1,
                          nodes[srv(2, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(2, S_H)].set(
                jnp.where(inst, 1, nodes[srv(2, S_H)]).astype(jnp.int32))
            # AMO merge: per-client max of own and the snapshot's
            for c in range(NC):
                samo2 = nodes[srv(2, S_AMO + c)]
                nodes = nodes.at[srv(2, S_AMO + c)].set(
                    jnp.where(inst, jnp.maximum(samo2, msg[2 + c]),
                              samo2).astype(jnp.int32))
            nodes = nodes.at[srv(2, S_IN)].set(
                jnp.where(inst, 0, nodes[srv(2, S_IN)]).astype(jnp.int32))
            sends.append(msg_row(inst | reack, SMACK, 1))

            # ---- SMACK -> group 1: propose MoveDone while the handoff
            # is outstanding (shardstore.py handle_ShardMoveAck)
            is_ack = (tag == SMACK) & (a == 1)
            fin = is_ack & (nodes[srv(1, S_OUT)] == 1)
            nodes = nodes.at[srv(1, S_CNT)].set(
                jnp.where(fin, nodes[srv(1, S_CNT)] + 1,
                          nodes[srv(1, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(1, S_H)].set(
                jnp.where(fin, 1, nodes[srv(1, S_H)]).astype(jnp.int32))
            nodes = nodes.at[srv(1, S_OUT)].set(
                jnp.where(fin, 0, nodes[srv(1, S_OUT)]).astype(jnp.int32))

        sends = jnp.concatenate(
            sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(
            tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        sends = []
        tsets = []

        # ---- ClientTimer (shardstore.py on_ClientTimer): re-query (+1
        # more query when there is no config yet — _send_pending falls
        # back to _query_config) and re-send the pending command.
        for c in range(NC):
            k = nodes[cli(c, 0)]
            live = ((node_idx == node_of(c)) & (tag == T_CLIENT)
                    & (p0 == k) & (k <= Ws[c]))
            cq = nodes[cli(c, 2)]
            has_cfg = nodes[cli(c, 1)] == 1
            cq2 = jnp.where(live, jnp.where(has_cfg, cq + 1, cq + 2), cq)
            nodes = nodes.at[cli(c, 2)].set(cq2.astype(jnp.int32))
            sends.append(msg_row(live, QRY, c, cq + 1, -1))
            sends.append(jnp.where(
                has_cfg,
                msg_row(live, SSREQ, c, k)[0],
                msg_row(live, QRY, c, cq + 2, -1)[0])[None])
            tsets.append(timer_row(live, node_of(c), T_CLIENT,
                                   CLIENT_MS, CLIENT_MS, k))

        for g in range(1, G + 1):
            here = node_idx == g
            # ---- QueryTimer (shardstore.py on_QueryTimer): the query
            # itself is gated on _reconfig_done; _send_moves always runs
            # (re-sends the stored ShardMove while a handoff is pending).
            is_q = here & (tag == T_QUERY)
            done = ((nodes[srv(g, S_OUT)] == 0)
                    & (nodes[srv(g, S_IN)] == 0))
            ask = is_q & done
            sq = nodes[srv(g, S_Q)]
            nodes = nodes.at[srv(g, S_Q)].set(
                jnp.where(ask, sq + 1, sq).astype(jnp.int32))
            sends.append(msg_row(ask, QRY, NC + g - 1, sq + 1,
                                 nodes[srv(g, S_CFG)]))
            if g == 1 and G > 1:
                sends.append(msg_row(
                    is_q & (nodes[srv(1, S_OUT)] == 1), SM, 2,
                    *[nodes[srv(1, S_OSAMO + c)] for c in range(NC)]))
            tsets.append(timer_row(is_q, g, T_QUERY,
                                   QUERY_MS, QUERY_MS, 0))

            # ---- ElectionTimer (paxos.py on_ElectionTimer): the lone
            # server is its own decided leader; only heard resets.
            is_el = here & (tag == T_ELECTION)
            nodes = nodes.at[srv(g, S_H)].set(
                jnp.where(is_el, 0, nodes[srv(g, S_H)]).astype(jnp.int32))
            tsets.append(timer_row(is_el, g, T_ELECTION,
                                   ELECTION_MIN, ELECTION_MAX, 0))

            # ---- HeartbeatTimer: no peers, nothing in flight — pure
            # re-arm (state unchanged).
            is_hb = here & (tag == T_HEARTBEAT)
            tsets.append(timer_row(is_hb, g, T_HEARTBEAT,
                                   HEARTBEAT_MS, HEARTBEAT_MS, 0))

        # ---- master ElectionTimer/HeartbeatTimer (model_master_timers):
        # the lone master is its own decided leader — heard resets on
        # election, heartbeat is a pure re-arm (paxos.py:261-265,
        # 412-427), exactly the group-server pattern.
        if model_master_timers:
            m_el = (node_idx == 0) & (tag == T_ELECTION)
            nodes = nodes.at[M_H].set(
                jnp.where(m_el, 0, nodes[M_H]).astype(jnp.int32))
            tsets.append(timer_row(m_el, 0, T_ELECTION,
                                   ELECTION_MIN, ELECTION_MAX, 0))
            m_hb = (node_idx == 0) & (tag == T_HEARTBEAT)
            tsets.append(timer_row(m_hb, 0, T_HEARTBEAT,
                                   HEARTBEAT_MS, HEARTBEAT_MS, 0))

        # ---- the controller's stale ClientTimers (model_ctl): pending
        # is None after the joins, so delivery only consumes the timer
        # (no re-arm, no sends) — the state change IS the queue pop.

        sends = jnp.concatenate(
            sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(
            tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    # Row budgets = the TOTAL rows each step function appends (rows are
    # individually condition-masked; the pad/slice below must never
    # truncate a real row).
    MSG_SENDS = ((NC + G)               # QRY -> QREP per source
                 + NC                   # QREP-client adopt SSREQ
                 + (1 if G > 1 else 0)  # g1 install SM
                 + 2 * G                # SSREQ: WG + SSREP per g
                 + NC                   # SSREP pump per client
                 + NC                   # WG re-query per client
                 + (1 if G > 1 else 0)  # SM -> SMACK
                 + (1 if model_ctl else 0))   # JREQ re-reply
    TMR_SENDS = 2 * NC + G + (1 if G > 1 else 0)
    MAX_SENDS = max(MSG_SENDS, TMR_SENDS)
    MAX_SETS = max(NC, NC + 3 * G
                   + (2 if model_master_timers else 0))

    # ------------------------------------------------------------- initials

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        nodes[M_MC] = G          # one decided Join per group
        nodes[M_H] = 1           # the final fresh Join's self-P2a
        for c in range(NC):
            nodes[cli(c, 0)] = 1     # first command pending
            # init() queries once; send_command -> _send_pending with no
            # config falls back to _query_config and queries AGAIN
            # (shardstore.py:624-650), so two queries are in flight.
            nodes[cli(c, 2)] = 2
        return nodes

    def init_messages():
        rows = [[QRY, c, s, -1] + [0] * (MW - 4)
                for c in range(NC) for s in (1, 2)]
        if model_ctl:
            for j in range(1, G + 1):
                rows.append([JREQ, j] + [0] * (MW - 2))
                rows.append([JREP, j] + [0] * (MW - 2))
        return np.array(rows, np.int32)

    def init_timers():
        rows = []
        if model_master_timers:
            rows.append([0, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0])
            rows.append([0, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, 0])
        if model_ctl:
            for j in range(1, G + 1):
                rows.append([CCA, T_CLIENT, CLIENT_MS, CLIENT_MS, j])
        for g in range(1, G + 1):
            # ShardStoreServer.init: paxos.init (Election, then the
            # immediate self-election arms Heartbeat), then QueryTimer.
            rows.append([g, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0])
            rows.append([g, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, 0])
            rows.append([g, T_QUERY, QUERY_MS, QUERY_MS, 0])
        for c in range(NC):
            rows.append([node_of(c), T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(rows, np.int32)

    def msg_dest(msg):
        tag, a = msg[0], msg[1]
        dest = jnp.asarray(0, jnp.int32)                      # QRY -> master
        dest = jnp.where(tag == QREP,
                         jnp.where(a < NC, G + 1 + a, a - NC + 1), dest)
        dest = jnp.where(tag == SSREQ, grp_of(a, msg[2]), dest)
        dest = jnp.where((tag == SSREP) | (tag == WG), G + 1 + a, dest)
        dest = jnp.where((tag == SM) | (tag == SMACK), a, dest)
        dest = jnp.where(tag == JREP, CCA, dest)     # JREQ stays 0
        return dest

    def clients_done(state):
        done = jnp.asarray(True)
        for c in range(NC):
            done = done & (state["nodes"][cli(c, 0)] == Ws[c] + 1)
        return done

    return TensorProtocol(
        name=f"shardstore-g{G}-c{NC}-w{sum(Ws)}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        goals={"CLIENTS_DONE": clients_done},
    )
