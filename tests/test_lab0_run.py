"""Lab 0 run tests — behavioural port of the reference's PingTest run half
(labs/lab0-pingpong/tst/dslabs/pingpong/PingTest.java:32-124): workload runs
to completion on the real-time emulated network, in both threading modes,
including under an unreliable network (retry timer must recover losses).
"""

import pytest

from dslabs_tpu.harness import RUN_TESTS, UNRELIABLE_TESTS, lab_test
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingClient, PingServer,
                                               Pong)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import RESULTS_OK
from dslabs_tpu.testing.workload import Workload
from dslabs_tpu.utils.structural import clone

SERVER = LocalAddress("pingserver")


def ping_parser(cmd, res):
    return Ping(cmd), (Pong(res) if res is not None else None)


def make_state(num_clients=1, num_pings=5):
    gen = NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=["ping-%i-%a" for _ in range(num_pings)],
            result_strings=["ping-%i-%a" for _ in range(num_pings)],
            parser=ping_parser),
    )
    state = RunState(gen)
    state.add_server(SERVER)
    for i in range(1, num_clients + 1):
        state.add_client_worker(LocalAddress(f"client{i}"))
    return state


def assert_results_ok(state):
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


@lab_test("0", 1, "Single client ping test", categories=(RUN_TESTS,))
def test_basic_run_multithreaded():
    state = make_state(num_clients=2)
    settings = RunSettings().max_time(10)
    state.run(settings)
    assert_results_ok(state)
    for w in state.client_workers().values():
        assert w.done()
        assert len(w.results) == 5


@lab_test("0", 5, "Single client ping test (single-threaded engine)", categories=(RUN_TESTS,))
def test_basic_run_single_threaded():
    state = make_state(num_clients=2)
    settings = RunSettings().max_time(10)
    settings.set_single_threaded(True)
    state.run(settings)
    assert_results_ok(state)
    for w in state.client_workers().values():
        assert w.done()


@lab_test("0", 3, "Client can still ping if some messages are dropped", categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test_unreliable_network_retries_recover():
    state = make_state(num_clients=1, num_pings=3)
    settings = RunSettings().max_time(20)
    settings.network_deliver_rate(0.5)
    state.run(settings)
    assert_results_ok(state)
    for w in state.client_workers().values():
        assert w.done()


@lab_test("0", 6, "Blocking get_result on the client interface", categories=(RUN_TESTS,))
def test_direct_client_blocking_get_result():
    """Drive a bare client (no worker) through the blocking Client API."""
    gen = NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER))
    state = RunState(gen)
    state.add_server(SERVER)
    client = state.add_client(LocalAddress("client1"))
    state.start(RunSettings())
    try:
        client.send_command(Ping("hello"))
        result = client.get_result(timeout=5)
        assert result == Pong("hello")
    finally:
        state.stop()


@lab_test("0", 7, "Client worker tracks max wait", categories=(RUN_TESTS,))
def test_max_wait_tracked():
    state = make_state(num_clients=1, num_pings=2)
    state.run(RunSettings().max_time(10))
    for w in state.client_workers().values():
        mw = w.max_wait(state.stop_time)
        assert mw is not None
        assert mw[0] < 1.0  # reliable local network: sub-second waits


@lab_test("0", 2, "Multiple clients can ping simultaneously", categories=(RUN_TESTS,))
def test02_multiple_clients_ping():
    """PingTest.test02MultipleClientsPing: ten clients, %a-templated
    workload (each pings its own address string)."""
    state = make_state(num_clients=0, num_pings=1)
    workload = Workload(command_strings=["hello from %a"],
                        result_strings=["hello from %a"],
                        parser=ping_parser)
    for i in range(1, 11):
        state.add_client_worker(LocalAddress(f"client{i}"), clone(workload))
    state.run(RunSettings().max_time(10))
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()
