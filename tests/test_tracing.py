"""End-to-end causal tracing + per-tenant cost accounting (ISSUE 13,
tpu/tracing.py, ``make trace-smoke``).

The contract under test:

* **Trace-ID discipline** — ``submit`` mints a trace id, the journal
  persists it, the warden threads it to children via
  ``DSLABS_TRACE_ID``/``DSLABS_PARENT_SPAN``, and every flight-recorder
  span / STATUS.json carries it, at ZERO added dispatches or transfers
  (the overhead guard in tests/test_telemetry.py is extended for this).
* **ACCEPTANCE** — a job whose child is SIGKILLed mid-level yields a
  ``telemetry trace`` timeline reconstructed FROM DISK ALONE with an
  unbroken parent chain submit -> queue -> admission -> attempt ->
  child run -> in-flight dispatch, the kill point named.
* **Cost ledger** — per-tenant COSTS.jsonl sums agree with the jobs'
  SearchOutcome counters EXACTLY; device-seconds by site and dispatch
  counts come from the spans already on disk.
* **Torn reads** — the assembler and the ``service status`` CLI
  tolerate a mid-write SERVER_STATUS snapshot and a torn COSTS tail.
* **Retention** — the scheduler-idle sweep prunes only finished run
  dirs, never running/queued jobs, journaling each prune.
* **Ledger compare** — compile-time creep and cost-per-unique-state
  regressions are flagged with the same rc-1 discipline as the rate
  guards.
"""

import json
import os

import pytest

from dslabs_tpu.tpu import tracing
from dslabs_tpu.tpu import telemetry as tel_mod

pytestmark = pytest.mark.trace

FACTORY = ("dslabs_tpu.tpu.protocols.pingpong:"
           "make_exhaustive_pingpong")
SMALL = dict(factory_kwargs={"workload_size": 2}, chunk=64,
             frontier_cap=1 << 8, visited_cap=1 << 12)
CHILD_ENV = {"DSLABS_COMPILE_CACHE": "/tmp/jaxcache-cpu"}
GRACES = {"boot_grace": 120.0, "first_grace": 120.0,
          "steady_grace": 30.0, "idle_grace": 60.0, "grace_slack": 1.0}


def _server(root, **kw):
    from dslabs_tpu.service import CheckServer

    kw.setdefault("admission", False)
    kw.setdefault("elastic", False)
    kw.setdefault("env", CHILD_ENV)
    kw.setdefault("warden_kwargs", dict(GRACES))
    return CheckServer(str(root), **kw)


# ------------------------------------------------------------- id basics

def test_trace_ids_and_env_roundtrip(monkeypatch):
    a, b = tracing.mint_trace_id(), tracing.mint_trace_id()
    assert a != b and len(a) == 16 and len(tracing.new_span_id()) == 8
    assert tracing.attempt_span_id("t-000001", 2) == "t-000001:a2"
    env = tracing.child_trace_env(a, "t-000001:a2")
    assert env == {tracing.TRACE_ENV: a,
                   tracing.PARENT_ENV: "t-000001:a2"}
    monkeypatch.setenv(tracing.TRACE_ENV, a)
    monkeypatch.setenv(tracing.PARENT_ENV, "t-000001:a2")
    assert tracing.current_trace() == (a, "t-000001:a2")
    monkeypatch.delenv(tracing.TRACE_ENV)
    monkeypatch.delenv(tracing.PARENT_ENV)
    assert tracing.current_trace() == (None, None)


def test_read_flight_lax_and_segmentation(tmp_path):
    """A per-job flight log is appended to by EVERY child: a SIGKILLed
    first child can leave a torn line MID-file with a second child's
    records after it — the lax reader skips it (counted) and the
    segmenter scopes in-flight detection per child, because dispatch
    indices restart in every child."""
    p = tmp_path / "flight.jsonl"
    lines = [
        {"t": "meta", "started": 100.0, "span_id": "s1",
         "parent_span": "j:a1", "trace_id": "abc"},
        {"t": "dispatch", "ts": 0.1, "tag": "device.step", "i": 0},
        {"t": "span", "ts": 0.2, "tag": "device.step", "i": 0,
         "wall": 0.1},
        {"t": "dispatch", "ts": 0.3, "tag": "device.step", "i": 1},
    ]
    body = "\n".join(json.dumps(r) for r in lines)
    body += "\n" + '{"t": "span", "ts": 0.35, "tag":'       # torn
    lines2 = [
        {"t": "meta", "started": 110.0, "span_id": "s2",
         "parent_span": "j:a1", "trace_id": "abc"},
        {"t": "dispatch", "ts": 0.1, "tag": "host.expand", "i": 0},
        {"t": "span", "ts": 0.2, "tag": "host.expand", "i": 0,
         "wall": 0.1},
    ]
    body += "\n" + "\n".join(json.dumps(r) for r in lines2) + "\n"
    p.write_text(body)
    recs, torn = tracing.read_flight_lax(str(p))
    assert torn == 1 and len(recs) == 7
    segs = tracing.segment_flight(recs)
    assert len(segs) == 2
    # Segment 1 died inside device.step i=1; segment 2 is clean even
    # though its dispatch indices restarted at 0.
    assert segs[0]["in_flight"]["i"] == 1
    assert segs[0]["in_flight"]["tag"] == "device.step"
    assert segs[1]["in_flight"] is None


def test_load_json_tolerant_mid_write(tmp_path):
    p = tmp_path / "SERVER_STATUS.json"
    p.write_text('{"t": "server_status", "queue_de')   # mid-write
    assert tracing.load_json_tolerant(str(p)) is None
    p.write_text(json.dumps({"t": "server_status", "queue_depth": 0}))
    assert tracing.load_json_tolerant(str(p))["queue_depth"] == 0
    assert tracing.load_json_tolerant(str(tmp_path / "nope.json")) is None


# ------------------------------------------------ recorder integration

def test_spans_and_status_carry_trace_and_run_dir_trace_cli(
        tmp_path, monkeypatch, capsys):
    """A recorder inside a traced process stamps trace/span ids into
    the meta record, every span, and STATUS.json — and ``telemetry
    trace <run-dir>`` assembles the single-run causal tree from the
    flight log alone."""
    import dataclasses

    pytest.importorskip("jax")
    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    trace = tracing.mint_trace_id()
    monkeypatch.setenv(tracing.TRACE_ENV, trace)
    monkeypatch.setenv(tracing.PARENT_ENV, "job-1:a1")
    pp = make_pingpong_protocol(workload_size=2)
    pp = dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
    tel = tel_mod.Telemetry.for_checkpoint(
        str(tmp_path / "search.ckpt"), engine_hint="trace-test")
    assert tel.trace_id == trace and tel.parent_span == "job-1:a1"
    search = TensorSearch(pp, max_depth=8, frontier_cap=1 << 10,
                          visited_cap=1 << 12, telemetry=tel)
    out = search.run()
    tel.close()
    # The verdict is stamped at span emission (engine-side).
    assert out.trace_id == trace

    recs = tel_mod.read_flight(str(tmp_path / "flight.jsonl"))
    meta = recs[0]
    assert meta["t"] == "meta" and meta["trace_id"] == trace
    assert meta["parent_span"] == "job-1:a1"
    spans = [r for r in recs if r["t"] == "span"]
    assert spans and all(s.get("trace") == trace for s in spans)
    oc = [r for r in recs if r["t"] == "outcome"][-1]
    assert oc["trace"] == trace

    st = json.loads((tmp_path / "STATUS.json").read_text())
    assert st["trace_id"] == trace
    assert st["parent_span"] == "job-1:a1"
    assert st["span_id"] == tel.span_id
    # Satellite: BOTH rates, schema-pinned.
    assert st["rate_per_min"] is not None
    assert st["rate_per_min_window"] is not None
    # watch --json: the scripting hook, staleness verdict included.
    frame = tel_mod.watch_frame(str(tmp_path))
    assert frame["trace_id"] == trace
    assert frame["finished"] is True
    assert frame["in_flight"] is None
    assert isinstance(frame["stale"], bool)

    # The run-dir trace CLI: one causal tree from the flight log alone.
    assert tel_mod.main(["trace", str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert "== dslabs causal trace" in text
    assert trace in text
    tr = tracing.assemble(str(tmp_path))
    j = tr["jobs"][0]
    assert j["trace_id"] == trace
    ids = {n["span_id"] for n in j["nodes"]}
    assert all(n["parent"] is None or n["parent"] in ids
               for n in j["nodes"])
    assert j["phases"]["search_secs"] > 0


# ----------------------------------- ACCEPTANCE: SIGKILL + cost ledger

def test_sigkill_mid_level_trace_chain_and_cost_ledger(tmp_path, capsys):
    """ISSUE 13 acceptance: a job whose warden child is SIGKILLed
    mid-level yields a ``telemetry trace`` timeline reconstructed from
    disk alone with an UNBROKEN parent chain submit -> queue ->
    admission -> attempt -> child run -> in-flight dispatch (the kill
    point named); the per-tenant COSTS.jsonl sums agree with the jobs'
    SearchOutcome counters exactly; torn snapshots of SERVER_STATUS
    and COSTS never break the readers; the retention sweep prunes only
    finished run dirs."""
    root = tmp_path / "svc"
    srv = _server(root, workers=1)
    # alice: child SIGKILLs itself mid-run (after a durable checkpoint,
    # so the resume chain is deterministic) — warden fails over to the
    # host rung and still lands the exact verdict.
    res_a = srv.submit(FACTORY, tenant="alice",
                       ladder=("device", "host"),
                       fault={"kind": "die", "at": 8,
                              "after_ckpt": True}, **SMALL)
    assert res_a["accepted"] and res_a["trace_id"]
    # bob: clean single-rung baseline.
    res_b = srv.submit(FACTORY, tenant="bob", ladder=("device",),
                       **SMALL)
    assert res_b["accepted"]
    summary = srv.drain()
    srv.close()
    results = {r["tenant"]: r for r in summary["results"]}
    assert results["alice"]["status"] == "done"
    assert results["bob"]["status"] == "done"
    assert [d["kind"] for d in results["alice"]["deaths"]] == ["oom"]
    # The verdict carries the submit's trace id end to end.
    assert results["alice"]["trace_id"] == res_a["trace_id"]

    # ---- the causal tree, from disk alone
    tr = tracing.assemble(str(root), job=res_a["job_id"])
    (j,) = tr["jobs"]
    assert j["trace_id"] == res_a["trace_id"]
    assert j["status"] == "done"
    nodes = {n["span_id"]: n for n in j["nodes"]}
    # Unbroken parent chain: every node's parent exists.
    for n in j["nodes"]:
        assert n["parent"] is None or n["parent"] in nodes, n
    kinds = {n["kind"] for n in j["nodes"]}
    assert {"submit", "queue", "admission", "attempt", "run",
            "in_flight", "outcome"} <= kinds
    # The in-flight dispatch of the SIGKILLed child is named, and its
    # chain walks back to the submit root: dispatch -> run (child) ->
    # attempt -> submit.
    inflight = [n for n in j["nodes"] if n["kind"] == "in_flight"]
    assert len(inflight) == 1
    assert inflight[0]["tag"].startswith("device.")
    run_node = nodes[inflight[0]["parent"]]
    assert run_node["kind"] == "run"
    attempt = nodes[run_node["parent"]]
    assert attempt["kind"] == "attempt"
    assert nodes[attempt["parent"]]["kind"] == "submit"
    # The child run is linked via the DERIVED attempt span id (the
    # warden passed it through DSLABS_PARENT_SPAN).
    assert attempt["span_id"] == tracing.attempt_span_id(
        res_a["job_id"], 1)
    # Phase latency breakdown present.
    ph = j["phases"]
    assert ph["queue_wait_secs"] is not None
    assert ph["compile_secs"] >= 0 and ph["search_secs"] > 0
    assert ph["total_secs"] > 0
    # Rendered timeline names the kill point; CLI exits 0.
    text = tracing.render_trace(tr)
    assert "!! in-flight" in text and "device." in text
    assert tel_mod.main(["trace", str(root), "--job",
                         res_a["job_id"]]) == 0
    capsys.readouterr()

    # ---- perfetto export
    pf = tracing.to_perfetto(tr)
    names = {e.get("name") for e in pf["traceEvents"]}
    assert any(n and n.startswith("in-flight") for n in names)
    assert any(e.get("ph") == "X" for e in pf["traceEvents"])

    # ---- the cost ledger: sums agree with the verdicts EXACTLY
    costs_path = os.path.join(str(root), tracing.COSTS_NAME)
    recs, torn = tracing.read_flight_lax(costs_path)
    assert torn == 0
    per = tracing.aggregate_costs(recs)
    for tenant in ("alice", "bob"):
        v = results[tenant]
        assert per[tenant]["explored"] == v["explored"], tenant
        assert per[tenant]["unique"] == v["unique"], tenant
        assert per[tenant]["jobs"] == 1
        assert per[tenant]["device_secs"] > 0
        assert per[tenant]["dispatches"] > 0
        assert per[tenant]["cost_per_unique"] > 0
    assert per["alice"]["failovers"] == 1      # the burned device rung
    # The drain summary and SERVER_STATUS surface the same ledger.
    assert summary["costs"]["alice"]["unique"] == \
        results["alice"]["unique"]
    assert summary["cost_per_unique"] > 0
    st = tracing.load_json_tolerant(
        os.path.join(str(root), "SERVER_STATUS.json"))
    assert st["tenants"]["alice"]["costs"]["device_secs"] > 0

    # ---- torn/partial snapshots never break the readers (satellite)
    with open(costs_path, "a") as f:
        f.write('{"t": "cost", "tenant": "ali')      # torn tail
    recs2, torn2 = tracing.read_flight_lax(costs_path)
    assert torn2 == 1 and len(recs2) == len(recs)
    with open(os.path.join(str(root), "SERVER_STATUS.json"), "w") as f:
        f.write('{"t": "server_status", "tena')      # mid-write race
    tr2 = tracing.assemble(str(root))                # must not raise
    assert tr2["server"] is None
    assert tr2["costs"]["alice"]["unique"] == results["alice"]["unique"]
    from dslabs_tpu.service.__main__ import main as svc_main

    assert svc_main(["status", "--root", str(root)]) == 0
    status_line = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert status_line["server"] is None             # torn -> degraded
    assert status_line["costs"]["bob"]["jobs"] == 1

    # ---- retention sweep (satellite): prune oldest finished run dirs
    srv2 = _server(root, keep=1)
    job_dirs = {t: results[t]["run_dir"] for t in ("alice", "bob")}
    assert all(os.path.isdir(d) for d in job_dirs.values())
    pruned = srv2.retention_sweep()
    srv2.close()
    assert pruned == [res_a["job_id"]]               # oldest finished
    assert not os.path.exists(job_dirs["alice"])
    assert os.path.isdir(job_dirs["bob"])
    journal, _ = tracing.read_flight_lax(
        os.path.join(str(root), "journal.jsonl"))
    prunes = [r for r in journal if r.get("t") == "prune"]
    assert [r["job_id"] for r in prunes] == [res_a["job_id"]]
    # The causal chain survives the prune (journal + ledger remain).
    tr3 = tracing.assemble(str(root), job=res_a["job_id"])
    kinds3 = {n["kind"] for n in tr3["jobs"][0]["nodes"]}
    assert {"submit", "queue", "admission", "attempt"} <= kinds3


# ------------------------------------------------- retention unit rules

def test_retention_never_touches_unfinished_jobs(tmp_path):
    srv = _server(tmp_path / "svc", keep=0)
    for jid, status in (("t-000001", "done"), ("t-000002", "failed"),
                        ("t-000003", "pending"),
                        ("t-000004", "running")):
        srv.queue.records[jid] = {"status": status, "tenant": "t",
                                  "job": {"job_id": jid}}
        os.makedirs(srv.job_dir(jid))
    pruned = srv.retention_sweep()
    srv.close()
    assert pruned == ["t-000001", "t-000002"]
    assert not os.path.exists(srv.job_dir("t-000001"))
    assert os.path.isdir(srv.job_dir("t-000003"))
    assert os.path.isdir(srv.job_dir("t-000004"))


# --------------------------------------------------- cost meter units

def test_cost_meter_replays_ledger_and_flight_costs(tmp_path):
    flight = tmp_path / "flight.jsonl"
    recs = [
        {"t": "meta", "started": 100.0},
        {"t": "span", "ts": 0.1, "tag": "device.init", "i": 0,
         "wall": 1.0, "retries": 0},
        {"t": "span", "ts": 0.3, "tag": "device.step", "i": 1,
         "wall": 0.5, "retries": 1},
        {"t": "span", "ts": 0.6, "tag": "device.step", "i": 2,
         "wall": 0.25, "retries": 0},
        {"t": "level", "ts": 0.7, "depth": 1, "wall": 0.6},
        {"t": "outcome", "ts": 0.8, "compile_secs": 2.0},
    ]
    flight.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    fc = tracing.CostMeter.flight_costs(str(flight))
    assert fc["device_secs"] == 1.75
    assert fc["device_secs_by_site"] == {"device.init": 1.0,
                                         "device.step": 0.75}
    assert fc["dispatches"] == 3 and fc["retries"] == 1
    # compile = AOT (outcome) + first dispatch per site (1.0 + 0.5).
    assert fc["compile_secs"] == 3.5
    assert fc["search_secs"] == 0.25
    assert fc["levels"] == 1

    path = str(tmp_path / "COSTS.jsonl")
    m = tracing.CostMeter(path)
    m.charge({"job_id": "a-1", "tenant": "a", "status": "done",
              "unique": 10, "explored": 20, "budget_units": 2.0},
             str(flight))
    m.charge({"job_id": "a-2", "tenant": "a", "status": "failed",
              "unique": 0, "explored": 0})
    m.close()
    # A restarted meter replays the ledger (totals survive).
    m2 = tracing.CostMeter(path)
    per = m2.tenant_summary()
    assert per["a"]["jobs"] == 2 and per["a"]["completed"] == 1
    assert per["a"]["unique"] == 10 and per["a"]["explored"] == 20
    assert per["a"]["cost_per_unique"] == round(1.75 / 10, 9)
    tot = m2.totals()
    assert tot["device_secs"] == 1.75 and tot["unique"] == 10
    m2.close()


# ------------------------------------------- ledger compare satellites

def test_compare_flags_compile_creep_and_cost_regression(tmp_path):
    from dslabs_tpu.tpu.telemetry import (append_ledger, compare_ledger,
                                          read_ledger)

    ledger = str(tmp_path / "BENCH_HISTORY.jsonl")
    base = {"t": "bench", "value": 4.0e6,
            "strict": {"value": 4.0e6, "compile_secs": 10.0},
            "service": {"value": 12.0, "fairness_index": 1.0,
                        "cost_per_unique": 1.0e-4}}
    append_ledger(ledger, base)
    # Parity run: nothing flagged.
    append_ledger(ledger, {**base,
                           "strict": {"value": 3.9e6,
                                      "compile_secs": 10.5},
                           "service": {"value": 12.0,
                                       "cost_per_unique": 1.05e-4}})
    cmp = compare_ledger(read_ledger(ledger))
    assert not cmp["regressions"]
    assert cmp["compile"]["strict"]["latest"] == 10.5
    # Injected compile creep + cost-per-unique blowup: both flagged,
    # rc-1 via the regressions list, even at parity states/min.
    append_ledger(ledger, {**base,
                           "strict": {"value": 4.0e6,
                                      "compile_secs": 30.0},
                           "service": {"value": 12.0,
                                       "cost_per_unique": 5.0e-4}})
    cmp = compare_ledger(read_ledger(ledger))
    reg = {e["phase"] for e in cmp["regressions"]}
    assert "compile:strict" in reg
    assert "service:cost_per_unique" in reg
    # Sub-second compile jitter is never creep.
    ledger2 = str(tmp_path / "L2.jsonl")
    append_ledger(ledger2, {"t": "bench", "value": 1.0,
                            "strict": {"value": 1.0,
                                       "compile_secs": 0.2}})
    append_ledger(ledger2, {"t": "bench", "value": 1.0,
                            "strict": {"value": 1.0,
                                       "compile_secs": 0.8}})
    cmp = compare_ledger(read_ledger(ledger2))
    assert not any(e["phase"].startswith("compile:")
                   for e in cmp["regressions"])
