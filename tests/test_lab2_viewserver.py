"""Lab 2 part 1 tests — behavioural port of ViewServerTest.java:40-303.

Unit-style direct drive: the ViewServer node is configured with list-capturing
hooks and fed messages/timers by hand (no engine), mirroring the reference's
test pattern (SURVEY §4.1 "unit-style tests without any engine").
"""

from dslabs_tpu.harness import RUN_TESTS, lab_test
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.core.node import NodeConfig
from dslabs_tpu.labs.primarybackup.viewserver import (GetView, INITIAL_VIEWNUM,
                                                      Ping, PingCheckTimer,
                                                      STARTUP_VIEWNUM,
                                                      ViewReply, ViewServer)

VSA = LocalAddress("viewserver")
TA = LocalAddress("testserver")


def server(i):
    return LocalAddress(f"server{i}")


class ViewServerHarness:

    def __init__(self):
        self.vs = ViewServer(VSA)
        self.messages = []
        self.timers = []
        self.vs.config(NodeConfig(
            message_adder=lambda frm, to, m: self.messages.append((frm, to, m)),
            timer_adder=lambda frm, t, mn, mx: self.timers.append((frm, t)),
        ))
        self.vs.init()

    def timeout(self):
        assert self.timers
        frm, timer = self.timers.pop(0)
        assert isinstance(timer, PingCheckTimer)
        self.vs.deliver_timer(timer, frm)

    def send_ping(self, view_num, frm):
        self.vs.deliver_message(Ping(view_num), frm, VSA)

    def get_view(self):
        self.vs.deliver_message(GetView(), TA, VSA)
        frm, to, m = self.messages[-1]
        assert frm == VSA and to == TA and isinstance(m, ViewReply)
        return m.view

    def check(self, primary, backup, view_num=None):
        v = self.get_view()
        assert v.primary == primary, f"primary: {v.primary} != {primary}"
        assert v.backup == backup, f"backup: {v.backup} != {backup}"
        if view_num is not None:
            assert v.view_num == view_num

    def setup_view(self, primary, backup, ack_view=False):
        self.send_ping(STARTUP_VIEWNUM, primary)
        self.check(primary, None, INITIAL_VIEWNUM)
        if backup is not None:
            self.send_ping(INITIAL_VIEWNUM, primary)
            self.send_ping(STARTUP_VIEWNUM, backup)
            self.check(primary, backup, INITIAL_VIEWNUM + 1)
        if ack_view:
            if backup is None:
                self.send_ping(INITIAL_VIEWNUM, primary)
            else:
                self.send_ping(INITIAL_VIEWNUM + 1, primary)

    def timeout_fully(self, *servers_sending_pings):
        current = self.get_view()
        for _ in range(2):
            for a in servers_sending_pings:
                self.send_ping(current.view_num, a)
            self.timeout()


@lab_test("2", 1, "Startup view", points=5, part=1, categories=(RUN_TESTS,))
def test01_startup_view_correct():
    h = ViewServerHarness()
    h.check(None, None, STARTUP_VIEWNUM)


@lab_test("2", 2, "Primary initialized", points=5, part=1, categories=(RUN_TESTS,))
def test02_first_primary():
    h = ViewServerHarness()
    h.setup_view(server(1), None)


@lab_test("2", 3, "Backup initialized", points=5, part=1, categories=(RUN_TESTS,))
def test03_first_backup():
    h = ViewServerHarness()
    h.setup_view(server(1), server(2))


@lab_test("2", 4, "Backup pings first, initialized", points=5, part=1, categories=(RUN_TESTS,))
def test04_backup_pings_first():
    h = ViewServerHarness()
    h.setup_view(server(1), None)
    h.send_ping(STARTUP_VIEWNUM, server(2))
    h.send_ping(INITIAL_VIEWNUM, server(1))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)


@lab_test("2", 5, "Backup takes over", points=5, part=1, categories=(RUN_TESTS,))
def test05_backup_takes_over():
    h = ViewServerHarness()
    h.setup_view(server(1), server(2), ack_view=True)
    h.send_ping(INITIAL_VIEWNUM + 1, server(2))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)
    h.timeout()
    h.send_ping(INITIAL_VIEWNUM + 1, server(2))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)
    h.timeout()
    h.check(server(2), None, INITIAL_VIEWNUM + 2)


@lab_test("2", 6, "Old primary becomes backup", points=5, part=1, categories=(RUN_TESTS,))
def test06_old_server_becomes_backup():
    h = ViewServerHarness()
    h.setup_view(server(1), server(2), ack_view=True)
    h.timeout_fully(server(2))
    h.check(server(2), None, INITIAL_VIEWNUM + 2)
    h.send_ping(INITIAL_VIEWNUM + 2, server(2))
    h.send_ping(INITIAL_VIEWNUM + 1, server(1))
    h.check(server(2), server(1), INITIAL_VIEWNUM + 3)


@lab_test("2", 7, "Idle server becomes backup", points=5, part=1, categories=(RUN_TESTS,))
def test07_idle_third_server_becomes_backup():
    h = ViewServerHarness()
    h.setup_view(server(1), server(2), ack_view=True)
    h.timeout_fully(server(2), server(3))
    h.check(server(2), server(3), INITIAL_VIEWNUM + 2)


@lab_test("2", 8, "Wait for primary ACK", points=5, part=1, categories=(RUN_TESTS,))
def test08_wait_for_primary_ack():
    h = ViewServerHarness()
    h.send_ping(STARTUP_VIEWNUM, server(1))
    h.send_ping(STARTUP_VIEWNUM, server(2))
    h.check(server(1), None, INITIAL_VIEWNUM)
    h.send_ping(INITIAL_VIEWNUM, server(1))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)
    h.send_ping(INITIAL_VIEWNUM, server(2))
    # Fail the primary; the unacked view must not advance.
    h.timeout_fully(server(2))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)


@lab_test("2", 9, "Dead backup removed", points=5, part=1, categories=(RUN_TESTS,))
def test09_dead_backup_removed():
    h = ViewServerHarness()
    h.setup_view(server(1), server(2), ack_view=True)
    h.timeout_fully(server(1))
    h.check(server(1), None, INITIAL_VIEWNUM + 2)


@lab_test("2", 10, "Uninitialized server not made primary", points=5, part=1, categories=(RUN_TESTS,))
def test10_uninitialized_not_promoted():
    h = ViewServerHarness()
    h.setup_view(server(1), server(2), ack_view=True)
    h.timeout_fully(server(2), server(3))
    h.check(server(2), server(3), INITIAL_VIEWNUM + 2)
    h.timeout_fully(server(3))
    h.check(server(2), server(3), INITIAL_VIEWNUM + 2)


@lab_test("2", 11, "Dead idle server shouldn't become backup", points=5, part=1, categories=(RUN_TESTS,))
def test11_dead_server_not_made_backup():
    h = ViewServerHarness()
    h.setup_view(server(1), None)
    h.send_ping(STARTUP_VIEWNUM, server(2))
    h.timeout_fully()
    h.send_ping(INITIAL_VIEWNUM, server(1))
    h.check(server(1), None, INITIAL_VIEWNUM)


@lab_test("2", 12, "Consecutive views have different configurations", points=5, part=1, categories=(RUN_TESTS,))
def test12_new_view_not_started():
    h = ViewServerHarness()
    h.setup_view(server(1), None)
    h.timeout_fully(server(1))
    h.check(server(1), None, INITIAL_VIEWNUM)
    h.timeout_fully()
    h.check(server(1), None, INITIAL_VIEWNUM)
    h.send_ping(INITIAL_VIEWNUM, server(1))
    h.timeout_fully(server(1))
    h.check(server(1), None, INITIAL_VIEWNUM)
    h.timeout_fully()
    h.check(server(1), None, INITIAL_VIEWNUM)
    h.send_ping(STARTUP_VIEWNUM, server(2))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)
    h.send_ping(INITIAL_VIEWNUM + 1, server(1))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)
    h.timeout_fully(server(1), server(2))
    h.check(server(1), server(2), INITIAL_VIEWNUM + 1)
    h.timeout_fully()
    v = h.get_view()
    if v.primary == server(1) and v.backup == server(2):
        assert v.view_num == INITIAL_VIEWNUM + 1
