"""Lab 4 tensor-twin parity: the sharded-store search configuration
(ShardStorePart1Test.test10 shape — one single-server group, one shard
master, static post-Join config, CCA/master timers frozen) must produce
the object checker's exact unique-state counts depth by depth.
"""

import os

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
from dslabs_tpu.search.search import BFS
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.predicates import RESULTS_OK

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.protocols.shardstore import make_shardstore_protocol

import tests.test_lab4_shardstore as lab4

SLOW = pytest.mark.skipif(
    not os.environ.get("DSLABS_SLOW_TESTS"),
    reason="long object-oracle search (set DSLABS_SLOW_TESTS=1)")


def _object_joined(max_levels):
    state = lab4.make_search(1, 1, 1, 10)
    joined = lab4._joined_state(state, 1)
    joined.add_client_worker(
        LocalAddress("client1"),
        kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"]))
    settings = SearchSettings().max_time(600)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(lab4.CCA, False)
    settings.deliver_timers(lab4.CCA, False)
    settings.deliver_timers(lab4.shard_master(1), False)
    # max_depth is absolute: the staged join already sits at joined.depth.
    settings.set_max_depth(joined.depth + max_levels)
    return BFS(settings).run(joined)


def test_lab4_depth_parity():
    """Depth-limited unique-state parity (verified by hand for depths 1-5:
    6/23/74/219/606); CI checks depth 3 unconditionally."""
    obj = _object_joined(3)
    ten = TensorSearch(make_shardstore_protocol([1, 1]), chunk=256,
                       max_depth=3).run()
    assert ten.unique_states == obj.discovered_count == 74


@SLOW
def test_lab4_goal_parity():
    """The twin reaches CLIENTS_DONE (put/get complete through config
    discovery, the group's replicated log, and AMO dedup).  The object
    side of this verdict is test_lab4_shardstore.test10 — the oracle's
    goal search there takes minutes, so it is not repeated here."""
    ten = TensorSearch(make_shardstore_protocol([1, 1]), chunk=1024,
                       frontier_cap=1 << 18, max_depth=11).run()
    assert ten.end_condition == "GOAL_FOUND"   # depth 10, ~22k unique
