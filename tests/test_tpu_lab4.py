"""Lab 4 tensor-twin parity: the sharded-store search configurations
(ShardStorePart1Test.test10/test11 shapes — single-server groups, one
shard master, CCA/master timers frozen) must produce the object
checker's exact unique-state counts depth by depth.  The 2-group config
exercises the config walk (None -> cfg0 -> cfg1), WrongGroup routing,
and the g1 -> g2 shard handoff (ShardMove/InstallShards/Ack/MoveDone).
"""

import os

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
from dslabs_tpu.search.search import BFS
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.predicates import RESULTS_OK

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.specs_lab4 import make_shardstore_protocol

import tests.test_lab4_shardstore as lab4

SLOW = pytest.mark.skipif(
    not os.environ.get("DSLABS_SLOW_TESTS"),
    reason="long object-oracle search (set DSLABS_SLOW_TESTS=1)")


# (commands, expected results, per-command owning group under the final
# config — key-1 -> shard 1 -> g1, key-6 -> shard 6 -> g2 after the
# staged Join(1), Join(2) rebalance of 10 shards)
WORKLOADS = {
    1: (["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"], [1, 1]),
    2: (["PUT:key-1:v1", "PUT:key-6:v6", "GET:key-1"],
        ["PutOk", "PutOk", "v1"], [1, 2, 1]),
}


def _object_joined(max_levels, n_groups=1):
    cmds, results, _ = WORKLOADS[n_groups]
    state = lab4.make_search(n_groups, 1, 1, 10)
    joined = lab4._joined_state(state, n_groups)
    joined.add_client_worker(LocalAddress("client1"),
                             kv_workload(cmds, results))
    settings = SearchSettings().max_time(600)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(lab4.CCA, False)
    settings.deliver_timers(lab4.CCA, False)
    settings.deliver_timers(lab4.shard_master(1), False)
    # max_depth is absolute: the staged join already sits at joined.depth.
    settings.set_max_depth(joined.depth + max_levels)
    return BFS(settings).run(joined)


def test_lab4_depth_parity():
    """Depth-limited unique-state parity (verified by hand for depths 1-5:
    6/23/74/219/606); CI checks depth 3 unconditionally."""
    obj = _object_joined(3)
    ten = TensorSearch(make_shardstore_protocol([1, 1]), chunk=256,
                       max_depth=3).run()
    assert ten.unique_states == obj.discovered_count == 74


def test_lab4_two_group_depth_parity():
    """2-group config-walk/handoff parity (verified by hand for depths
    1-5: 8/38/142/467/1411); CI checks depth 3 unconditionally."""
    obj = _object_joined(3, n_groups=2)
    ten = TensorSearch(make_shardstore_protocol(WORKLOADS[2][2]),
                       chunk=256, max_depth=3).run()
    assert ten.unique_states == obj.discovered_count == 142


@SLOW
def test_lab4_goal_parity():
    """The twin reaches CLIENTS_DONE (put/get complete through config
    discovery, the group's replicated log, and AMO dedup).  The object
    side of this verdict is test_lab4_shardstore.test10 — the oracle's
    goal search there takes minutes, so it is not repeated here."""
    ten = TensorSearch(make_shardstore_protocol([1, 1]), chunk=1024,
                       frontier_cap=1 << 18, max_depth=11).run()
    assert ten.end_condition == "GOAL_FOUND"   # depth 10, ~22k unique


@SLOW
def test_lab4_deep_depth_sweep():
    """tools/parity_lab4.py's depth-by-depth unique-count comparison,
    promoted into the slow CI job (round-3 verdict: a collapse-argument
    regression must fail a build, not live in a docstring).  Sweeps both
    Part 1 shapes depth by depth against the object oracle."""
    for n_groups, maxd in ((1, 5), (2, 4)):
        proto = make_shardstore_protocol(WORKLOADS[n_groups][2])
        for depth in range(4, maxd + 1):
            obj = _object_joined(depth, n_groups=n_groups)
            ten = TensorSearch(proto, chunk=512, max_depth=depth).run()
            assert ten.unique_states == obj.discovered_count, (
                f"groups={n_groups} depth={depth}: tensor "
                f"{ten.unique_states} != object {obj.discovered_count}")


# ------------------------------------------------- multi-client (test12)

def _object_joined_multi(max_levels):
    """test12's shape: two clients appending to keys owned by different
    groups (foo-1 -> shard 1 -> g1, foo-2 -> shard 2 -> g2 under the
    2-shard rebalance of Join(1), Join(2))."""
    state = lab4.make_search(2, 1, 1, 2)
    joined = lab4._joined_state(state, 2)
    joined.add_client_worker(LocalAddress("client1"),
                             kv_workload(["APPEND:foo-1:X1"], ["X1"]))
    joined.add_client_worker(LocalAddress("client2"),
                             kv_workload(["APPEND:foo-2:Y2"], ["Y2"]))
    settings = SearchSettings().max_time(600)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(lab4.CCA, False)
    settings.deliver_timers(lab4.CCA, False)
    settings.deliver_timers(lab4.shard_master(1), False)
    settings.set_max_depth(joined.depth + max_levels)
    return BFS(settings).run(joined)


def test_lab4_multi_client_depth_parity():
    """Two-client, two-group twin parity (multi-client lanes: per-client
    AMO vectors, per-client query/config state, vector ShardMove
    snapshots).  CI checks depth 3 unconditionally."""
    from dslabs_tpu.labs.shardedstore.shardstore import key_to_shard

    # Pin the key->group mapping assumption of the fixture.
    assert key_to_shard("foo-1", 2) == 1
    assert key_to_shard("foo-2", 2) == 2
    obj = _object_joined_multi(3)
    groups = [[1], [2]]
    ten = TensorSearch(make_shardstore_protocol(groups), chunk=256,
                       max_depth=3).run()
    assert ten.unique_states == obj.discovered_count, (
        f"tensor {ten.unique_states} != object {obj.discovered_count}")


@SLOW
def test_lab4_multi_client_deep_parity():
    for d in (4, 5):
        obj = _object_joined_multi(d)
        ten = TensorSearch(make_shardstore_protocol([[1], [2]]),
                           chunk=512, max_depth=d).run()
        assert ten.unique_states == obj.discovered_count, (
            f"depth {d}: tensor {ten.unique_states} != "
            f"object {obj.discovered_count}")


# -------------------------------------- unrestricted space (test13 shape)

def _object_joined_unrestricted(max_levels):
    """test13's search narrows NOTHING: master election/heartbeat
    timers live, the controller node active with its join-phase debris
    deliverable (tests/test_lab4_shardstore.py _random_search)."""
    state = lab4.make_search(2, 1, 1, 2)
    joined = lab4._joined_state(state, 2)
    joined.add_client_worker(LocalAddress("client1"),
                             kv_workload(["APPEND:foo-1:x"]))
    joined.add_client_worker(LocalAddress("client2"),
                             kv_workload(["APPEND:foo-2:y"]))
    settings = SearchSettings().max_time(600)
    settings.add_invariant(RESULTS_OK)
    settings.set_max_depth(joined.depth + max_levels)
    return BFS(settings).run(joined)


def test_lab4_unrestricted_depth_parity():
    """model_master_timers + model_ctl twin parity: the master's heard
    lane, its election/heartbeat timers, the controller's stale
    ClientTimers, and the join REQ/REP debris self-loops must reproduce
    the object space exactly."""
    obj = _object_joined_unrestricted(3)
    ten = TensorSearch(
        make_shardstore_protocol([[1], [2]], model_master_timers=True,
                                 model_ctl=True),
        chunk=256, max_depth=3).run()
    assert ten.unique_states == obj.discovered_count, (
        f"tensor {ten.unique_states} != object {obj.discovered_count}")


@SLOW
def test_lab4_unrestricted_deep_parity():
    for d in (4, 5):
        obj = _object_joined_unrestricted(d)
        ten = TensorSearch(
            make_shardstore_protocol([[1], [2]],
                                     model_master_timers=True,
                                     model_ctl=True),
            chunk=512, max_depth=d).run()
        assert ten.unique_states == obj.discovered_count, (
            f"depth {d}: tensor {ten.unique_states} != "
            f"object {obj.discovered_count}")


# ------------------------------------------------------- join-phase twin

def _join_initial(n_groups):
    """The join-phase initial state + settings, exactly as
    _joined_state builds them (partition {CCA, master}, store-server
    timers suppressed)."""
    from dslabs_tpu.labs.shardedstore.shardmaster import Join, Ok
    from dslabs_tpu.testing.workload import Workload

    state = lab4.make_search(n_groups, 1, 1, 10)
    cmds = [Join(g, lab4.group(g, 1)) for g in range(1, n_groups + 1)]
    state.add_client_worker(lab4.CCA, Workload(commands=cmds,
                                               results=[Ok()] * len(cmds)))
    settings = SearchSettings().max_time(300)
    settings.add_invariant(RESULTS_OK)
    settings.partition(lab4.CCA, lab4.shard_master(1))
    for a in list(state.servers):
        if "server" in str(a):
            settings.deliver_timers(a, False)
    return state, settings


def test_join_twin_depth_parity():
    """The join twin (tpu/protocols/shardmaster_join.py) matches the
    object oracle's unique-state counts depth by depth for both group
    counts, including full exhaustion of the done-pruned space."""
    from dslabs_tpu.testing.predicates import CLIENTS_DONE
    from dslabs_tpu.tpu.specs_lab4 import \
        make_join_protocol

    for G in (1, 2):
        state, settings = _join_initial(G)
        settings.add_prune(CLIENTS_DONE)
        import dataclasses as _dc

        proto = make_join_protocol(G)
        proto = _dc.replace(
            proto, goals={},
            prunes={"CLIENTS_DONE": proto.goals["CLIENTS_DONE"]})
        for depth in (2, 4, 30):
            settings.set_max_depth(depth)
            obj = BFS(settings).run(state)
            ten = TensorSearch(proto, chunk=64, max_depth=depth).run()
            assert ten.unique_states == obj.discovered_count, (
                f"G={G} depth={depth}: tensor {ten.unique_states} != "
                f"object {obj.discovered_count}")


# ----------------------------------------------------- Part 2: 2PC twin

def _object_tx_joined(max_levels, n_tx=1):
    """Object oracle for the Part-2 shape: 2 one-server groups joined,
    client workload of cross-group transactions (test09's configuration
    with the tx spanning shards 1 and 6 of the 10-shard rebalance)."""
    from dslabs_tpu.labs.shardedstore.txkvstore import (MultiGet,
                                                       MultiGetResult,
                                                       MultiPut,
                                                       MultiPutOk)
    from dslabs_tpu.testing.workload import Workload

    cmds = [MultiPut({"key-1": "v", "key-6": "v"})]
    results = [MultiPutOk()]
    if n_tx > 1:
        cmds.append(MultiGet({"key-1", "key-6"}))
        results.append(MultiGetResult({"key-1": "v", "key-6": "v"}))
    state = lab4.make_search(2, 1, 1, 10)
    joined = lab4._joined_state(state, 2)
    joined.add_client_worker(LocalAddress("client1"),
                             Workload(commands=cmds, results=results))
    settings = SearchSettings().max_time(600)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(lab4.CCA, False)
    settings.deliver_timers(lab4.CCA, False)
    settings.deliver_timers(lab4.shard_master(1), False)
    settings.set_max_depth(joined.depth + max_levels)
    return BFS(settings).run(joined)


def test_lab4_tx_depth_parity():
    """Cross-group 2PC twin parity (MultiPut spanning both groups —
    the flagship lab4 semantics on the tensor backend)."""
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_tx_protocol

    obj = _object_tx_joined(3)
    ten = TensorSearch(make_shardstore_tx_protocol(n_tx=1), chunk=256,
                       max_depth=3).run()
    assert ten.unique_states == obj.discovered_count, (
        f"tensor {ten.unique_states} != object {obj.discovered_count}")


def test_lab4_tx_two_shard_depth_parity():
    """The tx twin is shard-count agnostic (handoff collapses to flags,
    never masks): test09's OWN 2-shard configuration must walk the same
    space shape — pinned against its object oracle so the tensor-backend
    run of test09 rests on a verified twin, not an analogy to the
    10-shard fixture."""
    from dslabs_tpu.labs.shardedstore.txkvstore import (MultiPut,
                                                       MultiPutOk)
    from dslabs_tpu.testing.workload import Workload
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_tx_protocol

    state = lab4.make_search(2, 1, 1, 2)
    joined = lab4._joined_state(state, 2)
    joined.add_client_worker(
        LocalAddress("client1"),
        Workload(commands=[MultiPut({"key-1": "x", "key-2": "y"})],
                 results=[MultiPutOk()]))
    settings = SearchSettings().max_time(600)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(lab4.CCA, False)
    settings.deliver_timers(lab4.CCA, False)
    settings.deliver_timers(lab4.shard_master(1), False)
    settings.set_max_depth(joined.depth + 3)
    obj = BFS(settings).run(joined)
    ten = TensorSearch(make_shardstore_tx_protocol(n_tx=1), chunk=256,
                       max_depth=3).run()
    assert ten.unique_states == obj.discovered_count, (
        f"tensor {ten.unique_states} != object {obj.discovered_count}")


@SLOW
def test_lab4_tx_deep_parity():
    """Depths 4-5 (slow: the object oracle expands thousands of 2PC
    interleavings)."""
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_tx_protocol

    for d in (4, 5):
        obj = _object_tx_joined(d)
        ten = TensorSearch(make_shardstore_tx_protocol(n_tx=1),
                           chunk=512, max_depth=d).run()
        assert ten.unique_states == obj.discovered_count, (
            f"depth {d}: tensor {ten.unique_states} != "
            f"object {obj.discovered_count}")


@SLOW
def test_lab4_tx_goal_and_invariant():
    """The 2PC twin completes the transaction (CLIENTS_DONE reached)
    with MULTI_GETS_MATCH clean along the way."""
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_tx_protocol

    ten = TensorSearch(make_shardstore_tx_protocol(n_tx=1), chunk=1024,
                       frontier_cap=1 << 18, max_depth=14).run()
    assert ten.end_condition == "GOAL_FOUND"


@SLOW
def test_lab4_tx2_depth_parity():
    """n_tx=2 (MultiPut then MultiGet) twin parity at depths 3-5.  The
    second transaction only becomes reachable much deeper; these depths
    pin the lane layout and the shared config-walk/2PC prefix."""
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_tx_protocol

    for d in (3, 4, 5):
        obj = _object_tx_joined(d, n_tx=2)
        ten = TensorSearch(make_shardstore_tx_protocol(n_tx=2),
                           chunk=512, max_depth=d).run()
        assert ten.unique_states == obj.discovered_count, (
            f"depth {d}: tensor {ten.unique_states} != "
            f"object {obj.discovered_count}")
