"""Lab 3 tests — behavioural port of PaxosTest.java:67-1160.

Run tests: basic ops + log interface, progress in majority, no progress in
minority, heal, concurrent appends, message budget, garbage collection.
Search tests: staged BFS with LOGS_CONSISTENT invariants (test20/test21
style) and randomized DFS probes (test25 style).
"""

import time

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS, UNRELIABLE_TESTS,
                                lab_test)
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import (
    APPENDS_LINEARIZABLE, append_same_key_workload,
    append_different_key_workload, get, get_result, kv_workload, put,
    put_get_workload, put_ok, simple_workload)
from dslabs_tpu.labs.clientserver.kvstore import KVStore
from dslabs_tpu.labs.paxos.paxos import (PaxosClient, PaxosLogSlotStatus,
                                         PaxosServer)
from dslabs_tpu.labs.paxos.predicates import (LOGS_CONSISTENT,
                                              LOGS_CONSISTENT_ALL_SLOTS,
                                              slot_valid)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs, dfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import (CLIENTS_DONE, NONE_DECIDED,
                                           RESULTS_OK)


def server(i):
    return LocalAddress(f"server{i}")


def client(i):
    return LocalAddress(f"client{i}")


def servers(n):
    return tuple(server(i) for i in range(1, n + 1))


def generator(n, workload_factory=put_get_workload):
    addrs = servers(n)
    return NodeGenerator(
        server_supplier=lambda a: PaxosServer(a, addrs, KVStore()),
        client_supplier=lambda a: PaxosClient(a, addrs),
        workload_supplier=lambda a: workload_factory())


def make_run_state(n, workload_factory=put_get_workload):
    state = RunState(generator(n, workload_factory))
    for a in servers(n):
        state.add_server(a)
    return state


def make_search_state(n, workload_factory=put_get_workload):
    state = SearchState(generator(n, workload_factory))
    for a in servers(n):
        state.add_server(a)
    return state


def assert_ok(state):
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


def assert_logs_consistent(state, all_slots=True):
    p = LOGS_CONSISTENT_ALL_SLOTS if all_slots else LOGS_CONSISTENT
    r = p.check(state)
    assert r.value, r.error_message()


# ------------------------------------------------------------------ run tests

@lab_test("3", 2, "Single client, simple operations", points=5, categories=(RUN_TESTS,))
def test02_basic():
    state = make_run_state(3, simple_workload)
    state.add_client_worker(client(1))

    for p in state.servers.values():
        assert p.first_non_cleared() == 1
        assert p.last_non_empty() == 0

    state.run(RunSettings().max_time(10))
    assert_ok(state)
    assert_logs_consistent(state)

    size = 7  # simple_workload length
    num_full = sum(1 for p in state.servers.values()
                   if p.last_non_empty() >= size)
    assert 2 * num_full > len(state.servers)
    for i in range(1, size + 1):
        assert any(p.status(i) in (PaxosLogSlotStatus.CHOSEN,
                                   PaxosLogSlotStatus.CLEARED)
                   for p in state.servers.values()), f"slot {i} undecided"


@lab_test("3", 4, "Progress in majority", points=5, categories=(RUN_TESTS,))
def test04_progress_in_majority():
    state = make_run_state(5)
    c = state.add_client(client(1))
    settings = RunSettings().max_time(10)
    settings.partition(server(1), server(2), server(3), client(1))
    state.start(settings)
    c.send_command(put("foo", "bar"))
    assert c.get_result(timeout=5) == put_ok()
    state.stop()


@lab_test("3", 5, "No progress in minority", points=5, categories=(RUN_TESTS,))
def test05_no_progress_in_minority():
    state = make_run_state(5)
    c = state.add_client(client(1))
    settings = RunSettings().max_time(10)
    settings.partition(server(1), server(2), client(1))
    state.start(settings)
    c.send_command(put("foo", "bar"))
    time.sleep(2)
    assert not c.has_result()
    assert NONE_DECIDED.check(state).value
    state.stop()


@lab_test("3", 6, "Progress after partition healed", points=5, categories=(RUN_TESTS,))
def test06_progress_after_heal():
    state = make_run_state(5)
    c1 = state.add_client(client(1))
    c2 = state.add_client(client(2))
    settings = RunSettings().max_time(15)
    settings.partition(server(1), server(2), client(1))
    state.start(settings)
    c1.send_command(put("foo", "bar"))
    time.sleep(1)
    assert not c1.has_result()
    settings.reset_network()
    assert c1.get_result(timeout=10) == put_ok()
    c2.send_command(get("foo"))
    assert c2.get_result(timeout=5) == get_result("bar")
    state.stop()


@lab_test("3", 9, "Multiple clients, concurrent appends", points=10, categories=(RUN_TESTS,))
def test09_concurrent_appends():
    n_clients, n_rounds = 5, 3
    state = make_run_state(3, lambda: append_same_key_workload(n_rounds))
    for i in range(1, n_clients + 1):
        state.add_client_worker(client(i))
    state.run(RunSettings().max_time(20))
    assert all(w.done() for w in state.client_workers().values())
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()
    assert_logs_consistent(state)


@lab_test("3", 10, "Message count", points=10, categories=(RUN_TESTS,))
def test10_message_count():
    n_rounds, n_servers = 100, 5
    state = make_run_state(n_servers, lambda: append_same_key_workload(n_rounds))
    state.add_client_worker(client(1))
    state.run(RunSettings().max_time(30))
    assert_ok(state)
    total = sum(state.network.num_messages_received(a)
                for a in state.servers)
    per_agreement = total / n_rounds
    allowed = 15 * n_servers
    assert per_agreement <= allowed, \
        f"Too many messages: {per_agreement:.1f}/agreement (allowed {allowed})"


@lab_test("3", 11, "Old commands garbage collected", points=15, categories=(RUN_TESTS,))
def test11_clears_memory():
    """Scaled-down port of test11ClearsMemory: bulk values are garbage
    collected once the partitioned server heals and catches up."""
    value_size, items = 50_000, 10
    state = make_run_state(3)
    c = state.add_client(client(1))
    settings = RunSettings().max_time(60)
    settings.partition(server(2), server(3), client(1))
    state.start(settings)

    for key in range(items):
        c.send_command(put(key, "x" * value_size))
        assert c.get_result(timeout=5) == put_ok()

    def log_entries(p):
        return p.last_non_empty() - p.first_non_cleared() + 1

    # Partitioned: server(1) can't execute, so nothing may be GC'd.
    assert any(log_entries(p) >= items for p in state.servers.values())

    # Heal; overwrite with small values; wait for catchup + GC.
    settings.reset_network()
    for key in range(items):
        c.send_command(put(key, "foo"))
        assert c.get_result(timeout=5) == put_ok()
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(log_entries(p) <= 3 for p in state.servers.values()):
            break
        time.sleep(0.2)
    state.stop()
    for a, p in state.servers.items():
        assert log_entries(p) <= 3, \
            f"{a} retains {log_entries(p)} log entries after GC"
        assert p.first_non_cleared() > items
    assert_logs_consistent(state, all_slots=False)


@lab_test("3", 12, "Single client, simple operations", points=10, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test12_basic_unreliable():
    state = make_run_state(3, lambda: append_different_key_workload(5))
    state.add_client_worker(client(1))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.8)
    state.run(settings)
    assert_ok(state)
    assert_logs_consistent(state)


# --------------------------------------------------------------- search tests

@lab_test("3", 20, "Single client, simple operations", points=20, categories=(SEARCH_TESTS,))
def test20_basic_search():
    state = make_search_state(3)
    state.add_client_worker(client(1), kv_workload(["PUT:foo:bar", "GET:foo"],
                                                   ["PutOk", "bar"]))

    settings = SearchSettings()
    settings.max_time(60)
    settings.partition(server(1), server(2), client(1))
    settings.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings.add_goal(NONE_DECIDED.negate())
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    one_executed = results.goal_matching_state

    settings2 = SearchSettings()
    settings2.max_time(60)
    settings2.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings2.add_goal(CLIENTS_DONE)
    results2 = bfs(one_executed, settings2)
    assert results2.end_condition == EndCondition.GOAL_FOUND, results2

    # Linearizability within the partitioned subspace, timers frozen
    # (reference narrows the same way, PaxosTest.java:924-930).
    settings3 = SearchSettings()
    settings3.max_time(30).set_max_depth(one_executed.depth + 6)
    settings3.partition(server(1), server(2), client(1))
    settings3.deliver_timers(False)
    settings3.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings3.add_prune(CLIENTS_DONE)
    results3 = bfs(one_executed, settings3)
    assert results3.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                      EndCondition.TIME_EXHAUSTED), results3


@lab_test("3", 21, "Single client, no progress in minority", points=15, categories=(SEARCH_TESTS,))
def test21_no_progress_in_minority_search():
    state = make_search_state(5, lambda: kv_workload(["PUT:foo:bar"]))
    state.add_client_worker(client(1))

    settings = SearchSettings()
    settings.max_time(20)
    settings.add_invariant(NONE_DECIDED).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings.partition(server(1), server(2), client(1))
    settings.set_max_depth(12)
    results = bfs(state, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results

    settings.deliver_timers(False)
    results = bfs(state, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results


@lab_test("3", 25, "Three server random search", points=20, categories=(SEARCH_TESTS,))
def test25_random_search():
    state = make_search_state(3, lambda: kv_workload(["APPEND:foo:x"]))
    state.add_client_worker(client(1))
    state.add_client_worker(client(2))

    settings = SearchSettings()
    settings.set_max_depth(1000).max_time(8)
    settings.add_invariant(APPENDS_LINEARIZABLE).add_invariant(LOGS_CONSISTENT)
    settings.add_prune(CLIENTS_DONE)
    results = dfs(state, settings)
    assert results.end_condition == EndCondition.TIME_EXHAUSTED, results
