"""Lab 3 tests — behavioural port of PaxosTest.java:67-1160.

Run tests: basic ops + log interface, progress in majority, no progress in
minority, heal, concurrent appends, message budget, garbage collection.
Search tests: staged BFS with LOGS_CONSISTENT invariants (test20/test21
style) and randomized DFS probes (test25 style).
"""

import functools
import os
import time

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS, UNRELIABLE_TESTS,
                                lab_test)

# DSLABS_FULL_BUDGET=1 runs the wall-clock storm tests at the reference's
# original budgets (30 s repartition storms, PaxosTest.java:744-803)
# instead of the CI-scaled ones.
FULL_BUDGET = bool(os.environ.get("DSLABS_FULL_BUDGET"))
STORM_SECS = 30 if FULL_BUDGET else 10


def retry_wallclock_flake(fn):
    """Isolate + retry a wall-clock-bounded test when its timing
    assertion fails: the maxWait bounds assume a quiet machine.  The
    reference gets this two ways — grading runs every test twice
    (TIMES_TO_RUN=2, grading/grader.py:44) AND BaseJUnitTest isolates
    tests with a GC + settle pause between them
    (BaseJUnitTest.java:111-191); this decorator applies both: a GC +
    settle before the first attempt (no mid-run collector pause lands in
    the measured window) and up to two retries after a longer settle.  A
    deterministic failure still fails every attempt."""
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        import gc

        gc.collect()
        time.sleep(0.05)
        for attempt in range(3):
            try:
                return fn(*a, **kw)
            except AssertionError as e:
                if "max wait" not in str(e) or attempt == 2:
                    raise
                gc.collect()
                time.sleep(2.0)
    return wrapper
from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import (
    APPENDS_LINEARIZABLE, append, append_same_key_workload,
    append_different_key_workload, different_keys_infinite_workload, get,
    get_result, kv_workload, put, put_get_workload, put_ok, simple_workload)
from dslabs_tpu.labs.clientserver.kvstore import KVStore
from dslabs_tpu.labs.paxos.paxos import (PaxosClient, PaxosLogSlotStatus,
                                         PaxosServer)
from dslabs_tpu.labs.paxos.predicates import (LOGS_CONSISTENT,
                                              LOGS_CONSISTENT_ALL_SLOTS,
                                              slot_valid)
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.runner.run_state import RunState
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs, dfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import (ALL_RESULTS_SAME, CLIENTS_DONE,
                                           NONE_DECIDED, RESULTS_OK)


def server(i):
    return LocalAddress(f"server{i}")


def client(i):
    return LocalAddress(f"client{i}")


def servers(n):
    return tuple(server(i) for i in range(1, n + 1))


def generator(n, workload_factory=put_get_workload):
    addrs = servers(n)
    return NodeGenerator(
        server_supplier=lambda a: PaxosServer(a, addrs, KVStore()),
        client_supplier=lambda a: PaxosClient(a, addrs),
        workload_supplier=lambda a: workload_factory())


def make_run_state(n, workload_factory=put_get_workload):
    state = RunState(generator(n, workload_factory))
    for a in servers(n):
        state.add_server(a)
    return state


def make_search_state(n, workload_factory=put_get_workload):
    state = SearchState(generator(n, workload_factory))
    for a in servers(n):
        state.add_server(a)
    return state


def assert_ok(state):
    r = RESULTS_OK.check(state)
    assert r.value, r.error_message()


def assert_logs_consistent(state, all_slots=True):
    p = LOGS_CONSISTENT_ALL_SLOTS if all_slots else LOGS_CONSISTENT
    r = p.check(state)
    assert r.value, r.error_message()


# ------------------------------------------------------------------ run tests

@lab_test("3", 2, "Single client, simple operations", points=5, categories=(RUN_TESTS,))
def test02_basic():
    state = make_run_state(3, simple_workload)
    state.add_client_worker(client(1))

    for p in state.servers.values():
        assert p.first_non_cleared() == 1
        assert p.last_non_empty() == 0

    state.run(RunSettings().max_time(10))
    assert_ok(state)
    assert_logs_consistent(state)

    size = 7  # simple_workload length
    num_full = sum(1 for p in state.servers.values()
                   if p.last_non_empty() >= size)
    assert 2 * num_full > len(state.servers)
    for i in range(1, size + 1):
        assert any(p.status(i) in (PaxosLogSlotStatus.CHOSEN,
                                   PaxosLogSlotStatus.CLEARED)
                   for p in state.servers.values()), f"slot {i} undecided"


@lab_test("3", 4, "Progress in majority", points=5, categories=(RUN_TESTS,))
def test04_progress_in_majority():
    state = make_run_state(5)
    c = state.add_client(client(1))
    settings = RunSettings().max_time(10)
    settings.partition(server(1), server(2), server(3), client(1))
    state.start(settings)
    c.send_command(put("foo", "bar"))
    assert c.get_result(timeout=5) == put_ok()
    state.stop()


@lab_test("3", 5, "No progress in minority", points=5, categories=(RUN_TESTS,))
def test05_no_progress_in_minority():
    state = make_run_state(5)
    c = state.add_client(client(1))
    settings = RunSettings().max_time(10)
    settings.partition(server(1), server(2), client(1))
    state.start(settings)
    c.send_command(put("foo", "bar"))
    time.sleep(2)
    assert not c.has_result()
    assert NONE_DECIDED.check(state).value
    state.stop()


@lab_test("3", 6, "Progress after partition healed", points=5, categories=(RUN_TESTS,))
def test06_progress_after_heal():
    state = make_run_state(5)
    c1 = state.add_client(client(1))
    c2 = state.add_client(client(2))
    settings = RunSettings().max_time(15)
    settings.partition(server(1), server(2), client(1))
    state.start(settings)
    c1.send_command(put("foo", "bar"))
    time.sleep(1)
    assert not c1.has_result()
    settings.reset_network()
    assert c1.get_result(timeout=10) == put_ok()
    c2.send_command(get("foo"))
    assert c2.get_result(timeout=5) == get_result("bar")
    state.stop()


@lab_test("3", 9, "Multiple clients, concurrent appends", points=10, categories=(RUN_TESTS,))
def test09_concurrent_appends():
    n_clients, n_rounds = 5, 3
    state = make_run_state(3, lambda: append_same_key_workload(n_rounds))
    for i in range(1, n_clients + 1):
        state.add_client_worker(client(i))
    state.run(RunSettings().max_time(20))
    assert all(w.done() for w in state.client_workers().values())
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()
    assert_logs_consistent(state)


@lab_test("3", 10, "Message count", points=10, categories=(RUN_TESTS,))
def test10_message_count():
    n_rounds, n_servers = 100, 5
    state = make_run_state(n_servers, lambda: append_same_key_workload(n_rounds))
    state.add_client_worker(client(1))
    state.run(RunSettings().max_time(30))
    assert_ok(state)
    total = sum(state.network.num_messages_received(a)
                for a in state.servers)
    per_agreement = total / n_rounds
    allowed = 15 * n_servers
    assert per_agreement <= allowed, \
        f"Too many messages: {per_agreement:.1f}/agreement (allowed {allowed})"


@lab_test("3", 11, "Old commands garbage collected", points=15, categories=(RUN_TESTS,))
def test11_clears_memory():
    """Scaled-down port of test11ClearsMemory: bulk values are garbage
    collected once the partitioned server heals and catches up."""
    value_size, items = 50_000, 10
    state = make_run_state(3)
    c = state.add_client(client(1))
    settings = RunSettings().max_time(60)
    settings.partition(server(2), server(3), client(1))
    state.start(settings)

    for key in range(items):
        c.send_command(put(key, "x" * value_size))
        assert c.get_result(timeout=5) == put_ok()

    def log_entries(p):
        return p.last_non_empty() - p.first_non_cleared() + 1

    # Partitioned: server(1) can't execute, so nothing may be GC'd.
    assert any(log_entries(p) >= items for p in state.servers.values())

    # Heal; overwrite with small values; wait for catchup + GC.
    settings.reset_network()
    for key in range(items):
        c.send_command(put(key, "foo"))
        assert c.get_result(timeout=5) == put_ok()
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(log_entries(p) <= 3 for p in state.servers.values()):
            break
        time.sleep(0.2)
    state.stop()
    for a, p in state.servers.items():
        assert log_entries(p) <= 3, \
            f"{a} retains {log_entries(p)} log entries after GC"
        assert p.first_non_cleared() > items
    assert_logs_consistent(state, all_slots=False)


@lab_test("3", 12, "Single client, simple operations", points=10, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test12_basic_unreliable():
    state = make_run_state(3, lambda: append_different_key_workload(5))
    state.add_client_worker(client(1))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.8)
    state.run(settings)
    assert_ok(state)
    assert_logs_consistent(state)


# --------------------------------------------------------------- search tests

@lab_test("3", 20, "Single client, simple operations", points=20, categories=(SEARCH_TESTS,))
def test20_basic_search():
    state = make_search_state(3)
    state.add_client_worker(client(1), kv_workload(["PUT:foo:bar", "GET:foo"],
                                                   ["PutOk", "bar"]))

    settings = SearchSettings()
    settings.max_time(60)
    settings.partition(server(1), server(2), client(1))
    settings.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings.add_goal(NONE_DECIDED.negate())
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    one_executed = results.goal_matching_state

    settings2 = SearchSettings()
    settings2.max_time(60)
    settings2.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings2.add_goal(CLIENTS_DONE)
    results2 = bfs(one_executed, settings2)
    assert results2.end_condition == EndCondition.GOAL_FOUND, results2

    # Linearizability within the partitioned subspace, timers frozen
    # (reference narrows the same way, PaxosTest.java:924-930).
    settings3 = SearchSettings()
    settings3.max_time(30).set_max_depth(one_executed.depth + 6)
    settings3.partition(server(1), server(2), client(1))
    settings3.deliver_timers(False)
    settings3.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings3.add_prune(CLIENTS_DONE)
    results3 = bfs(one_executed, settings3)
    assert results3.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                      EndCondition.TIME_EXHAUSTED), results3


@lab_test("3", 21, "Single client, no progress in minority", points=15, categories=(SEARCH_TESTS,))
def test21_no_progress_in_minority_search():
    state = make_search_state(5, lambda: kv_workload(["PUT:foo:bar"]))
    state.add_client_worker(client(1))

    settings = SearchSettings()
    settings.max_time(20)
    settings.add_invariant(NONE_DECIDED).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings.partition(server(1), server(2), client(1))
    settings.set_max_depth(12)
    results = bfs(state, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results

    settings.deliver_timers(False)
    results = bfs(state, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results


@lab_test("3", 25, "Three server random search", points=20, categories=(SEARCH_TESTS,))
def test25_random_search():
    state = make_search_state(3, lambda: kv_workload(["APPEND:foo:x"]))
    state.add_client_worker(client(1))
    state.add_client_worker(client(2))

    settings = SearchSettings()
    settings.set_max_depth(1000).max_time(8)
    settings.add_invariant(APPENDS_LINEARIZABLE).add_invariant(LOGS_CONSISTENT)
    settings.add_prune(CLIENTS_DONE)
    results = dfs(state, settings)
    assert results.end_condition == EndCondition.TIME_EXHAUSTED, results


@lab_test("3", 1, "Client throws InterruptedException", points=5, categories=(RUN_TESTS,))
def test01_throws_exception():
    """PaxosTest.test01ThrowsException: get_result must block (time out)
    when the run state was never started."""
    state = make_run_state(3)
    c = state.add_client(client(1))
    c.send_command(get("FOO"))
    with pytest.raises(TimeoutError):
        c.get_result(timeout=0.5)


@lab_test("3", 3, "Progress with no partition", points=5, categories=(RUN_TESTS,))
def test03_no_partition():
    """PaxosTest.test03NoPartition: three direct clients, 5 servers."""
    state = make_run_state(5)
    c1, c2, c3 = (state.add_client(client(i)) for i in (1, 2, 3))
    state.start(RunSettings().max_time(30))
    c1.send_command(put("foo", "bar"))
    assert c1.get_result(timeout=5) == put_ok()
    c2.send_command(put("foo", "baz"))
    assert c2.get_result(timeout=5) == put_ok()
    c3.send_command(get("foo"))
    assert c3.get_result(timeout=5) == get_result("baz")
    state.stop()


@lab_test("3", 7, "One server switches partitions", points=10, categories=(RUN_TESTS,))
def test07_server_switches_partitions():
    """PaxosTest.test07: a value decided in {1,2,3} must be visible from
    {3,4,5} after the overlap server switches sides."""
    state = make_run_state(5)
    c1 = state.add_client(client(1))
    c2 = state.add_client(client(2))
    settings = RunSettings().max_time(30)
    settings.partition(server(1), server(2), server(3), client(1))
    state.start(settings)
    c1.send_command(put("foo", "bar"))
    assert c1.get_result(timeout=10) == put_ok()
    state.stop()

    settings.reset_network()
    settings.partition(server(3), server(4), server(5), client(2))
    state.start(settings)
    c2.send_command(get("foo"))
    assert c2.get_result(timeout=10) == get_result("bar")
    state.stop()


@lab_test("3", 8, "Multiple clients, synchronous put/get", points=10, categories=(RUN_TESTS,))
def test08_synchronous_clients():
    """PaxosTest.test08 (scaled 15x20 -> 5x5): all clients issue the same
    command each round via addCommand; every round's results must agree."""
    n_iters, n_clients = 5, 5
    state = make_run_state(3, lambda: kv_workload([]))
    for i in range(1, n_clients + 1):
        state.add_client_worker(client(i))
    state.start(RunSettings().max_time(60))
    for i in range(n_iters):
        state.add_command("PUT:foo:%r8")
        state.wait_for()
        state.add_command("GET:foo")
        state.wait_for()
    state.stop()
    r = ALL_RESULTS_SAME.check(state)
    assert r.value, r.error_message()
    assert_logs_consistent(state)


@lab_test("3", 13, "Two sequential clients", points=10, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test13_simple_put_get_unreliable():
    state = make_run_state(3)
    c1 = state.add_client(client(1))
    c2 = state.add_client(client(2))
    settings = RunSettings().max_time(30)
    settings.network_deliver_rate(0.8)
    state.start(settings)
    c1.send_command(put("foo", "bar"))
    assert c1.get_result(timeout=15) == put_ok()
    c2.send_command(get("foo"))
    assert c2.get_result(timeout=15) == get_result("bar")
    state.stop()


@lab_test("3", 14, "Multiple clients, synchronous put/get", points=15, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test14_synchronous_clients_unreliable():
    """PaxosTest.test14: test08 at deliver rate 0.8 (scaled)."""
    n_iters, n_clients = 3, 4
    state = make_run_state(3, lambda: kv_workload([]))
    for i in range(1, n_clients + 1):
        state.add_client_worker(client(i))
    settings = RunSettings().max_time(90)
    settings.network_deliver_rate(0.8)
    state.start(settings)
    for i in range(n_iters):
        state.add_command("PUT:foo:%r8")
        state.wait_for()
        state.add_command("GET:foo")
        state.wait_for()
    state.stop()
    r = ALL_RESULTS_SAME.check(state)
    assert r.value, r.error_message()
    assert_logs_consistent(state)


@lab_test("3", 15, "Multiple clients, concurrent appends", points=15, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
def test15_concurrent_appends_unreliable():
    """PaxosTest.test15 (scaled 25x5 -> 8x3): same-key appends at 0.8 stay
    linearizable."""
    state = make_run_state(3, lambda: append_same_key_workload(3))
    for i in range(1, 9):
        state.add_client_worker(client(i))
    settings = RunSettings().max_time(60)
    settings.network_deliver_rate(0.8)
    state.run(settings)
    assert_ok(state)
    r = APPENDS_LINEARIZABLE.check(state)
    assert r.value, r.error_message()
    assert_logs_consistent(state)


def _repartition_loop(state, settings, stop, n_servers, n_clients,
                      period=1.0):
    import random as _random

    addrs = [server(i) for i in range(1, n_servers + 1)]
    clients = [client(i) for i in range(1, n_clients + 1)]
    while not stop.is_set():
        for _ in range(2):
            _random.shuffle(addrs)
            majority = addrs[:n_servers // 2 + 1]
            settings.reconnect().partition(*(clients + majority))
            if stop.wait(period):
                return
        settings.reconnect()
        if stop.wait(period):
            return


@lab_test("3", 16, "Multiple clients, single partition and heal", points=15, categories=(RUN_TESTS,))
@retry_wallclock_flake
def test16_single_partition():
    """PaxosTest.test16: infinite workloads keep running through one
    partition-and-heal cycle; max wait stays under 3s."""
    n_clients = 3
    state = make_run_state(5, different_keys_infinite_workload)
    for i in range(1, n_clients + 1):
        state.add_client_worker(client(i))
    settings = RunSettings().max_time(60)
    state.start(settings)
    time.sleep(3)
    settings.partition(server(1), server(2), server(3),
                       *(client(i) for i in range(1, n_clients + 1)))
    time.sleep(2)
    settings.reconnect()
    time.sleep(3)
    state.stop()
    assert_ok(state)
    assert_logs_consistent(state, all_slots=False)
    for w in state.client_workers().values():
        mw = w.max_wait(state.stop_time)
        assert mw is not None and mw[0] < 3.0, f"max wait {mw}"


def _constant_repartition(deliver_rate=None, length_secs=None):
    import threading

    if length_secs is None:
        length_secs = STORM_SECS
    n_clients, n_servers = 3, 5
    state = make_run_state(
        n_servers, lambda: different_keys_infinite_workload(10))
    for i in range(1, n_clients + 1):
        state.add_client_worker(client(i))
    settings = RunSettings().max_time(length_secs + 30)
    if deliver_rate is not None:
        settings.network_deliver_rate(deliver_rate)
    stop = threading.Event()
    th = threading.Thread(target=_repartition_loop,
                          args=(state, settings, stop, n_servers, n_clients),
                          daemon=True)
    th.start()
    state.start(settings)
    time.sleep(length_secs)
    stop.set()
    th.join(5)
    state.stop()
    assert_ok(state)
    assert_logs_consistent(state, all_slots=False)
    for w in state.client_workers().values():
        mw = w.max_wait(state.stop_time)
        assert mw is not None and mw[0] < 2.5, f"max wait {mw}"
    return state


@lab_test("3", 17, "Constant repartitioning, check maximum wait time", points=20, categories=(RUN_TESTS,))
@retry_wallclock_flake
def test17_constant_repartition():
    """PaxosTest.test17 (30s, CI-scaled to 10s unless
    DSLABS_FULL_BUDGET=1): live repartition thread grabbing a
    fresh majority every period; waits stay bounded."""
    _constant_repartition()


@lab_test("3", 18, "Constant repartitioning, check maximum wait time", points=30, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
@retry_wallclock_flake
def test18_constant_repartition_unreliable():
    """PaxosTest.test18: test17 at deliver rate 0.8."""
    _constant_repartition(deliver_rate=0.8)


@lab_test("3", 19, "Constant repartitioning, full throughput", points=30, categories=(RUN_TESTS, UNRELIABLE_TESTS,))
@retry_wallclock_flake
def test19_repartition_full_throughput():
    """PaxosTest.test19 (scaled): after a repartition storm, a FRESH batch
    of clients replacing the old ones must still complete (no deadlock)."""
    state = _constant_repartition(deliver_rate=0.8, length_secs=8)
    n_clients, n_rounds = 3, 4
    for i in range(1, n_clients + 1):
        state.remove_node(client(i))
    for i in range(1, n_clients + 1):
        state.add_client_worker(client(i + n_clients),
                                append_different_key_workload(n_rounds))
    settings = RunSettings().max_time(60)
    state.run(settings)
    assert_ok(state)


@lab_test("3", 22, "Two clients, sequential appends visible", points=30, categories=(SEARCH_TESTS,))
def test22_two_clients_search():
    """PaxosTest.test22: append X decided in partition {1,2}; append Y must
    then be able to complete (result XY) in BOTH other majorities."""
    state = make_search_state(3, lambda: None)
    state.add_client_worker(client(1), kv_workload(["APPEND:foo:X"], ["X"]))
    state.add_client_worker(client(2), kv_workload(["APPEND:foo:Y"], ["XY"]))

    settings = SearchSettings().max_time(60)
    settings.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings.add_goal(NONE_DECIDED.negate())
    settings.partition(server(1), server(2), client(1))
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    first_append = results.goal_matching_state

    for other, spectator in (((server(1), server(3)), server(2)),
                             ((server(2), server(3)), server(1))):
        s2 = SearchSettings().max_time(180)
        s2.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
        s2.add_goal(CLIENTS_DONE)
        s2.partition(*other, client(2))
        # Retry/election timers of nodes outside the partition explode the
        # Python checker's branching without adding behaviours; gate them
        # (the reference narrows with deliverTimers the same way,
        # PaxosTest.java:1028-1031).
        s2.deliver_timers(client(1), False).deliver_timers(client(2), False)
        s2.deliver_timers(spectator, False)
        results = bfs(first_append, s2)
        assert results.end_condition == EndCondition.GOAL_FOUND, results

    # Linearizability in the narrowed subspaces, timers frozen (the
    # reference's final phases, PaxosTest.java:973-985).
    for other in ((server(1), server(3)), (server(2), server(3))):
        s3 = SearchSettings().max_time(20)
        s3.set_max_depth(first_append.depth + 4)
        s3.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
        s3.add_prune(CLIENTS_DONE)
        s3.partition(*other, client(2))
        s3.deliver_timers(False)
        results = bfs(first_append, s3)
        assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                         EndCondition.TIME_EXHAUSTED), results


@lab_test("3", 23, "Two clients, five servers, multiple leader changes", points=20, categories=(SEARCH_TESTS,))
def test23_quorum_checking_search():
    """PaxosTest.test23QuorumCheckingSearch: surgical staged narrowing —
    two commands forced onto disjoint quorums through multiple leader
    changes; slot 1 must stay valid throughout and c1 must win."""
    from dslabs_tpu.labs.paxos.paxos import PaxosLogSlotStatus as S
    from dslabs_tpu.labs.paxos.predicates import has_command, has_status

    state = make_search_state(5, lambda: None)
    c1 = append("foo", "X")
    c2 = append("foo", "Y")
    state.add_client_worker(client(1), kv_workload(["APPEND:foo:X"]))
    state.add_client_worker(client(2), kv_workload(["APPEND:foo:Y"]))

    def base_settings():
        s = SearchSettings().max_time(60)
        s.add_invariant(slot_valid(1))
        for i in range(1, 6):
            s.add_prune(has_status(server(i), 2, S.EMPTY).negate())
            s.add_prune(has_status(server(i), 1, S.CLEARED))
        s.add_prune(has_status(server(1), 1, S.EMPTY).negate())
        s.add_prune(has_status(server(2), 1, S.EMPTY).negate())
        s.node_active(client(1), False)
        s.link_active(client(1), server(4), True)
        s.node_active(client(2), False)
        s.link_active(client(2), server(5), True)
        s.add_prune(has_command(server(4), 1, c2))
        s.add_prune(has_command(server(5), 1, c1))
        return s

    # c1's command to server 4, then on to server 3 (quorum {2,3,4}).
    s = base_settings()
    s.node_active(server(1), False).node_active(server(5), False)
    s.deliver_timers(server(1), False).deliver_timers(server(5), False)
    s.deliver_timers(client(2), False)
    s.add_goal(has_command(server(4), 1, c1))
    results = bfs(state, s)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    c1_at_s4 = results.goal_matching_state

    s.clear_goals().add_goal(has_command(server(3), 1, c1))
    results = bfs(c1_at_s4, s)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    c1_at_s3 = results.goal_matching_state

    # c2's command via quorum {1,2,3,5} (servers 3 & 4 asleep first).
    s = base_settings()
    s.node_active(server(4), False).node_active(server(3), False)
    s.clear_deliver_timers()
    s.deliver_timers(server(4), False).deliver_timers(server(3), False)
    s.deliver_timers(client(1), False)
    s.add_goal(has_command(server(5), 1, c2))
    results = bfs(c1_at_s3, s)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    c2_at_s5 = results.goal_matching_state

    s.node_active(server(3), True).deliver_timers(server(3), True)
    s.clear_goals().add_goal(has_command(server(3), 1, c2))
    results = bfs(c2_at_s5, s)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    c2_at_s3 = results.goal_matching_state

    # Clear the narrowing; drop all pending messages; force c1 back onto
    # server 1 and make sure it can still be CHOSEN (the overwrite path).
    c2_at_s3.drop_pending_messages()
    s = SearchSettings().max_time(60)
    s.add_invariant(slot_valid(1))
    for i in range(1, 6):
        s.add_prune(has_status(server(i), 1, S.CLEARED))
    s.add_prune(has_command(server(4), 1, c2))
    s.add_prune(has_command(server(2), 1, c2))
    s.add_prune(has_command(server(1), 1, c2))
    s.node_active(server(5), False).node_active(server(3), False)
    s.node_active(client(2), False)
    s.link_active(server(1), server(2), False)
    s.link_active(server(2), server(1), False)
    s.deliver_timers(server(5), False).deliver_timers(server(3), False)
    s.deliver_timers(client(2), False)
    # c1 is already in s4's log, so the idle client's retries are noise
    # (s1/s2 elections stay enabled — they are what dethrone s4's stale
    # leadership so it can re-elect and re-propose c1).
    s.deliver_timers(client(1), False)
    s.max_time(240)
    s.add_goal(has_command(server(1), 1, c1))
    results = bfs(c2_at_s3, s)
    assert results.end_condition == EndCondition.GOAL_FOUND, results
    c1_at_s1 = results.goal_matching_state

    s.clear_goals().add_goal(has_status(server(4), 1, S.CHOSEN))
    results = bfs(c1_at_s1, s)
    assert results.end_condition == EndCondition.GOAL_FOUND, results

    # Re-admit server 3's dropped messages and keep the space clean.
    c1_at_s1.undrop_messages_from(server(3))
    s.clear_goals()
    s.link_active(server(3), server(4), True)
    s.set_max_depth(c1_at_s1.depth + 4)
    results = bfs(c1_at_s1, s)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results


@lab_test("3", 24, "Handling of logs with holes", points=0, categories=(SEARCH_TESTS,))
def test24_logs_with_holes_search():
    """PaxosTest.test24: find a state where slot 2 is chosen while slot 1
    is not, drop pending messages, and verify the space stays clean."""
    from dslabs_tpu.labs.paxos.paxos import PaxosLogSlotStatus as S
    from dslabs_tpu.labs.paxos.predicates import has_status

    state = make_search_state(3, lambda: None)
    state.add_client_worker(client(1), kv_workload(
        ["APPEND:foo:x", "APPEND:foo:z"]))
    state.add_client_worker(client(2), kv_workload(
        ["APPEND:foo:y", "APPEND:foo:w"]))

    settings = SearchSettings().max_time(30)
    settings.add_invariant(APPENDS_LINEARIZABLE)
    settings.add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings.add_prune(CLIENTS_DONE)
    for i in range(1, 4):
        settings.add_goal(
            has_status(server(i), 2, S.CHOSEN).and_(
                has_status(server(i), 1, S.ACCEPTED).or_(
                    has_status(server(i), 1, S.EMPTY))))
    results = bfs(state, settings)

    # Not all correct implementations reach such states (the reference
    # returns silently too, PaxosTest.java:1125-1127).
    if results.end_condition != EndCondition.GOAL_FOUND:
        return
    hole = results.goal_matching_state
    hole.drop_pending_messages()

    settings.clear_goals().max_time(20)
    settings.set_max_depth(hole.depth + 4)
    results = bfs(hole, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results


@lab_test("3", 26, "Five server random search", points=20, categories=(SEARCH_TESTS,))
def test26_five_server_random_search():
    """PaxosTest.test26: randomized DFS probes over five servers."""
    state = make_search_state(5, lambda: None)
    state.add_client_worker(client(1), kv_workload(["APPEND:foo:x"]))
    state.add_client_worker(client(2), kv_workload(["APPEND:foo:y"]))

    settings = SearchSettings()
    settings.set_max_depth(1000).max_time(8)
    settings.add_invariant(APPENDS_LINEARIZABLE).add_invariant(LOGS_CONSISTENT)
    settings.add_prune(CLIENTS_DONE)
    results = dfs(state, settings)
    assert not results.terminal_found()


@lab_test("3", 27, "Paxos runs in singleton group", points=0, categories=(RUN_TESTS, SEARCH_TESTS,))
def test27_singleton_paxos():
    """PaxosTest.test27: a single-server Paxos group both runs and
    searches correctly (the degenerate quorum of one)."""
    state = make_run_state(1, lambda: append_different_key_workload(3))
    state.add_client_worker(client(1))
    state.run(RunSettings().max_time(20))
    assert_ok(state)
    assert_logs_consistent(state)

    sstate = make_search_state(1)
    sstate.add_client_worker(client(1), kv_workload(["PUT:foo:bar", "GET:foo"],
                                                    ["PutOk", "bar"]))
    settings = SearchSettings().max_time(30)
    settings.add_invariant(RESULTS_OK).add_invariant(LOGS_CONSISTENT_ALL_SLOTS)
    settings.add_goal(CLIENTS_DONE)
    results = bfs(sstate, settings)
    assert results.end_condition == EndCondition.GOAL_FOUND, results

    settings.clear_goals().add_prune(CLIENTS_DONE)
    results = bfs(sstate, settings)
    assert results.end_condition in (EndCondition.SPACE_EXHAUSTED,
                                     EndCondition.TIME_EXHAUSTED), results
