"""Packed-wire mesh exchange + skew-balanced owner shards (ISSUE 18):
the sharded carry moves PACKED rows through the fused owner-hashed
``all_to_all`` and levels what it moves —

* the wire descriptor cuts bytes-per-state >= 8x on the generated lab1
  and paxos specs (13.7x / 13.5x measured — asserted from the
  descriptor the engine actually installs);
* packed-vs-raw exchange (``mesh_pack=False`` = the parity oracle
  behind DSLABS_MESH_PACK) is BIT-IDENTICAL
  (unique/explored/verdict/depth/dropped) across widths {1, 2, 4, 8},
  strict and beam, and across a cross-width resume chain 8 -> 4 -> 2
  -> 1 through the packed checkpoint format;
* delta-from-level-base lanes (``Field(delta=)``, the varint lane for
  view-number-style unbounded fields) pack the pb spec and stay exact;
* root-fanout seeding + chunk-granular boundary stealing strictly
  improve the skewed fixture's frontier imbalance at width 8 with
  exact count parity (visited shards never move, so dedup ownership —
  and therefore every count — is untouched by construction AND by
  assertion);
* the spill spool rides the packed encoding: 1/8-capacity strict runs
  keep exact parity with the full-table oracle;
* the fused promote still lowers with ZERO collectives under packing
  (raw-lane repack at the boundary is elementwise);
* pack/decode/steal are first-class dispatch sites (DISPATCH_SITES +
  ``dispatch_site_programs()``) and their jaxprs audit clean;
* a mesh job that runs UNPACKED (hand twin -> identity codec, or the
  parity-oracle knob) is loud: a ``mesh_unpacked`` telemetry event,
  never silence.

Marked ``mesh`` (``make mesh-smoke`` runs this suite too).
"""

import dataclasses
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import packing as packing_mod  # noqa: E402
from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import (ShardedTensorSearch,  # noqa: E402
                                    make_mesh)
from dslabs_tpu.tpu.specs import (clientserver_spec,  # noqa: E402
                                  paxos_spec, pb_spec, pingpong_spec)
from dslabs_tpu.tpu.telemetry import Telemetry  # noqa: E402

pytestmark = pytest.mark.mesh

_COLLECTIVES = ("all-to-all", "all_to_all", "all-reduce", "all_reduce",
                "all-gather", "all_gather", "collective-permute",
                "collective_permute", "reduce-scatter", "reduce_scatter")


def _pruned(p):
    name = next(iter(p.goals))
    return dataclasses.replace(p, goals={},
                               prunes={name: p.goals[name]})


def _pingpong():
    return _pruned(pingpong_spec(2).compile())


def _lab1_small():
    return _pruned(clientserver_spec(1, 2).compile())


def _build(proto, n_devices, **kw):
    kw.setdefault("chunk_per_device", 16)
    kw.setdefault("frontier_cap", 1 << 8)
    kw.setdefault("visited_cap", 1 << 10)
    kw.setdefault("row_exchange", True)
    return ShardedTensorSearch(proto, make_mesh(n_devices), **kw)


def _assert_exact(a, b):
    assert a.end_condition == b.end_condition
    assert a.unique_states == b.unique_states
    assert a.states_explored == b.states_explored
    assert a.depth == b.depth
    assert a.dropped == b.dropped


# ------------------------------------------------------ wire descriptor

@pytest.mark.parametrize("spec_fn,floor", [
    (lambda: clientserver_spec(3, 4).compile(), 8.0),
    (lambda: paxos_spec(3).compile(), 8.0),
])
def test_wire_bytes_per_state_floor(spec_fn, floor):
    """ACCEPTANCE: the mesh wire descriptor (same derivation the
    sharded engine installs: delta=True) cuts bytes-per-state >= 8x on
    the lab1 and packed-paxos specs."""
    proto = dataclasses.replace(spec_fn(), goals={})
    lanes = TensorSearch(proto, chunk=8).lanes
    pk = packing_mod.derive_packing(proto, lanes, delta=True)
    assert pk is not None and not pk.identity
    assert pk.pack_ratio >= floor, pk.descriptor()
    assert pk.words * 4 * floor <= lanes * 4


def test_engine_installs_packed_wire_by_default():
    """DSLABS_MESH_PACK defaults ON: a generated spec gets the
    non-identity codec, the carry plane shrinks to the packed word
    count, and the verdict stamps the ratio (satellite: pack_ratio on
    SearchOutcome + levels)."""
    search = _build(_pingpong(), 2)
    assert search.mesh_pack and search._pk is not None
    assert search.plane == search._pk.words < search.lanes
    out = search.run()
    assert out.pack_ratio == pytest.approx(
        search.lanes * 4 / (search.plane * 4), rel=0.01)
    assert out.levels
    assert all(lv["pack_ratio"] > 1.0 for lv in out.levels)
    raw = _build(_pingpong(), 2, mesh_pack=False)
    assert raw._pk is None and raw.plane == raw.lanes


# ------------------------------------------------------- parity matrix

@pytest.mark.parametrize("width", [1, 2, 4, 8])
def test_packed_vs_raw_parity_pingpong(width):
    """ACCEPTANCE: bit-identical verdicts between the packed wire and
    the raw parity oracle at every mesh width."""
    proto = _pingpong()
    packed = _build(proto, width).run()
    raw = _build(proto, width, mesh_pack=False).run()
    assert packed.end_condition == "SPACE_EXHAUSTED"
    _assert_exact(packed, raw)


@pytest.mark.parametrize("strict", [True, False])
def test_packed_vs_raw_parity_lab1(strict):
    """Lab1 (generated, 13.7x codec) at width 8, strict AND beam: the
    beam run truncates at a deliberately small frontier cap and the
    drop count must match bit-for-bit too."""
    proto = _pruned(clientserver_spec(2, 2).compile())
    width = 8 if strict else 2
    kw = dict(frontier_cap=1 << 8 if strict else 4,
              visited_cap=1 << 12, strict=strict, max_depth=8)
    if not strict:
        # f_cap floors at chunk_per_device; 4 rows/device truncates
        # this fixture's levels (per-device occupancy peaks at 7).
        kw["chunk_per_device"] = 4
    packed = _build(proto, width, **kw).run()
    raw = _build(proto, width, mesh_pack=False, **kw).run()
    _assert_exact(packed, raw)
    if not strict:
        assert packed.dropped > 0   # the beam really truncated


def test_delta_lane_parity_pb():
    """The varint lane (ISSUE 18b): pb's view-number fields carry
    ``Field(delta=)`` domains, so the wire codec packs them against a
    per-level base instead of falling back to identity — and the
    delta-packed run matches the raw oracle exactly."""
    proto = _pruned(pb_spec(2, 1, 1).compile())
    search = _build(proto, 2, max_depth=3, frontier_cap=1 << 9,
                    visited_cap=1 << 12)
    assert search._pk is not None and search._pk.has_delta
    assert search._mesh_delta
    assert {"pb_cur", "pb_nxt"} <= set(search._carry_names())
    packed = search.run()
    raw = _build(proto, 2, mesh_pack=False, max_depth=3,
                 frontier_cap=1 << 9, visited_cap=1 << 12).run()
    _assert_exact(packed, raw)
    assert packed.states_explored > 0


def test_cross_width_resume_packed_8_4_2_1(tmp_path):
    """A packed-wire checkpoint re-shards exactly onto every narrower
    width: the dump stores packed rows + the encoding marker + (for
    delta specs) the pack base, and each resume re-hashes owners at
    the new D."""
    proto = _pingpong()
    oracle = _build(proto, 8).run()
    assert oracle.end_condition == "SPACE_EXHAUSTED"

    path = str(tmp_path / "mesh-packed.ckpt")
    out = _build(proto, 8, checkpoint_path=path,
                 checkpoint_every=1, max_depth=2).run()
    assert out.end_condition == "DEPTH_EXHAUSTED"
    for width, depth in ((4, 3), (2, 4), (1, None)):
        search = _build(proto, width, checkpoint_path=path,
                        checkpoint_every=1, max_depth=depth)
        assert search._pk is not None   # the packed wire, end to end
        out = search.run(resume=True)
    assert out.end_condition == oracle.end_condition
    assert out.unique_states == oracle.unique_states
    assert out.states_explored == oracle.states_explored
    assert out.depth == oracle.depth


# ------------------------------------------------------- work stealing

def test_steal_plan_conserves_rows():
    """Host planner unit contract: donations conserve rows, never
    exceed one chunk per (donor, receiver) pair, only move whole
    chunks past depth 1, and respect the threshold gate."""
    search = _build(_pingpong(), 8, steal_threshold=1.25)
    D, K = search.n_devices, search.cpd

    occ = [800] + [0] * (D - 1)          # the skewed fixture
    plan = search._steal_plan(occ, depth=5)
    assert plan is not None and plan.shape == (D, D)
    assert plan.max() <= K
    assert (plan.sum(axis=1) <= np.asarray(occ)).all()
    after = [int(o - plan[d].sum() + plan[:, d].sum())
             for d, o in enumerate(occ)]
    assert sum(after) == sum(occ)        # conservation
    mean = sum(occ) / D
    assert max(after) / mean < max(occ) / mean   # strictly better
    assert (plan[plan > 0] % K == 0).all()       # whole chunks only

    # Depth 1 = root fanout: unconditional and unrounded.
    plan1 = search._steal_plan([5] + [0] * (D - 1), depth=1)
    assert plan1 is not None and plan1.sum() > 0

    # Balanced frontier under the threshold: no plan, no dispatch.
    assert search._steal_plan([100] * D, depth=5) is None
    assert _build(_pingpong(), 1,
                  steal_threshold=1.25)._steal_plan([100], 5) is None


def test_steal_parity_and_imbalance_improves():
    """ACCEPTANCE: on the skewed fixture (a lone root hashes to ONE
    owner, so level 1 starts at imbalance D) stealing at width 8
    strictly improves imbalance_max with exact count parity."""
    proto = _pruned(clientserver_spec(3, 4).compile())
    kw = dict(chunk_per_device=4, frontier_cap=1 << 9,
              visited_cap=1 << 13, max_depth=8)
    base = _build(proto, 8, **kw).run()
    search = _build(proto, 8, steal_threshold=1.05, **kw)
    assert search._steal_on
    out = search.run()
    _assert_exact(base, out)             # counts bit-identical
    steals = [lv["steal"] for lv in (out.levels or [])
              if lv.get("steal")]
    assert steals, "the skewed fixture must trigger at least one steal"
    for s in steals:
        assert s["moved"] > 0
        assert s["imbalance_after"] <= s["imbalance_before"]
    # The worst post-steal frontier imbalance strictly beats the worst
    # pre-steal one — the number bench --mesh reports and the ledger
    # guards (mesh:imbalance_max).
    assert (max(s["imbalance_after"] for s in steals)
            < max(s["imbalance_before"] for s in steals))
    post = [lv["skew"]["frontier_post_steal"] for lv in out.levels
            if lv.get("skew", {}).get("frontier_post_steal")]
    assert post and all("imbalance" in m for m in post)


def test_steal_off_by_default():
    """DSLABS_MESH_STEAL_THRESHOLD unset = no stealing: the knob is
    opt-in (bench --mesh opts in; parity oracles stay untouched)."""
    assert "DSLABS_MESH_STEAL_THRESHOLD" not in os.environ
    search = _build(_pingpong(), 8)
    assert not search._steal_on
    out = search.run()
    assert not any(lv.get("steal") for lv in (out.levels or []))


# ------------------------------------------------------- spill + promote

def test_packed_spill_parity_eighth_capacity():
    """ACCEPTANCE: the spill spool rides the packed encoding — a
    strict run with the visited table capped at ~1/8 of the reachable
    count keeps exact parity with the full-table oracle through
    drain/evict/reinject of PACKED spool segments."""
    proto = _lab1_small()
    base = _build(proto, 2, frontier_cap=1 << 9,
                  visited_cap=1 << 13, max_depth=8).run()
    cap = 1 << max(3, int(np.floor(
        np.log2(max(base.unique_states // 8, 8)))))
    out = _build(proto, 2, frontier_cap=1 << 9, visited_cap=cap,
                 max_depth=8, spill=True).run()
    _assert_exact(base, out)
    assert out.dropped_states == 0
    assert out.spilled_keys > 0          # the tier really engaged


@pytest.mark.parametrize("spec_fn", [_pingpong,
                                     lambda: _pruned(
                                         pb_spec(2, 1, 1).compile())])
def test_fused_promote_zero_collectives_under_packing(spec_fn):
    """ACCEPTANCE pin: the fused promote stays a LOCAL buffer swap
    under the packed wire — including the delta repack (pb spec),
    which re-bases rows elementwise against the replicated pb vector
    and must not reintroduce a boundary collective."""
    search = _build(spec_fn(), 8)
    assert search._pk is not None
    text = search._finish_level.lower(search._carry_sds()).as_text()
    assert not any(c in text for c in _COLLECTIVES), (
        "packed fused-exchange promote must stay collective-free")


# ------------------------------------------------------- observability

def test_dispatch_sites_cover_pack_decode_steal():
    """CI satellite: pack/decode/steal are canonical dispatch sites —
    registered in DISPATCH_SITES, emitted by the sharded engine's
    dispatch_site_programs(), and their jaxprs audit clean (J1-J5)."""
    from dslabs_tpu.analysis.jaxpr_audit import audit_sites
    from dslabs_tpu.tpu.telemetry import DISPATCH_SITES

    for site in ("packing.pack", "packing.unpack", "sharded.steal"):
        assert site in DISPATCH_SITES
    assert DISPATCH_SITES["sharded.steal"]["program"]
    search = _build(_pingpong(), 2, steal_threshold=1.25)
    sites = search.dispatch_site_programs()
    picked = {k: v for k, v in sites.items()
              if k in ("packing.pack", "packing.unpack",
                       "sharded.steal")}
    assert set(picked) == {"packing.pack", "packing.unpack",
                           "sharded.steal"}
    assert audit_sites(picked, "ShardedTensorSearch") == []


def test_mesh_unpacked_event_is_loud():
    """Satellite: a mesh job shipping RAW lanes is loud — the hand
    twin (identity codec) and the parity-oracle knob both emit a
    ``mesh_unpacked`` event; the packed default emits none."""
    def run(proto, **kw):
        tel = Telemetry()
        _build(proto, 2, telemetry=tel, max_depth=4, **kw).run()
        return [e for e in tel.events
                if e.get("t") == "event"
                and e.get("kind") == "mesh_unpacked"]

    hand = dataclasses.replace(
        make_pingpong_protocol(2), goals={})
    ev = run(hand)
    assert ev and ev[0]["reason"] == "identity descriptor"
    ev = run(_pingpong(), mesh_pack=False)
    assert ev and ev[0]["reason"] == "knob"
    assert run(_pingpong()) == []


def test_status_skew_agg_block_and_watch(tmp_path, capsys):
    """Bugfix satellite: STATUS.json carries a schema-pinned skew
    aggregate (imbalance_max/mean/cv live from the per-level lanes)
    and ``telemetry watch`` renders it during a run."""
    import json

    from dslabs_tpu.tpu import telemetry as tel_mod

    ck = str(tmp_path / "search.ckpt")
    tel = Telemetry.for_checkpoint(ck)
    search = _build(_pruned(clientserver_spec(3, 4).compile()), 8,
                    steal_threshold=1.05, chunk_per_device=4,
                    frontier_cap=1 << 9, visited_cap=1 << 13,
                    max_depth=6, telemetry=tel)
    search.run()
    tel.close()

    st = json.loads((tmp_path / "STATUS.json").read_text())
    assert "skew_agg" in st              # schema-pinned
    agg = st["skew_agg"]
    for key in ("imbalance_max", "imbalance_mean", "cv_max", "levels"):
        assert key in agg
    assert agg["levels"] > 0
    assert agg["imbalance_max"] >= agg["imbalance_mean"] > 0
    assert agg["stolen_rows"] > 0        # the steal rode the feed

    assert tel_mod.main(["watch", str(tmp_path), "--once"]) == 0
    text = capsys.readouterr().out
    assert "skew agg:" in text
    assert "imbalance_max=" in text


def test_compare_ledger_guards_mesh_wire_and_imbalance():
    """Bench satellite: the ledger guards the two numbers this PR
    exists to hold — wire bytes-per-state rising (codec fell back to
    raw) or post-steal imbalance_max rising (stealing stopped
    levelling) past the threshold is an rc-1 regression."""
    from dslabs_tpu.tpu.telemetry import compare_ledger

    def rec(wire_bps, imb):
        return {"t": "bench", "value": 1000.0,
                "mesh": {"value": 1000.0,
                         "wire": {"wire_bytes_per_state": wire_bps,
                                  "wire_bytes_per_state_raw": 264,
                                  "key_bytes_per_state": 16},
                         "imbalance_max": imb}}

    cmp = compare_ledger([rec(16, 1.2), rec(264, 8.0)], threshold=0.1)
    phases = {e["phase"] for e in cmp["regressions"]}
    assert "mesh:wire_bytes_per_state" in phases
    assert "mesh:imbalance_max" in phases
    assert cmp["mesh"]["wire_bytes_per_state"]["best_prior"] == 16

    cmp = compare_ledger([rec(16, 1.2), rec(16, 1.2)], threshold=0.1)
    assert not [e for e in cmp["regressions"]
                if str(e["phase"]).startswith("mesh:")]
