"""Replicated-protocol spec layer (ISSUE 20): the shipped lab3/lab4
protocols compile from ProtocolSpec (tpu/specs_lab3.py +
tpu/specs_lab4.py — Slots blocks, QuorumCount declarations, Fragment
composition); the retired hand twins live on UNSHIPPED in
tests/fixtures/hand_twins/ as parity ORACLES —

* generated-vs-hand parity matrix: identical unique-state counts at
  every pinned small depth for lab3 paxos and all four lab4 scopes
  (join, part-1 shardstore, 2PC tx, multi-server groups);
* init-vector equality where the generated layout is lane-identical to
  the hand twin (join, part-1 shardstore);
* compile gates: a STATIC slot index outside the declared block range
  and a quorum over an empty or unknown group refuse loudly
  (structured SpecError) at compile, never silently misread lanes;
* packed slot lanes roundtrip bit-exactly through the storage codec,
  the checkpoint format, and the mesh wire descriptor (the PR-18
  parity-oracle pattern: packed vs unpacked is assertion-exact);
* spec-declared domains reach the bit-packer: >= 2x bytes-per-state
  reduction on every generated lab3/lab4 spec (the bench ``--labs``
  phase records the same numbers behind the ``labs:bytes_per_state``
  ledger guard).

Marked ``spec`` (``make spec-smoke``)."""

import dataclasses
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import packing as packing_mod  # noqa: E402
from dslabs_tpu.tpu.compiler import (Field, MessageType,  # noqa: E402
                                     NodeKind, ProtocolSpec, SpecError,
                                     TimerType)
from dslabs_tpu.tpu.engine import TensorSearch  # noqa: E402
from dslabs_tpu.tpu.quorum import QuorumCount  # noqa: E402
from dslabs_tpu.tpu.slots import SlotField, Slots  # noqa: E402
from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol  # noqa: E402
from dslabs_tpu.tpu.specs_lab4 import (make_join_protocol,  # noqa: E402
                                       make_shardstore_multi_protocol,
                                       make_shardstore_protocol,
                                       make_shardstore_tx_protocol)

# The hand twins are test fixtures now — ORACLES for this module, not
# shipped modules (the generated specs are the single source of truth).
_FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
if _FIXTURES not in sys.path:
    sys.path.insert(0, _FIXTURES)

from hand_twins.paxos import \
    make_paxos_protocol as hand_paxos  # noqa: E402
from hand_twins.shardmaster_join import \
    make_join_protocol as hand_join  # noqa: E402
from hand_twins.shardstore import \
    make_shardstore_protocol as hand_shardstore  # noqa: E402
from hand_twins.shardstore_multi import \
    make_shardstore_multi_protocol as hand_multi  # noqa: E402
from hand_twins.shardstore_tx import \
    make_shardstore_tx_protocol as hand_tx  # noqa: E402

pytestmark = pytest.mark.spec


def _count(proto, depth, chunk=256):
    out = TensorSearch(dataclasses.replace(proto, goals={}),
                       chunk=chunk, max_depth=depth).run()
    return out.unique_states


# ------------------------------------------- generated-vs-hand matrix

@pytest.mark.parametrize("depth,expect", [(1, 6), (2, 25), (3, 102)])
def test_parity_lab3_paxos(depth, expect):
    assert _count(make_paxos_protocol(), depth) == expect
    assert _count(hand_paxos(), depth) == expect


@pytest.mark.parametrize("g,depth,expect", [
    (1, 1, 3), (1, 3, 10), (2, 2, 6), (2, 3, 11),
])
def test_parity_lab4_join(g, depth, expect):
    assert _count(make_join_protocol(g), depth) == expect
    assert _count(hand_join(g), depth) == expect


@pytest.mark.parametrize("depth,expect", [(1, 6), (2, 23), (3, 74)])
def test_parity_lab4_shardstore(depth, expect):
    assert _count(make_shardstore_protocol([1, 1]), depth) == expect
    assert _count(hand_shardstore([1, 1]), depth) == expect


@pytest.mark.parametrize("depth,expect", [(1, 8), (2, 38)])
def test_parity_lab4_tx(depth, expect):
    assert _count(make_shardstore_tx_protocol(1), depth) == expect
    assert _count(hand_tx(1), depth) == expect


@pytest.mark.slow
@pytest.mark.parametrize("depth,expect", [(1, 8), (2, 42)])
def test_parity_lab4_multi(depth, expect):
    assert _count(make_shardstore_multi_protocol(), depth,
                  chunk=512) == expect
    assert _count(hand_multi(), depth, chunk=512) == expect


@pytest.mark.parametrize("gen_fn,hand_fn", [
    (lambda: make_join_protocol(1), lambda: hand_join(1)),
    (lambda: make_shardstore_protocol([1, 1]),
     lambda: hand_shardstore([1, 1])),
])
def test_init_vectors_lane_identical(gen_fn, hand_fn):
    """Where the generated layout reproduces the hand twin's lanes
    one-for-one (join, part-1 shardstore), the initial node vector is
    BIT-IDENTICAL — the adapters' lane predicates carry over unedited."""
    gen, hand = gen_fn(), hand_fn()
    assert np.array_equal(np.asarray(gen.init_nodes()),
                          np.asarray(hand.init_nodes()))


# ------------------------------------------------------ compile gates

def _tiny_spec(slot_index=1, quorums=(), kinds=None):
    spec = ProtocolSpec(
        "spec-gate",
        nodes=kinds if kinds is not None else [
            NodeKind("proc", 3, (
                Field("x", hi=4),
                Slots("log", 2, (SlotField("cmd", hi=7),), base=1),
            ))],
        messages=[MessageType("GO", ())],
        timers=[TimerType("TICK", (), 10, 10)],
        net_cap=4, timer_cap=1, quorums=quorums)

    @spec.on("proc", "GO")
    def go(ctx, m):
        ctx.put("x", ctx.slot_get("log", "cmd", slot_index))

    spec.initial_messages.append(("GO", 0, 0, {}))
    spec.invariants["OK"] = lambda v: True
    return spec


def test_static_slot_index_out_of_range_refused():
    """slot_get/slot_put with a STATIC index outside [base, base+n)
    is a structured SpecError at compile — the off-by-one that would
    silently read the neighbouring lane in a hand twin."""
    _tiny_spec(slot_index=2).compile()          # in range: fine
    with pytest.raises(SpecError, match="outside declared range"):
        _tiny_spec(slot_index=3).compile()      # base=1, n=2 -> [1, 3)
    with pytest.raises(SpecError, match="outside declared range"):
        _tiny_spec(slot_index=0).compile()


def test_quorum_over_empty_or_unknown_group_refused():
    """A quorum over zero instances is vacuous at every threshold; a
    quorum over an undeclared kind is a typo.  Both refuse loudly at
    compile instead of deep inside a search."""
    _tiny_spec(quorums=(QuorumCount("q", over="proc"),)).compile()
    with pytest.raises(SpecError, match="unknown node kind"):
        _tiny_spec(quorums=(QuorumCount("q", over="procs"),)).compile()
    kinds = [
        NodeKind("proc", 3, (
            Field("x", hi=4),
            Slots("log", 2, (SlotField("cmd", hi=7),), base=1))),
        NodeKind("ghost", 0, (Field("y", hi=1),)),
    ]
    with pytest.raises(SpecError, match="EMPTY group"):
        _tiny_spec(kinds=kinds,
                   quorums=(QuorumCount("q", over="ghost"),)).compile()


# ------------------------------------- packed slot-lane roundtrips

def test_packed_slot_lanes_codec_roundtrip():
    """Random in-domain rows of the generated paxos spec — whose log /
    p2bv / votes lanes all come from Slots declarations — roundtrip
    bit-exactly through BOTH codecs the engine installs: the storage
    descriptor (frontier SoA, spill spool, checkpoints) and the mesh
    wire descriptor (delta=True), numpy and jnp agreeing."""
    proto = dataclasses.replace(make_paxos_protocol(), goals={})
    eng = TensorSearch(proto, chunk=64)
    doms, sents = packing_mod._flat_domains(proto)
    rng = np.random.default_rng(20)
    rows = np.zeros((64, eng.lanes), np.int32)
    from dslabs_tpu.tpu.engine import SENTINEL
    for i, (dom, s_cap) in enumerate(zip(doms, sents)):
        if dom is None:
            rows[:, i] = rng.integers(-2**31, 2**31 - 1, 64)
        elif isinstance(dom, tuple) and dom and dom[0] == "delta":
            rows[:, i] = rng.integers(0, 1 << int(dom[1]), 64)
        else:
            rows[:, i] = rng.integers(dom[0], dom[1] + 1, 64)
        if s_cap:
            rows[rng.random(64) < 0.3, i] = SENTINEL
    for delta in (False, True):
        pk = packing_mod.derive_packing(proto, eng.lanes, delta=delta)
        assert not pk.identity
        base = (np.zeros(eng.lanes, np.int32)
                if delta and pk.has_delta else None)
        kw = {"base": base} if base is not None else {}
        assert (pk.unpack_np(pk.pack_np(rows, **kw), **kw)
                == rows).all()
        rt = np.asarray(pk.unpack_jnp(
            pk.pack_jnp(jax.numpy.asarray(rows), **kw), **kw))
        assert (rt == rows).all()


@pytest.mark.parametrize("spec_fn", [
    lambda: make_join_protocol(1),
    lambda: make_shardstore_protocol([1, 1]),
])
def test_packed_vs_unpacked_search_parity(spec_fn):
    """The PR-18 parity-oracle pattern on the generated specs: the
    packed (default) and unpacked device loops land the identical
    unique/explored/verdict/depth."""
    kw = dict(chunk=128, frontier_cap=1 << 10, visited_cap=1 << 13,
              max_depth=4)
    packed = TensorSearch(
        dataclasses.replace(spec_fn(), goals={}), **kw).run()
    raw = TensorSearch(
        dataclasses.replace(spec_fn(), goals={}), packed=False,
        **kw).run()
    assert packed.end_condition == raw.end_condition
    assert packed.unique_states == raw.unique_states
    assert packed.states_explored == raw.states_explored
    assert packed.depth == raw.depth
    assert packed.bytes_per_state < packed.bytes_per_state_unpacked


def test_packed_checkpoint_resume_generated_paxos(tmp_path):
    """A packed checkpoint of the generated paxos spec (slot lanes
    stored PACKED) resumes to the exact straight-run counts."""
    path = str(tmp_path / "spec.ckpt.npz")
    proto = dataclasses.replace(make_paxos_protocol(), goals={})
    TensorSearch(proto, chunk=256, max_depth=2, checkpoint_path=path,
                 checkpoint_every=1).run()
    resumed = TensorSearch(proto, chunk=256, max_depth=3,
                           checkpoint_path=path,
                           checkpoint_every=1).run()
    straight = TensorSearch(proto, chunk=256, max_depth=3).run()
    assert resumed.unique_states == straight.unique_states
    assert resumed.depth == straight.depth


def test_mesh_wire_packed_parity_generated_join():
    """The packed mesh wire moves generated-spec slot lanes bit-exactly:
    width-2 sharded runs with the wire codec ON vs OFF (the parity
    oracle) agree on every count."""
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    def run(mesh_pack):
        proto = dataclasses.replace(make_join_protocol(2), goals={})
        return ShardedTensorSearch(
            proto, make_mesh(2), chunk_per_device=16,
            frontier_cap=1 << 8, visited_cap=1 << 10,
            row_exchange=True, mesh_pack=mesh_pack).run()

    on, off = run(True), run(False)
    assert on.end_condition == off.end_condition
    assert on.unique_states == off.unique_states
    assert on.states_explored == off.states_explored
    assert on.depth == off.depth


# ------------------------------------------------ bytes-per-state

@pytest.mark.parametrize("spec_fn", [
    make_paxos_protocol,
    lambda: make_join_protocol(1),
    lambda: make_shardstore_protocol([1, 1]),
    lambda: make_shardstore_tx_protocol(1),
])
def test_bytes_per_state_floor_generated_labs(spec_fn):
    """ACCEPTANCE: the spec-declared Field/Slots domains buy >= 2x
    smaller packed bytes-per-state on every generated lab3/lab4 spec
    (the hand twins declared nothing and derived identity)."""
    eng = TensorSearch(dataclasses.replace(spec_fn(), goals={}),
                       chunk=64)
    pk = eng._pk
    assert pk is not None and not pk.identity
    assert pk.pack_ratio >= 2.0, pk.descriptor()


@pytest.mark.slow
def test_bytes_per_state_floor_generated_multi():
    eng = TensorSearch(dataclasses.replace(
        make_shardstore_multi_protocol(), goals={}), chunk=64)
    pk = eng._pk
    assert pk is not None and pk.pack_ratio >= 2.0, pk.descriptor()


# -------------------------------- fault scenarios on generated twins

def _fault_pruned(proto):
    """Goals off (count the full bounded-depth space), reach goals kept
    as prunes, invariants live — the scenario-count discipline of
    tests/test_scenarios.py."""
    return dataclasses.replace(proto, goals={}, prunes=dict(proto.goals),
                               invariants=dict(proto.invariants))


def test_partition_on_generated_paxos_pinned_counts():
    """ISSUE 20 + ISSUE 19 composed: a Partition fault model declared
    ON THE GENERATED lab3 paxos spec (majority side {s0, s1} vs {s2})
    explores a pinned bounded-depth space — fault events included."""
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_partition_spec

    for depth, (unique, explored, pev) in {
            2: (32, 64, 7), 3: (133, 328, 31)}.items():
        proto = _fault_pruned(make_paxos_partition_spec(3).compile())
        out = TensorSearch(proto, chunk=256, max_depth=depth).run()
        assert out.end_condition == "DEPTH_EXHAUSTED"
        assert out.unique_states == unique
        assert out.states_explored == explored
        assert out.partition_events == pev
        assert out.fault_events == pev


def test_partition_witness_on_generated_paxos_names_fault_events():
    """A deliberately-falsifiable invariant (NO_HEAL: the cut never
    heals) yields a witness whose decoded trace NAMES the fault
    events — CUT then HEAL — on the generated spec."""
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_partition_spec
    from dslabs_tpu.tpu.trace import decode_trace

    spec = make_paxos_partition_spec(3)
    spec.invariants["NO_HEAL"] = lambda v: ~(
        (v.get("$fault", 0, "pcut") == 0)
        & (v.get("$fault", 0, "eras") == 1))
    proto = dataclasses.replace(spec.compile(), goals={})
    search = TensorSearch(proto, chunk=256, record_trace=True,
                          max_depth=6)
    out = search.run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.predicate_name == "NO_HEAL"
    assert out.depth == 2
    labels = [a[0] for k, a in decode_trace(search, out)
              if k == "fault"]
    assert labels == ["CUT", "HEAL"]


def test_crash_on_generated_shardstore_pinned_counts():
    """Crash-recovery (durable samo, volatile everything else) on the
    GENERATED lab4 part-1 shardstore spec: pinned bounded-depth
    exhaustive counts, crash events included."""
    from dslabs_tpu.tpu.specs_lab4 import make_shardstore_crash_spec

    for depth, (unique, explored, cev) in {
            2: (30, 43, 7), 3: (103, 200, 29)}.items():
        proto = _fault_pruned(
            make_shardstore_crash_spec([1, 1]).compile())
        out = TensorSearch(proto, chunk=256, max_depth=depth).run()
        assert out.end_condition == "DEPTH_EXHAUSTED"
        assert out.unique_states == unique
        assert out.states_explored == explored
        assert out.crash_events == cev
        assert out.fault_events == cev


def test_crash_witness_on_generated_shardstore_names_fault_event():
    """NO_CRASH (no server ever crashes) is falsified in one step; the
    decoded witness names which instance went down."""
    from dslabs_tpu.tpu.specs_lab4 import make_shardstore_crash_spec
    from dslabs_tpu.tpu.trace import decode_trace

    spec = make_shardstore_crash_spec([1, 1])
    spec.invariants["NO_CRASH"] = \
        lambda v: v.get("$fault", 0, "crashes") == 0
    proto = dataclasses.replace(spec.compile(), goals={})
    search = TensorSearch(proto, chunk=256, record_trace=True,
                          max_depth=4)
    out = search.run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.predicate_name == "NO_CRASH"
    assert out.depth == 1
    labels = [a[0] for k, a in decode_trace(search, out)
              if k == "fault"]
    assert labels == ["CRASH(server[0])"]
