"""Verdict-parity sweep (SURVEY §8.2 step 6): ONE registry enumerating a
search configuration per tensor twin x verdict class, each run on BOTH
backends — the object-graph checker (the oracle) and the TPU tensor
engine — with end conditions diffed, not hand-picked pairwise tests.

Every entry returns (object EndCondition, object discovered count) and
(tensor end_condition, tensor unique count); the sweep asserts the
verdicts agree under the shared mapping and, for exhaustion/depth-limit
entries (order-independent), that the state counts match exactly.

The per-lab parity tests (test_tpu_engine / test_tpu_lab4 /
test_tpu_sharded) probe these pairings more deeply; this file is the
breadth guarantee the round-2 verdict asked for: every search-capable
twin's verdict is diffed in CI, in one place.
"""

import dataclasses

import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.tpu.engine import TensorSearch

# Object EndCondition <-> tensor end-condition string.  The object
# checker treats the depth limit as a prune (Search.java:222-229), so a
# depth-limited object run ends SPACE_EXHAUSTED where the tensor engine
# reports DEPTH_EXHAUSTED — both map to "exhausted".
VERDICT = {
    EndCondition.GOAL_FOUND: "GOAL_FOUND",
    EndCondition.SPACE_EXHAUSTED: "SPACE_EXHAUSTED",
    EndCondition.INVARIANT_VIOLATED: "INVARIANT_VIOLATED",
    EndCondition.EXCEPTION_THROWN: "EXCEPTION_THROWN",
}


def _never_done(p):
    """Invariant that must be violated once the workload completes —
    turns any goal-reaching twin config into an INVARIANT_VIOLATED
    probe."""
    done = p.goals["CLIENTS_DONE"]
    return dataclasses.replace(
        p, goals={}, invariants={**p.invariants,
                                 "NEVER_DONE": lambda s, f=done: ~f(s)})


# ---- registry: name -> (object_runner, tensor_runner, count_exact)
# object_runner() -> SearchResults; tensor_runner() -> SearchOutcome.


def _pingpong_goal():
    import tests.test_tpu_engine as te
    return te.object_search(2), te.tensor_search(2), False


def _pingpong_exhaust():
    import tests.test_tpu_engine as te
    return (te.object_search(2, prune_done=True),
            te.tensor_search(2, prune_done=True), True)


def _pingpong_violation():
    import tests.test_tpu_engine as te
    from dslabs_tpu.search.search import bfs
    from dslabs_tpu.search.search_state import SearchState
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.predicates import CLIENTS_DONE
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    # Object side rebuilt with the NEVER_DONE invariant.
    from dslabs_tpu.core.address import LocalAddress
    from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingClient,
                                                   PingServer, Pong)
    from dslabs_tpu.testing.generator import NodeGenerator
    from dslabs_tpu.testing.workload import Workload

    def parser(c, r):
        return Ping(c), (Pong(r) if r is not None else None)

    gen = NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, te.SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=["hi-1"], result_strings=["hi-1"],
            parser=parser))
    state = SearchState(gen)
    state.add_server(te.SERVER)
    state.add_client_worker(LocalAddress("client1"))
    settings = SearchSettings().add_invariant(CLIENTS_DONE.negate())
    settings.max_time(60)
    obj = bfs(state, settings)
    ten = TensorSearch(_never_done(make_pingpong_protocol(1)),
                       chunk=256).run()
    return obj, ten, False


def _clientserver_exhaust():
    import tests.test_tpu_engine as te
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    obj = te._clientserver_object_search(1, 1, prune_done=True)
    p = make_clientserver_protocol(n_clients=1, w=1)
    p = dataclasses.replace(p, goals={},
                            prunes={"DONE": p.goals["CLIENTS_DONE"]})
    return obj, TensorSearch(p, chunk=256).run(), True


def _clientserver_violation():
    import tests.test_tpu_engine as te
    from dslabs_tpu.search.search import bfs  # noqa: F401
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    # Object oracle: same workload, NEVER_DONE invariant.
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.search.search import BFS
    from dslabs_tpu.testing.predicates import CLIENTS_DONE
    import tests.test_tpu_trace as tt

    state = tt._object_initial(1, 1)
    settings = SearchSettings().add_invariant(CLIENTS_DONE.negate())
    settings.max_time(120)
    obj = BFS(settings).run(state)
    ten = TensorSearch(
        _never_done(make_clientserver_protocol(n_clients=1, w=1)),
        chunk=256).run()
    return obj, ten, False


def _pb_depth():
    import tests.test_tpu_engine as te
    from dslabs_tpu.tpu.protocols.primarybackup import make_pb_protocol

    obj = te._pb_object_search(2, 1, 1, 3)
    ten = TensorSearch(make_pb_protocol(ns=2, n_clients=1, w=1),
                       chunk=256, max_depth=3).run()
    return obj, ten, True


def _paxos_depth():
    from dslabs_tpu.core.address import LocalAddress
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    from dslabs_tpu.labs.clientserver.kvstore import KVStore
    from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer
    from dslabs_tpu.search.search import BFS
    from dslabs_tpu.search.search_state import SearchState
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.testing.generator import NodeGenerator
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol

    servers = tuple(LocalAddress(f"server{i}") for i in range(1, 4))
    gen = NodeGenerator(
        server_supplier=lambda a: PaxosServer(a, servers, KVStore()),
        client_supplier=lambda a: PaxosClient(a, servers),
        workload_supplier=lambda a: None)
    st = SearchState(gen)
    for a in servers:
        st.add_server(a)
    st.add_client_worker(LocalAddress("client0"),
                         kv_workload(["PUT:key-0:v1"], ["PutOk"]))
    settings = SearchSettings()
    settings.set_max_depth(3).max_time(300)
    obj = BFS(settings).run(st)
    ten = TensorSearch(make_paxos_protocol(n=3, n_clients=1, w=1,
                                           max_slots=2, net_cap=48,
                                           timer_cap=6),
                       chunk=256, max_depth=3).run()
    return obj, ten, True


def _shardstore_depth():
    import tests.test_tpu_lab4 as tl
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_protocol

    obj = tl._object_joined(3)
    ten = TensorSearch(make_shardstore_protocol([1, 1]), chunk=256,
                       max_depth=3).run()
    return obj, ten, True


def _shardstore_tx_depth():
    import tests.test_tpu_lab4 as tl
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_tx_protocol

    obj = tl._object_tx_joined(3)
    ten = TensorSearch(make_shardstore_tx_protocol(n_tx=1), chunk=256,
                       max_depth=3).run()
    return obj, ten, True


def _pingpong_gen_exhaust():
    """The schema-compiled lab0 twin (tpu/specs.py) against the object
    oracle — the compiler's generated twin runs alongside the
    hand-written entries (SURVEY §8.1 Protocol IR first cut)."""
    import tests.test_tpu_engine as te
    from dslabs_tpu.tpu.specs import pingpong_spec

    obj = te.object_search(2, prune_done=True)
    p = pingpong_spec(2).compile()
    p = dataclasses.replace(p, goals={},
                            prunes={"DONE": p.goals["CLIENTS_DONE"]})
    return obj, TensorSearch(p, chunk=256).run(), True


def _clientserver_gen_exhaust():
    import tests.test_tpu_engine as te
    from dslabs_tpu.tpu.specs import clientserver_spec

    obj = te._clientserver_object_search(1, 1, prune_done=True)
    p = clientserver_spec(n_clients=1, w=1).compile()
    p = dataclasses.replace(p, goals={},
                            prunes={"DONE": p.goals["CLIENTS_DONE"]})
    return obj, TensorSearch(p, chunk=256).run(), True


REGISTRY = {
    "lab0-pingpong-gen-exhaust": _pingpong_gen_exhaust,
    "lab1-clientserver-gen-exhaust": _clientserver_gen_exhaust,
    "lab0-pingpong-goal": _pingpong_goal,
    "lab0-pingpong-exhaust": _pingpong_exhaust,
    "lab0-pingpong-violation": _pingpong_violation,
    "lab1-clientserver-exhaust": _clientserver_exhaust,
    "lab1-clientserver-violation": _clientserver_violation,
    "lab2-pb-depth": _pb_depth,
    "lab3-paxos-depth": _paxos_depth,
    "lab4-shardstore-depth": _shardstore_depth,
    "lab4-shardstore-tx-depth": _shardstore_tx_depth,
}


@pytest.mark.parametrize("name", sorted(REGISTRY))
def test_verdict_parity(name):
    obj, ten, count_exact = REGISTRY[name]()
    expect = VERDICT.get(obj.end_condition)
    assert expect is not None, (
        f"{name}: object ended {obj.end_condition} (budget too small?)")
    # DEPTH_EXHAUSTED and SPACE_EXHAUSTED can legitimately interchange
    # when the depth limit coincides with exhaustion; everything else
    # must match exactly.
    if expect in ("DEPTH_EXHAUSTED", "SPACE_EXHAUSTED"):
        assert ten.end_condition in ("DEPTH_EXHAUSTED",
                                     "SPACE_EXHAUSTED"), (
            f"{name}: object {expect}, tensor {ten.end_condition}")
        assert ten.end_condition == expect or count_exact, name
    else:
        assert ten.end_condition == expect, (
            f"{name}: object {expect}, tensor {ten.end_condition}")
    if count_exact:
        assert ten.unique_states == obj.discovered_count, (
            f"{name}: object discovered {obj.discovered_count}, "
            f"tensor {ten.unique_states}")
