"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

The real TPU (single chip) is reserved for bench runs; tests exercise the
multi-chip sharding paths on virtual CPU devices per the project environment
contract.
"""

import os

# Hard override, not setdefault: the driver environment pins
# JAX_PLATFORMS to the real accelerator, but the test suite must run on
# the virtual CPU mesh (the accelerator is reserved for bench runs, and
# every jit would otherwise pay a multi-minute TPU compile).
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

# The env var alone is NOT enough on machines with the axon TPU plugin:
# its site hook re-pins jax_platforms to "axon,cpu" at interpreter start
# (AFTER the env is read), so default jits land on the real TPU even
# though jax.devices("cpu") shows the virtual mesh — measured round 3:
# the whole "CPU" suite was silently compiling on (and contending for)
# the accelerator.  Re-pin through the config, which wins over the
# plugin because conftest runs after site initialisation.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass  # older jax: XLA_FLAGS above already sets the count

# Persistent compile cache: XLA compiles dominate the suite's wall time
# (measured: 20 min cold, most of it building the same tensor-engine
# programs every run); cached re-runs skip straight to execution.
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache-cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
