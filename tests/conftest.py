"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

The real TPU (single chip) is reserved for bench runs; tests exercise the
multi-chip sharding paths on virtual CPU devices per the project environment
contract.
"""

import os

# Hard override, not setdefault: the driver environment pins
# JAX_PLATFORMS to the real accelerator, but the test suite must run on
# the virtual CPU mesh (the accelerator is reserved for bench runs, and
# every jit would otherwise pay a multi-minute TPU compile).
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
