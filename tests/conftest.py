"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

The real TPU (single chip) is reserved for bench runs; tests exercise the
multi-chip sharding paths on virtual CPU devices per the project environment
contract.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
