"""Owner-sharded multi-chip superstep (ISSUE 12): the fused in-superstep
row exchange, first-class carry placement, and the Pallas bucket-probe
kernel.

The fused exchange routes successor ROWS through the same owner-hashed
``all_to_all`` as their fingerprints, so fresh states land on their
owner's frontier shard as they are produced and the level promote
shrinks to a local buffer swap (no reverse fresh-flag exchange, no
boundary rebalance).  This suite is the acceptance matrix:

* exact unique/explored/verdict/depth parity between the fused-exchange
  superstep and the legacy promote-boundary driver
  (``DSLABS_SHARDED_SUPERSTEP=0`` / ``superstep=False``) at
  n_devices in {1, 2, 4, 8} on pingpong + lab1;
* per-level host dispatches stay within the PR 3 budget (<= 2/level)
  and the fused promote program carries ZERO collectives;
* Pallas-vs-jnp visited-table parity — bit-exact tables, insert flags,
  and the unresolved/overflow contract — standalone and through a full
  sharded search (``DSLABS_VISITED_PALLAS=interpret``);
* cross-width checkpoint resume 8 -> 4 -> 2 -> 1 stays exact on the new
  exchange path (owner re-hashing at each narrower width);
* the supervisor's transient-retry boundary covers the fused dispatch.

Marked ``mesh`` (``make mesh-smoke`` runs exactly this suite on the
CPU virtual 8-device mesh); the heavier combinations are additionally
``slow`` so tier-1 keeps only the cheap ones.
"""

import dataclasses
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dslabs_tpu.tpu import visited as visited_mod  # noqa: E402
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import (CARRY_PARTITION_RULES,  # noqa: E402
                                    ShardedTensorSearch, make_mesh,
                                    match_partition_rules)

pytestmark = pytest.mark.mesh

_COLLECTIVES = ("all-to-all", "all_to_all", "all-reduce", "all_reduce",
                "all-gather", "all_gather", "collective-permute",
                "collective_permute", "reduce-scatter", "reduce_scatter")


def _pruned_pingpong():
    pp = make_pingpong_protocol(workload_size=2)
    return dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})


def _pruned_lab1():
    cs = make_clientserver_protocol(n_clients=1, w=2)
    return dataclasses.replace(
        cs, goals={}, prunes={"CLIENTS_DONE": cs.goals["CLIENTS_DONE"]})


def _build(proto, n_devices, **kw):
    kw.setdefault("chunk_per_device", 16)
    kw.setdefault("frontier_cap", 1 << 8)
    kw.setdefault("visited_cap", 1 << 10)
    return ShardedTensorSearch(proto, make_mesh(n_devices), **kw)


def _assert_exact(a, b):
    assert a.end_condition == b.end_condition
    assert a.unique_states == b.unique_states
    assert a.states_explored == b.states_explored
    assert a.depth == b.depth
    assert a.dropped == b.dropped


# --------------------------------------------------- width-parity matrix

@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_width_parity_matrix_pingpong(n_devices):
    """Acceptance: the fused-exchange superstep matches the legacy
    promote-boundary driver EXACTLY at every mesh width."""
    proto = _pruned_pingpong()
    fused = _build(proto, n_devices, superstep=True,
                   row_exchange=True).run()
    legacy = _build(proto, n_devices, superstep=False).run()
    assert fused.end_condition == "SPACE_EXHAUSTED"
    _assert_exact(fused, legacy)


@pytest.mark.parametrize("n_devices", [1, 8])
def test_width_parity_matrix_lab1(n_devices):
    proto = _pruned_lab1()
    fused = _build(proto, n_devices, superstep=True,
                   row_exchange=True).run()
    legacy = _build(proto, n_devices, superstep=False).run()
    assert fused.end_condition == "SPACE_EXHAUSTED"
    _assert_exact(fused, legacy)


@pytest.mark.slow
@pytest.mark.parametrize("n_devices", [2, 4])
def test_width_parity_matrix_lab1_mid_widths(n_devices):
    proto = _pruned_lab1()
    fused = _build(proto, n_devices, superstep=True,
                   row_exchange=True).run()
    legacy = _build(proto, n_devices, superstep=False).run()
    _assert_exact(fused, legacy)


@pytest.mark.slow
def test_width_parity_strict_vs_beam():
    """The exchange is verdict-preserving in BOTH capacity modes."""
    proto = _pruned_pingpong()
    for strict in (True, False):
        fused = _build(proto, 8, superstep=True, row_exchange=True,
                       strict=strict).run()
        legacy = _build(proto, 8, superstep=False, strict=strict).run()
        _assert_exact(fused, legacy)


def test_row_exchange_vs_legacy_exchange_superstep():
    """Both superstep exchanges (fused rows vs promote-boundary) agree
    — the DSLABS_SHARDED_EXCHANGE=0 escape hatch is a real oracle."""
    proto = _pruned_pingpong()
    fused = _build(proto, 8, superstep=True, row_exchange=True).run()
    boundary = _build(proto, 8, superstep=True,
                      row_exchange=False).run()
    _assert_exact(fused, boundary)


def test_row_exchange_knob_and_legacy_driver_forcing():
    """The knob wiring: DSLABS_SHARDED_EXCHANGE gates the default, the
    legacy per-chunk driver always keeps the promote-boundary
    exchange (it IS the oracle)."""
    proto = _pruned_pingpong()
    assert _build(proto, 2).row_exchange is True        # default ON
    assert _build(proto, 2, superstep=False).row_exchange is False
    os.environ["DSLABS_SHARDED_EXCHANGE"] = "0"
    try:
        assert _build(proto, 2).row_exchange is False
    finally:
        del os.environ["DSLABS_SHARDED_EXCHANGE"]
    assert _build(proto, 2, row_exchange=True).row_exchange is True


# ---------------------------------------------- dispatch budget + promote

def test_fused_exchange_dispatch_budget():
    """The dispatch-counter pin (PR 3 budget): the fused-exchange level
    spends <= 2 host dispatches (superstep + thin promote), and the
    promote program moves ZERO rows over ICI — its lowering contains
    no collective at width 8."""
    proto = _pruned_pingpong()
    search = _build(proto, 8, superstep=True, row_exchange=True)
    counts = {}

    def hook(tag, fn, *args):
        counts[tag] = counts.get(tag, 0) + 1
        return fn(*args)

    search._dispatch_hook = hook
    out = search.run()
    assert out.depth >= 3
    assert counts.get("sharded.step", 0) == 0
    assert counts.get("sharded.sync", 0) == 0
    assert (counts["sharded.superstep"] + counts["sharded.promote"]
            <= 2 * out.depth)

    text = search._finish_level.lower(search._carry_sds()).as_text()
    assert not any(c in text for c in _COLLECTIVES), (
        "fused-exchange promote must be a local buffer swap")
    # ... while the legacy promote at the same width IS the rebalance.
    legacy = _build(proto, 8, superstep=True, row_exchange=False)
    text = legacy._finish_level.lower(legacy._carry_sds()).as_text()
    assert any(c in text for c in _COLLECTIVES)


# --------------------------------------------------- carry placement (b)

def test_partition_rules_cover_every_carry_leaf():
    """Every carry leaf (base + trace + spill variants) resolves
    through CARRY_PARTITION_RULES; an undeclared leaf is loud."""
    names = ["cur", "cur_n", "j", "evp", "noapp", "nxt", "nxt_n",
             "visited", "vis_n", "explored", "overflow", "vis_over",
             "drops", "flag_cnt", "flag_rows", "tmeta", "flag_meta",
             "f_full"]
    specs = match_partition_rules(CARRY_PARTITION_RULES, names,
                                  "search")
    assert set(specs) == set(names)
    from jax.sharding import PartitionSpec as P
    assert specs["cur"] == P("search")
    assert specs["visited"] == P("search")
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(CARRY_PARTITION_RULES, ["mystery"],
                              "search")


def test_carry_placement_is_first_class():
    """The rule-derived NamedShardings feed every placement consumer:
    shard_map specs, the init program's outputs, and the AOT
    ShapeDtypeStructs agree leaf for leaf — and survive a width
    change (the elastic-ladder contract)."""
    from jax.sharding import NamedSharding

    proto = _pruned_pingpong()
    for width in (8, 2):
        search = _build(proto, width)
        shards = search._carry_shardings()
        specs = search._carry_specs()
        sds = search._carry_sds()
        assert set(shards) == set(specs) == set(sds)
        for k, s in shards.items():
            assert isinstance(s, NamedSharding)
            assert s.spec == specs[k]
            assert sds[k].sharding == s
        carry = search._init_carry(search.initial_state())
        for k, v in carry.items():
            assert v.sharding.is_equivalent_to(shards[k], v.ndim), k


# ------------------------------------------------ Pallas bucket kernel (c)

def _key_batch(n=300, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 2 ** 32, size=(n, 4), dtype=np.uint32)
    keys[50:60] = keys[0:10]            # in-batch duplicates
    keys[99] = np.uint32(0xFFFFFFFF)    # the all-MAX collider
    valid = rng.random(n) > 0.2
    return jnp.asarray(keys), jnp.asarray(valid)


def test_pallas_vs_jnp_insert_bitexact():
    """The kernel body is the SAME traced algorithm as the jnp oracle:
    tables, insert flags, and unresolved flags are bit-identical."""
    keys, valid = _key_batch()
    table = visited_mod.empty_table(1 << 9)
    tj, ij, uj = visited_mod.insert_jnp(table, keys, valid)
    tp, ip, up = visited_mod.pallas_insert(table, keys, valid,
                                           interpret=True)
    assert (np.asarray(tj) == np.asarray(tp)).all()
    assert (np.asarray(ij) == np.asarray(ip)).all()
    assert (np.asarray(uj) == np.asarray(up)).all()


def test_pallas_overflow_contract_parity():
    """Table-full overflow (ISSUE 1 contract): the unresolved set — the
    keys a strict driver raises CapacityOverflow on — is identical
    between the kernel and the oracle on a saturated table."""
    keys, valid = _key_batch()
    tiny = visited_mod.empty_table(visited_mod.BKT * 2)
    tj, ij, uj = visited_mod.insert_jnp(tiny, keys, valid)
    tp, ip, up = visited_mod.pallas_insert(tiny, keys, valid,
                                           interpret=True)
    assert int(np.asarray(uj).sum()) > 0        # genuinely overflowed
    assert (np.asarray(uj) == np.asarray(up)).all()
    assert (np.asarray(ij) == np.asarray(ip)).all()
    assert (np.asarray(tj) == np.asarray(tp)).all()


def test_pallas_mode_knob():
    os.environ["DSLABS_VISITED_PALLAS"] = "0"
    try:
        assert visited_mod.pallas_mode() == "off"
        assert visited_mod._pallas_interpret(1 << 10) is None
    finally:
        os.environ["DSLABS_VISITED_PALLAS"] = "interpret"
    try:
        assert visited_mod.pallas_mode() == "interpret"
        assert visited_mod._pallas_interpret(1 << 30) is True
    finally:
        del os.environ["DSLABS_VISITED_PALLAS"]
    # auto on CPU: the jnp oracle (no Mosaic backend to win on).
    assert visited_mod.pallas_mode() == "auto"
    assert visited_mod._pallas_interpret(1 << 10) is None


def test_pallas_engine_parity(monkeypatch):
    """A full fused-exchange search with the table probe forced through
    the Pallas interpreter matches the jnp-path run exactly — the
    CapacityOverflow/visited_overflow contract is unchanged."""
    proto = _pruned_pingpong()
    base = _build(proto, 2, superstep=True, row_exchange=True).run()
    monkeypatch.setenv("DSLABS_VISITED_PALLAS", "interpret")
    out = _build(proto, 2, superstep=True, row_exchange=True).run()
    _assert_exact(out, base)


def test_pallas_site_registered_and_clean():
    """The bucket kernel is a canonical dispatch site: registered in
    telemetry.DISPATCH_SITES (hot -> profiler selection), present in
    both engines' site maps, and its lowering audits clean."""
    from dslabs_tpu.analysis.jaxpr_audit import audit_sites
    from dslabs_tpu.tpu.telemetry import (DISPATCH_SITES,
                                          _PROFILE_SITES)

    assert "visited.insert" in DISPATCH_SITES
    assert DISPATCH_SITES["visited.insert"]["hot"]
    assert "insert" in _PROFILE_SITES
    proto = _pruned_pingpong()
    search = _build(proto, 2)
    sites = search.dispatch_site_programs()
    assert "visited.insert" in sites
    findings = audit_sites(
        {"visited.insert": sites["visited.insert"]},
        "ShardedTensorSearch")
    assert findings == []


# ------------------------------------------------- cross-width resilience

def test_cross_width_resume_8_4_2_1(tmp_path):
    """Satellite: a fused-exchange checkpoint re-shards exactly onto
    every narrower width (owner re-hash at the new D) — the elastic
    ladder's resume contract holds on the new exchange path."""
    proto = _pruned_pingpong()
    oracle = _build(proto, 8, row_exchange=True).run()
    assert oracle.end_condition == "SPACE_EXHAUSTED"

    path = str(tmp_path / "mesh.ckpt")
    out = _build(proto, 8, row_exchange=True, checkpoint_path=path,
                 checkpoint_every=1, max_depth=2).run()
    assert out.end_condition == "DEPTH_EXHAUSTED"
    for width, depth in ((4, 3), (2, 4), (1, None)):
        search = _build(proto, width, row_exchange=True,
                        checkpoint_path=path, checkpoint_every=1,
                        max_depth=depth)
        out = search.run(resume=True)
    assert out.end_condition == oracle.end_condition
    assert out.unique_states == oracle.unique_states
    assert out.states_explored == oracle.states_explored
    assert out.depth == oracle.depth


def test_fused_exchange_transient_retry():
    """The supervisor's retry boundary covers the fused dispatch: a
    transient raise inside a superstep retries in place with an
    identical verdict (fault site = sharded.superstep, the fused
    exchange's dispatch tag in DISPATCH_SITES)."""
    from dslabs_tpu.tpu.supervisor import (FaultPlan, RetryPolicy,
                                           SearchSupervisor)

    proto = _pruned_pingpong()

    def sup(**kw):
        return SearchSupervisor(
            proto, mesh=make_mesh(8), chunk=16, frontier_cap=1 << 8,
            visited_cap=1 << 10, row_exchange=True, **kw)

    base = sup().run()
    assert base.end_condition == "SPACE_EXHAUSTED"
    out = sup(fault_plan=FaultPlan().raise_at(2, count=2),
              policy=RetryPolicy(max_retries=3,
                                 backoff_base=0.001)).run()
    assert out.end_condition == base.end_condition
    assert out.unique_states == base.unique_states
    assert out.states_explored == base.states_explored
    assert out.retries == 2
    assert out.failovers == 0


# ------------------------------------------------------- bench mesh phase

@pytest.mark.slow
def test_bench_mesh_phase_schema():
    """The bench's --mesh phase (the new headline): last-line JSON
    carries mesh_width, finite skew, per-level per-device lanes, and
    clean recovery counters on the CPU virtual 8-device mesh."""
    import json
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DSLABS_BENCH_PROTOCOL", None)
    proc = subprocess.run(
        [sys.executable, "bench.py", "--mesh", "90"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    line = proc.stdout.strip().splitlines()[-1]
    phase = json.loads(line)
    assert phase["mesh_width"] == 8
    assert phase["virtual_cpu_mesh"] is True
    assert phase["value"] > 0
    assert phase["unique"] > 0
    sk = phase["skew"]
    assert np.isfinite(sk["imbalance_max"])
    assert sk["imbalance_max"] >= 1.0
    assert phase["mesh_shrinks"] == 0
    assert phase["knob_retries"] == 0
    levels = phase["levels"]
    assert levels and "per_device" in levels[-1]
    assert len(levels[-1]["per_device"]["explored"]) == 8
