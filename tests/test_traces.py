"""Saved-trace persistence + replay (reference: SerializableTrace.java,
CheckSavedTracesTest.java) and human-readable causal reordering."""

import os

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.pingpong.pingpong import Ping, PingClient, PingServer, Pong
from dslabs_tpu.search.replay import replay_trace
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.search.trace import (SerializableTrace, human_readable_trace,
                                     save_trace)
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import NONE_DECIDED, RESULTS_OK
from dslabs_tpu.testing.workload import Workload

SERVER = LocalAddress("pingserver")


def ping_parser(cmd, res):
    return Ping(cmd), (Pong(res) if res is not None else None)


def make_generator():
    return NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=["p1", "p2"], result_strings=["p1", "p2"],
            parser=ping_parser))


def violating_state():
    state = SearchState(make_generator())
    state.add_server(SERVER)
    state.add_client_worker(LocalAddress("client1"))
    settings = SearchSettings().add_invariant(NONE_DECIDED)
    settings.max_time(15)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.INVARIANT_VIOLATED
    return results.invariant_violating_state


def test_save_and_replay_trace(tmp_path):
    end = violating_state()
    path = save_trace(end, [NONE_DECIDED], "0", None, "PingTest",
                      "test_viol", directory=str(tmp_path))
    assert os.path.exists(path)

    loaded = SerializableTrace.load(path)
    assert loaded is not None
    assert len(loaded.history) == len(end.trace()) - 1

    # Replaying the trace with the violated invariant re-finds the violation.
    settings = SearchSettings().add_invariant(NONE_DECIDED)
    results = replay_trace(loaded.initial_state(), loaded.history, settings)
    assert results.end_condition == EndCondition.INVARIANT_VIOLATED

    # Replaying with a holding invariant completes exhausted.
    settings2 = SearchSettings().add_invariant(RESULTS_OK)
    results2 = replay_trace(loaded.initial_state(), loaded.history, settings2)
    assert results2.end_condition == EndCondition.SPACE_EXHAUSTED


def test_stale_trace_skipped(tmp_path):
    bad = tmp_path / "lab0_garbage.trace"
    bad.write_bytes(b"not a pickle")
    assert SerializableTrace.load(str(bad)) is None
    assert SerializableTrace.traces(str(tmp_path)) == []


def test_human_readable_trace_reaches_same_verdict():
    end = violating_state()
    hr = human_readable_trace(end)
    assert hr[0].previous is None
    # End state of the re-ordered trace still violates the predicate.
    r = NONE_DECIDED.check(hr[-1])
    assert not r.value
    # Events are causally ordered: every message delivery happens after its
    # send (checked implicitly by successful replay inside the reordering).
    assert len(hr) <= len(end.trace())
