"""Saved-trace persistence + replay (reference: SerializableTrace.java,
CheckSavedTracesTest.java) and human-readable causal reordering."""

import os

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.pingpong.pingpong import Ping, PingClient, PingServer, Pong
from dslabs_tpu.search.replay import replay_trace
from dslabs_tpu.search.results import EndCondition
from dslabs_tpu.search.search import bfs
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.search.trace import (SerializableTrace, human_readable_trace,
                                     save_trace)
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import NONE_DECIDED, RESULTS_OK
from dslabs_tpu.testing.workload import Workload

SERVER = LocalAddress("pingserver")


def ping_parser(cmd, res):
    return Ping(cmd), (Pong(res) if res is not None else None)


def make_generator():
    return NodeGenerator(
        server_supplier=lambda a: PingServer(a),
        client_supplier=lambda a: PingClient(a, SERVER),
        workload_supplier=lambda a: Workload(
            command_strings=["p1", "p2"], result_strings=["p1", "p2"],
            parser=ping_parser))


def violating_state():
    state = SearchState(make_generator())
    state.add_server(SERVER)
    state.add_client_worker(LocalAddress("client1"))
    settings = SearchSettings().add_invariant(NONE_DECIDED)
    settings.max_time(15)
    results = bfs(state, settings)
    assert results.end_condition == EndCondition.INVARIANT_VIOLATED
    return results.invariant_violating_state


def test_save_and_replay_trace(tmp_path):
    end = violating_state()
    path = save_trace(end, [NONE_DECIDED], "0", None, "PingTest",
                      "test_viol", directory=str(tmp_path))
    assert os.path.exists(path)

    loaded = SerializableTrace.load(path)
    assert loaded is not None
    assert len(loaded.history) == len(end.trace()) - 1

    # Replaying the trace with the violated invariant re-finds the violation.
    settings = SearchSettings().add_invariant(NONE_DECIDED)
    results = replay_trace(loaded.initial_state(), loaded.history, settings)
    assert results.end_condition == EndCondition.INVARIANT_VIOLATED

    # Replaying with a holding invariant completes exhausted.
    settings2 = SearchSettings().add_invariant(RESULTS_OK)
    results2 = replay_trace(loaded.initial_state(), loaded.history, settings2)
    assert results2.end_condition == EndCondition.SPACE_EXHAUSTED


def test_stale_trace_skipped(tmp_path):
    bad = tmp_path / "lab0_garbage.trace"
    bad.write_bytes(b"not a pickle")
    assert SerializableTrace.load(str(bad)) is None
    assert SerializableTrace.traces(str(tmp_path)) == []


def test_human_readable_trace_reaches_same_verdict():
    end = violating_state()
    hr = human_readable_trace(end)
    assert hr[0].previous is None
    # End state of the re-ordered trace still violates the predicate.
    r = NONE_DECIDED.check(hr[-1])
    assert not r.value
    # Events are causally ordered: every message delivery happens after its
    # send (checked implicitly by successful replay inside the reordering).
    assert len(hr) <= len(end.trace())


def test_saved_traces_directory_sweep(tmp_path):
    """CheckSavedTracesTest analog (CheckSavedTracesTest.java:44-108): every
    trace in a directory is re-checked as its own case, stale files are
    skipped with a warning rather than failing the sweep."""
    end = violating_state()
    save_trace(end, [NONE_DECIDED], "0", None, "PingTest", "t1",
               directory=str(tmp_path))
    save_trace(end, [NONE_DECIDED], "0", 1, "PingTest", "t2",
               directory=str(tmp_path))
    # A stale/corrupt trace file must be skipped, not crash the sweep.
    (tmp_path / "lab9_corrupt.trace").write_bytes(b"not a pickle")

    traces = SerializableTrace.traces(str(tmp_path))
    assert len(traces) == 2
    for t in traces:
        settings = SearchSettings()
        for inv in t.invariants:
            settings.add_invariant(inv)
        results = replay_trace(t.initial_state(), t.history, settings)
        assert results.end_condition == EndCondition.INVARIANT_VIOLATED


def test_clone_conformance_checks_route_to_check_logger():
    """Cloning.java:130-138 analog: under do_error_checks every clone is
    verified equal + hash-consistent; violations land in the CheckLogger."""
    from dslabs_tpu.utils.check_logger import CheckLogger
    from dslabs_tpu.utils.flags import GlobalSettings
    from dslabs_tpu.utils.structural import clone

    class IdentityEq:
        """Broken: equality by identity, so a clone is never equal."""

        def __eq__(self, other):
            return self is other

        def __hash__(self):
            return id(self)

    CheckLogger.clear()
    saved = GlobalSettings.error_checks_temporarily_enabled
    GlobalSettings.error_checks_temporarily_enabled = True
    try:
        good = clone({"k": [1, 2, 3]})
        assert good == {"k": [1, 2, 3]}
        clone(IdentityEq())
        kinds = {k for (k, _loc) in CheckLogger.findings}
        assert "CLONE_NOT_EQUAL" in kinds
    finally:
        GlobalSettings.error_checks_temporarily_enabled = saved
        CheckLogger.clear()
