"""bench.py contract smoke tests: whatever happens — wedged runtime,
exhausted deadline, healthy run — the bench must exit 0 with exactly one
parseable JSON line on stdout (round-4's BENCH_r04.json was rc=124 with
an empty tail; the round-5 rework makes that shape impossible)."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(ROOT, "bench.py")


def _run(env_extra, timeout):
    env = dict(os.environ, DSLABS_FORCE_CPU="1", **env_extra)
    # The bench manages its own platform pinning; drop the test
    # harness's CPU-mesh flags so the child sees a clean slate.
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in out, out
    return out


def test_bench_exhausted_deadline_still_emits_json():
    """With a deadline too small for any phase, the bench must skip
    phases (never race an external killer) and still land the JSON
    line with an attributable error."""
    out = _run({"DSLABS_BENCH_DEADLINE_SECS": "1"}, timeout=240)
    assert out["value"] == 0.0
    assert "error" in out


@pytest.mark.skipif(not os.environ.get("DSLABS_SLOW_TESTS"),
                    reason="runs the full cpu-fallback before/after pair")
def test_bench_wedged_tpu_lands_cpu_fallback_rate():
    """A wedged TPU preflight (simulated via DSLABS_BENCH_FAKE_WEDGE)
    must still land a REAL nonzero states/min number tagged
    cpu-fallback — never the 0.0 of BENCH_r04/r05 — plus the legacy
    host-loop rate as the comparable before/after pair."""
    out = _run({"DSLABS_BENCH_FAKE_WEDGE": "1",
                "DSLABS_BENCH_DEADLINE_SECS": "400"}, timeout=450)
    assert out["backend"] == "cpu-fallback"
    assert out["value"] > 0, out
    assert "error" in out           # the wedge stays attributable
    fb = out["cpu_fallback"]
    # The pair ran the identical search: count parity is the device
    # loop's correctness witness riding along with the rate.
    assert fb["legacy"]["unique"] == fb["unique"]
    assert fb["legacy"]["explored"] == fb["explored"]
    assert fb["speedup_vs_legacy"] > 0


@pytest.mark.skipif(not os.environ.get("DSLABS_SLOW_TESTS"),
                    reason="runs a real (small) CPU beam rung")
def test_bench_cpu_smoke_lands_a_rate():
    """The healthy-path contract on the CPU backend: preflight, one
    beam rung, a nonzero rate, compile_secs reported."""
    out = _run({"DSLABS_BENCH_DEADLINE_SECS": "400"}, timeout=450)
    assert out["value"] > 0, out
    assert out["beam"]["dropped"] >= 0
    assert "compile_secs" in out["beam"]
