"""bench.py contract smoke tests: whatever happens — wedged runtime,
exhausted deadline, external kill, healthy run — the bench must exit 0
with exactly one parseable JSON line on stdout (round-4's BENCH_r04.json
was rc=124 with an empty tail; round 5 bounded the phases, and the
ISSUE-4 warden rework adds the guarantees for the two shapes that still
escaped: an external SIGTERM kill of the parent, and a preflight that
hangs SILENTLY and used to eat the CPU fallback's budget)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(ROOT, "bench.py")


def _run(env_extra, timeout):
    env = dict(os.environ, DSLABS_FORCE_CPU="1", **env_extra)
    # The bench manages its own platform pinning; drop the test
    # harness's CPU-mesh flags so the child sees a clean slate.
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in out, out
    return out


def test_bench_exhausted_deadline_still_emits_json():
    """With a deadline too small for any phase, the bench must skip
    phases (never race an external killer) and still land the JSON
    line with an attributable error."""
    out = _run({"DSLABS_BENCH_DEADLINE_SECS": "1"}, timeout=240)
    assert out["value"] == 0.0
    assert "error" in out


def test_bench_external_kill_still_emits_json():
    """ACCEPTANCE (the BENCH_r04 shape): an external ``timeout``-style
    SIGTERM mid-run must still produce rc=0 and a parsable last-line
    JSON naming the signal — never an empty tail."""
    env = dict(os.environ, DSLABS_FORCE_CPU="1",
               DSLABS_BENCH_DEADLINE_SECS="400")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env, cwd=ROOT)
    # Let the run get into its first phase, then kill like a driver
    # timeout would.
    t0 = time.time()
    for line in proc.stderr:
        if "phase preflight: start" in line or time.time() - t0 > 60:
            break
    time.sleep(1.0)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    assert proc.returncode == 0
    lines = [ln for ln in out.strip().splitlines() if ln]
    assert len(lines) == 1, out
    parsed = json.loads(lines[0])
    assert "error" in parsed and "SIGTERM" in parsed["error"], parsed
    assert "total_secs" in parsed


def test_bench_wedged_preflight_fast_kill_lands_fallback_value():
    """ACCEPTANCE (the BENCH_r05 shape): a preflight that hangs
    SILENTLY (DSLABS_BENCH_FAKE_WEDGE=hang) is SIGKILLed at the
    heartbeat-silence budget — seconds, not the 300 s that starved
    BENCH_r05 — and the CPU fallback still lands a REAL tagged
    states/min value, never 0.0."""
    out = _run({"DSLABS_BENCH_FAKE_WEDGE": "hang",
                "DSLABS_BENCH_PREFLIGHT_SILENCE_SECS": "8",
                "DSLABS_FALLBACK_DEPTH": "6",
                "DSLABS_BENCH_DEADLINE_SECS": "400"}, timeout=380)
    assert out["backend"] == "cpu-fallback"
    assert out["value"] > 0, out
    assert "error" in out and "wedged" in out["error"]
    # The kill must be silence-driven (fast), leaving the fallback its
    # full budget — the whole run fits well under the deadline.
    assert out["total_secs"] < 350, out


@pytest.mark.skipif(not os.environ.get("DSLABS_SLOW_TESTS"),
                    reason="runs the full cpu-fallback before/after pair")
def test_bench_wedged_tpu_lands_cpu_fallback_rate():
    """A wedged TPU preflight (simulated via DSLABS_BENCH_FAKE_WEDGE)
    must still land a REAL nonzero states/min number tagged
    cpu-fallback — never the 0.0 of BENCH_r04/r05 — plus the legacy
    host-loop rate as the comparable before/after pair."""
    out = _run({"DSLABS_BENCH_FAKE_WEDGE": "1",
                "DSLABS_BENCH_DEADLINE_SECS": "400"}, timeout=450)
    assert out["backend"] == "cpu-fallback"
    assert out["value"] > 0, out
    assert "error" in out           # the wedge stays attributable
    fb = out["cpu_fallback"]
    # The pair ran the identical search: count parity is the device
    # loop's correctness witness riding along with the rate.
    assert fb["legacy"]["unique"] == fb["unique"]
    assert fb["legacy"]["explored"] == fb["explored"]
    assert fb["speedup_vs_legacy"] > 0


@pytest.mark.skipif(not os.environ.get("DSLABS_SLOW_TESTS"),
                    reason="runs a real (small) CPU beam rung")
def test_bench_cpu_smoke_lands_a_rate():
    """The healthy-path contract on the CPU backend: preflight, one
    beam rung, a nonzero rate, compile_secs reported."""
    out = _run({"DSLABS_BENCH_DEADLINE_SECS": "400"}, timeout=450)
    assert out["value"] > 0, out
    assert out["beam"]["dropped"] >= 0
    assert "compile_secs" in out["beam"]
