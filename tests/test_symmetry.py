"""Symmetry reduction (ISSUE 15 leg (b), tpu/symmetry.py): canonical
ordering of indistinguishable node ids, opt-in and default OFF —

* default OFF: raw unique counts pinned (202 on the single-decree
  paxos spec, 3 symmetric acceptors) — no default behavior change;
* symmetry=True: the CANONICAL unique count is pinned (50), strictly
  smaller than raw, deterministic, and identical across the device
  loop, the host-dedup oracle, and the 2-device sharded owner-hash;
* verdict parity: goal found <=> goal found, violation found <=>
  violation found, exhaustion <=> exhaustion vs the unreduced run;
* the violation witness replays: the recorded event trace drives the
  tensor step from the root to a state that genuinely violates the
  invariant;
* canonicalize unit law: states that differ only by an acceptor
  permutation hash equal; packing composes (packed+symmetric ==
  unpacked+symmetric);
* guard rails: symmetry=True without declared groups is a loud
  ValueError; a symmetry-reduced checkpoint never silently resumes an
  unreduced search (config fingerprints differ).

Marked ``capacity2`` (``make capacity2-smoke``)."""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dslabs_tpu.tpu import checkpoint as ckpt_mod  # noqa: E402
from dslabs_tpu.tpu.engine import (TensorSearch,  # noqa: E402
                                   flatten_state)
from dslabs_tpu.tpu.sharded import (ShardedTensorSearch,  # noqa: E402
                                    make_mesh)
from dslabs_tpu.tpu.specs import paxos_spec  # noqa: E402

pytestmark = pytest.mark.capacity2

# Pinned counts for paxos_spec(3) exhaustive with the DECIDED goal
# pruned: the raw reachable set and its canonical quotient (orbit
# count under the 3! acceptor permutations).  Determinism of the
# canonical count is part of the contract (lex-min representative).
RAW_UNIQUE = 202
CANONICAL_UNIQUE = 50


def _pruned():
    p = paxos_spec(3).compile()
    return dataclasses.replace(p, goals={},
                               prunes={"D": p.goals["DECIDED"]})


def test_default_off_raw_count_pinned():
    out = TensorSearch(_pruned(), chunk=256, visited_cap=1 << 14).run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert out.unique_states == RAW_UNIQUE
    assert out.symmetry_perms == 0


def test_canonical_count_pinned_and_smaller():
    """ACCEPTANCE: canonical unique count pinned, strictly smaller
    than raw, verdict parity with the unreduced run."""
    out = TensorSearch(_pruned(), chunk=256, visited_cap=1 << 14,
                       symmetry=True).run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert out.unique_states == CANONICAL_UNIQUE < RAW_UNIQUE
    assert out.symmetry_perms == 6


def test_canonical_count_engine_agreement():
    """Device loop, host oracle, and the sharded owner-hash all land
    the same canonical count — symmetric twins dedup to ONE owner."""
    dev = TensorSearch(_pruned(), chunk=256, visited_cap=1 << 14,
                       symmetry=True).run()
    host = TensorSearch(_pruned(), chunk=256, visited_cap=1 << 14,
                        symmetry=True, use_host_visited=True).run()
    sh = ShardedTensorSearch(_pruned(), make_mesh(2),
                             chunk_per_device=64, frontier_cap=512,
                             visited_cap=1 << 14, symmetry=True).run()
    for out in (dev, host, sh):
        assert out.end_condition == "SPACE_EXHAUSTED"
        assert out.unique_states == CANONICAL_UNIQUE
        assert out.states_explored == dev.states_explored


def test_goal_verdict_parity():
    p = paxos_spec(3).compile()
    raw = TensorSearch(p, chunk=256, visited_cap=1 << 14).run()
    sym = TensorSearch(p, chunk=256, visited_cap=1 << 14,
                       symmetry=True).run()
    assert raw.end_condition == sym.end_condition == "GOAL_FOUND"
    assert raw.predicate_name == sym.predicate_name == "DECIDED"


def test_violation_witness_replays():
    """ACCEPTANCE: the symmetry-reduced violation's recorded event
    trace replays on the tensor step from the root to a state that
    genuinely violates the invariant."""
    p = dataclasses.replace(paxos_spec(3, never_decided=True).compile(),
                            goals={})
    eng = TensorSearch(p, chunk=256, visited_cap=1 << 14,
                       symmetry=True, record_trace=True)
    out = eng.run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.predicate_name == "NONE_DECIDED"
    assert out.trace, "violation must carry a replayable trace"
    row = np.asarray(flatten_state(eng.initial_state()))[0]
    for ev in out.trace:
        nxt, ok, over = eng._step_one(jax.numpy.asarray(row),
                                      jax.numpy.asarray(ev))
        assert bool(ok), f"trace event {ev} not deliverable on replay"
        assert int(over) == 0
        row = np.asarray(nxt)
    final = eng.unflatten_rows(row[None])
    inv = p.invariants["NONE_DECIDED"]
    assert not bool(jax.vmap(inv)(final)[0]), \
        "replayed final state does not violate the invariant"


def test_permuted_states_hash_equal():
    """Unit law: delivering the root's PREPARE to acceptor 1 vs
    acceptor 3 yields states in one orbit — canonical rows (and so
    fingerprints) are identical; the raw rows are not."""
    eng = TensorSearch(_pruned(), chunk=64, symmetry=True)
    row0 = flatten_state(eng.initial_state())
    net = eng.unflatten_rows(np.asarray(row0))["net"][0]
    # Occupied net rows are the three PREPAREs, sorted by 'to'.
    occ = [i for i in range(net.shape[0]) if net[i][0] != 2**31 - 1]
    assert len(occ) == 3
    rows = []
    for slot in (occ[0], occ[-1]):
        nxt, ok, _ = eng._step_one(row0[0], jax.numpy.asarray(slot))
        assert bool(ok)
        rows.append(np.asarray(nxt))
    a, b = rows
    assert not (a == b).all()
    ca = np.asarray(eng._canon_rows(jax.numpy.asarray(a[None])))
    cb = np.asarray(eng._canon_rows(jax.numpy.asarray(b[None])))
    assert (ca == cb).all()


def test_packed_and_symmetry_compose():
    kw = dict(chunk=256, visited_cap=1 << 14, symmetry=True)
    packed = TensorSearch(_pruned(), **kw).run()
    raw = TensorSearch(_pruned(), packed=False, **kw).run()
    assert packed.unique_states == raw.unique_states == CANONICAL_UNIQUE
    assert packed.states_explored == raw.states_explored
    assert packed.bytes_per_state < packed.bytes_per_state_unpacked


def test_symmetry_without_groups_is_loud():
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    with pytest.raises(ValueError, match="symmetry"):
        TensorSearch(make_pingpong_protocol(2), symmetry=True)


def test_symmetry_checkpoint_identity(tmp_path):
    """A reduced dump's visited keys describe the QUOTIENT space — an
    unreduced search refuses it loudly (fingerprint mismatch), never
    resumes it silently."""
    pth = str(tmp_path / "sym.ckpt")
    kw = dict(chunk=64, visited_cap=1 << 14, checkpoint_path=pth,
              checkpoint_every=1)
    TensorSearch(_pruned(), symmetry=True, max_depth=4, **kw).run()
    unreduced = TensorSearch(_pruned(), max_depth=8, **kw)
    assert not unreduced.has_resumable_checkpoint()
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        unreduced.run(resume=True)
    # The reduced engine itself resumes its own dump exactly.
    full = TensorSearch(_pruned(), symmetry=True, chunk=64,
                        visited_cap=1 << 14).run()
    out = TensorSearch(_pruned(), symmetry=True, **kw).run(resume=True)
    assert out.unique_states == full.unique_states
    assert out.end_condition == full.end_condition
