"""Harness self-tests: registry/selection, runner scoring + JSON results,
tee capture, assertion helpers (mirrors the reference's framework
self-tests junit/JUnitSanityCheckTest + TeeStdOutErrTest)."""

import json

import pytest

from dslabs_tpu.harness import (RUN_TESTS, SEARCH_TESTS, UNRELIABLE_TESTS,
                                FailureAccumulator, TeeStdOutErr, TestFailure,
                                assert_end_condition_valid, assert_goal_found,
                                assert_space_exhausted)
from dslabs_tpu.harness.annotations import TestEntry
from dslabs_tpu.harness.runner import run_tests, select_tests
from dslabs_tpu.search.results import EndCondition, SearchResults


def entry(name, lab="1", num=1, part=None, points=0, cats=(RUN_TESTS,),
          fn=None, timeout=None):
    return TestEntry(fn=fn or (lambda: None), lab=lab, num=num,
                     description=name, points=points, part=part,
                     categories=tuple(cats), timeout_secs=timeout)


def test_selection_filters():
    es = [
        entry("a", lab="1", num=1, part=1, cats=(RUN_TESTS,)),
        entry("b", lab="1", num=2, part=1, cats=(SEARCH_TESTS,)),
        entry("c", lab="1", num=1, part=2, cats=(RUN_TESTS, UNRELIABLE_TESTS)),
        entry("d", lab="2", num=1, cats=(RUN_TESTS,)),
    ]
    assert [e.description for e in select_tests(es, lab="1")] == ["a", "b", "c"]
    assert [e.description for e in select_tests(es, lab="1", part=2)] == ["c"]
    assert [e.description for e in select_tests(es, lab="1", nums=[2])] == ["b"]
    assert [e.description for e in select_tests(es, lab="1",
                                                exclude_search=True)] == \
        ["a", "c"]
    assert [e.description for e in select_tests(es, lab="1",
                                                exclude_run=True)] == ["b"]
    assert [e.description for e in
            select_tests(es, exclude_unreliable=True)] == ["a", "b", "d"]


def test_runner_scores_and_json(tmp_path, capsys):
    def ok():
        print("hello from test")

    def bad():
        raise AssertionError("boom")

    es = [entry("passes", num=1, points=10, fn=ok),
          entry("fails", num=2, points=5, fn=bad)]
    out_file = tmp_path / "results.json"
    report = run_tests(es, results_output_file=str(out_file))
    assert report.num_passed == 1
    assert report.points_earned == 10
    assert report.points_available == 15
    assert not report.all_passed
    data = json.loads(out_file.read_text())
    assert data["points_earned"] == 10
    assert data["tests"][0]["passed"] is True
    assert data["tests"][1]["passed"] is False
    assert "boom" in data["tests"][1]["error"]
    assert "hello from test" in data["tests"][0]["stdout"]
    printed = capsys.readouterr().out
    assert "Tests passed: 1/2" in printed
    assert "Points: 10/15" in printed
    assert "FAIL" in printed


def test_runner_timeout():
    import time

    def slow():
        time.sleep(5)

    report = run_tests([entry("slow", num=1, fn=slow, timeout=0.2)])
    assert not report.all_passed
    assert report.results[0].timed_out


def test_timed_out_thread_does_not_contaminate_next_capture():
    import time

    def slow_then_print():
        time.sleep(0.5)
        print("LATE OUTPUT FROM TIMED-OUT TEST")

    def quick():
        time.sleep(0.8)   # long enough for the orphan thread to wake
        print("quick output")

    report = run_tests([
        entry("slow", num=1, fn=slow_then_print, timeout=0.1),
        entry("quick", num=2, fn=quick),
    ])
    assert report.results[0].timed_out
    assert "LATE OUTPUT" not in report.results[1].stdout
    assert "quick output" in report.results[1].stdout


def test_tee_capture_and_truncation(capsys):
    with TeeStdOutErr(max_bytes=8) as tee:
        print("0123456789abcdef")
    assert tee.stdout.startswith("01234567")
    assert len(tee.stdout) == 8
    assert tee.stdout_truncated
    # the real stream still saw everything
    assert "0123456789abcdef" in capsys.readouterr().out


def test_failure_accumulator():
    acc = FailureAccumulator()
    acc.check(True, "fine")
    acc.assert_no_failures()
    acc.check(False, "first")
    acc.fail_and_continue("second")
    with pytest.raises(TestFailure, match="2 accumulated"):
        acc.assert_no_failures()


def _results(end, invariants=(), goals=()):
    r = SearchResults(list(invariants), list(goals))
    r.end_condition = end
    return r


def test_assert_helpers():
    assert_end_condition_valid(_results(EndCondition.SPACE_EXHAUSTED))
    assert_space_exhausted(_results(EndCondition.SPACE_EXHAUSTED))
    assert_goal_found(_results(EndCondition.GOAL_FOUND))
    with pytest.raises(TestFailure, match="Goal not found"):
        assert_goal_found(_results(EndCondition.TIME_EXHAUSTED))
    with pytest.raises(TestFailure, match="not exhausted"):
        assert_space_exhausted(_results(EndCondition.TIME_EXHAUSTED))
    with pytest.raises(TestFailure, match="Invariant violated"):
        assert_end_condition_valid(_results(EndCondition.INVARIANT_VIOLATED))


def test_registry_decorator_roundtrip():
    from dslabs_tpu.harness import lab_test

    @lab_test("9", 3, "registry probe", points=7, part=2,
              categories=(SEARCH_TESTS,))
    def probe():
        return 42

    try:
        e = probe._dslabs_test_entry
        assert (e.lab, e.num, e.part, e.points) == ("9", 3, 2, 7)
        assert e.full_number == "2.3"
        assert probe() == 42  # function itself untouched
        from dslabs_tpu.harness import registry
        assert any(x.description == "registry probe" for x in registry())
    finally:
        from dslabs_tpu.harness.annotations import _REGISTRY
        _REGISTRY.remove(probe._dslabs_test_entry)
