"""Cross-job memoization parity suite (ISSUE 16, ``make memo-smoke``).

The reuse layer's one inviolable contract: a memoized answer is
BIT-IDENTICAL to the cold answer it replaced, or it is not given.
Covered bottom-up:

* structural fingerprints: rename-only/whitespace resubmits map to the
  SAME signature, a one-handler edit maps to a different one, and both
  the admission cache and the verdict cache key on that identity
  (satellite: they can never disagree about what a spec IS);
* HostVisitedTier persistence: versioned save/load with CRC + .prev
  rotation, loud refusal on foreign pack-descriptor or symmetry-flag
  mismatch (never a silently poisoned visited set);
* the divergence bound: tag-reachability over the union effect table
  lower-bounds the first level a handler edit can touch;
* service-level reuse: exact-key hit (zero dispatches, memo_hit
  journaled, ~0 COSTS device_secs), warm-start parity vs a cold run,
  incremental re-check after a one-handler edit finding the same
  violation with an identical witness digest, stale-verdict
  impossibility (an edited spec never returns a cached verdict),
  SIGKILL-mid-warm-start resume parity, a 3-tenant drain where the
  identical resubmit bills <10% of the cold run, and the memo-OFF
  overhead guard (no memo dir, no memo events, verdicts unchanged).
"""

import json
import os
import textwrap

import numpy as np
import pytest

from dslabs_tpu.service import CheckServer
from dslabs_tpu.service import memo as memo_mod
from dslabs_tpu.tpu import spill as spill_mod

pytestmark = [pytest.mark.service, pytest.mark.memo]

CHILD_ENV = {"DSLABS_COMPILE_CACHE": "/tmp/jaxcache-cpu"}
FACTORY = ("dslabs_tpu.tpu.protocols.pingpong:"
           "make_exhaustive_pingpong")
SMALL = dict(factory_kwargs={"workload_size": 2}, chunk=64,
             frontier_cap=1 << 8, visited_cap=1 << 12)
GRACES = {"boot_grace": 120.0, "first_grace": 120.0,
          "steady_grace": 3.0, "idle_grace": 60.0, "grace_slack": 1.0}


def _server(root, **kw):
    kw.setdefault("admission", False)
    kw.setdefault("elastic", False)
    kw.setdefault("env", CHILD_ENV)
    kw.setdefault("warden_kwargs", dict(GRACES))
    return CheckServer(str(root), **kw)


def _same_verdict(a: dict, b: dict):
    for key in ("end", "unique", "explored", "depth"):
        assert a[key] == b[key], (key, a, b)


def _journal(root):
    path = os.path.join(str(root), "journal.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _events(root, kind):
    return [e for e in _journal(root) if e.get("t") == kind]


def _costs(root, tenant):
    path = os.path.join(str(root), "COSTS.jsonl")
    rows = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("tenant") == tenant:
                rows.append(rec)
    return rows


# --------------------------------------------- spec fixture modules

# A 3-stage message chain: S1 -> S2 -> S3, x walks 0..FINAL.  The
# final stage's write is the ONE knob the incremental tests edit —
# FINAL=3 is invariant-clean (SPACE_EXHAUSTED), FINAL=4 fires NO_FOUR
# at depth 3.  Field bounds are identical in both versions so the
# structural base (nodes/domains/messages) matches and only the S3
# handler hash differs.
CHAIN_MODULE = textwrap.dedent("""
    from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                         ProtocolSpec, TimerType)


    def make_chain():
        spec = ProtocolSpec(
            "memo-chain",
            nodes=[NodeKind("proc", 1, (Field("x", init=0, hi=4),))],
            messages=[MessageType("S1", ()), MessageType("S2", ()),
                      MessageType("S3", ())],
            timers=[TimerType("TICK", (), 10, 10)],
            net_cap=4, timer_cap=1)

        @spec.on("proc", "S1")
        def h1(ctx, m):
            ctx.put("x", 1)
            ctx.send("S2", 0)

        @spec.on("proc", "S2")
        def h2(ctx, m):
            ctx.put("x", 2)
            ctx.send("S3", 0)

        @spec.on("proc", "S3")
        def h3(ctx, m):
            ctx.put("x", {final})

        spec.initial_messages.append(("S1", 0, 0, {{}}))

        def no_four(v):
            return v.get("proc", 0, "x") != 4

        spec.invariants["NO_FOUR"] = no_four
        return spec.compile()
""")

# Rename-only variant of the pingpong-spec factory: different module
# name, different factory-function name, extra comments/whitespace/
# docstring — structurally the SAME protocol.
SPEC_PP = textwrap.dedent("""
    from dslabs_tpu.tpu.specs import pingpong_spec


    def make(workload_size=2):
        return pingpong_spec(workload_size).compile()
""")

SPEC_PP_RENAMED = textwrap.dedent('''
    # A cosmetic rewrite of the same submission: renamed module,
    # renamed factory, reflowed whitespace.  Structurally identical.
    from dslabs_tpu.tpu.specs import pingpong_spec


    def build(workload_size=2):
        """Same lab-0 spec, different spelling."""

        return pingpong_spec(workload_size).compile()
''')


def _write_chain(tmp_path, name, final):
    (tmp_path / f"{name}.py").write_text(
        CHAIN_MODULE.format(final=final))
    return f"{name}:make_chain"


CHAIN = dict(chunk=64, frontier_cap=1 << 8, visited_cap=1 << 12)


# -------------------------------------------- fingerprint identity


def test_rename_only_same_fingerprint(tmp_path):
    """Whitespace/rename-only edits hash to the SAME structural
    fingerprint; a handler edit hashes to a different one."""
    (tmp_path / "fp_a.py").write_text(SPEC_PP)
    (tmp_path / "fp_b.py").write_text(SPEC_PP_RENAMED)
    extra = [str(tmp_path)]
    a = memo_mod.introspect_child("fp_a:make", {"workload_size": 2},
                                  None, extra_sys_path=extra)
    b = memo_mod.introspect_child("fp_b:build", {"workload_size": 2},
                                  None, extra_sys_path=extra)
    assert a["ok"] and b["ok"], (a, b)
    assert not a["weak"] and not b["weak"]
    assert a["spec_fp"] == b["spec_fp"]
    assert a["base_fp"] == b["base_fp"]
    # Different workload -> different structure (domains change).
    c = memo_mod.introspect_child("fp_a:make", {"workload_size": 3},
                                  None, extra_sys_path=extra)
    assert c["ok"] and c["spec_fp"] != a["spec_fp"]
    # One-handler edit -> different spec_fp, same base/predicates,
    # exactly one differing handler hash (the incremental precondition).
    v1 = _write_chain(tmp_path, "fp_v1", 3)
    v2 = _write_chain(tmp_path, "fp_v2", 4)
    i1 = memo_mod.introspect_child(v1, {}, None, extra_sys_path=extra)
    i2 = memo_mod.introspect_child(v2, {}, None, extra_sys_path=extra)
    assert i1["ok"] and i2["ok"]
    assert i1["kind"] == "spec" and not i1["weak"]
    assert i1["spec_fp"] != i2["spec_fp"]
    assert i1["base_fp"] == i2["base_fp"]
    assert i1["predicates"] == i2["predicates"]
    diff = [k for k in i1["handlers"]
            if i1["handlers"][k] != i2["handlers"][k]]
    assert diff == ["m:proc:S3"]


REP_MODULE = textwrap.dedent("""
    from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                         ProtocolSpec, TimerType)
    from dslabs_tpu.tpu.quorum import QuorumCount
    from dslabs_tpu.tpu.slots import SlotField, Slots


    def {factory}():
        spec = ProtocolSpec(
            "memo-rep",
            nodes=[NodeKind("proc", 3, (
                Field("x", hi=7),
                Slots("log", {n}, (SlotField("cmd", hi=7,
                                             clear={clear}),),
                      base=1),
            ))],
            messages=[MessageType("GO", ())],
            timers=[TimerType("TICK", (), 10, 10)],
            net_cap=4, timer_cap=1,
            quorums=(QuorumCount("q", over="proc",
                                 threshold={threshold!r}),))

        @spec.on("proc", "GO")
        def go(ctx, m):
            met = ctx.quorum("q").met_bits(ctx.get("x"))
            ctx.slot_put("log", "cmd", 1, 2, when=met)
            ctx.slot_clear_upto("log", 2, when=~met)

        spec.initial_messages.append(("GO", 0, 0, {{}}))
        spec.invariants["OK"] = lambda v: True
        return spec.compile()
""")


def _write_rep(tmp_path, name, factory="make_rep", n=2, clear=0,
               threshold="majority"):
    (tmp_path / f"{name}.py").write_text(REP_MODULE.format(
        factory=factory, n=n, clear=clear, threshold=threshold))
    return f"{name}:{factory}"


def test_slot_quorum_rename_vs_resize_fingerprints(tmp_path):
    """ISSUE 20 satellite: the Slots/Quorum declarations participate in
    the structural fingerprint.  A factory rename is cosmetic (same
    fp); resizing the slot block, changing a SlotField ``clear``, or
    moving the quorum threshold — all invisible to the expanded node
    fields and the handler ASTs — each change the base fingerprint."""
    extra = [str(tmp_path)]

    def introspect(ref):
        out = memo_mod.introspect_child(ref, {}, None,
                                        extra_sys_path=extra)
        assert out["ok"] and not out["weak"], out
        return out

    base = introspect(_write_rep(tmp_path, "rep_a"))
    renamed = introspect(_write_rep(tmp_path, "rep_b",
                                    factory="build_replicated"))
    assert renamed["spec_fp"] == base["spec_fp"]
    assert renamed["base_fp"] == base["base_fp"]
    resized = introspect(_write_rep(tmp_path, "rep_c", n=3))
    cleared = introspect(_write_rep(tmp_path, "rep_d", clear=1))
    rethresh = introspect(_write_rep(tmp_path, "rep_e",
                                     threshold="all"))
    fps = {v["base_fp"] for v in (base, resized, cleared, rethresh)}
    assert len(fps) == 4, fps
    # The handler ASTs never changed — only the declarations did.
    assert resized["handlers"] == base["handlers"]
    assert cleared["handlers"] == base["handlers"]
    assert rethresh["handlers"] == base["handlers"]


def test_duck_typed_slot_block_marks_weak():
    """A partially-spec'd protocol (a slot declaration that is not a
    real Slots block) fingerprints WEAK, so the store refuses to
    memoize it rather than guess at its identity."""
    from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                         ProtocolSpec, TimerType)
    from dslabs_tpu.tpu.slots import SlotField, Slots

    spec = ProtocolSpec(
        "memo-duck",
        nodes=[NodeKind("proc", 1, (
            Field("x", hi=4),
            Slots("log", 2, (SlotField("cmd", hi=7),), base=1)))],
        messages=[MessageType("GO", ())],
        timers=[TimerType("TICK", (), 10, 10)],
        net_cap=4, timer_cap=1)

    @spec.on("proc", "GO")
    def go(ctx, m):
        ctx.put("x", ctx.slot_get("log", "cmd", 1))

    spec.initial_messages.append(("GO", 0, 0, {}))
    spec.invariants["OK"] = lambda v: True
    proto = spec.compile()
    info = memo_mod.introspect_protocol(proto)
    assert not info["weak"]

    class DuckBlock:
        # Enough surface for the Ctx slot ops (the effect-table trace
        # still runs), but no ``name``/``fields`` — the declaration
        # fingerprint cannot see inside it.
        base, n = 1, 2

        def lane(self, field):
            return f"log.{field}"

    spec.slot_blocks[("proc", "log")] = DuckBlock()
    assert memo_mod.introspect_protocol(proto)["weak"]


def test_divergence_bound_chain(tmp_path):
    """Tag-reachability lower-bounds the first level a changed handler
    can fire: editing S3 in the 3-stage chain shares levels 0..2."""
    extra = [str(tmp_path)]
    v1 = _write_chain(tmp_path, "div_v1", 3)
    i1 = memo_mod.introspect_child(v1, {}, None, extra_sys_path=extra)
    assert i1["ok"]
    eff, init = i1["effects"], i1["initial"]
    assert memo_mod.divergence_depth(eff, init, {"m:proc:S3"}) == 2
    assert memo_mod.divergence_depth(eff, init, {"m:proc:S2"}) == 1
    assert memo_mod.divergence_depth(eff, init, {"m:proc:S1"}) == 0
    # A handler whose trigger is unreachable diverges nowhere.
    assert memo_mod.divergence_depth(
        eff, ["m1"], {"m:proc:S1"}) >= memo_mod._INF


# ------------------------------------- visited-tier save/load/refuse


def _tier_arrays(n=64, seed=7):
    rng = np.random.default_rng(seed)
    h1 = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    h2 = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    return h1, h2


def test_tier_roundtrip_and_prev_rotation(tmp_path):
    path = str(tmp_path / "tier.npz")
    h1, h2 = _tier_arrays()
    spill_mod.save_tier(path, h1, h2, {"pack": "p1", "sym": 0})
    r1, r2, meta = spill_mod.load_tier(
        path, expect_meta={"pack": "p1", "sym": 0})
    assert np.array_equal(r1, h1) and np.array_equal(r2, h2)
    assert meta["fmt"] == spill_mod.TIER_FORMAT
    # Second save rotates .prev; a torn main file falls back to it.
    g1, g2 = _tier_arrays(seed=8)
    spill_mod.save_tier(path, g1, g2, {"pack": "p1", "sym": 0})
    assert os.path.exists(path + ".prev")
    with open(path, "wb") as f:
        f.write(b"torn")
    f1, _, _ = spill_mod.load_tier(path,
                                   expect_meta={"pack": "p1", "sym": 0})
    assert np.array_equal(f1, h1)  # .prev holds the FIRST save
    # Both gone/torn -> loud corruption, never empty arrays.
    with open(path + ".prev", "wb") as f:
        f.write(b"also-torn")
    with pytest.raises(spill_mod.TierCorrupt):
        spill_mod.load_tier(path, expect_meta={"pack": "p1", "sym": 0})


def test_tier_refuses_foreign_pack_and_symmetry(tmp_path):
    """Satellite: the two refusal paths are LOUD — a tier saved under
    one pack descriptor or symmetry flag never loads under another."""
    path = str(tmp_path / "tier.npz")
    h1, h2 = _tier_arrays()
    spill_mod.save_tier(path, h1, h2, {"pack": "pack-v1:abcd", "sym": 0})
    with pytest.raises(spill_mod.TierMismatch, match="pack"):
        spill_mod.load_tier(path,
                            expect_meta={"pack": "pack-v2:ffff",
                                         "sym": 0})
    with pytest.raises(spill_mod.TierMismatch, match="sym"):
        spill_mod.load_tier(path,
                            expect_meta={"pack": "pack-v1:abcd",
                                         "sym": 6})


# ------------------------------------------- service-level reuse


def test_exact_hit_zero_dispatch(tmp_path):
    """ISSUE 16 acceptance leg (a): the identical resubmit returns the
    cached verdict with ZERO device dispatches — journaled memo_hit,
    cached=true, ~0 COSTS device_secs."""
    srv = _server(tmp_path)
    srv.submit(FACTORY, tenant="alice", **SMALL)
    srv.drain()
    cold = [v for v in srv.results if v["tenant"] == "alice"][0]
    assert cold["status"] == "done"

    res = srv.submit(FACTORY, tenant="bob", **SMALL)
    srv.close()
    assert res.get("memo") == "hit"
    hit = res["verdict"]
    assert hit["cached"] is True
    _same_verdict(hit, cold)
    assert hit["witness"] == cold["witness"]
    assert len(_events(tmp_path, "memo_hit")) == 1
    bob = _costs(tmp_path, "bob")[-1]
    assert bob["device_secs"] == 0.0 and bob["dispatches"] == 0
    st = srv.server_status()
    assert st["memo"]["hits"] == 1
    assert st["memo"]["device_secs_saved"] > 0


def test_warm_start_parity(tmp_path):
    """Leg (b): budget grew, signature matched — the new job resumes
    from the archived frontier and lands counts bit-identical to a
    cold run at the same depth."""
    ref_srv = _server(tmp_path / "ref", memo=False)
    ref_srv.submit(FACTORY, tenant="ref", **SMALL)
    ref = ref_srv.drain()["results"][0]
    ref_srv.close()

    srv = _server(tmp_path / "svc")
    srv.submit(FACTORY, tenant="a", max_depth=3, **SMALL)
    srv.drain()
    srv.submit(FACTORY, tenant="b", **SMALL)
    srv.drain()
    srv.close()
    warm = [v for v in srv.results if v["tenant"] == "b"][0]
    _same_verdict(warm, ref)
    assert warm["resumed_from_depth"] > 0
    ev = [e for e in _events(tmp_path / "svc", "memo")
          if e.get("mode") == "warm"]
    assert len(ev) == 1 and ev[0]["seed_depth"] > 0
    assert srv.server_status()["memo"]["warm_starts"] == 1


def test_incremental_recheck_and_stale_impossibility(tmp_path):
    """Leg (c) + stale-verdict impossibility, via the true hazard: the
    module is edited IN PLACE under the same factory path.  The edited
    spec must never return the old cached verdict; it completes via
    incremental re-check (levels_skipped >= 1) with a verdict and
    witness digest bit-identical to its own cold run."""
    extra = [str(tmp_path)]
    ref_root = tmp_path / "ref"
    _write_chain(tmp_path, "chain_cold", 4)
    ref_srv = _server(ref_root, extra_sys_path=extra, memo=False)
    ref_srv.submit("chain_cold:make_chain", tenant="ref", **CHAIN)
    ref = ref_srv.drain()["results"][0]
    ref_srv.close()
    assert ref["end"] == "INVARIANT_VIOLATED"
    assert ref["predicate"] == "NO_FOUR"

    factory = _write_chain(tmp_path, "chain", 3)
    srv = _server(tmp_path / "svc", extra_sys_path=extra)
    srv.submit(factory, tenant="v1", **CHAIN)
    v1 = srv.drain()["results"][0]
    assert v1["end"] == "SPACE_EXHAUSTED"

    _write_chain(tmp_path, "chain", 4)      # the one-handler edit
    srv.submit(factory, tenant="v2", **CHAIN)
    srv.drain()
    srv.close()
    v2 = [v for v in srv.results if v["tenant"] == "v2"][0]
    # Stale-verdict impossibility: the edit was SEEN (no memo_hit, no
    # SPACE_EXHAUSTED replay) …
    assert _events(tmp_path / "svc", "memo_hit") == []
    assert v2["end"] == "INVARIANT_VIOLATED"
    # … and the re-check was incremental yet bit-identical to cold.
    _same_verdict(v2, ref)
    assert v2["predicate"] == ref["predicate"]
    assert v2["witness"] == ref["witness"]
    ev = [e for e in _events(tmp_path / "svc", "memo")
          if e.get("mode") == "incremental"]
    assert len(ev) == 1
    assert ev[0]["levels_skipped"] >= 1
    st = srv.server_status()
    assert st["memo"]["incremental"] == 1
    assert st["memo"]["levels_skipped"] >= 1


def test_rename_only_resubmit_hits_both_caches(tmp_path):
    """Satellite: admission and memoization share ONE spec identity —
    a rename-only resubmit is an admission-cache hit AND a verdict-
    cache hit."""
    (tmp_path / "ren_a.py").write_text(SPEC_PP)
    (tmp_path / "ren_b.py").write_text(SPEC_PP_RENAMED)
    srv = _server(tmp_path / "svc", admission=True,
                  extra_sys_path=[str(tmp_path)])
    res = srv.submit("ren_a:make", tenant="alice",
                     factory_kwargs={"workload_size": 2}, chunk=64,
                     frontier_cap=1 << 8, visited_cap=1 << 12)
    assert res.get("accepted"), res
    srv.drain()
    res2 = srv.submit("ren_b:build", tenant="bob",
                      factory_kwargs={"workload_size": 2}, chunk=64,
                      frontier_cap=1 << 8, visited_cap=1 << 12)
    srv.close()
    assert res2.get("memo") == "hit"
    adm = _events(tmp_path / "svc", "admission")
    assert [e["cached"] for e in adm] == [False, True]
    _same_verdict(res2["verdict"],
                  [v for v in srv.results if v["tenant"] == "alice"][0])


def test_memo_off_overhead_guard(tmp_path, monkeypatch):
    """Memo OFF (constructor or DSLABS_MEMO=0) leaves the existing
    service path untouched: no memo dir, no memo events, no intro
    children, verdicts unchanged."""
    monkeypatch.setenv("DSLABS_MEMO", "0")
    srv = _server(tmp_path / "env_off")
    assert srv.memo is None
    srv.submit(FACTORY, tenant="a", **SMALL)
    srv.drain()
    srv.submit(FACTORY, tenant="b", **SMALL)
    srv.drain()
    srv.close()
    monkeypatch.delenv("DSLABS_MEMO")
    a, b = srv.results[0], srv.results[1]
    assert a["status"] == "done" and b["status"] == "done"
    _same_verdict(a, b)
    assert not os.path.isdir(os.path.join(str(tmp_path / "env_off"),
                                          "memo"))
    ev = _journal(tmp_path / "env_off")
    assert not [e for e in ev if e.get("t") in ("memo", "memo_hit")]
    assert srv.server_status()["memo"] == {"enabled": False}
    # Default-ON contract for the service path.
    srv_on = _server(tmp_path / "on")
    assert srv_on.memo is not None
    srv_on.close()


def test_three_tenant_drain_resubmit_bills_under_ten_percent(tmp_path):
    """Satellite acceptance: in a 3-tenant drain, tenant B's identical
    resubmit of tenant A's job bills <10% of A's cold device_secs in
    COSTS (here: exactly zero — the hit never dispatches)."""
    srv = _server(tmp_path, workers=1)
    srv.submit(FACTORY, tenant="alice", **SMALL)
    srv.submit(FACTORY, tenant="bob", **SMALL)
    srv.submit(FACTORY, tenant="carol",
               factory_kwargs={"workload_size": 3}, chunk=64,
               frontier_cap=1 << 8, visited_cap=1 << 12)
    summary = srv.drain()
    srv.close()
    assert summary["completed"] == 3
    va = [v for v in srv.results if v["tenant"] == "alice"][0]
    vb = [v for v in srv.results if v["tenant"] == "bob"][0]
    _same_verdict(va, vb)
    ca = _costs(tmp_path, "alice")[-1]
    cb = _costs(tmp_path, "bob")[-1]
    assert ca["device_secs"] > 0
    assert cb["device_secs"] < 0.10 * ca["device_secs"]
    assert len(_events(tmp_path, "memo_hit")) == 1
    assert summary["memo"]["hits"] == 1


@pytest.mark.slow
def test_sigkill_mid_warm_start_resume_parity(tmp_path):
    """A SIGKILL landing mid-warm-start is survived by the normal
    resume path: the seeded job's final verdict is bit-identical to
    the cold full run, and the fault never lands a cached verdict."""
    ref_srv = _server(tmp_path / "ref", memo=False)
    ref_srv.submit(FACTORY, tenant="ref", **SMALL)
    ref = ref_srv.drain()["results"][0]
    ref_srv.close()

    srv = _server(tmp_path / "svc", workers=1)
    srv.submit(FACTORY, tenant="a", max_depth=3, ladder=("device",),
               **SMALL)
    srv.drain()
    # The seeded checkpoint exists BEFORE the child boots, so
    # after_ckpt arms immediately and the kill lands on the very
    # first warm dispatch — mid-warm-start by construction.
    srv.submit(FACTORY, tenant="b", ladder=("device",),
               fault={"kind": "die", "at": 1, "after_ckpt": True},
               **SMALL)
    srv.drain()
    srv.close()
    out = [v for v in srv.results if v["tenant"] == "b"][0]
    assert out["status"] == "done"
    _same_verdict(out, ref)
    assert out["attempts"] >= 2           # the fault really fired
    assert out.get("cached") is not True
    warm = [e for e in _events(tmp_path / "svc", "memo")
            if e.get("mode") == "warm"]
    assert len(warm) == 1                 # seeded before the SIGKILL


@pytest.mark.slow
@pytest.mark.parametrize("strict", [True, False])
@pytest.mark.parametrize("packed", ["1", "0"])
def test_warm_parity_sweep(tmp_path, strict, packed):
    """Warm-start exactness across the engine's encoding matrix:
    strict and beam, packed frontier on and off (lab-0 spec factory +
    the lab-1 clientserver knob ride the same compiled path)."""
    env = dict(CHILD_ENV, DSLABS_PACKED=packed)
    (tmp_path / "sw.py").write_text(SPEC_PP)
    extra = [str(tmp_path)]
    kw = dict(factory_kwargs={"workload_size": 2}, strict=strict,
              chunk=64, frontier_cap=1 << 8, visited_cap=1 << 12)
    ref_srv = _server(tmp_path / "ref", env=env, memo=False,
                      extra_sys_path=extra)
    ref_srv.submit("sw:make", tenant="ref", **kw)
    ref = ref_srv.drain()["results"][0]
    ref_srv.close()
    assert ref["status"] == "done"

    srv = _server(tmp_path / "svc", env=env, extra_sys_path=extra)
    srv.submit("sw:make", tenant="a", max_depth=3, **kw)
    srv.drain()
    srv.submit("sw:make", tenant="b", **kw)
    srv.drain()
    srv.close()
    warm = [v for v in srv.results if v["tenant"] == "b"][0]
    _same_verdict(warm, ref)
    assert srv.server_status()["memo"]["warm_starts"] == 1
