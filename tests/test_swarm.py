"""Device-sharded swarm explorer (ISSUE 5): diversified random-walk
fleets with shared dedup and replay-verified witnesses
(dslabs_tpu/tpu/swarm.py), proven on the virtual CPU mesh:

* seeded determinism — same seed, same witness, bit for bit;
* swarm-vs-BFS verdict parity on pingpong + lab1 (the host BFS loop is
  the parity oracle; a minimized swarm witness can never undercut the
  BFS's minimal violation depth);
* dedup sharing — walkers restarting from a mid-BFS checkpoint
  frontier (table pre-seeded with the BFS's keys) re-tread covered
  territory at a measurably lower rate than a root-started fleet;
* frontier-seeding resume parity — a swarm cut mid-flight resumes
  from its round checkpoint to the IDENTICAL witness;
* FaultPlan transient-retry inside a swarm dispatch (the `_dispatch`
  seam contract);
* loud walker-overflow accounting (the old rollout probe restarted
  capacity-truncated walkers silently);
* the portfolio acceptance: on a deep-narrow violation with a fixed
  wall-clock budget, BFS alone returns TIME_EXHAUSTED while
  ``SearchSupervisor(portfolio=True)`` returns the violation with a
  minimized, independently-replayed witness.

Deep-narrow paxos scenarios are marked ``slow`` + ``perf`` and run via
``make swarm-smoke``.
"""

import dataclasses
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dslabs_tpu.tpu.engine import (CapacityOverflow, SENTINEL,  # noqa: E402
                                   TensorProtocol, TensorSearch)
from dslabs_tpu.tpu.protocols.clientserver import \
    make_clientserver_protocol  # noqa: E402
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402
from dslabs_tpu.tpu.sharded import make_mesh  # noqa: E402
from dslabs_tpu.tpu.supervisor import (FaultPlan, RetryPolicy,  # noqa: E402
                                       SearchSupervisor, install_retry)
from dslabs_tpu.tpu.swarm import SwarmSearch  # noqa: E402

pytestmark = pytest.mark.swarm


def _violating(proto):
    """Plant a reachable violation: the completion goal negated into an
    invariant (violated exactly at the done state — the deepest state
    of the space, which is what the walkers are for)."""
    done = proto.goals["CLIENTS_DONE"]
    return dataclasses.replace(
        proto, goals={},
        invariants={"NOT_DONE": lambda s, f=done: ~f(s)})


def _swarm(proto, **kw):
    kw.setdefault("mesh", make_mesh(2))
    kw.setdefault("walkers_per_device", 16)
    kw.setdefault("max_steps", 32)
    kw.setdefault("steps_per_round", 32)
    kw.setdefault("seed", 7)
    kw.setdefault("visited_cap", 1 << 12)
    return SwarmSearch(proto, **kw)


def make_lock_protocol(m=6, k=9, noise_bits=16):
    """The deep-narrow scenario: a combination lock.  ``m`` persistent
    digit messages (delivery never removes a message), progress
    advances only on the ONE correct next digit, and a noise register
    folds every delivered digit into the state — so the space branches
    ``m`` ways per step while the violation (``p == k``) sits at depth
    >= k down exactly one digit sequence.  BFS must breadth through
    ~m^d states per level; a random walker reaches depth k in ~m*k
    steps."""
    MW, TW = 2, 3
    mask = (1 << noise_bits) - 1

    def init_nodes():
        return np.array([0, 0], np.int32)

    def init_messages():
        return np.array([[d, 0] for d in range(m)], np.int32)

    def init_timers():
        return np.zeros((0, 1 + TW), np.int32)

    def step_message(nodes, msg):
        d = msg[0]
        p, noise = nodes[0], nodes[1]
        good = d == (p * 5 + 3) % m
        p2 = jnp.where(good, p + 1, p)
        noise2 = (noise * 31 + d + 1) & mask
        nodes2 = nodes.at[0].set(p2).at[1].set(noise2)
        return (nodes2, jnp.full((1, MW), SENTINEL, jnp.int32),
                jnp.full((1, 1 + TW), SENTINEL, jnp.int32))

    def step_timer(nodes, node_idx, timer):
        return (nodes, jnp.full((1, MW), SENTINEL, jnp.int32),
                jnp.full((1, 1 + TW), SENTINEL, jnp.int32))

    return TensorProtocol(
        name=f"lock-m{m}-k{k}-b{noise_bits}", n_nodes=1, node_width=2,
        msg_width=MW, timer_width=TW, net_cap=m, timer_cap=1,
        max_sends=1, max_sets=1, init_nodes=init_nodes,
        init_messages=init_messages, init_timers=init_timers,
        step_message=step_message, step_timer=step_timer,
        msg_dest=lambda msg: 0,
        invariants={"LOCK_HELD": lambda s, k=k: s["nodes"][0] < k})


# ------------------------------------------------------- determinism

def test_seeded_determinism_identical_witness():
    """Same seed => identical verdict, witness (raw AND minimized),
    and fleet counters — the PRNG state is the only nondeterminism
    source and it is fully seeded."""
    proto = _violating(make_pingpong_protocol(2))
    a = _swarm(proto).run()
    b = _swarm(proto).run()
    assert a.end_condition == b.end_condition == "INVARIANT_VIOLATED"
    assert a.predicate_name == b.predicate_name == "NOT_DONE"
    assert a.witness.raw_trace == b.witness.raw_trace
    assert a.witness.trace == b.witness.trace

    def counters(o):
        # Everything but the wall-clock-derived rates.
        return {k: v for k, v in o.swarm.items()
                if not k.endswith(("_per_sec", "_per_min"))}

    assert counters(a) == counters(b)


# --------------------------------------------------- verdict parity

@pytest.mark.parametrize("maker", [
    lambda: _violating(make_pingpong_protocol(2)),
    lambda: _violating(make_clientserver_protocol(n_clients=1, w=2)),
], ids=["pingpong", "lab1"])
def test_swarm_vs_bfs_verdict_parity(maker):
    """The swarm lands the same verdict + predicate as the host BFS
    parity oracle, its witness replays clean, and — BFS depth being
    the MINIMAL violation distance — the minimized witness can never
    be shorter than it."""
    proto = maker()
    bfs = TensorSearch(proto, chunk=64, use_host_visited=True).run()
    assert bfs.end_condition == "INVARIANT_VIOLATED"
    out = _swarm(proto, max_steps=48).run()
    assert out.end_condition == bfs.end_condition
    assert out.predicate_name == bfs.predicate_name
    w = out.witness
    assert w.replay_verified and w.minimized
    assert len(w.trace) <= len(w.raw_trace)
    assert len(w.trace) >= bfs.depth


def test_witness_trace_decodes_and_replays():
    """The witness rides the existing tpu/trace.py contract: the
    minimized event-id list decodes to concrete message/timer records,
    and re-applying it manually from the root reproduces the violating
    predicate result."""
    from dslabs_tpu.tpu.swarm import replay_events
    from dslabs_tpu.tpu.trace import decode_trace

    proto = _violating(make_pingpong_protocol(2))
    sw = _swarm(proto)
    out = sw.run()
    recs = decode_trace(sw, out)
    assert len(recs) == len(out.witness.trace)
    from dslabs_tpu.tpu.engine import flatten_state

    root = np.asarray(flatten_state(jax.tree.map(
        jnp.asarray, sw._trace_root)))[0]
    row, applied = replay_events(sw, root, out.witness.trace)
    assert applied == len(out.witness.trace)
    end = sw.unflatten_rows(jnp.asarray(row)[None])
    holds = bool(np.asarray(jax.vmap(
        proto.invariants["NOT_DONE"])(end))[0])
    assert not holds


# ---------------------------------------------------- dedup sharing

def test_dedup_sharing_frontier_seed_drops_revisit_rate(tmp_path):
    """Dedup sharing with BFS: seeding the fleet from a mid-BFS
    checkpoint (frontier restarts + table pre-seeded with the BFS's
    visited keys) makes walkers re-tread covered territory at a lower
    rate than a root-started fleet, whose walkers all funnel through
    the same shallow states.  The lock protocol (wide branching, no
    reachable violation here) makes the funnel measurable: every
    root-started walker's first step lands on one of six states."""
    proto = make_lock_protocol(m=6, k=10 ** 6, noise_bits=16)
    ckpt = str(tmp_path / "bfs.npz")
    cut = TensorSearch(proto, chunk=256, max_depth=4,
                       checkpoint_path=ckpt, checkpoint_every=1)
    assert cut.run().end_condition == "DEPTH_EXHAUSTED"
    kw = dict(walkers_per_device=16, max_steps=40, steps_per_round=40,
              max_rounds=1, seed=5)
    rooted = _swarm(proto, **kw).run()
    seeded = _swarm(proto, frontier_seed=ckpt, **kw).run()
    assert rooted.end_condition == seeded.end_condition \
        == "TIME_EXHAUSTED"

    def rate(o):
        return o.swarm["revisits"] / max(o.swarm["explored"], 1)

    assert rate(seeded) < rate(rooted)
    # Pre-seeded BFS keys are already in the table, so the seeded
    # fleet's unique count (fresh inserts) never re-counts them.
    assert seeded.swarm["vis_over"] == 0
    assert seeded.unique_states > 0


# ------------------------------------------------------- checkpoints

def test_frontier_seeding_resume_parity(tmp_path):
    """A frontier-seeded swarm cut mid-flight resumes from its round
    checkpoint (walker rows, histories, PRNG keys, seed pool, table)
    to a BIT-IDENTICAL continuation: same verdict, same witness, same
    counters as the uncut run."""
    proto = _violating(make_pingpong_protocol(3))
    bfs_ck = str(tmp_path / "bfs.npz")
    TensorSearch(proto, chunk=64, max_depth=2, checkpoint_path=bfs_ck,
                 checkpoint_every=1).run()
    kw = dict(walkers_per_device=8, max_steps=24, steps_per_round=8,
              seed=3, frontier_seed=bfs_ck)
    full = _swarm(proto, **kw).run()
    assert full.end_condition == "INVARIANT_VIOLATED"
    sw_ck = str(tmp_path / "swarm.npz")
    cut = _swarm(proto, max_rounds=1, checkpoint_path=sw_ck,
                 checkpoint_every=1, **kw).run()
    assert cut.end_condition == "TIME_EXHAUSTED"
    assert os.path.exists(sw_ck)
    resumed = _swarm(proto, checkpoint_path=sw_ck, **kw)
    out = resumed.run(resume=True)
    assert out.end_condition == full.end_condition
    assert out.witness.raw_trace == full.witness.raw_trace
    assert out.witness.trace == full.witness.trace
    assert out.swarm["explored"] == full.swarm["explored"]
    assert out.resumed_from_depth == 1


def test_swarm_checkpoint_not_resumable_by_bfs(tmp_path):
    """Swarm dumps are their own fingerprint family: a BFS engine must
    refuse one loudly rather than resume walker rows as a frontier."""
    from dslabs_tpu.tpu import checkpoint as ckpt_mod

    # No reachable violation (goal pruned away), so the round runs to
    # its cap and the checkpoint actually lands.
    pp = make_pingpong_protocol(2)
    proto = dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
    sw_ck = str(tmp_path / "swarm.npz")
    _swarm(proto, max_rounds=1, checkpoint_path=sw_ck,
           checkpoint_every=1).run()
    assert os.path.exists(sw_ck)
    bfs = TensorSearch(proto, chunk=64, checkpoint_path=sw_ck)
    assert not bfs.has_resumable_checkpoint()
    with pytest.raises(ckpt_mod.CheckpointMismatch):
        bfs.run(resume=True)


# ------------------------------------------------- dispatch seam

def test_faultplan_transient_retry_inside_swarm_dispatch():
    """The swarm rides the `_dispatch` seam: a transient fault injected
    into a swarm round dispatch retries in place with an identical
    witness — the supervisor/watchdog/warden contracts apply to swarm
    runs without modification."""
    proto = _violating(make_pingpong_protocol(2))
    base = _swarm(proto).run()
    faulted = _swarm(proto)
    boundary = install_retry(
        faulted, RetryPolicy(max_retries=2, backoff_base=0.001),
        FaultPlan().raise_at(2, count=1))
    out = faulted.run()
    assert boundary.retries == 1
    assert out.end_condition == base.end_condition
    assert out.witness.trace == base.witness.trace


# -------------------------------------------- overflow accounting

def test_walker_overflow_counted_and_warned():
    """The satellite bugfix: a capacity-truncated walker step restarts
    LOUDLY — counted on SearchOutcome.swarm_overflow (with
    walker_restarts alongside) and warned about past the threshold —
    where the old rollout probe restarted silently."""
    # net_cap 4 cannot hold the depth the walkers reach: truncated
    # steps are guaranteed.
    proto = _violating(make_clientserver_protocol(n_clients=2, w=3,
                                                  net_cap=4))
    sw = _swarm(proto, max_steps=48, steps_per_round=48, max_rounds=2)
    with pytest.warns(RuntimeWarning, match="capacity-truncated"):
        out = sw.run()
    assert out.swarm_overflow > 0
    assert out.walker_restarts > 0
    assert out.swarm["overflow_restarts"] == out.swarm_overflow


def test_strict_swarm_raises_on_truncation():
    """Strict swarms keep the PR-1 overflow contract's strict half: a
    truncated step raises CapacityOverflow instead of degrading."""
    proto = _violating(make_clientserver_protocol(n_clients=2, w=3,
                                                  net_cap=4))
    sw = _swarm(proto, max_steps=48, steps_per_round=48, max_rounds=2,
                strict=True)
    with pytest.raises(CapacityOverflow):
        sw.run()


# ------------------------------------------------------- portfolio

def _lock_sup(proto, mesh, max_secs, **kw):
    return SearchSupervisor(
        proto, ladder=("sharded",), mesh=mesh, chunk=1024,
        frontier_cap=1 << 14, visited_cap=1 << 18, strict=False,
        max_secs=max_secs, **kw)


def test_portfolio_beats_bfs_on_deep_narrow():
    """The ISSUE 5 acceptance: on a deep-narrow violation with a fixed
    wall-clock budget, BFS alone returns TIME_EXHAUSTED;
    SearchSupervisor(portfolio=True) returns the violation through the
    swarm lane, the witness replays to the same predicate result, and
    the minimized trace is no longer than the raw one."""
    # Unsaturated noise (22 bits) keeps level sizes at the beam cap,
    # so the kept beam is the genealogically-leftmost subtree — the
    # golden path's append position (~slot0 * m^(d-1)) falls out of it
    # by level 5, and the BFS lane measurably stalls (depth 11 after
    # 60 s on the CPU mesh) while a walker reaches depth k in ~m*k
    # random steps.
    proto = make_lock_protocol(m=8, k=12, noise_bits=22)
    mesh = make_mesh(2)
    bfs = _lock_sup(proto, mesh, max_secs=2.5).run()
    assert bfs.end_condition == "TIME_EXHAUSTED"

    sup = _lock_sup(
        proto, mesh, max_secs=90.0, portfolio=True,
        swarm_kwargs=dict(mesh=mesh, walkers_per_device=24,
                          max_steps=240, steps_per_round=64, seed=0,
                          visited_cap=1 << 14))
    out = sup.run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.engine == "swarm"
    assert out.predicate_name == "LOCK_HELD"
    w = out.witness
    assert w.replay_verified
    assert len(w.trace) <= len(w.raw_trace)
    # The lock needs exactly k good digits: the minimizer must land on
    # the true minimal witness.
    assert len(w.trace) == 12
    # The losing BFS lane was cancelled, not left to burn its budget.
    assert sup.lanes["bfs"].cancelled
    # Replay the minimized witness manually: same predicate result.
    from dslabs_tpu.tpu.engine import flatten_state
    from dslabs_tpu.tpu.swarm import replay_events

    sw = SwarmSearch(proto, mesh=mesh, walkers_per_device=8)
    root = np.asarray(flatten_state(sw.initial_state()))[0]
    row, applied = replay_events(sw, root, w.trace)
    assert applied == len(w.trace)
    end = sw.unflatten_rows(jnp.asarray(row)[None])
    assert int(np.asarray(end["nodes"])[0, 0]) == 12


def test_portfolio_exhaustive_bfs_verdict_wins():
    """With no violation in the space, the portfolio returns the BFS
    lane's exhaustive verdict (swarm TIME_EXHAUSTED never outranks
    SPACE_EXHAUSTED) and cancels the walkers."""
    pp = make_pingpong_protocol(2)
    proto = dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
    mesh = make_mesh(2)
    base = SearchSupervisor(proto, ladder=("sharded",), mesh=mesh,
                            chunk=16, frontier_cap=1 << 8,
                            visited_cap=1 << 10).run()
    assert base.end_condition == "SPACE_EXHAUSTED"
    sup = SearchSupervisor(
        proto, ladder=("sharded",), mesh=mesh, chunk=16,
        frontier_cap=1 << 8, visited_cap=1 << 10, portfolio=True,
        max_secs=60.0,
        swarm_kwargs=dict(mesh=mesh, walkers_per_device=8,
                          max_steps=16, steps_per_round=16, seed=1,
                          visited_cap=1 << 10))
    out = sup.run()
    assert out.end_condition == "SPACE_EXHAUSTED"
    assert out.unique_states == base.unique_states


# ------------------------------------------- deep-narrow, swarm-smoke

@pytest.mark.slow
@pytest.mark.perf
def test_portfolio_deep_narrow_paxos():
    """Deep-narrow on a REAL protocol twin (lab 3 paxos): completing
    two client commands through leader election + two Paxos instances
    sits far deeper than a seconds-budget BFS clears, but the
    portfolio's swarm lane lands it with a verified witness (`make
    swarm-smoke`)."""
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol

    proto = _violating(make_paxos_protocol(n=3, n_clients=1, w=2,
                                           max_slots=3))
    mesh = make_mesh(2)
    bfs = _lock_sup(proto, mesh, max_secs=3.0).run()
    assert bfs.end_condition == "TIME_EXHAUSTED"
    sup = _lock_sup(
        proto, mesh, max_secs=240.0, portfolio=True,
        swarm_kwargs=dict(mesh=mesh, walkers_per_device=64,
                          max_steps=192, steps_per_round=64, seed=0,
                          visited_cap=1 << 16))
    out = sup.run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.engine == "swarm"
    assert out.witness.replay_verified
    assert len(out.witness.trace) <= len(out.witness.raw_trace)
    assert len(out.witness.trace) >= bfs.depth


@pytest.mark.slow
@pytest.mark.perf
def test_deep_narrow_lab4_shardstore_swarm():
    """Deep-narrow on the lab 4 shardstore twin: the swarm reaches the
    deep completion state a bounded BFS cannot (`make swarm-smoke`)."""
    from dslabs_tpu.tpu.specs_lab4 import \
        make_shardstore_protocol

    base = make_shardstore_protocol(groups_of=[1, 2])
    proto = _violating(base)
    sw = SwarmSearch(proto, mesh=make_mesh(2), walkers_per_device=64,
                     max_steps=192, steps_per_round=64, seed=0,
                     visited_cap=1 << 16, max_secs=240.0)
    out = sw.run()
    assert out.end_condition == "INVARIANT_VIOLATED"
    assert out.witness.replay_verified
