"""Soundness sanitizer (ISSUE 10, dslabs_tpu/analysis/).

The contract under test:

* **red fixtures** — every rule (C1-C4 conformance, J0-J5 jaxpr) has a
  deliberately-violating fixture asserting the EXACT finding code, so
  a rule that silently stops firing is a test failure, not quiet rot;
* **clean pins** — the shipped tree lints clean (zero unwaived
  conformance findings over specs/protocols/adapters/labs) and the
  pingpong superstep + promote programs audit clean on BOTH engines
  under JAX_PLATFORMS=cpu;
* **compile gate** — malformed ProtocolSpecs raise structured
  ``SpecError`` naming the handler and field at ``compile()`` time
  (the bare-KeyError shape is retired);
* **waivers + CLI** — the waiver file suppresses (but still reports)
  findings; the CLI exits 1 on unwaived findings, 0 otherwise;
* **build-time hook** — ``DSLABS_SANITIZE=1`` audits at engine build
  and records telemetry events; off is off (the overhead guard in
  tests/test_telemetry.py pins zero added dispatches/transfers).

``make analysis-smoke`` runs this file plus the CLI end to end.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dslabs_tpu.analysis import (apply_waivers, load_waivers,  # noqa: E402
                                 run_conformance)
from dslabs_tpu.analysis import main as analysis_main  # noqa: E402
from dslabs_tpu.analysis.conformance import (check_spec,  # noqa: E402
                                             lint_source)
from dslabs_tpu.analysis.jaxpr_audit import (audit_search,  # noqa: E402
                                             audit_sites)
from dslabs_tpu.tpu.compiler import (Field, MessageType,  # noqa: E402
                                     NodeKind, ProtocolSpec, SpecError,
                                     TimerType)
from dslabs_tpu.tpu.protocols.pingpong import \
    make_pingpong_protocol  # noqa: E402

pytestmark = pytest.mark.analysis

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(findings):
    return sorted({f.code for f in findings})


# ------------------------------------------------- red fixtures: C1-C3

def test_c1_payload_mutation_object_and_spec_handlers():
    src = textwrap.dedent("""
        class FooNode(Node):
            def handle_Req(self, message, sender):
                message.seq = 1
                message.entries.append(3)
            def on_Tick(self, timer):
                timer.count += 1

        @spec.on("server", "REQ")
        def srv(ctx, m):
            m["i"] = 3
    """)
    found = lint_source(src, "fixture.py")
    c1 = [f for f in found if f.code == "C1"]
    assert len(c1) == 4
    assert {f.obj for f in c1} == {"FooNode.handle_Req",
                                   "FooNode.on_Tick", "srv"}
    assert all(f.leg == "conformance" for f in c1)


def test_c1_alias_mutable_state_into_send_and_copy_exemption():
    src = textwrap.dedent("""
        class FooNode(Node):
            def __init__(self, address):
                self.log = []
                self.acks: Dict[int, int] = {}
            def handle_Req(self, message, sender):
                self.send(Reply(self.log), sender)          # finding
                self.send(Reply(list(self.log)), sender)    # copied: ok
                self.send(Reply(clone(self.acks)), sender)  # cloned: ok
                self.broadcast(Reply(self.acks), sender)    # finding
    """)
    c1 = [f for f in lint_source(src, "fixture.py") if f.code == "C1"]
    assert len(c1) == 2
    assert all("aliases mutable node state" in f.message for f in c1)


def test_c2_nondeterminism_variants():
    src = textwrap.dedent("""
        import random, time
        class FooNode(Node):
            def __init__(self, address):
                self.peers = set()
            def handle_Req(self, message, sender):
                a = random.randint(0, 3)
                b = time.time()
                c = id(message)
                for p in self.peers:
                    self.send(Reply(1), p)
                for p in sorted(self.peers):   # canonical order: ok
                    pass
    """)
    c2 = [f for f in lint_source(src, "fixture.py") if f.code == "C2"]
    assert len(c2) == 4
    msgs = " ".join(f.message for f in c2)
    assert "randomness" in msgs and "wall clock" in msgs
    assert "identity" in msgs and "unordered set" in msgs


def test_c3_hash_hostile_state_public_only():
    src = textwrap.dedent("""
        import numpy as np
        class FooNode(Node):
            def __init__(self, address):
                self.weights = np.zeros(4)     # finding
                self.pick = lambda x: x        # finding
                self._scratch = np.zeros(4)    # private: excluded
    """)
    c3 = [f for f in lint_source(src, "fixture.py") if f.code == "C3"]
    assert {f.obj for f in c3} == {"FooNode.weights", "FooNode.pick"}


# -------------------------------------- red fixtures: C4 compile gate

def _bad_field_spec():
    sp = ProtocolSpec("bad", nodes=[NodeKind("n", 1, (Field("x"),))],
                      messages=[MessageType("M", ("i",))], timers=[])

    @sp.on("n", "M")
    def h(ctx, m):
        ctx.put("y", m["i"])
    return sp


def test_c4_compile_raises_structured_spec_error_undeclared_field():
    sp = _bad_field_spec()
    with pytest.raises(SpecError) as ei:
        sp.compile()
    e = ei.value
    assert e.code == "C4" and e.handler == "h" and e.field == "y"
    assert e.kind == "n" and e.line
    assert "undeclared field 'y'" in str(e)


def test_c4_compile_raises_on_unknown_message_registration():
    sp = ProtocolSpec("bad2", nodes=[NodeKind("n", 1, ())],
                      messages=[MessageType("M", ())], timers=[])

    @sp.on("n", "NOPE")
    def h(ctx, m):
        pass
    with pytest.raises(SpecError, match="unknown message 'NOPE'"):
        sp.compile()


def test_c4_compile_raises_on_unknown_kind_and_payload_read():
    sp = ProtocolSpec("bad3", nodes=[NodeKind("n", 1, ())],
                      messages=[MessageType("M", ("i",))], timers=[])

    @sp.on("ghost", "M")
    def h(ctx, m):
        pass
    with pytest.raises(SpecError, match="unknown node kind 'ghost'"):
        sp.compile()

    sp2 = ProtocolSpec("bad4", nodes=[NodeKind("n", 1, ())],
                       messages=[MessageType("M", ("i",))], timers=[])

    @sp2.on("n", "M")
    def h2(ctx, m):
        _ = m["zz"]
    with pytest.raises(SpecError, match="not declared by 'M'") as ei:
        sp2.compile()
    assert ei.value.handler == "h2"


def test_c4_send_of_undeclared_message_and_fields():
    sp = ProtocolSpec("bad5", nodes=[NodeKind("n", 1, ())],
                      messages=[MessageType("M", ("i",))],
                      timers=[TimerType("T", ())])

    @sp.on("n", "M")
    def h(ctx, m):
        ctx.send("GHOST", 0, i=1)
    with pytest.raises(SpecError, match="undeclared message 'GHOST'"):
        sp.compile()

    sp2 = ProtocolSpec("bad6", nodes=[NodeKind("n", 1, ())],
                       messages=[MessageType("M", ("i",))], timers=[])

    @sp2.on("n", "M")
    def h2(ctx, m):
        ctx.send("M", 0, i=1, zz=2)
    with pytest.raises(SpecError, match="unknown fields \\['zz'\\]"):
        sp2.compile()


def test_c4_check_spec_reports_unhandled_declared_types():
    sp = ProtocolSpec(
        "soft", nodes=[NodeKind("n", 1, ())],
        messages=[MessageType("M", ()), MessageType("DEAD", ())],
        timers=[TimerType("TICK", ())])

    @sp.on("n", "M")
    def h(ctx, m):
        pass
    found = check_spec(sp, origin="fixture")
    assert _codes(found) == ["C4"]
    msgs = " ".join(f.message for f in found)
    assert "'DEAD' has no handler" in msgs
    assert "'TICK' has no handler" in msgs
    sp.compile()          # soft findings do NOT fail the compile gate


# ------------------------------------------- red fixtures: C5 symmetry

_C5_RED = textwrap.dedent("""
    from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                         ProtocolSpec)

    spec = ProtocolSpec(
        "sym", nodes=[NodeKind("acceptor", 3, (Field("b"),))],
        messages=[MessageType("M", ())], timers=[],
        symmetry=("acceptor",))

    @spec.on("acceptor", "M")
    def h(ctx, m):
        me = ctx.node_index()
        ctx.put("b", 1, when=me == 1)        # member-specific branch
""")


def test_c5_symmetric_kind_branching_on_node_id():
    """ISSUE 15 red fixture: a handler on a kind inside a declared
    symmetry group comparing node_index() (here through a tainted
    local) against a constant is flagged C5."""
    c5 = [f for f in lint_source(_C5_RED, "fixture.py")
          if f.code == "C5"]
    assert len(c5) == 1
    assert c5[0].obj == "h"
    assert "interchangeable" in c5[0].message


def test_c5_clean_counterparts():
    """The symmetry-safe styles stay clean: identifying peers via
    _from, comparing tainted values against payloads (not constants),
    and the same constant-branching handler on a kind OUTSIDE the
    symmetry declaration."""
    clean = _C5_RED.replace("me == 1", 'm["_from"] == me')
    assert [f.code for f in lint_source(clean, "f.py")] == []
    outside = _C5_RED.replace('symmetry=("acceptor",)', "symmetry=()")
    assert [f.code for f in lint_source(outside, "f.py")] == []


def test_c5_direct_comparison_and_rules_catalog():
    src = _C5_RED.replace(
        "me = ctx.node_index()\n"
        "    ctx.put(\"b\", 1, when=me == 1)        "
        "# member-specific branch",
        "ctx.put(\"b\", 1, when=ctx.node_index() == 2)")
    c5 = [f for f in lint_source(src, "fixture.py") if f.code == "C5"]
    assert len(c5) == 1
    from dslabs_tpu.analysis.core import RULES

    assert "C5" in RULES and "symmetry" in RULES["C5"]


def test_c5_compile_gate_guards_group_declarations():
    """The compile gate's half of C5: unknown group kinds and
    malformed index_group declarations raise structured SpecErrors."""
    sp = ProtocolSpec("s1", nodes=[NodeKind("n", 2, ())],
                      messages=[MessageType("M", ())], timers=[],
                      symmetry=("ghost",))
    with pytest.raises(SpecError, match="unknown node kind 'ghost'"):
        sp.compile()
    sp2 = ProtocolSpec(
        "s2",
        nodes=[NodeKind("p", 1, (Field("x", size=3,
                                       index_group="a"),)),
               NodeKind("a", 2, ())],
        messages=[MessageType("M", ())], timers=[], symmetry=("a",))
    with pytest.raises(SpecError, match="size 3 but index_group"):
        sp2.compile()


# ------------------- red fixtures: C5 slot/quorum reads (ISSUE 20)

_C5_REP_RED = textwrap.dedent("""
    from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                         ProtocolSpec)
    from dslabs_tpu.tpu.quorum import QuorumCount

    spec = ProtocolSpec(
        "rep",
        nodes=[NodeKind("proposer", 1,
                        (Field("seen", size=3,
                               index_group="acceptor"),
                         Field("bv", hi=7))),
               NodeKind("acceptor", 3, (Field("b"),))],
        messages=[MessageType("M", ())], timers=[],
        symmetry=("acceptor",),
        quorums=(QuorumCount("q", over="acceptor",
                             threshold="majority"),))

    @spec.on("proposer", "M")
    def h(ctx, m):
        ctx.put_at("seen", 2, 1)          # fixed member's element
""")


def test_c5_constant_index_into_symmetric_group_array():
    """ISSUE 20 red fixture: get_at/put_at of an index_group array
    over a symmetric kind at an integer-constant index is
    member-specific — flagged C5 even though the handler's own kind
    is outside the symmetry group."""
    c5 = [f for f in lint_source(_C5_REP_RED, "fixture.py")
          if f.code == "C5"]
    assert len(c5) == 1
    assert c5[0].obj == "h"
    assert "index_group" in c5[0].message
    assert "'acceptor'" in c5[0].message


def test_c5_quorum_constant_bitmask():
    """ISSUE 20 red fixture: met_bits/count_bits of a quorum over a
    symmetric kind fed a constant bitmask names members by bit."""
    src = _C5_REP_RED.replace(
        'ctx.put_at("seen", 2, 1)          # fixed member\'s element',
        'ctx.put("bv", ctx.quorum("q").met_bits(5))')
    c5 = [f for f in lint_source(src, "fixture.py") if f.code == "C5"]
    assert len(c5) == 1
    assert "bitmask" in c5[0].message and "'q'" in c5[0].message


def test_c5_slot_quorum_clean_counterparts():
    """The symmetric-safe styles stay clean: indexing the group array
    by the sender, feeding the quorum reducer the protocol's own
    vote-bit field, a constant index into a NON-group array, and the
    same red bodies with the symmetry declaration removed."""
    by_from = _C5_REP_RED.replace(
        'ctx.put_at("seen", 2, 1)', 'ctx.put_at("seen", m["_from"], 1)')
    assert [f.code for f in lint_source(by_from, "f.py")] == []
    own_bits = _C5_REP_RED.replace(
        'ctx.put_at("seen", 2, 1)',
        'ctx.put("bv", ctx.quorum("q").met_bits(ctx.get("bv")))')
    assert [f.code for f in lint_source(own_bits, "f.py")] == []
    non_group = _C5_REP_RED.replace(
        'ctx.put_at("seen", 2, 1)', 'ctx.put_at("bv", 0, 1)')
    assert [f.code for f in lint_source(non_group, "f.py")] == []
    asym = _C5_REP_RED.replace('symmetry=("acceptor",),', "")
    assert [f.code for f in lint_source(asym, "f.py")] == []


def test_c4_check_spec_flags_untouched_slots_and_quorums():
    """ISSUE 20 soft C4: the budget dry-run records which Slots blocks
    and quorums handlers touch; declared-but-unreached ones are dead
    lanes in every packed row.  Touching both clears the findings."""
    from dslabs_tpu.tpu.quorum import QuorumCount
    from dslabs_tpu.tpu.slots import SlotField, Slots

    def build(touch):
        sp = ProtocolSpec(
            "dead", nodes=[NodeKind("n", 3, (
                Field("x", hi=3),
                Slots("log", 2, base=1,
                      fields=(SlotField("cmd", hi=3),))))],
            messages=[MessageType("M", ())], timers=[],
            quorums=(QuorumCount("q", over="n",
                                 threshold="majority"),))

        @sp.on("n", "M")
        def h(ctx, m):
            if touch:
                ctx.slot_put("log", "cmd", 1, 2)
                ctx.put("x", ctx.quorum("q").met_bits(ctx.get("x")))
            else:
                ctx.put("x", 1)
        return sp

    found = check_spec(build(False), origin="fixture")
    assert _codes(found) == ["C4"]
    msgs = " ".join(f.message for f in found)
    assert "Slots block 'log'" in msgs and "dead lanes" in msgs
    assert "quorum 'q'" in msgs and "never read" in msgs
    assert check_spec(build(True), origin="fixture") == []


def test_c4_unhandled_but_sent_message_is_dead_letter_clean():
    """The dead-letter idiom (a message some handler sends to an
    address that ignores it — the lab4 reconfig-debris rows) is NOT
    an unhandled-message finding; an unsent+unhandled one still is
    (see test_c4_check_spec_reports_unhandled_declared_types)."""
    sp = ProtocolSpec(
        "dl", nodes=[NodeKind("n", 2, (Field("x"),))],
        messages=[MessageType("M", ()), MessageType("DEBRIS", ())],
        timers=[])

    @sp.on("n", "M")
    def h(ctx, m):
        ctx.send("DEBRIS", to=1)
    assert check_spec(sp, origin="fixture") == []


# ------------------------------------------- red fixtures: jaxpr J0-J5

def _entry(fn, args, donate=(), multi=False, builder=None):
    return dict(fn=fn, args=args, donate=donate, multi=multi,
                builder=builder)


def test_j0_unregistered_site_and_unlowerable_program():
    fn = jax.jit(lambda x: x + 1)
    sds = jax.ShapeDtypeStruct((4,), jnp.int32)
    found = audit_sites({"bogus.site": _entry(fn, (sds,))}, "Fixture")
    assert _codes(found) == ["J0"]
    assert "DISPATCH_SITES" in found[0].message

    def broken(x):
        raise RuntimeError("trace bomb")
    found = audit_sites(
        {"device.promote": _entry(jax.jit(broken), (sds,))}, "Fixture")
    assert _codes(found) == ["J0"]
    assert "failed to lower" in found[0].message


def test_j1_host_callback_in_program():
    def prog(x):
        jax.debug.print("leak {}", x[0])
        return x + 1
    sds = jax.ShapeDtypeStruct((4,), jnp.int32)
    found = audit_sites(
        {"device.step": _entry(jax.jit(prog), (sds,))}, "Fixture")
    assert "J1" in _codes(found)
    assert "host callback" in found[0].message


def test_j2_float64_upcast():
    def prog(x):
        return x.astype(jnp.float64) * 1.5
    sds = jax.ShapeDtypeStruct((4,), jnp.int32)
    from jax.experimental import enable_x64

    with enable_x64():
        found = audit_sites(
            {"device.promote": _entry(jax.jit(prog), (sds,))},
            "Fixture")
    assert _codes(found) == ["J2"]


def test_j3_large_carry_not_donated():
    big = jax.ShapeDtypeStruct((512, 512), jnp.int32)   # 1 MiB
    fn = jax.jit(lambda c: c * 2)                        # NO donation
    found = audit_sites(
        {"device.step": _entry(fn, (big,), donate=(0,))}, "Fixture")
    assert _codes(found) == ["J3"]
    assert "no input/output aliasing" in found[0].message
    # The genuinely-donated twin of the same program audits clean.
    ok = jax.jit(lambda c: c * 2, donate_argnums=0)
    assert audit_sites(
        {"device.step": _entry(ok, (big,), donate=(0,))},
        "Fixture") == []


def test_j4_collective_in_single_device_program():
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:                      # pragma: no cover
        from jax.sharding import shard_map

    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("d",))
    fn = jax.jit(shard_map(lambda x: jax.lax.psum(x, "d"), mesh=mesh,
                           in_specs=P("d"), out_specs=P()))
    sds = jax.ShapeDtypeStruct((8, 4), jnp.int32)
    found = audit_sites(
        {"device.promote": _entry(fn, (sds,), multi=False)}, "Fixture")
    assert _codes(found) == ["J4"]
    assert "all_reduce" in found[0].message
    # The same program declared multi-device audits clean.
    assert audit_sites(
        {"sharded.promote": _entry(fn, (sds,), multi=True)},
        "Fixture") == []


def test_j4_collective_leaking_into_width1_build():
    """ISSUE 12 red fixture: the REAL width-1 sharded superstep build
    still lowers its mesh collectives (identity all_to_all/psum become
    all_reduce over a one-element group) — registered as a
    single-device program (multi=False) it must be a loud J4, which is
    exactly the drift J4 exists to catch: a program built against the
    wrong mesh scope leaking collectives into the single-chip bench
    path.  Registered honestly (the registry's multi=True for
    sharded.*), the same build audits clean."""
    import dataclasses

    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    pp = make_pingpong_protocol(workload_size=2)
    proto = dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
    search = ShardedTensorSearch(proto, make_mesh(1),
                                 chunk_per_device=16,
                                 frontier_cap=1 << 8,
                                 visited_cap=1 << 10)
    sites = search.dispatch_site_programs()
    entry = dict(sites["sharded.superstep"], multi=False)
    found = audit_sites({"width1.superstep": entry}, "Fixture")
    assert "J4" in _codes(found)            # the red shape
    # The honest registration (registry multi=True) is clean end to
    # end — the standing zero-findings pin covers it, re-asserted here
    # for the fused-exchange build specifically.
    assert [f for f in audit_sites(sites, "ShardedTensorSearch")
            if f.code == "J4"] == []


def test_j5_retrace_hazard_fresh_constants_per_build():
    sds = jax.ShapeDtypeStruct((8,), jnp.float32)

    def churning_builder():
        consts = np.random.rand(8).astype(np.float32)   # fresh/build
        return jax.jit(lambda x: x + consts)

    found = audit_sites(
        {"device.promote": _entry(churning_builder(), (sds,),
                                  builder=churning_builder)},
        "Fixture", deep=True)
    assert _codes(found) == ["J5"]

    stable = np.ones(8, np.float32)

    def stable_builder():
        return jax.jit(lambda x: x + stable)

    assert audit_sites(
        {"device.promote": _entry(stable_builder(), (sds,),
                                  builder=stable_builder)},
        "Fixture", deep=True) == []


# ----------------------------------------------------- clean-pass pins

def test_shipped_tree_conformance_clean():
    """ACCEPTANCE: the shipped specs/protocols/adapters/labs lint
    clean modulo the documented waiver file."""
    findings = run_conformance()
    live = [f for f in findings if not f.waived]
    assert live == [], "\n".join(f.render() for f in live)


def test_jaxpr_zero_findings_pingpong_both_engines():
    """ACCEPTANCE: the pingpong superstep+promote (sharded) and
    step+promote (single-device) programs audit clean under
    JAX_PLATFORMS=cpu."""
    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    proto = make_pingpong_protocol(workload_size=2)
    dev = TensorSearch(proto, max_depth=8, frontier_cap=1 << 8,
                       visited_cap=1 << 10)
    assert audit_search(dev) == []
    sh = ShardedTensorSearch(proto, make_mesh(8), chunk_per_device=16,
                             frontier_cap=1 << 8, visited_cap=1 << 10,
                             max_depth=8)
    sites = sh.dispatch_site_programs()
    assert {"sharded.superstep", "sharded.promote"} <= set(sites)
    assert audit_sites(sites, "ShardedTensorSearch") == []


@pytest.mark.slow
def test_jaxpr_deep_retrace_clean_pingpong():
    """The J5 double-trace on the real engines: rebuilding the
    superstep/step/promote programs lowers bit-identically, so warden
    children and failover rungs keep hitting the compile cache."""
    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    proto = make_pingpong_protocol(workload_size=2)
    assert audit_search(
        TensorSearch(proto, max_depth=8, frontier_cap=1 << 8,
                     visited_cap=1 << 10), deep=True) == []
    assert audit_search(
        ShardedTensorSearch(proto, make_mesh(2), chunk_per_device=16,
                            frontier_cap=1 << 8, visited_cap=1 << 10,
                            max_depth=8), deep=True) == []


# ------------------------------------------------------ waivers + CLI

def test_waiver_file_suppresses_but_reports(tmp_path):
    wf = tmp_path / "waivers"
    wf.write_text("# test waivers\n"
                  "C1 fixture.py::FooNode.* known-shared reply buffer\n")
    src = textwrap.dedent("""
        class FooNode(Node):
            def handle_Req(self, message, sender):
                message.seq = 1
    """)
    found = apply_waivers(lint_source(src, "fixture.py"),
                          load_waivers(str(wf)))
    assert len(found) == 1 and found[0].waived
    assert found[0].waiver == "known-shared reply buffer"


def test_waiver_file_malformed_line_is_loud(tmp_path):
    wf = tmp_path / "waivers"
    wf.write_text("C1 only-two-fields\n")
    with pytest.raises(ValueError, match="waiver needs"):
        load_waivers(str(wf))
    wf.write_text("Q9 x::y reason\n")
    with pytest.raises(ValueError, match="unknown rule code"):
        load_waivers(str(wf))


def test_cli_rc_contract(tmp_path, capsys):
    # conformance over an explicit violating file -> rc 1 + findings
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        class FooNode(Node):
            def handle_Req(self, message, sender):
                message.seq = 1
    """))
    rc = analysis_main(["conformance", "--paths", str(bad), "--json"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and out["findings"] == 1
    assert out["detail"][0]["code"] == "C1"
    # same file, waived -> rc 0, finding still reported
    wf = tmp_path / "waivers"
    wf.write_text(f"C1 {bad}::* justified for the fixture\n")
    rc = analysis_main(["conformance", "--paths", str(bad),
                        "--waivers", str(wf), "--json"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and out["findings"] == 0 and out["waived"] == 1


@pytest.mark.slow
def test_cli_all_subprocess_clean():
    """ACCEPTANCE: `python -m dslabs_tpu.analysis all` exits 0 on the
    shipped tree (modulo documented waivers)."""
    proc = subprocess.run(
        [sys.executable, "-m", "dslabs_tpu.analysis", "all", "--json"],
        capture_output=True, text=True, cwd=ROOT, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    assert data["findings"] == 0
    assert data["conformance"] == 0 and data["jaxpr"] == 0


# ------------------------------------------------- build-time sanitize

def test_sanitize_hook_records_telemetry_events(monkeypatch):
    """DSLABS_SANITIZE=1 audits at engine build time and records
    findings as telemetry events (fixture: hide a tag from the site
    registry so the audit has something to find)."""
    from dslabs_tpu.tpu import telemetry as tel_mod
    from dslabs_tpu.tpu.engine import TensorSearch

    monkeypatch.setenv("DSLABS_SANITIZE", "1")
    sites = {k: v for k, v in tel_mod.DISPATCH_SITES.items()
             if k != "device.promote"}
    monkeypatch.setattr(tel_mod, "DISPATCH_SITES", sites)
    tel = tel_mod.Telemetry()
    with pytest.warns(RuntimeWarning, match="jaxpr-audit finding"):
        TensorSearch(make_pingpong_protocol(2), max_depth=8,
                     frontier_cap=1 << 8, visited_cap=1 << 10,
                     telemetry=tel)
    evs = [e for e in tel.events if e.get("kind") == "sanitizer_finding"]
    assert evs and evs[0]["code"] == "J0"
    assert evs[0]["site"] == "device.promote"


def test_sanitize_off_is_off(monkeypatch):
    """No DSLABS_SANITIZE -> the hook is one env read: no audit, no
    events, no warning (the dispatch/transfer half of this guarantee
    is pinned by the test_telemetry overhead guard)."""
    from dslabs_tpu.tpu import telemetry as tel_mod
    from dslabs_tpu.tpu.engine import TensorSearch

    monkeypatch.delenv("DSLABS_SANITIZE", raising=False)
    called = []
    import dslabs_tpu.analysis.jaxpr_audit as ja

    monkeypatch.setattr(ja, "audit_search",
                        lambda *a, **k: called.append(1) or [])
    tel = tel_mod.Telemetry()
    TensorSearch(make_pingpong_protocol(2), max_depth=8,
                 frontier_cap=1 << 8, visited_cap=1 << 10,
                 telemetry=tel)
    assert not called
    assert not [e for e in tel.events
                if e.get("kind") == "sanitizer_finding"]


# --------------------------------------------- ledger compare + bench

def test_compare_ledger_flags_sanitizer_regression():
    from dslabs_tpu.tpu.telemetry import compare_ledger

    prior = {"t": "bench", "value": 100.0,
             "sanitizer": {"findings": 0, "conformance": 0, "jaxpr": 0,
                           "waived": 0}}
    worse = {"t": "bench", "value": 100.0,
             "sanitizer": {"findings": 2, "conformance": 1, "jaxpr": 1,
                           "waived": 0}}
    cmp = compare_ledger([prior, worse])
    regressed = {e["phase"] for e in cmp["regressions"]}
    assert "sanitizer:findings" in regressed
    # parity: equal findings is not a regression
    cmp = compare_ledger([prior, dict(prior)])
    assert not any(e["phase"].startswith("sanitizer")
                   for e in cmp["regressions"])
    # waived findings never count (summary only carries live counts)
    assert cmp["sanitizer"]["findings"]["latest"] == 0


def test_run_tests_lint_flag(tmp_path, capsys):
    """run_tests.py --lint runs the conformance pass before the labs
    and passes on the (clean) shipped tree."""
    sys.path.insert(0, ROOT)
    try:
        import run_tests as rt

        rc = rt.main(["--lint", "--replay-traces"])
    finally:
        sys.path.remove(ROOT)
    out = capsys.readouterr().out
    assert rc == 0
    assert "conformance lint" in out
