"""Assemble the student handout distribution (the reference's
handout-files/ + build.gradle handout assembly, re-designed for a pure
Python tree): copy the framework, tests, and driver, and replace every
lab SOLUTION with an AST-stripped SKELETON — class/function signatures
and docstrings kept, every solution method body replaced by
``raise NotImplementedError`` — so students receive exactly the surface
the scored tests drive.

    python tools/handout.py [--out handout] [--tar]

What ships:
  dslabs_tpu/            framework (core/testing/search/runner/harness/
                         viz/utils/tpu) — unchanged
  dslabs_tpu/labs/       SKELETONS (bodies stripped)
  tests/ run_tests.py    the scored suites + CLI driver, unchanged
  Makefile README.md     entry points

What is kept verbatim inside labs/ (students build on top of these the
way the reference hands out AMOCommand/KVStore scaffolding): module
docstrings, dataclass field declarations, constants, and __init__
bodies — only handler/logic methods are stripped.
"""

from __future__ import annotations

import argparse
import ast
import os
import shutil
import sys
import tarfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHIP = ["dslabs_tpu", "tests", "run_tests.py", "bench.py", "Makefile",
        "README.md", "docs", "__graft_entry__.py"]
# Instructor-only material and SOLUTION MIRRORS never ship: the tensor
# protocol twins + compiler specs are handler-for-handler readable
# reimplementations of the lab solutions (their module docstrings say
# so), and the adapters embed the same logic — handing them out would
# defeat the skeleton stripping.  The tensor ENGINE ships (it is
# framework); twin resolution then fails loudly with NoTensorTwin for
# students, and the default object search path is unaffected.
OMIT = [
    "dslabs_tpu/tpu/protocols",
    "dslabs_tpu/tpu/specs.py",
    "dslabs_tpu/tpu/adapters",
    "grading",
]
# Lab modules whose logic methods are the assignment (stripped); the
# scaffolding modules (amo, kv_workload, workloads, predicates) ship
# verbatim like the reference's handed-out utility classes.
STRIP = {
    "dslabs_tpu/labs/pingpong/pingpong.py",
    "dslabs_tpu/labs/clientserver/clientserver.py",
    "dslabs_tpu/labs/primarybackup/viewserver.py",
    "dslabs_tpu/labs/primarybackup/pb.py",
    "dslabs_tpu/labs/paxos/paxos.py",
    "dslabs_tpu/labs/shardedstore/shardmaster.py",
    "dslabs_tpu/labs/shardedstore/shardstore.py",
    "dslabs_tpu/labs/shardedstore/txkvstore.py",
}
# Methods every node needs untouched for the harness to even load.
KEEP_METHODS = {"__init__", "__post_init__"}


class _Stripper(ast.NodeTransformer):
    """Replace function bodies with docstring + raise NotImplementedError
    (the skeleton shape of the reference's handed-out lab sources)."""

    def _strip(self, node):
        body = []
        if (node.body and isinstance(node.body[0], ast.Expr)
                and isinstance(node.body[0].value, ast.Constant)
                and isinstance(node.body[0].value.value, str)):
            body.append(node.body[0])
        body.append(ast.Raise(
            exc=ast.Call(
                func=ast.Name(id="NotImplementedError", ctx=ast.Load()),
                args=[ast.Constant(value="Your code here...")],
                keywords=[]),
            cause=None))
        node.body = body
        return node

    def visit_FunctionDef(self, node):
        if node.name in KEEP_METHODS:
            return node
        return self._strip(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def build(out_dir: str, make_tar: bool) -> str:
    out = os.path.abspath(out_dir)
    if os.path.exists(out):
        shutil.rmtree(out)
    os.makedirs(out)
    for item in SHIP:
        src = os.path.join(ROOT, item)
        dst = os.path.join(out, item)
        if not os.path.exists(src):
            continue
        if os.path.isdir(src):
            shutil.copytree(src, dst, ignore=shutil.ignore_patterns(
                "__pycache__", "*.pyc"))
        else:
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copy2(src, dst)
    for rel in OMIT:
        path = os.path.join(out, rel)
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)
    stripped = []
    for rel in sorted(STRIP):
        path = os.path.join(out, rel)
        with open(path) as f:
            tree = ast.parse(f.read())
        tree = _Stripper().visit(tree)
        ast.fix_missing_locations(tree)
        with open(path, "w") as f:
            f.write("# HANDOUT SKELETON — solution bodies stripped; "
                    "implement the raises.\n" + ast.unparse(tree) + "\n")
        stripped.append(rel)
    print(f"handout: {out} ({len(stripped)} lab files stripped)")
    if make_tar:
        tar_path = out + ".tar.gz"
        with tarfile.open(tar_path, "w:gz") as t:
            t.add(out, arcname=os.path.basename(out))
        print(f"handout: {tar_path}")
        return tar_path
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="handout")
    ap.add_argument("--tar", action="store_true")
    args = ap.parse_args(argv)
    build(args.out, args.tar)
    return 0


if __name__ == "__main__":
    sys.exit(main())
