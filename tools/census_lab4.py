"""Dev tool: enumerate the object-checker event space for the lab4
test10 config (1 group, 1 server, 1 master, joined, CCA+master frozen) to
ground the tensor twin's message/timer schema."""

import os
os.environ["JAX_PLATFORMS"] = "cpu"

from collections import Counter

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.predicates import RESULTS_OK, CLIENTS_DONE

import tests.test_lab4_shardstore as t


def main():
    state = t.make_search(1, 1, 1, 10)
    joined = t._joined_state(state, 1)
    from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
    joined.add_client_worker(
        LocalAddress("client1"),
        kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"]))

    settings = SearchSettings().max_time(240)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(t.CCA, False)
    settings.deliver_timers(t.CCA, False)
    settings.deliver_timers(t.shard_master(1), False)

    print("=== nodes:", sorted(str(a) for a in joined.addresses()))
    # BFS by hand, collecting event signatures
    frontier = [joined]
    seen = {joined.search_equivalence_key()}
    msg_types = Counter()
    timer_types = Counter()
    examples = {}
    for depth in range(5):
        nxt = []
        for s in frontier:
            for ev in s.events(settings):
                if hasattr(ev, "message"):
                    k = (type(ev.message).__name__, str(ev.frm),
                         str(ev.to))
                    inner = getattr(ev.message, "command", None) or getattr(
                        ev.message, "result", None)
                    k = k + (type(inner).__name__ if inner else "",)
                    msg_types[k] += 1
                    examples.setdefault(k, ev.message)
                else:
                    k = (type(ev.timer).__name__, str(ev.to))
                    timer_types[k] += 1
                    examples.setdefault(k, ev.timer)
                s2 = s.step_event(ev, settings)
                if s2 is None:
                    continue
                key = s2.search_equivalence_key()
                if key not in seen:
                    seen.add(key)
                    nxt.append(s2)
        frontier = nxt
        print(f"depth {depth+1}: frontier={len(frontier)} seen={len(seen)}")

    print("\n=== message event signatures (type, from, to, payload type):")
    for k, c in sorted(msg_types.items()):
        print(f"  {c:5d}  {k}")
        print(f"         e.g. {examples[k]}")
    print("\n=== timer event signatures:")
    for k, c in sorted(timer_types.items()):
        print(f"  {c:5d}  {k}")
        print(f"         e.g. {examples[k]}")


if __name__ == "__main__":
    main()
