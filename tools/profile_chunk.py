"""Decompose the TPU chunk-step cost: which stage dominates?

Times jitted sub-programs of the bench configuration's expand pipeline
on whatever accelerator is present — a thin client of the telemetry
API (tpu/telemetry.py): each stage is a compile span + N steady spans
and the output is the shared per-site latency table (the old hand-rolled
``bench_fn`` stopwatch scaffold is gone).  Not part of the test suite —
a dev tool."""

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp

from dslabs_tpu.tpu.engine import (TensorSearch, canonicalize_net,
                                   insert_messages, state_fingerprints,
                                   append_timers, flatten_state)
from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol
from dslabs_tpu.tpu.telemetry import Telemetry, render_sites

TEL = Telemetry(engine_hint="profile_chunk")


def timed(name, fn, *args, iters=5):
    """One compile span + ``iters`` steady spans through the telemetry
    recorder; returns the steady mean seconds (for derived rates)."""
    fn = jax.jit(fn)
    with TEL.span(f"profile.{name}.compile"):
        jax.block_until_ready(fn(*args))
    for _ in range(iters):
        with TEL.span(f"profile.{name}"):
            jax.block_until_ready(fn(*args))
    h = TEL.registry.histogram(f"dispatch_secs.profile.{name}")
    return h.total / max(h.count, 1)


def main():
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    C = 256
    search = TensorSearch(protocol, chunk=C)
    state = search.initial_state()
    chunk_state = jnp.repeat(flatten_state(state), C, axis=0)
    chunk_valid = jnp.ones(C, bool)
    ne = search._num_events()
    n_pairs = C * ne
    print(f"chunk={C} events/state={ne} pairs={n_pairs} "
          f"lanes={flatten_state(state).shape[1]}")

    # full expand
    dt = timed("expand_chunk", search._expand_chunk, chunk_state,
               chunk_valid)
    print(f"full _expand_chunk -> {n_pairs/max(dt, 1e-9):,.0f} "
          "explored pairs/s")

    # pieces, over the flattened pair batch
    rep_state = jnp.repeat(chunk_state, ne, axis=0)
    ev = jnp.tile(jnp.arange(ne), C)

    timed("step_one", lambda rs, e: jax.vmap(search._step_one)(rs, e),
          rep_state, ev)

    p = protocol
    rep_states = search.unflatten_rows(rep_state)   # views into the rows
    live = p.max_live_sends or p.max_sends
    sends = jnp.full((n_pairs, live, p.msg_width), 2**31 - 1, jnp.int32)

    timed("insert_messages",
          lambda net, s: jax.vmap(insert_messages)(net, s),
          rep_states["net"], sends)
    timed("canonicalize_net",
          lambda net: jax.vmap(canonicalize_net)(net),
          rep_states["net"])

    new_t = jnp.full((n_pairs, p.max_sets, 1 + p.timer_width), 2**31 - 1,
                     jnp.int32)
    timed("append_timers",
          lambda t, nt: jax.vmap(append_timers)(t, nt),
          rep_states["timers"], new_t)

    from dslabs_tpu.tpu.engine import row_fingerprints

    timed("row_fingerprints", row_fingerprints, rep_state)

    # the in-chunk lexsort
    fp = row_fingerprints(rep_state)

    def sort_only(fp, valids):
        inv = ~valids
        order = jnp.lexsort((fp[:, 3], fp[:, 2], fp[:, 1], fp[:, 0], inv))
        fps = fp[order]
        first = jnp.ones(fps.shape[0], bool).at[1:].set(
            jnp.any(fps[1:] != fps[:-1], axis=1))
        return jnp.zeros_like(valids).at[order].set(first & valids)

    timed("lexsort_unique", sort_only, fp, jnp.ones(n_pairs, bool))

    # predicate flags
    rows_all = jax.vmap(search._step_one)(rep_state, ev)[0]

    def flags_only(rows):
        states = search.unflatten_rows(rows)
        out = {}
        for kind, preds in (("inv", p.invariants), ("goal", p.goals),
                            ("prune", p.prunes)):
            for name, fn in preds.items():
                out[f"{kind}:{name}"] = jax.vmap(fn)(states)
        return out

    timed("predicate_flags", flags_only, rows_all)

    print()
    print(render_sites(TEL.summary()))


if __name__ == "__main__":
    main()
