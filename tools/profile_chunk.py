"""Decompose the TPU chunk-step cost: which stage dominates?

Times jitted sub-programs of the bench configuration's expand pipeline on
whatever accelerator is present. Not part of the test suite — a dev tool.
"""

import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp

from dslabs_tpu.tpu.engine import (TensorSearch, canonicalize_net,
                                   insert_messages, state_fingerprints,
                                   append_timers, flatten_state)
from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol


def bench_fn(name, fn, *args, iters=5):
    fn = jax.jit(fn)
    t0 = time.time()
    out = fn(*args)
    jax.block_until_ready(out)
    print(f"{name:40s} compile+1st {time.time()-t0:6.1f} s")
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / iters
    print(f"{name:40s} {dt*1e3:9.2f} ms")
    return dt


def main():
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    C = 256
    search = TensorSearch(protocol, chunk=C)
    state = search.initial_state()
    chunk_state = jnp.repeat(flatten_state(state), C, axis=0)
    chunk_valid = jnp.ones(C, bool)
    ne = search._num_events()
    n_pairs = C * ne
    print(f"chunk={C} events/state={ne} pairs={n_pairs} "
          f"lanes={flatten_state(state).shape[1]}")

    # full expand
    dt = bench_fn("full _expand_chunk", search._expand_chunk,
                  chunk_state, chunk_valid)
    print(f"  -> {n_pairs/dt:,.0f} explored pairs/s")

    # pieces, over the flattened pair batch
    rep_state = jnp.repeat(chunk_state, ne, axis=0)
    ev = jnp.tile(jnp.arange(ne), C)

    def step_only(rs, e):
        return jax.vmap(search._step_one)(rs, e)

    dt = bench_fn("vmapped _step_one (incl. insert/append)", step_only,
                  rep_state, ev)

    p = protocol
    rep_states = search.unflatten_rows(rep_state)   # views into the rows
    live = p.max_live_sends or p.max_sends
    sends = jnp.full((n_pairs, live, p.msg_width), 2**31 - 1, jnp.int32)

    def ins_only(net, s):
        return jax.vmap(insert_messages)(net, s)

    dt = bench_fn("insert_messages alone", ins_only, rep_states["net"],
                  sends)

    def canon_only(net):
        return jax.vmap(canonicalize_net)(net)

    bench_fn("canonicalize_net alone", canon_only, rep_states["net"])

    new_t = jnp.full((n_pairs, p.max_sets, 1 + p.timer_width), 2**31 - 1,
                     jnp.int32)

    def app_only(t, nt):
        return jax.vmap(append_timers)(t, nt)

    bench_fn("append_timers alone", app_only, rep_states["timers"], new_t)

    from dslabs_tpu.tpu.engine import row_fingerprints

    def fp_only(rs):
        return row_fingerprints(rs)

    bench_fn("row_fingerprints alone", fp_only, rep_state)

    # the in-chunk lexsort
    fp = row_fingerprints(rep_state)

    def sort_only(fp, valids):
        inv = ~valids
        order = jnp.lexsort((fp[:, 3], fp[:, 2], fp[:, 1], fp[:, 0], inv))
        fps = fp[order]
        first = jnp.ones(fps.shape[0], bool).at[1:].set(
            jnp.any(fps[1:] != fps[:-1], axis=1))
        return jnp.zeros_like(valids).at[order].set(first & valids)

    bench_fn("in-chunk lexsort+unique", sort_only, fp,
             jnp.ones(n_pairs, bool))

    # predicate flags
    rows_all = jax.vmap(search._step_one)(rep_state, ev)[0]

    def flags_only(rows):
        states = search.unflatten_rows(rows)
        out = {}
        for kind, preds in (("inv", p.invariants), ("goal", p.goals),
                            ("prune", p.prunes)):
            for name, fn in preds.items():
                out[f"{kind}:{name}"] = jax.vmap(fn)(states)
        return out

    bench_fn("predicate flags alone", flags_only, rows_all)


if __name__ == "__main__":
    main()
