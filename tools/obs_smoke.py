"""Observability smoke driver (`make obs-smoke`, ISSUE 8 satellite):
the end-to-end CLI paths the pytest tier exercises through the API —

1. run a tiny search with a run-dir recorder (flight.jsonl +
   STATUS.json) and render it with ``telemetry watch --once`` and
   ``telemetry report`` (the watch-on-a-finished-run step);
2. build a parity ledger (no flag expected, rc 0) and an
   injected-slow-run ledger (regression flagged, rc 1) and diff both
   with ``telemetry compare`` (the ledger-compare step);
3. (ISSUE 13) run the same search INSIDE a trace context and assemble
   it with ``telemetry trace`` — the causal tree, the trace id on
   every span, ``watch --json``, and the Perfetto export
   (the trace-assembler step).

Exits nonzero on any mismatch; prints one OK line per step."""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache-cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dslabs_tpu.tpu import telemetry as tel_mod


def run_search(run_dir: str):
    import dataclasses

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    pp = make_pingpong_protocol(workload_size=2)
    pp = dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
    tel = tel_mod.Telemetry.for_checkpoint(
        os.path.join(run_dir, "search.ckpt"), engine_hint="obs-smoke")
    search = TensorSearch(pp, max_depth=8, frontier_cap=1 << 10,
                          visited_cap=1 << 12, telemetry=tel)
    out = search.run()
    tel.close()
    return out


def main() -> int:
    run_dir = tempfile.mkdtemp(prefix="dslabs_obs_smoke_")
    out = run_search(run_dir)
    assert out.end_condition == "SPACE_EXHAUSTED", out.end_condition

    # -- watch on a finished run, from the run dir alone
    frame = tel_mod.render_watch(run_dir)
    for needle in ("depth", "rate", "engine device",
                   f"end: {out.end_condition}"):
        assert needle in frame, (needle, frame)
    rc = tel_mod.main(["watch", run_dir, "--once"])
    assert rc == 0, rc
    rc = tel_mod.main(["report", run_dir])
    assert rc == 0, rc
    print("obs-smoke: watch + report on a finished run OK")

    # -- ledger compare: parity flags nothing, a slow run is flagged
    parity = os.path.join(run_dir, "parity.jsonl")
    for v in (100.0, 98.0):
        tel_mod.append_ledger(parity, {"t": "bench", "value": v,
                                       "strict": {"value": v}})
    rc = tel_mod.main(["compare", parity])
    assert rc == 0, "parity ledger must not flag"
    slow = os.path.join(run_dir, "slow.jsonl")
    for v in (100.0, 40.0):
        tel_mod.append_ledger(slow, {"t": "bench", "value": v,
                                     "strict": {"value": v}})
    rc = tel_mod.main(["compare", slow])
    assert rc == 1, "injected slow run must flag a regression"
    cmp = tel_mod.compare_ledger(tel_mod.read_ledger(slow))
    assert any(e["phase"] == "strict" for e in cmp["regressions"]), cmp
    print("obs-smoke: ledger compare (parity + injected regression) OK")

    # -- trace assembler (ISSUE 13): the same run inside a trace
    # context assembles into a causal tree from the run dir alone.
    from dslabs_tpu.tpu import tracing

    trace_dir = tempfile.mkdtemp(prefix="dslabs_obs_smoke_trace_")
    trace_id = tracing.mint_trace_id()
    os.environ[tracing.TRACE_ENV] = trace_id
    try:
        run_search(trace_dir)
    finally:
        os.environ.pop(tracing.TRACE_ENV, None)
    rc = tel_mod.main(["trace", trace_dir])
    assert rc == 0, rc
    tr = tracing.assemble(trace_dir)
    (j,) = tr["jobs"]
    assert j["trace_id"] == trace_id, j
    ids = {n["span_id"] for n in j["nodes"]}
    assert all(n["parent"] is None or n["parent"] in ids
               for n in j["nodes"]), "broken parent chain"
    assert j["phases"]["search_secs"] > 0, j["phases"]
    frame = tel_mod.watch_frame(trace_dir)
    assert frame["trace_id"] == trace_id and frame["finished"], frame
    pf = tracing.to_perfetto(tr)
    assert pf["traceEvents"], "perfetto export empty"
    print("obs-smoke: trace assembler (causal tree + perfetto) OK")
    print(json.dumps({"obs_smoke": "ok", "run_dir": run_dir,
                      "trace_dir": trace_dir, "trace_id": trace_id}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
