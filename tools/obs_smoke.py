"""Observability smoke driver (`make obs-smoke`, ISSUE 8 satellite):
the end-to-end CLI paths the pytest tier exercises through the API —

1. run a tiny search with a run-dir recorder (flight.jsonl +
   STATUS.json) and render it with ``telemetry watch --once`` and
   ``telemetry report`` (the watch-on-a-finished-run step);
2. build a parity ledger (no flag expected, rc 0) and an
   injected-slow-run ledger (regression flagged, rc 1) and diff both
   with ``telemetry compare`` (the ledger-compare step);
3. (ISSUE 13) run the same search INSIDE a trace context and assemble
   it with ``telemetry trace`` — the causal tree, the trace id on
   every span, ``watch --json``, and the Perfetto export
   (the trace-assembler step);
4. (ISSUE 14) parse a lanes bench-phase record end to end: the
   ledger's ``service:dispatches_per_job`` and ``lanes:occupancy``
   compare guards flag an injected amortisation regression (rc 1)
   and stay quiet on parity, and a lane-batch run dir's STATUS.json
   renders its per-lane block through ``telemetry watch``
   (the lanes leg);
5. (ISSUE 15) drive the PACKED path end to end: a domain-declared
   generated spec runs with the bit-packed frontier encoding ON,
   its STATUS.json carries the schema-pinned ``capacity`` block
   (bytes_per_state / pack_ratio), ``telemetry watch`` renders it,
   and the ledger's ``capacity:bytes_per_state`` guard flags an
   injected encoding regression (rc 1) while parity stays rc 0
   (the capacity2 leg).

Exits nonzero on any mismatch; prints one OK line per step."""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache-cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dslabs_tpu.tpu import telemetry as tel_mod


def run_search(run_dir: str):
    import dataclasses

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    pp = make_pingpong_protocol(workload_size=2)
    pp = dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
    tel = tel_mod.Telemetry.for_checkpoint(
        os.path.join(run_dir, "search.ckpt"), engine_hint="obs-smoke")
    search = TensorSearch(pp, max_depth=8, frontier_cap=1 << 10,
                          visited_cap=1 << 12, telemetry=tel)
    out = search.run()
    tel.close()
    return out


def run_lane_batch(run_dir: str):
    """A tiny 2-lane batch with a run-dir recorder — the lanes watch
    fixture (ISSUE 14)."""
    import dataclasses

    from dslabs_tpu.tpu.lanes import LaneJob, LaneSearch
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol

    pp = make_pingpong_protocol(workload_size=2)
    pp = dataclasses.replace(
        pp, goals={}, prunes={"CLIENTS_DONE": pp.goals["CLIENTS_DONE"]})
    tel = tel_mod.Telemetry.for_checkpoint(
        os.path.join(run_dir, "search.ckpt"), engine_hint="lane-batch")
    search = LaneSearch(pp, n_lanes=2, frontier_cap=1 << 10,
                        visited_cap=1 << 12, telemetry=tel)
    res = search.run_lanes([LaneJob("smoke-a"), LaneJob("smoke-b")])
    tel.close()
    assert not res.errors, res.errors
    return res


def main() -> int:
    run_dir = tempfile.mkdtemp(prefix="dslabs_obs_smoke_")
    out = run_search(run_dir)
    assert out.end_condition == "SPACE_EXHAUSTED", out.end_condition

    # -- watch on a finished run, from the run dir alone
    frame = tel_mod.render_watch(run_dir)
    for needle in ("depth", "rate", "engine device",
                   f"end: {out.end_condition}"):
        assert needle in frame, (needle, frame)
    rc = tel_mod.main(["watch", run_dir, "--once"])
    assert rc == 0, rc
    rc = tel_mod.main(["report", run_dir])
    assert rc == 0, rc
    print("obs-smoke: watch + report on a finished run OK")

    # -- ledger compare: parity flags nothing, a slow run is flagged
    parity = os.path.join(run_dir, "parity.jsonl")
    for v in (100.0, 98.0):
        tel_mod.append_ledger(parity, {"t": "bench", "value": v,
                                       "strict": {"value": v}})
    rc = tel_mod.main(["compare", parity])
    assert rc == 0, "parity ledger must not flag"
    slow = os.path.join(run_dir, "slow.jsonl")
    for v in (100.0, 40.0):
        tel_mod.append_ledger(slow, {"t": "bench", "value": v,
                                     "strict": {"value": v}})
    rc = tel_mod.main(["compare", slow])
    assert rc == 1, "injected slow run must flag a regression"
    cmp = tel_mod.compare_ledger(tel_mod.read_ledger(slow))
    assert any(e["phase"] == "strict" for e in cmp["regressions"]), cmp
    print("obs-smoke: ledger compare (parity + injected regression) OK")

    # -- trace assembler (ISSUE 13): the same run inside a trace
    # context assembles into a causal tree from the run dir alone.
    from dslabs_tpu.tpu import tracing

    trace_dir = tempfile.mkdtemp(prefix="dslabs_obs_smoke_trace_")
    trace_id = tracing.mint_trace_id()
    os.environ[tracing.TRACE_ENV] = trace_id
    try:
        run_search(trace_dir)
    finally:
        os.environ.pop(tracing.TRACE_ENV, None)
    rc = tel_mod.main(["trace", trace_dir])
    assert rc == 0, rc
    tr = tracing.assemble(trace_dir)
    (j,) = tr["jobs"]
    assert j["trace_id"] == trace_id, j
    ids = {n["span_id"] for n in j["nodes"]}
    assert all(n["parent"] is None or n["parent"] in ids
               for n in j["nodes"]), "broken parent chain"
    assert j["phases"]["search_secs"] > 0, j["phases"]
    frame = tel_mod.watch_frame(trace_dir)
    assert frame["trace_id"] == trace_id and frame["finished"], frame
    pf = tracing.to_perfetto(tr)
    assert pf["traceEvents"], "perfetto export empty"
    print("obs-smoke: trace assembler (causal tree + perfetto) OK")

    # -- lanes leg (ISSUE 14): the amortisation compare guards parse
    # a lanes bench-phase record end to end.  Parity ledger: equal
    # dispatches-per-job + occupancy -> rc 0; regression ledger: dpj
    # doubled AND occupancy halved -> both guards flag, rc 1.
    lanes_ok = os.path.join(run_dir, "lanes_parity.jsonl")
    base = {"t": "bench", "value": 100.0,
            "lanes": {"value": 500.0, "dispatches_per_job": 8.0,
                      "occupancy": 4.0}}
    for _ in range(2):
        tel_mod.append_ledger(lanes_ok, base)
    rc = tel_mod.main(["compare", lanes_ok])
    assert rc == 0, "lane parity ledger must not flag"
    lanes_bad = os.path.join(run_dir, "lanes_regress.jsonl")
    tel_mod.append_ledger(lanes_bad, base)
    tel_mod.append_ledger(lanes_bad, {
        "t": "bench", "value": 100.0,
        "lanes": {"value": 500.0, "dispatches_per_job": 16.0,
                  "occupancy": 2.0}})
    rc = tel_mod.main(["compare", lanes_bad])
    assert rc == 1, "lane amortisation regression must flag"
    cmp = tel_mod.compare_ledger(tel_mod.read_ledger(lanes_bad))
    flagged = {e["phase"] for e in cmp["regressions"]}
    assert "service:dispatches_per_job" in flagged, cmp
    assert "lanes:occupancy" in flagged, cmp
    # A lane-batch STATUS.json (the child's monitor file) renders the
    # per-lane block through the same watch CLI.
    lane_dir = tempfile.mkdtemp(prefix="dslabs_obs_smoke_lanes_")
    run_lane_batch(lane_dir)
    frame = tel_mod.render_watch(lane_dir)
    assert "job lane" in frame, frame
    rc = tel_mod.main(["watch", lane_dir, "--once"])
    assert rc == 0, rc
    print("obs-smoke: lanes compare guards + batched watch OK")

    # -- capacity2 leg (ISSUE 15): the packed path end to end.
    import dataclasses

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.specs import clientserver_spec

    cap_dir = tempfile.mkdtemp(prefix="dslabs_obs_smoke_cap2_")
    cs = clientserver_spec(2, 2).compile()
    cs = dataclasses.replace(
        cs, goals={}, prunes={"DONE": cs.goals["CLIENTS_DONE"]})
    tel = tel_mod.Telemetry.for_checkpoint(
        os.path.join(cap_dir, "search.ckpt"), engine_hint="capacity2")
    search = TensorSearch(cs, chunk=128, frontier_cap=1 << 10,
                          visited_cap=1 << 12, telemetry=tel)
    assert search._pk is not None, "generated spec must derive packing"
    out = search.run()
    tel.close()
    assert out.pack_ratio and out.pack_ratio >= 2.0, out.pack_ratio
    assert out.bytes_per_state < out.bytes_per_state_unpacked, out
    st = tel_mod.load_status(
        os.path.join(cap_dir, "STATUS.json"))
    assert st["capacity"]["bytes_per_state"] == out.bytes_per_state, st
    assert st["capacity"]["pack_ratio"] == out.pack_ratio, st
    frame = tel_mod.render_watch(cap_dir)
    assert "capacity:" in frame and "bytes_per_state" in frame, frame
    cap_ok = os.path.join(run_dir, "cap_parity.jsonl")
    base = {"t": "bench", "value": 100.0,
            "capacity2": {"value": 50.0, "bytes_per_state": 44.0}}
    for _ in range(2):
        tel_mod.append_ledger(cap_ok, base)
    rc = tel_mod.main(["compare", cap_ok])
    assert rc == 0, "capacity parity ledger must not flag"
    cap_bad = os.path.join(run_dir, "cap_regress.jsonl")
    tel_mod.append_ledger(cap_bad, base)
    tel_mod.append_ledger(cap_bad, {
        "t": "bench", "value": 100.0,
        "capacity2": {"value": 50.0, "bytes_per_state": 604.0}})
    rc = tel_mod.main(["compare", cap_bad])
    assert rc == 1, "bytes_per_state regression must flag"
    cmp = tel_mod.compare_ledger(tel_mod.read_ledger(cap_bad))
    flagged = {e["phase"] for e in cmp["regressions"]}
    assert "capacity:bytes_per_state" in flagged, cmp
    print("obs-smoke: packed path + capacity compare guard OK")

    # -- memo leg (ISSUE 16, service/memo.py): the same job drained
    # TWICE through a real CheckServer — the second drain lands as a
    # journaled memo_hit with zero dispatches — then the compare
    # guard exercised rc 0/1 both ways: steady hit_rate passes, an
    # injected hit_rate collapse flags ``memo:hit_rate``, and
    # ``service:device_secs_saved`` renders in the compare output.
    from dslabs_tpu.service import CheckServer

    memo_root = tempfile.mkdtemp(prefix="dslabs_obs_smoke_memo_")
    srv = CheckServer(
        memo_root, workers=1, admission=False, elastic=False,
        env={"DSLABS_COMPILE_CACHE":
             os.environ.get("DSLABS_COMPILE_CACHE",
                            "/tmp/jaxcache-cpu")})
    job = dict(factory="dslabs_tpu.tpu.protocols.pingpong:"
                       "make_exhaustive_pingpong",
               factory_kwargs={"workload_size": 2}, chunk=64,
               frontier_cap=1 << 8, visited_cap=1 << 12)
    srv.submit(tenant="first", **job)
    first = srv.drain()
    assert first["completed"] == 1, first
    srv.submit(tenant="second", **job)
    second = srv.drain()
    srv.close()
    assert second["memo"]["hits"] == 1, second["memo"]
    with open(os.path.join(memo_root, "journal.jsonl")) as f:
        kinds = [json.loads(ln).get("t") for ln in f if ln.strip()]
    assert "memo_hit" in kinds, kinds
    memo_ok = os.path.join(run_dir, "memo_parity.jsonl")
    base = {"t": "bench", "value": 100.0,
            "memo": {"value": 40.0, "hit_rate": 0.5,
                     "device_secs_saved": 2.0}}
    for _ in range(2):
        tel_mod.append_ledger(memo_ok, base)
    rc = tel_mod.main(["compare", memo_ok])
    assert rc == 0, "steady memo hit_rate must not flag"
    memo_bad = os.path.join(run_dir, "memo_regress.jsonl")
    tel_mod.append_ledger(memo_bad, base)
    tel_mod.append_ledger(memo_bad, {
        "t": "bench", "value": 100.0,
        "memo": {"value": 40.0, "hit_rate": 0.05,
                 "device_secs_saved": 0.1}})
    rc = tel_mod.main(["compare", memo_bad])
    assert rc == 1, "hit_rate collapse must flag"
    cmp = tel_mod.compare_ledger(tel_mod.read_ledger(memo_bad))
    flagged = {e["phase"] for e in cmp["regressions"]}
    assert "memo:hit_rate" in flagged, cmp
    rendered = tel_mod.render_compare(cmp)
    assert "device_secs_saved" in rendered, rendered
    print("obs-smoke: memo drain-twice hit + hit_rate guard OK")
    print(json.dumps({"obs_smoke": "ok", "run_dir": run_dir,
                      "trace_dir": trace_dir, "trace_id": trace_id}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
