"""Bisect the sharded chunk step: progressively truncated variants of the
local step, keeping results alive via counter sums so XLA cannot DCE the
stages under test. Dev tool."""

import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from dslabs_tpu.tpu.engine import flatten_state
from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol
from dslabs_tpu.tpu.sharded import (MAXU32, OVERFLOW_FACTOR,
                                    ShardedTensorSearch, make_mesh)


def build_variant(search, stop_after):
    """stop_after: 'expand' | 'route' | 'a2a' | 'probe' | 'full'."""
    p = search.p
    D = search.n_devices
    C = search.cpd
    V = search.v_cap
    ne = search._num_events()
    ax = search.axis
    lanes = search.lanes
    bucket = (C * ne // D + 1) * OVERFLOW_FACTOR

    def local(carry, j):
        cur, cur_n = carry["cur"], carry["cur_n"][0]
        start = j * C
        rows_chunk = jax.lax.dynamic_slice(cur, (start, 0), (C, lanes))
        valid = (start + jnp.arange(C)) < cur_n
        states = search.unflatten_rows(rows_chunk)
        flat, valids, fp, unique, overflow, flags = search._expand_chunk(
            states, valid)
        rows = flatten_state(flat)
        if stop_after == "expand":
            carry = dict(carry)
            carry["explored"] = carry["explored"].at[0].add(
                jnp.sum(rows).astype(jnp.int32)
                + jnp.sum(fp).astype(jnp.int32)
                + jnp.sum(unique).astype(jnp.int32))
            return carry
        if stop_after == "mat":
            # Force full materialisation of the successor rows into HBM
            # (contiguous write, no permutation) — isolates the cost of
            # the expand's output materialisation from routing/dedup.
            carry = dict(carry)
            nxt = carry["nxt"]
            carry["nxt"] = jax.lax.dynamic_update_slice(
                nxt, rows[:nxt.shape[0]], (0, 0))
            carry["explored"] = carry["explored"].at[0].add(
                jnp.sum(fp).astype(jnp.int32))
            return carry

        owner = (fp[:, 0] % jnp.uint32(D)).astype(jnp.int32)
        owner = jnp.where(unique, owner, D)
        order = jnp.argsort(owner, stable=True)
        if stop_after == "argsort":
            carry = dict(carry)
            carry["explored"] = carry["explored"].at[0].add(
                jnp.sum(order).astype(jnp.int32)
                + jnp.sum(rows).astype(jnp.int32))
            return carry
        owner_s = owner[order]
        dev = jnp.arange(D)
        starts = jnp.searchsorted(owner_s, dev, side="left")
        ends = jnp.searchsorted(owner_s, dev, side="right")
        src = starts[:, None] + jnp.arange(bucket)[None, :]
        send_valid = src < ends[:, None]
        gidx = order[src.clip(0, owner.shape[0] - 1)].reshape(-1)
        send_rows = rows[gidx].reshape(D, bucket, lanes)
        send_keys = fp[gidx].reshape(D, bucket, 4)
        if stop_after == "route":
            carry = dict(carry)
            carry["explored"] = carry["explored"].at[0].add(
                jnp.sum(send_rows).astype(jnp.int32)
                + jnp.sum(send_keys).astype(jnp.int32))
            return carry

        recv_rows = jax.lax.all_to_all(send_rows, ax, 0, 0)
        recv_keys = jax.lax.all_to_all(send_keys, ax, 0, 0)
        recv_valid = jax.lax.all_to_all(send_valid, ax, 0, 0)
        rb = D * bucket
        recv_rows = recv_rows.reshape(rb, lanes)
        recv_keys = jnp.where(recv_valid.reshape(rb, 1),
                              recv_keys.reshape(rb, 4), MAXU32)
        recv_valid = recv_valid.reshape(rb)
        if stop_after == "a2a":
            carry = dict(carry)
            carry["explored"] = carry["explored"].at[0].add(
                jnp.sum(recv_rows).astype(jnp.int32)
                + jnp.sum(recv_keys).astype(jnp.int32))
            return carry

        visited = carry["visited"]
        all_max = jnp.all(recv_keys == MAXU32, axis=1)
        ckeys = recv_keys.at[:, 3].set(
            jnp.where(all_max & recv_valid, MAXU32 - 1, recv_keys[:, 3]))
        bo = jnp.lexsort((ckeys[:, 3], ckeys[:, 2], ckeys[:, 1],
                          ckeys[:, 0], ~recv_valid))
        skeys = ckeys[bo]
        svalid = recv_valid[bo]
        batch_first = jnp.ones(rb, bool).at[1:].set(
            jnp.any(skeys[1:] != skeys[:-1], axis=1))
        cand = svalid & batch_first
        slot0 = (skeys[:, 2] & jnp.uint32(V - 1)).astype(jnp.int32)
        pstep = (skeys[:, 1] | jnp.uint32(1)).astype(jnp.uint32)

        def probe_cond(st):
            _, _, resolved, _, it = st
            return (it < 64) & jnp.any(~resolved)

        def probe_body(st):
            table, slot, resolved, fresh, it = st
            cur_ = table[slot]
            eq = jnp.all(cur_ == skeys, axis=1)
            empty = jnp.all(cur_ == MAXU32, axis=1)
            unres = ~resolved
            tryi = unres & empty
            dsti = jnp.where(tryi, slot, V)
            table = table.at[dsti].set(skeys)
            back = table[slot]
            won = tryi & jnp.all(back == skeys, axis=1)
            resolved = resolved | eq | won
            nslot = (slot.astype(jnp.uint32) + pstep).astype(
                jnp.int32) & (V - 1)
            slot = jnp.where(~resolved, nslot, slot)
            return table, slot, resolved, fresh | won, it + 1

        table, _, resolved, fresh_s, _ = jax.lax.while_loop(
            probe_cond, probe_body,
            (visited, slot0, ~cand, jnp.zeros(rb, bool), jnp.int32(0)))
        if stop_after == "probe":
            carry = dict(carry)
            carry["visited"] = table
            carry["explored"] = carry["explored"].at[0].add(
                jnp.sum(fresh_s).astype(jnp.int32)
                + jnp.sum(resolved).astype(jnp.int32))
            return carry
        raise ValueError(stop_after)

    spec = search._carry_specs()
    return jax.jit(shard_map(local, mesh=search.mesh,
                             in_specs=(spec, P()), out_specs=spec,
                             check_rep=False), donate_argnums=0)


def main():
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        protocol, mesh, chunk_per_device=256,
        frontier_cap=1 << 16, visited_cap=1 << 21, max_depth=1,
        strict=False)
    state = search.initial_state()
    with mesh:
        import sys
        variants = (sys.argv[1:] if len(sys.argv) > 1
                    else ["expand", "argsort", "route", "a2a", "probe"])
        for variant in variants:
            fn = build_variant(search, variant)
            carry = search._init_carry(state)
            t0 = time.time()
            carry = fn(carry, jnp.int32(0))
            jax.block_until_ready(carry["explored"])
            print(f"{variant:8s} compile+1st {time.time()-t0:6.1f}s")
            iters = 20
            t0 = time.time()
            for _ in range(iters):
                carry = fn(carry, jnp.int32(0))
            jax.block_until_ready(carry["explored"])
            print(f"{variant:8s} steady {(time.time()-t0)/iters*1e3:9.2f} ms")


if __name__ == "__main__":
    main()
