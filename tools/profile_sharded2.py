"""Bisect the sharded chunk step via the engine's `_stop_after` dev hook:
run a REAL search to load the frontier + visited table, snapshot the
carry, then time progressively truncated variants of the genuine
`_build_chunk_step` program (no drifting copy).  Self-feeding loops only
(each step consumes the previous carry) — independent-arg microbenchmarks
lie on the axon platform.

A thin client of the telemetry API (tpu/telemetry.py): every timed
iteration is a span (`bisect.<stage>`; the compile-paying first dispatch
is its own `.compile` site), the table is the shared per-site latency
renderer, and ``--flight <path>`` leaves a flight log the report CLI can
render.  Dev tool, not part of the test suite."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh
from dslabs_tpu.tpu.telemetry import Telemetry, render_sites

ARGS = [a for a in sys.argv[1:] if not a.startswith("--")]
CHUNK = int(ARGS[0]) if len(ARGS) > 0 else 1024
EVB = int(ARGS[1]) if len(ARGS) > 1 else 48  # 48 -> (40, 8)
WARM_DEPTH = 10
ITERS = 20
STAGES = ["events", "handlers", "tail", "fp", "expand", "route",
          "a2a", "probe", "back", None]


def make_search(stop_after):
    import dataclasses
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    protocol = dataclasses.replace(protocol, goals={})
    mesh = make_mesh(len(jax.devices()))
    s = ShardedTensorSearch(protocol, mesh, chunk_per_device=CHUNK,
                            frontier_cap=1 << 17, visited_cap=1 << 23,
                            max_depth=WARM_DEPTH, strict=False,
                            ev_budget=((40, 8) if EVB == 48 else (EVB or None)))
    s._stop_after = stop_after
    # Rebuild the jitted step AFTER setting the hook (the ctor built it
    # with stop_after=None).
    s._chunk_step = jax.jit(s._build_chunk_step(), donate_argnums=0)
    return s


def warm_carry(s):
    """Run the REAL search (full program) to WARM_DEPTH, returning the
    loaded device-resident carry — no host roundtrip (a 1.5 GB carry
    device_get/put through the tunnel dominated the old design)."""
    import time

    state = s.initial_state()
    carry = s._init_carry(state)
    max_n = 1
    depth = 0
    t0 = time.time()
    while depth < WARM_DEPTH:
        depth += 1
        n_chunks = -(-(max_n + s.n_devices - 1) // s.cpd)
        for _ in range(n_chunks):
            carry = s._chunk_step(carry)
        _, _, _, _, max_n, _ = s._sync_checks(carry, depth, t0)
        carry = s._finish_level(carry)
    return carry, max_n


def main():
    flight = None
    if "--flight" in sys.argv:
        flight = sys.argv[sys.argv.index("--flight") + 1]
    tel = Telemetry(flight_log=flight, engine_hint="profile_sharded2")

    for stop in STAGES:
        sv = make_search(None)          # warm with the FULL program
        name = stop or "full"
        with sv.mesh:
            carry, max_n = warm_carry(sv)
            if stop is not None:        # then swap in the variant
                sv._stop_after = stop
                sv._chunk_step = jax.jit(sv._build_chunk_step(),
                                         donate_argnums=0)
            c = carry
            with tel.span(f"bisect.{name}.compile", frontier=max_n):
                c = sv._chunk_step(c)
                jax.block_until_ready(c["explored"])
            # Each iteration blocks inside its span (same discipline as
            # tools/profile_sharded.py): the chunk step self-feeds, so
            # the device work is serialized either way and the span
            # wall is the honest per-step cost.
            for _ in range(ITERS):
                with tel.span(f"bisect.{name}"):
                    c = sv._chunk_step(c)
                    jax.block_until_ready(c["explored"])
            st = tel.summary()["sites"][f"bisect.{name}"]
            dt = max(st["total"] / max(st["count"], 1), 1e-9)
            print(f"{name:8s} (frontier/dev {max_n}) "
                  f"steady {dt*1e3:8.2f} ms  "
                  f"({CHUNK*sv._num_events()/dt/1e6:.2f}M pairs/s)",
                  flush=True)

    print()
    print(render_sites(tel.summary()))
    if flight:
        print(f"\nflight log: {flight} "
              f"(python -m dslabs_tpu.tpu.telemetry report {flight})")
    tel.close()


if __name__ == "__main__":
    main()
