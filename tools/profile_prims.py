"""Microbenchmark TPU primitive costs for [B, lanes] row movement.

A thin client of the telemetry API (tpu/telemetry.py): each iteration is
a span (`prims.<name>`), the table is the shared per-site latency
renderer, ``--flight <path>`` leaves a flight log the report CLI can
render.  Dev tool."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp

from dslabs_tpu.tpu.telemetry import Telemetry, render_sites

B, LANES, F = 24064, 1354, 65537
ITERS = 10


def bench(tel, name, fn, *args):
    site = "prims." + name.replace(" ", "_")
    fn = jax.jit(fn, donate_argnums=0) if name.startswith("donate") \
        else jax.jit(fn)
    with tel.span(f"{site}.compile"):
        out = fn(*args)
        jax.block_until_ready(out)
    for _ in range(ITERS):
        with tel.span(site):
            out = fn(*args)
            jax.block_until_ready(out)
    st = tel.summary()["sites"][site]
    dt = max(st["p50"], 1e-9)
    gb = B * LANES * 4 / 1e9
    print(f"{name:36s} {dt*1e3:9.2f} ms  ({gb/dt:6.1f} GB/s eff)")


def main():
    flight = None
    if "--flight" in sys.argv:
        flight = sys.argv[sys.argv.index("--flight") + 1]
    tel = Telemetry(flight_log=flight, engine_hint="profile_prims")

    key = jax.random.PRNGKey(0)
    rows = jax.random.randint(key, (B, LANES), 0, 1000, jnp.int32)
    nxt = jnp.zeros((F, LANES), jnp.int32)
    gidx = jax.random.randint(key, (2 * B,), 0, B, jnp.int32)
    sdst = jax.random.permutation(key, F)[:B]
    sel = jax.random.bernoulli(key, 0.3, (B,))

    bench(tel, "copy rows * 2", lambda r: r * 2, rows)
    bench(tel, "gather 2B rows [gidx]", lambda r, g: r[g], rows, gidx)
    bench(tel, "gather B rows [sdst range]", lambda r, s: r[s % B],
          rows, sdst)
    bench(tel, "scatter B rows into F",
          lambda n, r, s: n.at[s].set(r), nxt, rows, sdst)
    bench(tel, "donate scatter B rows into F",
          lambda n, r, s: n.at[s].set(r), nxt, rows, sdst)
    bench(tel, "dyn_update_slice B rows",
          lambda n, r: jax.lax.dynamic_update_slice(n, r, (0, 0)), nxt, rows)
    bench(tel, "donate dyn_update_slice",
          lambda n, r: jax.lax.dynamic_update_slice(n, r, (0, 0)), nxt, rows)

    # masked compact scatter (the nxt append pattern)
    def append(n, r, s):
        spos = jnp.cumsum(s) - 1
        dst = jnp.where(s & (spos < F), spos, F - 1)
        return n.at[dst].set(r)
    bench(tel, "donate masked append scatter", append, nxt, rows, sel)
    # take_along_axis variant
    bench(tel, "take_along_axis 2B rows",
          lambda r, g: jnp.take_along_axis(
              r, g[:, None].astype(jnp.int32), axis=0), rows, gidx)

    print()
    print(render_sites(tel.summary()))
    if flight:
        print(f"\nflight log: {flight} "
              f"(python -m dslabs_tpu.tpu.telemetry report {flight})")
    tel.close()


if __name__ == "__main__":
    main()
