"""Microbenchmark TPU primitive costs for [B, lanes] row movement. Dev
tool."""

import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np

B, LANES, F = 24064, 1354, 65537


def bench(name, fn, *args, iters=10):
    fn = jax.jit(fn, donate_argnums=0) if name.startswith("donate") \
        else jax.jit(fn)
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / iters
    gb = B * LANES * 4 / 1e9
    print(f"{name:36s} {dt*1e3:9.2f} ms  ({gb/dt:6.1f} GB/s eff)")


def main():
    key = jax.random.PRNGKey(0)
    rows = jax.random.randint(key, (B, LANES), 0, 1000, jnp.int32)
    nxt = jnp.zeros((F, LANES), jnp.int32)
    gidx = jax.random.randint(key, (2 * B,), 0, B, jnp.int32)
    sdst = jax.random.permutation(key, F)[:B]
    sel = jax.random.bernoulli(key, 0.3, (B,))

    bench("copy rows * 2", lambda r: r * 2, rows)
    bench("gather 2B rows [gidx]", lambda r, g: r[g], rows, gidx)
    bench("gather B rows [sdst range]", lambda r, s: r[s % B], rows, sdst)
    bench("scatter B rows into F",
          lambda n, r, s: n.at[s].set(r), nxt, rows, sdst)
    bench("donate scatter B rows into F",
          lambda n, r, s: n.at[s].set(r), nxt, rows, sdst)
    bench("dyn_update_slice B rows",
          lambda n, r: jax.lax.dynamic_update_slice(n, r, (0, 0)), nxt, rows)
    bench("donate dyn_update_slice",
          lambda n, r: jax.lax.dynamic_update_slice(n, r, (0, 0)), nxt, rows)
    # masked compact scatter (the nxt append pattern)
    def append(n, r, s):
        spos = jnp.cumsum(s) - 1
        dst = jnp.where(s & (spos < F), spos, F - 1)
        return n.at[dst].set(r)
    bench("donate masked append scatter", append, nxt, rows, sel)
    # take_along_axis variant
    bench("take_along_axis 2B rows",
          lambda r, g: jnp.take_along_axis(
              r, g[:, None].astype(jnp.int32), axis=0), rows, gidx)


if __name__ == "__main__":
    main()
