"""Self-feeding (dependency-chained) microbenchmark: wide row scatter /
gather cost vs lane alignment. Dev tool."""

import sys
import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp

B, F = 24064, 65537


def run(lanes):
    key = jax.random.PRNGKey(0)
    rows = jax.random.randint(key, (B, lanes), 0, 1000, jnp.int32)
    nxt = jnp.zeros((F, lanes), jnp.int32)
    sdst = jax.random.permutation(key, F)[:B]
    gidx = jax.random.randint(key, (B,), 0, F, jnp.int32)

    @jax.jit
    def scatter_step(nxt, rows):
        nxt = nxt.at[sdst].set(rows)
        # feed back: rows depend on nxt so iterations serialize
        rows = rows + nxt[0, 0]
        return nxt, rows

    @jax.jit
    def gather_step(nxt, rows):
        g = nxt[gidx]                      # [B, lanes] wide gather
        rows = rows + g
        nxt = nxt + rows[0, 0]
        return nxt, rows

    for name, fn in (("scatter", scatter_step), ("gather", gather_step)):
        n2, r2 = fn(nxt, rows)
        jax.block_until_ready(r2)
        t0 = time.time()
        n2, r2 = nxt, rows
        iters = 10
        for _ in range(iters):
            n2, r2 = fn(n2, r2)
        jax.block_until_ready(r2)
        dt = (time.time() - t0) / iters
        gb = B * lanes * 4 / 1e9
        print(f"lanes={lanes:5d} {name:8s} {dt*1e3:9.2f} ms "
              f"({gb/dt:7.1f} GB/s eff)")


if __name__ == "__main__":
    for lanes in ([int(x) for x in sys.argv[1:]] or [1354, 1408, 1280]):
        run(lanes)
