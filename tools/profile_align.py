"""Self-feeding (dependency-chained) microbenchmark: wide row scatter /
gather cost vs lane alignment.

A thin client of the telemetry API (tpu/telemetry.py): each iteration is
a span (`align.l<lanes>.<op>`), the table is the shared per-site latency
renderer, ``--flight <path>`` leaves a flight log the report CLI can
render.  Dev tool."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp

from dslabs_tpu.tpu.telemetry import Telemetry, render_sites

B, F = 24064, 65537
ITERS = 10


def run(tel, lanes):
    key = jax.random.PRNGKey(0)
    rows = jax.random.randint(key, (B, lanes), 0, 1000, jnp.int32)
    nxt = jnp.zeros((F, lanes), jnp.int32)
    sdst = jax.random.permutation(key, F)[:B]
    gidx = jax.random.randint(key, (B,), 0, F, jnp.int32)

    @jax.jit
    def scatter_step(nxt, rows):
        nxt = nxt.at[sdst].set(rows)
        # feed back: rows depend on nxt so iterations serialize
        rows = rows + nxt[0, 0]
        return nxt, rows

    @jax.jit
    def gather_step(nxt, rows):
        g = nxt[gidx]                      # [B, lanes] wide gather
        rows = rows + g
        nxt = nxt + rows[0, 0]
        return nxt, rows

    gb = B * lanes * 4 / 1e9
    for name, fn in (("scatter", scatter_step), ("gather", gather_step)):
        site = f"align.l{lanes}.{name}"
        with tel.span(f"{site}.compile"):
            n2, r2 = fn(nxt, rows)
            jax.block_until_ready(r2)
        n2, r2 = nxt, rows
        for _ in range(ITERS):
            with tel.span(site, gb=gb):
                n2, r2 = fn(n2, r2)
                jax.block_until_ready(r2)
        st = tel.summary()["sites"][site]
        dt = max(st["p50"], 1e-9)
        print(f"lanes={lanes:5d} {name:8s} {dt*1e3:9.2f} ms "
              f"({gb/dt:7.1f} GB/s eff)")


def main():
    flight = None
    if "--flight" in sys.argv:
        flight = sys.argv[sys.argv.index("--flight") + 1]
    tel = Telemetry(flight_log=flight, engine_hint="profile_align")
    lane_args = [int(x) for x in sys.argv[1:] if x.isdigit()]
    for lanes in (lane_args or [1354, 1408, 1280]):
        run(tel, lanes)
    print()
    print(render_sites(tel.summary()))
    if flight:
        print(f"\nflight log: {flight} "
              f"(python -m dslabs_tpu.tpu.telemetry report {flight})")
    tel.close()


if __name__ == "__main__":
    main()
