"""Measure per-state valid-event occupancy by BFS level on the bench
config: how many of the net_cap + nn*timer_cap event slots are actually
deliverable?  Sets the budget for occupancy-compacted enumeration.

A thin client of the telemetry API (tpu/telemetry.py): each level's
occupancy scalars become telemetry level records (and flight-log lines
under ``--flight <path>``) and the chunk work is spanned, replacing the
old hand-rolled timing scaffold.  Dev tool, not part of the suite."""

import sys
import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, timer_deliverable_mask
from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh
from dslabs_tpu.tpu.telemetry import Telemetry, render_sites


def main():
    flight = None
    if "--flight" in sys.argv:
        flight = sys.argv[sys.argv.index("--flight") + 1]
    tel = Telemetry(flight_log=flight, engine_hint="profile_occupancy")

    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    import dataclasses
    protocol = dataclasses.replace(protocol, goals={})
    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        protocol, mesh, chunk_per_device=256, frontier_cap=1 << 16,
        visited_cap=1 << 22, max_depth=1, strict=False)
    tel.attach(search)      # chunk/promote dispatches become spans

    def stats(carry):
        cur, cur_n = carry["cur"], carry["cur_n"][0]
        states = search.unflatten_rows(cur)
        valid_state = jnp.arange(cur.shape[0]) < cur_n
        msg_occ = states["net"][:, :, 0] != SENTINEL          # [F, cap]
        tmask = jax.vmap(jax.vmap(timer_deliverable_mask))(
            states["timers"])                                  # [F, nn, tc]
        nev = (jnp.sum(msg_occ, axis=1)
               + jnp.sum(tmask, axis=(1, 2))).astype(jnp.int32)
        nev = jnp.where(valid_state, nev, 0)
        hist = jnp.bincount(nev, weights=valid_state.astype(jnp.int32),
                            length=search._num_events() + 1)
        return (hist, jnp.max(nev), jnp.sum(nev),
                jnp.sum(valid_state.astype(jnp.int32)),
                jnp.max(jnp.sum(msg_occ, axis=1) * valid_state),
                jnp.max(jnp.sum(tmask, axis=(1, 2)) * valid_state))

    jstats = jax.jit(stats)

    with mesh:
        state = search.initial_state()
        carry = search._init_carry(state)
        t0 = time.time()
        max_n = 1
        depth = 0
        while max_n > 0 and depth < 24 and time.time() - t0 < 400:
            depth += 1
            t_lvl = time.time()
            n_chunks = -(-(max_n + search.n_devices - 1) // search.cpd)
            for _ in range(n_chunks):
                carry = search._chunk_step(carry)
            _, _, _, drops, max_n, _ = search._sync_checks(carry, depth,
                                                           t0)
            carry = search._finish_level(carry)
            hist, mx, tot, n, mmx, tmx = jax.tree.map(np.asarray,
                                                      jstats(carry))
            if n == 0:
                break
            mean = tot / max(int(n), 1)
            c = np.cumsum(hist)
            p99 = int(np.searchsorted(c, 0.99 * c[-1]))
            p90 = int(np.searchsorted(c, 0.90 * c[-1]))
            # The occupancy scalars become one telemetry level record
            # per depth — the report CLI renders the series, and the
            # live print below is just a view of the same record.
            rec = {"depth": int(depth),
                   "wall": round(time.time() - t_lvl, 4),
                   "explored": int(tot), "unique": int(n),
                   "next_frontier": int(max_n),
                   "ev_mean": round(float(mean), 2),
                   "ev_p90": p90, "ev_p99": p99, "ev_max": int(mx),
                   "msgs_max": int(mmx), "timers_max": int(tmx),
                   "drops": int(drops)}
            tel.on_level("occupancy", rec)
            print(f"lvl {depth:2d} n={int(n):6d} mean={mean:5.1f} "
                  f"p90={p90} p99={p99} max={int(mx)} "
                  f"msgs_max={int(mmx)} tmax={int(tmx)} drops={drops}",
                  flush=True)

    print()
    print(render_sites(tel.summary()))
    if flight:
        print(f"\nflight log: {flight} "
              f"(python -m dslabs_tpu.tpu.telemetry report {flight})")
    tel.close()


if __name__ == "__main__":
    main()
