"""Time the sharded chunk step end-to-end and in pieces on the current
accelerator — a thin client of the telemetry API (tpu/telemetry.py):
every timed block is a span, the table is the shared per-site latency
renderer, and ``--flight <path>`` leaves a flight log the report CLI
can render.  Dev tool, not part of the test suite."""

import sys

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import numpy as np

from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh
from dslabs_tpu.tpu.telemetry import Telemetry, render_sites


def main():
    flight = None
    if "--flight" in sys.argv:
        flight = sys.argv[sys.argv.index("--flight") + 1]
    tel = Telemetry(flight_log=flight, engine_hint="profile_sharded")

    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        protocol, mesh, chunk_per_device=256,
        frontier_cap=1 << 16, visited_cap=1 << 21, max_depth=1,
        strict=False)
    state = search.initial_state()
    with mesh:
        carry = search._init_carry(state)
        with tel.span("profile.chunk_step_compile"):
            carry = search._chunk_step(carry)
            jax.block_until_ready(carry["nxt_n"])

        # steady state: run 20 chunk steps back to back (the carry-resident
        # chunk index self-increments; work is shape-identical regardless
        # of occupancy) — one span each, so the table shows p50/p90.
        for _ in range(20):
            with tel.span("profile.chunk_step"):
                carry = search._chunk_step(carry)
                jax.block_until_ready(carry["nxt_n"])

        with tel.span("profile.finish_level_compile"):
            carry = search._finish_level(carry)
            jax.block_until_ready(carry["nxt_n"])
        for _ in range(5):
            with tel.span("profile.finish_level"):
                carry = search._finish_level(carry)
                jax.block_until_ready(carry["nxt_n"])

        # host-sync cost per level
        for _ in range(5):
            with tel.span("profile.host_sync"):
                _ = int(np.asarray(carry["overflow"]).sum())
                _ = int(np.asarray(carry["drops"]).sum())
                _ = np.asarray(carry["vis_n"])
                _ = int(np.asarray(carry["explored"]).sum())
                _ = np.asarray(carry["flag_cnt"])
                _ = int(np.asarray(carry["nxt_n"]).max())

    print(render_sites(tel.summary()))
    if flight:
        print(f"\nflight log: {flight} "
              f"(python -m dslabs_tpu.tpu.telemetry report {flight})")
    tel.close()


if __name__ == "__main__":
    main()
