"""Time the sharded chunk step end-to-end and in pieces on the current
accelerator. Dev tool, not part of the test suite."""

import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import numpy as np

from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol
from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh


def main():
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        protocol, mesh, chunk_per_device=256,
        frontier_cap=1 << 16, visited_cap=1 << 21, max_depth=1,
        strict=False)
    state = search.initial_state()
    with mesh:
        carry = search._init_carry(state)
        t0 = time.time()
        carry = search._chunk_step(carry)
        jax.block_until_ready(carry["nxt_n"])
        print(f"chunk_step compile+1st {time.time()-t0:6.1f}s")

        # steady state: run 20 chunk steps back to back (the carry-resident
        # chunk index self-increments; work is shape-identical regardless of occupancy)
        iters = 20
        t0 = time.time()
        for _ in range(iters):
            carry = search._chunk_step(carry)
        jax.block_until_ready(carry["nxt_n"])
        dt = (time.time() - t0) / iters
        print(f"chunk_step steady {dt*1e3:9.2f} ms")

        t0 = time.time()
        carry = search._finish_level(carry)
        jax.block_until_ready(carry["nxt_n"])
        print(f"finish_level compile+1st {time.time()-t0:6.1f}s")
        t0 = time.time()
        for _ in range(5):
            carry = search._finish_level(carry)
        jax.block_until_ready(carry["nxt_n"])
        print(f"finish_level steady {(time.time()-t0)/5*1e3:9.2f} ms")

        # host-sync cost per level
        t0 = time.time()
        for _ in range(5):
            _ = int(np.asarray(carry["overflow"]).sum())
            _ = int(np.asarray(carry["drops"]).sum())
            _ = np.asarray(carry["vis_n"])
            _ = int(np.asarray(carry["explored"]).sum())
            _ = np.asarray(carry["flag_cnt"])
            _ = int(np.asarray(carry["nxt_n"]).max())
        print(f"host sync steady {(time.time()-t0)/5*1e3:9.2f} ms")


if __name__ == "__main__":
    main()
