"""Dev: depth-by-depth unique-state parity of the lab4 twin vs the object
checker on the test10 config."""

import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
from dslabs_tpu.search.search import BFS
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.predicates import RESULTS_OK

import tests.test_lab4_shardstore as t

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.protocols.shardstore import make_shardstore_protocol


def object_counts(max_depth):
    state = t.make_search(1, 1, 1, 10)
    joined = t._joined_state(state, 1)
    joined.add_client_worker(
        LocalAddress("client1"),
        kv_workload(["PUT:foo:bar", "GET:foo"], ["PutOk", "bar"]))
    settings = SearchSettings().max_time(600)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(t.CCA, False)
    settings.deliver_timers(t.CCA, False)
    settings.deliver_timers(t.shard_master(1), False)
    # max_depth is absolute: the staged join already sits at joined.depth.
    settings.set_max_depth(joined.depth + max_depth)
    res = BFS(settings).run(joined)
    return res.discovered_count, res.end_condition


def main():
    # PUT:foo:bar, GET:foo both key "foo" -> one group anyway
    proto = make_shardstore_protocol([1, 1])
    for depth in range(1, 6):
        oc, oe = object_counts(depth)
        ten = TensorSearch(proto, chunk=256, max_depth=depth).run()
        flag = "OK " if ten.unique_states == oc else "MISMATCH"
        print(f"depth {depth}: object={oc} tensor={ten.unique_states} "
              f"{flag} (obj {oe}, ten {ten.end_condition})", flush=True)


if __name__ == "__main__":
    main()
