"""Dev: depth-by-depth unique-state parity of the lab4 twin vs the object
checker on the test10 (1 group) and test11 (2 groups, config walk +
handoff) configs.  Usage: python tools/parity_lab4.py [n_groups] [maxd]"""

import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dslabs_tpu.core.address import LocalAddress
from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
from dslabs_tpu.search.search import BFS
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.predicates import RESULTS_OK

import tests.test_lab4_shardstore as t

from dslabs_tpu.tpu.engine import TensorSearch
from dslabs_tpu.tpu.specs_lab4 import make_shardstore_protocol
from tests.test_tpu_lab4 import WORKLOADS


def object_counts(n_groups, max_depth):
    cmds, results, _ = WORKLOADS[n_groups]
    state = t.make_search(n_groups, 1, 1, 10)
    joined = t._joined_state(state, n_groups)
    joined.add_client_worker(LocalAddress("client1"),
                             kv_workload(cmds, results))
    settings = SearchSettings().max_time(1200)
    settings.add_invariant(RESULTS_OK)
    settings.node_active(t.CCA, False)
    settings.deliver_timers(t.CCA, False)
    settings.deliver_timers(t.shard_master(1), False)
    # max_depth is absolute: the staged join already sits at joined.depth.
    settings.set_max_depth(joined.depth + max_depth)
    res = BFS(settings).run(joined)
    return res.discovered_count, res.end_condition


def main():
    n_groups = int(sys.argv[1]) if len(sys.argv) > 1 else 1
    maxd = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    proto = make_shardstore_protocol(WORKLOADS[n_groups][2])
    for depth in range(1, maxd + 1):
        oc, oe = object_counts(n_groups, depth)
        ten = TensorSearch(proto, chunk=256, max_depth=depth).run()
        flag = "OK " if ten.unique_states == oc else "MISMATCH"
        print(f"depth {depth}: object={oc} tensor={ten.unique_states} "
              f"{flag} (obj {oe}, ten {ten.end_condition})", flush=True)


if __name__ == "__main__":
    main()
